"""Process-wide metrics registry: counters, gauges, fixed-bucket histograms.

The reference stack treats MFU/tokens-per-sec as first-class outputs
(SURVEY.md §5, BASELINE north star) but the seed left every producer to
invent its own ad-hoc JSON. This registry is the one sink: near-zero
overhead on the hot path (a counter inc is one int add; a histogram
observe is one bisect + int add — no allocation, no I/O), exporters pay
their cost only when called.

Label model: every metric is keyed by (name, sorted label items). The
registry carries *default labels* (e.g. ``rank`` — set by ``fleet.init``
under ``parallel/launch.py``) merged under per-call labels, so the same
call site emits distinguishable series per rank without threading rank
through every caller.
"""

import bisect
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "RegistryView",
    "registry", "set_default_labels", "DEFAULT_BUCKETS",
]

# Latency-shaped default buckets (seconds): decode steps sit in the
# 100 µs – 100 ms band on TPU, whole requests in the 10 ms – 10 s band.
DEFAULT_BUCKETS = (1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3, 1e-2, 2.5e-2,
                   5e-2, 1e-1, 2.5e-1, 5e-1, 1.0, 2.5, 5.0, 10.0)


class Counter:
    """Monotonic counter. ``inc`` is allocation-free (one lock + add —
    concurrent requests against an attached tracer share these
    objects, and ``+=`` alone can lose updates between bytecodes)."""

    __slots__ = ("name", "labels", "value", "_lock")
    kind = "counter"

    def __init__(self, name: str, labels: Tuple = ()):
        self.name = name
        self.labels = labels
        self.value = 0
        self._lock = threading.Lock()

    def inc(self, n=1):
        with self._lock:
            self.value += n

    def snapshot(self) -> dict:
        return {"name": self.name, "type": self.kind,
                "labels": dict(self.labels), "value": self.value}


class Gauge:
    """Last-value gauge."""

    __slots__ = ("name", "labels", "value")
    kind = "gauge"

    def __init__(self, name: str, labels: Tuple = ()):
        self.name = name
        self.labels = labels
        self.value = 0.0

    def set(self, v):
        self.value = v

    def snapshot(self) -> dict:
        return {"name": self.name, "type": self.kind,
                "labels": dict(self.labels), "value": self.value}


class Histogram:
    """Fixed-bucket histogram (Prometheus-style cumulative export).

    ``observe`` is allocation-free: the bucket counts list is
    preallocated at construction; one bisect + two int adds + one float
    add per observation.
    """

    __slots__ = ("name", "labels", "bounds", "counts", "sum", "count",
                 "_lock")
    kind = "histogram"

    def __init__(self, name: str, labels: Tuple = (),
                 buckets: Optional[Tuple] = None):
        self.name = name
        self.labels = labels
        self.bounds = tuple(sorted(buckets or DEFAULT_BUCKETS))
        self.counts = [0] * (len(self.bounds) + 1)  # +1: +Inf overflow
        self.sum = 0.0
        self.count = 0
        self._lock = threading.Lock()

    def observe(self, v):
        v = float(v)
        with self._lock:
            self.counts[bisect.bisect_left(self.bounds, v)] += 1
            self.sum += v
            self.count += 1

    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None

    def quantile(self, q: float) -> Optional[float]:
        """Prometheus ``histogram_quantile`` over the cumulative ``le``
        buckets: find the first bucket whose cumulative count reaches
        ``q * count`` and interpolate linearly inside it (uniform-
        within-bucket assumption; the lowest bucket's lower edge is 0).
        A rank landing in the +Inf overflow returns the highest finite
        bound — exactly Prometheus's behavior. None while empty.

        Accuracy is bounded by the bucket layout — for tight tail
        quantiles use ``MetricsRegistry.sketch`` (bounded *relative*
        error at any quantile)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        with self._lock:
            if self.count == 0:
                return None
            target = q * self.count
            cum = 0
            for i, b in enumerate(self.bounds):
                prev = cum
                cum += self.counts[i]
                if cum >= target and self.counts[i]:
                    lo = self.bounds[i - 1] if i else 0.0
                    return lo + (b - lo) * (target - prev) / self.counts[i]
            return self.bounds[-1]

    def snapshot(self) -> dict:
        return {"name": self.name, "type": self.kind,
                "labels": dict(self.labels),
                "buckets": {("%g" % b): c
                            for b, c in zip(self.bounds, self.counts)},
                "inf": self.counts[-1], "sum": self.sum, "count": self.count}


def _label_key(labels: Dict) -> Tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def append_jsonl_lines(path: str, lines) -> int:
    """Append pre-serialized JSON lines with ONE O_APPEND write — POSIX
    appends are atomic per write, so concurrent per-rank writers sharing
    a path can't interleave partial lines. The one shared implementation
    behind MetricsRegistry/Tracer/MetricsLogger JSONL sinks."""
    lines = list(lines)
    if not lines:
        return 0
    buf = memoryview(("\n".join(lines) + "\n").encode())
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
    try:
        # loop on short writes: a truncated write would leave exactly the
        # torn partial line this helper exists to prevent
        while buf:
            buf = buf[os.write(fd, buf):]
    finally:
        os.close(fd)
    return len(lines)


class MetricsRegistry:
    """Get-or-create registry of named metrics, keyed by (name, labels)."""

    def __init__(self):
        self._metrics: Dict[Tuple, object] = {}
        self._lock = threading.Lock()
        self._default_labels: Dict[str, str] = {}

    # -- creation ----------------------------------------------------------

    def set_default_labels(self, **labels):
        """Merge `labels` into the labels every metric created AFTER this
        call carries (per-rank tagging: fleet.init sets rank=...)."""
        self._default_labels.update({k: str(v) for k, v in labels.items()})

    @property
    def default_labels(self) -> Dict[str, str]:
        return dict(self._default_labels)

    def _get(self, cls, name, labels, **kw):
        merged = dict(self._default_labels)
        merged.update(labels)
        key = (name, cls.kind, _label_key(merged))
        m = self._metrics.get(key)
        if m is None:
            with self._lock:
                m = self._metrics.get(key)
                if m is None:
                    m = cls(name, _label_key(merged), **kw)
                    self._metrics[key] = m
        if kw.get("buckets") is not None \
                and m.bounds != tuple(sorted(kw["buckets"])):
            # get-or-create must not silently hand back a histogram with
            # a DIFFERENT bucket layout than the caller asked for
            raise ValueError(
                f"histogram {name!r}{dict(merged)} already exists with "
                f"buckets {m.bounds}; requested {tuple(sorted(kw['buckets']))}")
        if kw.get("relative_accuracy") is not None \
                and m.relative_accuracy != float(kw["relative_accuracy"]):
            # same contract for sketches: a silently different accuracy
            # would change the error bound callers rely on
            raise ValueError(
                f"sketch {name!r}{dict(merged)} already exists with "
                f"relative_accuracy {m.relative_accuracy}; requested "
                f"{float(kw['relative_accuracy'])}")
        return m

    # positional-only metric names: labels may legitimately be called
    # "name" (e.g. executable.*_bytes{name=...})
    def counter(self, name: str, /, **labels) -> Counter:
        return self._get(Counter, name, labels)

    def gauge(self, name: str, /, **labels) -> Gauge:
        return self._get(Gauge, name, labels)

    def histogram(self, name: str, /, buckets: Optional[Tuple] = None,
                  **labels) -> Histogram:
        return self._get(Histogram, name, labels, buckets=buckets)

    def sketch(self, name: str, /,
               relative_accuracy: Optional[float] = None, **labels):
        """DDSketch-style streaming quantile sketch
        (:class:`observability.slo.QuantileSketch`): bounded relative
        error at ANY quantile — the tool for latency tails, where a
        fixed bucket layout can't promise accuracy. Exported by
        ``prometheus_text`` as a summary with quantile labels."""
        from paddle_tpu.observability.slo import QuantileSketch
        return self._get(QuantileSketch, name, labels,
                         relative_accuracy=relative_accuracy)

    def view(self, **labels) -> "RegistryView":
        """A label-stamping facade over THIS registry: every metric
        created through the view carries ``labels`` merged under the
        caller's own. Storage stays here — ``counter_total`` /
        ``snapshot`` / exporters see the view's series like any other —
        so a Router can tag each replica engine's series
        (``view(replica="0")``) without forking the registry or
        threading labels through every call site."""
        return RegistryView(self, labels)

    # -- export ------------------------------------------------------------

    def snapshot(self) -> List[dict]:
        return [m.snapshot() for m in list(self._metrics.values())]

    def series(self, name: str, kind: Optional[str] = None) -> List:
        """Every live instrument registered under ``name`` (one per
        label set), optionally filtered by kind — the tier-merge and
        burn-rate consumers' accessor."""
        return [m for (n, k, _), m in list(self._metrics.items())
                if n == name and (kind is None or k == kind)]

    def merged_across(self, label: str) -> "MetricsRegistry":
        """A NEW registry with the given label collapsed: counters
        summed, histograms added bucket-wise, sketches merged
        (``QuantileSketch.merge`` — same relative-accuracy bound as one
        sketch over the pooled samples). Gauges are last-value samples
        — summing replicas' queue depths into one number would fake a
        gauge nobody set — so they KEEP the label, one labeled series
        per replica. Series never carrying ``label`` pass through
        unchanged. The result is a plain registry: ``export_jsonl`` /
        ``prometheus_text`` work on it directly
        (``Router.metrics_snapshot`` is this over ``"replica"``)."""
        out = MetricsRegistry()
        for (name, kind, _), m in sorted(list(self._metrics.items()),
                                         key=lambda kv: kv[0]):
            labels = dict(m.labels)
            if kind != "gauge":
                labels.pop(label, None)
            if kind == "counter":
                out.counter(name, **labels).inc(m.value)
            elif kind == "gauge":
                out.gauge(name, **labels).set(m.value)
            elif kind == "histogram":
                h = out.histogram(name, buckets=m.bounds, **labels)
                with m._lock:
                    counts, s, c = list(m.counts), m.sum, m.count
                for i, cv in enumerate(counts):
                    h.counts[i] += cv
                h.sum += s
                h.count += c
            elif kind == "sketch":
                out.sketch(name,
                           relative_accuracy=m.relative_accuracy,
                           **labels).merge(m)
        return out

    def counter_total(self, name: str) -> int:
        """Sum a counter across every label set it was created with —
        e.g. ``counter_total("serving.rejected")`` is total sheds over
        all ``reason`` labels (the shed-rate numerator the load/chaos
        harnesses report)."""
        return sum(m.value for (n, kind, _), m in list(self._metrics.items())
                   if n == name and kind == "counter")

    def export_jsonl(self, path: str, extra: Optional[Dict] = None) -> int:
        """Append one JSON line per metric. The whole snapshot goes out
        as ONE O_APPEND write (``append_jsonl_lines``), so concurrent
        per-rank writers sharing a path interleave only between whole
        snapshots, never inside a line. Returns lines written."""
        ts = time.time()
        lines = []
        for snap in self.snapshot():
            snap["ts"] = ts
            if extra:
                snap.update(extra)
            lines.append(json.dumps(snap))
        return append_jsonl_lines(path, lines)

    def prometheus_text(self) -> str:
        """Prometheus text exposition of the current state."""
        out = []
        seen_types = set()
        for snap in self.snapshot():
            name = _prom_name(snap["name"])
            if name not in seen_types:
                # a sketch is exposed in the summary exposition shape
                # (quantile-labeled gauges + _sum/_count)
                ptype = ("summary" if snap["type"] == "sketch"
                         else snap["type"])
                out.append(f"# TYPE {name} {ptype}")
                seen_types.add(name)
            labels = snap["labels"]
            if snap["type"] == "sketch":
                for q, v in snap["quantiles"].items():
                    if v is not None:
                        out.append(
                            f"{name}{_prom_labels(labels, quantile=q)} {v}")
                out.append(f"{name}_sum{_prom_labels(labels)} {snap['sum']}")
                out.append(f"{name}_count{_prom_labels(labels)} "
                           f"{snap['count']}")
            elif snap["type"] == "histogram":
                cum = 0
                for bound, cnt in snap["buckets"].items():
                    cum += cnt
                    out.append(f"{name}_bucket"
                               f"{_prom_labels(labels, le=bound)} {cum}")
                cum += snap["inf"]
                out.append(f"{name}_bucket"
                           f"{_prom_labels(labels, le='+Inf')} {cum}")
                out.append(f"{name}_sum{_prom_labels(labels)} {snap['sum']}")
                out.append(f"{name}_count{_prom_labels(labels)} "
                           f"{snap['count']}")
            else:
                out.append(f"{name}{_prom_labels(labels)} {snap['value']}")
        return "\n".join(out) + ("\n" if out else "")

    def reset(self):
        """Drop all metrics AND the default labels (test isolation).

        The label drop is deliberate but easy to trip over: after
        ``fleet.init`` has set ``rank=...``, a ``reset()`` leaves the
        registry untagged — metrics created afterwards carry no rank
        until ``set_default_labels`` runs again (re-init, or re-set
        explicitly in tests that reset between phases)."""
        with self._lock:
            self._metrics.clear()
            self._default_labels.clear()


class RegistryView:
    """Label-stamping facade returned by :meth:`MetricsRegistry.view`.

    Quacks like the registry for the metric-producing surface
    (``counter``/``gauge``/``histogram``/``sketch`` — the only methods
    hot paths touch) and delegates storage to the backing registry with
    the view's labels merged UNDER per-call labels (a caller's explicit
    label wins). Reading/exporting goes through the backing registry.
    """

    __slots__ = ("_reg", "_labels")

    def __init__(self, reg: MetricsRegistry, labels: Dict):
        self._reg = reg
        self._labels = {str(k): str(v) for k, v in labels.items()}

    @property
    def backing(self) -> MetricsRegistry:
        return self._reg

    @property
    def labels(self) -> Dict[str, str]:
        return dict(self._labels)

    def _merged(self, labels: Dict) -> Dict:
        merged = dict(self._labels)
        merged.update(labels)
        return merged

    def counter(self, name: str, /, **labels) -> Counter:
        return self._reg.counter(name, **self._merged(labels))

    def gauge(self, name: str, /, **labels) -> Gauge:
        return self._reg.gauge(name, **self._merged(labels))

    def histogram(self, name: str, /, buckets: Optional[Tuple] = None,
                  **labels) -> Histogram:
        return self._reg.histogram(name, buckets=buckets,
                                   **self._merged(labels))

    def sketch(self, name: str, /,
               relative_accuracy: Optional[float] = None, **labels):
        return self._reg.sketch(name, relative_accuracy=relative_accuracy,
                                **self._merged(labels))


def _prom_name(name: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


def _prom_value(v: str) -> str:
    """Escape per the Prometheus exposition format: backslash, double
    quote and newline inside label values."""
    return (str(v).replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


def _prom_labels(labels: Dict, **extra) -> str:
    items = dict(labels)
    items.update({k: str(v) for k, v in extra.items()})
    if not items:
        return ""
    body = ",".join(f'{_prom_name(k)}="{_prom_value(v)}"'
                    for k, v in sorted(items.items()))
    return "{" + body + "}"


_default_registry = MetricsRegistry()


def registry() -> MetricsRegistry:
    """The process-wide default registry."""
    return _default_registry


def set_default_labels(**labels):
    """Tag every metric subsequently created in the default registry
    (e.g. ``set_default_labels(rank=3)`` from fleet.init)."""
    _default_registry.set_default_labels(**labels)
