"""Unified BENCH / span schemas + validation.

Every bench (bench.py, examples/{decode,moe,train,unet}_bench.py) emits
ONE JSON line in the shared ``paddle_tpu.bench/v1`` shape, validated by
``validate_bench`` — the same helper ``examples/scale_report.py
--report`` uses before trusting a bench record's embedded roofline
plan. Spans from ``observability.Tracer`` follow the span schema below,
validated by ``validate_spans``.
"""

import numbers
from typing import Dict, List

__all__ = ["BENCH_SCHEMA", "bench_record", "validate_bench",
           "validate_spans", "validate_roofline_plan"]

BENCH_SCHEMA = "paddle_tpu.bench/v1"

# required field -> accepted types
_BENCH_REQUIRED = {
    "schema": str,
    "metric": str,
    "value": numbers.Real,
    "unit": str,
    "device": str,
}
# optional well-known fields -> accepted types (None always allowed)
_BENCH_OPTIONAL = {
    "timing": str,           # "device(xplane)" | "wall" | ...
    "batch": numbers.Integral,
    "seq": numbers.Integral,
    "steps": numbers.Integral,
    "prompt_len": numbers.Integral,
    "new_tokens": numbers.Integral,
    "params": numbers.Integral,
    "step_time_ms": numbers.Real,
    "wall_step_time_ms": numbers.Real,
    "mfu": numbers.Real,
    # what the mfu denominator/flop count means — the shared key would
    # otherwise conflate activated-params MoE MFU, XLA-counted-flops MFU
    # and the dense 6N estimate: "dense_6n" | "activated" | "xla_counted"
    "mfu_basis": str,
    "final_loss": numbers.Real,
    "roofline_plan": dict,
    "memory": dict,
    # SLO / tail-latency fields (observability.slo.SLOReport.bench_fields
    # emits them): percentile TTFT/TPOT in seconds, offered vs achieved
    # open-loop request rate, and token-weighted goodput under a
    # (slo_ttft_s, slo_tpot_s) target
    "ttft_p50_s": numbers.Real,
    "ttft_p95_s": numbers.Real,
    "ttft_p99_s": numbers.Real,
    "tpot_p50_s": numbers.Real,
    "tpot_p95_s": numbers.Real,
    "tpot_p99_s": numbers.Real,
    "offered_rps": numbers.Real,
    "achieved_rps": numbers.Real,
    "goodput": numbers.Real,
    "slo_ttft_s": numbers.Real,
    "slo_tpot_s": numbers.Real,
    # overload-robustness fields (load_bench --shed / chaos_bench):
    # shed_rate = shed+rejected submissions / offered requests;
    # preemptions / restores are engine counters over the run
    "shed_rate": numbers.Real,
    "preemptions": numbers.Integral,
    "restores": numbers.Integral,
    "lost_requests": numbers.Integral,
    # timeline-export fields (--timeline out.json on the serving
    # benches): where the Perfetto-loadable trace-event JSON landed
    # and how many distinct trace_id chains it carries
    "timeline_path": str,
    "trace_count": numbers.Integral,
    # chunked-prefill fields (load_bench/serving_bench --chunk_tokens):
    # chunk_tokens = the engine's chunk size (null = monolithic wave
    # prefill), prefill_chunks = chunk programs run over the measured
    # pass
    "chunk_tokens": numbers.Integral,
    "prefill_chunks": numbers.Integral,
    # speculative-decoding fields (--speculate k / --proposer):
    # speculate_k = proposals verified per slot per tick (null = off),
    # acceptance_rate = accepted / proposed over the measured pass,
    # accepted_len_hist = {accepted-length: slot-tick count} from the
    # serving.spec_accepted_len histogram buckets
    "speculate_k": numbers.Integral,
    "proposer": str,
    "acceptance_rate": numbers.Real,
    "accepted_len_hist": dict,
    # state-protocol sanitizer field (chaos_bench --roundtrip_every):
    # snapshot->restore->snapshot byte-identity checks run mid-soak
    # (analysis.runtime.snapshot_roundtrip; any drift exits non-zero)
    "roundtrip_checks": numbers.Integral,
    # replicated-tier fields (chaos_bench/load_bench --replicas):
    # replicas = engine replicas behind the serving router (null/1 =
    # single engine), replica_kills = whole-replica kills injected over
    # the run, failovers = dead replicas rebuilt (restore-or-
    # redistribute, each zero-loss)
    "replicas": numbers.Integral,
    "replica_kills": numbers.Integral,
    "failovers": numbers.Integral,
    # tensor-parallel replica fields (serving_bench/load_bench/
    # chaos_bench --mp/--fsdp): mp_degree = model-parallel shards per
    # replica (null/1 = unsharded), fsdp_degree = layer-dim weight
    # shards, mesh_shape = {axis: size} of the replica submesh actually
    # built (e.g. {"mp": 2} or {"fsdp": 2, "mp": 4})
    "mp_degree": numbers.Integral,
    "fsdp_degree": numbers.Integral,
    "mesh_shape": dict,
    # hierarchical-KV offload fields (serving_bench/load_bench/
    # chaos_bench --offload): host_blocks_total = host-RAM block-store
    # capacity summed over replicas, swap_out_bytes / swap_in_bytes =
    # KV bytes through the D2H / H2D swap paths over the measured pass,
    # prefetch_hit_rate = swap-in admissions served from a
    # prefetch-staged device buffer (vs staged on demand)
    "host_blocks_total": numbers.Integral,
    "swap_out_bytes": numbers.Integral,
    "swap_in_bytes": numbers.Integral,
    "prefetch_hit_rate": numbers.Real,
    # prefix-reuse fields: prefix_hit_rate = block-aligned prefill
    # blocks served from a prefix cache (tier-merged across live +
    # retired engines under --replicas); tier_prefix_hit_rate = the
    # router's TierPrefixStore cross-replica share rate (blocks COPIED
    # from a sibling replica instead of recomputed)
    "prefix_hit_rate": numbers.Real,
    "tier_prefix_hit_rate": numbers.Real,
}


def validate_bench(rec: Dict) -> Dict:
    """Validate a BENCH record; raises ValueError listing EVERY problem
    (not just the first). Returns the record unchanged on success."""
    problems = []
    if not isinstance(rec, dict):
        raise ValueError(f"bench record must be a dict, got {type(rec)}")
    for field, typ in _BENCH_REQUIRED.items():
        if field not in rec:
            problems.append(f"missing required field {field!r}")
        elif not isinstance(rec[field], typ) or isinstance(rec[field], bool):
            problems.append(
                f"field {field!r} must be {getattr(typ, '__name__', typ)}, "
                f"got {type(rec[field]).__name__}")
    if rec.get("schema") not in (None, BENCH_SCHEMA):
        problems.append(
            f"schema must be {BENCH_SCHEMA!r}, got {rec.get('schema')!r}")
    for field, typ in _BENCH_OPTIONAL.items():
        v = rec.get(field)
        if v is not None and field in rec and not isinstance(v, typ):
            problems.append(
                f"field {field!r} must be {getattr(typ, '__name__', typ)} "
                f"or null, got {type(v).__name__}")
    for frac in ("goodput", "shed_rate", "acceptance_rate",
                 "prefetch_hit_rate", "prefix_hit_rate",
                 "tier_prefix_hit_rate"):
        g = rec.get(frac)
        if isinstance(g, numbers.Real) and not isinstance(g, bool) \
                and not 0.0 <= g <= 1.0:
            problems.append(f"{frac} must be in [0, 1], got {g}")
    if "roofline_plan" in rec and isinstance(rec["roofline_plan"], dict):
        try:
            validate_roofline_plan(rec["roofline_plan"])
        except ValueError as e:
            problems.append(f"roofline_plan: {e}")
    if problems:
        raise ValueError("invalid BENCH record: " + "; ".join(problems))
    return rec


def bench_record(metric: str, value, unit: str, *, device: str,
                 **extra) -> Dict:
    """Build + validate a BENCH record and mirror its headline value into
    the default registry (gauge ``bench.value{metric=...}``, counter
    ``bench.records``) so the exporters see bench outputs too."""
    rec = {"schema": BENCH_SCHEMA, "metric": metric, "value": value,
           "unit": unit, "device": device}
    rec.update(extra)
    validate_bench(rec)
    try:
        from paddle_tpu.observability.registry import registry as _reg
        r = _reg()
        r.gauge("bench.value", metric=metric, unit=unit).set(value)
        r.counter("bench.records").inc()
    except Exception:
        pass
    return rec


# ---- roofline plan ---------------------------------------------------------

def validate_roofline_plan(plan: Dict) -> Dict:
    """A roofline plan joins measured xplane buckets against analytic
    floors (see profiler.xplane.roofline_report):

      {"hbm_gbps": 819.0, "peak_tflops": 197.0, "steps": 256,
       "phases": [{"name": "decode", "match": ["fused_decode", ...],
                   "bytes_per_step": 1.2e9, "flops_per_step": 0.0}]}
    """
    problems = []
    hbm = plan.get("hbm_gbps")
    if not isinstance(hbm, numbers.Real) or isinstance(hbm, bool) \
            or hbm <= 0:
        problems.append("hbm_gbps (GB/s, positive number) is required")
    if not isinstance(plan.get("steps", 1), numbers.Real):
        problems.append("steps must be a number")
    phases = plan.get("phases")
    if not isinstance(phases, (list, tuple)) or not phases:
        problems.append("phases must be a non-empty list")
    else:
        for i, p in enumerate(phases):
            if not isinstance(p, dict) or not isinstance(p.get("name"), str):
                problems.append(f"phases[{i}].name (str) is required")
                continue
            m = p.get("match")
            if not isinstance(m, (list, tuple)) or not all(
                    isinstance(s, str) for s in m):
                problems.append(f"phases[{i}].match must be a list of "
                                "substrings")
            if not isinstance(p.get("bytes_per_step", 0), numbers.Real):
                problems.append(f"phases[{i}].bytes_per_step must be a "
                                "number")
            if not isinstance(p.get("flops_per_step", 0), numbers.Real):
                problems.append(f"phases[{i}].flops_per_step must be a "
                                "number")
    if problems:
        raise ValueError("; ".join(problems))
    return plan


# ---- spans -----------------------------------------------------------------

_SPAN_REQUIRED = {"name": str, "ts": numbers.Real, "dur_s": numbers.Real}
# attrs the decode.request span must carry (the acceptance contract)
REQUEST_SPAN_ATTRS = ("ttft_s", "tokens_per_sec", "kv_cache_dtype",
                      "kv_cache_bytes")


def validate_spans(spans: List[Dict], require_request: bool = False) -> List:
    """Validate a list of span dicts (``Tracer.span_dicts()`` output).
    With require_request=True additionally asserts a ``decode.request``
    span carrying the TTFT/TPOT/tokens-per-sec + cache attrs."""
    problems = []
    names = set()
    for i, s in enumerate(spans):
        if not isinstance(s, dict):
            problems.append(f"spans[{i}] is not a dict")
            continue
        for field, typ in _SPAN_REQUIRED.items():
            if not isinstance(s.get(field), typ):
                problems.append(f"spans[{i}].{field} must be "
                                f"{typ.__name__}")
        if s.get("dur_s", 0) < 0:
            problems.append(f"spans[{i}].dur_s is negative")
        p = s.get("parent")
        if p is not None and not isinstance(p, str):
            problems.append(f"spans[{i}].parent must be str or null")
        if not isinstance(s.get("attrs", {}), dict):
            problems.append(f"spans[{i}].attrs must be a dict")
        names.add(s.get("name"))
    if require_request:
        reqs = [s for s in spans if isinstance(s, dict)
                and s.get("name") == "decode.request"]
        if not reqs:
            problems.append("no decode.request span present")
        for s in reqs:
            attrs = s.get("attrs", {})
            for a in REQUEST_SPAN_ATTRS:
                if a not in attrs:
                    problems.append(f"decode.request missing attr {a!r}")
            if s.get("attrs", {}).get("max_new_tokens", 2) > 1 \
                    and attrs.get("tpot_s") is None:
                problems.append("decode.request missing attr 'tpot_s'")
    if problems:
        raise ValueError("invalid spans: " + "; ".join(problems))
    return spans
