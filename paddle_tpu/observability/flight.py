"""Serving flight recorder: a fixed-size ring of per-step events.

When a TPOT spike or a pool stall hits a production engine, the gauges
say *that* something went wrong; this module records *what the last N
scheduler steps actually did* so the failure is reconstructable after
the fact. The ``ServingEngine`` writes one compact event per ``step()``
(admissions, retirements with finish reason, occupancy, queue depth,
pool blocks used, prefill wave shapes, per-segment wall times) into a
preallocated ring — steady-state cost is one small dict and a ring
write, no I/O.

``dump_jsonl()`` snapshots the ring to JSONL on demand: one
``paddle_tpu.flight/v1`` header line (reason, timestamp, event count)
followed by the events oldest-first. **Auto-dump** wires the snapshot
to the resilience seams (docs/RESILIENCE.md): a fired ``FaultPlan``
site (``faults._count_fired`` calls :func:`auto_dump_all`), a
``PoolExhausted``, and a deadline retirement each dump the last N
steps — but only when the recorder was given an ``auto_dump_path``
(``ServingEngine(flight_dump_path=...)``); with no path configured
auto-dump is a no-op, so tests and embedded uses never write files as
a side effect.
"""

import json
import logging
import threading
import time
import weakref
from typing import Dict, List, Optional

from paddle_tpu.observability.registry import append_jsonl_lines

logger = logging.getLogger("paddle_tpu.observability")

__all__ = ["FLIGHT_SCHEMA", "FlightRecorder", "auto_dump_all"]

FLIGHT_SCHEMA = "paddle_tpu.flight/v1"

# every live recorder, for auto_dump_all (fault seam); weak so an
# engine's recorder dies with the engine
_recorders: "weakref.WeakSet[FlightRecorder]" = weakref.WeakSet()


class FlightRecorder:
    """Fixed-capacity ring buffer of per-step event dicts.

    ``record`` overwrites the oldest event once ``capacity`` is
    exceeded — the ring always holds exactly the last
    ``min(total_events, capacity)`` events (wraparound pinned by
    tests/test_slo.py). Events must be JSON-serializable.
    """

    def __init__(self, capacity: int = 256,
                 auto_dump_path: Optional[str] = None,
                 name: str = "flight"):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.auto_dump_path = auto_dump_path
        self.name = name
        self._buf: List[Optional[Dict]] = [None] * self.capacity
        self._n = 0                      # total events ever recorded
        self._lock = threading.Lock()
        _recorders.add(self)

    def record(self, event: Dict):
        with self._lock:
            self._buf[self._n % self.capacity] = event
            self._n += 1

    def mark(self, kind: str, **fields):
        """Record a non-step marker event (``{"kind": kind, "ts": ...,
        "ts_mono": ...}`` + fields) — engine restores, operator
        annotations. Markers ride the same ring as step events, so a
        dump shows them in sequence with the scheduler ticks around
        them. ``ts`` is wall-clock (cross-process timeline alignment),
        ``ts_mono`` is ``perf_counter`` (monotonic ordering + exact
        deltas against span clocks, immune to wall-clock steps)."""
        evt = {"kind": kind, "ts": round(time.time(), 6),
               "ts_mono": round(time.perf_counter(), 6)}
        evt.update(fields)
        self.record(evt)

    @property
    def total_events(self) -> int:
        return self._n

    def __len__(self) -> int:
        return min(self._n, self.capacity)

    def _snapshot(self):
        """(events oldest-first, total recorded) under ONE lock hold —
        dump headers must agree with the events they describe even with
        a concurrent recorder thread."""
        with self._lock:
            n, cap = self._n, self.capacity
            if n <= cap:
                return list(self._buf[:n]), n
            start = n % cap
            return self._buf[start:] + self._buf[:start], n

    def events(self) -> List[Dict]:
        """The retained events, oldest first."""
        return self._snapshot()[0]

    def dump(self) -> List[Dict]:
        return self.events()

    def dump_jsonl(self, path: Optional[str] = None,
                   reason: str = "manual") -> Optional[str]:
        """Append a header line + the retained events to ``path``
        (default: ``auto_dump_path``). Returns the path written, or
        None when neither is set. Appending means repeated dumps stack
        in one file; a postmortem reads from the LAST header line."""
        path = path if path is not None else self.auto_dump_path
        if path is None:
            return None
        events, total = self._snapshot()
        header = {"schema": FLIGHT_SCHEMA, "kind": "flight_dump",
                  "name": self.name, "reason": reason,
                  "ts": time.time(), "events": len(events),
                  "total_recorded": total}
        append_jsonl_lines(path, [json.dumps(header)]
                           + [json.dumps(e) for e in events])
        return path

    def auto_dump(self, reason: str) -> Optional[str]:
        """Dump iff an ``auto_dump_path`` is configured (else no-op) —
        the form every resilience-seam trigger calls. NEVER raises: a
        broken dump sink (missing directory, read-only disk) must not
        mask the failure being recorded — the engine calls this while
        re-raising ``PoolExhausted``/injected faults, and an I/O error
        here would replace the real exception. Use ``dump_jsonl``
        directly when a write failure should surface."""
        if self.auto_dump_path is None:
            return None
        try:
            return self.dump_jsonl(self.auto_dump_path, reason=reason)
        except Exception:
            logger.warning("flight recorder %r: auto-dump to %s failed",
                           self.name, self.auto_dump_path, exc_info=True)
            return None


def auto_dump_all(reason: str) -> List[str]:
    """Auto-dump every live recorder (those with a path configured).
    Called from ``resilience.faults`` when a fault fires; like
    ``auto_dump`` it never raises."""
    out = []
    for rec in list(_recorders):
        p = rec.auto_dump(reason)
        if p is not None:
            out.append(p)
    return out
