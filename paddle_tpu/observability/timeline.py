"""Chrome trace-event (Perfetto) timeline export for the serving tier.

The flight rings say what each replica's last N ticks did, the tracer
says what each request cost, and the router journal says which replica
touched which request when — three true but disjoint views. This module
folds all three into ONE Chrome trace-event JSON file
(``chrome://tracing`` / https://ui.perfetto.dev): per-replica process
tracks, per-request thread tracks, tick-segment duration events
(admit / prefill / dispatch / sync, reconstructed from the step
breakdown each flight event carries), journal instants, and **flow
arrows keyed by ``trace_id``** — so a request that was preempted,
resumed, or migrated off a killed replica renders as one connected
chain across process tracks instead of disconnected fragments. This is
the serving-tier analog of the reference profiler's chrome-tracing
export (``paddle/fluid/platform/profiler`` + the timeline tool), driven
by host telemetry instead of device events.

Clock model: every producer stamps wall-clock ``ts`` (spans via the
retirement mapping, flight events directly, journal appends directly)
plus, where available, a monotonic ``ts_mono``. A per-process
:func:`clock_anchor` — ONE ``(perf_counter, time.time)`` pair — lets
the builder re-derive wall time from ``ts_mono`` so cross-replica
ordering is immune to wall-clock steps mid-run; with no anchor the
wall ``ts`` is used as-is.

Nothing here imports jax; the module is postmortem/CLI-side only.
"""

import json
import time
from typing import Dict, Iterable, List, Optional, Sequence

__all__ = ["clock_anchor", "build_timeline", "write_timeline",
           "verify_trace_continuity", "TICK_SEGMENTS"]

#: the per-tick step segments, in dispatch order, with their flight
#: event fields (docs/OBSERVABILITY.md §Timelines)
TICK_SEGMENTS = (("admit", "t_admit_s"), ("prefill", "t_prefill_s"),
                 ("dispatch", "t_dispatch_s"), ("sync", "t_sync_s"))

#: flight tick-event list fields that name requests → per-request
#: instant events; (field, event name, entry shape)
_REQUEST_FIELDS = (("admitted", "admit"), ("retired", "retire"),
                   ("preempted", "preempt"), ("resumed", "resume"),
                   ("shed", "shed"))


def clock_anchor() -> Dict[str, float]:
    """One wall/monotonic clock pair — sample once per process and pass
    it to :func:`build_timeline` so ``ts_mono`` timestamps from that
    process land on the shared wall-clock axis."""
    return {"mono": time.perf_counter(), "wall": time.time()}


def _us(ts: float) -> int:
    return int(round(float(ts) * 1e6))


def _event_ts(evt: Dict, anchor: Optional[Dict]) -> Optional[float]:
    """An event's wall-clock seconds: anchored monotonic when both
    sides exist (immune to wall steps), the recorded wall ``ts``
    otherwise."""
    if anchor is not None and evt.get("ts_mono") is not None:
        return anchor["wall"] + (float(evt["ts_mono"]) - anchor["mono"])
    return evt.get("ts")


class _Builder:
    def __init__(self):
        self.events: List[Dict] = []
        # (pid, rid) -> tid; per-request thread tracks are allocated
        # densely per process above the fixed segment/marker threads
        self._req_tid: Dict = {}
        # trace_id -> [(ts_us, pid, tid, rid)] flow touch points
        self.touches: Dict[str, List] = {}

    def meta(self, pid: int, name: str):
        self.events.append({"ph": "M", "name": "process_name", "pid": pid,
                            "tid": 0, "args": {"name": name}})
        for tid, tname in ((0, "ticks"), (1, "spans"), (2, "markers"),
                           (3, "journal")):
            self.events.append({"ph": "M", "name": "thread_name",
                                "pid": pid, "tid": tid,
                                "args": {"name": tname}})

    def req_tid(self, pid: int, rid) -> int:
        key = (pid, rid)
        tid = self._req_tid.get(key)
        if tid is None:
            tid = 16 + sum(1 for (p, _) in self._req_tid if p == pid)
            self._req_tid[key] = tid
            self.events.append({"ph": "M", "name": "thread_name",
                                "pid": pid, "tid": tid,
                                "args": {"name": f"req {rid}"}})
        return tid

    def duration(self, pid, tid, name, ts_s, dur_s, args=None):
        self.events.append({"ph": "X", "name": name, "pid": pid,
                            "tid": tid, "ts": _us(ts_s),
                            "dur": max(_us(dur_s), 1),
                            "args": args or {}})

    def instant(self, pid, tid, name, ts_s, args=None):
        self.events.append({"ph": "i", "s": "t", "name": name, "pid": pid,
                            "tid": tid, "ts": _us(ts_s),
                            "args": args or {}})

    def touch(self, trace_id, ts_s, pid, tid, rid=None):
        if trace_id:
            self.touches.setdefault(str(trace_id), []).append(
                (_us(ts_s), pid, tid, rid))

    def flows(self):
        """One flow chain per trace_id over its touch points in time
        order — a migrated request's arrow crosses process tracks, the
        failover rendered as geometry."""
        for trace_id, pts in sorted(self.touches.items()):
            pts = sorted(pts)
            if len(pts) < 2:
                continue
            for j, (ts, pid, tid, _) in enumerate(pts):
                ph = "s" if j == 0 else ("f" if j == len(pts) - 1 else "t")
                evt = {"ph": ph, "name": "request", "cat": "trace",
                       "id": trace_id, "pid": pid, "tid": tid, "ts": ts}
                if ph == "f":
                    evt["bp"] = "e"
                self.events.append(evt)


def _flight_event(b: _Builder, pid: int, evt: Dict,
                  anchor: Optional[Dict], trace_map: Dict):
    ts = _event_ts(evt, anchor)
    if ts is None:
        return
    if "kind" in evt:           # marker (mark()): restore/failover/...
        args = {k: v for k, v in evt.items()
                if k not in ("kind", "ts", "ts_mono")
                and isinstance(v, (int, float, str, bool, type(None)))}
        b.instant(pid, 2, evt["kind"], ts, args)
        return
    if "step" not in evt:
        return
    # tick event: segment durations end-aligned at the record stamp
    segs = [(nm, float(evt.get(f) or 0.0)) for nm, f in TICK_SEGMENTS]
    total = sum(d for _, d in segs)
    cursor = ts - total
    for nm, dur in segs:
        if dur > 0.0:
            b.duration(pid, 0, nm, cursor, dur,
                       {"step": evt.get("step")})
        cursor += dur
    if evt.get("err"):
        b.instant(pid, 0, "tick_error", ts, {"err": evt["err"]})
    # per-request instants on their own thread tracks, flow-touched
    for field, name in _REQUEST_FIELDS:
        for entry in evt.get(field) or ():
            rid, extra = (entry[0], entry[1:]) \
                if isinstance(entry, (list, tuple)) else (entry, ())
            args = {"step": evt.get("step")}
            if extra:
                args["detail"] = list(extra)
            tid = b.req_tid(pid, rid)
            b.instant(pid, tid, name, ts, args)
            b.touch(trace_map.get(rid), ts, pid, tid, rid)


def build_timeline(processes: Sequence[Dict],
                   journal: Iterable[Dict] = (),
                   trace_map: Optional[Dict] = None) -> Dict:
    """Fold telemetry into a Chrome trace-event document.

    ``processes``: one dict per process track —
    ``{"name": str, "flight": [events], "spans": [span dicts],
    "anchor": clock_anchor() or None, "pid": optional}``. ``journal``:
    replayed router-journal events (``RouterJournal.replay``); an event
    naming a ``replica`` lands on the process named ``replica_<i>``
    when present, else on the first process. ``trace_map``
    (``{request_id: trace_id}``) supplements the trace ids the journal
    itself carries — single-engine runs (no journal) pass the map from
    their ``RequestResult.trace_id``s.

    Returns ``{"traceEvents": [...], "displayTimeUnit": "ms",
    "otherData": {"trace_count": N}}`` — Perfetto-loadable as-is.
    """
    b = _Builder()
    trace_map = dict(trace_map or {})
    journal = list(journal)
    for evt in journal:         # journal trace ids feed the shared map
        if evt.get("trace_id") is not None and evt.get("rid") is not None:
            trace_map.setdefault(evt["rid"], evt["trace_id"])
    name_to_pid: Dict[str, int] = {}
    for i, proc in enumerate(processes):
        pid = int(proc.get("pid", i))
        name = str(proc.get("name", f"process_{i}"))
        name_to_pid[name] = pid
        b.meta(pid, name)
        anchor = proc.get("anchor")
        for evt in proc.get("flight") or ():
            _flight_event(b, pid, evt, anchor, trace_map)
        for span in proc.get("spans") or ():
            attrs = dict(span.get("attrs") or {})
            rid = attrs.get("request_id")
            trace_id = attrs.get("trace_id") or trace_map.get(rid)
            args = {k: v for k, v in attrs.items()
                    if isinstance(v, (int, float, str, bool, type(None)))}
            tid = b.req_tid(pid, rid) if rid is not None else 1
            b.duration(pid, tid, span["name"], span["ts"],
                       span.get("dur_s", 0.0), args)
            if trace_id:
                b.touch(trace_id, span["ts"], pid, tid, rid)
    jpid = next(iter(name_to_pid.values()), 0)
    for evt in journal:
        kind = evt.get("kind")
        if kind is None or evt.get("ts") is None:
            continue
        pid = name_to_pid.get(f"replica_{evt.get('replica')}", jpid)
        args = {k: v for k, v in evt.items()
                if k not in ("kind", "ts", "tokens", "prompt")
                and isinstance(v, (int, float, str, bool, type(None)))}
        b.instant(pid, 3, f"journal:{kind}", evt["ts"], args)
        if evt.get("rid") is not None:
            b.touch(trace_map.get(evt["rid"]), evt["ts"], pid, 3,
                    evt["rid"])
    b.flows()
    b.events.sort(key=lambda e: (e["ph"] != "M", e.get("ts", 0)))
    return {"traceEvents": b.events, "displayTimeUnit": "ms",
            "otherData": {"trace_count": len(b.touches)}}


def write_timeline(path: str, *, processes: Sequence[Dict],
                   journal: Iterable[Dict] = (),
                   trace_map: Optional[Dict] = None) -> Dict:
    """:func:`build_timeline` to a file; returns
    ``{"path", "events", "trace_count"}`` (the bench-record fields)."""
    doc = build_timeline(processes, journal=journal, trace_map=trace_map)
    with open(path, "w") as f:
        json.dump(doc, f)
    return {"path": path, "events": len(doc["traceEvents"]),
            "trace_count": doc["otherData"]["trace_count"]}


def verify_trace_continuity(journal_events: Iterable[Dict],
                            accepted_rids: Optional[Iterable] = None,
                            require_finish: bool = False) -> List[str]:
    """Check that every accepted request's journal events form ONE
    causally-linked ``trace_id`` chain — the acceptance gate
    ``examples/chaos_bench.py`` runs after a kill-replica chaos drive
    (a broken chain exits non-zero there).

    A chain is broken when an ``accept`` lacks a ``trace_id``, when a
    later ``place``/``finish`` for the same request carries a DIFFERENT
    trace_id (an orphan fragment — e.g. a migration that re-minted
    instead of carrying the id), or when a rid in ``accepted_rids``
    never got an accept event at all. ``require_finish=True``
    additionally demands a finish event per accepted request (the
    zero-loss drain contract). Returns human-readable problems; empty
    means every chain is connected.
    """
    accepts: Dict = {}
    problems: List[str] = []
    for evt in journal_events:
        kind = evt.get("kind")
        rid = evt.get("rid")
        if kind == "accept":
            if rid in accepts:
                problems.append(f"rid {rid}: duplicate accept")
            accepts[rid] = {"trace_id": evt.get("trace_id"),
                            "finished": False}
            if evt.get("trace_id") is None:
                problems.append(f"rid {rid}: accept has no trace_id")
        elif kind in ("place", "finish") and rid in accepts:
            want = accepts[rid]["trace_id"]
            got = evt.get("trace_id")
            if got is None:
                problems.append(f"rid {rid}: {kind} has no trace_id")
            elif want is not None and got != want:
                problems.append(
                    f"rid {rid}: {kind} trace_id {got!r} != accept "
                    f"trace_id {want!r} (orphan fragment)")
            if kind == "finish":
                accepts[rid]["finished"] = True
    rids = set(accepts) if accepted_rids is None else set(accepted_rids)
    for rid in sorted(rids, key=str):
        if rid not in accepts:
            problems.append(f"rid {rid}: accepted but never journaled")
        elif require_finish and not accepts[rid]["finished"]:
            problems.append(f"rid {rid}: no finish event (chain never "
                            f"terminates)")
    return problems
