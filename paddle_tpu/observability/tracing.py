"""Request-level span tracing for the decode path (and anything else).

A `Tracer` collects `Span` records (name, start, duration, parent,
attrs). `Tracer.span(...)` nests a `jax.profiler.TraceAnnotation` so
host-side spans land in xplane captures alongside the device planes —
the RecordEvent analog (SURVEY.md §5), but attached to a *request*, not
a training step.

Zero-overhead contract: nothing in this module runs on the hot path
unless a tracer is attached (`active_tracer()` is one global read).
`inference.generate` keeps its single-dispatch program when no tracer
is attached; with a tracer it switches to a prefill program + chunked
decode programs so TTFT and per-chunk TPOT are real measurements, not
estimates (the chunked scan applies the identical step function, so
tokens are unchanged — pinned by tests/test_observability.py).
"""

import contextlib
import json
import threading
import time
from typing import Callable, List, Optional

from paddle_tpu.observability.registry import (MetricsRegistry,
                                               append_jsonl_lines,
                                               registry as default_registry)

__all__ = ["Span", "Tracer", "attach", "detach", "active_tracer", "trace",
           "run_traced_decode"]


class Span:
    __slots__ = ("name", "ts", "dur_s", "parent", "attrs")

    def __init__(self, name, ts, parent=None, attrs=None):
        self.name = name
        self.ts = ts
        self.dur_s = 0.0
        self.parent = parent
        self.attrs = attrs if attrs is not None else {}

    def to_dict(self) -> dict:
        return {"name": self.name, "ts": self.ts, "dur_s": self.dur_s,
                "parent": self.parent, "attrs": self.attrs}


class Tracer:
    """Collects spans; mirrors request metrics into a registry.

    decode_chunk: tokens per decode dispatch in traced generate() —
    each chunk is one span (and one device dispatch), so smaller chunks
    trade dispatch overhead for span resolution.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 decode_chunk: int = 32, max_spans: int = 100_000):
        self.registry = registry or default_registry()
        self.decode_chunk = int(decode_chunk)
        self.max_spans = max_spans
        self.spans: List[Span] = []
        # per-THREAD open-span stack: concurrent requests against one
        # attached tracer must not cross-parent each other's spans; the
        # completed-spans list is shared, appended under a lock
        self._tls = threading.local()
        self._lock = threading.Lock()

    def _stack(self) -> List[Span]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        import jax

        stack = self._stack()
        s = Span(name, time.time(),
                 parent=stack[-1].name if stack else None,
                 attrs=attrs)
        stack.append(s)
        t0 = time.perf_counter()
        try:
            with jax.profiler.TraceAnnotation(name):
                yield s
        finally:
            s.dur_s = time.perf_counter() - t0
            stack.pop()
            with self._lock:
                if len(self.spans) < self.max_spans:
                    self.spans.append(s)

    def record(self, name: str, ts: float, dur_s: float,
               parent: Optional[str] = None, **attrs) -> Span:
        """Append an already-measured span (no open/close nesting) — for
        producers whose spans interleave across many dispatches, like
        the serving engine's per-request TTFT/TPOT spans: a request's
        lifetime brackets other requests' steps, so a stack-scoped
        context manager can't represent it."""
        s = Span(name, ts, parent=parent, attrs=attrs)
        s.dur_s = float(dur_s)
        with self._lock:
            if len(self.spans) < self.max_spans:
                self.spans.append(s)
        return s

    def span_dicts(self) -> List[dict]:
        return [s.to_dict() for s in self.spans]

    def export_jsonl(self, path: str) -> int:
        """Append one JSON line per span (single O_APPEND write)."""
        return append_jsonl_lines(
            path, (json.dumps(d) for d in self.span_dicts()))


_active: Optional[Tracer] = None


def attach(tracer: Tracer) -> Tracer:
    """Make `tracer` the process-wide active tracer."""
    global _active
    _active = tracer
    return tracer


def detach() -> Optional[Tracer]:
    global _active
    t, _active = _active, None
    return t


def active_tracer() -> Optional[Tracer]:
    return _active


@contextlib.contextmanager
def trace(**tracer_kwargs):
    """`with observability.trace() as t:` — attach a fresh Tracer for the
    block; spans/metrics collected on `t`. Reentrant: a nested trace()
    restores the ENCLOSING tracer on exit (it does not end it)."""
    global _active
    prev = _active
    t = Tracer(**tracer_kwargs)
    attach(t)
    try:
        yield t
    finally:
        if _active is t:
            _active = prev


# ---- the traced decode driver ---------------------------------------------

def run_traced_decode(tracer: Tracer, prefill_call: Callable,
                      decode_call: Callable, *, batch: int,
                      max_new_tokens: int, attrs: dict,
                      deadline_s: Optional[float] = None):
    """Drive a split decode under spans; returns the list of token pieces
    (each (b, n)) to concatenate along axis 1.

    prefill_call() -> (carry, aux); carry[0] is the first sampled token
    (b,). decode_call(carry, aux, i0, nsteps) -> (carry, toks) with toks
    (nsteps, b). Records TTFT (request start → first token *on the
    host*), TPOT (decode span / (new-1)), tokens/s into the tracer's
    registry and onto the request span's attrs.

    deadline_s: per-request wall-clock budget (graceful degradation,
    paddle_tpu.resilience): measured from request start; once a chunk
    boundary finds it spent, the request STOPS and returns the tokens
    produced so far (never fewer than the prefill's first token —
    already-dispatched work is not abandoned), bumping
    ``resilience.deadline_exceeded`` and tagging the request span
    ``deadline_exceeded=True``.

    Sync discipline: each phase is fenced by PULLING token values to the
    host (np.asarray of the tiny token arrays), not block_until_ready —
    through the remote-TPU tunnel block_until_ready returns early (the
    decode_bench methodology), and a dependent host transfer is the only
    fence that holds everywhere.
    """
    import numpy as np

    reg = tracer.registry
    t0 = time.perf_counter()
    with tracer.span("decode.request", batch=batch,
                     max_new_tokens=max_new_tokens, **attrs) as req:
        with tracer.span("decode.prefill",
                         tokens=attrs.get("prompt_len")):
            carry, aux = prefill_call()
            # tpu-lint: allow(host-sync): TTFT fence — tiny token array
            np.asarray(carry[0])
        ttft = time.perf_counter() - t0
        pieces = [carry[0][:, None]]
        i, chunk = 1, max(tracer.decode_chunk, 1)
        cut = False
        while i < max_new_tokens:
            if deadline_s is not None \
                    and time.perf_counter() - t0 >= deadline_s:
                cut = True
                break
            c = min(chunk, max_new_tokens - i)
            with tracer.span("decode.chunk", start=i, tokens=c) as cs:
                carry, toks = decode_call(carry, aux, i, c)
                # tpu-lint: allow(host-sync): chunk fence — tiny array
                np.asarray(toks[-1])
            cs.attrs["tokens_per_sec"] = round(batch * c / cs.dur_s, 1) \
                if cs.dur_s else None
            pieces.append(toks.T)
            i += c
        produced = sum(int(p.shape[1]) for p in pieces)
        dur = time.perf_counter() - t0
        tok_s = batch * produced / dur if dur else 0.0
        tpot = (dur - ttft) / (produced - 1) if produced > 1 else None
        req.attrs.update(ttft_s=round(ttft, 6),
                         tpot_s=round(tpot, 6) if tpot is not None else None,
                         tokens_per_sec=round(tok_s, 1))
        if cut:
            req.attrs.update(deadline_exceeded=True, tokens_produced=produced)
            reg.counter("resilience.deadline_exceeded").inc()
        reg.histogram("decode.ttft_seconds").observe(ttft)
        if tpot is not None:
            reg.histogram("decode.tpot_seconds").observe(tpot)
        reg.counter("decode.requests").inc()
        reg.counter("decode.tokens").inc(batch * produced)
        reg.gauge("decode.tokens_per_sec").set(round(tok_s, 1))
    return pieces
