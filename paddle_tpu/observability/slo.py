"""Streaming latency quantiles + SLO accounting (the percentile layer).

Production serving is judged by p50/p99 TTFT/TPOT and goodput-under-SLO,
not raw tokens/s (ROADMAP "SLO-aware scheduling"). The registry's
fixed-bucket `Histogram` can answer coarse quantile questions
(`Histogram.quantile`, Prometheus-style interpolation), but its
relative error blows up wherever the bucket layout is sparse. This
module adds the precise tool:

* **QuantileSketch** — a DDSketch-style log-spaced-bucket sketch:
  every observation lands in bucket ``ceil(log_gamma(v))`` with
  ``gamma = (1+a)/(1-a)``, so any quantile is answered within relative
  error ``a`` (default 1%) from a few KB of preallocated counts.
  ``observe`` is allocation-free like ``Histogram.observe`` (one log +
  one int add under the lock); registry integration via
  ``registry().sketch(name)`` exports through ``export_jsonl`` and
  ``prometheus_text`` (as a summary with quantile labels).
* **SLOReport** — folds per-request ``(ttft_s, tpot_s, tokens)``
  samples into p50/p95/p99 TTFT/TPOT plus **goodput-under-SLO**: the
  token-weighted fraction of requests meeting a ``(ttft_s, tpot_s)``
  target. ``bench_fields()`` returns the optional percentile fields of
  the ``paddle_tpu.bench/v1`` schema, which is how
  ``examples/load_bench.py`` and ``examples/serving_bench.py`` put
  tail latency on the bench record.

Accuracy contract (pinned by tests/test_slo.py): ``quantile(q)``
returns a value within ``relative_accuracy`` of the sample at rank
``max(1, ceil(q * count))`` — the ``numpy.percentile(...,
method="inverted_cdf")`` convention — for any distribution whose
values lie in ``[min_value, max_value]``.
"""

import logging
import math
import threading
from typing import Dict, Optional, Tuple

logger = logging.getLogger("paddle_tpu.observability")

__all__ = ["QuantileSketch", "SLOReport", "BurnRateWatchdog",
           "DEFAULT_QUANTILES"]

# the quantiles snapshot()/prometheus export answer by default
DEFAULT_QUANTILES = (0.5, 0.9, 0.95, 0.99)


class QuantileSketch:
    """DDSketch-style streaming quantile sketch with bounded relative
    error.

    Bucket ``i`` covers ``(gamma^(i-1), gamma^i]``; the estimate for a
    rank landing in bucket ``i`` is the bucket's harmonic midpoint
    ``2 * gamma^i / (gamma + 1)``, whose relative error against any
    value in the bucket is at most ``relative_accuracy``. Values in
    ``(0, min_value]`` (and the occasional non-positive outlier — e.g.
    a clock-skewed 0-duration) collapse into a zero bucket answered as
    ``0.0``; values above ``max_value`` clamp into the last bucket.
    Estimates are additionally clamped to the observed ``[min, max]``,
    so single-valued streams are answered exactly.
    """

    __slots__ = ("name", "labels", "relative_accuracy", "counts", "count",
                 "sum", "min", "max", "zero_count", "_gamma", "_log_gamma",
                 "_min_value", "_max_value", "_offset", "_lock")
    kind = "sketch"

    def __init__(self, name: str = "", labels: Tuple = (),
                 relative_accuracy: Optional[float] = None,
                 min_value: float = 1e-6, max_value: float = 1e5):
        a = 0.01 if relative_accuracy is None else float(relative_accuracy)
        if not 0.0 < a < 1.0:
            raise ValueError(
                f"relative_accuracy must be in (0, 1), got {a}")
        if not 0.0 < min_value < max_value:
            raise ValueError(
                f"need 0 < min_value < max_value, got "
                f"({min_value}, {max_value})")
        self.name = name
        self.labels = labels
        self.relative_accuracy = a
        self._gamma = (1.0 + a) / (1.0 - a)
        self._log_gamma = math.log(self._gamma)
        self._min_value = float(min_value)
        self._max_value = float(max_value)
        self._offset = int(math.ceil(
            math.log(min_value) / self._log_gamma))
        nb = int(math.ceil(math.log(max_value) / self._log_gamma)) \
            - self._offset + 1
        # preallocated once (1% accuracy over [1e-6, 1e5] s is ~1300
        # ints): observe never allocates, mirroring Histogram
        self.counts = [0] * nb
        self.zero_count = 0
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._lock = threading.Lock()

    def _index(self, v: float) -> int:
        i = int(math.ceil(math.log(v) / self._log_gamma)) - self._offset
        return min(max(i, 0), len(self.counts) - 1)

    def observe(self, v):
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            if v <= self._min_value:
                self.zero_count += 1
            else:
                self.counts[self._index(v)] += 1

    def quantile(self, q: float) -> Optional[float]:
        """Value at rank ``max(1, ceil(q * count))`` within
        ``relative_accuracy`` (None while empty)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        with self._lock:
            if self.count == 0:
                return None
            # the 1e-9 slack keeps ceil() from bumping a rank whose
            # q*count is mathematically integral but lands epsilon high
            # in floats (0.999*5000 = 4995.000000000001) — matching
            # numpy.percentile(method="inverted_cdf") exactly
            rank = max(1, int(math.ceil(q * self.count - 1e-9)))
            if rank <= self.zero_count:
                return max(0.0, self.min)
            cum = self.zero_count
            for i, c in enumerate(self.counts):
                cum += c
                if cum >= rank:
                    est = (2.0 * self._gamma ** (i + self._offset)
                           / (self._gamma + 1.0))
                    return min(max(est, self.min), self.max)
            return self.max

    def count_above(self, v) -> int:
        """Observations above ``v``, answered to bucket granularity:
        whole buckets strictly above the one containing ``v`` — so the
        miscount is confined to the threshold's own bucket, i.e. to
        observations within ``relative_accuracy`` of ``v``. The SLO
        burn-rate numerator (:class:`BurnRateWatchdog`)."""
        v = float(v)
        with self._lock:
            if self.count == 0:
                return 0
            if v < 0.0:
                return self.count
            if v <= self._min_value:
                return self.count - self.zero_count
            return sum(self.counts[self._index(v) + 1:])

    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        """Fold ``other``'s observations into this sketch, bucket-wise.
        Exact in the DDSketch sense: merging per-replica sketches then
        asking a quantile is within ``relative_accuracy`` of the
        pooled-sample quantile, same bound as a single sketch (the
        ``Router.metrics_snapshot`` merge relies on it — pinned by the
        property test in tests/test_slo.py). Both sketches must share
        the bucket geometry (``relative_accuracy`` and the
        ``[min_value, max_value]`` range)."""
        if not isinstance(other, QuantileSketch):
            raise TypeError(f"cannot merge {type(other).__name__} into a "
                            f"QuantileSketch")
        if (other.relative_accuracy != self.relative_accuracy
                or other._min_value != self._min_value
                or other._max_value != self._max_value):
            raise ValueError(
                f"sketch geometry mismatch: cannot merge "
                f"(a={other.relative_accuracy}, range=[{other._min_value}, "
                f"{other._max_value}]) into (a={self.relative_accuracy}, "
                f"range=[{self._min_value}, {self._max_value}])")
        # copy under other's lock, fold under ours — never hold both
        # (two registries merging into each other must not deadlock)
        with other._lock:
            o_counts = list(other.counts)
            o_zero, o_count, o_sum = (other.zero_count, other.count,
                                      other.sum)
            o_min, o_max = other.min, other.max
        with self._lock:
            for i, c in enumerate(o_counts):
                self.counts[i] += c
            self.zero_count += o_zero
            self.count += o_count
            self.sum += o_sum
            if o_min is not None:
                self.min = o_min if self.min is None \
                    else min(self.min, o_min)
            if o_max is not None:
                self.max = o_max if self.max is None \
                    else max(self.max, o_max)
        return self

    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None

    def snapshot(self) -> dict:
        qs = {("%g" % q): self.quantile(q) for q in DEFAULT_QUANTILES}
        return {"name": self.name, "type": self.kind,
                "labels": dict(self.labels),
                "relative_accuracy": self.relative_accuracy,
                "quantiles": qs, "min": self.min, "max": self.max,
                "sum": self.sum, "count": self.count}


def _round6(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(float(v), 6)


class SLOReport:
    """Per-request TTFT/TPOT samples folded into percentiles + goodput.

    ``add(ttft_s, tpot_s, tokens)`` once per finished request
    (``tpot_s=None`` for one-token requests — they have no decode steps
    and cannot miss a TPOT target). A request is *good* when it meets
    BOTH targets; **goodput** is the token-weighted fraction
    ``good_tokens / tokens`` — a 500-token answer that blows its SLO
    costs 500 tokens of goodput, not 1/N of a request count. Targets
    left as ``None`` are not enforced (and with neither set, goodput is
    omitted from ``bench_fields()`` rather than reported as a
    vacuous 1.0).
    """

    def __init__(self, ttft_slo_s: Optional[float] = None,
                 tpot_slo_s: Optional[float] = None,
                 relative_accuracy: float = 0.01):
        self.ttft_slo_s = ttft_slo_s
        self.tpot_slo_s = tpot_slo_s
        self.ttft = QuantileSketch("ttft_s",
                                   relative_accuracy=relative_accuracy)
        self.tpot = QuantileSketch("tpot_s",
                                   relative_accuracy=relative_accuracy)
        self.requests = 0
        self.good_requests = 0
        self.tokens = 0
        self.good_tokens = 0

    def add(self, ttft_s: Optional[float], tpot_s: Optional[float],
            tokens: int = 1) -> bool:
        """Record one finished request; returns whether it met the SLO.

        ``ttft_s=None`` means the request never produced a first token
        (e.g. a chunked-engine slot whose deadline expired mid-prefill
        — ``RequestResult.ttft_s is None``): it is excluded from the
        TTFT percentiles (no sample exists) but, when a TTFT SLO is
        set, counts as MISSING the SLO — a request that died before
        its first token must drag goodput down, not vanish from it."""
        self.requests += 1
        self.tokens += int(tokens)
        if ttft_s is not None:
            self.ttft.observe(ttft_s)
        if tpot_s is not None:
            self.tpot.observe(tpot_s)
        good = not (self.ttft_slo_s is not None
                    and (ttft_s is None or ttft_s > self.ttft_slo_s)) \
            and not (self.tpot_slo_s is not None and tpot_s is not None
                     and tpot_s > self.tpot_slo_s)
        if good:
            self.good_requests += 1
            self.good_tokens += int(tokens)
        return good

    @property
    def goodput(self) -> float:
        """Token-weighted fraction of requests meeting the SLO target."""
        return self.good_tokens / self.tokens if self.tokens else 0.0

    def percentiles(self) -> Dict[str, Optional[float]]:
        return {
            "ttft_p50_s": _round6(self.ttft.quantile(0.5)),
            "ttft_p95_s": _round6(self.ttft.quantile(0.95)),
            "ttft_p99_s": _round6(self.ttft.quantile(0.99)),
            "tpot_p50_s": _round6(self.tpot.quantile(0.5)),
            "tpot_p95_s": _round6(self.tpot.quantile(0.95)),
            "tpot_p99_s": _round6(self.tpot.quantile(0.99)),
        }

    def bench_fields(self) -> Dict:
        """The optional percentile/goodput fields of the
        ``paddle_tpu.bench/v1`` schema (``schema.validate_bench``),
        ready to splat into ``bench_record(...)``."""
        out: Dict = dict(self.percentiles())
        if self.ttft_slo_s is not None or self.tpot_slo_s is not None:
            out["goodput"] = round(self.goodput, 4)
            out["slo_ttft_s"] = self.ttft_slo_s
            out["slo_tpot_s"] = self.tpot_slo_s
        return out


class BurnRateWatchdog:
    """Rolling-window SLO burn-rate tripwire over the registry sketches
    (docs/OBSERVABILITY.md §Burn-rate watchdog).

    The engines stream per-request TTFT/TPOT into the
    ``serving.ttft_s`` / ``serving.tpot_s`` sketches; each
    :meth:`check` reads the cumulative (count, violations-above-SLO)
    totals across every label set of those sketches — so a
    replica-labeled tier sums naturally — and differences them against
    the previous check. The window is therefore "since the last check":

        burn = (window violations / window samples) / error_budget

    A burn of 1.0 means the tier is spending its error budget exactly
    at the sustainable rate; above ``trip_burn`` the watchdog TRIPS:
    it bumps ``serving.slo_watchdog_trips``, auto-dumps every flight
    ring with a path configured (:func:`flight.auto_dump_all`), and —
    when built with ``dump_dir`` — writes a Perfetto timeline slice of
    the tripping source's flight ring so the postmortem starts with a
    picture, not a grep. The per-window burns land in the
    ``serving.slo_ttft_burn_rate`` / ``serving.slo_tpot_burn_rate``
    gauges either way.

    Wired as ``Router(watchdog=BurnRateWatchdog(...))``: the router
    calls ``check(self)`` every ``check_every`` ticks. ``check`` never
    raises — a broken dump sink must not kill the serving tick.
    """

    def __init__(self, ttft_slo_s: Optional[float] = None,
                 tpot_slo_s: Optional[float] = None, *,
                 error_budget: float = 0.1, trip_burn: float = 1.0,
                 min_samples: int = 16, check_every: int = 8,
                 dump_dir: Optional[str] = None, registry=None):
        if ttft_slo_s is None and tpot_slo_s is None:
            raise ValueError("BurnRateWatchdog needs at least one of "
                             "ttft_slo_s / tpot_slo_s")
        if not 0.0 < error_budget <= 1.0:
            raise ValueError(f"error_budget must be in (0, 1], got "
                             f"{error_budget}")
        if check_every < 1 or min_samples < 1:
            raise ValueError("check_every and min_samples must be >= 1")
        self.ttft_slo_s = ttft_slo_s
        self.tpot_slo_s = tpot_slo_s
        self.error_budget = float(error_budget)
        self.trip_burn = float(trip_burn)
        self.min_samples = int(min_samples)
        self.check_every = int(check_every)
        self.dump_dir = dump_dir
        self.registry = registry
        self.trips = 0
        self._last: Dict[str, Tuple[int, int]] = {}

    def _registry(self):
        from paddle_tpu.observability.registry import \
            registry as default_registry
        return self.registry if self.registry is not None \
            else default_registry()

    def _totals(self, name: str, slo: float) -> Tuple[int, int]:
        """Cumulative (samples, violations-above-slo) summed over every
        label set of sketch ``name`` — per-replica series included."""
        count = viol = 0
        for m in self._registry().series(name, kind="sketch"):
            count += m.count
            viol += m.count_above(slo)
        return count, viol

    def check(self, source=None) -> Dict:
        """One watchdog pass. ``source`` (optional) is the tripping
        tier — anything with a ``flight`` ring (the Router); its events
        feed the timeline slice on a trip. Returns
        ``{"burn": {...}, "tripped": [...]}``."""
        reg = self._registry()
        status: Dict = {"burn": {}, "tripped": []}
        for key, metric, slo in (
                ("ttft", "serving.ttft_s", self.ttft_slo_s),
                ("tpot", "serving.tpot_s", self.tpot_slo_s)):
            if slo is None:
                continue
            count, viol = self._totals(metric, slo)
            last_c, last_v = self._last.get(key, (0, 0))
            dc, dv = count - last_c, viol - last_v
            if dc < self.min_samples:
                continue        # window too thin to judge — keep it open
            self._last[key] = (count, viol)
            burn = (dv / dc) / self.error_budget
            reg.gauge(f"serving.slo_{key}_burn_rate").set(round(burn, 4))
            status["burn"][key] = burn
            if burn > self.trip_burn:
                status["tripped"].append(key)
        if status["tripped"]:
            self.trips += 1
            reg.counter("serving.slo_watchdog_trips").inc()
            self._on_trip(status, source)
        return status

    def _on_trip(self, status: Dict, source) -> None:
        from paddle_tpu.observability import flight as _flight

        reason = "slo_burn:" + ",".join(status["tripped"])
        try:
            fl = getattr(source, "flight", None)
            if fl is not None:
                fl.mark("slo_burn_trip",
                        burn={k: round(v, 4)
                              for k, v in status["burn"].items()},
                        tripped=list(status["tripped"]))
            _flight.auto_dump_all(reason)
            if self.dump_dir is not None and fl is not None:
                import os

                from paddle_tpu.observability import timeline as _timeline
                os.makedirs(self.dump_dir, exist_ok=True)
                path = os.path.join(
                    self.dump_dir, f"slo_trip_{self.trips}.json")
                _timeline.write_timeline(
                    path,
                    processes=[{"name": getattr(fl, "name", "tier"),
                                "flight": fl.events()}])
                status["timeline_path"] = path
        except Exception:   # noqa: BLE001 — diagnostics must not raise
            logger.warning("SLO watchdog trip dump failed", exc_info=True)
