"""Streaming latency quantiles + SLO accounting (the percentile layer).

Production serving is judged by p50/p99 TTFT/TPOT and goodput-under-SLO,
not raw tokens/s (ROADMAP "SLO-aware scheduling"). The registry's
fixed-bucket `Histogram` can answer coarse quantile questions
(`Histogram.quantile`, Prometheus-style interpolation), but its
relative error blows up wherever the bucket layout is sparse. This
module adds the precise tool:

* **QuantileSketch** — a DDSketch-style log-spaced-bucket sketch:
  every observation lands in bucket ``ceil(log_gamma(v))`` with
  ``gamma = (1+a)/(1-a)``, so any quantile is answered within relative
  error ``a`` (default 1%) from a few KB of preallocated counts.
  ``observe`` is allocation-free like ``Histogram.observe`` (one log +
  one int add under the lock); registry integration via
  ``registry().sketch(name)`` exports through ``export_jsonl`` and
  ``prometheus_text`` (as a summary with quantile labels).
* **SLOReport** — folds per-request ``(ttft_s, tpot_s, tokens)``
  samples into p50/p95/p99 TTFT/TPOT plus **goodput-under-SLO**: the
  token-weighted fraction of requests meeting a ``(ttft_s, tpot_s)``
  target. ``bench_fields()`` returns the optional percentile fields of
  the ``paddle_tpu.bench/v1`` schema, which is how
  ``examples/load_bench.py`` and ``examples/serving_bench.py`` put
  tail latency on the bench record.

Accuracy contract (pinned by tests/test_slo.py): ``quantile(q)``
returns a value within ``relative_accuracy`` of the sample at rank
``max(1, ceil(q * count))`` — the ``numpy.percentile(...,
method="inverted_cdf")`` convention — for any distribution whose
values lie in ``[min_value, max_value]``.
"""

import math
import threading
from typing import Dict, Optional, Tuple

__all__ = ["QuantileSketch", "SLOReport", "DEFAULT_QUANTILES"]

# the quantiles snapshot()/prometheus export answer by default
DEFAULT_QUANTILES = (0.5, 0.9, 0.95, 0.99)


class QuantileSketch:
    """DDSketch-style streaming quantile sketch with bounded relative
    error.

    Bucket ``i`` covers ``(gamma^(i-1), gamma^i]``; the estimate for a
    rank landing in bucket ``i`` is the bucket's harmonic midpoint
    ``2 * gamma^i / (gamma + 1)``, whose relative error against any
    value in the bucket is at most ``relative_accuracy``. Values in
    ``(0, min_value]`` (and the occasional non-positive outlier — e.g.
    a clock-skewed 0-duration) collapse into a zero bucket answered as
    ``0.0``; values above ``max_value`` clamp into the last bucket.
    Estimates are additionally clamped to the observed ``[min, max]``,
    so single-valued streams are answered exactly.
    """

    __slots__ = ("name", "labels", "relative_accuracy", "counts", "count",
                 "sum", "min", "max", "zero_count", "_gamma", "_log_gamma",
                 "_min_value", "_max_value", "_offset", "_lock")
    kind = "sketch"

    def __init__(self, name: str = "", labels: Tuple = (),
                 relative_accuracy: Optional[float] = None,
                 min_value: float = 1e-6, max_value: float = 1e5):
        a = 0.01 if relative_accuracy is None else float(relative_accuracy)
        if not 0.0 < a < 1.0:
            raise ValueError(
                f"relative_accuracy must be in (0, 1), got {a}")
        if not 0.0 < min_value < max_value:
            raise ValueError(
                f"need 0 < min_value < max_value, got "
                f"({min_value}, {max_value})")
        self.name = name
        self.labels = labels
        self.relative_accuracy = a
        self._gamma = (1.0 + a) / (1.0 - a)
        self._log_gamma = math.log(self._gamma)
        self._min_value = float(min_value)
        self._max_value = float(max_value)
        self._offset = int(math.ceil(
            math.log(min_value) / self._log_gamma))
        nb = int(math.ceil(math.log(max_value) / self._log_gamma)) \
            - self._offset + 1
        # preallocated once (1% accuracy over [1e-6, 1e5] s is ~1300
        # ints): observe never allocates, mirroring Histogram
        self.counts = [0] * nb
        self.zero_count = 0
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._lock = threading.Lock()

    def _index(self, v: float) -> int:
        i = int(math.ceil(math.log(v) / self._log_gamma)) - self._offset
        return min(max(i, 0), len(self.counts) - 1)

    def observe(self, v):
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            if v <= self._min_value:
                self.zero_count += 1
            else:
                self.counts[self._index(v)] += 1

    def quantile(self, q: float) -> Optional[float]:
        """Value at rank ``max(1, ceil(q * count))`` within
        ``relative_accuracy`` (None while empty)."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f"quantile must be in [0, 1], got {q}")
        with self._lock:
            if self.count == 0:
                return None
            # the 1e-9 slack keeps ceil() from bumping a rank whose
            # q*count is mathematically integral but lands epsilon high
            # in floats (0.999*5000 = 4995.000000000001) — matching
            # numpy.percentile(method="inverted_cdf") exactly
            rank = max(1, int(math.ceil(q * self.count - 1e-9)))
            if rank <= self.zero_count:
                return max(0.0, self.min)
            cum = self.zero_count
            for i, c in enumerate(self.counts):
                cum += c
                if cum >= rank:
                    est = (2.0 * self._gamma ** (i + self._offset)
                           / (self._gamma + 1.0))
                    return min(max(est, self.min), self.max)
            return self.max

    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None

    def snapshot(self) -> dict:
        qs = {("%g" % q): self.quantile(q) for q in DEFAULT_QUANTILES}
        return {"name": self.name, "type": self.kind,
                "labels": dict(self.labels),
                "relative_accuracy": self.relative_accuracy,
                "quantiles": qs, "min": self.min, "max": self.max,
                "sum": self.sum, "count": self.count}


def _round6(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(float(v), 6)


class SLOReport:
    """Per-request TTFT/TPOT samples folded into percentiles + goodput.

    ``add(ttft_s, tpot_s, tokens)`` once per finished request
    (``tpot_s=None`` for one-token requests — they have no decode steps
    and cannot miss a TPOT target). A request is *good* when it meets
    BOTH targets; **goodput** is the token-weighted fraction
    ``good_tokens / tokens`` — a 500-token answer that blows its SLO
    costs 500 tokens of goodput, not 1/N of a request count. Targets
    left as ``None`` are not enforced (and with neither set, goodput is
    omitted from ``bench_fields()`` rather than reported as a
    vacuous 1.0).
    """

    def __init__(self, ttft_slo_s: Optional[float] = None,
                 tpot_slo_s: Optional[float] = None,
                 relative_accuracy: float = 0.01):
        self.ttft_slo_s = ttft_slo_s
        self.tpot_slo_s = tpot_slo_s
        self.ttft = QuantileSketch("ttft_s",
                                   relative_accuracy=relative_accuracy)
        self.tpot = QuantileSketch("tpot_s",
                                   relative_accuracy=relative_accuracy)
        self.requests = 0
        self.good_requests = 0
        self.tokens = 0
        self.good_tokens = 0

    def add(self, ttft_s: Optional[float], tpot_s: Optional[float],
            tokens: int = 1) -> bool:
        """Record one finished request; returns whether it met the SLO.

        ``ttft_s=None`` means the request never produced a first token
        (e.g. a chunked-engine slot whose deadline expired mid-prefill
        — ``RequestResult.ttft_s is None``): it is excluded from the
        TTFT percentiles (no sample exists) but, when a TTFT SLO is
        set, counts as MISSING the SLO — a request that died before
        its first token must drag goodput down, not vanish from it."""
        self.requests += 1
        self.tokens += int(tokens)
        if ttft_s is not None:
            self.ttft.observe(ttft_s)
        if tpot_s is not None:
            self.tpot.observe(tpot_s)
        good = not (self.ttft_slo_s is not None
                    and (ttft_s is None or ttft_s > self.ttft_slo_s)) \
            and not (self.tpot_slo_s is not None and tpot_s is not None
                     and tpot_s > self.tpot_slo_s)
        if good:
            self.good_requests += 1
            self.good_tokens += int(tokens)
        return good

    @property
    def goodput(self) -> float:
        """Token-weighted fraction of requests meeting the SLO target."""
        return self.good_tokens / self.tokens if self.tokens else 0.0

    def percentiles(self) -> Dict[str, Optional[float]]:
        return {
            "ttft_p50_s": _round6(self.ttft.quantile(0.5)),
            "ttft_p95_s": _round6(self.ttft.quantile(0.95)),
            "ttft_p99_s": _round6(self.ttft.quantile(0.99)),
            "tpot_p50_s": _round6(self.tpot.quantile(0.5)),
            "tpot_p95_s": _round6(self.tpot.quantile(0.95)),
            "tpot_p99_s": _round6(self.tpot.quantile(0.99)),
        }

    def bench_fields(self) -> Dict:
        """The optional percentile/goodput fields of the
        ``paddle_tpu.bench/v1`` schema (``schema.validate_bench``),
        ready to splat into ``bench_record(...)``."""
        out: Dict = dict(self.percentiles())
        if self.ttft_slo_s is not None or self.tpot_slo_s is not None:
            out["goodput"] = round(self.goodput, 4)
            out["slo_ttft_s"] = self.ttft_slo_s
            out["slo_tpot_s"] = self.tpot_slo_s
        return out
