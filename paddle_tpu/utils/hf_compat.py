"""HuggingFace checkpoint interop.

Reference users come from an ecosystem (PaddleNLP) whose Llama checkpoints
interconvert with HuggingFace's; the TPU-native framework accepts HF
`LlamaForCausalLM` state dicts directly. Our module tree mirrors HF naming
(`model.layers.N.self_attn.q_proj.weight`, ...), so conversion is just
layout: torch `nn.Linear` stores (out, in) while our Linear is (in, out) —
linear weights transpose; embeddings and norms copy through.

Works with torch tensors, numpy arrays, or anything `np.asarray` accepts
(e.g. safetensors slices).
"""

from typing import Dict

import numpy as np

import jax.numpy as jnp

# weights that live in (out, in) torch-Linear layout → transpose
_LINEAR_SUFFIXES = (
    "self_attn.q_proj.weight", "self_attn.k_proj.weight",
    "self_attn.v_proj.weight", "self_attn.o_proj.weight",
    "mlp.gate_proj.weight", "mlp.up_proj.weight", "mlp.down_proj.weight",
    "lm_head.weight",
)
_SKIP_SUBSTRINGS = ("rotary_emb", "masked_bias", "attn.bias")


def _to_np(v):
    if hasattr(v, "detach"):  # torch tensor
        v = v.detach().cpu().float().numpy()
    return np.asarray(v)


def convert_hf_llama_state_dict(hf_state: Dict, dtype=None) -> Dict:
    """HF LlamaForCausalLM state_dict → paddle_tpu Llama state dict."""
    out = {}
    for k, v in hf_state.items():
        if any(s in k for s in _SKIP_SUBSTRINGS):
            continue
        arr = _to_np(v)
        if any(k.endswith(s) for s in _LINEAR_SUFFIXES):
            arr = arr.T
        a = jnp.asarray(arr)
        if dtype is not None:
            a = a.astype(dtype)
        out[k] = a
    return out


def load_hf_llama(model, hf_state: Dict, dtype=None, strict: bool = True):
    """Load a converted HF state into a paddle_tpu LlamaForCausalLM
    (in place); returns the model's new trainable state for functional use.

    strict=True (default) raises if any model parameter was NOT covered by
    the checkpoint — a silent partial load (e.g. a tied-embeddings HF
    checkpoint with no lm_head.weight) would otherwise leave random-init
    weights in place."""
    converted = convert_hf_llama_state_dict(hf_state, dtype=dtype)
    missing, unexpected = model.set_state_dict(converted)
    if strict and missing:
        raise ValueError(
            f"HF checkpoint did not cover model parameters {missing}; "
            "pass strict=False to accept a partial load")
    return model.trainable_state()
