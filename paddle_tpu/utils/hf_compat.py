"""HuggingFace checkpoint interop.

Reference users come from an ecosystem (PaddleNLP) whose Llama checkpoints
interconvert with HuggingFace's; the TPU-native framework accepts HF
`LlamaForCausalLM` state dicts directly. Our module tree mirrors HF naming
(`model.layers.N.self_attn.q_proj.weight`, ...), so conversion is just
layout: torch `nn.Linear` stores (out, in) while our Linear is (in, out) —
linear weights transpose; embeddings and norms copy through.

Works with torch tensors, numpy arrays, or anything `np.asarray` accepts
(e.g. safetensors slices).
"""

from typing import Dict

import numpy as np

import jax.numpy as jnp

# weights that live in (out, in) torch-Linear layout → transpose
_LINEAR_SUFFIXES = (
    "self_attn.q_proj.weight", "self_attn.k_proj.weight",
    "self_attn.v_proj.weight", "self_attn.o_proj.weight",
    "mlp.gate_proj.weight", "mlp.up_proj.weight", "mlp.down_proj.weight",
    "lm_head.weight",
)
_SKIP_SUBSTRINGS = ("rotary_emb", "masked_bias", "attn.bias")


def _to_np(v):
    if hasattr(v, "detach"):  # torch tensor
        v = v.detach().cpu().float().numpy()
    return np.asarray(v)


def convert_hf_llama_state_dict(hf_state: Dict, dtype=None) -> Dict:
    """HF LlamaForCausalLM state_dict → paddle_tpu Llama state dict."""
    out = {}
    for k, v in hf_state.items():
        if any(s in k for s in _SKIP_SUBSTRINGS):
            continue
        arr = _to_np(v)
        if any(k.endswith(s) for s in _LINEAR_SUFFIXES):
            arr = arr.T
        out[k] = _cast(arr, dtype)
    return out


def load_hf_llama(model, hf_state: Dict, dtype=None, strict: bool = True):
    """Load a converted HF state into a paddle_tpu LlamaForCausalLM
    (in place); returns the model's new trainable state for functional use.

    strict=True (default) raises if any model parameter was NOT covered by
    the checkpoint — a silent partial load (e.g. a tied-embeddings HF
    checkpoint with no lm_head.weight) would otherwise leave random-init
    weights in place."""
    converted = convert_hf_llama_state_dict(hf_state, dtype=dtype)
    return _strict_load(model, converted, strict)


def _strict_load(model, converted, strict):
    missing, unexpected = model.set_state_dict(converted)
    if strict and missing:
        raise ValueError(
            f"HF checkpoint did not cover model parameters {missing}; "
            "pass strict=False to accept a partial load")
    return model.trainable_state()


def _cast(arr, dtype):
    a = jnp.asarray(arr)
    return a.astype(dtype) if dtype is not None else a


def convert_hf_gpt2_state_dict(hf_state: Dict, tie_word_embeddings=True,
                               dtype=None) -> Dict:
    """HF GPT2LMHeadModel state_dict → paddle_tpu GPTPretrainModel state.

    HF GPT-2 stores its projections as Conv1D — (in, out) layout, the SAME
    as our Linear — so unlike Llama, no transposes except the (out, in)
    lm_head. Key renames: transformer.* → gpt.*, attn.c_attn → attn.qkv_proj,
    attn.c_proj → attn.out_proj, mlp.c_fc → fc_in, mlp.c_proj → fc_out.
    """
    rename = (("transformer.", "gpt."),
              ("attn.c_attn", "attn.qkv_proj"),
              ("attn.c_proj", "attn.out_proj"),
              ("mlp.c_fc", "fc_in"),
              ("mlp.c_proj", "fc_out"))
    out = {}
    for k, v in hf_state.items():
        # GPT-2's causal-mask buffers are `.attn.bias`/`.attn.masked_bias`;
        # the substring rule would also eat the real `c_attn.bias`
        if k.endswith(".attn.bias") or k.endswith(".attn.masked_bias"):
            continue
        if k == "lm_head.weight":
            if tie_word_embeddings:
                # tied to wte — our tied model unembeds with wte.T. Guard
                # against a genuinely untied checkpoint being silently
                # truncated to the embedding weights.
                wte = hf_state.get("transformer.wte.weight")
                if wte is not None and not np.array_equal(_to_np(v),
                                                          _to_np(wte)):
                    raise ValueError(
                        "lm_head.weight differs from transformer.wte.weight "
                        "but the target model is tie_word_embeddings=True — "
                        "build the model untied to keep the trained head")
                continue
            out[k] = _cast(_to_np(v).T, dtype)
            continue
        nk = k
        for old, new in rename:  # rename table also strips the mlp. prefix
            nk = nk.replace(old, new)
        out[nk] = _cast(_to_np(v), dtype)
    return out


def load_hf_gpt2(model, hf_state: Dict, dtype=None, strict: bool = True):
    """Load an HF GPT2LMHeadModel state_dict into a paddle_tpu
    GPTPretrainModel (in place); returns the new trainable state."""
    tied = getattr(model.cfg, "tie_word_embeddings", True)
    converted = convert_hf_gpt2_state_dict(
        hf_state, tie_word_embeddings=tied, dtype=dtype)
    return _strict_load(model, converted, strict)


def convert_hf_mixtral_state_dict(hf_state: Dict, dtype=None) -> Dict:
    """HF MixtralForCausalLM state_dict → paddle_tpu MixtralForCausalLM.

    Attention/lm_head linears transpose like Llama. The sparse-MoE block
    regroups: HF's per-expert `block_sparse_moe.experts.E.{w1,w2,w3}.weight`
    ((out, in) each) stack into our grouped (E, in, out) tensors
    `moe.experts.{w_gate,w_down,w_up}`, and the (E, h) router
    `block_sparse_moe.gate.weight` transposes into `moe.gate.proj.weight`.
    """
    import re
    out = {}
    experts = {}  # (layer, expert, which) -> np array
    exp_re = re.compile(
        r"model\.layers\.(\d+)\.block_sparse_moe\.experts\.(\d+)\.(w[123])\.weight")
    for k, v in hf_state.items():
        if any(s in k for s in _SKIP_SUBSTRINGS):
            continue
        m = exp_re.match(k)
        if m:
            layer, eidx, which = int(m.group(1)), int(m.group(2)), m.group(3)
            experts[(layer, eidx, which)] = _to_np(v).T  # (in, out)
            continue
        arr = _to_np(v)
        if k.endswith("block_sparse_moe.gate.weight"):
            nk = k.replace("block_sparse_moe.gate.weight", "moe.gate.proj.weight")
            out[nk] = _cast(arr.T, dtype)  # (E, h) → (h, E)
            continue
        if any(k.endswith(s) for s in _LINEAR_SUFFIXES):
            arr = arr.T
        out[k] = _cast(arr, dtype)
    if experts:
        n_layers = max(k[0] for k in experts) + 1
        n_exp = max(k[1] for k in experts) + 1
        names = {"w1": "w_gate", "w3": "w_up", "w2": "w_down"}
        for layer in range(n_layers):
            for which, ours in names.items():
                # a sharded/partial checkpoint may miss some experts for
                # this (layer, which) group: leave the grouped tensor out
                # so _strict_load reports it as missing, rather than
                # KeyError-ing mid-conversion
                group = [(layer, e, which) for e in range(n_exp)]
                if not all(g in experts for g in group):
                    continue
                stack = np.stack([experts[g] for g in group])
                out[f"model.layers.{layer}.moe.experts.{ours}"] = _cast(
                    stack, dtype)
    return out


def load_hf_mixtral(model, hf_state: Dict, dtype=None, strict: bool = True):
    """Load an HF MixtralForCausalLM state_dict into a paddle_tpu
    MixtralForCausalLM (in place); returns the new trainable state."""
    converted = convert_hf_mixtral_state_dict(hf_state, dtype=dtype)
    return _strict_load(model, converted, strict)
