"""NaN/Inf watcher (≈ FLAGS_check_nan_inf walking op outputs —
paddle/fluid/framework/details/nan_inf_utils_detail.cc).

TPU-native: per-op scanning would break fusion; instead scan the step's
OUTPUT pytrees (loss/grads/params) — one fused reduction per tensor — plus
jax's debug_nans for eager pinpointing.
"""

import jax
import jax.numpy as jnp

from paddle_tpu.core.flags import flag


def tree_nonfinite_count(tree):
    leaves = [l for l in jax.tree_util.tree_leaves(tree)
              if hasattr(l, "dtype") and jnp.issubdtype(l.dtype, jnp.floating)]
    if not leaves:
        return jnp.zeros((), jnp.int32)
    return sum(jnp.sum(~jnp.isfinite(l)).astype(jnp.int32) for l in leaves)


def check_numerics(tree, name="tensors", raise_error=True):
    """Host-side check (call on step outputs when FLAGS_check_nan_inf)."""
    if not flag("FLAGS_check_nan_inf"):
        return True
    n = int(tree_nonfinite_count(tree))
    if n:
        msg = f"[paddle_tpu] {n} non-finite values detected in {name}"
        if raise_error:
            raise FloatingPointError(msg)
        print(msg)
        return False
    return True


def nan_inf_guard(step_fn):
    """Wrap a train step: after each call, scan loss/grads when the flag is on."""
    def wrapped(*args, **kwargs):
        out = step_fn(*args, **kwargs)
        if flag("FLAGS_check_nan_inf"):
            check_numerics(out, name="train step outputs")
        return out
    return wrapped


def enable_debug_nans(enable=True):
    jax.config.update("jax_debug_nans", enable)
