"""NaN/Inf watcher (≈ FLAGS_check_nan_inf walking op outputs —
paddle/fluid/framework/details/nan_inf_utils_detail.cc).

TPU-native: per-op scanning would break fusion; instead scan the step's
OUTPUT pytrees (loss/grads/params) — ONE jitted fused reduction over the
whole tree (a single device program, one scalar host sync) — plus jax's
debug_nans for eager pinpointing. Detections log through `logging` and
bump the ``numerics.nonfinite_detected`` registry counter, so fleet-wide
NaN storms show up in the JSONL/Prometheus exporters; this is also the
primitive behind `ElasticTrainLoop`'s non-finite skip/rewind policy
(paddle_tpu.resilience).
"""

import logging

import jax
import jax.numpy as jnp

from paddle_tpu.core.flags import flag

logger = logging.getLogger("paddle_tpu.nan_inf")


@jax.jit
def _fused_nonfinite_count(leaves):
    # one compiled program for the WHOLE tree: per-leaf reductions fuse
    # into a single device dispatch, vs the old eager per-leaf jnp.sum +
    # Python sum that issued (and synced) one tiny program per leaf
    total = jnp.zeros((), jnp.int32)
    for leaf in leaves:
        total = total + jnp.sum(~jnp.isfinite(leaf)).astype(jnp.int32)
    return total


def tree_nonfinite_count(tree):
    leaves = [l for l in jax.tree_util.tree_leaves(tree)
              if hasattr(l, "dtype") and jnp.issubdtype(l.dtype, jnp.floating)]
    if not leaves:
        return jnp.zeros((), jnp.int32)
    return _fused_nonfinite_count(leaves)


def check_numerics(tree, name="tensors", raise_error=True):
    """Host-side check (call on step outputs when FLAGS_check_nan_inf)."""
    if not flag("FLAGS_check_nan_inf"):
        return True
    n = int(tree_nonfinite_count(tree))
    if n:
        from paddle_tpu.observability import registry
        registry().counter("numerics.nonfinite_detected").inc()
        msg = f"[paddle_tpu] {n} non-finite values detected in {name}"
        if raise_error:
            raise FloatingPointError(msg)
        logger.warning(msg)
        return False
    return True


def nan_inf_guard(step_fn):
    """Wrap a train step: after each call, scan loss/grads when the flag is on."""
    def wrapped(*args, **kwargs):
        out = step_fn(*args, **kwargs)
        if flag("FLAGS_check_nan_inf"):
            check_numerics(out, name="train step outputs")
        return out
    return wrapped


def enable_debug_nans(enable=True):
    jax.config.update("jax_debug_nans", enable)
