from paddle_tpu.utils.nan_inf import check_numerics, nan_inf_guard  # noqa: F401
from paddle_tpu.utils import recompute  # noqa: F401
from paddle_tpu.utils.recompute import recompute as recompute_fn  # noqa: F401
