"""Activation recompute (≈ paddle.distributed.fleet.utils.recompute —
PyLayer segment replay with RNG state restore, fleet/recompute/recompute.py).

TPU-native: jax.checkpoint IS recompute — XLA rematerializes the segment in
backward, and functional RNG keys replay identically by construction (no RNG
state save/restore machinery needed).
"""

import functools

import jax


def recompute(function, *args, preserve_rng_state=True, use_reentrant=True,
              policy=None, **kwargs):
    """Checkpoint `function(*args)` — gradients recompute the forward."""
    ck = jax.checkpoint(function, policy=policy)
    return ck(*args, **kwargs)


def recompute_sequential(functions, x, segments=1):
    """Checkpoint a sequence in `segments` chunks (recompute_sequential parity)."""
    funcs = list(functions)
    n = len(funcs)
    seg_size = max(1, n // max(segments, 1))

    def run_segment(fs):
        def seg(y):
            for f in fs:
                y = f(y)
            return y
        return seg

    i = 0
    while i < n:
        seg = run_segment(funcs[i:i + seg_size])
        x = jax.checkpoint(seg)(x)
        i += seg_size
    return x


def recompute_wrapper(policy=None):
    def deco(fn):
        return functools.wraps(fn)(jax.checkpoint(fn, policy=policy))
    return deco
