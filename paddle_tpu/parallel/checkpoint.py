"""Distributed checkpointing: sharding-aware save/load with resharding.

Reference (SURVEY.md §5-checkpoint): per-rank shard files via
`fleet.save_persistables`, unified dist checkpoint with re-sharding on load
in python/paddle/distributed/checkpoint/{save_state_dict,load_state_dict}.py.

TPU-native: Orbax. Arrays save with their shardings (each host writes its
shards — multi-host safe); on load the caller supplies target shardings and
Orbax reshards, so a checkpoint written on an mp×pp×sharding mesh restores
onto any other topology — the 65B resume-across-topologies requirement.
`CheckpointManager` adds step numbering, keep-K retention, async save and
latest-step auto-resume (the launcher's restart-from-checkpoint recovery).
"""

import os
from typing import Any, Dict, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.StandardCheckpointer()


def _lookup(table, full_key):
    """Look a leaf up by full dotted path, falling back to the bare leaf
    name (the pre-path-keyed convention) when the path key is absent."""
    if full_key in table:
        return table[full_key]
    return table.get(full_key.rsplit(".", 1)[-1])


def _abstract_target(tree, shardings=None, mesh: Optional[Mesh] = None,
                     specs=None, _prefix=""):
    """Abstract pytree with target shardings for resharding-on-load.

    `tree` may hold real arrays OR jax.ShapeDtypeStruct. Shardings come from
    `shardings` ({dotted.path: Sharding}, bare leaf names accepted), or
    (mesh, specs {dotted.path: PartitionSpec}), or the arrays' current
    shardings. Nested dicts are keyed by full dotted path so repeated leaf
    names (e.g. every layer's 'weight') don't collide.
    """
    def one(full_key, leaf):
        sh = None
        if isinstance(shardings, dict):
            sh = _lookup(shardings, full_key)
            if sh is None:
                raise KeyError(
                    f"shardings has no entry for {full_key!r} (neither the "
                    "dotted path nor the bare leaf name)")
        elif mesh is not None:
            spec = _lookup(specs or {}, full_key)
            sh = NamedSharding(mesh, spec if spec is not None else P())
        elif isinstance(leaf, jax.Array) and hasattr(leaf, "sharding"):
            sh = leaf.sharding
        return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=sh)

    if isinstance(tree, dict):
        out = {}
        for k, v in tree.items():
            full = f"{_prefix}{k}"
            if isinstance(v, dict):
                out[k] = _abstract_target(v, shardings, mesh, specs,
                                          _prefix=full + ".")
            else:
                out[k] = one(full, v)
        return out

    def generic(leaf):
        sh = leaf.sharding if isinstance(leaf, jax.Array) else getattr(
            leaf, "sharding", None)
        return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=sh)

    return jax.tree_util.tree_map(generic, tree)


def save_state_dict(state_dict: Dict[str, Any], path: str):
    """Save a (possibly sharded) pytree of arrays to `path` (a directory)."""
    ckptr = _checkpointer()
    ckptr.save(os.path.abspath(path), state_dict, force=True)
    ckptr.wait_until_finished()


def load_state_dict(path: str, target=None, mesh: Optional[Mesh] = None,
                    specs=None, shardings=None):
    """Load from `path`. With `target` (pytree of arrays or ShapeDtypeStruct)
    and/or (mesh, specs) the restore reshards onto the requested placement;
    with nothing it restores as saved (single-process)."""
    ckptr = _checkpointer()
    path = os.path.abspath(path)
    if target is None and mesh is None and shardings is None:
        return ckptr.restore(path)
    abstract = _abstract_target(target, shardings=shardings, mesh=mesh,
                                specs=specs) if target is not None else None
    return ckptr.restore(path, abstract)


class CheckpointManager:
    """Step-numbered checkpoints with retention, async save and auto-resume.

    Parity: the reference launcher's restart-from-checkpoint loop + 2.6's
    unified dist checkpoint; implemented over orbax.CheckpointManager.
    """

    def __init__(self, directory: str, max_to_keep: int = 5,
                 save_interval_steps: int = 1, async_save: bool = True):
        import orbax.checkpoint as ocp
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=async_save)
        self._mngr = ocp.CheckpointManager(self._dir, options=self._options)

    def save(self, step: int, state: Dict[str, Any], force: bool = False):
        import orbax.checkpoint as ocp
        return self._mngr.save(step, args=ocp.args.StandardSave(state),
                               force=force)

    def restore(self, step: Optional[int] = None, target=None,
                mesh: Optional[Mesh] = None, specs=None):
        import orbax.checkpoint as ocp
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        abstract = (_abstract_target(target, mesh=mesh, specs=specs)
                    if target is not None else None)
        if abstract is None:
            return self._mngr.restore(step)
        return self._mngr.restore(step,
                                  args=ocp.args.StandardRestore(abstract))

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def all_steps(self):
        return self._mngr.all_steps()

    def wait_until_finished(self):
        self._mngr.wait_until_finished()

    def close(self):
        self._mngr.close()


def save_persistables(model, optimizer=None, path: str = "checkpoint",
                      opt_state=None):
    """fleet.save_persistables parity: model (+optimizer) state to `path`."""
    tree = {"model": model.state_dict()}
    if opt_state is not None:
        tree["optimizer"] = opt_state
    save_state_dict(tree, path)
