"""Distributed checkpointing: sharding-aware save/load with resharding.

Reference (SURVEY.md §5-checkpoint): per-rank shard files via
`fleet.save_persistables`, unified dist checkpoint with re-sharding on load
in python/paddle/distributed/checkpoint/{save_state_dict,load_state_dict}.py.

TPU-native: Orbax. Arrays save with their shardings (each host writes its
shards — multi-host safe); on load the caller supplies target shardings and
Orbax reshards, so a checkpoint written on an mp×pp×sharding mesh restores
onto any other topology — the 65B resume-across-topologies requirement.
`CheckpointManager` adds step numbering, keep-K retention, async save and
latest-step auto-resume (the launcher's restart-from-checkpoint recovery).
"""

import os
from typing import Any, Dict, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.StandardCheckpointer()


def _lookup(table, full_key):
    """Look a leaf up by full dotted path, falling back to the bare leaf
    name (the pre-path-keyed convention) when the path key is absent."""
    if full_key in table:
        return table[full_key]
    return table.get(full_key.rsplit(".", 1)[-1])


def _abstract_target(tree, shardings=None, mesh: Optional[Mesh] = None,
                     specs=None, _prefix=""):
    """Abstract pytree with target shardings for resharding-on-load.

    `tree` may hold real arrays OR jax.ShapeDtypeStruct. Shardings come from
    `shardings` ({dotted.path: Sharding}, bare leaf names accepted), or
    (mesh, specs {dotted.path: PartitionSpec}), or the arrays' current
    shardings. Nested dicts are keyed by full dotted path so repeated leaf
    names (e.g. every layer's 'weight') don't collide.
    """
    def one(full_key, leaf):
        sh = None
        if isinstance(shardings, dict):
            sh = _lookup(shardings, full_key)
            if sh is None:
                raise KeyError(
                    f"shardings has no entry for {full_key!r} (neither the "
                    "dotted path nor the bare leaf name)")
        elif mesh is not None:
            spec = _lookup(specs or {}, full_key)
            sh = NamedSharding(mesh, spec if spec is not None else P())
        elif isinstance(leaf, jax.Array) and hasattr(leaf, "sharding"):
            sh = leaf.sharding
        return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=sh)

    if isinstance(tree, dict):
        out = {}
        for k, v in tree.items():
            full = f"{_prefix}{k}"
            if isinstance(v, dict):
                out[k] = _abstract_target(v, shardings, mesh, specs,
                                          _prefix=full + ".")
            else:
                out[k] = one(full, v)
        return out

    def generic(leaf):
        sh = leaf.sharding if isinstance(leaf, jax.Array) else getattr(
            leaf, "sharding", None)
        return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=sh)

    return jax.tree_util.tree_map(generic, tree)


def save_state_dict(state_dict: Dict[str, Any], path: str):
    """Save a (possibly sharded) pytree of arrays to `path` (a directory)."""
    ckptr = _checkpointer()
    ckptr.save(os.path.abspath(path), state_dict, force=True)
    ckptr.wait_until_finished()


def load_state_dict(path: str, target=None, mesh: Optional[Mesh] = None,
                    specs=None, shardings=None):
    """Load from `path`. With `target` (pytree of arrays or ShapeDtypeStruct)
    and/or (mesh, specs) the restore reshards onto the requested placement;
    with nothing it restores as saved (single-process)."""
    ckptr = _checkpointer()
    path = os.path.abspath(path)
    if target is None and mesh is None and shardings is None:
        return ckptr.restore(path)
    abstract = _abstract_target(target, shardings=shardings, mesh=mesh,
                                specs=specs) if target is not None else None
    return ckptr.restore(path, abstract)


class CheckpointManager:
    """Step-numbered checkpoints with retention, async save, auto-resume
    and integrity manifests.

    Parity: the reference launcher's restart-from-checkpoint loop + 2.6's
    unified dist checkpoint; implemented over orbax.CheckpointManager.

    Integrity (paddle_tpu.resilience.integrity, on by default): every
    completed save commits a manifest — per-file size+crc32 and
    (``tensor_checksums``; defaults to sync-saves-only since it
    host-pulls the whole state) per-tensor checksums — under
    ``<directory>/integrity/step_<N>.json``, written only AFTER the data
    is durable (async saves flush manifests on ``wait_until_finished`` /
    the next ``save``). The manifest is the step's commit marker:
    ``verified_latest_step()`` walks back past steps with no manifest
    (save never committed) or mismatched files (corruption), which is
    what ``ElasticTrainLoop`` resumes from — one torn latest checkpoint
    no longer means a permanent crash loop.
    """

    def __init__(self, directory: str, max_to_keep: int = 5,
                 save_interval_steps: int = 1, async_save: bool = True,
                 integrity: bool = True,
                 tensor_checksums: Optional[bool] = None):
        import orbax.checkpoint as ocp
        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=save_interval_steps,
            enable_async_checkpointing=async_save)
        self._mngr = ocp.CheckpointManager(self._dir, options=self._options)
        self._async = async_save
        self._integrity = integrity
        # per-tensor checksums host-pull + crc the WHOLE state on the
        # caller thread at save() — defeating exactly the stall an async
        # save exists to avoid, for a deep-verify mode nothing on the
        # default resume path consumes. Default: on for sync saves
        # (tests, small models — full end-to-end verification), off for
        # async (file-level manifests still catch truncation/bit-rot).
        self._tensor_checksums = (not async_save if tensor_checksums is None
                                  else tensor_checksums)
        self._pending: Dict[int, Optional[dict]] = {}

    def save(self, step: int, state: Dict[str, Any], force: bool = False):
        import orbax.checkpoint as ocp
        from paddle_tpu.resilience import faults as _faults
        from paddle_tpu.resilience import integrity as _integ

        # cooperative fault site: kind='corrupt_checkpoint' damages the
        # files AFTER the commit below — the torn/bit-rotted checkpoint
        # verified_latest_step() exists to walk past
        fault = _faults.maybe_fire("checkpoint.save", index=int(step))
        if self._integrity and self._pending:
            # a new save waits for the previous async commit anyway
            # (orbax serializes); manifest those now-durable steps first
            self._mngr.wait_until_finished()
            self._flush_manifests()
        saved = self._mngr.save(step, args=ocp.args.StandardSave(state),
                                force=force)
        if saved and self._integrity:
            self._pending[int(step)] = (
                _integ.tensor_checksums(state)
                if self._tensor_checksums else None)
            if not self._async:
                self._flush_manifests()
        if fault is not None and fault.kind == "corrupt_checkpoint":
            if not saved:
                # nothing was written (save_interval skip): give the fire
                # back so the plan's fired()/pending() stay honest — a
                # wider `count` window can then still hit a real save
                # instead of the budget silently evaporating on a no-op
                fault.refund()
            else:
                self.wait_until_finished()  # durable + manifest committed
                step_dir = self._step_dir(step)
                if step_dir is not None:
                    _integ.corrupt_checkpoint(
                        step_dir,
                        mode=fault.payload.get("mode", "truncate"))
        return saved

    def restore(self, step: Optional[int] = None, target=None,
                mesh: Optional[Mesh] = None, specs=None):
        import orbax.checkpoint as ocp
        step = self.latest_step() if step is None else step
        if step is None:
            return None
        abstract = (_abstract_target(target, mesh=mesh, specs=specs)
                    if target is not None else None)
        if abstract is None:
            return self._mngr.restore(step)
        return self._mngr.restore(step,
                                  args=ocp.args.StandardRestore(abstract))

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def all_steps(self):
        return self._mngr.all_steps()

    def wait_until_finished(self):
        self._mngr.wait_until_finished()
        if self._integrity:
            self._flush_manifests()

    def close(self):
        self.wait_until_finished()
        self._mngr.close()

    # -- integrity ---------------------------------------------------------

    def _step_dir(self, step: int) -> Optional[str]:
        """The orbax step directory (plain str(step) on current orbax;
        scan tolerates prefixed/padded layouts)."""
        cand = os.path.join(self._dir, str(int(step)))
        if os.path.isdir(cand):
            return cand
        for fn in os.listdir(self._dir):
            digits = "".join(c for c in fn if c.isdigit())
            p = os.path.join(self._dir, fn)
            if os.path.isdir(p) and digits and int(digits) == int(step):
                return p
        return None

    def _flush_manifests(self):
        """Commit manifests for saves whose data is durable, and prune
        manifests orphaned by keep-K retention. Callers must ensure the
        orbax save finished (wait_until_finished) first."""
        from paddle_tpu.resilience import integrity as _integ

        live = set(self._mngr.all_steps())
        for step in sorted(self._pending):
            tensors = self._pending.pop(step)
            if step not in live:
                continue            # already reaped by retention
            step_dir = self._step_dir(step)
            if step_dir is None:
                continue
            _integ.write_manifest(self._dir, step,
                                  _integ.file_checksums(step_dir), tensors)
        man_dir = os.path.join(self._dir, _integ.MANIFEST_SUBDIR)
        if os.path.isdir(man_dir):
            for fn in os.listdir(man_dir):
                digits = "".join(c for c in fn if c.isdigit())
                if digits and int(digits) not in live:
                    try:
                        os.unlink(os.path.join(man_dir, fn))
                    except OSError:
                        pass

    def verify_step(self, step: int, deep: bool = False):
        """(ok, reason). Fast mode checks the commit manifest + every
        file's size/crc32; ``deep=True`` additionally RESTORES the step
        and compares per-tensor checksums (end-to-end, needs
        tensor_checksums=True at save time)."""
        from paddle_tpu.resilience import integrity as _integ

        manifest = _integ.read_manifest(self._dir, step)
        if manifest is None:
            return False, "no integrity manifest (save never committed?)"
        step_dir = self._step_dir(step)
        if step_dir is None:
            return False, "step directory missing"
        ok, reason = _integ.verify_files(manifest, step_dir)
        if not ok or not deep:
            return ok, reason
        try:
            state = self._mngr.restore(step)
        except Exception as e:  # noqa: BLE001 — any failure = unverified
            return False, f"restore failed: {type(e).__name__}: {e}"
        return _integ.verify_tensors(manifest, state)

    def verified_latest_step(self, deep: bool = False,
                             quarantine: bool = True) -> Optional[int]:
        """Newest step that passes integrity verification, walking back
        past incomplete/corrupt steps (each skip increments
        ``resilience.checkpoint_corrupt_skipped``). With ``quarantine``
        (default) a step failing with a DETERMINISTIC content mismatch
        (size/crc/tensor) is DELETED as it is skipped, so a plain
        ``latest_step()`` caller (or the re-save of that step number
        after the resumed run catches back up) never lands on known-bad
        data; transient-looking failures (unreadable file, missing
        manifest) are walked past but left on disk — deleting a
        checkpoint over an I/O blip would turn a recoverable error into
        data loss. Checkpoints written without integrity (no manifest
        anywhere) fall back to ``latest_step()`` so pre-existing runs
        still resume."""
        from paddle_tpu.resilience import integrity as _integ
        from paddle_tpu.resilience import record_event
        import logging

        logger = logging.getLogger("paddle_tpu.resilience")
        self.wait_until_finished()
        steps = sorted(self._mngr.all_steps(), reverse=True)
        if not steps:
            return None
        # steps saved BEFORE integrity was enabled have no manifest and
        # can only be legacy-accepted; steps at/after the oldest
        # manifested one were saved with integrity on, so "no manifest"
        # there genuinely means the save never committed. Without the
        # split, one corrupt post-upgrade step would strand every valid
        # pre-upgrade checkpoint behind it and restart training from 0.
        manifested = [s for s in steps
                      if os.path.isfile(_integ.manifest_path(self._dir, s))]
        if not manifested:
            logger.info("no integrity manifests under %s (legacy "
                        "checkpoints); resuming from latest_step()",
                        self._dir)
            return steps[0]
        first_manifested = min(manifested)
        for s in steps:
            if s < first_manifested:
                logger.info("checkpoint step %d predates integrity "
                            "manifests; accepting as legacy", s)
                return s
            ok, reason = self.verify_step(s, deep=deep)
            if ok:
                return s
            record_event("checkpoint_corrupt_skipped")
            logger.warning("checkpoint step %d failed verification (%s); "
                           "walking back", s, reason)
            if quarantine and _integ.is_content_failure(reason):
                try:
                    self._mngr.delete(s)
                except Exception as e:  # noqa: BLE001 — best-effort
                    # keep the manifest when the delete failed: unlinking
                    # it while the data survives would flip a later call
                    # into the legacy no-manifest fallback, which resumes
                    # from exactly this known-corrupt step
                    logger.warning("could not quarantine corrupt step %d "
                                   "(%s)", s, e)
                    continue
                try:
                    os.unlink(_integ.manifest_path(self._dir, s))
                except OSError:
                    pass
        return None


def save_persistables(model, optimizer=None, path: str = "checkpoint",
                      opt_state=None):
    """fleet.save_persistables parity: model (+optimizer) state to `path`."""
    tree = {"model": model.state_dict()}
    if opt_state is not None:
        tree["optimizer"] = opt_state
    save_state_dict(tree, path)
