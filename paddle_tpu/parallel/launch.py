"""Launcher (≈ python -m paddle.distributed.launch).

Reference (SURVEY.md §3.4): launch/main.py spawns N local procs with
PADDLE_TRAINER_ID/... env and a watch loop (elastic restart per §5).

TPU-native: one process drives all local chips (SPMD), so the launcher's job
is per-HOST process management: set the env contract, exec the script, watch
and restart on failure (restart-from-checkpoint recovery). `spawn` mirrors
paddle.distributed.spawn for multi-process CPU testing.
"""

import multiprocessing as mp
import os
import subprocess
import sys
import time


def _worker_env(rank, nprocs, master):
    env = dict(os.environ)
    env.update({
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(nprocs),
        "PADDLE_MASTER": master,
        "PROCESS_ID": str(rank),
        "NUM_PROCESSES": str(nprocs),
    })
    return env


def spawn(func, args=(), nprocs=1, join=True, daemon=False, **kwargs):
    """Run `func(rank, *args)` in `nprocs` processes (reference spawn parity)."""
    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        p = ctx.Process(target=_spawn_target, args=(func, rank, nprocs, args),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if join:
        for p in procs:
            p.join()
        for p in procs:
            if p.exitcode != 0:
                raise RuntimeError(f"spawned rank exited with {p.exitcode}")
    return procs


def _spawn_target(func, rank, nprocs, args):
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    func(rank, *args)


def launch(script_args, nnodes=1, node_rank=0, master="127.0.0.1:49175",
           max_restarts=0, log_dir=None, elastic_dir=None,
           heartbeat_interval=2.0, elastic_world_timeout=300.0,
           elastic_master=None):
    """Run the training script once per host with restart-on-failure
    (elastic_level ≈ max_restarts; recovery is resume-from-checkpoint).

    With `elastic_dir` (a directory all hosts share) OR `elastic_master`
    (host:port — node 0's launcher hosts a coordination-service KV there,
    no shared filesystem needed), this node heartbeats an ElasticManager
    registry and a watch thread kills the child when a peer host's
    heartbeat lapses — the relaunch then resumes from the last
    checkpoint, the reference ElasticManager's recovery contract
    (SURVEY.md §5-failure, fleet/elastic/manager.py; etcd analog)."""
    mgr = None
    membership_changed = [False]
    proc_holder = [None]
    if elastic_dir or elastic_master:
        from paddle_tpu.parallel.elastic import (CoordinationServiceStore,
                                                 ElasticManager,
                                                 FileHeartbeatStore)
        store = (CoordinationServiceStore.connect(
            elastic_master, node_rank, nnodes) if elastic_master
            else FileHeartbeatStore(elastic_dir))
        mgr = ElasticManager(store, rank=node_rank,
                             world_size=nnodes,
                             heartbeat_interval=heartbeat_interval).start()

        def on_change(alive, dead):
            p = proc_holder[0]  # snapshot: the child may exit concurrently
            if dead and p is not None:
                membership_changed[0] = True
                try:
                    p.terminate()
                except OSError:  # already reaped
                    pass

        mgr.watch(on_change)
    restarts = 0
    try:
        while True:
            env = _worker_env(node_rank, nnodes, master)
            if log_dir:
                os.makedirs(log_dir, exist_ok=True)
                logfile = open(os.path.join(log_dir, f"workerlog.{node_rank}"), "ab")
            else:
                logfile = None
            membership_changed[0] = False
            proc = subprocess.Popen([sys.executable] + script_args, env=env,
                                    stdout=logfile or None, stderr=subprocess.STDOUT
                                    if logfile else None)
            proc_holder[0] = proc
            code = proc.wait()
            proc_holder[0] = None
            if logfile:
                logfile.close()
            if code == 0:
                return 0
            if mgr is not None and membership_changed[0]:
                # elastic termination is not a training failure: it does
                # not consume the restart budget (reference ElasticManager
                # relaunches on membership change regardless of
                # elastic_level). Wait for the lost peer before relaunch —
                # a restarted world needs every host present for rendezvous.
                if not mgr.wait_for_world(timeout=elastic_world_timeout):
                    return code  # peer never came back; give up
                from paddle_tpu.resilience import record_event
                record_event("launcher_elastic_relaunch")
                time.sleep(1.0)
                continue
            restarts += 1
            from paddle_tpu.resilience import record_event
            record_event("launcher_restart")
            if restarts > max_restarts:
                return code
            time.sleep(min(2 ** restarts, 30))
    finally:
        if mgr is not None:
            mgr.stop()
            # NO collective client.shutdown() here: launchers exit at
            # different times (success, restart budget, give-up), so the
            # shutdown barrier would block and then poison the service for
            # survivors. The client is constructed non-fatal
            # (shutdown_on_destruction=False, logging heartbeat callback),
            # so simply dropping it is safe.


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(prog="paddle_tpu.parallel.launch")
    ap.add_argument("--nnodes", type=int, default=1)
    ap.add_argument("--node_rank", type=int, default=int(os.environ.get("NODE_RANK", 0)))
    ap.add_argument("--master", default=os.environ.get("PADDLE_MASTER", "127.0.0.1:49175"))
    ap.add_argument("--max_restarts", type=int, default=0)
    ap.add_argument("--log_dir", default=None)
    ap.add_argument("--elastic_dir", default=None,
                    help="shared dir for membership heartbeats (etcd analog)")
    ap.add_argument("--elastic_master", default=None,
                    help="host:port for a coordination-service heartbeat "
                    "KV hosted by node 0's launcher (storeless elastic — "
                    "no shared dir needed)")
    ap.add_argument("--heartbeat_interval", type=float, default=2.0)
    ap.add_argument("script", nargs=argparse.REMAINDER)
    ns = ap.parse_args(argv)
    sys.exit(launch(ns.script, ns.nnodes, ns.node_rank, ns.master,
                    ns.max_restarts, ns.log_dir, elastic_dir=ns.elastic_dir,
                    heartbeat_interval=ns.heartbeat_interval,
                    elastic_master=ns.elastic_master))


if __name__ == "__main__":
    main()
