"""Megatron-style tensor-parallel layers, TPU-native.

Reference (SURVEY.md §2.6-TP): `ColumnParallelLinear`, `RowParallelLinear`,
`VocabParallelEmbedding`, `ParallelCrossEntropy` in
python/paddle/distributed/fleet/meta_parallel/parallel_layers/mp_layers.py,
with hand-written identity/allreduce custom autograd ops
(fleet/layers/mpu/mp_ops.py: `_c_identity`, `_c_allreduce`, `_c_split`).

TPU-first design: under GSPMD there is no custom autograd — each layer

* annotates its parameters with a `PartitionSpec` placement hint
  (``Parameter.pspec``, consumed by fleet's train-step builder), and
* places `with_sharding_constraint` hints on activations so XLA's sharding
  propagation reproduces the Megatron comm pattern (identity fwd / allreduce
  bwd for column, allreduce fwd / identity bwd for row) — including the
  backward collectives, automatically, because constraints apply to the
  transposed program too.

Numerics are device-count invariant: on one device every constraint is a
no-op and the layers equal their dense counterparts (tested in
tests/test_mp_layers.py via the 8-device CPU mesh).
"""

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.nn.layer import Layer
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as init
from paddle_tpu.parallel.topology import get_hybrid_communicate_group

MP_AXIS = "mp"


def _active_mesh(axis: str):
    """The hybrid mesh, if one is set and `axis` has degree > 1."""
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        return None
    mesh = hcg.mesh
    if axis in mesh.axis_names and mesh.shape[axis] > 1:
        return mesh
    return None


def constrain(x, spec_for_ndim, axis: str = MP_AXIS):
    """Apply a sharding constraint if a mesh with `axis` is active.

    `spec_for_ndim(ndim) -> PartitionSpec` builds the rank-appropriate spec.

    Dispatch: when an ambient abstract mesh is set (under ``jax.set_mesh`` —
    notably inside a partial-manual ``shard_map`` like the pipeline schedule),
    use a bare PartitionSpec so the constraint applies to the mesh's Auto
    axes; axes the caller has taken Manual are skipped (explicit collectives
    own them there). Otherwise fall back to the hybrid group's concrete mesh.
    """
    try:
        from jax.sharding import get_abstract_mesh, AxisType
        am = get_abstract_mesh()
    except ImportError:                      # older jax
        am = None
    if am is not None and not am.empty and axis in am.axis_names:
        types = dict(zip(am.axis_names, am.axis_types))
        if types[axis] == AxisType.Manual or am.shape[axis] <= 1:
            return x
        return jax.lax.with_sharding_constraint(x, spec_for_ndim(x.ndim))
    # old-jax (0.4.x) spelling of the same Manual-axis skip: inside a
    # shard_map body the manual axes live in the trace's axis env, and a
    # sharding constraint over one is an error, not a hint
    try:
        from jax._src import core as _core
        if _core.get_axis_env().axis_exists(axis):
            return x
    except Exception:
        pass
    mesh = _active_mesh(axis)
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, spec_for_ndim(x.ndim)))


def _last_dim_spec(axis):
    return lambda nd: P(*([None] * (nd - 1) + [axis]))


def _seq_dim_spec(axis, seq_dim=1):
    def spec(nd):
        dims = [None] * nd
        dims[seq_dim] = axis
        return P(*dims)
    return spec


def _replicated_spec():
    return lambda nd: P(*([None] * nd))


class ColumnParallelLinear(Layer):
    """Linear with the output dim sharded over the mp axis.

    Forward comm: identity (input replicated); backward: allreduce of the
    input grad — both inserted by GSPMD from the weight/activation shardings.
    """

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None, dtype=None, axis: str = MP_AXIS):
        super().__init__()
        w_init = weight_attr if isinstance(weight_attr, init.Initializer) \
            else init.XavierNormal()
        self.weight = self.create_parameter(
            (in_features, out_features), dtype=dtype, default_initializer=w_init)
        self._parameters["weight"].pspec = P(None, axis)
        self._parameters["weight"].is_distributed = True
        if has_bias:
            self.bias = self.create_parameter(
                (out_features,), dtype=dtype, is_bias=True)
            self._parameters["bias"].pspec = P(axis)
            self._parameters["bias"].is_distributed = True
        else:
            self.bias = None
        self.gather_output = gather_output
        self.axis = axis
        self.in_features, self.out_features = in_features, out_features

    def forward(self, x):
        y = F.linear(x, self.weight,
                     self.bias if "bias" in self._parameters else None)
        if self.gather_output:
            return constrain(y, _replicated_spec(), self.axis)
        return constrain(y, _last_dim_spec(self.axis), self.axis)


class RowParallelLinear(Layer):
    """Linear with the input (contracting) dim sharded over the mp axis.

    Forward comm: allreduce of the partial products; backward: identity —
    GSPMD emits the psum because the contraction dim is sharded.
    """

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False, fuse_matmul_bias=False,
                 mp_group=None, name=None, dtype=None, axis: str = MP_AXIS):
        super().__init__()
        w_init = weight_attr if isinstance(weight_attr, init.Initializer) \
            else init.XavierNormal()
        self.weight = self.create_parameter(
            (in_features, out_features), dtype=dtype, default_initializer=w_init)
        self._parameters["weight"].pspec = P(axis, None)
        self._parameters["weight"].is_distributed = True
        if has_bias:
            # bias is added once, after the reduce — replicated
            self.bias = self.create_parameter(
                (out_features,), dtype=dtype, is_bias=True)
        else:
            self.bias = None
        self.input_is_parallel = input_is_parallel
        self.axis = axis
        self.in_features, self.out_features = in_features, out_features

    def _out_spec(self):
        """Output placement after the reduce — SP subclass reduce-scatters."""
        return _replicated_spec()

    def forward(self, x):
        if self.input_is_parallel:
            x = constrain(x, _last_dim_spec(self.axis), self.axis)
        y = jnp.matmul(x, self.weight)
        y = constrain(y, self._out_spec(), self.axis)
        if "bias" in self._parameters and self.bias is not None:
            y = y + self.bias
        return y


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over the mp axis.

    The reference masks out-of-shard ids, looks up locally, then allreduces;
    GSPMD derives the identical pattern from the row-sharded table.
    """

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None, dtype=None, axis: str = MP_AXIS):
        super().__init__()
        w_init = weight_attr if isinstance(weight_attr, init.Initializer) \
            else init.Normal(0.0, 1.0)
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), dtype=dtype,
            default_initializer=w_init)
        self._parameters["weight"].pspec = P(axis, None)
        self._parameters["weight"].is_distributed = True
        self.num_embeddings, self.embedding_dim = num_embeddings, embedding_dim
        self.axis = axis

    def forward(self, x):
        y = F.embedding(x, self.weight)
        return constrain(y, _replicated_spec(), self.axis)


class ParallelCrossEntropy(Layer):
    """Softmax cross-entropy over vocab-sharded logits.

    The reference computes a local max/sum + two allreduces
    (fleet/layers/mpu/mp_ops.py `_c_softmax_with_cross_entropy`); here the
    logits are constrained vocab-sharded and XLA decomposes the logsumexp
    reduction into the same pattern.
    """

    def __init__(self, mp_group=None, name=None, ignore_index=-100,
                 axis: str = MP_AXIS):
        super().__init__()
        self.ignore_index = ignore_index
        self.axis = axis

    def forward(self, logits, labels, soft_label=False, reduction="none"):
        logits = constrain(logits, _last_dim_spec(self.axis), self.axis)
        return F.cross_entropy(logits, labels, soft_label=soft_label,
                               ignore_index=self.ignore_index,
                               reduction=reduction)


# ---- Megatron sequence parallelism (SP over the mp axis) -------------------
# Reference: python/paddle/distributed/fleet/utils/sequence_parallel_utils.py.
# Between TP regions activations are sharded along the sequence dim on the mp
# axis; entering a TP region all-gathers seq, leaving it reduce-scatters.
# Under GSPMD each of these is a sharding constraint.

def scatter(x, axis: str = MP_AXIS, seq_dim: int = 1):
    """ScatterOp parity: replicated → seq-sharded (fwd split, bwd allgather)."""
    return constrain(x, _seq_dim_spec(axis, seq_dim), axis)


def gather(x, axis: str = MP_AXIS, seq_dim: int = 1):
    """GatherOp parity: seq-sharded → replicated."""
    return constrain(x, _replicated_spec(), axis)


class AllGatherOp(Layer):
    """all-gather seq fwd / reduce-scatter bwd (entering a TP region)."""

    def __init__(self, axis: str = MP_AXIS, seq_dim: int = 1):
        super().__init__()
        self.axis, self.seq_dim = axis, seq_dim

    def forward(self, x):
        return gather(x, self.axis, self.seq_dim)


class ReduceScatterOp(Layer):
    """reduce-scatter seq fwd / all-gather bwd (leaving a TP region)."""

    def __init__(self, axis: str = MP_AXIS, seq_dim: int = 1):
        super().__init__()
        self.axis, self.seq_dim = axis, seq_dim

    def forward(self, x):
        return scatter(x, self.axis, self.seq_dim)


class ColumnSequenceParallelLinear(ColumnParallelLinear):
    """Column-parallel linear whose input arrives seq-sharded (SP)."""

    def __init__(self, *args, seq_dim: int = 1, **kwargs):
        kwargs.setdefault("gather_output", False)
        super().__init__(*args, **kwargs)
        self.seq_dim = seq_dim

    def forward(self, x):
        x = constrain(x, _seq_dim_spec(self.axis, self.seq_dim), self.axis)
        return super().forward(x)


class RowSequenceParallelLinear(RowParallelLinear):
    """Row-parallel linear whose output leaves seq-sharded (SP)."""

    def __init__(self, *args, seq_dim: int = 1, **kwargs):
        kwargs.setdefault("input_is_parallel", True)
        super().__init__(*args, **kwargs)
        self.seq_dim = seq_dim

    def _out_spec(self):
        return _seq_dim_spec(self.axis, self.seq_dim)


def mark_as_sequence_parallel_parameter(param):
    """Reference tags SP params (e.g. layernorm inside SP regions) so their
    grads get allreduced over mp; GSPMD derives that from the replicated
    param sharding, so this is a recorded no-op kept for API parity."""
    setattr(param, "sequence_parallel", True)
    return param


def register_sequence_parallel_allreduce_hooks(model, accumulation_steps=1,
                                               fuse_allreduce=False):
    """No-op under GSPMD (grad psum over mp is emitted by the compiler)."""
    return model


# ---- paddle.distributed.split parity ---------------------------------------

def split_layer(size, operation="linear", axis=1, num_partitions=None,
                gather_out=True, weight_attr=None, bias_attr=None):
    """`paddle.distributed.split` parity: build the sharded layer directly.

    operation='linear': axis=0 → RowParallelLinear, axis=1 → ColumnParallel.
    operation='embedding': VocabParallelEmbedding.
    """
    if operation == "embedding":
        return VocabParallelEmbedding(size[0], size[1], weight_attr=weight_attr)
    if operation != "linear":
        raise ValueError(f"unsupported operation {operation!r}")
    in_f, out_f = size
    if axis == 0:
        return RowParallelLinear(in_f, out_f, weight_attr=weight_attr,
                                 has_bias=bias_attr is not False,
                                 input_is_parallel=not gather_out)
    return ColumnParallelLinear(in_f, out_f, weight_attr=weight_attr,
                                has_bias=bias_attr is not False,
                                gather_output=gather_out)
