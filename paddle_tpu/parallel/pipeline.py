"""Pipeline parallelism — LayerDesc model description + TPU-native schedules.

Reference (SURVEY.md §2.6-PP): `PipelineLayer` (LayerDesc list → stage
segments, SharedLayerDesc weight tying) + `PipelineParallel` runtime with the
1F1B schedule over NCCL p2p (meta_parallel/pipeline_parallel.py,
pp_layers.py, p2p_communication.py).

TPU-native: stages live on the mesh's "pp" axis. The production schedule is
collective-permute pipelining INSIDE one jit: stage weights are stacked on a
leading pp dim, shard_map splits them, and a lax.scan over (microbatches +
bubble) rotates activations with ppermute — XLA overlaps the permute with the
next microbatch's compute, which is the 1F1B overlap the reference hand-codes
with comm streams. Implemented in `pipeline_spmd_fn` (full impl in this
module; see tests/test_pipeline.py for invariance vs single-device).
"""

from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.nn.layer import Layer
from paddle_tpu.nn.layers.common import LayerList


class LayerDesc:
    """Deferred layer construction (reference parity: pp_layers.py)."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Weight-tied layer appearing on multiple stages (e.g. embedding/unembed).

    On TPU tying is free inside one jit program: the builder returns the same
    layer object, and GSPMD replicates/reduces as needed."""

    def __init__(self, key, layer_cls, *args, forward_func=None, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.key = key
        self.forward_func = forward_func


class PipelineLayer(Layer):
    """Describes a model as a flat list of LayerDescs split into pp stages."""

    def __init__(self, layers: Sequence, num_stages: int = 1, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0):
        super().__init__()
        self.descs = list(layers)
        self.num_stages = num_stages
        self.loss_fn = loss_fn
        self.recompute_interval = recompute_interval
        self._shared = {}
        built = []
        for d in self.descs:
            if isinstance(d, SharedLayerDesc):
                if d.key not in self._shared:
                    self._shared[d.key] = d.build_layer()
                built.append(self._shared[d.key])
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            elif isinstance(d, Layer):
                built.append(d)
            else:  # plain callable
                built.append(_FnLayer(d))
        self.run_function = LayerList(built)
        self.segments = self._segment(len(built), num_stages)

    @staticmethod
    def _segment(n_layers, n_stages):
        """Uniform segmentation (reference seg_method='uniform')."""
        base = n_layers // n_stages
        rem = n_layers % n_stages
        bounds = [0]
        for s in range(n_stages):
            bounds.append(bounds[-1] + base + (1 if s < rem else 0))
        return bounds

    def stage_layers(self, stage_id):
        lo, hi = self.segments[stage_id], self.segments[stage_id + 1]
        return list(self.run_function)[lo:hi]

    def forward(self, x):
        for l in self.run_function:
            x = l(x)
        return x


class _FnLayer(Layer):
    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def forward(self, *args):
        return self._fn(*args)


class PipelineParallel(Layer):
    """Runtime wrapper chosen by fleet.distributed_model when pp_degree>1.

    `train_batch(data, optimizer)` runs the microbatched schedule selected
    by `strategy.pipeline_configs.schedule_mode`: the lockstep 1F1B engine
    (default; interleaved when virtual_pp_degree > 1) or GPipe-style
    accumulate-then-backward ('FThenB'). Both compile into one jit.
    """

    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = strategy.pipeline_configs if strategy else None
        self.micro_batch_size = cfg.micro_batch_size if cfg else 1
        self.accumulate_steps = cfg.accumulate_steps if cfg else 1

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    @property
    def inner_layer(self):
        return self._layers


# ---- SPMD pipeline schedule (collective-permute pipelining) ---------------

import dataclasses
from typing import Any, Dict

import jax.numpy as _jnp


@dataclasses.dataclass
class PipelineParts:
    """A model factored for the SPMD pipeline: embed → N identical blocks →
    head(loss). Models expose this via ``.pipeline_parts()``.

    The reference expresses the same factoring as a LayerDesc list fed to
    PipelineLayer (pp_layers.py); identical-block stacking is the TPU twist
    that lets stage weights live as one (n_stages, per_stage, ...) array
    sharded over the pp mesh axis.
    """

    embed_state: Dict[str, Any]
    embed_apply: Callable            # (embed_state, batch_ids) -> h
    block_states: List[Dict[str, Any]]   # per-layer, identical structure
    block_apply: Callable            # (one_block_state, h) -> h
    head_state: Dict[str, Any]
    head_apply: Callable             # (head_state, h, labels) -> scalar loss
    embed_pspecs: Dict[str, Any]
    block_pspecs: Dict[str, Any]     # specs for ONE block (unstacked)
    head_pspecs: Dict[str, Any]
    # SharedLayerDesc parity: tied unembedding. When True, head_apply is
    # (head_state, embed_state, h, labels) — the embed weights are
    # pp-replicated, so the head reads them directly and autodiff sums the
    # two grad paths (no explicit cross-stage grad sync needed).
    tied_head: bool = False


def _norm_pspec(p, ndim):
    """Normalize a Parameter.pspec (possibly None/short) to `ndim` entries."""
    from jax.sharding import PartitionSpec as P
    if p is None:
        return P(*([None] * ndim))
    entries = list(p) + [None] * (ndim - len(tuple(p)))
    return P(*entries[:ndim])


def part_specs(layer) -> Dict[str, Any]:
    return {name: _norm_pspec(getattr(param, "pspec", None), param.value.ndim)
            for name, param in layer.named_parameters() if param.trainable}


def make_pipeline_train_step(model, optimizer, strategy=None, hcg=None,
                             donate: bool = True):
    """Compiled pp×mp×dp×sharding train step via collective-permute pipelining.

    One jit for the whole schedule; TP/DP/ZeRO ride the mesh's Auto axes via
    GSPMD inside the same program. `strategy.pipeline_configs.schedule_mode`
    selects the schedule (reference: `PipelineParallel.
    forward_backward_pipeline` 1F1B + interleaved, SURVEY.md §2.6-PP):

    - '1F1B' (default): lockstep table-driven 1F1B — each scan tick runs one
      forward unit and one backward unit per stage, activations ppermute
      forward, gradients ppermute backward, backward recomputes from an
      O(pp)-deep stash (activation liveness independent of n_micro). With
      `virtual_pp_degree > 1` the same engine runs the interleaved
      (virtual-chunk) schedule.
    - 'FThenB' / 'gpipe': GPipe-style accumulation in one differentiated
      scan over (n_micro + n_stages - 1) ticks; activation liveness grows
      with n_micro (remat when strategy.recompute is on).

    Returns (step_fn, init_fn); state is a flat dict with ``embed.``/
    ``blocks.``/``head.`` key prefixes, block params stacked
    (n_stages, per_stage, ...) — (n_stages, v, per_chunk, ...) when
    interleaved — and sharded over the "pp" axis.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.parallel import fleet as fleet_mod
    from paddle_tpu.parallel import sharding as sharding_mod
    from paddle_tpu.parallel.strategy import DistributedStrategy
    from paddle_tpu.parallel.topology import get_hybrid_communicate_group

    strategy = strategy or DistributedStrategy()
    hcg = hcg or fleet_mod.get_fleet().get_hybrid_communicate_group() \
        or get_hybrid_communicate_group()
    mesh = hcg.mesh
    n_stages = hcg.get_pipe_parallel_world_size()
    n_micro = strategy.pipeline_configs.accumulate_steps
    if n_micro < n_stages:
        n_micro = n_stages  # keep the bubble bounded; reference asserts too

    schedule = (strategy.pipeline_configs.schedule_mode or "1F1B").lower()
    v_chunks = max(1, strategy.pipeline_configs.virtual_pp_degree)
    if schedule in ("fthenb", "gpipe"):
        schedule = "gpipe"
        if v_chunks > 1:
            raise ValueError("virtual_pp_degree > 1 requires the 1F1B "
                             "schedule (interleaved)")
    elif schedule == "1f1b":
        if v_chunks > 1 and n_micro % n_stages:
            raise ValueError(
                f"interleaved schedule needs accumulate_steps "
                f"({n_micro}) divisible by pp ({n_stages})")
    else:
        raise ValueError(f"unknown schedule_mode "
                         f"{strategy.pipeline_configs.schedule_mode!r}")

    parts: PipelineParts = model.pipeline_parts()
    n_layers = len(parts.block_states)
    if n_layers % (n_stages * v_chunks):
        raise ValueError(f"{n_layers} layers not divisible by "
                         f"pp×virtual_pp={n_stages}×{v_chunks}")
    per_stage = n_layers // n_stages
    per_chunk = n_layers // (n_stages * v_chunks)

    # ---- flat state: embed. / blocks.(stacked) / head. ----
    # Layer ownership: virtual stage V = c*n_stages + s holds layers
    # [V*per_chunk, (V+1)*per_chunk) — for v_chunks == 1 this is the plain
    # contiguous split, stacked (n_stages, per_stage, ...); interleaved
    # stacks (n_stages, v, per_chunk, ...) with [s, c, j] = layer
    # (c*n_stages + s)*per_chunk + j.
    def stack_blocks(leaves):
        arr = _jnp.stack(leaves)                    # (L, ...)
        if v_chunks == 1:
            return arr.reshape((n_stages, per_stage) + leaves[0].shape)
        arr = arr.reshape((v_chunks, n_stages, per_chunk) + leaves[0].shape)
        return _jnp.swapaxes(arr, 0, 1)             # (S, v, per_chunk, ...)

    # LazyGuard-built models carry ShapeDtypeStructs: stack abstractly
    # (shapes only). Such a builder serves ONLY the AOT lower() path —
    # init_fn raises (there are no buffers to place).
    abstract = any(
        isinstance(v, jax.ShapeDtypeStruct)
        for st in parts.block_states for v in st.values())
    if abstract:
        stacked = jax.eval_shape(
            lambda sts: {k: stack_blocks([st[k] for st in sts])
                         for k in sts[0]}, parts.block_states)
    else:
        stacked = {k: stack_blocks([st[k] for st in parts.block_states])
                   for k in parts.block_states[0]}
    state0 = {}
    state0.update({f"embed.{k}": v for k, v in parts.embed_state.items()})
    state0.update({f"blocks.{k}": v for k, v in stacked.items()})
    state0.update({f"head.{k}": v for k, v in parts.head_state.items()})
    # re-check over the ASSEMBLED state: embed/head may be abstract even
    # when blocks were made concrete (partial set_state_dict) — init_fn's
    # guard must cover any abstract leaf, mirroring fleet.py
    abstract = abstract or any(
        isinstance(v, jax.ShapeDtypeStruct) for v in state0.values())

    # ---- shardings: pp on the stage dim, TP placements, ZeRO composition ----
    zstage = strategy.sharding_configs.stage if strategy.sharding else 0
    zdeg = hcg.get_sharding_parallel_world_size()

    blk_lead = ("pp", None) if v_chunks == 1 else ("pp", None, None)
    pspecs = {}
    for k, spec in parts.embed_pspecs.items():
        pspecs[f"embed.{k}"] = spec
    for k, spec in parts.block_pspecs.items():
        pspecs[f"blocks.{k}"] = P(*blk_lead, *tuple(spec))
    for k, spec in parts.head_pspecs.items():
        pspecs[f"head.{k}"] = spec
    if zstage >= 3 and zdeg > 1:
        pspecs = {k: sharding_mod.param_pspec(state0[k].shape, zdeg,
                                              existing=pspecs[k])
                  for k in pspecs}
    ospecs = sharding_mod.opt_state_specs(pspecs, zstage, zdeg, state0)

    dp_axes = tuple(a for a in ("dp", "sharding")
                    if a in mesh.axis_names and mesh.shape[a] > 1)
    bspec = P(dp_axes if dp_axes else None)

    remat = strategy.recompute
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def split_state(flat):
        e = {k[len("embed."):]: v for k, v in flat.items()
             if k.startswith("embed.")}
        b = {k[len("blocks."):]: v for k, v in flat.items()
             if k.startswith("blocks.")}
        h = {k[len("head."):]: v for k, v in flat.items()
             if k.startswith("head.")}
        return e, b, h

    def pipeline_loss(flat_state, ids_mb, labels_mb):
        """ids_mb/labels_mb: (n_micro, mb, seq)."""
        embed_st, blocks_st, head_st = split_state(flat_state)

        def inner(blocks_local, embed_st, head_st, ids_mb, labels_mb):
            stage = jax.lax.axis_index("pp")
            blocks_me = jax.tree_util.tree_map(lambda a: a[0], blocks_local)
            total = n_micro + n_stages - 1

            def stage_fwd(h):
                def body(h, one_layer):
                    out = parts.block_apply(one_layer, h)
                    if isinstance(out, tuple):   # (h, extra_loss) — e.g. MoE aux
                        return out[0], out[1].astype(_jnp.float32)
                    return out, _jnp.zeros((), _jnp.float32)
                h, extras = jax.lax.scan(body, h, blocks_me)
                return h, _jnp.sum(extras)

            def tick(carry, t):
                h_carry, loss_acc = carry
                ids_t = jax.lax.dynamic_index_in_dim(
                    ids_mb, _jnp.minimum(t, n_micro - 1), 0, keepdims=False)
                h_in = parts.embed_apply(embed_st, ids_t)
                h = _jnp.where(stage == 0, h_in, h_carry)
                h_out, extra = stage_fwd(h)
                out_idx = t - (n_stages - 1)
                lbl = jax.lax.dynamic_index_in_dim(
                    labels_mb, _jnp.clip(out_idx, 0, n_micro - 1), 0,
                    keepdims=False)
                if parts.tied_head:
                    mb_loss = parts.head_apply(head_st, embed_st, h_out, lbl)
                else:
                    mb_loss = parts.head_apply(head_st, h_out, lbl)
                emit = (stage == n_stages - 1) & (out_idx >= 0)
                # stage s holds microbatch (t - s); its extra losses count
                # only while that microbatch is real (not a bubble tick)
                valid = (t >= stage) & (t - stage < n_micro)
                loss_acc = (loss_acc + _jnp.where(emit, mb_loss, 0.0)
                            + _jnp.where(valid, extra, 0.0))
                h_next = jax.lax.ppermute(h_out, "pp", perm)
                return (h_next, loss_acc), None

            if remat:
                tick = jax.checkpoint(tick)

            mb = ids_mb.shape[1]
            seq = ids_mb.shape[2]
            h0_probe = jax.eval_shape(
                lambda s, i: parts.embed_apply(s, i), embed_st,
                jax.ShapeDtypeStruct((mb, seq), ids_mb.dtype))
            h0 = _jnp.zeros(h0_probe.shape, h0_probe.dtype)
            # carries vary per-stage: mark them varying over the manual axis
            h0 = jax.lax.pcast(h0, ("pp",), to="varying")
            loss0 = jax.lax.pcast(_jnp.zeros((), _jnp.float32), ("pp",),
                                  to="varying")
            (_, loss_acc), _ = jax.lax.scan(tick, (h0, loss0),
                                            _jnp.arange(total))
            return jax.lax.psum(loss_acc, "pp") / n_micro

        f = jax.shard_map(
            inner, mesh=mesh, axis_names={"pp"},
            in_specs=(P("pp"), P(), P(), P(), P()),
            out_specs=P())
        return f(blocks_st, embed_st, head_st, ids_mb, labels_mb)

    # ---- 1F1B / interleaved lockstep engine ------------------------------
    #
    # Manual backward: the schedule tables (pipeline_schedules.py) fix, per
    # tick and stage, one forward unit and one backward unit. Backward
    # recomputes the unit forward from a stashed input (jax.vjp), so
    # activation liveness is the stash ring (O(pp·v)), not O(n_micro) as in
    # the differentiated-scan GPipe path.
    def loss_and_grads_1f1b(flat_state, ids_mb, labels_mb):
        from paddle_tpu.parallel.pipeline_schedules import (
            build_schedule_tables)

        tb = build_schedule_tables(n_stages, v_chunks, n_micro)
        # tick-major int32 tables → scan xs (rows shaped (n_stages,))
        xs = {name: _jnp.asarray(getattr(tb, name)) for name in
              ("f_c", "f_m", "f_active", "f_is_last", "f_src", "f_wr",
               "f_stash", "b_c", "b_m", "b_active", "b_is_v0", "b_gsrc",
               "b_gwr", "b_stash")}

        embed_st, blocks_st, head_st = split_state(flat_state)

        def inner(blocks_local, embed_st, head_st, ids_mb, labels_mb):
            stage = jax.lax.axis_index("pp")
            # local blocks: (1, per_stage, ...) or (1, v, per_chunk, ...)
            #   → uniform (v, per_chunk, ...)
            blocks_me = jax.tree_util.tree_map(
                lambda a: a[0].reshape((v_chunks, per_chunk) + a.shape[2:])
                if v_chunks == 1 else a[0], blocks_local)

            f32 = _jnp.float32

            def unit_fwd(e_st, w_unit, h_st, x_in, ids_m, labels_m, is_v0):
                """One virtual-stage unit: (embed-if-V0) → per_chunk blocks
                → head loss. Head/embed run on every unit; cotangent seeds
                select which gradients are real."""
                emb = parts.embed_apply(e_st, ids_m)
                a = _jnp.where(is_v0, emb, x_in)

                def body(h, one_layer):
                    out = parts.block_apply(one_layer, h)
                    if isinstance(out, tuple):
                        return out[0], out[1].astype(f32)
                    return out, _jnp.zeros((), f32)

                h, extras = jax.lax.scan(body, a, w_unit)
                if parts.tied_head:
                    mb_loss = parts.head_apply(h_st, e_st, h, labels_m)
                else:
                    mb_loss = parts.head_apply(h_st, h, labels_m)
                return h, mb_loss.astype(f32), _jnp.sum(extras)

            mb = ids_mb.shape[1]
            seq = ids_mb.shape[2]
            h_probe = jax.eval_shape(
                lambda s, i: parts.embed_apply(s, i), embed_st,
                jax.ShapeDtypeStruct((mb, seq), ids_mb.dtype))
            h_shape, h_dtype = h_probe.shape, h_probe.dtype

            def zeros_h(lead=()):
                return _jnp.zeros(tuple(lead) + h_shape, h_dtype)

            def _vary_one(a):
                if "pp" in getattr(jax.typeof(a), "vma", ()):
                    return a   # already varying over pp
                return jax.lax.pcast(a, ("pp",), to="varying")

            vary = lambda t: jax.tree_util.tree_map(_vary_one, t)

            carry0 = dict(
                h_wire=zeros_h(), g_wire=zeros_h(),
                f_buf=zeros_h((tb.fwd_ring,)),
                g_buf=zeros_h((tb.grad_ring,)),
                stash=zeros_h((tb.stash_ring,)),
                dembed=jax.tree_util.tree_map(_jnp.zeros_like, embed_st),
                dblocks=jax.tree_util.tree_map(_jnp.zeros_like, blocks_me),
                dhead=jax.tree_util.tree_map(_jnp.zeros_like, head_st),
                loss=_jnp.zeros((), f32), extra=_jnp.zeros((), f32))
            carry0 = vary(carry0)

            def pick(row):
                return _jnp.take(row, stage, axis=0)

            inv_m = 1.0 / n_micro

            def tick(carry, row):
                c = carry
                # ---- store wire arrivals (writes land before any read) ----
                f_wr = pick(row["f_wr"])
                f_buf = c["f_buf"].at[_jnp.clip(f_wr, 0, tb.fwd_ring - 1)
                                      ].set(_jnp.where(f_wr >= 0,
                                                       c["h_wire"],
                                                       c["f_buf"][_jnp.clip(
                                                           f_wr, 0,
                                                           tb.fwd_ring - 1)]))
                b_gwr = pick(row["b_gwr"])
                g_buf = c["g_buf"].at[_jnp.clip(b_gwr, 0, tb.grad_ring - 1)
                                      ].set(_jnp.where(b_gwr >= 0,
                                                       c["g_wire"],
                                                       c["g_buf"][_jnp.clip(
                                                           b_gwr, 0,
                                                           tb.grad_ring - 1)]))

                # ---- F slot ----
                f_act = pick(row["f_active"]).astype(bool)
                f_src = pick(row["f_src"])
                f_is_v0 = f_src == -2
                c_f = pick(row["f_c"])
                m_f = pick(row["f_m"])
                ids_f = jax.lax.dynamic_index_in_dim(
                    ids_mb, m_f, 0, keepdims=False)
                lbl_f = jax.lax.dynamic_index_in_dim(
                    labels_mb, m_f, 0, keepdims=False)
                x_f = jax.lax.dynamic_index_in_dim(
                    f_buf, _jnp.clip(f_src, 0, tb.fwd_ring - 1), 0,
                    keepdims=False)
                w_f = jax.tree_util.tree_map(
                    lambda a: jax.lax.dynamic_index_in_dim(
                        a, c_f, 0, keepdims=False), blocks_me)
                h_f, loss_f, extra_f = unit_fwd(
                    embed_st, w_f, head_st, x_f, ids_f, lbl_f, f_is_v0)
                f_stash = pick(row["f_stash"])
                stash = c["stash"].at[f_stash].set(
                    _jnp.where(f_act, x_f, c["stash"][f_stash]))
                f_is_last = pick(row["f_is_last"]).astype(bool)
                loss_acc = c["loss"] + _jnp.where(f_act & f_is_last,
                                                  loss_f, 0.0)
                extra_acc = c["extra"] + _jnp.where(f_act, extra_f, 0.0)
                h_wire = _jnp.where(f_act, h_f, _jnp.zeros_like(h_f))

                # ---- B slot (vjp recompute from the stash) ----
                b_act = pick(row["b_active"]).astype(bool)
                b_gsrc = pick(row["b_gsrc"])
                b_is_last = b_gsrc == -2
                b_is_v0 = pick(row["b_is_v0"]).astype(bool)
                c_b = pick(row["b_c"])
                m_b = pick(row["b_m"])
                ids_b = jax.lax.dynamic_index_in_dim(
                    ids_mb, m_b, 0, keepdims=False)
                lbl_b = jax.lax.dynamic_index_in_dim(
                    labels_mb, m_b, 0, keepdims=False)
                x_b = jax.lax.dynamic_index_in_dim(
                    stash, pick(row["b_stash"]), 0, keepdims=False)
                w_b = jax.tree_util.tree_map(
                    lambda a: jax.lax.dynamic_index_in_dim(
                        a, c_b, 0, keepdims=False), blocks_me)

                def b_fwd(e_st, w_unit, h_st, x_in):
                    return unit_fwd(e_st, w_unit, h_st, x_in, ids_b, lbl_b,
                                    b_is_v0)

                (h_b, loss_b, extra_b), vjp_fn = jax.vjp(
                    b_fwd, embed_st, w_b, head_st, x_b)
                g_read = jax.lax.dynamic_index_in_dim(
                    g_buf, _jnp.clip(b_gsrc, 0, tb.grad_ring - 1), 0,
                    keepdims=False)
                b_act_f = b_act.astype(f32)
                g_h = _jnp.where(b_is_last, _jnp.zeros_like(g_read),
                                 g_read) * b_act_f.astype(g_read.dtype)
                g_loss = _jnp.where(b_is_last & b_act, inv_m, 0.0)
                g_extra = b_act_f * inv_m

                def match_cot(g, primal):
                    """Cotangent vma must equal the primal's. An invariant
                    primal (e.g. the constant-zero aux loss of a non-MoE
                    block) contributes no gradient, so a zero cotangent is
                    exact there."""
                    if "pp" in getattr(jax.typeof(primal), "vma", ()):
                        return _vary_one(g)
                    return _jnp.zeros_like(primal)

                de, dw, dh, dx = vjp_fn((match_cot(g_h, h_b),
                                         match_cot(g_loss, loss_b),
                                         match_cot(g_extra, extra_b)))
                dembed = jax.tree_util.tree_map(
                    lambda acc, d: acc + d, c["dembed"], de)
                dhead = jax.tree_util.tree_map(
                    lambda acc, d: acc + d, c["dhead"], dh)
                dblocks = jax.tree_util.tree_map(
                    lambda acc, d: acc.at[c_b].add(d), c["dblocks"], dw)
                g_wire = _jnp.where(b_act, dx, _jnp.zeros_like(dx))

                # ---- rotate wires ----
                h_wire = jax.lax.ppermute(h_wire, "pp", perm)
                g_wire = jax.lax.ppermute(
                    g_wire, "pp", [(d, s_) for (s_, d) in perm])

                new_c = dict(h_wire=h_wire, g_wire=g_wire, f_buf=f_buf,
                             g_buf=g_buf, stash=stash, dembed=dembed,
                             dblocks=dblocks, dhead=dhead, loss=loss_acc,
                             extra=extra_acc)
                return new_c, None

            final, _ = jax.lax.scan(tick, carry0, xs)

            loss_total = jax.lax.psum(final["loss"] + final["extra"],
                                      "pp") * inv_m
            dembed = jax.lax.psum(final["dembed"], "pp")
            dhead = jax.lax.psum(final["dhead"], "pp")
            # back to the state layout, with the local leading stage dim
            dblocks = jax.tree_util.tree_map(
                lambda a: (a.reshape((1, per_stage) + a.shape[2:])
                           if v_chunks == 1 else a[None]),
                final["dblocks"])
            return loss_total, dembed, dblocks, dhead

        f = jax.shard_map(
            inner, mesh=mesh, axis_names={"pp"},
            in_specs=(P("pp"), P(), P(), P(), P()),
            out_specs=(P(), P(), P("pp"), P()))
        loss, dembed, dblocks, dhead = f(blocks_st, embed_st, head_st,
                                         ids_mb, labels_mb)
        grads = {}
        grads.update({f"embed.{k}": g for k, g in dembed.items()})
        grads.update({f"blocks.{k}": g for k, g in dblocks.items()})
        grads.update({f"head.{k}": g for k, g in dhead.items()})
        return loss, grads

    def _step(flat_state, opt_state, ids_mb, labels_mb):
        if schedule == "gpipe":
            loss, grads = jax.value_and_grad(pipeline_loss)(
                flat_state, ids_mb, labels_mb)
        else:
            loss, grads = loss_and_grads_1f1b(flat_state, ids_mb, labels_mb)
        grads = {k: jax.lax.with_sharding_constraint(
            g, NamedSharding(mesh, pspecs[k])) for k, g in grads.items()}
        new_state, new_opt = optimizer.update(grads, opt_state, flat_state)
        new_state = {k: jax.lax.with_sharding_constraint(
            v, NamedSharding(mesh, pspecs[k])) for k, v in new_state.items()}
        return new_state, new_opt, loss

    jit_step = jax.jit(_step, donate_argnums=(0, 1) if donate else ())

    def init_fn():
        if abstract:
            raise RuntimeError(
                "this train step was built from a LazyGuard (meta-init) "
                "model — it has no parameter buffers to place; only the "
                "AOT step_fn.lower() feasibility path is available")
        # copy so jit donation can never free the Layer's own param buffers
        placed = {k: jax.device_put(_jnp.array(v, copy=True),
                                    NamedSharding(mesh, pspecs[k]))
                  for k, v in state0.items()}
        opt_state = optimizer.init_state(placed)

        def place_slot(tree):
            if isinstance(tree, dict):
                return {k: jax.device_put(v, NamedSharding(
                    mesh, ospecs.get(k, P()))) for k, v in tree.items()}
            return tree
        opt_state = {slot: place_slot(t) for slot, t in opt_state.items()}
        return placed, opt_state

    def step_fn(state, opt_state, batch):
        """batch: dict with 'input' (B, seq) and 'labels' (B, seq);
        B must be divisible by n_micro."""
        ids, labels = batch["input"], batch["labels"]
        B = ids.shape[0]
        if B % n_micro:
            raise ValueError(f"batch {B} not divisible by n_micro={n_micro}")
        mb = B // n_micro
        dp_total = 1
        for a in dp_axes:
            dp_total *= mesh.shape[a]
        if dp_total > 1 and mb % dp_total == 0:
            mb_spec = bspec
        else:
            mb_spec = P(None)
            if dp_total > 1:
                import warnings
                warnings.warn(
                    f"microbatch size {mb} not divisible by dp×sharding="
                    f"{dp_total}: replicating the batch across those axes "
                    "(no data parallelism this step)", stacklevel=2)
        ids_mb = ids.reshape(n_micro, mb, *ids.shape[1:])
        labels_mb = labels.reshape(n_micro, mb, *labels.shape[1:])
        ids_mb = jax.device_put(ids_mb, NamedSharding(
            mesh, P(None, *tuple(mb_spec))))
        labels_mb = jax.device_put(labels_mb, NamedSharding(
            mesh, P(None, *tuple(mb_spec))))
        with jax.set_mesh(mesh):
            return jit_step(state, opt_state, ids_mb, labels_mb)

    def lower(batch_shape, seq_len, ids_dtype=_jnp.int32):
        """AOT-lower the compiled step from abstract shapes (no real
        buffers): returns jax.stages.Lowered — .compile().memory_analysis()
        gives the per-device memory accounting used by feasibility reports
        (SCALE.md) without allocating a single parameter."""
        if batch_shape % n_micro:
            raise ValueError(
                f"batch {batch_shape} not divisible by n_micro={n_micro}")
        mb = batch_shape // n_micro
        from paddle_tpu.parallel.fleet import abstract_train_state
        abstract_state, abstract_opt = abstract_train_state(
            state0, pspecs, ospecs, optimizer, mesh)
        dp_total = 1
        for a in dp_axes:
            dp_total *= mesh.shape[a]
        mb_spec = bspec if (dp_total > 1 and mb % dp_total == 0) else P(None)
        mbatch = jax.ShapeDtypeStruct(
            (n_micro, mb, seq_len), ids_dtype,
            sharding=NamedSharding(mesh, P(None, *tuple(mb_spec))))
        with jax.set_mesh(mesh):
            return jit_step.lower(abstract_state, abstract_opt, mbatch,
                                  mbatch)

    step_fn.lower = lower
    step_fn.n_micro = n_micro
    return step_fn, init_fn


def pipeline_spmd_fn(stage_fn: Callable, n_stages: int, n_micro: int,
                     axis_name: str = "pp"):
    """Build a pipelined forward over stage-stacked params.

    stage_fn(stage_params, x) -> y : one stage's compute (same shape in/out).
    Returns fn(stacked_params, microbatches) -> stacked outputs, to be called
    INSIDE shard_map over `axis_name` where stacked_params' leading dim is the
    (sharded) stage dim and microbatches is (n_micro, mb, ...) replicated.

    Steady-state rotation: each of the (n_micro + n_stages - 1) ticks, every
    stage processes its current activation and ppermutes it to the next stage
    — the standard TPU pipeline recipe (scaling-book §pipelining): compute and
    ICI transfer overlap via XLA's latency-hiding scheduler.
    """

    def run(stage_params, microbatches):
        stage = jax.lax.axis_index(axis_name)
        total = n_micro + n_stages - 1
        mb_shape = microbatches.shape[1:]
        state = jnp.zeros(mb_shape, microbatches.dtype)
        outputs = jnp.zeros((n_micro,) + mb_shape, microbatches.dtype)

        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(t, carry):
            state, outputs = carry
            # stage 0 ingests microbatch t (while t < n_micro)
            inject = jax.lax.dynamic_index_in_dim(
                microbatches, jnp.minimum(t, n_micro - 1), axis=0, keepdims=False)
            x = jnp.where(stage == 0, inject, state)
            y = stage_fn(stage_params, x)
            # last stage emits microbatch t-(n_stages-1)
            out_idx = t - (n_stages - 1)
            emit = (stage == n_stages - 1) & (out_idx >= 0)
            outputs = jax.lax.cond(
                emit,
                lambda o: jax.lax.dynamic_update_index_in_dim(
                    o, y, jnp.maximum(out_idx, 0), axis=0),
                lambda o: o, outputs)
            state = jax.lax.ppermute(y, axis_name, perm)
            return state, outputs

        _, outputs = jax.lax.fori_loop(0, total, tick, (state, outputs))
        # outputs live on the last stage; broadcast so every stage agrees
        outputs = jax.lax.psum(
            jnp.where(stage == n_stages - 1, outputs, jnp.zeros_like(outputs)),
            axis_name)
        return outputs

    return run
