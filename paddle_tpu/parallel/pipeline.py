"""Pipeline parallelism — LayerDesc model description + TPU-native schedules.

Reference (SURVEY.md §2.6-PP): `PipelineLayer` (LayerDesc list → stage
segments, SharedLayerDesc weight tying) + `PipelineParallel` runtime with the
1F1B schedule over NCCL p2p (meta_parallel/pipeline_parallel.py,
pp_layers.py, p2p_communication.py).

TPU-native: stages live on the mesh's "pp" axis. The production schedule is
collective-permute pipelining INSIDE one jit: stage weights are stacked on a
leading pp dim, shard_map splits them, and a lax.scan over (microbatches +
bubble) rotates activations with ppermute — XLA overlaps the permute with the
next microbatch's compute, which is the 1F1B overlap the reference hand-codes
with comm streams. Implemented in `pipeline_spmd_fn` (full impl in this
module; see tests/test_pipeline.py for invariance vs single-device).
"""

from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.nn.layer import Layer
from paddle_tpu.nn.layers.common import LayerList


class LayerDesc:
    """Deferred layer construction (reference parity: pp_layers.py)."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Weight-tied layer appearing on multiple stages (e.g. embedding/unembed).

    On TPU tying is free inside one jit program: the builder returns the same
    layer object, and GSPMD replicates/reduces as needed."""

    def __init__(self, key, layer_cls, *args, forward_func=None, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.key = key
        self.forward_func = forward_func


class PipelineLayer(Layer):
    """Describes a model as a flat list of LayerDescs split into pp stages."""

    def __init__(self, layers: Sequence, num_stages: int = 1, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0):
        super().__init__()
        self.descs = list(layers)
        self.num_stages = num_stages
        self.loss_fn = loss_fn
        self.recompute_interval = recompute_interval
        self._shared = {}
        built = []
        for d in self.descs:
            if isinstance(d, SharedLayerDesc):
                if d.key not in self._shared:
                    self._shared[d.key] = d.build_layer()
                built.append(self._shared[d.key])
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            elif isinstance(d, Layer):
                built.append(d)
            else:  # plain callable
                built.append(_FnLayer(d))
        self.run_function = LayerList(built)
        self.segments = self._segment(len(built), num_stages)

    @staticmethod
    def _segment(n_layers, n_stages):
        """Uniform segmentation (reference seg_method='uniform')."""
        base = n_layers // n_stages
        rem = n_layers % n_stages
        bounds = [0]
        for s in range(n_stages):
            bounds.append(bounds[-1] + base + (1 if s < rem else 0))
        return bounds

    def stage_layers(self, stage_id):
        lo, hi = self.segments[stage_id], self.segments[stage_id + 1]
        return list(self.run_function)[lo:hi]

    def forward(self, x):
        for l in self.run_function:
            x = l(x)
        return x


class _FnLayer(Layer):
    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def forward(self, *args):
        return self._fn(*args)


class PipelineParallel(Layer):
    """Runtime wrapper chosen by fleet.distributed_model when pp_degree>1.

    `train_batch(data, optimizer)` runs the microbatched schedule. The
    underlying schedule is GPipe-style accumulation compiled into one jit
    (`pipeline_spmd_fn`); host-driven 1F1B over per-stage jits is available
    as `schedule='host1f1b'` for DCN-spanning topologies.
    """

    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = strategy.pipeline_configs if strategy else None
        self.micro_batch_size = cfg.micro_batch_size if cfg else 1
        self.accumulate_steps = cfg.accumulate_steps if cfg else 1

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    @property
    def inner_layer(self):
        return self._layers


# ---- SPMD pipeline schedule (collective-permute pipelining) ---------------

import dataclasses
from typing import Any, Dict

import jax.numpy as _jnp


@dataclasses.dataclass
class PipelineParts:
    """A model factored for the SPMD pipeline: embed → N identical blocks →
    head(loss). Models expose this via ``.pipeline_parts()``.

    The reference expresses the same factoring as a LayerDesc list fed to
    PipelineLayer (pp_layers.py); identical-block stacking is the TPU twist
    that lets stage weights live as one (n_stages, per_stage, ...) array
    sharded over the pp mesh axis.
    """

    embed_state: Dict[str, Any]
    embed_apply: Callable            # (embed_state, batch_ids) -> h
    block_states: List[Dict[str, Any]]   # per-layer, identical structure
    block_apply: Callable            # (one_block_state, h) -> h
    head_state: Dict[str, Any]
    head_apply: Callable             # (head_state, h, labels) -> scalar loss
    embed_pspecs: Dict[str, Any]
    block_pspecs: Dict[str, Any]     # specs for ONE block (unstacked)
    head_pspecs: Dict[str, Any]
    # SharedLayerDesc parity: tied unembedding. When True, head_apply is
    # (head_state, embed_state, h, labels) — the embed weights are
    # pp-replicated, so the head reads them directly and autodiff sums the
    # two grad paths (no explicit cross-stage grad sync needed).
    tied_head: bool = False


def _norm_pspec(p, ndim):
    """Normalize a Parameter.pspec (possibly None/short) to `ndim` entries."""
    from jax.sharding import PartitionSpec as P
    if p is None:
        return P(*([None] * ndim))
    entries = list(p) + [None] * (ndim - len(tuple(p)))
    return P(*entries[:ndim])


def part_specs(layer) -> Dict[str, Any]:
    return {name: _norm_pspec(getattr(param, "pspec", None), param.value.ndim)
            for name, param in layer.named_parameters() if param.trainable}


def make_pipeline_train_step(model, optimizer, strategy=None, hcg=None,
                             donate: bool = True):
    """Compiled pp×mp×dp×sharding train step via collective-permute pipelining.

    One jit: embed + a scan over (n_micro + n_stages - 1) ticks, each tick
    running this stage's block stack and rotating activations to the next
    stage with ppermute (reference 1F1B/NCCL-p2p analog — SURVEY.md §3.3);
    TP/DP/ZeRO ride the mesh's Auto axes via GSPMD inside the same program.
    Schedule is GPipe-style accumulation (activations for in-flight
    microbatches are rematerialized when strategy.recompute is on).

    Returns (step_fn, init_fn); state is a flat dict with ``embed.``/
    ``blocks.``/``head.`` key prefixes, block params stacked
    (n_stages, per_stage, ...) and sharded over the "pp" axis.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.parallel import fleet as fleet_mod
    from paddle_tpu.parallel import sharding as sharding_mod
    from paddle_tpu.parallel.strategy import DistributedStrategy
    from paddle_tpu.parallel.topology import get_hybrid_communicate_group

    strategy = strategy or DistributedStrategy()
    hcg = hcg or fleet_mod.get_fleet().get_hybrid_communicate_group() \
        or get_hybrid_communicate_group()
    mesh = hcg.mesh
    n_stages = hcg.get_pipe_parallel_world_size()
    n_micro = strategy.pipeline_configs.accumulate_steps
    if n_micro < n_stages:
        n_micro = n_stages  # keep the bubble bounded; reference asserts too

    parts: PipelineParts = model.pipeline_parts()
    n_layers = len(parts.block_states)
    if n_layers % n_stages:
        raise ValueError(f"{n_layers} layers not divisible by pp={n_stages}")
    per_stage = n_layers // n_stages

    # ---- flat state: embed. / blocks.(stacked) / head. ----
    stacked = {
        k: _jnp.stack([st[k] for st in parts.block_states]).reshape(
            (n_stages, per_stage) + parts.block_states[0][k].shape)
        for k in parts.block_states[0]}
    state0 = {}
    state0.update({f"embed.{k}": v for k, v in parts.embed_state.items()})
    state0.update({f"blocks.{k}": v for k, v in stacked.items()})
    state0.update({f"head.{k}": v for k, v in parts.head_state.items()})

    # ---- shardings: pp on the stage dim, TP placements, ZeRO composition ----
    zstage = strategy.sharding_configs.stage if strategy.sharding else 0
    zdeg = hcg.get_sharding_parallel_world_size()

    pspecs = {}
    for k, spec in parts.embed_pspecs.items():
        pspecs[f"embed.{k}"] = spec
    for k, spec in parts.block_pspecs.items():
        pspecs[f"blocks.{k}"] = P("pp", None, *tuple(spec))
    for k, spec in parts.head_pspecs.items():
        pspecs[f"head.{k}"] = spec
    if zstage >= 3 and zdeg > 1:
        pspecs = {k: sharding_mod.param_pspec(state0[k].shape, zdeg,
                                              existing=pspecs[k])
                  for k in pspecs}
    ospecs = sharding_mod.opt_state_specs(pspecs, zstage, zdeg, state0)

    dp_axes = tuple(a for a in ("dp", "sharding")
                    if a in mesh.axis_names and mesh.shape[a] > 1)
    bspec = P(dp_axes if dp_axes else None)

    remat = strategy.recompute
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def split_state(flat):
        e = {k[len("embed."):]: v for k, v in flat.items()
             if k.startswith("embed.")}
        b = {k[len("blocks."):]: v for k, v in flat.items()
             if k.startswith("blocks.")}
        h = {k[len("head."):]: v for k, v in flat.items()
             if k.startswith("head.")}
        return e, b, h

    def pipeline_loss(flat_state, ids_mb, labels_mb):
        """ids_mb/labels_mb: (n_micro, mb, seq)."""
        embed_st, blocks_st, head_st = split_state(flat_state)

        def inner(blocks_local, embed_st, head_st, ids_mb, labels_mb):
            stage = jax.lax.axis_index("pp")
            blocks_me = jax.tree_util.tree_map(lambda a: a[0], blocks_local)
            total = n_micro + n_stages - 1

            def stage_fwd(h):
                def body(h, one_layer):
                    out = parts.block_apply(one_layer, h)
                    if isinstance(out, tuple):   # (h, extra_loss) — e.g. MoE aux
                        return out[0], out[1].astype(_jnp.float32)
                    return out, _jnp.zeros((), _jnp.float32)
                h, extras = jax.lax.scan(body, h, blocks_me)
                return h, _jnp.sum(extras)

            def tick(carry, t):
                h_carry, loss_acc = carry
                ids_t = jax.lax.dynamic_index_in_dim(
                    ids_mb, _jnp.minimum(t, n_micro - 1), 0, keepdims=False)
                h_in = parts.embed_apply(embed_st, ids_t)
                h = _jnp.where(stage == 0, h_in, h_carry)
                h_out, extra = stage_fwd(h)
                out_idx = t - (n_stages - 1)
                lbl = jax.lax.dynamic_index_in_dim(
                    labels_mb, _jnp.clip(out_idx, 0, n_micro - 1), 0,
                    keepdims=False)
                if parts.tied_head:
                    mb_loss = parts.head_apply(head_st, embed_st, h_out, lbl)
                else:
                    mb_loss = parts.head_apply(head_st, h_out, lbl)
                emit = (stage == n_stages - 1) & (out_idx >= 0)
                # stage s holds microbatch (t - s); its extra losses count
                # only while that microbatch is real (not a bubble tick)
                valid = (t >= stage) & (t - stage < n_micro)
                loss_acc = (loss_acc + _jnp.where(emit, mb_loss, 0.0)
                            + _jnp.where(valid, extra, 0.0))
                h_next = jax.lax.ppermute(h_out, "pp", perm)
                return (h_next, loss_acc), None

            if remat:
                tick = jax.checkpoint(tick)

            mb = ids_mb.shape[1]
            seq = ids_mb.shape[2]
            h0_probe = jax.eval_shape(
                lambda s, i: parts.embed_apply(s, i), embed_st,
                jax.ShapeDtypeStruct((mb, seq), ids_mb.dtype))
            h0 = _jnp.zeros(h0_probe.shape, h0_probe.dtype)
            # carries vary per-stage: mark them varying over the manual axis
            h0 = jax.lax.pcast(h0, ("pp",), to="varying")
            loss0 = jax.lax.pcast(_jnp.zeros((), _jnp.float32), ("pp",),
                                  to="varying")
            (_, loss_acc), _ = jax.lax.scan(tick, (h0, loss0),
                                            _jnp.arange(total))
            return jax.lax.psum(loss_acc, "pp") / n_micro

        f = jax.shard_map(
            inner, mesh=mesh, axis_names={"pp"},
            in_specs=(P("pp"), P(), P(), P(), P()),
            out_specs=P())
        return f(blocks_st, embed_st, head_st, ids_mb, labels_mb)

    def _step(flat_state, opt_state, ids_mb, labels_mb):
        loss, grads = jax.value_and_grad(pipeline_loss)(
            flat_state, ids_mb, labels_mb)
        grads = {k: jax.lax.with_sharding_constraint(
            g, NamedSharding(mesh, pspecs[k])) for k, g in grads.items()}
        new_state, new_opt = optimizer.update(grads, opt_state, flat_state)
        new_state = {k: jax.lax.with_sharding_constraint(
            v, NamedSharding(mesh, pspecs[k])) for k, v in new_state.items()}
        return new_state, new_opt, loss

    jit_step = jax.jit(_step, donate_argnums=(0, 1) if donate else ())

    def init_fn():
        # copy so jit donation can never free the Layer's own param buffers
        placed = {k: jax.device_put(_jnp.array(v, copy=True),
                                    NamedSharding(mesh, pspecs[k]))
                  for k, v in state0.items()}
        opt_state = optimizer.init_state(placed)

        def place_slot(tree):
            if isinstance(tree, dict):
                return {k: jax.device_put(v, NamedSharding(
                    mesh, ospecs.get(k, P()))) for k, v in tree.items()}
            return tree
        opt_state = {slot: place_slot(t) for slot, t in opt_state.items()}
        return placed, opt_state

    def step_fn(state, opt_state, batch):
        """batch: dict with 'input' (B, seq) and 'labels' (B, seq);
        B must be divisible by n_micro."""
        ids, labels = batch["input"], batch["labels"]
        B = ids.shape[0]
        if B % n_micro:
            raise ValueError(f"batch {B} not divisible by n_micro={n_micro}")
        mb = B // n_micro
        dp_total = 1
        for a in dp_axes:
            dp_total *= mesh.shape[a]
        if dp_total > 1 and mb % dp_total == 0:
            mb_spec = bspec
        else:
            mb_spec = P(None)
            if dp_total > 1:
                import warnings
                warnings.warn(
                    f"microbatch size {mb} not divisible by dp×sharding="
                    f"{dp_total}: replicating the batch across those axes "
                    "(no data parallelism this step)", stacklevel=2)
        ids_mb = ids.reshape(n_micro, mb, *ids.shape[1:])
        labels_mb = labels.reshape(n_micro, mb, *labels.shape[1:])
        ids_mb = jax.device_put(ids_mb, NamedSharding(
            mesh, P(None, *tuple(mb_spec))))
        labels_mb = jax.device_put(labels_mb, NamedSharding(
            mesh, P(None, *tuple(mb_spec))))
        with jax.set_mesh(mesh):
            return jit_step(state, opt_state, ids_mb, labels_mb)

    return step_fn, init_fn


def pipeline_spmd_fn(stage_fn: Callable, n_stages: int, n_micro: int,
                     axis_name: str = "pp"):
    """Build a pipelined forward over stage-stacked params.

    stage_fn(stage_params, x) -> y : one stage's compute (same shape in/out).
    Returns fn(stacked_params, microbatches) -> stacked outputs, to be called
    INSIDE shard_map over `axis_name` where stacked_params' leading dim is the
    (sharded) stage dim and microbatches is (n_micro, mb, ...) replicated.

    Steady-state rotation: each of the (n_micro + n_stages - 1) ticks, every
    stage processes its current activation and ppermutes it to the next stage
    — the standard TPU pipeline recipe (scaling-book §pipelining): compute and
    ICI transfer overlap via XLA's latency-hiding scheduler.
    """

    def run(stage_params, microbatches):
        stage = jax.lax.axis_index(axis_name)
        total = n_micro + n_stages - 1
        mb_shape = microbatches.shape[1:]
        state = jnp.zeros(mb_shape, microbatches.dtype)
        outputs = jnp.zeros((n_micro,) + mb_shape, microbatches.dtype)

        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(t, carry):
            state, outputs = carry
            # stage 0 ingests microbatch t (while t < n_micro)
            inject = jax.lax.dynamic_index_in_dim(
                microbatches, jnp.minimum(t, n_micro - 1), axis=0, keepdims=False)
            x = jnp.where(stage == 0, inject, state)
            y = stage_fn(stage_params, x)
            # last stage emits microbatch t-(n_stages-1)
            out_idx = t - (n_stages - 1)
            emit = (stage == n_stages - 1) & (out_idx >= 0)
            outputs = jax.lax.cond(
                emit,
                lambda o: jax.lax.dynamic_update_index_in_dim(
                    o, y, jnp.maximum(out_idx, 0), axis=0),
                lambda o: o, outputs)
            state = jax.lax.ppermute(y, axis_name, perm)
            return state, outputs

        _, outputs = jax.lax.fori_loop(0, total, tick, (state, outputs))
        # outputs live on the last stage; broadcast so every stage agrees
        outputs = jax.lax.psum(
            jnp.where(stage == n_stages - 1, outputs, jnp.zeros_like(outputs)),
            axis_name)
        return outputs

    return run
