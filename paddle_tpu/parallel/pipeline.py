"""Pipeline parallelism — LayerDesc model description + TPU-native schedules.

Reference (SURVEY.md §2.6-PP): `PipelineLayer` (LayerDesc list → stage
segments, SharedLayerDesc weight tying) + `PipelineParallel` runtime with the
1F1B schedule over NCCL p2p (meta_parallel/pipeline_parallel.py,
pp_layers.py, p2p_communication.py).

TPU-native: stages live on the mesh's "pp" axis. The production schedule is
collective-permute pipelining INSIDE one jit: stage weights are stacked on a
leading pp dim, shard_map splits them, and a lax.scan over (microbatches +
bubble) rotates activations with ppermute — XLA overlaps the permute with the
next microbatch's compute, which is the 1F1B overlap the reference hand-codes
with comm streams. Implemented in `pipeline_spmd_fn` (full impl in this
module; see tests/test_pipeline.py for invariance vs single-device).
"""

from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.nn.layer import Layer
from paddle_tpu.nn.layers.common import LayerList


class LayerDesc:
    """Deferred layer construction (reference parity: pp_layers.py)."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Weight-tied layer appearing on multiple stages (e.g. embedding/unembed).

    On TPU tying is free inside one jit program: the builder returns the same
    layer object, and GSPMD replicates/reduces as needed."""

    def __init__(self, key, layer_cls, *args, forward_func=None, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.key = key
        self.forward_func = forward_func


class PipelineLayer(Layer):
    """Describes a model as a flat list of LayerDescs split into pp stages."""

    def __init__(self, layers: Sequence, num_stages: int = 1, topology=None,
                 loss_fn=None, seg_method="uniform", recompute_interval=0):
        super().__init__()
        self.descs = list(layers)
        self.num_stages = num_stages
        self.loss_fn = loss_fn
        self.recompute_interval = recompute_interval
        self._shared = {}
        built = []
        for d in self.descs:
            if isinstance(d, SharedLayerDesc):
                if d.key not in self._shared:
                    self._shared[d.key] = d.build_layer()
                built.append(self._shared[d.key])
            elif isinstance(d, LayerDesc):
                built.append(d.build_layer())
            elif isinstance(d, Layer):
                built.append(d)
            else:  # plain callable
                built.append(_FnLayer(d))
        self.run_function = LayerList(built)
        self.segments = self._segment(len(built), num_stages)

    @staticmethod
    def _segment(n_layers, n_stages):
        """Uniform segmentation (reference seg_method='uniform')."""
        base = n_layers // n_stages
        rem = n_layers % n_stages
        bounds = [0]
        for s in range(n_stages):
            bounds.append(bounds[-1] + base + (1 if s < rem else 0))
        return bounds

    def stage_layers(self, stage_id):
        lo, hi = self.segments[stage_id], self.segments[stage_id + 1]
        return list(self.run_function)[lo:hi]

    def forward(self, x):
        for l in self.run_function:
            x = l(x)
        return x


class _FnLayer(Layer):
    def __init__(self, fn):
        super().__init__()
        self._fn = fn

    def forward(self, *args):
        return self._fn(*args)


class PipelineParallel(Layer):
    """Runtime wrapper chosen by fleet.distributed_model when pp_degree>1.

    `train_batch(data, optimizer)` runs the microbatched schedule. The
    underlying schedule is GPipe-style accumulation compiled into one jit
    (`pipeline_spmd_fn`); host-driven 1F1B over per-stage jits is available
    as `schedule='host1f1b'` for DCN-spanning topologies.
    """

    def __init__(self, layers, hcg, strategy=None):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = strategy.pipeline_configs if strategy else None
        self.micro_batch_size = cfg.micro_batch_size if cfg else 1
        self.accumulate_steps = cfg.accumulate_steps if cfg else 1

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    @property
    def inner_layer(self):
        return self._layers


# ---- SPMD pipeline schedule (collective-permute pipelining) ---------------

def pipeline_spmd_fn(stage_fn: Callable, n_stages: int, n_micro: int,
                     axis_name: str = "pp"):
    """Build a pipelined forward over stage-stacked params.

    stage_fn(stage_params, x) -> y : one stage's compute (same shape in/out).
    Returns fn(stacked_params, microbatches) -> stacked outputs, to be called
    INSIDE shard_map over `axis_name` where stacked_params' leading dim is the
    (sharded) stage dim and microbatches is (n_micro, mb, ...) replicated.

    Steady-state rotation: each of the (n_micro + n_stages - 1) ticks, every
    stage processes its current activation and ppermutes it to the next stage
    — the standard TPU pipeline recipe (scaling-book §pipelining): compute and
    ICI transfer overlap via XLA's latency-hiding scheduler.
    """

    def run(stage_params, microbatches):
        stage = jax.lax.axis_index(axis_name)
        total = n_micro + n_stages - 1
        mb_shape = microbatches.shape[1:]
        state = jnp.zeros(mb_shape, microbatches.dtype)
        outputs = jnp.zeros((n_micro,) + mb_shape, microbatches.dtype)

        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(t, carry):
            state, outputs = carry
            # stage 0 ingests microbatch t (while t < n_micro)
            inject = jax.lax.dynamic_index_in_dim(
                microbatches, jnp.minimum(t, n_micro - 1), axis=0, keepdims=False)
            x = jnp.where(stage == 0, inject, state)
            y = stage_fn(stage_params, x)
            # last stage emits microbatch t-(n_stages-1)
            out_idx = t - (n_stages - 1)
            emit = (stage == n_stages - 1) & (out_idx >= 0)
            outputs = jax.lax.cond(
                emit,
                lambda o: jax.lax.dynamic_update_index_in_dim(
                    o, y, jnp.maximum(out_idx, 0), axis=0),
                lambda o: o, outputs)
            state = jax.lax.ppermute(y, axis_name, perm)
            return state, outputs

        _, outputs = jax.lax.fori_loop(0, total, tick, (state, outputs))
        # outputs live on the last stage; broadcast so every stage agrees
        outputs = jax.lax.psum(
            jnp.where(stage == n_stages - 1, outputs, jnp.zeros_like(outputs)),
            axis_name)
        return outputs

    return run
