"""Static lockstep schedule tables for SPMD pipeline parallelism.

Reference: the 1F1B loop and its interleaved (virtual-chunk) variant in
`python/paddle/distributed/fleet/meta_parallel/pipeline_parallel.py`
(`forward_backward_pipeline`, SURVEY.md §2.6-PP). The reference runs the
schedule as host Python issuing NCCL p2p per microbatch; on TPU the whole
schedule compiles into ONE jitted scan where every tick each stage runs at
most one forward unit and one backward unit, activations rotate forward with
ppermute, gradients rotate backward, and ring buffers absorb schedule slack.

Because the program is SPMD (one program, all stages), the schedule must be
*static*: this module precomputes, per (pp_degree, virtual chunks, n_micro),
per-stage tick tables — which (chunk, microbatch) each stage processes at
each tick, which ring-buffer slot each wire arrival lands in, and which slot
each consumer reads — via greedy list scheduling over the Megatron-style
per-rank unit orders. The tables become small constant int32 arrays baked
into the jit; all control flow is data-independent, which is exactly what
XLA wants.

Scheduling model (one tick = one F slot + one B slot per stage):
- F of virtual stage V = c*S + s for microbatch m needs F of (V-1, m) at a
  strictly earlier tick (the activation travels one ppermute hop per tick).
- B of (V, m) needs the stage's own F of (V, m) at the same tick or earlier
  (the stashed input is local) and, for V < VS-1, B of (V+1, m) strictly
  earlier (the gradient hop). The last virtual stage seeds its own loss
  cotangent, so its B can follow its F immediately.
- Backward recomputes the unit forward from the stashed input (activation
  rematerialization) — stash liveness is O(pp), not O(n_micro): the 1F1B
  memory profile that GPipe-style accumulation lacks.
"""

import dataclasses
from typing import List, Tuple

import numpy as np


def _unit_sequences(S: int, v: int, M: int):
    """Shared (chunk, microbatch) orders for F and B.

    All stages enumerate the same (c, m) sequence (Megatron's
    get_model_chunk_id convention: microbatch groups of S cycle through the
    v chunks; B mirrors the chunk order). A shared order is what makes every
    wire FIFO: producer stage and consumer stage emit/absorb units in the
    same sequence, so ring-buffer slots can be assigned by arrival index.
    """
    fseq: List[Tuple[int, int]] = []
    bseq: List[Tuple[int, int]] = []
    for g0 in range(0, M, S):
        ms = list(range(g0, min(g0 + S, M)))
        for c in range(v):
            fseq += [(c, m) for m in ms]
        for c in reversed(range(v)):
            bseq += [(c, m) for m in ms]
    return fseq, bseq


def _simulate(S: int, v: int, M: int):
    """Greedy lockstep list-scheduling → per-stage (tick, c, m) exec lists."""
    fseq, bseq = _unit_sequences(S, v, M)
    VS = v * S
    fi = [0] * S
    bi = [0] * S
    done_f = {}
    done_b = {}
    f_exec: List[List[Tuple[int, int, int]]] = [[] for _ in range(S)]
    b_exec: List[List[Tuple[int, int, int]]] = [[] for _ in range(S)]
    t = 0
    while any(fi[s] < len(fseq) or bi[s] < len(bseq) for s in range(S)):
        new_f = []
        new_b = []
        for s in range(S):
            if fi[s] < len(fseq):
                c, m = fseq[fi[s]]
                V = c * S + s
                if V == 0 or done_f.get((V - 1, m), t) < t:
                    f_exec[s].append((t, c, m))
                    new_f.append(((V, m), t))
                    fi[s] += 1
            if bi[s] < len(bseq):
                c, m = bseq[bi[s]]
                V = c * S + s
                own_f = ((V, m) in done_f
                         or any(u == (V, m) for u, _ in new_f))
                grad_ok = (V == VS - 1) or done_b.get((V + 1, m), t) < t
                if own_f and grad_ok:
                    b_exec[s].append((t, c, m))
                    new_b.append(((V, m), t))
                    bi[s] += 1
        done_f.update(dict(new_f))
        done_b.update(dict(new_b))
        t += 1
        if t > 8 * (M * v + S) + 64:
            raise RuntimeError(
                f"pipeline schedule did not converge (S={S}, v={v}, M={M})")
    return f_exec, b_exec, done_f, done_b, t


def _fifo_ring(events_write, events_read):
    """Assign FIFO ring slots. events_* are tick lists in unit order (i-th
    write is consumed by i-th read). Returns (slots, ring_size)."""
    assert len(events_write) == len(events_read)
    n = len(events_write)
    if n == 0:
        return [], 1
    # max in flight at any moment
    depth = 0
    for i in range(n):
        inflight = sum(1 for j in range(n)
                       if events_write[j] <= events_write[i] < events_read[j])
        depth = max(depth, inflight)
    size = max(depth, 1)
    # modular reuse safety: within a tick, writes land before reads, so the
    # read of slot k must be STRICTLY before the write of unit k+size
    while any(events_read[i] >= events_write[i + size]
              for i in range(n - size)):
        size += 1
    return [i % size for i in range(n)], size


def _out_of_order_ring(write_ticks, read_by_index):
    """Ring slots for the stash, where reads may be out of write order.
    write_ticks[i] is the tick unit i was written; read_by_index[i] the tick
    it is read. Find the smallest size where every reuse is safe."""
    n = len(write_ticks)
    if n == 0:
        return [], 1
    size = 1
    while True:
        ok = True
        for i in range(n):
            j = i + size
            # F slots write the stash before B slots read it in the same
            # tick, so reuse needs read strictly before the next write
            if j < n and read_by_index[i] >= write_ticks[j]:
                ok = False
                break
        if ok:
            return [i % size for i in range(n)], size
        size += 1


@dataclasses.dataclass
class ScheduleTables:
    """Per-tick int32 tables, each shaped (T, S) (tick-major for lax.scan).

    Sentinels: slot -1 = inactive / no event. `f_src`: -1 inactive,
    -2 embed injection (V == 0), >= 0 ring slot. `b_gsrc`: -1 inactive,
    -2 loss seed (V == VS-1), >= 0 ring slot.
    """
    n_ticks: int
    n_stages: int
    n_chunks: int
    n_micro: int
    f_c: np.ndarray          # chunk of the F unit (0 when inactive)
    f_m: np.ndarray          # microbatch of the F unit
    f_active: np.ndarray     # 0/1
    f_is_last: np.ndarray    # F unit is the last virtual stage (emits loss)
    f_src: np.ndarray        # input source (see sentinels)
    f_wr: np.ndarray         # ring slot an arriving activation stores to
    f_stash: np.ndarray      # stash slot the F input writes to
    b_c: np.ndarray
    b_m: np.ndarray
    b_active: np.ndarray
    b_is_v0: np.ndarray      # B unit is virtual stage 0 (emits embed grads)
    b_gsrc: np.ndarray       # gradient source (see sentinels)
    b_gwr: np.ndarray        # ring slot an arriving gradient stores to
    b_stash: np.ndarray      # stash slot the B unit reads its input from
    fwd_ring: int
    grad_ring: int
    stash_ring: int
    bubble_fraction: float   # fraction of idle (F or B) slots — the bubble


def build_schedule_tables(S: int, v: int, M: int) -> ScheduleTables:
    """Build lockstep tables for pp=S stages, v virtual chunks, M microbatches.

    v == 1 reproduces the classic non-interleaved 1F1B schedule; v > 1 is the
    interleaved (virtual pipeline) variant and requires M % S == 0, as the
    reference does for its interleaved scheduler.
    """
    if v > 1 and M % S:
        raise ValueError(
            f"interleaved schedule needs accumulate_steps % pp == 0 "
            f"(got M={M}, pp={S})")
    VS = v * S
    f_exec, b_exec, done_f, done_b, T = _simulate(S, v, M)
    fseq, bseq = _unit_sequences(S, v, M)

    shape = (T, S)
    tbl = {k: np.zeros(shape, np.int32) for k in
           ("f_c", "f_m", "f_active", "f_is_last", "f_stash",
            "b_c", "b_m", "b_active", "b_is_v0", "b_stash")}
    f_src = np.full(shape, -1, np.int32)
    f_wr = np.full(shape, -1, np.int32)
    b_gsrc = np.full(shape, -1, np.int32)
    b_gwr = np.full(shape, -1, np.int32)

    fwd_ring = 1
    grad_ring = 1
    stash_ring = 1
    for s in range(S):
        # ---- forward wire: units with V > 0, consumed in shared order ----
        cons = [(t, c, m) for (t, c, m) in f_exec[s] if c * S + s > 0]
        writes = [done_f[(c * S + s - 1, m)] + 1 for (_, c, m) in cons]
        reads = [t for (t, _, _) in cons]
        assert writes == sorted(writes), "forward wire lost FIFO order"
        assert all(w <= r for w, r in zip(writes, reads))
        slots, size = _fifo_ring(writes, reads)
        fwd_ring = max(fwd_ring, size)
        for (tick, _, _), w, sl in zip(cons, writes, slots):
            assert f_wr[w, s] == -1, "two arrivals in one tick"
            f_wr[w, s] = sl
            f_src[tick, s] = sl

        # ---- F table + stash writes ----
        stash_write_tick = {}
        for i, (t, c, m) in enumerate(f_exec[s]):
            tbl["f_c"][t, s] = c
            tbl["f_m"][t, s] = m
            tbl["f_active"][t, s] = 1
            tbl["f_is_last"][t, s] = int(c * S + s == VS - 1)
            if c * S + s == 0:
                f_src[t, s] = -2
            stash_write_tick[(c, m)] = (i, t)

        # ---- stash ring (reads may be out of order for v > 1) ----
        w_ticks = [t for (t, _, _) in f_exec[s]]
        read_by_index = [0] * len(w_ticks)
        for (t, c, m) in b_exec[s]:
            i, _ = stash_write_tick[(c, m)]
            read_by_index[i] = t
        sslots, ssize = _out_of_order_ring(w_ticks, read_by_index)
        stash_ring = max(stash_ring, ssize)
        for i, (t, _, _) in enumerate(f_exec[s]):
            tbl["f_stash"][t, s] = sslots[i]

        # ---- gradient wire: B units with V < VS-1, shared order ----
        bcons = [(t, c, m) for (t, c, m) in b_exec[s] if c * S + s < VS - 1]
        bwrites = [done_b[(c * S + s + 1, m)] + 1 for (_, c, m) in bcons]
        breads = [t for (t, _, _) in bcons]
        assert bwrites == sorted(bwrites), "gradient wire lost FIFO order"
        assert all(w <= r for w, r in zip(bwrites, breads))
        gslots, gsize = _fifo_ring(bwrites, breads)
        grad_ring = max(grad_ring, gsize)
        for (tick, _, _), w, sl in zip(bcons, bwrites, gslots):
            assert b_gwr[w, s] == -1, "two gradient arrivals in one tick"
            b_gwr[w, s] = sl
            b_gsrc[tick, s] = sl

        # ---- B table ----
        for (t, c, m) in b_exec[s]:
            tbl["b_c"][t, s] = c
            tbl["b_m"][t, s] = m
            tbl["b_active"][t, s] = 1
            tbl["b_is_v0"][t, s] = int(c * S + s == 0)
            tbl["b_stash"][t, s] = sslots[stash_write_tick[(c, m)][0]]
            if c * S + s == VS - 1:
                b_gsrc[t, s] = -2

    idle = (2 * T * S - int(tbl["f_active"].sum())
            - int(tbl["b_active"].sum()))
    return ScheduleTables(
        n_ticks=T, n_stages=S, n_chunks=v, n_micro=M,
        f_c=tbl["f_c"], f_m=tbl["f_m"], f_active=tbl["f_active"],
        f_is_last=tbl["f_is_last"], f_src=f_src, f_wr=f_wr,
        f_stash=tbl["f_stash"],
        b_c=tbl["b_c"], b_m=tbl["b_m"], b_active=tbl["b_active"],
        b_is_v0=tbl["b_is_v0"], b_gsrc=b_gsrc, b_gwr=b_gwr,
        b_stash=tbl["b_stash"],
        fwd_ring=fwd_ring, grad_ring=grad_ring, stash_ring=stash_ring,
        bubble_fraction=idle / float(2 * T * S))
