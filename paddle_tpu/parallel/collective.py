"""Collective communication veneer (≈ paddle.distributed.{all_reduce,...}).

Reference (SURVEY.md §2.5): ProcessGroup async collectives on dedicated NCCL
comm streams. TPU-native: collectives are XLA ops — inside jit/shard_map they
compile to ICI transfers scheduled by XLA's latency-hiding scheduler (no manual
streams). This module provides:

* `new_group(ranks)` → a `Group` wrapping a 1-D device mesh, the handle parity
  object for code ported from the reference.
* Eager functions (`all_reduce(x, group=...)`) for outside-jit use: each takes
  an array sharded (or shardable) over the group's axis, runs a tiny jitted
  shard_map collective, and returns the result. On a single device they are
  identities — matching the reference's degenerate world_size==1 behavior.
* In-jit primitives re-exported (`psum`, `ppermute`, ...) for strategy code.

API-design note: the reference returns waitable Tasks (`sync_op=False`); XLA's
async dispatch makes every call non-blocking already, so ops return arrays and
`.wait()` parity is a no-op wrapper.

Multi-process semantics: when `jax.process_count() > 1` (after
`init_parallel_env` / `jax.distributed.initialize`), the eager functions
switch from the single-process stacked-per-rank convention to true
cross-process collectives over `multihost_utils` — each process passes its
LOCAL value and receives the collective result, matching the reference's
ProcessGroup semantics. Point-to-point `send`/`recv` have no eager
multi-process implementation (use in-jit `ppermute`); they raise rather
than silently compute garbage.
"""

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from paddle_tpu.core.enforce import enforce


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


def _multiprocess() -> bool:
    return jax.process_count() > 1


class Group:
    """A communicator: an ordered set of devices with a private 1-D mesh."""

    def __init__(self, devices: Sequence, name: str = "group"):
        self.devices = list(devices)
        self.nranks = len(self.devices)
        self.name = name
        self.mesh = Mesh(np.asarray(self.devices), axis_names=("g",))
        # single-process SPMD: all group members live here (rank 0);
        # multi-process: this process's rank in the world
        self.rank = jax.process_index() if _multiprocess() else 0

    @property
    def world_size(self):
        return self.nranks

    def __repr__(self):
        return f"Group(nranks={self.nranks}, name={self.name!r})"


_default_group: List[Optional[Group]] = [None]


def _get_group(group: Optional[Group]) -> Group:
    if group is not None:
        return group
    if _default_group[0] is None:
        _default_group[0] = Group(jax.devices(), name="default")
    return _default_group[0]


def new_group(ranks=None, backend=None, name="group") -> Group:
    devs = jax.devices()
    if ranks is None:
        ranks = list(range(len(devs)))
    return Group([devs[r] for r in ranks], name=name)


def _sharded_over_group(x, g: Group):
    return jax.device_put(x, NamedSharding(g.mesh, P("g")))


def _reduce_fn(op):
    return {
        ReduceOp.SUM: jax.lax.psum,
        ReduceOp.MAX: jax.lax.pmax,
        ReduceOp.MIN: jax.lax.pmin,
        ReduceOp.AVG: lambda v, ax: jax.lax.pmean(v, ax),
    }[op]


# ---- eager veneers ---------------------------------------------------------
# Single-process: each operates on an array whose leading axis is the group
# dimension (one slice per rank — the single-process analog of per-rank
# tensors). Multi-process: each process passes its LOCAL value; the op is a
# true cross-process collective (multihost_utils over the distributed
# runtime — ProcessGroup semantics, SURVEY.md §2.5).

def _mp_utils():
    from jax.experimental import multihost_utils
    return multihost_utils


def _mp_world_only(g: Group, opname: str):
    # The eager multi-process path gathers per PROCESS; with several local
    # devices per process the rank arithmetic below would silently mix
    # process and device indices — refuse loudly (in-jit shard_map
    # collectives are the supported path on pod slices).
    if jax.local_device_count() != 1:
        raise NotImplementedError(
            f"{opname}: eager multi-process collectives support only "
            f"1 device per process (local_device_count="
            f"{jax.local_device_count()}); use in-jit collectives "
            "(shard_map/psum) for multi-device hosts")
    enforce(g.nranks == jax.process_count(),
            f"{opname}: eager multi-process collectives support only the "
            f"world group (got nranks={g.nranks}, "
            f"world={jax.process_count()});"
            " use in-jit shard_map collectives for subgroups")


_MP_REDUCERS = {
    ReduceOp.SUM: jnp.sum,
    ReduceOp.MAX: jnp.max,
    ReduceOp.MIN: jnp.min,
    ReduceOp.PROD: jnp.prod,
    ReduceOp.AVG: jnp.mean,
}


def all_reduce(x, op=ReduceOp.SUM, group=None, sync_op=True):
    """Single-process: x is (nranks, ...) stacked per-rank values → same
    shape, reduced copies. Multi-process: x is this process's value →
    the cross-process reduction."""
    g = _get_group(group)
    if _multiprocess():
        _mp_world_only(g, "all_reduce")
        gathered = _mp_utils().process_allgather(x)     # (nprocs, ...)
        return _MP_REDUCERS[op](gathered, axis=0).astype(x.dtype)
    if g.nranks == 1:
        return x
    enforce(x.shape[0] == g.nranks, f"leading dim {x.shape[0]} != nranks {g.nranks}")
    x = _sharded_over_group(x, g)
    fn = _reduce_fn(op)

    @jax.jit
    def run(v):
        def body(s):
            r = fn(s, "g")
            return r
        return shard_map(body, mesh=g.mesh, in_specs=P("g"),
                         out_specs=P("g"))(v)

    return run(x)


def all_gather(tensor_list_or_x, x=None, group=None, sync_op=True, axis=0):
    """Single-process: per-rank slices are already globally visible.
    Multi-process: gathers each process's local value into a (nranks, ...)
    stack (the reference returns a list; pass a list as the first arg to get
    that form)."""
    if isinstance(tensor_list_or_x, list):
        out_list, x = tensor_list_or_x, x
    else:
        out_list, x = None, tensor_list_or_x
    g = _get_group(group)
    if _multiprocess():
        _mp_world_only(g, "all_gather")
        res = _mp_utils().process_allgather(x)          # (nprocs, ...)
    else:
        res = x  # already globally visible in single-process SPMD
    if out_list is not None:
        for i in range(g.nranks):
            out_list.append(res[i])
        return out_list
    return res


def reduce(x, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    """Reduce to `dst`. Implemented as all_reduce: every rank receives the
    reduced value, a strict superset of the reference contract (which
    defines the result only at `dst`). `dst` is accepted for API parity;
    there is no bandwidth saving on TPU — XLA's all-reduce over ICI is the
    primitive a rooted reduce would lower to anyway."""
    return all_reduce(x, op=op, group=group)


def broadcast(x, src=0, group=None, sync_op=True):
    g = _get_group(group)
    if _multiprocess():
        _mp_world_only(g, "broadcast")
        return _mp_utils().broadcast_one_to_all(
            x, is_source=jax.process_index() == src)
    if g.nranks == 1:
        return x
    src_slice = x[src]
    return jnp.broadcast_to(src_slice[None], x.shape)


def scatter(x, tensor_list=None, src=0, group=None, sync_op=True):
    """Scatter `tensor_list` from `src`, one chunk per rank.

    Multi-process note: implemented as a full broadcast of the stacked
    list followed by a local slice — O(world) data per rank for an
    O(1/world) result. Fine at the tensor sizes eager scatter is used for
    (setup/debug); inside jit, GSPMD sharding is the fast path."""
    g = _get_group(group)
    if _multiprocess():
        _mp_world_only(g, "scatter")
        if tensor_list is not None and jax.process_index() == src:
            stacked = jnp.stack(tensor_list)
        else:
            # non-src ranks contribute only the output shape
            stacked = jnp.broadcast_to(x[None], (g.nranks,) + tuple(x.shape))
        data = _mp_utils().broadcast_one_to_all(
            stacked, is_source=jax.process_index() == src)
        return data[g.rank]
    if tensor_list is not None:
        return jnp.stack(tensor_list)[g.rank] if g.nranks > 1 else tensor_list[0]
    return x


def reduce_scatter(x, op=ReduceOp.SUM, group=None, sync_op=True):
    """Single-process: x (nranks, nranks*chunk, ...) per-rank values →
    (nranks, chunk, ...). Multi-process: x (nranks*chunk, ...) local value →
    this rank's reduced (chunk, ...) slice."""
    g = _get_group(group)
    if _multiprocess():
        _mp_world_only(g, "reduce_scatter")
        gathered = _mp_utils().process_allgather(x)
        reduced = _MP_REDUCERS[op](gathered, axis=0).astype(x.dtype)
        chunk = reduced.shape[0] // g.nranks
        return reduced[g.rank * chunk:(g.rank + 1) * chunk]
    if g.nranks == 1:
        return x
    x = _sharded_over_group(x, g)
    fn = _reduce_fn(op)

    @jax.jit
    def run(v):
        def body(s):
            r = fn(s, "g")  # (1, n*chunk, ...)
            i = jax.lax.axis_index("g")
            chunk = r.shape[1] // g.nranks
            return jax.lax.dynamic_slice_in_dim(r, i * chunk, chunk, axis=1)
        return shard_map(body, mesh=g.mesh, in_specs=P("g"),
                         out_specs=P("g"))(v)

    return run(x)


def alltoall(x, group=None, sync_op=True):
    """Single-process: x (nranks, nranks, ...) — rank i holds row i of
    per-dest chunks → output rank i holds column i. Multi-process: x
    (nranks, ...) — row j is this rank's chunk for rank j → output
    (nranks, ...) — row j is rank j's chunk for this rank."""
    g = _get_group(group)
    if _multiprocess():
        _mp_world_only(g, "alltoall")
        gathered = _mp_utils().process_allgather(x)     # (nprocs, nranks, ...)
        return gathered[:, g.rank]
    if g.nranks == 1:
        return x
    return jnp.swapaxes(x, 0, 1)


all_to_all = alltoall


def send(x, dst=0, group=None, sync_op=True):
    g = _get_group(group)
    if _multiprocess():
        raise NotImplementedError(
            "eager send() has no multi-process implementation on TPU — "
            "point-to-point transfers belong inside jit (lax.ppermute / "
            "pipeline schedules); refusing to silently no-op")
    # Point-to-point outside jit is a device_put in single-process SPMD.
    return jax.device_put(x, g.devices[dst])


def recv(x, src=0, group=None, sync_op=True):
    if _multiprocess():
        raise NotImplementedError(
            "eager recv() has no multi-process implementation on TPU — "
            "point-to-point transfers belong inside jit (lax.ppermute / "
            "pipeline schedules); refusing to silently no-op")
    return x


def barrier(group=None):
    g = _get_group(group)
    if _multiprocess():
        _mp_utils().sync_global_devices("paddle_tpu.barrier")
        return
    jax.block_until_ready(jnp.zeros((), jnp.int32))


# ---- in-jit primitives (for strategy/shard_map code) ----------------------

psum = jax.lax.psum
pmean = jax.lax.pmean
pmax = jax.lax.pmax
ppermute = jax.lax.ppermute
axis_index = jax.lax.axis_index


def all_gather_in_jit(x, axis_name, axis=0, tiled=True):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def all_to_all_in_jit(x, axis_name, split_axis, concat_axis, tiled=True):
    return jax.lax.all_to_all(x, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=tiled)


def reduce_scatter_in_jit(x, axis_name, scatter_dimension=0, tiled=True):
    return jax.lax.psum_scatter(x, axis_name,
                                scatter_dimension=scatter_dimension, tiled=tiled)
