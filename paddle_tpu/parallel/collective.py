"""Collective communication veneer (≈ paddle.distributed.{all_reduce,...}).

Reference (SURVEY.md §2.5): ProcessGroup async collectives on dedicated NCCL
comm streams. TPU-native: collectives are XLA ops — inside jit/shard_map they
compile to ICI transfers scheduled by XLA's latency-hiding scheduler (no manual
streams). This module provides:

* `new_group(ranks)` → a `Group` wrapping a 1-D device mesh, the handle parity
  object for code ported from the reference.
* Eager functions (`all_reduce(x, group=...)`) for outside-jit use: each takes
  an array sharded (or shardable) over the group's axis, runs a tiny jitted
  shard_map collective, and returns the result. On a single device they are
  identities — matching the reference's degenerate world_size==1 behavior.
* In-jit primitives re-exported (`psum`, `ppermute`, ...) for strategy code.

API-design note: the reference returns waitable Tasks (`sync_op=False`); XLA's
async dispatch makes every call non-blocking already, so ops return arrays and
`.wait()` parity is a no-op wrapper.

Multi-process semantics: when `jax.process_count() > 1` (after
`init_parallel_env` / `jax.distributed.initialize`), the eager functions
switch from the single-process stacked-per-rank convention to true
cross-process collectives — each process passes its LOCAL value and
receives the collective result, matching the reference's ProcessGroup
semantics. The world group on 1-device processes rides
`multihost_utils.process_allgather`; subgroups, multi-device hosts, and
eager point-to-point `send`/`recv` ride the coordination-service KV
exchange (`_kv_put_get` — the TCPStore analog, control-plane sizes).
`src`/`dst` arguments are GLOBAL process ranks everywhere, like the
reference.
"""

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from paddle_tpu.core.enforce import enforce


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


def _multiprocess() -> bool:
    return jax.process_count() > 1


class Group:
    """A communicator: an ordered set of devices with a private 1-D mesh.

    Multi-process mode additionally carries PROCESS-group semantics:
    `process_ranks` is the ordered set of member process indices (eager
    collectives exchange one value per PROCESS), `pg_rank` is this
    process's position in it (-1 when not a member), `pg_size` the member
    count. Single-process SPMD keeps the stacked-per-rank device forms."""

    def __init__(self, devices: Sequence, name: str = "group",
                 process_ranks: Optional[Sequence[int]] = None):
        self.devices = list(devices)
        self.nranks = len(self.devices)
        self.name = name
        self.mesh = Mesh(np.asarray(self.devices), axis_names=("g",))
        if _multiprocess():
            self.process_ranks = (list(process_ranks)
                                  if process_ranks is not None
                                  else list(range(jax.process_count())))
            me = jax.process_index()
            self.pg_rank = (self.process_ranks.index(me)
                            if me in self.process_ranks else -1)
            self.pg_size = len(self.process_ranks)
            self.rank = jax.process_index()
        else:
            self.process_ranks = [0]
            self.pg_rank = 0
            self.pg_size = 1
            self.rank = 0

    @property
    def world_size(self):
        return self.nranks

    def is_member(self):
        return self.pg_rank >= 0 or not _multiprocess()

    def __repr__(self):
        return f"Group(nranks={self.nranks}, name={self.name!r})"


_default_group: List[Optional[Group]] = [None]


def _get_group(group: Optional[Group]) -> Group:
    if group is not None:
        return group
    if _default_group[0] is None:
        _default_group[0] = Group(jax.devices(), name="default")
    return _default_group[0]


_group_counter = [0]


def new_group(ranks=None, backend=None, name="group") -> Group:
    """Create a communicator. `ranks` are DEVICE indices in single-process
    SPMD and PROCESS indices in multi-process mode (reference ProcessGroup
    semantics — each process contributes one value)."""
    _group_counter[0] += 1
    uname = f"{name}#{_group_counter[0]}"
    devs = jax.devices()
    if _multiprocess():
        if ranks is None:
            ranks = list(range(jax.process_count()))
        gdevs = [d for d in devs if d.process_index in set(ranks)]
        return Group(gdevs, name=uname, process_ranks=ranks)
    if ranks is None:
        ranks = list(range(len(devs)))
    return Group([devs[r] for r in ranks], name=uname)


def _sharded_over_group(x, g: Group):
    return jax.device_put(x, NamedSharding(g.mesh, P("g")))


def _reduce_fn(op):
    return {
        ReduceOp.SUM: jax.lax.psum,
        ReduceOp.MAX: jax.lax.pmax,
        ReduceOp.MIN: jax.lax.pmin,
        ReduceOp.AVG: lambda v, ax: jax.lax.pmean(v, ax),
    }[op]


# ---- eager veneers ---------------------------------------------------------
# Single-process: each operates on an array whose leading axis is the group
# dimension (one slice per rank — the single-process analog of per-rank
# tensors). Multi-process: each process passes its LOCAL value; the op is a
# true cross-process collective (multihost_utils over the distributed
# runtime — ProcessGroup semantics, SURVEY.md §2.5).

def _mp_utils():
    from jax.experimental import multihost_utils
    return multihost_utils


def _is_world(g: Group) -> bool:
    return g.process_ranks == list(range(jax.process_count()))


def _fast_world_path(g: Group) -> bool:
    """multihost_utils.process_allgather is the fast path, but it is a
    WORLD collective over one-device processes; subgroups and multi-device
    hosts ride the coordination-service KV exchange instead."""
    return _is_world(g) and jax.local_device_count() == 1


def _member_only(g: Group, opname: str):
    if not g.is_member():
        raise RuntimeError(
            f"{opname}: process {jax.process_index()} is not a member of "
            f"group {g.name!r} (ranks {g.process_ranks}) — only member "
            "processes may enter a collective")


# ---- coordination-service exchange (subgroups / multi-device hosts / p2p)
# The jax.distributed coordination service doubles as the reference's
# TCPStore: small-tensor eager exchange for setup/debug flows. The data
# plane (training collectives) stays in-jit over ICI — these veneers are
# the ported-user-code story, not the fast path. Keys are sequence-
# numbered per tag; members must call in the same order (standard
# ProcessGroup contract). Values transit base64-encoded npy bytes.

_kv_seq: dict = {}

# transient coordination-service hiccups (RPC reset, brief leader loss)
# retry under resilience.kv_op's shared default-bounded policy (which
# also carries the injectable kv.op fault site); DEADLINE_EXCEEDED on a
# blocking get is NOT transient — it means the peer never posted (in-order
# contract violation / dead peer) and extending it 3x only hides that


def _kv_retry(describe, fn, retry_if=None):
    from paddle_tpu.resilience import kv_op
    return kv_op(describe, fn, retry_if=retry_if)


def _kv_client():
    from jax._src import distributed
    client = distributed.global_state.client
    enforce(client is not None, "jax.distributed is not initialized")
    return client


def _kv_put_get(tag: str, payload, me, peers, timeout_ms=60_000,
                consume=False, gc=False):
    """Post `payload` (np array) as rank `me` (skipped when payload is
    None — pure receive), fetch each rank in `peers`.

    Garbage collection (`gc=True` — ONLY valid for allgather-style calls
    where every member fetches from every member): entering sequence s
    then proves every member finished call s-1, hence consumed the s-2
    keys — each rank deletes its OWN s-2 key. One-way ops (send/
    broadcast/scatter) must NOT gc (a slow reader may not have consumed
    old keys); p2p recv passes `consume=True` instead (single reader
    deletes the key after reading)."""
    import base64
    import io

    client = _kv_client()
    seq = _kv_seq.get(tag, 0)
    _kv_seq[tag] = seq + 1
    if payload is not None:
        buf = io.BytesIO()
        np.save(buf, np.asarray(payload), allow_pickle=False)
        val = base64.b64encode(buf.getvalue()).decode("ascii")
        # allow_overwrite: a retried set must be idempotent — the value
        # may have committed server-side with only the RPC reply lost,
        # and an already-exists rejection would burn the whole retry
        # budget on a guaranteed failure
        _kv_retry("collective.kv_set",
                  lambda: client.key_value_set(f"ptkv/{tag}/{seq}/{me}",
                                               val, allow_overwrite=True))
        # allgather-style tags (gc=True) prove consumption 2 generations
        # back and GC safely. One-way tags (broadcast/scatter/send) have
        # NO consumption evidence — a fire-and-forget sender may be
        # arbitrarily far ahead of a legal in-order reader — so their
        # keys are left in place: one entry per call leaks in the
        # coordination service. Documented limitation; these veneers are
        # control-plane (setup/debug), not per-step data plane.
        if gc and seq >= 2:
            try:
                client.key_value_delete(f"ptkv/{tag}/{seq - 2}/{me}")
            except Exception:
                pass
    out = {}
    from paddle_tpu.resilience import is_timeout
    for r in peers:
        key = f"ptkv/{tag}/{seq}/{r}"
        raw = _kv_retry(
            "collective.kv_get",
            lambda key=key: client.blocking_key_value_get(key, timeout_ms),
            retry_if=lambda e: not is_timeout(e))
        out[r] = np.load(io.BytesIO(base64.b64decode(raw)),
                         allow_pickle=False)
        if consume:
            try:
                client.key_value_delete(key)
            except Exception:
                pass
    return out


def _kv_allgather(g: Group, x, opname: str):
    """(pg_size, ...) stack of every member process's value."""
    _member_only(g, opname)
    vals = _kv_put_get(f"{g.name}/{opname}", x, g.pg_rank,
                       range(g.pg_size), gc=True)
    return jnp.asarray(np.stack([vals[r] for r in range(g.pg_size)]))


_MP_REDUCERS = {
    ReduceOp.SUM: jnp.sum,
    ReduceOp.MAX: jnp.max,
    ReduceOp.MIN: jnp.min,
    ReduceOp.PROD: jnp.prod,
    ReduceOp.AVG: jnp.mean,
}


def all_reduce(x, op=ReduceOp.SUM, group=None, sync_op=True):
    """Single-process: x is (nranks, ...) stacked per-rank values → same
    shape, reduced copies. Multi-process: x is this process's value →
    the cross-process reduction."""
    g = _get_group(group)
    if _multiprocess():
        if _fast_world_path(g):
            gathered = _mp_utils().process_allgather(x)  # (nprocs, ...)
        else:
            gathered = _kv_allgather(g, x, "all_reduce")
        return _MP_REDUCERS[op](gathered, axis=0).astype(x.dtype)
    if g.nranks == 1:
        return x
    enforce(x.shape[0] == g.nranks, f"leading dim {x.shape[0]} != nranks {g.nranks}")
    x = _sharded_over_group(x, g)
    fn = _reduce_fn(op)

    @jax.jit
    def run(v):
        def body(s):
            r = fn(s, "g")
            return r
        return shard_map(body, mesh=g.mesh, in_specs=P("g"),
                         out_specs=P("g"))(v)

    return run(x)


def all_gather(tensor_list_or_x, x=None, group=None, sync_op=True, axis=0):
    """Single-process: per-rank slices are already globally visible.
    Multi-process: gathers each process's local value into a (nranks, ...)
    stack (the reference returns a list; pass a list as the first arg to get
    that form)."""
    if isinstance(tensor_list_or_x, list):
        out_list, x = tensor_list_or_x, x
    else:
        out_list, x = None, tensor_list_or_x
    g = _get_group(group)
    if _multiprocess():
        if _fast_world_path(g):
            res = _mp_utils().process_allgather(x)      # (nprocs, ...)
        else:
            res = _kv_allgather(g, x, "all_gather")
    else:
        res = x  # already globally visible in single-process SPMD
    if out_list is not None:
        for i in range(res.shape[0]):   # rows = processes (multi-process)
            out_list.append(res[i])     # or device ranks (single-process)
        return out_list
    return res


def reduce(x, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    """Reduce to `dst`. Implemented as all_reduce: every rank receives the
    reduced value, a strict superset of the reference contract (which
    defines the result only at `dst`). `dst` is accepted for API parity;
    there is no bandwidth saving on TPU — XLA's all-reduce over ICI is the
    primitive a rooted reduce would lower to anyway."""
    return all_reduce(x, op=op, group=group)


def broadcast(x, src=0, group=None, sync_op=True):
    g = _get_group(group)
    if _multiprocess():
        if _fast_world_path(g):
            return _mp_utils().broadcast_one_to_all(
                x, is_source=jax.process_index() == src)
        _member_only(g, "broadcast")
        src_pg = g.process_ranks.index(src)   # src is a GLOBAL rank
        vals = _kv_put_get(f"{g.name}/broadcast",
                           x if g.pg_rank == src_pg else None,
                           g.pg_rank, [src_pg])
        return jnp.asarray(vals[src_pg])
    if g.nranks == 1:
        return x
    src_slice = x[src]
    return jnp.broadcast_to(src_slice[None], x.shape)


def scatter(x, tensor_list=None, src=0, group=None, sync_op=True):
    """Scatter `tensor_list` from `src`, one chunk per rank.

    Multi-process note: implemented as a full broadcast of the stacked
    list followed by a local slice — O(world) data per rank for an
    O(1/world) result. Fine at the tensor sizes eager scatter is used for
    (setup/debug); inside jit, GSPMD sharding is the fast path."""
    g = _get_group(group)
    if _multiprocess():
        _member_only(g, "scatter")
        src_pg = g.process_ranks.index(src)   # src is a GLOBAL rank
        stacked = (np.stack([np.asarray(t) for t in tensor_list])
                   if g.pg_rank == src_pg else None)
        vals = _kv_put_get(f"{g.name}/scatter", stacked, g.pg_rank,
                           [src_pg])
        return jnp.asarray(vals[src_pg][g.pg_rank])
    if tensor_list is not None:
        return jnp.stack(tensor_list)[g.rank] if g.nranks > 1 else tensor_list[0]
    return x


def reduce_scatter(x, op=ReduceOp.SUM, group=None, sync_op=True):
    """Single-process: x (nranks, nranks*chunk, ...) per-rank values →
    (nranks, chunk, ...). Multi-process: x (nranks*chunk, ...) local value →
    this rank's reduced (chunk, ...) slice."""
    g = _get_group(group)
    if _multiprocess():
        if _fast_world_path(g):
            gathered = _mp_utils().process_allgather(x)
        else:
            gathered = _kv_allgather(g, x, "reduce_scatter")
        reduced = _MP_REDUCERS[op](gathered, axis=0).astype(x.dtype)
        chunk = reduced.shape[0] // g.pg_size
        return reduced[g.pg_rank * chunk:(g.pg_rank + 1) * chunk]
    if g.nranks == 1:
        return x
    x = _sharded_over_group(x, g)
    fn = _reduce_fn(op)

    @jax.jit
    def run(v):
        def body(s):
            r = fn(s, "g")  # (1, n*chunk, ...)
            i = jax.lax.axis_index("g")
            chunk = r.shape[1] // g.nranks
            return jax.lax.dynamic_slice_in_dim(r, i * chunk, chunk, axis=1)
        return shard_map(body, mesh=g.mesh, in_specs=P("g"),
                         out_specs=P("g"))(v)

    return run(x)


def alltoall(x, group=None, sync_op=True):
    """Single-process: x (nranks, nranks, ...) — rank i holds row i of
    per-dest chunks → output rank i holds column i. Multi-process: x
    (nranks, ...) — row j is this rank's chunk for rank j → output
    (nranks, ...) — row j is rank j's chunk for this rank."""
    g = _get_group(group)
    if _multiprocess():
        # per-PROCESS semantics: x carries pg_size rows, one chunk per
        # member process (multi-device hosts exchange per process, not
        # per device — in-jit shard_map alltoall is the per-device path)
        enforce(x.shape[0] == g.pg_size,
                f"alltoall: leading dim {x.shape[0]} != process-group "
                f"size {g.pg_size} (eager alltoall is per-process)")
        if _fast_world_path(g):
            gathered = _mp_utils().process_allgather(x)  # (np, nranks, ...)
        else:
            gathered = _kv_allgather(g, x, "alltoall")
        return gathered[:, g.pg_rank]
    if g.nranks == 1:
        return x
    return jnp.swapaxes(x, 0, 1)


all_to_all = alltoall


def send(x, dst=0, group=None, sync_op=True):
    """Eager point-to-point. Multi-process: the payload rides the
    coordination-service KV store (control-plane sizes; in-jit
    lax.ppermute / the pipeline schedules are the data plane)."""
    g = _get_group(group)
    if _multiprocess():
        _member_only(g, "send")
        me = jax.process_index()              # GLOBAL ranks in p2p tags
        _kv_put_get(f"{g.name}/p2p/{me}->{dst}", x, me, [])
        return x
    # Point-to-point outside jit is a device_put in single-process SPMD.
    return jax.device_put(x, g.devices[dst])


def recv(x, src=0, group=None, sync_op=True):
    """Eager point-to-point receive (see send)."""
    g = _get_group(group)
    if _multiprocess():
        _member_only(g, "recv")
        me = jax.process_index()
        vals = _kv_put_get(f"{g.name}/p2p/{src}->{me}", None, None,
                           [src], consume=True)
        return jnp.asarray(vals[src]).astype(x.dtype).reshape(x.shape)
    return x


def barrier(group=None):
    g = _get_group(group)
    if _multiprocess():
        if _is_world(g):
            _mp_utils().sync_global_devices("paddle_tpu.barrier")
        else:
            _kv_allgather(g, np.zeros((), np.int8), "barrier")
        return
    jax.block_until_ready(jnp.zeros((), jnp.int32))


# ---- in-jit primitives (for strategy/shard_map code) ----------------------

psum = jax.lax.psum
pmean = jax.lax.pmean
pmax = jax.lax.pmax
ppermute = jax.lax.ppermute
axis_index = jax.lax.axis_index


def all_gather_in_jit(x, axis_name, axis=0, tiled=True):
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def all_to_all_in_jit(x, axis_name, split_axis, concat_axis, tiled=True):
    return jax.lax.all_to_all(x, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=tiled)


def reduce_scatter_in_jit(x, axis_name, scatter_dimension=0, tiled=True):
    return jax.lax.psum_scatter(x, axis_name,
                                scatter_dimension=scatter_dimension, tiled=tiled)
