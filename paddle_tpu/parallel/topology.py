"""Hybrid-parallel topology: axes → named device mesh.

Reference (SURVEY.md §2.6): `CommunicateTopology`/`HybridCommunicateGroup`
(python/paddle/distributed/fleet/base/topology.py) build the dp×pp×sharding×
mp(×sep) rank grid and create one NCCL ProcessGroup per axis.

TPU-native: the grid IS a `jax.sharding.Mesh` with named axes; "groups" are
mesh axes, and collectives ride ICI because the mesh is laid out over the
physical torus by `mesh_utils.create_device_mesh`. One mesh, all axes — GSPMD
inserts the per-axis collectives the reference issues by hand.

Axis order follows the reference ("dp", "pp", "sharding", "sep", "mp"):
outer axes get DCN-ish placement, inner axes (mp/sep) stay on the
fastest ICI links — same intent as Paddle putting mp innermost on NVLink.
An optional "ep" (expert) axis is carved out of dp×sharding for MoE.
"""

import collections
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_ORDER = ("dp", "pp", "sharding", "sep", "fsdp", "mp")

#: The sanctioned mesh-axis names, mapped to the degree the multichip
#: dryrun validates (MULTICHIP_r0x leg(16): {dp: 2, pp: 2, sharding: 2,
#: mp: 2} with loss invariance) — None for axes with no pinned degree
#: (`sep` runs degree 1 in the dryrun, `ep` is carved out of
#: dp×sharding per deployment, `g` is the eager collective veneer's
#: private 1-D group axis). This registry is what the `collective-axis`
#: and `pspec-axis` lint rules (paddle_tpu/analysis/rules.py,
#: docs/ANALYSIS.md) pin axis-name literals against: a typo'd or
#: unregistered axis is a lint finding at author time instead of a
#: trace error on a v5p mesh. The degrees feed the pspec-axis
#: sharded-dim divisibility check where tensor sizes are statically
#: known. Registering a new axis here is the one-line gate for
#: introducing it anywhere in the package.
KNOWN_AXES = {
    "dp": 2,
    "pp": 2,
    "sharding": 2,
    "sep": None,
    # fsdp: the serving engine's weight-sharding axis (ServingLayout
    # splits stacked per-layer weights on L over it; mp stays the
    # head/ffn axis). No pinned degree — the serving parity matrix runs
    # it at 1 on CPU and deployments pick L-divisible degrees.
    "fsdp": None,
    "mp": 2,
    "ep": None,
    "g": None,
}


def build_mesh(axis_dims: Dict[str, int], devices=None) -> Mesh:
    """Build a named Mesh from {axis: degree}; degrees must multiply to #devices
    (axes with degree 1 are kept so sharding specs can always name them)."""
    devices = list(devices if devices is not None else jax.devices())
    names = [a for a in AXIS_ORDER if a in axis_dims]
    extra = [a for a in axis_dims if a not in AXIS_ORDER]
    names += extra
    dims = [int(axis_dims[a]) for a in names]
    total = int(np.prod(dims)) if dims else 1
    if total != len(devices):
        raise ValueError(
            f"mesh dims {dict(zip(names, dims))} multiply to {total}, "
            f"but {len(devices)} devices are available")
    try:
        from jax.experimental import mesh_utils
        dev_array = mesh_utils.create_device_mesh(dims, devices=devices)
    except Exception:
        dev_array = np.asarray(devices).reshape(dims)
    return Mesh(dev_array, axis_names=tuple(names))


class CommunicateTopology:
    """Rank-grid arithmetic (reference parity: fleet/base/topology.py)."""

    def __init__(self, hybrid_group_names: Sequence[str], dims: Sequence[int]):
        self._names = list(hybrid_group_names)
        self._dims = [int(d) for d in dims]
        self._shape = tuple(self._dims)
        self._world = int(np.prod(self._dims)) if self._dims else 1

    def get_hybrid_group_names(self):
        return list(self._names)

    def get_dim(self, axis_name):
        return self._dims[self._names.index(axis_name)]

    def world_size(self):
        return self._world

    def get_rank(self, **kw):
        coord = [kw[n] for n in self._names]
        return int(np.ravel_multi_index(coord, self._shape))

    def get_coord(self, rank):
        return tuple(int(c) for c in np.unravel_index(rank, self._shape))

    def get_axis_list(self, axis_name, index):
        """All ranks whose coord on `axis_name` equals `index`."""
        ax = self._names.index(axis_name)
        out = []
        for r in range(self._world):
            if self.get_coord(r)[ax] == index:
                out.append(r)
        return out

    def get_comm_list(self, axis_name):
        """List of rank-groups that communicate along `axis_name`."""
        ax = self._names.index(axis_name)
        groups = collections.defaultdict(list)
        for r in range(self._world):
            coord = list(self.get_coord(r))
            coord[ax] = -1
            groups[tuple(coord)].append(r)
        return [sorted(v) for _, v in sorted(groups.items())]


class HybridCommunicateGroup:
    """Builds the global mesh and exposes per-axis degree/rank queries.

    In the reference each axis materializes a ProcessGroupNCCL; here the mesh
    axis name is the group handle — pass `hcg.mesh` + axis names into
    shardings/shard_map and XLA emits the collectives.
    """

    def __init__(self, topology: Optional[CommunicateTopology] = None,
                 strategy=None, devices=None):
        if topology is None:
            cfg = (strategy.hybrid_configs if strategy is not None else {})
            n_dev = len(devices) if devices is not None else jax.device_count()
            dp = cfg.get("dp_degree", 1)
            mp = cfg.get("mp_degree", 1)
            pp = cfg.get("pp_degree", 1)
            sh = cfg.get("sharding_degree", 1)
            sep = cfg.get("sep_degree", 1)
            known = mp * pp * sh * sep
            if dp in (0, -1, None):
                dp = n_dev // known
            topology = CommunicateTopology(
                ["dp", "pp", "sharding", "sep", "mp"], [dp, pp, sh, sep, mp])
        self._topo = topology
        dims = {n: topology.get_dim(n) for n in topology.get_hybrid_group_names()}
        self.mesh = build_mesh(dims, devices=devices)
        self.global_rank = jax.process_index()

    # -- reference accessors -------------------------------------------------

    @property
    def topology(self):
        return self._topo

    def _dim(self, name):
        try:
            return self._topo.get_dim(name)
        except ValueError:
            return 1

    def get_parallel_mode(self):
        if self._dim("pp") > 1:
            return "pipeline"
        if self._dim("sharding") > 1:
            return "sharding"
        if self._dim("mp") > 1:
            return "tensor"
        return "data"

    def get_data_parallel_world_size(self):
        return self._dim("dp")

    def get_model_parallel_world_size(self):
        return self._dim("mp")

    def get_pipe_parallel_world_size(self):
        return self._dim("pp")

    def get_sharding_parallel_world_size(self):
        return self._dim("sharding")

    def get_sep_parallel_world_size(self):
        return self._dim("sep")

    # ranks are meaningful per-process in multi-host; single-process SPMD
    # places all coords in one program, so these report the process's coord.
    def _coord(self):
        return self._topo.get_coord(self.global_rank % self._topo.world_size())

    def get_data_parallel_rank(self):
        return self._coord()[self._topo.get_hybrid_group_names().index("dp")]

    def get_model_parallel_rank(self):
        return self._coord()[self._topo.get_hybrid_group_names().index("mp")]

    def get_stage_id(self):
        return self._coord()[self._topo.get_hybrid_group_names().index("pp")]

    def get_sharding_parallel_rank(self):
        return self._coord()[self._topo.get_hybrid_group_names().index("sharding")]

    # -- mesh views ----------------------------------------------------------

    def axis_size(self, name):
        return self._dim(name)

    def dp_axis(self):
        return "dp"

    def mp_axis(self):
        return "mp"

    def pp_axis(self):
        return "pp"

    def sharding_axis(self):
        return "sharding"


_HCG: List[Optional[HybridCommunicateGroup]] = [None]


def set_hybrid_communicate_group(hcg: HybridCommunicateGroup):
    _HCG[0] = hcg


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _HCG[0]
