"""Distributed/parallelism package — exposed as both `paddle_tpu.parallel` and
`paddle_tpu.distributed` (reference namespace).

SURVEY.md §2.5/§2.6: replaces ProcessGroupNCCL + Fleet with a named JAX mesh
over ICI/DCN, GSPMD shardings, and explicit collective veneers.
"""

from paddle_tpu.parallel.env import (  # noqa: F401
    init_parallel_env,
    get_rank,
    get_world_size,
    is_initialized,
    ParallelEnv,
)
from paddle_tpu.parallel.collective import (  # noqa: F401
    all_reduce,
    all_gather,
    reduce,
    broadcast,
    scatter,
    reduce_scatter,
    alltoall,
    all_to_all,
    send,
    recv,
    barrier,
    new_group,
    ReduceOp,
)
from paddle_tpu.parallel.topology import (  # noqa: F401
    CommunicateTopology,
    HybridCommunicateGroup,
    get_hybrid_communicate_group,
    build_mesh,
)
from paddle_tpu.parallel.strategy import DistributedStrategy  # noqa: F401
from paddle_tpu.parallel.data_parallel import DataParallel  # noqa: F401
from paddle_tpu.parallel import fleet  # noqa: F401
from paddle_tpu.parallel import env  # noqa: F401
from paddle_tpu.parallel import sharding  # noqa: F401
from paddle_tpu.parallel import auto_parallel as auto  # noqa: F401
from paddle_tpu.parallel.auto_parallel import (  # noqa: F401
    ProcessMesh,
    shard_tensor,
    Shard,
    Replicate,
    Partial,
)
from paddle_tpu.parallel.launch import spawn  # noqa: F401
from paddle_tpu.parallel import mp_layers  # noqa: F401
from paddle_tpu.parallel import context_parallel  # noqa: F401
from paddle_tpu.parallel import checkpoint  # noqa: F401
from paddle_tpu.parallel.checkpoint import (  # noqa: F401
    save_state_dict,
    load_state_dict,
    CheckpointManager,
)
from paddle_tpu.parallel.elastic import ElasticTrainLoop  # noqa: F401
from paddle_tpu.parallel.context_parallel import (  # noqa: F401
    context_parallel_attention,
    ring_attention_local,
    ulysses_attention_local,
)
from paddle_tpu.parallel.mp_layers import (  # noqa: F401
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
    ParallelCrossEntropy,
    split_layer as split,
)
