"""Fleet — hybrid-parallel orchestration (≈ paddle.distributed.fleet).

Reference call stack (SURVEY.md §3.2): fleet.init(strategy) builds
HybridCommunicateGroup + per-axis NCCL groups; fleet.distributed_model wraps
the model per active degrees (TensorParallel/PipelineParallel/DataParallel/
GroupSharded); fleet.distributed_optimizer wraps the optimizer.

TPU-native: `init` builds ONE named mesh; `distributed_model` records axes
(parameters already carry TP placements from the mp layers);
`make_train_step` compiles the whole step — forward, backward, clip, update —
into one jitted SPMD program whose in/out shardings encode DP, ZeRO stage
1/2/3, TP and SP simultaneously. XLA inserts and overlaps every collective
the reference hand-schedules in HybridParallelOptimizer/reducer/sharding hooks.
"""

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from paddle_tpu.nn.layer import Layer, functional_call
from paddle_tpu.parallel import sharding as sharding_mod
from paddle_tpu.parallel.strategy import DistributedStrategy
from paddle_tpu.parallel.topology import (
    HybridCommunicateGroup,
    set_hybrid_communicate_group,
    get_hybrid_communicate_group,
)
from paddle_tpu.parallel.data_parallel import DataParallel


class Fleet:
    def __init__(self):
        self._strategy: Optional[DistributedStrategy] = None
        self._hcg: Optional[HybridCommunicateGroup] = None
        self._is_initialized = False

    def init(self, role_maker=None, is_collective=True, strategy=None,
             devices=None):
        self._strategy = strategy or DistributedStrategy()
        self._hcg = HybridCommunicateGroup(strategy=self._strategy,
                                           devices=devices)
        set_hybrid_communicate_group(self._hcg)
        self._is_initialized = True
        # per-rank metric tagging: every metric created after fleet.init
        # carries this host's rank label, so per-rank writers under
        # parallel/launch.py emit distinguishable series into shared
        # JSONL/Prometheus sinks
        import os
        from paddle_tpu.observability.registry import set_default_labels
        set_default_labels(rank=os.environ.get("PADDLE_TRAINER_ID", "0"))
        return self

    @property
    def strategy(self):
        return self._strategy

    def get_hybrid_communicate_group(self):
        return self._hcg

    @property
    def mesh(self):
        return self._hcg.mesh if self._hcg else None

    def distributed_model(self, model: Layer):
        assert self._is_initialized, "call fleet.init first"
        hcg = self._hcg
        if hcg.get_pipe_parallel_world_size() > 1:
            from paddle_tpu.parallel.pipeline import PipelineParallel
            if not isinstance(model, PipelineParallel):
                model = PipelineParallel(model, hcg, self._strategy)
        elif hcg.get_data_parallel_world_size() > 1 and not isinstance(model, DataParallel):
            model = DataParallel(model)
        return model

    def distributed_optimizer(self, optimizer, strategy=None):
        return HybridParallelOptimizer(optimizer, self._hcg,
                                       strategy or self._strategy)

    # -- state placement -----------------------------------------------------

    def param_specs(self, model: Layer) -> Dict[str, P]:
        """Final parameter PartitionSpecs: TP placements from the layers,
        composed with ZeRO stage-3 sharding if enabled."""
        hcg, strat = self._hcg, self._strategy
        base = {}
        for name, p in model.named_parameters():
            base[name] = getattr(p, "pspec", None) or P()
        stage = strat.sharding_configs.stage if strat.sharding else 0
        degree = hcg.get_sharding_parallel_world_size()
        params = {n: p.value for n, p in model.named_parameters()}
        return sharding_mod.shard_params_spec(params, stage, degree,
                                              base_specs=base)

    def shard_model_state(self, model: Layer):
        """Place the model's trainable state onto the mesh per strategy."""
        specs = self.param_specs(model)
        state = model.trainable_state()
        placed = {k: jax.device_put(v, NamedSharding(self.mesh, specs[k]))
                  for k, v in state.items()}
        return placed, specs


class HybridParallelOptimizer:
    """API-shape veneer over the inner optimizer — it intentionally adds NO
    behavior. The reference class (meta_optimizers/dygraph_optimizer/
    hybrid_parallel_optimizer.py) exists to hand-fuse the grad-clip
    global-norm allreduces across dp/mp/pp/sharding groups; under GSPMD the
    clip in the inner optimizer already computes the global norm in one XLA
    reduction over the whole mesh, so there is nothing left to fuse. The
    class survives only so `fleet.distributed_optimizer(opt)` returns the
    reference's type shape."""

    def __init__(self, inner, hcg, strategy):
        self._inner = inner
        self._hcg = hcg
        self._strategy = strategy

    def __getattr__(self, name):
        return getattr(self._inner, name)

    @property
    def inner_opt(self):
        return self._inner


_fleet = Fleet()


def init(role_maker=None, is_collective=True, strategy=None, devices=None):
    return _fleet.init(role_maker, is_collective, strategy, devices)


def distributed_model(model):
    return _fleet.distributed_model(model)


def distributed_optimizer(optimizer, strategy=None):
    return _fleet.distributed_optimizer(optimizer, strategy)


def get_fleet() -> Fleet:
    return _fleet


def get_hybrid_communicate_group_():
    return _fleet.get_hybrid_communicate_group()


# ---- the compiled hybrid train step ---------------------------------------

def abstract_train_state(state0, pspecs, ospecs, optimizer, mesh,
                         scaler=None):
    """(abstract_state, abstract_opt) ShapeDtypeStructs with shardings —
    the shared AOT-lowering substrate of this module's and the pipeline
    engine's `step_fn.lower` hooks (one copy: an opt-state layout change
    must not silently diverge the two feasibility reports)."""
    abstract_state = {k: jax.ShapeDtypeStruct(
        v.shape, v.dtype, sharding=NamedSharding(mesh, pspecs[k]))
        for k, v in state0.items()}
    abstract_opt = jax.eval_shape(optimizer.init_state, abstract_state)
    if scaler is not None:
        abstract_opt["scaler"] = jax.eval_shape(scaler.init_state)

    def shard_slot(tree):
        if isinstance(tree, dict):
            return {k: jax.ShapeDtypeStruct(
                v.shape, v.dtype,
                sharding=NamedSharding(mesh, ospecs.get(k, P())))
                for k, v in tree.items()}
        return tree
    return abstract_state, {slot: shard_slot(t)
                            for slot, t in abstract_opt.items()}

def make_train_step(model: Layer, optimizer, loss_fn: Callable,
                    strategy: Optional[DistributedStrategy] = None,
                    hcg: Optional[HybridCommunicateGroup] = None,
                    batch_axes=("dp", "sharding"),
                    donate: bool = True,
                    rng_streams=("dropout",)):
    """Build `(state, opt_state, batch, step) -> (state, opt_state, loss)` —
    one jitted SPMD program implementing the active parallelism strategy.

    * batch leading dim sharded over `batch_axes` (DP; the sharding axis also
      consumes batch — ZeRO semantics).
    * params/opt state sharded per strategy (stage 1/2/3 + TP placements).
    * loss_fn(outputs, batch) -> scalar loss.

    Returns (step_fn, init_fn): init_fn() places model + optimizer state.
    """
    strategy = strategy or _fleet.strategy or DistributedStrategy()
    hcg = hcg or _fleet.get_hybrid_communicate_group() or get_hybrid_communicate_group()
    if isinstance(model, DataParallel):
        model = model.inner_layer
    from paddle_tpu.parallel.pipeline import PipelineParallel
    if isinstance(model, PipelineParallel):
        model = model.inner_layer
    if hcg.get_pipe_parallel_world_size() > 1:
        if not hasattr(model, "pipeline_parts"):
            raise ValueError(
                f"pp_degree>1 but {type(model).__name__} does not implement "
                "pipeline_parts(); see parallel.pipeline.PipelineParts")
        if loss_fn is not None:
            raise ValueError(
                "pp_degree>1 computes the loss in the model's pipeline head "
                "(PipelineParts.head_apply); pass loss_fn=None")
        from paddle_tpu.parallel.pipeline import make_pipeline_train_step
        return make_pipeline_train_step(model, optimizer, strategy=strategy,
                                        hcg=hcg, donate=donate)
    mesh = hcg.mesh
    stage = strategy.sharding_configs.stage if strategy.sharding else 0
    degree = hcg.get_sharding_parallel_world_size()

    state0 = model.trainable_state()
    # LazyGuard (meta-init) models: shapes only — the AOT lower() path
    # works, init_fn raises loudly (mirrors the pipeline engine's guard)
    abstract = any(isinstance(v, jax.ShapeDtypeStruct)
                   for v in state0.values())

    # ---- AMP (strategy.amp, O2): params in low precision, fp32 masters in
    # the optimizer (multi_precision), dynamic loss scaling for fp16 ----
    amp_dt = None
    scaler = None
    if strategy.amp and strategy.amp_configs.level.upper() == "O2":
        from paddle_tpu.core.dtype import to_jax_dtype, is_floating
        amp_dt = to_jax_dtype(strategy.amp_configs.dtype)
        cast = (lambda v: jax.ShapeDtypeStruct(v.shape, amp_dt)) if abstract \
            else (lambda v: v.astype(amp_dt))
        state0 = {k: (cast(v) if is_floating(v.dtype) else v)
                  for k, v in state0.items()}
        if amp_dt == jnp.float16 and strategy.amp_configs.use_dynamic_loss_scaling:
            from paddle_tpu.amp import GradScaler
            scaler = GradScaler(
                init_loss_scaling=strategy.amp_configs.init_loss_scaling)

    base = {name: (getattr(p, "pspec", None) or P())
            for name, p in model.named_parameters() if p.trainable}
    pspecs = sharding_mod.shard_params_spec(state0, stage, degree,
                                            base_specs=base)
    ospecs = sharding_mod.opt_state_specs(pspecs, stage, degree, state0)
    gspecs = sharding_mod.grad_specs(pspecs, stage, degree, state0)

    active_batch_axes = tuple(a for a in batch_axes if hcg.axis_size(a) > 1)
    bspec = P(active_batch_axes if active_batch_axes else None)

    param_sh = {k: NamedSharding(mesh, s) for k, s in pspecs.items()}

    def opt_state_shardings(opt_state):
        def spec_for(path_key, leaf):
            return NamedSharding(mesh, ospecs.get(path_key, P()))
        sh = {}
        for slot, tree in opt_state.items():
            if isinstance(tree, dict):
                sh[slot] = {k: spec_for(k, v) for k, v in tree.items()}
            else:
                sh[slot] = NamedSharding(mesh, P())
        return sh

    remat_policy = None
    if strategy.recompute:
        from jax.ad_checkpoint import checkpoint_policies as cp
        remat_policy = {
            "full": cp.nothing_saveable,
            "nothing_saveable": cp.nothing_saveable,
            "dots_saveable": cp.dots_saveable,
            # reference recompute_granularity values — the models name
            # their matmul outputs (attn_qkv/ffn_gate/ffn_up); attn_out
            # is not saved (the flash bwd replays its fwd regardless)
            "full_attn": cp.save_only_these_names("ffn_gate", "ffn_up"),
            "core_attn": cp.save_only_these_names(
                "attn_qkv", "ffn_gate", "ffn_up"),
        }.get(strategy.recompute_configs.policy, cp.nothing_saveable)

    def forward_loss(state, batch, rngs):
        def fwd(s, b):
            out = functional_call(model, s, b["input"] if isinstance(b, dict)
                                  and "input" in b else b, rngs=rngs)
            return loss_fn(out, b)
        if remat_policy is not None:
            fwd = jax.checkpoint(fwd, policy=remat_policy)
        return fwd(state, batch)

    merge_k = (int(strategy.gradient_merge_configs.get("k_steps", 1))
               if strategy.gradient_merge else 1)

    def _value_and_grad(state, batch, rngs, scale=None):
        """Plain or gradient-merge (k-microbatch accumulated) grads."""
        def scalar_loss(s, b, r):
            l = forward_loss(s, b, r)
            return l * scale if scale is not None else l

        if merge_k <= 1:
            return jax.value_and_grad(
                lambda s: scalar_loss(s, batch, rngs))(state)

        def split(x):
            if not hasattr(x, "ndim") or x.ndim == 0:
                # scalar leaves replicate so the scan can unstack them
                return jnp.broadcast_to(jnp.asarray(x), (merge_k,))
            if x.shape[0] % merge_k:
                raise ValueError(
                    f"gradient_merge k_steps={merge_k} does not divide "
                    f"batch dim {x.shape[0]}")
            return x.reshape((merge_k, x.shape[0] // merge_k) + x.shape[1:])
        micro = jax.tree_util.tree_map(split, batch)

        def body(acc, xs):
            mb, i = xs
            loss_acc, g_acc = acc
            # independent randomness per microbatch (≈ k separate steps)
            rngs_i = {name: jax.random.fold_in(k, i)
                      for name, k in (rngs or {}).items()}
            loss, g = jax.value_and_grad(
                lambda s: scalar_loss(s, mb, rngs_i))(state)
            return (loss_acc + loss,
                    jax.tree_util.tree_map(jnp.add, g_acc, g)), None

        zero_g = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, jnp.float32), state)
        (loss_sum, g_sum), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), zero_g),
            (micro, jnp.arange(merge_k)))
        inv = 1.0 / merge_k
        return (loss_sum * inv,
                jax.tree_util.tree_map(lambda g: g * inv, g_sum))

    def _step(state, opt_state, batch, rngs):
        if scaler is not None:
            sstate = opt_state["scaler"]
            loss_s, grads = _value_and_grad(state, batch, rngs,
                                            scale=sstate["scale"])
            loss = loss_s / sstate["scale"]
            grads, found_inf = scaler.unscale(grads, sstate)
        else:
            loss, grads = _value_and_grad(state, batch, rngs)
        # constrain grads per stage-2 semantics; GSPMD propagates the rest
        grads = {k: jax.lax.with_sharding_constraint(
            g, NamedSharding(mesh, gspecs[k])) for k, g in grads.items()}
        new_state, new_opt = optimizer.update(grads, opt_state, state)
        if scaler is not None:
            # overflow step: keep old params/moments, only the scale moves
            pick = lambda n, o: jnp.where(found_inf, o, n)
            new_state = jax.tree_util.tree_map(pick, new_state, state)
            new_opt = jax.tree_util.tree_map(pick, new_opt, opt_state)
            new_opt["scaler"] = scaler.update_state(sstate, found_inf)
        new_state = {k: jax.lax.with_sharding_constraint(
            v, NamedSharding(mesh, pspecs[k])) for k, v in new_state.items()}
        return new_state, new_opt, loss

    def init_fn():
        if abstract:
            raise RuntimeError(
                "this train step was built from a LazyGuard (meta-init) "
                "model — it has no parameter buffers to place; only the "
                "AOT step_fn.lower() feasibility path is available")
        # copy so the jit step's donation can never free the Layer's own
        # param buffers (device_put aliases when placement already matches)
        placed = {k: jax.device_put(jnp.array(v, copy=True), param_sh[k])
                  for k, v in state0.items()}
        opt_state = optimizer.init_state(placed)
        if scaler is not None:
            opt_state["scaler"] = scaler.init_state()
        opt_state = jax.device_put(opt_state, opt_state_shardings(opt_state))
        return placed, opt_state

    jit_step = jax.jit(
        _step,
        donate_argnums=(0, 1) if donate else (),
    )

    batch_degree = 1
    for a in active_batch_axes:
        batch_degree *= hcg.axis_size(a)

    def _place_batch_leaf(x):
        if not hasattr(x, "ndim") or x.ndim == 0:
            return x
        x = jnp.asarray(x)
        spec0 = bspec[0]
        if batch_degree > 1 and x.shape[0] % batch_degree:
            import warnings
            warnings.warn(
                f"batch dim {x.shape[0]} not divisible by dp×sharding="
                f"{batch_degree}: replicating this array (no data "
                "parallelism for it)", stacklevel=3)
            spec0 = None
        return jax.device_put(x, NamedSharding(
            mesh, P(*([spec0] + [None] * (x.ndim - 1)))))

    def step_fn(state, opt_state, batch, rngs=None):
        if rngs is None:
            from paddle_tpu.core import rng as rng_mod
            rngs = {name: rng_mod.global_key() for name in rng_streams}
        batch = jax.tree_util.tree_map(_place_batch_leaf, batch)
        return jit_step(state, opt_state, batch, rngs)

    def lower(batch_shape, seq_len, ids_dtype=jnp.int32):
        """AOT-lower the compiled step from abstract shapes (no real
        buffers) — .compile().memory_analysis() gives the per-device
        accounting for feasibility reports (SCALE.md), mirroring the
        pipeline engine's hook."""
        abstract_state, abstract_opt = abstract_train_state(
            state0, pspecs, ospecs, optimizer, mesh, scaler=scaler)
        bsh = NamedSharding(mesh, P(bspec[0], None))
        abstract_batch = {
            "input": jax.ShapeDtypeStruct((batch_shape, seq_len), ids_dtype,
                                          sharding=bsh),
            "labels": jax.ShapeDtypeStruct((batch_shape, seq_len), ids_dtype,
                                           sharding=bsh)}
        abstract_rngs = {name: jax.eval_shape(
            lambda: jax.random.PRNGKey(0)) for name in rng_streams}
        return jit_step.lower(abstract_state, abstract_opt, abstract_batch,
                              abstract_rngs)

    step_fn.lower = lower

    return step_fn, init_fn
