"""ZeRO sharding stages 1/2/3 as GSPMD sharding rules.

Reference (SURVEY.md §2.6): DygraphShardingOptimizer (stage 1),
GroupShardedStage2 (grad reduce-scatter), GroupShardedStage3 (param
shard + per-layer allgather) — thousands of lines of hook machinery
(python/paddle/distributed/fleet/meta_parallel/sharding/).

TPU-native: each stage is a *sharding placement policy* over the mesh's
"sharding" axis; GSPMD materializes the all-gathers/reduce-scatters:

* stage 1 — params+grads replicated; optimizer state sharded.
* stage 2 — params replicated; grads + optimizer state sharded.
* stage 3 — params, grads, optimizer state all sharded (FSDP): XLA
  all-gathers weights where used (overlapped), reduce-scatters grads.

`shard_params_spec` picks, per parameter, the largest axis divisible by the
sharding degree — the analog of the reference's parameter-partition step in
GroupShardedStage3._segment_rank_params.
"""

from typing import Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _largest_divisible_axis(shape, degree, taken=()):
    best, best_ax = 0, None
    for i, s in enumerate(shape):
        if i in taken:
            continue
        if s % degree == 0 and s > best:
            best, best_ax = s, i
    return best_ax


def param_pspec(shape, degree, axis_name="sharding",
                existing: Optional[P] = None) -> P:
    """PartitionSpec sharding the largest divisible dim over `axis_name`,
    composing with an existing spec (e.g. TP sharding already present)."""
    existing_list = list(existing) if existing is not None else [None] * len(shape)
    while len(existing_list) < len(shape):
        existing_list.append(None)
    if degree <= 1:
        return P(*existing_list)
    taken = [i for i, e in enumerate(existing_list) if e is not None]
    ax = _largest_divisible_axis(shape, degree, taken)
    if ax is None:
        return P(*existing_list)
    existing_list[ax] = axis_name
    return P(*existing_list)


def shard_params_spec(state: Dict[str, jax.Array], stage: int, degree: int,
                      axis_name: str = "sharding",
                      base_specs: Optional[Dict[str, P]] = None) -> Dict[str, P]:
    """Per-parameter PartitionSpecs for the given ZeRO stage."""
    specs = {}
    for k, v in state.items():
        base = (base_specs or {}).get(k)
        if stage >= 3 and degree > 1:
            specs[k] = param_pspec(v.shape, degree, axis_name, existing=base)
        else:
            specs[k] = base if base is not None else P()
    return specs


def opt_state_specs(param_specs: Dict[str, P], stage: int, degree: int,
                    params: Dict[str, jax.Array],
                    axis_name: str = "sharding") -> Dict[str, P]:
    """Optimizer-moment specs: stages 1+ shard moments over the sharding
    axis REGARDLESS of existing TP/PP placements (GroupShardedStage2
    semantics — the reference shards optimizer state across the sharding
    group on top of whatever tensor parallelism already split; composing
    the axis onto a remaining dim is what makes 'mp × pp × sharding'
    multiplicative for state memory)."""
    out = {}
    for k, spec in param_specs.items():
        if stage >= 1 and degree > 1:
            if axis_name in _axes_of(spec):
                out[k] = spec  # stage-3: param already sharding-sharded
            else:
                out[k] = param_pspec(params[k].shape, degree, axis_name,
                                     existing=spec)
        else:
            out[k] = spec
    return out


def _axes_of(spec: P):
    axes = set()
    for entry in spec:
        if entry is None:
            continue
        for a in (entry if isinstance(entry, tuple) else (entry,)):
            axes.add(a)
    return axes


def grad_specs(param_specs: Dict[str, P], stage: int, degree: int,
               params: Dict[str, jax.Array],
               axis_name: str = "sharding") -> Dict[str, P]:
    """Stage-2 grads reduce-scatter over the sharding axis, composed with
    TP/PP placements like the moments."""
    if stage >= 2 and degree > 1:
        return {k: (param_specs[k]
                    if axis_name in _axes_of(param_specs[k])
                    else param_pspec(params[k].shape, degree, axis_name,
                                     existing=param_specs[k]))
                for k in param_specs}
    return dict(param_specs)


def apply_sharding(state: Dict[str, jax.Array], mesh: Mesh,
                   specs: Dict[str, P]) -> Dict[str, jax.Array]:
    return {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
            for k, v in state.items()}


def group_sharded_parallel(model, optimizer, level="os_g", scaler=None,
                           group=None, sync_buffers=False, buffer_max_size=None,
                           segment_size=None):
    """Reference convenience API parity (group_sharded_parallel):
    level: 'os' → stage1, 'os_g' → stage2, 'p_g_os' → stage3.
    Returns (model, optimizer, scaler) with the stage recorded for the
    fleet train-step builder to pick up."""
    stage = {"os": 1, "os_g": 2, "p_g_os": 3}[level]
    model._sharding_stage = stage
    optimizer._sharding_stage = stage
    return model, optimizer, scaler
