"""Elastic training: failure detection + restart-from-checkpoint.

Reference (SURVEY.md §5-failure): fleet/elastic/manager.py — ElasticManager
registers ranks in etcd, heartbeats, and on membership change the launcher
kills and relaunches workers; recovery is restart-from-latest-checkpoint,
not in-flight repair. Failure detection otherwise = the launcher watch loop
reaping dead children + NCCL timeouts.

TPU-native: multi-host membership/rendezvous belongs to
`jax.distributed.initialize` (DCN); what the framework owns is the
restart-from-checkpoint semantics. `ElasticTrainLoop` supervises a train
loop in-process: periodic (async) checkpoints via CheckpointManager, crash →
restore latest → resume, bounded restarts — the same recovery contract,
testable single-host by injecting faults (SURVEY.md §5: tests kill procs)."""

import logging
import time
from typing import Callable, Optional

logger = logging.getLogger("paddle_tpu.elastic")


class ElasticTrainLoop:
    """Supervised training with checkpoint/resume recovery.

    train_step(state, step) -> state : one (or k) optimizer steps; `state`
    is any orbax-serializable pytree (e.g. {"model":…, "opt":…}).
    """

    def __init__(self, checkpoint_manager, train_step: Callable,
                 init_state: Callable, max_restarts: int = 3,
                 save_every: int = 100,
                 restore_target: Optional[Callable] = None):
        self.mngr = checkpoint_manager
        self.train_step = train_step
        self.init_state = init_state
        self.max_restarts = max_restarts
        self.save_every = save_every
        self.restore_target = restore_target
        self.restarts = 0

    def _resume(self):
        step = self.mngr.latest_step()
        if step is None:
            return self.init_state(), 0
        target = self.restore_target() if self.restore_target else None
        state = self.mngr.restore(step, target=target)
        logger.info("resumed from checkpoint step %d", step)
        return state, step + 1

    def run(self, total_steps: int):
        state, start = self._resume()
        step = start
        while step < total_steps:
            try:
                state = self.train_step(state, step)
                if (step + 1) % self.save_every == 0 or step + 1 == total_steps:
                    self.mngr.save(step, state)
                step += 1
            except KeyboardInterrupt:
                raise
            except Exception as e:   # noqa: BLE001 — supervisor boundary
                self.restarts += 1
                logger.warning("train step %d failed (%s); restart %d/%d",
                               step, e, self.restarts, self.max_restarts)
                if self.restarts > self.max_restarts:
                    raise
                self.mngr.wait_until_finished()
                state, step = self._resume()
        self.mngr.wait_until_finished()
        return state
