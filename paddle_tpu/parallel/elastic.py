"""Elastic training: failure detection + restart-from-checkpoint.

Reference (SURVEY.md §5-failure): fleet/elastic/manager.py — ElasticManager
registers ranks in etcd, heartbeats, and on membership change the launcher
kills and relaunches workers; recovery is restart-from-latest-checkpoint,
not in-flight repair. Failure detection otherwise = the launcher watch loop
reaping dead children + NCCL timeouts.

TPU-native: multi-host membership/rendezvous belongs to
`jax.distributed.initialize` (DCN); what the framework owns is the
restart-from-checkpoint semantics. `ElasticTrainLoop` supervises a train
loop in-process: periodic (async) checkpoints via CheckpointManager, crash →
restore latest → resume, bounded restarts — the same recovery contract,
testable single-host by injecting faults (SURVEY.md §5: tests kill procs)."""

import json
import logging
import os
import threading
import time
from typing import Callable, Dict, Optional, Set

from paddle_tpu.resilience import (RetryPolicy, is_not_found, kv_op,
                                   record_event)
from paddle_tpu.resilience import faults as _faults

logger = logging.getLogger("paddle_tpu.elastic")


# ---- membership / heartbeat (reference: fleet/elastic/manager.py) ----------
#
# The reference registers each rank in etcd and heartbeats; a missed TTL
# triggers relaunch. TPU pods have no etcd; the equivalent substrate is any
# shared KV the hosts can all reach. `HeartbeatStore` is that interface;
# `FileHeartbeatStore` implements it over a shared directory (NFS/GCS-fuse
# on real pods, tmpdir in tests). `ElasticManager` owns register/heartbeat/
# watch semantics on top.

class HeartbeatStore:
    """KV with per-member freshness — the etcd-analog interface."""

    def put(self, member: str, payload: dict):
        raise NotImplementedError

    def members(self) -> Dict[str, dict]:
        """All registered members → their last payload (incl. 'ts')."""
        raise NotImplementedError

    def remove(self, member: str):
        raise NotImplementedError


class FileHeartbeatStore(HeartbeatStore):
    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, member):
        return os.path.join(self.root, f"{member}.hb")

    def put(self, member, payload):
        tmp = self._path(member) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, self._path(member))  # atomic on POSIX

    def members(self):
        out = {}
        for fn in os.listdir(self.root):
            if not fn.endswith(".hb"):
                continue
            try:
                with open(os.path.join(self.root, fn)) as f:
                    out[fn[:-3]] = json.load(f)
            except (OSError, ValueError):
                continue  # torn write / concurrent removal
        return out

    def remove(self, member):
        try:
            os.unlink(self._path(member))
        except FileNotFoundError:
            pass


class CoordinationServiceStore(HeartbeatStore):
    """Heartbeats over a coordination-service KV (the TCPStore/etcd
    analog) — no shared filesystem required (VERDICT r3 #8: clusters
    without a shared dir).

    Two modes:
    * ``CoordinationServiceStore.connect(address, rank, world)`` — the
      launcher-side mode: rank 0 HOSTS the service on `address`, every
      launcher connects a client. Mirrors the reference's etcd being
      infra-level, outside the trainers.
    * ``CoordinationServiceStore(client=...)`` / ``.from_jax()`` — reuse
      an existing client (inside a training process after
      `jax.distributed.initialize`, the job's own coordination service).

    Every KV op runs under the shared bounded-retry policy
    (paddle_tpu.resilience.retry) — a transient coordination-service
    hiccup (RPC reset, leader re-election blip) must not read as a dead
    peer or kill the heartbeat loop. Pass ``retry=None`` to disable.
    """

    def __init__(self, client, prefix: str = "pt_elastic", service=None,
                 retry: Optional[RetryPolicy] = RetryPolicy()):
        self._client = client
        self._prefix = prefix
        self._service = service        # kept alive on the hosting rank
        self._retry = retry

    def _kv_call(self, describe: str, fn, retry_if=None):
        # shared resilience.kv_op wrapper: retry + the injectable kv.op
        # fault site (policy=None → fault site only, no retry)
        return kv_op(describe, fn, policy=self._retry, retry_if=retry_if)

    @classmethod
    def connect(cls, address: str, rank: int, world_size: int,
                prefix: str = "pt_elastic", timeout_s: float = 60.0):
        try:
            from jax._src.lib import _jax
        except ImportError:     # jax 0.4.x module name for the same API
            from jax._src.lib import xla_extension as _jax
        service = None
        if rank == 0:
            service = _jax.get_distributed_runtime_service(
                address, world_size)
        # a peer launcher dying is the NORMAL event elastic mode exists
        # for — the default client callbacks would terminate THIS process
        # on a peer's missed heartbeat / service error, defeating the
        # whole recovery loop. Log instead; the ElasticManager TTL watch
        # owns the reaction.
        client = _jax.get_distributed_runtime_client(
            address, rank, init_timeout=int(timeout_s),
            shutdown_on_destruction=False,
            missed_heartbeat_callback=lambda *a:
                logger.warning("elastic KV heartbeat event: %s", a))
        client.connect()
        return cls(client, prefix=prefix, service=service)

    @classmethod
    def from_jax(cls, prefix: str = "pt_elastic"):
        from jax._src import distributed
        client = distributed.global_state.client
        if client is None:
            raise RuntimeError(
                "CoordinationServiceStore.from_jax needs "
                "jax.distributed.initialize (init_parallel_env) first")
        return cls(client, prefix=prefix)

    def put(self, member, payload):
        self._kv_call("elastic.kv_set",
                      lambda: self._client.key_value_set(
                          f"{self._prefix}/{member}", json.dumps(payload),
                          allow_overwrite=True))

    def members(self):
        out = {}
        try:
            # empty prefix reads as NOT_FOUND on some versions — that is
            # genuinely "no members", never worth a retry. Anything else
            # (RPC hiccup, service error) is retried, and past the retry
            # budget must NOT read as an empty world: the watcher would
            # declare every peer dead and kill a healthy job.
            items = self._kv_call(
                "elastic.kv_dir_get",
                lambda: self._client.key_value_dir_get(self._prefix),
                retry_if=lambda e: not is_not_found(e))
        except Exception as e:
            if is_not_found(e):
                return out
            raise
        for key, val in items:
            try:
                out[key.rsplit("/", 1)[-1]] = json.loads(val)
            except ValueError:
                continue
        return out

    def remove(self, member):
        try:
            self._kv_call("elastic.kv_delete",
                          lambda: self._client.key_value_delete(
                              f"{self._prefix}/{member}"))
        except Exception:
            pass

    def close(self):
        try:
            self._client.shutdown()
        finally:
            self._service = None


class ElasticManager:
    """Register + heartbeat this host; watch for lost/joined peers.

    Reference semantics (fleet/elastic/manager.py): every worker heartbeats
    a TTL'd key; the manager watches membership and signals the launcher to
    relaunch on change. `watch()` here invokes `on_change(alive, dead)` from
    a daemon thread; the launcher reacts by restarting the training script,
    whose recovery is restore-from-checkpoint (ElasticTrainLoop)."""

    def __init__(self, store: HeartbeatStore, rank: int, world_size: int,
                 heartbeat_interval: float = 2.0,
                 timeout: Optional[float] = None):
        self.store = store
        self.rank = rank
        self.world_size = world_size
        self.interval = heartbeat_interval
        self.timeout = timeout if timeout is not None else 3 * heartbeat_interval
        self._stop = threading.Event()
        self._threads = []

    # -- registration / heartbeat --

    def register(self):
        # cooperative fault site: kind='drop_heartbeat' swallows this
        # put — from the peers' view this host just went silent, the
        # exact signal a hung/partitioned host produces
        fault = _faults.maybe_fire("elastic.heartbeat")
        if fault is not None and fault.kind == "drop_heartbeat":
            record_event("heartbeat_dropped")
            return
        self.store.put(str(self.rank), {"rank": self.rank, "ts": time.time()})

    def _heartbeat_loop(self):
        while not self._stop.wait(self.interval):
            self.register()

    def start(self):
        self.register()
        t = threading.Thread(target=self._heartbeat_loop, daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def stop(self, deregister: bool = True):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=self.interval + 1)
        self._threads.clear()
        if deregister:
            self.store.remove(str(self.rank))

    # -- membership --

    def alive(self, now: Optional[float] = None,
              members: Optional[Dict[str, dict]] = None) -> Set[int]:
        """Ranks with a fresh heartbeat. `members` lets a caller reuse ONE
        store snapshot for several derived views (see watch) instead of
        re-polling per view."""
        now = now if now is not None else time.time()
        members = members if members is not None else self.store.members()
        out = set()
        for m, payload in members.items():
            if now - payload.get("ts", 0) <= self.timeout:
                out.add(int(m))
        return out

    def dead(self) -> Set[int]:
        return set(range(self.world_size)) - self.alive()

    def all_alive(self) -> bool:
        return len(self.alive()) == self.world_size

    def wait_for_world(self, timeout: float = 60.0) -> bool:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.all_alive():
                return True
            time.sleep(self.interval / 4)
        return False

    def watch(self, on_change: Callable[[Set[int], Set[int]], None],
              poll_interval: Optional[float] = None):
        """Daemon thread: calls on_change(alive, dead) whenever membership
        differs from the last poll (a lost heartbeat past TTL or a join)."""
        poll = poll_interval if poll_interval is not None else self.interval

        def loop():
            last = self.alive()
            while not self._stop.wait(poll):
                # ONE store snapshot per poll: alive and dead must be two
                # views of the same instant — a second poll (the old
                # self.dead() call) could disagree with `cur` mid-change
                cur = self.alive(members=self.store.members())
                if cur != last:
                    dead = set(range(self.world_size)) - cur
                    logger.warning("membership change: alive=%s dead=%s",
                                   sorted(cur), sorted(dead))
                    on_change(cur, dead)
                    last = cur

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        self._threads.append(t)
        return t


def _nan_poison(tree):
    """NaN-fill every floating leaf (the nan_grads fault injector)."""
    import jax
    import jax.numpy as jnp

    def one(leaf):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype,
                                                     jnp.floating):
            return jnp.full_like(leaf, jnp.nan)
        return leaf

    return jax.tree_util.tree_map(one, tree)


class ElasticTrainLoop:
    """Supervised training with checkpoint/resume recovery.

    train_step(state, step) -> state : one (or k) optimizer steps; `state`
    is any orbax-serializable pytree (e.g. {"model":…, "opt":…}).

    Recovery semantics (paddle_tpu.resilience):

    * Resume restores from ``CheckpointManager.verified_latest_step()``
      when the manager provides it — a corrupt/uncommitted latest step is
      walked past instead of crash-looping forever.
    * ``nonfinite_policy``: None (off — the step takes the exact code
      path the seed took), ``"skip"`` (a step whose outputs hold NaN/Inf
      is dropped: previous state kept, counter bumped, training moves
      on) or ``"rewind"`` (skip, and after ``nonfinite_limit``
      CONSECUTIVE bad steps rewind to the last verified checkpoint —
      charged against the restart budget so a deterministic NaN can't
      rewind forever). Built on utils.nan_inf's fused device reduction.
    * The restart budget RESETS after ``restart_reset_steps`` consecutive
      clean steps (default ``save_every``; 0 disables) — a flaky step at
      hour 40 is no longer charged against failures from hour 1.
    """

    def __init__(self, checkpoint_manager, train_step: Callable,
                 init_state: Callable, max_restarts: int = 3,
                 save_every: int = 100,
                 restore_target: Optional[Callable] = None,
                 nonfinite_policy: Optional[str] = None,
                 nonfinite_limit: int = 3,
                 restart_reset_steps: Optional[int] = None):
        if nonfinite_policy not in (None, "skip", "rewind"):
            raise ValueError(
                f"nonfinite_policy must be None, 'skip' or 'rewind'; got "
                f"{nonfinite_policy!r}")
        self.mngr = checkpoint_manager
        self.train_step = train_step
        self.init_state = init_state
        self.max_restarts = max_restarts
        self.save_every = save_every
        self.restore_target = restore_target
        self.nonfinite_policy = nonfinite_policy
        self.nonfinite_limit = int(nonfinite_limit)
        self.restart_reset_steps = (save_every if restart_reset_steps is None
                                    else int(restart_reset_steps))
        self.restarts = 0
        self.nonfinite_skipped = 0

    def _resume(self):
        verified = getattr(self.mngr, "verified_latest_step", None)
        step = verified() if callable(verified) else self.mngr.latest_step()
        if step is None:
            return self.init_state(), 0
        target = self.restore_target() if self.restore_target else None
        state = self.mngr.restore(step, target=target)
        logger.info("resumed from checkpoint step %d", step)
        return state, step + 1

    def run(self, total_steps: int):
        from paddle_tpu.utils.nan_inf import tree_nonfinite_count

        state, start = self._resume()
        step = start
        clean = 0      # consecutive completed steps since last recovery
        streak = 0     # consecutive non-finite steps
        while step < total_steps:
            try:
                # raising fault kinds crash here exactly like a real step
                # failure; kind='nan_grads' poisons the step's outputs so
                # the non-finite policy (or a downstream guard) reacts
                fault = _faults.maybe_fire("train.step", index=step)
                new_state = self.train_step(state, step)
                if fault is not None and fault.kind == "nan_grads":
                    new_state = _nan_poison(new_state)
                if self.nonfinite_policy is not None \
                        and int(tree_nonfinite_count(new_state)):
                    streak += 1
                    self.nonfinite_skipped += 1
                    record_event("nonfinite_step_skipped")
                    logger.warning(
                        "step %d produced non-finite values; skipping "
                        "(%d consecutive, policy=%s)", step, streak,
                        self.nonfinite_policy)
                    if self.nonfinite_policy == "rewind" \
                            and streak >= self.nonfinite_limit:
                        record_event("nonfinite_rewind")
                        # unify with the restart path below: rewind is a
                        # restore-from-checkpoint charged to the budget
                        raise FloatingPointError(
                            f"{streak} consecutive non-finite steps "
                            f"(limit {self.nonfinite_limit})")
                    # a skipped step still honors the save cadence with
                    # the RETAINED (valid) state — otherwise one NaN on a
                    # boundary step stretches the progress-loss window to
                    # 2x save_every
                    if (step + 1) % self.save_every == 0 \
                            or step + 1 == total_steps:
                        self.mngr.save(step, state)
                    clean = 0
                    step += 1        # skip-step: old state, batch consumed
                    continue
                streak = 0
                state = new_state
                if (step + 1) % self.save_every == 0 or step + 1 == total_steps:
                    self.mngr.save(step, state)
                step += 1
                clean += 1
                if (self.restarts and self.restart_reset_steps
                        and clean >= self.restart_reset_steps):
                    logger.info("restart budget reset after %d clean steps",
                                clean)
                    record_event("restart_budget_reset")
                    self.restarts = 0
            except KeyboardInterrupt:
                raise
            except Exception as e:   # noqa: BLE001 — supervisor boundary
                self.restarts += 1
                record_event("train_restart")
                logger.warning("train step %d failed (%s); restart %d/%d",
                               step, e, self.restarts, self.max_restarts)
                if self.restarts > self.max_restarts:
                    raise
                self.mngr.wait_until_finished()
                state, step = self._resume()
                clean = 0
                streak = 0
        self.mngr.wait_until_finished()
        return state
