"""Elastic training: failure detection + restart-from-checkpoint.

Reference (SURVEY.md §5-failure): fleet/elastic/manager.py — ElasticManager
registers ranks in etcd, heartbeats, and on membership change the launcher
kills and relaunches workers; recovery is restart-from-latest-checkpoint,
not in-flight repair. Failure detection otherwise = the launcher watch loop
reaping dead children + NCCL timeouts.

TPU-native: multi-host membership/rendezvous belongs to
`jax.distributed.initialize` (DCN); what the framework owns is the
restart-from-checkpoint semantics. `ElasticTrainLoop` supervises a train
loop in-process: periodic (async) checkpoints via CheckpointManager, crash →
restore latest → resume, bounded restarts — the same recovery contract,
testable single-host by injecting faults (SURVEY.md §5: tests kill procs)."""

import json
import logging
import os
import threading
import time
from typing import Callable, Dict, Optional, Set

logger = logging.getLogger("paddle_tpu.elastic")


# ---- membership / heartbeat (reference: fleet/elastic/manager.py) ----------
#
# The reference registers each rank in etcd and heartbeats; a missed TTL
# triggers relaunch. TPU pods have no etcd; the equivalent substrate is any
# shared KV the hosts can all reach. `HeartbeatStore` is that interface;
# `FileHeartbeatStore` implements it over a shared directory (NFS/GCS-fuse
# on real pods, tmpdir in tests). `ElasticManager` owns register/heartbeat/
# watch semantics on top.

class HeartbeatStore:
    """KV with per-member freshness — the etcd-analog interface."""

    def put(self, member: str, payload: dict):
        raise NotImplementedError

    def members(self) -> Dict[str, dict]:
        """All registered members → their last payload (incl. 'ts')."""
        raise NotImplementedError

    def remove(self, member: str):
        raise NotImplementedError


class FileHeartbeatStore(HeartbeatStore):
    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, member):
        return os.path.join(self.root, f"{member}.hb")

    def put(self, member, payload):
        tmp = self._path(member) + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f)
        os.replace(tmp, self._path(member))  # atomic on POSIX

    def members(self):
        out = {}
        for fn in os.listdir(self.root):
            if not fn.endswith(".hb"):
                continue
            try:
                with open(os.path.join(self.root, fn)) as f:
                    out[fn[:-3]] = json.load(f)
            except (OSError, ValueError):
                continue  # torn write / concurrent removal
        return out

    def remove(self, member):
        try:
            os.unlink(self._path(member))
        except FileNotFoundError:
            pass


class CoordinationServiceStore(HeartbeatStore):
    """Heartbeats over a coordination-service KV (the TCPStore/etcd
    analog) — no shared filesystem required (VERDICT r3 #8: clusters
    without a shared dir).

    Two modes:
    * ``CoordinationServiceStore.connect(address, rank, world)`` — the
      launcher-side mode: rank 0 HOSTS the service on `address`, every
      launcher connects a client. Mirrors the reference's etcd being
      infra-level, outside the trainers.
    * ``CoordinationServiceStore(client=...)`` / ``.from_jax()`` — reuse
      an existing client (inside a training process after
      `jax.distributed.initialize`, the job's own coordination service).
    """

    def __init__(self, client, prefix: str = "pt_elastic", service=None):
        self._client = client
        self._prefix = prefix
        self._service = service        # kept alive on the hosting rank

    @classmethod
    def connect(cls, address: str, rank: int, world_size: int,
                prefix: str = "pt_elastic", timeout_s: float = 60.0):
        try:
            from jax._src.lib import _jax
        except ImportError:     # jax 0.4.x module name for the same API
            from jax._src.lib import xla_extension as _jax
        service = None
        if rank == 0:
            service = _jax.get_distributed_runtime_service(
                address, world_size)
        # a peer launcher dying is the NORMAL event elastic mode exists
        # for — the default client callbacks would terminate THIS process
        # on a peer's missed heartbeat / service error, defeating the
        # whole recovery loop. Log instead; the ElasticManager TTL watch
        # owns the reaction.
        client = _jax.get_distributed_runtime_client(
            address, rank, init_timeout=int(timeout_s),
            shutdown_on_destruction=False,
            missed_heartbeat_callback=lambda *a:
                logger.warning("elastic KV heartbeat event: %s", a))
        client.connect()
        return cls(client, prefix=prefix, service=service)

    @classmethod
    def from_jax(cls, prefix: str = "pt_elastic"):
        from jax._src import distributed
        client = distributed.global_state.client
        if client is None:
            raise RuntimeError(
                "CoordinationServiceStore.from_jax needs "
                "jax.distributed.initialize (init_parallel_env) first")
        return cls(client, prefix=prefix)

    def put(self, member, payload):
        self._client.key_value_set(f"{self._prefix}/{member}",
                                   json.dumps(payload), allow_overwrite=True)

    def members(self):
        out = {}
        try:
            items = self._client.key_value_dir_get(self._prefix)
        except Exception as e:
            # empty prefix reads as NOT_FOUND on some versions — that is
            # genuinely "no members". Anything else (RPC hiccup, service
            # error) must NOT read as an empty world: the watcher would
            # declare every peer dead and kill a healthy job.
            if "NOT_FOUND" in str(e) or "not found" in str(e).lower():
                return out
            raise
        for key, val in items:
            try:
                out[key.rsplit("/", 1)[-1]] = json.loads(val)
            except ValueError:
                continue
        return out

    def remove(self, member):
        try:
            self._client.key_value_delete(f"{self._prefix}/{member}")
        except Exception:
            pass

    def close(self):
        try:
            self._client.shutdown()
        finally:
            self._service = None


class ElasticManager:
    """Register + heartbeat this host; watch for lost/joined peers.

    Reference semantics (fleet/elastic/manager.py): every worker heartbeats
    a TTL'd key; the manager watches membership and signals the launcher to
    relaunch on change. `watch()` here invokes `on_change(alive, dead)` from
    a daemon thread; the launcher reacts by restarting the training script,
    whose recovery is restore-from-checkpoint (ElasticTrainLoop)."""

    def __init__(self, store: HeartbeatStore, rank: int, world_size: int,
                 heartbeat_interval: float = 2.0,
                 timeout: Optional[float] = None):
        self.store = store
        self.rank = rank
        self.world_size = world_size
        self.interval = heartbeat_interval
        self.timeout = timeout if timeout is not None else 3 * heartbeat_interval
        self._stop = threading.Event()
        self._threads = []

    # -- registration / heartbeat --

    def register(self):
        self.store.put(str(self.rank), {"rank": self.rank, "ts": time.time()})

    def _heartbeat_loop(self):
        while not self._stop.wait(self.interval):
            self.register()

    def start(self):
        self.register()
        t = threading.Thread(target=self._heartbeat_loop, daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def stop(self, deregister: bool = True):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=self.interval + 1)
        self._threads.clear()
        if deregister:
            self.store.remove(str(self.rank))

    # -- membership --

    def alive(self, now: Optional[float] = None) -> Set[int]:
        now = now if now is not None else time.time()
        out = set()
        for m, payload in self.store.members().items():
            if now - payload.get("ts", 0) <= self.timeout:
                out.add(int(m))
        return out

    def dead(self) -> Set[int]:
        return set(range(self.world_size)) - self.alive()

    def all_alive(self) -> bool:
        return len(self.alive()) == self.world_size

    def wait_for_world(self, timeout: float = 60.0) -> bool:
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.all_alive():
                return True
            time.sleep(self.interval / 4)
        return False

    def watch(self, on_change: Callable[[Set[int], Set[int]], None],
              poll_interval: Optional[float] = None):
        """Daemon thread: calls on_change(alive, dead) whenever membership
        differs from the last poll (a lost heartbeat past TTL or a join)."""
        poll = poll_interval if poll_interval is not None else self.interval

        def loop():
            last = self.alive()
            while not self._stop.wait(poll):
                cur = self.alive()
                if cur != last:
                    logger.warning("membership change: alive=%s dead=%s",
                                   sorted(cur), sorted(self.dead()))
                    on_change(cur, set(range(self.world_size)) - cur)
                    last = cur

        t = threading.Thread(target=loop, daemon=True)
        t.start()
        self._threads.append(t)
        return t


class ElasticTrainLoop:
    """Supervised training with checkpoint/resume recovery.

    train_step(state, step) -> state : one (or k) optimizer steps; `state`
    is any orbax-serializable pytree (e.g. {"model":…, "opt":…}).
    """

    def __init__(self, checkpoint_manager, train_step: Callable,
                 init_state: Callable, max_restarts: int = 3,
                 save_every: int = 100,
                 restore_target: Optional[Callable] = None):
        self.mngr = checkpoint_manager
        self.train_step = train_step
        self.init_state = init_state
        self.max_restarts = max_restarts
        self.save_every = save_every
        self.restore_target = restore_target
        self.restarts = 0

    def _resume(self):
        step = self.mngr.latest_step()
        if step is None:
            return self.init_state(), 0
        target = self.restore_target() if self.restore_target else None
        state = self.mngr.restore(step, target=target)
        logger.info("resumed from checkpoint step %d", step)
        return state, step + 1

    def run(self, total_steps: int):
        state, start = self._resume()
        step = start
        while step < total_steps:
            try:
                state = self.train_step(state, step)
                if (step + 1) % self.save_every == 0 or step + 1 == total_steps:
                    self.mngr.save(step, state)
                step += 1
            except KeyboardInterrupt:
                raise
            except Exception as e:   # noqa: BLE001 — supervisor boundary
                self.restarts += 1
                logger.warning("train step %d failed (%s); restart %d/%d",
                               step, e, self.restarts, self.max_restarts)
                if self.restarts > self.max_restarts:
                    raise
                self.mngr.wait_until_finished()
                state, step = self._resume()
        self.mngr.wait_until_finished()
        return state
