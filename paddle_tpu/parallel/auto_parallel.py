"""Semi-auto parallel API (≈ paddle.distributed.auto_parallel).

Reference (SURVEY.md §3.5): `ProcessMesh` + per-tensor `dist_attr`
(dims_mapping); static pipeline Completer→Partitioner→Resharder; 2.6 dynamic
`shard_tensor(x, mesh, [Shard(0), Replicate()])` with C++ DistTensor + SPMD
rules (paddle/phi/infermeta/spmd_rules/).

This maps 1:1 onto GSPMD: placements ≈ PartitionSpec, the Completer ≈ XLA
sharding propagation, the Resharder ≈ XLA resharding. The build therefore
provides the API veneer; jit does the machinery.
"""

from typing import List, Optional, Sequence, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.nn.layer import Layer, Parameter


class Placement:
    pass


class Shard(Placement):
    def __init__(self, dim: int):
        self.dim = dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, other):
        return isinstance(other, Shard) and other.dim == self.dim


class Replicate(Placement):
    def __repr__(self):
        return "Replicate()"

    def __eq__(self, other):
        return isinstance(other, Replicate)


class Partial(Placement):
    """Pending-reduction placement. GSPMD resolves partials automatically at
    use sites; kept for dist_attr parity."""

    def __init__(self, reduce_type="sum"):
        self.reduce_type = reduce_type

    def __repr__(self):
        return f"Partial({self.reduce_type})"


class ProcessMesh:
    """N-D logical process mesh with named dims (reference parity object).

    Wraps a jax Mesh; `dim_names` become mesh axis names.
    """

    def __init__(self, mesh: Union[Sequence, np.ndarray, Mesh],
                 dim_names: Optional[List[str]] = None, process_ids=None):
        if isinstance(mesh, Mesh):
            self._jax_mesh = mesh
            self.shape = list(mesh.devices.shape)
            self.dim_names = list(mesh.axis_names)
            return
        arr = np.asarray(mesh)
        self.shape = list(arr.shape)
        self.dim_names = dim_names or [f"d{i}" for i in range(arr.ndim)]
        devs = np.asarray(jax.devices())
        flat = arr.reshape(-1)
        sel = devs[flat]
        self._jax_mesh = Mesh(sel.reshape(arr.shape), axis_names=tuple(self.dim_names))

    @property
    def mesh(self):
        return self._jax_mesh

    @property
    def process_ids(self):
        return [d.id for d in self._jax_mesh.devices.reshape(-1)]

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self.dim_names})"


def _placements_to_pspec(placements: Sequence[Placement], mesh: ProcessMesh,
                         ndim: int) -> P:
    """[Shard(0), Replicate()] over mesh dims → PartitionSpec over tensor dims.

    The i-th placement describes how the i-th MESH dim acts on the tensor
    (reference semantics): Shard(d) shards tensor dim d over mesh dim i.
    """
    spec = [None] * ndim
    for mesh_dim, pl in enumerate(placements):
        if isinstance(pl, Shard):
            axis = mesh.dim_names[mesh_dim]
            cur = spec[pl.dim]
            if cur is None:
                spec[pl.dim] = axis
            elif isinstance(cur, tuple):
                spec[pl.dim] = cur + (axis,)
            else:
                spec[pl.dim] = (cur, axis)
    return P(*spec)


def shard_tensor(x, mesh: ProcessMesh, placements: Sequence[Placement]):
    """Place `x` (array or Parameter) on `mesh` per `placements` — the dynamic
    DistTensor API. Returns the resharded array (or mutates the Parameter)."""
    if isinstance(x, Parameter):
        spec = _placements_to_pspec(placements, mesh, x.value.ndim)
        x.pspec = spec
        x.value = jax.device_put(x.value, NamedSharding(mesh.mesh, spec))
        x.is_distributed = True
        return x
    spec = _placements_to_pspec(placements, mesh, x.ndim)
    return jax.device_put(x, NamedSharding(mesh.mesh, spec))


def dtensor_from_fn(fn, mesh: ProcessMesh, placements, *args, **kwargs):
    return shard_tensor(fn(*args, **kwargs), mesh, placements)


def reshard(x, mesh: ProcessMesh, placements):
    """Explicit resharding (≈ the Resharder's r_to_s/s_to_r/p_to_r rules —
    all subsumed by device_put with a new sharding)."""
    spec = _placements_to_pspec(placements, mesh, x.ndim)
    return jax.device_put(x, NamedSharding(mesh.mesh, spec))


def shard_layer(layer: Layer, mesh: ProcessMesh, shard_fn=None,
                input_fn=None, output_fn=None):
    """Apply `shard_fn(name, sublayer, mesh)` over the layer tree
    (reference: paddle.distributed.shard_layer)."""
    if shard_fn is None:
        def shard_fn(name, sub, mesh_):
            for pname, p in sub._parameters.items():
                shard_tensor(p, mesh_, [Replicate()] * len(mesh_.shape))
    for name, sub in layer.named_sublayers(include_self=True):
        shard_fn(name, sub, mesh)
    return layer


class Engine:
    """`auto.Engine(model, loss, optimizer, strategy)` → `.fit(data)`
    (reference: python/paddle/distributed/auto_parallel/engine.py).

    The reference traces to a static Program, runs Completer/Partitioner/
    Resharder, then executes per-rank programs (SURVEY.md §3.5). Here the
    whole pipeline is `fleet.make_train_step`: GSPMD propagates shardings
    (Completer), partitions (Partitioner) and inserts collectives
    (Resharder) inside one jit."""

    def __init__(self, model, loss=None, optimizer=None, strategy=None,
                 mesh=None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.strategy = strategy
        self._step_fn = None
        self._init_fn = None
        self._state = None
        self._opt_state = None
        self._mesh = mesh
        self._history = []

    def _build_step(self):
        """Build the jitted hybrid step (no state materialization)."""
        if self._step_fn is not None:
            return None
        from paddle_tpu.parallel import fleet
        from paddle_tpu.parallel.strategy import DistributedStrategy
        from paddle_tpu.parallel.topology import (
            get_hybrid_communicate_group)
        self.strategy = self.strategy or DistributedStrategy()
        if get_hybrid_communicate_group() is None:
            fleet.init(is_collective=True, strategy=self.strategy)
        loss_fn = None
        if self.loss is not None:
            loss_fn = lambda outputs, batch: self.loss(outputs,
                                                       batch["labels"])
        hcg = get_hybrid_communicate_group()
        if hcg.get_pipe_parallel_world_size() > 1:
            loss_fn = None       # pipeline head computes the loss
        self._step_fn, self._init_fn = fleet.make_train_step(
            self.model, self.optimizer, loss_fn, strategy=self.strategy)
        return self._init_fn

    def _ensure_built(self):
        self._build_step()
        if self._state is None:   # not gated on _build_step's return —
            # the step may have been built state-free via lower() first
            self._state, self._opt_state = self._init_fn()

    def lower(self, batch_shape, seq_len, **kw):
        """AOT-lower the semi-auto program from abstract shapes — the
        scale-report path (SCALE.md): Engine.fit's built program without
        materializing a single parameter buffer."""
        self._build_step()
        return self._step_fn.lower(batch_shape, seq_len, **kw)

    @staticmethod
    def _as_batch(batch):
        if isinstance(batch, dict):
            return batch
        if isinstance(batch, (tuple, list)) and len(batch) == 2:
            return {"input": batch[0], "labels": batch[1]}
        raise TypeError(f"unsupported batch type {type(batch)}")

    def fit(self, train_data, epochs=1, steps_per_epoch=None, verbose=1,
            log_interval=10):
        """train_data: iterable of {'input','labels'} dicts or (x, y)."""
        self._ensure_built()
        import time as _time
        step = 0
        for epoch in range(epochs):
            for batch in train_data:
                t0 = _time.perf_counter()
                self._state, self._opt_state, loss = self._step_fn(
                    self._state, self._opt_state, self._as_batch(batch))
                step += 1
                if verbose and step % log_interval == 0:
                    self._history.append(
                        {"step": step, "loss": float(loss),
                         "step_time": _time.perf_counter() - t0})
                if steps_per_epoch and step % steps_per_epoch == 0:
                    break
        return self._history

    @property
    def state(self):
        return self._state

    def sync_model(self):
        """Copy the trained state back into the Layer tree (eager access).

        The pipeline path trains on stage-prefixed/stacked keys that don't
        map back onto the Layer tree — that specific (zero-overlap) mismatch
        is skipped; a partial overlap means a genuinely broken state and
        raises rather than half-updating the model."""
        if self._state is not None:
            model_keys = set(self.model.state_dict())
            if model_keys & set(self._state):
                # check coverage BEFORE mutating — a partial overlap must
                # not leave the model half-updated (parameters only; missing
                # buffers are fine, matching set_state_dict's semantics)
                missing = (set(dict(self.model.named_parameters()))
                           - set(self._state))
                if missing:
                    raise ValueError(
                        "Engine.sync_model: trained state only partially "
                        f"covers the model; missing {sorted(missing)[:8]}...")
                self.model.set_state_dict(self._state)
        return self.model

    def save(self, path):
        from paddle_tpu.parallel.checkpoint import save_state_dict
        tree = {"model": self._state or self.model.state_dict()}
        if self._opt_state is not None:
            tree["optimizer"] = self._opt_state
        save_state_dict(tree, path)

    def load(self, path):
        from paddle_tpu.parallel.checkpoint import load_state_dict
        self._ensure_built()
        tree = load_state_dict(
            path, target={"model": self._state,
                          "optimizer": self._opt_state})
        self._state = tree["model"]
        self._opt_state = tree["optimizer"]
        return self


def get_placements(x, mesh: ProcessMesh):
    """Inverse mapping for checkpoint metadata: array sharding → placements."""
    if not isinstance(x, jax.Array) or not isinstance(x.sharding, NamedSharding):
        return [Replicate()] * len(mesh.shape)
    spec = x.sharding.spec
    placements: List[Placement] = [Replicate()] * len(mesh.shape)
    for tdim, entry in enumerate(spec):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        for ax in axes:
            placements[mesh.dim_names.index(ax)] = Shard(tdim)
    return placements
