"""DataParallel wrapper (≈ paddle.DataParallel).

Reference: python/paddle/distributed/parallel.py + the C++ EagerReducer
(gradient bucketing + async allreduce overlapped with backward —
paddle/fluid/distributed/collective/reducer.cc).

TPU-native: DP is batch-axis sharding. The wrapper records the mesh axis; the
train step built by `paddle_tpu.parallel.fleet` shards the batch over "dp" and
grads come out of `jax.grad` already correct — XLA inserts the allreduce and
its latency-hiding scheduler overlaps it with the backward, which is exactly
the job the reference's reducer does by hand. No buckets, no hooks.
"""

from paddle_tpu.nn.layer import Layer


class DataParallel(Layer):
    def __init__(self, layers: Layer, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None, mesh_axis="dp"):
        super().__init__()
        self._layers = layers
        self.mesh_axis = mesh_axis
        self.find_unused_parameters = find_unused_parameters

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    # reference API surface
    def scale_loss(self, loss):
        return loss

    def no_sync(self):
        import contextlib
        return contextlib.nullcontext()

    @property
    def inner_layer(self):
        return self._layers
