"""Long-context strategies: ring attention and Ulysses head-sharding.

Reference (SURVEY.md §5-long-context): core Paddle ships only Megatron-SP
(+ a `sep` topology axis); ring/blockwise attention and Ulysses live in the
PaddleNLP ecosystem. Capability parity here = both strategies, TPU-native:

* **Ring attention** — q/k/v sharded along sequence over the `sep` mesh
  axis; each of the n ring steps computes a blockwise flash update (online
  softmax, fp32 accumulators) of local Q against the KV chunk currently in
  hand, then rotates KV to the next device with `ppermute` over the ICI
  ring. Compute of step i overlaps the permute of step i+1 via XLA's
  latency-hiding scheduler — the blockwise-ring-attention recipe.
* **Ulysses** — two `all_to_all`s re-shard (seq-sharded → head-sharded)
  around an ordinary full-sequence attention; cheaper comm volume than a
  full allgather, the standard alternative when head count ≥ sep degree.

Both run inside a partial-manual shard_map over the sep axis and compose
with the other mesh axes (dp/mp/...) handled by GSPMD, and both are
differentiable (scan + ppermute/all_to_all transpose cleanly).
"""

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def _repeat_kv(k, n_rep):
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.broadcast_to(k[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(
        b, s, h * n_rep, d)


def ring_attention_local(q, k, v, axis_name: str, causal: bool = True,
                         scale: Optional[float] = None):
    """Blockwise ring attention. MUST run inside shard_map manual over
    `axis_name`; q/k/v are the local seq shards (b, s_loc, h, d)."""
    n = jax.lax.axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape
    n_rep = h // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    sc = scale if scale is not None else 1.0 / math.sqrt(d)

    qf = q.astype(jnp.float32) * sc
    # positions of my queries within the global sequence
    q_pos = me * s_loc + jax.lax.broadcasted_iota(jnp.int32, (s_loc, s_loc), 0)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def ring_step(carry, i):
        acc, m_prev, l_prev, kv = carry
        k_i, v_i = kv
        # the KV chunk in hand at step i originated on shard (me - i) mod n
        src = (me - i) % n
        scores = jnp.einsum("bqhd,bkhd->bhqk", qf, k_i.astype(jnp.float32))
        if causal:
            k_pos = src * s_loc + jax.lax.broadcasted_iota(
                jnp.int32, (s_loc, s_loc), 1)
            mask = q_pos >= k_pos
            scores = jnp.where(mask[None, None], scores, NEG_INF)
        m_cur = jnp.maximum(m_prev, jnp.max(scores, axis=-1))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(scores - m_cur[..., None])
        l_cur = l_prev * alpha + jnp.sum(p, axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_i.astype(jnp.float32))
        kv = jax.lax.ppermute(kv, axis_name, perm)
        return (acc, m_cur, l_cur, kv), None

    vary = lambda x: jax.lax.pcast(x, (axis_name,), to="varying")
    acc0 = vary(jnp.zeros((b, h, s_loc, d), jnp.float32))
    m0 = vary(jnp.full((b, h, s_loc), NEG_INF, jnp.float32))
    l0 = vary(jnp.zeros((b, h, s_loc), jnp.float32))
    (acc, m, l, _), _ = jax.lax.scan(
        ring_step, (acc0, m0, l0, (k, v)), jnp.arange(n))
    # fully-masked rows (can't happen for causal self-attn, but keep safe)
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)


def ulysses_attention_local(q, k, v, axis_name: str, causal: bool = True,
                            scale: Optional[float] = None):
    """Ulysses: all_to_all seq↔head re-shard around full attention.
    MUST run inside shard_map manual over `axis_name`; q/k/v local
    (b, s_loc, h, d) with h divisible by the sep degree."""
    from paddle_tpu.ops.flash_attention import _xla_attention
    n = jax.lax.axis_size(axis_name)
    if q.shape[2] % n or k.shape[2] % n:
        raise ValueError(
            f"ulysses needs head counts divisible by sep={n}; "
            f"got q heads {q.shape[2]}, kv heads {k.shape[2]}")

    def to_heads(x):   # (b, s_loc, h, d) -> (b, s_full, h/n, d)
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def to_seq(x):     # (b, s_full, h/n, d) -> (b, s_loc, h, d)
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    out = _xla_attention(qh, kh, vh, is_causal=causal, scale=scale,
                         dropout_p=0.0)
    return to_seq(out)


def context_parallel_attention(q, k, v, mesh=None, axis: str = "sep",
                               mode: str = "ring", causal: bool = True,
                               scale: Optional[float] = None):
    """GSPMD-level entry: q/k/v (b, s, h, d) seq-sharded (or shardable) over
    `axis`; wraps the local kernel in a partial-manual shard_map. No-op
    degenerates to plain attention when the axis is absent or degree 1."""
    from paddle_tpu.ops.flash_attention import _xla_attention
    from paddle_tpu.parallel.topology import get_hybrid_communicate_group

    if mesh is None:
        hcg = get_hybrid_communicate_group()
        mesh = hcg.mesh if hcg is not None else None
    if mesh is None or axis not in mesh.axis_names or mesh.shape[axis] <= 1:
        return _xla_attention(q, k, v, is_causal=causal, scale=scale,
                              dropout_p=0.0)

    local = {"ring": ring_attention_local,
             "ulysses": ulysses_attention_local}[mode]
    spec = P(None, axis, None, None)
    f = jax.shard_map(
        partial(local, axis_name=axis, causal=causal, scale=scale),
        mesh=mesh, axis_names={axis},
        in_specs=(spec, spec, spec), out_specs=spec)
    return f(q, k, v)
