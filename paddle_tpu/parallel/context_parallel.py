"""Long-context strategies: ring attention and Ulysses head-sharding.

Reference (SURVEY.md §5-long-context): core Paddle ships only Megatron-SP
(+ a `sep` topology axis); ring/blockwise attention and Ulysses live in the
PaddleNLP ecosystem. Capability parity here = both strategies, TPU-native:

* **Ring attention** — q/k/v sharded along sequence over the `sep` mesh
  axis; each of the n ring steps computes a blockwise flash update (online
  softmax, fp32 accumulators) of local Q against the KV chunk currently in
  hand, then rotates KV to the next device with `ppermute` over the ICI
  ring. Compute of step i overlaps the permute of step i+1 via XLA's
  latency-hiding scheduler — the blockwise-ring-attention recipe.
* **Ulysses** — two `all_to_all`s re-shard (seq-sharded → head-sharded)
  around an ordinary full-sequence attention; cheaper comm volume than a
  full allgather, the standard alternative when head count ≥ sep degree.

Both run inside a partial-manual shard_map over the sep axis and compose
with the other mesh axes (dp/mp/...) handled by GSPMD, and both are
differentiable (scan + ppermute/all_to_all transpose cleanly).
"""

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30


def ring_attention_local(q, k, v, axis_name: str, causal: bool = True,
                         scale: Optional[float] = None):
    """Flash-grade ring attention. MUST run inside shard_map manual over
    `axis_name`; q/k/v are the local seq shards (b, s_loc, h, d).

    Each ring step runs the flash kernel (`flash_fwd_lse`: Pallas blockwise
    on TPU — memory bounded by the 512-block tiles, never s_loc²) of local
    Q against the KV chunk in hand, then merges the chunk's normalized
    output into the running result with the standard LSE merge and rotates
    KV with ppermute. Fully-masked chunks (a causal ring where the chunk
    comes from later positions) skip compute via lax.switch."""
    n = jax.lax.axis_size(axis_name)
    me = jax.lax.axis_index(axis_name)
    b, s_loc, h, d = q.shape

    perm = [(i, (i + 1) % n) for i in range(n)]
    from paddle_tpu.ops.flash_attention import flash_fwd_lse

    vary = lambda x: jax.lax.pcast(x, (axis_name,), to="varying")

    def chunk_masked(q, k_i, v_i):
        # constants, but pcast so all switch branches agree on vma
        return (vary(jnp.zeros((b, s_loc, h, d), q.dtype)),
                vary(jnp.full((b, h, s_loc), NEG_INF, jnp.float32)))

    def chunk_diag(q, k_i, v_i):
        return flash_fwd_lse(q, k_i, v_i, True, scale)

    def chunk_full(q, k_i, v_i):
        return flash_fwd_lse(q, k_i, v_i, False, scale)

    def ring_step(carry, i):
        acc, lse_run, kv = carry
        k_i, v_i = kv
        # the KV chunk in hand at step i originated on shard (me - i) mod n
        src = (me - i) % n
        if causal:
            branch = jnp.where(src == me, 1, jnp.where(src < me, 2, 0))
            out_i, lse_i = jax.lax.switch(
                branch, (chunk_masked, chunk_diag, chunk_full), q, k_i, v_i)
        else:
            out_i, lse_i = chunk_full(q, k_i, v_i)
        out_t = jnp.transpose(out_i, (0, 2, 1, 3)).astype(jnp.float32)
        # LSE merge of normalized partials: lse_new = logaddexp(run, chunk),
        # acc = Σ out_i · exp(lse_i − lse_new)
        m_new = jnp.maximum(lse_run, lse_i)
        e_run = jnp.exp(lse_run - m_new)
        e_i = jnp.exp(lse_i - m_new)
        denom = e_run + e_i
        acc = (acc * e_run[..., None] + out_t * e_i[..., None]) \
            / denom[..., None]
        lse_new = m_new + jnp.log(denom)
        kv = jax.lax.ppermute(kv, axis_name, perm)
        return (acc, lse_new, kv), None

    acc0 = vary(jnp.zeros((b, h, s_loc, d), jnp.float32))
    lse0 = vary(jnp.full((b, h, s_loc), NEG_INF, jnp.float32))
    (acc, _, _), _ = jax.lax.scan(
        ring_step, (acc0, lse0, (k, v)), jnp.arange(n))
    return jnp.transpose(acc, (0, 2, 1, 3)).astype(q.dtype)


def ulysses_attention_local(q, k, v, axis_name: str, causal: bool = True,
                            scale: Optional[float] = None):
    """Ulysses: all_to_all seq↔head re-shard around full attention.
    MUST run inside shard_map manual over `axis_name`; q/k/v local
    (b, s_loc, h, d) with h divisible by the sep degree."""
    from paddle_tpu.ops.flash_attention import _xla_attention
    n = jax.lax.axis_size(axis_name)
    if q.shape[2] % n or k.shape[2] % n:
        raise ValueError(
            f"ulysses needs head counts divisible by sep={n}; "
            f"got q heads {q.shape[2]}, kv heads {k.shape[2]}")

    def to_heads(x):   # (b, s_loc, h, d) -> (b, s_full, h/n, d)
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    def to_seq(x):     # (b, s_full, h/n, d) -> (b, s_loc, h, d)
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    out = _xla_attention(qh, kh, vh, is_causal=causal, scale=scale,
                         dropout_p=0.0)
    return to_seq(out)


def context_parallel_attention(q, k, v, mesh=None, axis: str = "sep",
                               mode: str = "ring", causal: bool = True,
                               scale: Optional[float] = None):
    """GSPMD-level entry: q/k/v (b, s, h, d) seq-sharded (or shardable) over
    `axis`; wraps the local kernel in a partial-manual shard_map. No-op
    degenerates to plain attention when the axis is absent or degree 1."""
    from paddle_tpu.ops.flash_attention import _xla_attention
    from paddle_tpu.parallel.topology import get_hybrid_communicate_group

    if mesh is None:
        hcg = get_hybrid_communicate_group()
        mesh = hcg.mesh if hcg is not None else None
    if mesh is None or axis not in mesh.axis_names or mesh.shape[axis] <= 1:
        return _xla_attention(q, k, v, is_causal=causal, scale=scale,
                              dropout_p=0.0)

    local = {"ring": ring_attention_local,
             "ulysses": ulysses_attention_local}[mode]
    spec = P(None, axis, None, None)
    f = jax.shard_map(
        partial(local, axis_name=axis, causal=causal, scale=scale),
        mesh=mesh, axis_names={axis},
        in_specs=(spec, spec, spec), out_specs=spec)
    return f(q, k, v)
