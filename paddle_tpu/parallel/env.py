"""Parallel environment bring-up (≈ paddle.distributed.init_parallel_env).

Reference call stack (SURVEY.md §3.2): TCPStore rendezvous on rank0 →
ProcessGroupNCCL per group. TPU-native: `jax.distributed.initialize` performs
the DCN rendezvous (coordinator ≈ TCPStore) and the ICI/DCN fabric replaces
NCCL communicators. Env vars mirror the reference launcher contract
(PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_MASTER) with JAX-native
fallbacks, so `python -m paddle_tpu.parallel.launch` scripts port over.
"""

import os

import jax

_initialized = [False]


def init_parallel_env(coordinator_address=None, num_processes=None,
                      process_id=None):
    """Multi-host bring-up. Single-process (possibly multi-device) needs no init."""
    if _initialized[0]:
        return ParallelEnv()
    coord = coordinator_address or os.environ.get("PADDLE_MASTER") or \
        os.environ.get("COORDINATOR_ADDRESS")
    nproc = num_processes or _env_int("PADDLE_TRAINERS_NUM") or _env_int("NUM_PROCESSES")
    pid = process_id if process_id is not None else \
        (_env_int("PADDLE_TRAINER_ID") if "PADDLE_TRAINER_ID" in os.environ
         else _env_int("PROCESS_ID"))
    if coord and nproc and nproc > 1:
        try:
            # CPU cross-process collectives need the gloo implementation
            # (the CPU-simulated analog of the reference's Gloo backend,
            # SURVEY.md §2.5); harmless when the backend is TPU.
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except (AttributeError, KeyError):
            pass  # older jax without this config knob — TPU path unaffected
        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=nproc, process_id=pid or 0)
    _initialized[0] = True
    return ParallelEnv()


def _env_int(name):
    v = os.environ.get(name)
    return int(v) if v is not None else None


def is_initialized():
    return _initialized[0]


def get_rank(group=None):
    if group is not None:
        return group.rank
    return jax.process_index()


def get_world_size(group=None):
    if group is not None:
        return group.nranks
    return jax.process_count()


def device_count():
    return jax.device_count()


class ParallelEnv:
    """Reference `paddle.distributed.ParallelEnv` parity object."""

    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0

    @property
    def dev_id(self):
        return 0
