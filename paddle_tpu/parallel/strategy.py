"""DistributedStrategy — the single config object for all parallelism.

Reference: paddle/fluid/framework/distributed_strategy.proto +
python/paddle/distributed/fleet/base/distributed_strategy.py. Kept as the
"one strategy object configures everything" UX (SURVEY.md §5-config), but as a
plain dataclass tree instead of protobuf.
"""

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class HybridConfig:
    dp_degree: int = -1          # -1: infer from device count
    mp_degree: int = 1
    pp_degree: int = 1
    sharding_degree: int = 1
    sep_degree: int = 1
    ep_degree: int = 1           # expert parallel (carved out of dp)

    def as_dict(self):
        return dataclasses.asdict(self)


@dataclass
class AmpConfig:
    enable: bool = False
    dtype: str = "bfloat16"
    level: str = "O2"
    init_loss_scaling: float = 2.0 ** 15
    use_dynamic_loss_scaling: bool = True


@dataclass
class ShardingConfig:
    stage: int = 1               # ZeRO stage 1/2/3
    offload: bool = False


@dataclass
class RecomputeConfig:
    enable: bool = False
    # names of remat policies: 'full', 'dots_saveable', 'nothing_saveable'
    policy: str = "full"


@dataclass
class PipelineConfig:
    micro_batch_size: int = 1
    accumulate_steps: int = 1
    # '1F1B' (lockstep 1F1B engine; with virtual_pp_degree > 1 it becomes
    # the interleaved/virtual-chunk schedule) or 'FThenB'/'gpipe'
    # (accumulate-then-backward in one differentiated scan)
    schedule_mode: str = "1F1B"
    virtual_pp_degree: int = 1


@dataclass
class MoEConfig:
    top_k: int = 2
    capacity_factor: float = 1.25
    gate: str = "gshard"          # 'gshard' (top2) | 'switch' (top1)


@dataclass
class DistributedStrategy:
    hybrid_configs_: HybridConfig = field(default_factory=HybridConfig)
    amp: bool = False
    amp_configs: AmpConfig = field(default_factory=AmpConfig)
    sharding: bool = False
    sharding_configs: ShardingConfig = field(default_factory=ShardingConfig)
    recompute: bool = False
    recompute_configs: RecomputeConfig = field(default_factory=RecomputeConfig)
    pipeline: bool = False
    pipeline_configs: PipelineConfig = field(default_factory=PipelineConfig)
    moe_configs: MoEConfig = field(default_factory=MoEConfig)
    gradient_merge: bool = False
    gradient_merge_configs: Dict[str, Any] = field(default_factory=lambda: {"k_steps": 1})
    find_unused_parameters: bool = False

    # reference exposes hybrid_configs as a dict property users assign to
    @property
    def hybrid_configs(self) -> Dict[str, int]:
        return self.hybrid_configs_.as_dict()

    @hybrid_configs.setter
    def hybrid_configs(self, cfg: Dict[str, int]):
        for k, v in cfg.items():
            if hasattr(self.hybrid_configs_, k):
                setattr(self.hybrid_configs_, k, v)
            else:
                raise KeyError(f"unknown hybrid config {k!r}")
