"""Profiler veneer (≈ paddle.profiler) + training observability.

Reference (SURVEY.md §5): Profiler with scheduler windows, RecordEvent ranges,
chrome-trace export (python/paddle/profiler/, CUPTI CudaTracer). TPU-native:
jax.profiler emits XPlane traces viewable in TensorBoard/Perfetto;
RecordEvent maps to jax.profiler ranges. MFU/tokens-per-sec metrics are
first-class (BASELINE.md north star) via `StepTimer`/`MetricsLogger`.
"""

import contextlib
import json
import os
import time
from typing import Optional

import jax


class ProfilerTarget:
    CPU = "cpu"
    GPU = "gpu"
    TPU = "tpu"


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, log_dir="./profiler_log"):
        self.log_dir = log_dir
        self.timer_only = timer_only
        self._active = False
        self._step = 0
        self.scheduler = scheduler  # (start_batch, end_batch) window
        self.on_trace_ready = on_trace_ready

    def start(self):
        if not self.timer_only:
            os.makedirs(self.log_dir, exist_ok=True)
            jax.profiler.start_trace(self.log_dir)
            self._active = True

    def stop(self):
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            if self.on_trace_ready is not None:
                self.on_trace_ready(self)

    def step(self):
        self._step += 1
        if self.scheduler and not self.timer_only:
            start, end = self.scheduler
            if self._step == start and not self._active:
                self.start()
            elif self._step == end and self._active:
                self.stop()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms", device_only=True, limit=30):
        """Per-op time table parsed from the captured xplane trace
        (reference: paddle.profiler summary tables)."""
        from paddle_tpu.profiler import xplane

        planes = xplane.load_latest(self.log_dir)
        if not planes:
            return f"no traces captured in {self.log_dir}"
        rows = xplane.op_summary(planes, device_only=device_only)
        if not rows:  # e.g. CPU-only run: fall back to host planes
            rows = xplane.op_summary(planes, device_only=False)
        return xplane.format_summary(rows, time_unit=time_unit, limit=limit)

    def export_chrome_trace(self, out_path=None):
        from paddle_tpu.profiler import xplane

        return xplane.export_chrome_trace(self.log_dir, out_path)


@contextlib.contextmanager
def RecordEvent(name: str, event_type=None):
    """User range (reference RecordEvent) → jax named trace annotation."""
    with jax.profiler.TraceAnnotation(name):
        yield


def export_chrome_tracing(dir_name: str):
    """on_trace_ready handler: write catapult trace.json next to the xplane
    dump (reference: paddle.profiler.export_chrome_tracing)."""
    def handler(prof):
        from paddle_tpu.profiler import xplane

        os.makedirs(dir_name, exist_ok=True)
        return xplane.export_chrome_trace(
            prof.log_dir, os.path.join(dir_name, "trace.json"))
    return handler


# ---- MFU / throughput metrics ---------------------------------------------

# bf16 peak FLOPs/chip for known TPU generations (approx, dense)
TPU_PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5 lite": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}


def detect_peak_flops(default=197e12):
    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return default
    for k, v in TPU_PEAK_FLOPS.items():
        if k in kind:
            return v
    return default


class StepTimer:
    """Per-step wall timing with warmup discard; reports tokens/s/chip + MFU."""

    def __init__(self, model_flops_per_token: Optional[float] = None,
                 warmup: int = 2):
        self.times = []
        self.warmup = warmup
        self.flops_per_token = model_flops_per_token
        self._t0 = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.times.append(time.perf_counter() - self._t0)

    def mean_step_time(self):
        xs = self.times[self.warmup:] or self.times
        return sum(xs) / max(len(xs), 1)

    def tokens_per_sec(self, tokens_per_step, n_chips=1):
        return tokens_per_step / self.mean_step_time() / n_chips

    def mfu(self, tokens_per_step, n_chips=1, peak=None):
        if self.flops_per_token is None:
            return None
        peak = peak or detect_peak_flops()
        achieved = self.flops_per_token * tokens_per_step / self.mean_step_time()
        return achieved / (peak * n_chips)


class MetricsLogger:
    """Structured JSONL metrics (SURVEY.md §5-metrics: step time, tokens/s/chip,
    MFU as first-class outputs)."""

    def __init__(self, path="metrics.jsonl"):
        self.path = path

    def log(self, **metrics):
        metrics.setdefault("ts", time.time())
        with open(self.path, "a") as f:
            f.write(json.dumps(metrics) + "\n")


def model_flops_per_token(n_params: int) -> float:
    """Transformer ≈ 6 * N flops/token for fwd+bwd (standard estimate)."""
    return 6.0 * n_params
