"""Profiler veneer (≈ paddle.profiler) + training observability.

Reference (SURVEY.md §5): Profiler with scheduler windows, RecordEvent ranges,
chrome-trace export (python/paddle/profiler/, CUPTI CudaTracer). TPU-native:
jax.profiler emits XPlane traces viewable in TensorBoard/Perfetto;
RecordEvent maps to jax.profiler ranges. MFU/tokens-per-sec metrics are
first-class (BASELINE.md north star) via `StepTimer`/`MetricsLogger`.
"""

import atexit
import contextlib
import json
import os
import time
from typing import Optional

import jax


class ProfilerTarget:
    CPU = "cpu"
    GPU = "gpu"
    TPU = "tpu"


class Profiler:
    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, log_dir="./profiler_log"):
        self.log_dir = log_dir
        self.timer_only = timer_only
        self._active = False
        self._step = 0
        self._atexit_registered = False
        self._window_started = False
        self._window_active = False   # the WINDOW opened the live trace
        self.scheduler = scheduler  # (start_batch, end_batch) window
        self.on_trace_ready = on_trace_ready

    def start(self):
        if not self.timer_only:
            os.makedirs(self.log_dir, exist_ok=True)
            jax.profiler.start_trace(self.log_dir)
            self._active = True
            if not self._atexit_registered:
                # a trace left open at process exit is never flushed —
                # guard against callers that exit inside the scheduler
                # window (or never call stop())
                self._atexit_registered = True
                atexit.register(self._atexit_stop)

    def stop(self):
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
            self._window_active = False
            if self._atexit_registered:
                # atexit holds a strong ref to self (and anything the
                # on_trace_ready closure captured) — release it, or every
                # Profiler ever started leaks until process exit
                self._atexit_registered = False
                atexit.unregister(self._atexit_stop)
            if self.on_trace_ready is not None:
                self.on_trace_ready(self)

    def _atexit_stop(self):
        try:
            self.stop()
        except Exception:   # interpreter teardown: never raise from atexit
            pass

    def step(self):
        self._step += 1
        if self.scheduler and not self.timer_only:
            start, end = self.scheduler
            # range (not ==) checks so a counter that jumps PAST a window
            # boundary can't leave the trace open forever; the
            # started-this-window flag keeps the window one-shot — a
            # manual stop() mid-window must not re-arm on the next step
            if start <= self._step < end and not self._active \
                    and not self._window_started:
                self._window_started = True
                self.start()
                self._window_active = True
            elif self._step >= end and self._active \
                    and self._window_active:
                # only close the trace the WINDOW opened — a manual
                # post-window start() stays under the caller's control
                self.stop()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms", device_only=True, limit=30):
        """Per-op time table parsed from the captured xplane trace
        (reference: paddle.profiler summary tables)."""
        from paddle_tpu.profiler import xplane

        planes = xplane.load_latest(self.log_dir)
        if not planes:
            return f"no traces captured in {self.log_dir}"
        rows = xplane.op_summary(planes, device_only=device_only)
        if not rows:  # e.g. CPU-only run: fall back to host planes
            rows = xplane.op_summary(planes, device_only=False)
        return xplane.format_summary(rows, time_unit=time_unit, limit=limit)

    def export_chrome_trace(self, out_path=None):
        from paddle_tpu.profiler import xplane

        return xplane.export_chrome_trace(self.log_dir, out_path)


@contextlib.contextmanager
def RecordEvent(name: str, event_type=None):
    """User range (reference RecordEvent) → jax named trace annotation."""
    with jax.profiler.TraceAnnotation(name):
        yield


def export_chrome_tracing(dir_name: str):
    """on_trace_ready handler: write catapult trace.json next to the xplane
    dump (reference: paddle.profiler.export_chrome_tracing)."""
    def handler(prof):
        from paddle_tpu.profiler import xplane

        os.makedirs(dir_name, exist_ok=True)
        return xplane.export_chrome_trace(
            prof.log_dir, os.path.join(dir_name, "trace.json"))
    return handler


# ---- MFU / throughput metrics ---------------------------------------------

# bf16 peak FLOPs/chip for known TPU generations (approx, dense)
TPU_PEAK_FLOPS = {
    "v4": 275e12,
    "v5e": 197e12,
    "v5 lite": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}


def detect_peak_flops(default=197e12):
    try:
        kind = jax.devices()[0].device_kind.lower()
    except Exception:
        return default
    for k, v in TPU_PEAK_FLOPS.items():
        if k in kind:
            return v
    return default


class StepTimer:
    """Per-step wall timing with warmup discard; reports tokens/s/chip + MFU.

    Each completed step's duration is also observed into the process-wide
    metrics registry (histogram ``train.step_seconds``) so exporters see
    training cadence without a second timer."""

    def __init__(self, model_flops_per_token: Optional[float] = None,
                 warmup: int = 2):
        self.times = []
        self.warmup = warmup
        self.flops_per_token = model_flops_per_token
        self._t0 = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        self.times.append(dt)
        # get-or-create each time (one dict lookup): caching the
        # Histogram object would orphan it across registry().reset()
        from paddle_tpu.observability.registry import registry
        registry().histogram("train.step_seconds").observe(dt)

    def mean_step_time(self):
        """Mean post-warmup step seconds; None before any step completes
        (a 0.0 here used to propagate into ZeroDivisionError in
        tokens_per_sec/mfu)."""
        xs = self.times[self.warmup:] or self.times
        if not xs:
            return None
        return sum(xs) / len(xs)

    def tokens_per_sec(self, tokens_per_step, n_chips=1):
        mst = self.mean_step_time()
        if not mst:
            return None     # no completed step yet (or 0-duration steps)
        return tokens_per_step / mst / n_chips

    def mfu(self, tokens_per_step, n_chips=1, peak=None):
        if self.flops_per_token is None:
            return None
        mst = self.mean_step_time()
        if not mst:
            return None     # no completed step yet
        peak = peak or detect_peak_flops()
        achieved = self.flops_per_token * tokens_per_step / mst
        return achieved / (peak * n_chips)


class MetricsLogger:
    """Structured JSONL metrics (SURVEY.md §5-metrics: step time, tokens/s/chip,
    MFU as first-class outputs).

    Each line is written with ONE ``os.write`` on an ``O_APPEND`` fd —
    POSIX appends are atomic per write, so per-rank writers under
    ``parallel/launch.py`` sharing a path can't interleave partial JSON
    (the old buffered ``open(..., "a").write`` could split a line across
    stdio flushes). Numeric fields are mirrored into the process-wide
    metrics registry as ``metrics.<key>`` gauges."""

    def __init__(self, path="metrics.jsonl", mirror_to_registry=True):
        self.path = path
        self.mirror_to_registry = mirror_to_registry

    def log(self, **metrics):
        from paddle_tpu.observability.registry import append_jsonl_lines
        metrics.setdefault("ts", time.time())
        append_jsonl_lines(self.path, [json.dumps(metrics)])
        if self.mirror_to_registry:
            from paddle_tpu.observability.registry import registry
            reg = registry()
            for k, v in metrics.items():
                if k != "ts" and isinstance(v, (int, float)) \
                        and not isinstance(v, bool):
                    reg.gauge(f"metrics.{k}").set(v)
            reg.counter("metrics.lines").inc()


def model_flops_per_token(n_params: int) -> float:
    """Transformer ≈ 6 * N flops/token for fwd+bwd (standard estimate)."""
    return 6.0 * n_params


def roofline_report(log_dir: str, plan):
    """Join the latest xplane capture in `log_dir` against an analytic
    roofline plan → per-phase "% of roofline, named residual" table (the
    artifact the SCALE.md re-measure items ask for). See
    `profiler.xplane.roofline_report` for the plan shape; benches embed
    one as `roofline_plan` in their BENCH json, and
    `examples/scale_report.py --report <log_dir> --plan <json>` prints
    the table from the command line."""
    from paddle_tpu.profiler import xplane

    return xplane.roofline_report(log_dir, plan)
