"""XPlane (.xplane.pb) parsing without a tensorflow dependency.

jax.profiler writes XSpace protobufs (tsl/profiler/protobuf/xplane.proto)
under ``<log_dir>/plugins/profile/<run>/*.xplane.pb``. The reference's
profiler (SURVEY.md §5: python/paddle/profiler, CUPTI tracer) exposes
per-op summaries and chrome-trace export from its own event records; the
TPU-native equivalents come from these traces. This module decodes the
protobuf wire format directly (generic tag/varint/length-delimited
reader + the xplane field numbers) so summaries work on the bare image.

Wire schema (field numbers from xplane.proto):
  XSpace:   planes=1
  XPlane:   id=1 name=2 lines=3 event_metadata=4(map) stat_metadata=5(map)
  XLine:    id=1 name=2 timestamp_ns=3 events=4 display_name=11
  XEvent:   metadata_id=1 offset_ps=2 duration_ps=3 num_occurrences=5
  XEventMetadata: id=1 name=2 display_name=4
  map entry: key=1 value=2
"""

import glob
import json
import os
from typing import Dict, List, Optional, Tuple


def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    shift = 0
    val = 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) over a message buffer.

    wire 0 → varint int; wire 1 → 8 raw bytes; wire 2 → bytes;
    wire 5 → 4 raw bytes. Groups (3/4) don't occur in xplane.
    """
    i, n = 0, len(buf)
    while i < n:
        key, i = _read_varint(buf, i)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, i = _read_varint(buf, i)
        elif wire == 1:
            v, i = buf[i:i + 8], i + 8
        elif wire == 2:
            ln, i = _read_varint(buf, i)
            v, i = buf[i:i + ln], i + ln
        elif wire == 5:
            v, i = buf[i:i + 4], i + 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, v


class XEvent:
    __slots__ = ("name", "offset_ps", "duration_ps", "occurrences")

    def __init__(self, name, offset_ps, duration_ps, occurrences):
        self.name = name
        self.offset_ps = offset_ps
        self.duration_ps = duration_ps
        self.occurrences = occurrences


class XLine:
    __slots__ = ("name", "timestamp_ns", "events")

    def __init__(self, name, timestamp_ns, events):
        self.name = name
        self.timestamp_ns = timestamp_ns
        self.events = events


class XPlane:
    __slots__ = ("name", "lines")

    def __init__(self, name, lines):
        self.name = name
        self.lines = lines


def _parse_event_metadata(buf: bytes) -> Tuple[int, str]:
    mid, name, display = 0, "", ""
    for f, w, v in _fields(buf):
        if f == 1 and w == 0:
            mid = v
        elif f == 2 and w == 2:
            name = v.decode("utf-8", "replace")
        elif f == 4 and w == 2:
            display = v.decode("utf-8", "replace")
    return mid, (display or name)


def _parse_plane(buf: bytes) -> XPlane:
    name = ""
    raw_lines: List[bytes] = []
    meta: Dict[int, str] = {}
    for f, w, v in _fields(buf):
        if f == 2 and w == 2:
            name = v.decode("utf-8", "replace")
        elif f == 3 and w == 2:
            raw_lines.append(v)
        elif f == 4 and w == 2:  # map<int64, XEventMetadata>
            for mf, mw, mv in _fields(v):
                if mf == 2 and mw == 2:
                    mid, mname = _parse_event_metadata(mv)
                    meta[mid] = mname
    lines = []
    for lb in raw_lines:
        lname, ts_ns = "", 0
        events = []
        for f, w, v in _fields(lb):
            if f == 2 and w == 2:
                lname = v.decode("utf-8", "replace")
            elif f == 11 and w == 2:
                lname = v.decode("utf-8", "replace") or lname
            elif f == 3 and w == 0:
                ts_ns = v
            elif f == 4 and w == 2:
                mid, off, dur, occ = 0, 0, 0, 1
                for ef, ew, ev in _fields(v):
                    if ef == 1 and ew == 0:
                        mid = ev
                    elif ef == 2 and ew == 0:
                        off = ev
                    elif ef == 3 and ew == 0:
                        dur = ev
                    elif ef == 5 and ew == 0:
                        occ = ev
                events.append(XEvent(meta.get(mid, f"op#{mid}"), off, dur, occ))
        lines.append(XLine(lname, ts_ns, events))
    return XPlane(name, lines)


def parse_xspace(path: str) -> List[XPlane]:
    with open(path, "rb") as f:
        buf = f.read()
    planes = []
    for f_, w, v in _fields(buf):
        if f_ == 1 and w == 2:
            planes.append(_parse_plane(v))
    return planes


def find_xplane_files(log_dir: str) -> List[str]:
    return sorted(glob.glob(
        os.path.join(log_dir, "plugins", "profile", "*", "*.xplane.pb")))


def load_latest(log_dir: str) -> List[XPlane]:
    files = find_xplane_files(log_dir)
    if not files:
        return []
    planes: List[XPlane] = []
    run_dir = os.path.dirname(files[-1])
    for p in files:
        if os.path.dirname(p) == run_dir:
            planes.extend(parse_xspace(p))
    return planes


# ---- aggregation ----------------------------------------------------------

def op_summary(planes: List[XPlane],
               device_only: bool = True,
               exclude_lines: Tuple = ()) -> List[dict]:
    """Aggregate per-op (event name) totals across device planes.

    `exclude_lines`: line names to skip (e.g. "XLA Modules", whose
    per-module rollup events double-count every op underneath them).
    Returns rows sorted by total time: {name, calls, total_ms, avg_ms, pct}.
    """
    rows: Dict[str, List[float]] = {}
    for plane in planes:
        if device_only and not any(
                k in plane.name for k in ("TPU", "GPU", "/device:")):
            continue
        for line in plane.lines:
            if line.name in exclude_lines:
                continue
            for ev in line.events:
                r = rows.setdefault(ev.name, [0, 0.0])
                r[0] += max(ev.occurrences, 1)
                r[1] += ev.duration_ps / 1e9  # ps → ms
    total = sum(r[1] for r in rows.values()) or 1.0
    out = [{"name": k, "calls": int(v[0]), "total_ms": v[1],
            "avg_ms": v[1] / max(v[0], 1), "pct": 100.0 * v[1] / total}
           for k, v in rows.items()]
    out.sort(key=lambda r: -r["total_ms"])
    return out


def format_summary(rows: List[dict], time_unit: str = "ms",
                   limit: int = 30) -> str:
    unit_div = {"s": 1e3, "ms": 1.0, "us": 1e-3}[time_unit]
    hdr = (f"{'Name':<52} {'Calls':>7} {'Total(' + time_unit + ')':>12} "
           f"{'Avg(' + time_unit + ')':>12} {'Ratio(%)':>9}")
    lines = [hdr, "-" * len(hdr)]
    for r in rows[:limit]:
        nm = r["name"] if len(r["name"]) <= 52 else r["name"][:49] + "..."
        lines.append(f"{nm:<52} {r['calls']:>7} "
                     f"{r['total_ms'] / unit_div:>12.3f} "
                     f"{r['avg_ms'] / unit_div:>12.3f} {r['pct']:>9.2f}")
    if len(rows) > limit:
        lines.append(f"... ({len(rows) - limit} more ops)")
    return "\n".join(lines)


# Residual-attribution buckets for the MoE training step (the r5 profile
# attributed the 22.9 ms dispatch residual to slice/gather fusions).
# First-match wins, so attention fusions don't land in "dispatch" via
# their transposes; anything unmatched stays visible as "other".
MOE_RESIDUAL_BUCKETS: Tuple = (
    ("attention", ("flash", "attention", "softmax")),
    ("optimizer", ("adam", "lamb", "momentum", "weight_decay")),
    # NOTE 'convolution' not 'conv' (would swallow 'convert' dtype casts)
    # and no 'rsqrt' in optimizer (would swallow RMSNorm fusions) — casts
    # and norms land in "other" rather than corrupting the attribution
    ("expert_matmul", ("dot", "einsum", "convolution", "ragged",
                      "matmul")),
    ("dispatch", ("gather", "scatter", "sort", "slice", "dynamic-update",
                  "dynamic_update", "iota", "cumsum", "one-hot", "one_hot",
                  "top-k", "top_k", "select", "transpose", "concatenate",
                  "broadcast", "pad", "reshape", "copy")),
)


def bucket_summary(rows: List[dict],
                   buckets=MOE_RESIDUAL_BUCKETS) -> Dict[str, float]:
    """Attribute `op_summary` rows to named buckets by FIRST substring
    match on the lowercased op/fusion name. Returns {bucket: total_ms}
    including an "other" catch-all — the per-op residual attribution the
    benches dump so a future round can verify a residual actually
    shrank (fusion names don't reveal contents; substring attribution is
    best-effort, which is why the raw top rows ride alongside)."""
    totals = {name: 0.0 for name, _ in buckets}
    totals["other"] = 0.0
    for r in rows:
        nm = r["name"].lower()
        for bname, subs in buckets:
            if any(s in nm for s in subs):
                totals[bname] += r["total_ms"]
                break
        else:
            totals["other"] += r["total_ms"]
    return totals


def roofline_report(log_dir: str, plan: Dict) -> Dict:
    """Join the latest xplane capture against an analytic roofline plan.

    `plan` (see observability.schema.validate_roofline_plan):
      hbm_gbps: float        — HBM bandwidth the DMA floor divides by (GB/s)
      peak_tflops: float     — optional matmul peak (TFLOP/s)
      steps: int             — timed steps the capture covers (divisor)
      phases: [{name, match: [substrings], bytes_per_step,
                flops_per_step}]

    Per phase: measured ms/step comes from `bucket_summary` over the
    capture's op rows (FIRST substring match wins, unmatched ops land in
    "other"); the roofline floor is max(bytes/BW, flops/peak); the
    residual is measured − floor, with the binding bound named ("dma"
    vs "matmul") — the per-phase "% of roofline, named residual" table
    the SCALE.md re-measure rows ask for. Substring attribution is
    best-effort (fusion names don't reveal contents), which is why the
    "other" row and the raw measured numbers ride along.

    Returns {"rows": [...], "other_ms_per_step": float, "table": str}.
    """
    from paddle_tpu.observability.schema import validate_roofline_plan

    validate_roofline_plan(plan)
    planes = load_latest(log_dir)
    # "XLA Modules" rollup events contain every op underneath them —
    # keeping them would double-count the whole capture into "other"
    op_rows = op_summary(planes, exclude_lines=("XLA Modules",))
    if not op_rows:                 # CPU sim: no device plane
        op_rows = op_summary(planes, device_only=False,
                             exclude_lines=("XLA Modules",))
    buckets = tuple((p["name"], tuple(s.lower() for s in p["match"]))
                    for p in plan["phases"])
    totals = bucket_summary(op_rows, buckets)
    steps = max(int(plan.get("steps", 1)), 1)
    bw = float(plan["hbm_gbps"]) * 1e9
    peak = float(plan.get("peak_tflops", 0.0)) * 1e12
    rows = []
    for p in plan["phases"]:
        measured_ms = totals.get(p["name"], 0.0) / steps
        t_dma = float(p.get("bytes_per_step", 0.0)) / bw
        flops = float(p.get("flops_per_step", 0.0))
        t_mxu = flops / peak if peak and flops else 0.0
        roof_ms = max(t_dma, t_mxu) * 1e3
        rows.append({
            "phase": p["name"],
            "measured_ms_per_step": measured_ms,
            "roofline_ms_per_step": roof_ms,
            "frac_of_roofline": (roof_ms / measured_ms
                                 if measured_ms > 0 and roof_ms > 0
                                 else None),
            "bound": ("matmul" if t_mxu > t_dma else "dma") if roof_ms
                     else None,
            "residual_ms_per_step": measured_ms - roof_ms,
        })
    other_ms = totals.get("other", 0.0) / steps
    return {"rows": rows, "other_ms_per_step": other_ms,
            "table": format_roofline(rows, other_ms)}


def format_roofline(rows: List[dict], other_ms: float = 0.0) -> str:
    hdr = (f"{'Phase':<20} {'Measured(ms)':>13} {'Roofline(ms)':>13} "
           f"{'%roof':>7} {'Bound':>7} {'Residual(ms)':>13}")
    lines = [hdr, "-" * len(hdr)]
    for r in rows:
        pct = (f"{100.0 * r['frac_of_roofline']:.1f}"
               if r["frac_of_roofline"] is not None else "-")
        lines.append(
            f"{r['phase']:<20} {r['measured_ms_per_step']:>13.3f} "
            f"{r['roofline_ms_per_step']:>13.3f} {pct:>7} "
            f"{r['bound'] or '-':>7} {r['residual_ms_per_step']:>13.3f}")
    lines.append(f"{'other':<20} {other_ms:>13.3f} {'-':>13} {'-':>7} "
                 f"{'-':>7} {'-':>13}")
    return "\n".join(lines)


# ---- synthetic xspace encoding (test fixtures) -----------------------------

def _enc_varint(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _enc_tag(field: int, wire: int) -> bytes:
    return _enc_varint((field << 3) | wire)


def _enc_bytes(field: int, payload: bytes) -> bytes:
    return _enc_tag(field, 2) + _enc_varint(len(payload)) + payload


def _enc_int(field: int, v: int) -> bytes:
    return _enc_tag(field, 0) + _enc_varint(v)


def build_xspace(planes) -> bytes:
    """Encode a synthetic XSpace protobuf this module can parse back —
    the CPU-only fixture generator for roofline/summary tests (no TPU,
    no tensorflow). `planes` is
    [(plane_name, [(line_name, timestamp_ns,
                    [(event_name, offset_ps, duration_ps, occurrences),
                     ...]), ...]), ...].
    """
    space = b""
    for plane_name, lines in planes:
        # stable metadata ids per event name within the plane
        meta_ids: Dict[str, int] = {}
        for _, _, events in lines:
            for name, *_ in events:
                meta_ids.setdefault(name, len(meta_ids) + 1)
        plane = _enc_bytes(2, plane_name.encode())
        for name, mid in meta_ids.items():
            entry = _enc_int(1, mid) + _enc_bytes(
                2, _enc_int(1, mid) + _enc_bytes(2, name.encode()))
            plane += _enc_bytes(4, entry)   # event_metadata map entry
        for line_name, ts_ns, events in lines:
            line = _enc_bytes(2, line_name.encode()) + _enc_int(3, ts_ns)
            for name, off_ps, dur_ps, occ in events:
                ev = (_enc_int(1, meta_ids[name]) + _enc_int(2, off_ps)
                      + _enc_int(3, dur_ps) + _enc_int(5, occ))
                line += _enc_bytes(4, ev)
            plane += _enc_bytes(3, line)
        space += _enc_bytes(1, plane)
    return space


def write_xspace(planes, log_dir: str, run: str = "run0",
                 host: str = "host0") -> str:
    """Write `build_xspace(planes)` where `load_latest(log_dir)` finds it."""
    d = os.path.join(log_dir, "plugins", "profile", run)
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, f"{host}.xplane.pb")
    with open(path, "wb") as f:
        f.write(build_xspace(planes))
    return path


def to_chrome_trace(planes: List[XPlane]) -> dict:
    """Chrome trace-event JSON (catapult format) from xplane events."""
    events = []
    pid = 0
    for plane in planes:
        pid += 1
        events.append({"ph": "M", "pid": pid, "name": "process_name",
                       "args": {"name": plane.name}})
        tid = 0
        for line in plane.lines:
            tid += 1
            events.append({"ph": "M", "pid": pid, "tid": tid,
                           "name": "thread_name",
                           "args": {"name": line.name}})
            base_us = line.timestamp_ns / 1e3
            for ev in line.events:
                events.append({
                    "ph": "X", "pid": pid, "tid": tid, "name": ev.name,
                    "ts": base_us + ev.offset_ps / 1e6,
                    "dur": ev.duration_ps / 1e6,
                })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def export_chrome_trace(log_dir: str, out_path: Optional[str] = None) -> str:
    planes = load_latest(log_dir)
    out_path = out_path or os.path.join(log_dir, "trace.json")
    with open(out_path, "w") as f:
        json.dump(to_chrome_trace(planes), f)
    return out_path


def device_total_seconds(log_dir: str, name_substr: str) -> Optional[float]:
    """Total device execution seconds of modules whose name contains
    `name_substr`, from the latest trace in log_dir ('XLA Modules' line).
    Returns None when no matching events exist. Shared by the benches —
    device-clock timing is immune to the remote tunnel's dispatch
    latency."""
    total = 0
    for plane in load_latest(log_dir):
        for line in plane.lines:
            if line.name == "XLA Modules":
                total += sum(e.duration_ps for e in line.events
                             if name_substr in e.name)
    return total / 1e12 if total else None
