"""XPlane (.xplane.pb) parsing without a tensorflow dependency.

jax.profiler writes XSpace protobufs (tsl/profiler/protobuf/xplane.proto)
under ``<log_dir>/plugins/profile/<run>/*.xplane.pb``. The reference's
profiler (SURVEY.md §5: python/paddle/profiler, CUPTI tracer) exposes
per-op summaries and chrome-trace export from its own event records; the
TPU-native equivalents come from these traces. This module decodes the
protobuf wire format directly (generic tag/varint/length-delimited
reader + the xplane field numbers) so summaries work on the bare image.

Wire schema (field numbers from xplane.proto):
  XSpace:   planes=1
  XPlane:   id=1 name=2 lines=3 event_metadata=4(map) stat_metadata=5(map)
  XLine:    id=1 name=2 timestamp_ns=3 events=4 display_name=11
  XEvent:   metadata_id=1 offset_ps=2 duration_ps=3 num_occurrences=5
  XEventMetadata: id=1 name=2 display_name=4
  map entry: key=1 value=2
"""

import glob
import json
import os
from typing import Dict, List, Optional, Tuple


def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    shift = 0
    val = 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7


def _fields(buf: bytes):
    """Yield (field_number, wire_type, value) over a message buffer.

    wire 0 → varint int; wire 1 → 8 raw bytes; wire 2 → bytes;
    wire 5 → 4 raw bytes. Groups (3/4) don't occur in xplane.
    """
    i, n = 0, len(buf)
    while i < n:
        key, i = _read_varint(buf, i)
        field, wire = key >> 3, key & 7
        if wire == 0:
            v, i = _read_varint(buf, i)
        elif wire == 1:
            v, i = buf[i:i + 8], i + 8
        elif wire == 2:
            ln, i = _read_varint(buf, i)
            v, i = buf[i:i + ln], i + ln
        elif wire == 5:
            v, i = buf[i:i + 4], i + 4
        else:
            raise ValueError(f"unsupported wire type {wire}")
        yield field, wire, v


class XEvent:
    __slots__ = ("name", "offset_ps", "duration_ps", "occurrences")

    def __init__(self, name, offset_ps, duration_ps, occurrences):
        self.name = name
        self.offset_ps = offset_ps
        self.duration_ps = duration_ps
        self.occurrences = occurrences


class XLine:
    __slots__ = ("name", "timestamp_ns", "events")

    def __init__(self, name, timestamp_ns, events):
        self.name = name
        self.timestamp_ns = timestamp_ns
        self.events = events


class XPlane:
    __slots__ = ("name", "lines")

    def __init__(self, name, lines):
        self.name = name
        self.lines = lines


def _parse_event_metadata(buf: bytes) -> Tuple[int, str]:
    mid, name, display = 0, "", ""
    for f, w, v in _fields(buf):
        if f == 1 and w == 0:
            mid = v
        elif f == 2 and w == 2:
            name = v.decode("utf-8", "replace")
        elif f == 4 and w == 2:
            display = v.decode("utf-8", "replace")
    return mid, (display or name)


def _parse_plane(buf: bytes) -> XPlane:
    name = ""
    raw_lines: List[bytes] = []
    meta: Dict[int, str] = {}
    for f, w, v in _fields(buf):
        if f == 2 and w == 2:
            name = v.decode("utf-8", "replace")
        elif f == 3 and w == 2:
            raw_lines.append(v)
        elif f == 4 and w == 2:  # map<int64, XEventMetadata>
            for mf, mw, mv in _fields(v):
                if mf == 2 and mw == 2:
                    mid, mname = _parse_event_metadata(mv)
                    meta[mid] = mname
    lines = []
    for lb in raw_lines:
        lname, ts_ns = "", 0
        events = []
        for f, w, v in _fields(lb):
            if f == 2 and w == 2:
                lname = v.decode("utf-8", "replace")
            elif f == 11 and w == 2:
                lname = v.decode("utf-8", "replace") or lname
            elif f == 3 and w == 0:
                ts_ns = v
            elif f == 4 and w == 2:
                mid, off, dur, occ = 0, 0, 0, 1
                for ef, ew, ev in _fields(v):
                    if ef == 1 and ew == 0:
                        mid = ev
                    elif ef == 2 and ew == 0:
                        off = ev
                    elif ef == 3 and ew == 0:
                        dur = ev
                    elif ef == 5 and ew == 0:
                        occ = ev
                events.append(XEvent(meta.get(mid, f"op#{mid}"), off, dur, occ))
        lines.append(XLine(lname, ts_ns, events))
    return XPlane(name, lines)


def parse_xspace(path: str) -> List[XPlane]:
    with open(path, "rb") as f:
        buf = f.read()
    planes = []
    for f_, w, v in _fields(buf):
        if f_ == 1 and w == 2:
            planes.append(_parse_plane(v))
    return planes


def find_xplane_files(log_dir: str) -> List[str]:
    return sorted(glob.glob(
        os.path.join(log_dir, "plugins", "profile", "*", "*.xplane.pb")))


def load_latest(log_dir: str) -> List[XPlane]:
    files = find_xplane_files(log_dir)
    if not files:
        return []
    planes: List[XPlane] = []
    run_dir = os.path.dirname(files[-1])
    for p in files:
        if os.path.dirname(p) == run_dir:
            planes.extend(parse_xspace(p))
    return planes


# ---- aggregation ----------------------------------------------------------

def op_summary(planes: List[XPlane],
               device_only: bool = True) -> List[dict]:
    """Aggregate per-op (event name) totals across device planes.

    Returns rows sorted by total time: {name, calls, total_ms, avg_ms, pct}.
    """
    rows: Dict[str, List[float]] = {}
    for plane in planes:
        if device_only and not any(
                k in plane.name for k in ("TPU", "GPU", "/device:")):
            continue
        for line in plane.lines:
            for ev in line.events:
                r = rows.setdefault(ev.name, [0, 0.0])
                r[0] += max(ev.occurrences, 1)
                r[1] += ev.duration_ps / 1e9  # ps → ms
    total = sum(r[1] for r in rows.values()) or 1.0
    out = [{"name": k, "calls": int(v[0]), "total_ms": v[1],
            "avg_ms": v[1] / max(v[0], 1), "pct": 100.0 * v[1] / total}
           for k, v in rows.items()]
    out.sort(key=lambda r: -r["total_ms"])
    return out


def format_summary(rows: List[dict], time_unit: str = "ms",
                   limit: int = 30) -> str:
    unit_div = {"s": 1e3, "ms": 1.0, "us": 1e-3}[time_unit]
    hdr = (f"{'Name':<52} {'Calls':>7} {'Total(' + time_unit + ')':>12} "
           f"{'Avg(' + time_unit + ')':>12} {'Ratio(%)':>9}")
    lines = [hdr, "-" * len(hdr)]
    for r in rows[:limit]:
        nm = r["name"] if len(r["name"]) <= 52 else r["name"][:49] + "..."
        lines.append(f"{nm:<52} {r['calls']:>7} "
                     f"{r['total_ms'] / unit_div:>12.3f} "
                     f"{r['avg_ms'] / unit_div:>12.3f} {r['pct']:>9.2f}")
    if len(rows) > limit:
        lines.append(f"... ({len(rows) - limit} more ops)")
    return "\n".join(lines)


# Residual-attribution buckets for the MoE training step (the r5 profile
# attributed the 22.9 ms dispatch residual to slice/gather fusions).
# First-match wins, so attention fusions don't land in "dispatch" via
# their transposes; anything unmatched stays visible as "other".
MOE_RESIDUAL_BUCKETS: Tuple = (
    ("attention", ("flash", "attention", "softmax")),
    ("optimizer", ("adam", "lamb", "momentum", "weight_decay")),
    # NOTE 'convolution' not 'conv' (would swallow 'convert' dtype casts)
    # and no 'rsqrt' in optimizer (would swallow RMSNorm fusions) — casts
    # and norms land in "other" rather than corrupting the attribution
    ("expert_matmul", ("dot", "einsum", "convolution", "ragged",
                      "matmul")),
    ("dispatch", ("gather", "scatter", "sort", "slice", "dynamic-update",
                  "dynamic_update", "iota", "cumsum", "one-hot", "one_hot",
                  "top-k", "top_k", "select", "transpose", "concatenate",
                  "broadcast", "pad", "reshape", "copy")),
)


def bucket_summary(rows: List[dict],
                   buckets=MOE_RESIDUAL_BUCKETS) -> Dict[str, float]:
    """Attribute `op_summary` rows to named buckets by FIRST substring
    match on the lowercased op/fusion name. Returns {bucket: total_ms}
    including an "other" catch-all — the per-op residual attribution the
    benches dump so a future round can verify a residual actually
    shrank (fusion names don't reveal contents; substring attribution is
    best-effort, which is why the raw top rows ride alongside)."""
    totals = {name: 0.0 for name, _ in buckets}
    totals["other"] = 0.0
    for r in rows:
        nm = r["name"].lower()
        for bname, subs in buckets:
            if any(s in nm for s in subs):
                totals[bname] += r["total_ms"]
                break
        else:
            totals["other"] += r["total_ms"]
    return totals


def to_chrome_trace(planes: List[XPlane]) -> dict:
    """Chrome trace-event JSON (catapult format) from xplane events."""
    events = []
    pid = 0
    for plane in planes:
        pid += 1
        events.append({"ph": "M", "pid": pid, "name": "process_name",
                       "args": {"name": plane.name}})
        tid = 0
        for line in plane.lines:
            tid += 1
            events.append({"ph": "M", "pid": pid, "tid": tid,
                           "name": "thread_name",
                           "args": {"name": line.name}})
            base_us = line.timestamp_ns / 1e3
            for ev in line.events:
                events.append({
                    "ph": "X", "pid": pid, "tid": tid, "name": ev.name,
                    "ts": base_us + ev.offset_ps / 1e6,
                    "dur": ev.duration_ps / 1e6,
                })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def export_chrome_trace(log_dir: str, out_path: Optional[str] = None) -> str:
    planes = load_latest(log_dir)
    out_path = out_path or os.path.join(log_dir, "trace.json")
    with open(out_path, "w") as f:
        json.dump(to_chrome_trace(planes), f)
    return out_path


def device_total_seconds(log_dir: str, name_substr: str) -> Optional[float]:
    """Total device execution seconds of modules whose name contains
    `name_substr`, from the latest trace in log_dir ('XLA Modules' line).
    Returns None when no matching events exist. Shared by the benches —
    device-clock timing is immune to the remote tunnel's dispatch
    latency."""
    total = 0
    for plane in load_latest(log_dir):
        for line in plane.lines:
            if line.name == "XLA Modules":
                total += sum(e.duration_ps for e in line.events
                             if name_substr in e.name)
    return total / 1e12 if total else None
