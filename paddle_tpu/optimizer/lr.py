"""LR schedulers (ref: python/paddle/optimizer/lr.py).

Dual API: stateful (`.step()`, `.get_lr()` — dygraph parity) and pure
(`.value(step)` — a jnp function of the step counter, used inside jitted train
steps so the schedule compiles into the update).
"""

import math

import jax.numpy as jnp


class LRScheduler:
    def __init__(self, learning_rate=0.1, last_epoch=-1, verbose=False):
        self.base_lr = learning_rate
        self.last_epoch = last_epoch
        self.step()

    def value(self, step):
        """Pure schedule: step (int or traced scalar) → lr."""
        raise NotImplementedError

    def get_lr(self):
        return float(self.value(jnp.asarray(max(self.last_epoch, 0))))

    def step(self, epoch=None):
        self.last_epoch = self.last_epoch + 1 if epoch is None else epoch

    def state_dict(self):
        return {"last_epoch": self.last_epoch}

    def set_state_dict(self, state):
        self.last_epoch = state["last_epoch"]


class ConstantLR(LRScheduler):
    def value(self, step):
        return jnp.asarray(self.base_lr, jnp.float32)


class NoamDecay(LRScheduler):
    def __init__(self, d_model, warmup_steps, learning_rate=1.0, last_epoch=-1,
                 verbose=False):
        self.d_model, self.warmup_steps = d_model, warmup_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def value(self, step):
        s = jnp.maximum(step.astype(jnp.float32) if hasattr(step, "astype")
                        else jnp.asarray(float(step)), 1.0)
        return self.base_lr * self.d_model ** -0.5 * jnp.minimum(
            s ** -0.5, s * self.warmup_steps ** -1.5)


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def value(self, step):
        return self.base_lr * jnp.power(self.gamma, step)


class StepDecay(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1, last_epoch=-1,
                 verbose=False):
        self.step_size, self.gamma = step_size, gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def value(self, step):
        return self.base_lr * jnp.power(self.gamma, step // self.step_size)


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1, last_epoch=-1,
                 verbose=False):
        self.milestones, self.gamma = list(milestones), gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def value(self, step):
        k = sum((jnp.asarray(step) >= m).astype(jnp.int32) for m in self.milestones)
        return self.base_lr * jnp.power(self.gamma, k)


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate, decay_steps, end_lr=0.0001, power=1.0,
                 cycle=False, last_epoch=-1, verbose=False):
        self.decay_steps, self.end_lr, self.power = decay_steps, end_lr, power
        super().__init__(learning_rate, last_epoch, verbose)

    def value(self, step):
        s = jnp.minimum(jnp.asarray(step, jnp.float32), self.decay_steps)
        frac = (1.0 - s / self.decay_steps) ** self.power
        return (self.base_lr - self.end_lr) * frac + self.end_lr


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate, T_max, eta_min=0.0, last_epoch=-1,
                 verbose=False):
        self.T_max, self.eta_min = T_max, eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def value(self, step):
        s = jnp.asarray(step, jnp.float32)
        return self.eta_min + (self.base_lr - self.eta_min) * 0.5 * (
            1.0 + jnp.cos(math.pi * jnp.minimum(s, self.T_max) / self.T_max))


class LinearWarmup(LRScheduler):
    def __init__(self, learning_rate, warmup_steps, start_lr=0.0, end_lr=None,
                 last_epoch=-1, verbose=False):
        self.inner = learning_rate if isinstance(learning_rate, LRScheduler) else None
        peak = learning_rate.base_lr if self.inner else learning_rate
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr if end_lr is not None else peak
        super().__init__(peak, last_epoch, verbose)

    def value(self, step):
        s = jnp.asarray(step, jnp.float32)
        warm = self.start_lr + (self.end_lr - self.start_lr) * jnp.minimum(
            s, self.warmup_steps) / max(self.warmup_steps, 1)
        if self.inner is not None:
            after = self.inner.value(jnp.maximum(s - self.warmup_steps, 0))
        else:
            after = jnp.asarray(self.end_lr, jnp.float32)
        return jnp.where(s < self.warmup_steps, warm, after)


class WarmupCosine(LRScheduler):
    """Linear warmup → cosine decay to `min_ratio`*peak — the LLM pretrain staple."""

    def __init__(self, learning_rate, warmup_steps, total_steps, min_ratio=0.1,
                 last_epoch=-1, verbose=False):
        self.warmup_steps, self.total_steps, self.min_ratio = warmup_steps, total_steps, min_ratio
        super().__init__(learning_rate, last_epoch, verbose)

    def value(self, step):
        s = jnp.asarray(step, jnp.float32)
        warm = self.base_lr * jnp.minimum(s, self.warmup_steps) / max(self.warmup_steps, 1)
        prog = jnp.clip((s - self.warmup_steps) /
                        max(self.total_steps - self.warmup_steps, 1), 0.0, 1.0)
        cos = self.base_lr * (self.min_ratio + (1 - self.min_ratio) * 0.5 *
                              (1.0 + jnp.cos(math.pi * prog)))
        return jnp.where(s < self.warmup_steps, warm, cos)
