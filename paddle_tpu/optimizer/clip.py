"""Gradient clipping (ref: python/paddle/nn/clip.py — ClipGradByGlobalNorm etc.).

Clips are pure pytree→pytree transforms usable inside jit. Global-norm clip is
the one Fleet wires through hybrid parallelism (HybridParallelOptimizer fuses
the norm allreduce across mesh axes); here the grads live on the mesh, so the
norm reduction is a single XLA reduction and GSPMD inserts the collectives.
"""

import jax
import jax.numpy as jnp


class ClipGradBase:
    def __call__(self, grads):
        raise NotImplementedError


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm=1.0):
        self.clip_norm = clip_norm

    def __call__(self, grads):
        leaves = jax.tree_util.tree_leaves(grads)
        gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                             for g in leaves))
        scale = jnp.minimum(1.0, self.clip_norm / jnp.maximum(gnorm, 1e-12))
        return jax.tree_util.tree_map(
            lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype), grads)

    def global_norm(self, grads):
        leaves = jax.tree_util.tree_leaves(grads)
        return jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                            for g in leaves))


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm=1.0):
        self.clip_norm = clip_norm

    def __call__(self, grads):
        def clip_one(g):
            n = jnp.linalg.norm(g.astype(jnp.float32))
            scale = jnp.minimum(1.0, self.clip_norm / jnp.maximum(n, 1e-12))
            return (g * scale).astype(g.dtype)
        return jax.tree_util.tree_map(clip_one, grads)


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def __call__(self, grads):
        return jax.tree_util.tree_map(
            lambda g: jnp.clip(g, self.min, self.max), grads)
