"""Optimizers (ref: python/paddle/optimizer/ — SGD, Momentum, Adam, AdamW, Lamb).

Dual API, TPU-first:

* **Functional** (the production path): ``state = opt.init_state(params)``;
  ``new_params, new_state = opt.update(grads, state, params, step=...)`` — pure,
  jit-able, shardable. Optimizer moments inherit parameter shardings by
  construction (same tree structure), which is what makes ZeRO stage-1/2
  "free" under GSPMD (SURVEY.md §2.6).
* **Eager veneer** (dygraph parity): construct with ``parameters=model.parameters()``,
  then ``opt.apply_gradients(grads_dict)`` / ``opt.step()`` mutate the layer's
  arrays in place.

Master weights: with ``multi_precision=True`` (the reference's AMP-O2 contract)
fp32 master copies live in the optimizer state and bf16/fp16 params are re-cast
from masters each step.
"""

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from paddle_tpu.optimizer import lr as lr_mod
from paddle_tpu.optimizer.clip import (  # noqa: F401
    ClipGradBase,
    ClipGradByGlobalNorm,
    ClipGradByNorm,
    ClipGradByValue,
)
from paddle_tpu.optimizer.lr import LRScheduler  # noqa: F401

_tree_map = jax.tree_util.tree_map


def _to_f32(t):
    return _tree_map(lambda x: x.astype(jnp.float32), t)


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=0.0,
                 grad_clip=None, multi_precision=True, apply_decay_param_fun=None):
        self._lr = learning_rate
        self._parameters = list(parameters) if parameters is not None else None
        from paddle_tpu import regularizer as _reg
        self._decay_l1 = isinstance(weight_decay, _reg.L1Decay)
        if self._decay_l1 and getattr(self, "_decoupled_wd", False):
            raise ValueError(
                f"{type(self).__name__} applies decoupled (AdamW-style) L2 "
                "decay; L1Decay is only meaningful with coupled-decay "
                "optimizers (SGD/Momentum/Adam/...)")
        if isinstance(weight_decay, (_reg.L1Decay, _reg.L2Decay)):
            weight_decay = weight_decay.coeff
        self.weight_decay = weight_decay if weight_decay is not None else 0.0
        self.grad_clip = grad_clip
        self.multi_precision = multi_precision
        self.apply_decay_param_fun = apply_decay_param_fun
        self._step_count = 0
        self._eager_state = None

    def _slot_zeros(self, params, fill=0.0):
        """Accumulator init honoring multi_precision: fp32 slots under the
        AMP-O2 contract (default), PARAM-dtype slots with
        multi_precision=False — the reference's pure-low-precision mode.
        fp32 slots halve to bf16 this way: at 1B params that is ~7.5 GB
        less optimizer read+write traffic per step AND ~4.4 GB less HBM."""
        dt = lambda p: jnp.float32 if self.multi_precision else p.dtype
        if fill:
            return _tree_map(lambda p: jnp.full(p.shape, fill, dt(p)), params)
        return _tree_map(lambda p: jnp.zeros(p.shape, dt(p)), params)

    def _decay_grads(self, grads, params):
        """Add the decay term to grads: L2 (default) or L1 when the
        weight_decay was a paddle_tpu.regularizer.L1Decay. Honors
        apply_decay_param_fun (params excluded there get no decay)."""
        if not self.weight_decay:
            return grads
        wd = self.weight_decay
        mask = self._decay_mask(params)
        term = (lambda p: wd * jnp.sign(p)) if self._decay_l1 \
            else (lambda p: wd * p)
        return {k: g + term(params[k]) if mask[k] else g
                for k, g in grads.items()}

    # -- lr ------------------------------------------------------------------

    def lr_value(self, step):
        if isinstance(self._lr, lr_mod.LRScheduler):
            return self._lr.value(step)
        return jnp.asarray(self._lr, jnp.float32)

    def get_lr(self):
        if isinstance(self._lr, lr_mod.LRScheduler):
            return self._lr.get_lr()
        return float(self._lr)

    def set_lr(self, value):
        self._lr = value

    # -- functional API ------------------------------------------------------

    def init_state(self, params: Dict[str, jax.Array]) -> Dict[str, Any]:
        slots = self._init_slots(params)
        if self.multi_precision:
            # master copies only for low-precision params (the reference's
            # AMP-O2 contract); fp32 params update in place — also keeps
            # state/master buffers distinct so jit donation never aliases.
            masters = {k: v.astype(jnp.float32) for k, v in params.items()
                       if v.dtype != jnp.float32}
            if masters:
                slots["master"] = masters
        slots["step"] = jnp.zeros((), jnp.int32)
        return slots

    def _init_slots(self, params):
        raise NotImplementedError

    def update(self, grads, state, params, step=None):
        """Pure update: returns (new_params, new_state).

        The elementwise slot math runs on 1-D views of every leaf
        (reshape to/from is a free bitcast): XLA tiles 1-D elementwise
        fusions at streaming bandwidth, while 4-D expert stacks
        (L, E, h, f) measured as low as ~370 GB/s with their native
        tiling — the MoE "flat update" lever (SCALE.md) without any
        concat/split copies or state-storage restructuring."""
        if self.grad_clip is not None:
            grads = self.grad_clip(grads)
        step_ = state["step"] if step is None else step
        lr = self.lr_value(step_)
        masters = state.get("master")
        work = ({k: masters[k] if k in masters else params[k] for k in params}
                if masters else params)
        gf = _to_f32(grads)
        shapes = {k: v.shape for k, v in work.items()}

        def flat(tree):
            """Flatten entries whose shape MATCHES the param's, recording
            which keys were actually flattened — unflat must only undo
            these. (A slot that is legitimately a REDUCED shape — e.g. a
            per-row accumulator (rows,) for a 2-D param — must pass
            through untouched in both directions.)"""
            out, done = {}, set()
            for k, v in tree.items():
                if (hasattr(v, "reshape") and k in shapes
                        and v.shape == shapes[k]):
                    out[k] = v.reshape(-1)
                    done.add(k)
                else:
                    out[k] = v
            return out, done

        def unflat(tree, done):
            return {k: (v.reshape(shapes[k])
                        if k in done and hasattr(v, "reshape") else v)
                    for k, v in tree.items()}

        gf, _ = flat(gf)
        work_flat, work_done = flat(work)
        flat_state, slot_done = {}, {}
        for k, v in state.items():
            if isinstance(v, dict):
                flat_state[k], slot_done[k] = flat(v)
            else:
                flat_state[k] = v
        new_work, new_slots = self._apply(gf, work_flat, flat_state,
                                          lr, step_)
        new_work = unflat(new_work, work_done)
        # a slot dict _apply introduces this step derives from flattened
        # params/grads, so it unflattens with the param key set
        new_slots = {k: (unflat(v, slot_done.get(k, work_done))
                         if isinstance(v, dict) else v)
                     for k, v in new_slots.items()}
        new_state = dict(state)
        # accumulator math runs in fp32; store back in the slot's own dtype
        # (bf16 under multi_precision=False — see _slot_zeros)
        for slot, tree in new_slots.items():
            old = state.get(slot)
            if old is not None and jax.tree_util.tree_structure(
                    old) == jax.tree_util.tree_structure(tree):
                tree = _tree_map(
                    lambda n, o: n.astype(o.dtype)
                    if hasattr(n, "astype") and n.dtype != o.dtype else n,
                    tree, old)
            new_state[slot] = tree
        new_state["step"] = state["step"] + 1
        if masters:
            new_state["master"] = {k: new_work[k] for k in masters}
        new_params = _tree_map(lambda m, p: m.astype(p.dtype), new_work, params)
        return new_params, new_state

    def _apply(self, grads, params, state, lr, step):
        raise NotImplementedError

    def _decay_mask(self, params):
        if self.apply_decay_param_fun is None:
            return {k: True for k in params}
        return {k: bool(self.apply_decay_param_fun(k)) for k in params}

    # -- eager veneer --------------------------------------------------------

    def apply_gradients(self, named_grads: Dict[str, jax.Array], model=None):
        """Mutate registered Parameters (or `model`'s) in place — dygraph UX."""
        if model is not None:
            named_params = {k: p for k, p in model.named_parameters() if p.trainable}
        else:
            if self._parameters is None:
                raise ValueError("pass parameters= at construction or model= here")
            named_params = {p.name or str(i): p
                            for i, p in enumerate(self._parameters) if p.trainable}
        values = {k: p.value for k, p in named_params.items()}
        grads = {k: named_grads[k] for k in values}
        if self._eager_state is None:
            self._eager_state = self.init_state(values)
        new_values, self._eager_state = self.update(grads, self._eager_state, values)
        for k, p in named_params.items():
            p.value = new_values[k]
        self._step_count += 1

    def step(self):
        raise RuntimeError(
            "paddle_tpu has no implicit autograd tape: compute grads with "
            "jax.grad over nn.functional_call (or paddle_tpu.grad) and call "
            "opt.apply_gradients(grads, model=...), or use the functional "
            "opt.update inside a jitted train step.")

    def clear_grad(self):
        pass

    def state_dict(self):
        sd = {"eager_state": self._eager_state, "step_count": self._step_count}
        if isinstance(self._lr, lr_mod.LRScheduler):
            sd["lr"] = self._lr.state_dict()
        return sd

    def set_state_dict(self, sd):
        self._eager_state = sd.get("eager_state")
        self._step_count = sd.get("step_count", 0)
        if "lr" in sd and isinstance(self._lr, lr_mod.LRScheduler):
            self._lr.set_state_dict(sd["lr"])


class SGD(Optimizer):
    def _init_slots(self, params):
        return {}

    def _apply(self, grads, params, state, lr, step):
        grads = self._decay_grads(grads, params)
        new = _tree_map(lambda p, g: p - lr * g, params, grads)
        return new, {}


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=0.0, grad_clip=None,
                 multi_precision=True):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self.momentum = momentum
        self.use_nesterov = use_nesterov

    def _init_slots(self, params):
        return {"velocity": self._slot_zeros(params)}

    def _apply(self, grads, params, state, lr, step):
        grads = self._decay_grads(grads, params)
        vel = _tree_map(lambda v, g: self.momentum * v + g, state["velocity"], grads)
        if self.use_nesterov:
            new = _tree_map(lambda p, v, g: p - lr * (g + self.momentum * v),
                            params, vel, grads)
        else:
            new = _tree_map(lambda p, v: p - lr * v, params, vel)
        return new, {"velocity": vel}


class Adam(Optimizer):
    _decoupled_wd = False

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.0,
                 grad_clip=None, multi_precision=True, lazy_mode=False,
                 apply_decay_param_fun=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, apply_decay_param_fun)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _init_slots(self, params):
        return {"moment1": self._slot_zeros(params),
                "moment2": self._slot_zeros(params)}

    def _apply(self, grads, params, state, lr, step):
        b1, b2, eps = self.beta1, self.beta2, self.epsilon
        t = (step + 1).astype(jnp.float32) if hasattr(step, "astype") else float(step + 1)
        bias1 = 1.0 - b1 ** t
        bias2 = 1.0 - b2 ** t
        wd = self.weight_decay

        if not self._decoupled_wd:
            grads = self._decay_grads(grads, params)

        m1 = _tree_map(lambda m, g: b1 * m + (1 - b1) * g, state["moment1"], grads)
        m2 = _tree_map(lambda v, g: b2 * v + (1 - b2) * jnp.square(g),
                       state["moment2"], grads)

        decay_mask = self._decay_mask(params)

        def upd(p, m, v, do_decay):
            mhat = m / bias1
            vhat = v / bias2
            delta = mhat / (jnp.sqrt(vhat) + eps)
            if self._decoupled_wd and wd and do_decay:
                delta = delta + wd * p
            return p - lr * delta

        new = {k: upd(params[k], m1[k], m2[k], decay_mask[k]) for k in params}
        return new, {"moment1": m1, "moment2": m2}


class AdamW(Adam):
    """Adam with decoupled weight decay (ref: python/paddle/optimizer/adamw.py)."""
    _decoupled_wd = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 grad_clip=None, multi_precision=True,
                 apply_decay_param_fun=None, lr_ratio=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, multi_precision,
                         apply_decay_param_fun=apply_decay_param_fun)


class Lamb(Optimizer):
    # LAMB's wd term enters the trust-ratio update decoupled-style (wd·p),
    # so L1Decay objects are rejected like AdamW's
    _decoupled_wd = True

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 multi_precision=True):
        super().__init__(learning_rate, parameters, lamb_weight_decay, grad_clip,
                         multi_precision)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _init_slots(self, params):
        return {"moment1": self._slot_zeros(params),
                "moment2": self._slot_zeros(params)}

    def _apply(self, grads, params, state, lr, step):
        b1, b2, eps, wd = self.beta1, self.beta2, self.epsilon, self.weight_decay
        t = (step + 1).astype(jnp.float32) if hasattr(step, "astype") else float(step + 1)
        bias1 = 1.0 - b1 ** t
        bias2 = 1.0 - b2 ** t
        m1 = _tree_map(lambda m, g: b1 * m + (1 - b1) * g, state["moment1"], grads)
        m2 = _tree_map(lambda v, g: b2 * v + (1 - b2) * jnp.square(g),
                       state["moment2"], grads)

        def upd(p, m, v):
            r = m / bias1 / (jnp.sqrt(v / bias2) + eps) + wd * p
            w_norm = jnp.linalg.norm(p)
            r_norm = jnp.linalg.norm(r)
            trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
            return p - lr * trust * r

        new = _tree_map(upd, params, m1, m2)
        return new, {"moment1": m1, "moment2": m2}


class Adagrad(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, parameters=None,
                 weight_decay=0.0, grad_clip=None, multi_precision=True,
                 initial_accumulator_value=0.0):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self.epsilon = epsilon
        self.initial_accumulator_value = initial_accumulator_value

    def _init_slots(self, params):
        return {"moment": self._slot_zeros(
            params, fill=self.initial_accumulator_value)}

    def _apply(self, grads, params, state, lr, step):
        grads = self._decay_grads(grads, params)
        mom = _tree_map(lambda m, g: m + jnp.square(g), state["moment"], grads)
        new = _tree_map(lambda p, m, g: p - lr * g / (jnp.sqrt(m) + self.epsilon),
                        params, mom, grads)
        return new, {"moment": mom}


class RMSProp(Optimizer):
    def __init__(self, learning_rate=0.001, rho=0.95, epsilon=1e-6,
                 momentum=0.0, centered=False, parameters=None,
                 weight_decay=0.0, grad_clip=None, multi_precision=True):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self.rho, self.epsilon = rho, epsilon
        self.momentum, self.centered = momentum, centered

    def _init_slots(self, params):
        slots = {"mean_square": self._slot_zeros(params),
                 "velocity": self._slot_zeros(params)}
        if self.centered:
            slots["mean_grad"] = self._slot_zeros(params)
        return slots

    def _apply(self, grads, params, state, lr, step):
        rho, eps = self.rho, self.epsilon
        grads = self._decay_grads(grads, params)
        ms = _tree_map(lambda m, g: rho * m + (1 - rho) * jnp.square(g),
                       state["mean_square"], grads)
        slots = {"mean_square": ms}
        if self.centered:
            mg = _tree_map(lambda m, g: rho * m + (1 - rho) * g,
                           state["mean_grad"], grads)
            slots["mean_grad"] = mg
            denom = _tree_map(lambda m, a: jnp.sqrt(m - jnp.square(a)) + eps,
                              ms, mg)
        else:
            denom = _tree_map(lambda m: jnp.sqrt(m) + eps, ms)
        vel = _tree_map(lambda v, g, d: self.momentum * v + lr * g / d,
                        state["velocity"], grads, denom)
        slots["velocity"] = vel
        new = _tree_map(lambda p, v: p - v, params, vel)
        return new, slots


class Adadelta(Optimizer):
    def __init__(self, learning_rate=1.0, rho=0.95, epsilon=1e-6,
                 parameters=None, weight_decay=0.0, grad_clip=None,
                 multi_precision=True):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self.rho, self.epsilon = rho, epsilon

    def _init_slots(self, params):
        return {"avg_sq_grad": self._slot_zeros(params),
                "avg_sq_update": self._slot_zeros(params)}

    def _apply(self, grads, params, state, lr, step):
        rho, eps = self.rho, self.epsilon
        grads = self._decay_grads(grads, params)
        asg = _tree_map(lambda a, g: rho * a + (1 - rho) * jnp.square(g),
                        state["avg_sq_grad"], grads)
        upd = _tree_map(
            lambda g, a, u: g * jnp.sqrt(u + eps) / jnp.sqrt(a + eps),
            grads, asg, state["avg_sq_update"])
        asu = _tree_map(lambda u, d: rho * u + (1 - rho) * jnp.square(d),
                        state["avg_sq_update"], upd)
        new = _tree_map(lambda p, d: p - lr * d, params, upd)
        return new, {"avg_sq_grad": asg, "avg_sq_update": asu}


from paddle_tpu.optimizer import lr  # noqa: F401,E402
