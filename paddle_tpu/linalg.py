"""paddle.linalg parity — decompositions/solvers over jnp.linalg.

Reference surface: python/paddle/tensor/linalg.py + paddle.linalg namespace
(phi kernels backed by cuSOLVER/MAGMA). On TPU these lower to XLA's
factorization ops; on CPU to LAPACK. Exposed as `paddle_tpu.linalg` and
re-exported through `paddle_tpu.tensor`.
"""

import jax
import jax.numpy as jnp


def cholesky(x, upper=False):
    L = jnp.linalg.cholesky(x)
    return jnp.swapaxes(L, -1, -2).conj() if upper else L


def cholesky_solve(x, y, upper=False):
    """Solve A X = B given the Cholesky factor `y` of A.

    upper=False: A = L Lᴴ with y=L; upper=True: A = Uᴴ U with y=U. Either
    way the first solve is against the lower-triangular factor."""
    lo = y if not upper else jnp.swapaxes(y, -1, -2).conj()
    up = jnp.swapaxes(y, -1, -2).conj() if not upper else y
    z = jax.scipy.linalg.solve_triangular(lo, x, lower=True)
    return jax.scipy.linalg.solve_triangular(up, z, lower=False)


def qr(x, mode="reduced"):
    return jnp.linalg.qr(x, mode=mode)


def svd(x, full_matrices=False):
    return jnp.linalg.svd(x, full_matrices=full_matrices)


def svdvals(x):
    return jnp.linalg.svd(x, compute_uv=False)


def eig(x):
    return jnp.linalg.eig(x)


def eigvals(x):
    return jnp.linalg.eigvals(x)


def eigh(x, UPLO="L"):
    return jnp.linalg.eigh(x, UPLO=UPLO)


def eigvalsh(x, UPLO="L"):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


def inv(x):
    return jnp.linalg.inv(x)


inverse = inv  # paddle.inverse name at tensor level


def det(x):
    return jnp.linalg.det(x)


def slogdet(x):
    return jnp.linalg.slogdet(x)


def solve(x, y):
    return jnp.linalg.solve(x, y)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular)


def lstsq(x, y, rcond=None, driver=None):
    return jnp.linalg.lstsq(x, y, rcond=rcond)


def lu(x):
    """LU factorization. Returns (LU, pivots) with 1-based LAPACK pivots
    (reference convention: paddle.linalg.lu returns ipiv starting at 1)."""
    lu_mat, piv = jax.scipy.linalg.lu_factor(x)
    return lu_mat, piv + 1


def lu_unpack(lu_mat, piv):
    """Unpack a 2-D lu_factor result into (P, L, U) with P @ L @ U == A.

    Consumes the 1-based pivots produced by :func:`lu` (LAPACK/reference
    convention)."""
    m, n = lu_mat.shape[-2], lu_mat.shape[-1]
    k = min(m, n)
    L = jnp.tril(lu_mat[..., :k], k=-1) + jnp.eye(m, k, dtype=lu_mat.dtype)
    U = jnp.triu(lu_mat[..., :k, :])
    perm = jnp.arange(m)
    piv = piv - 1  # back to 0-based row indices

    def body(i, perm):  # LAPACK ipiv: row i was swapped with row piv[i]
        j = piv[i]
        pi, pj = perm[i], perm[j]
        return perm.at[i].set(pj).at[j].set(pi)

    perm = jax.lax.fori_loop(0, piv.shape[0], body, perm)
    # rows were permuted as P_swaps @ A = L U  →  A = P_swapsᵀ L U
    P = jnp.eye(m, dtype=lu_mat.dtype)[perm].T
    return P, L, U


def matrix_power(x, n):
    return jnp.linalg.matrix_power(x, n)


def matrix_rank(x, tol=None, hermitian=False):
    if tol is None:
        return jnp.linalg.matrix_rank(x)
    # paddle's tol is an ABSOLUTE threshold on singular values
    s = jnp.abs(jnp.linalg.eigvalsh(x)) if hermitian else \
        jnp.linalg.svd(x, compute_uv=False)
    return jnp.sum((s > tol).astype(jnp.int64), axis=-1)


def pinv(x, rcond=1e-15, hermitian=False):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


def norm(x, p=None, axis=None, keepdim=False):
    return jnp.linalg.norm(x, ord=p, axis=axis, keepdims=keepdim)


def cond(x, p=None):
    return jnp.linalg.cond(x, p=p)


def multi_dot(xs):
    return jnp.linalg.multi_dot(xs)


def matmul(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2)
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2)
    return jnp.matmul(x, y)


def matrix_exp(x):
    return jax.scipy.linalg.expm(x)


def householder_product(x, tau):
    """Accumulate Householder reflectors (geqrf convention) into Q."""
    m, n = x.shape[-2], x.shape[-1]
    Q = jnp.eye(m, dtype=x.dtype)
    for i in range(tau.shape[-1]):
        v = jnp.where(jnp.arange(m) < i, 0.0,
                      jnp.where(jnp.arange(m) == i, 1.0, x[..., i]))
        Q = Q - tau[..., i] * (Q @ v)[..., None] * v[None, :].conj()
    return Q[..., :n]


# ---- round-3 long tail (VERDICT r2 #7) -------------------------------------

def vector_norm(x, ord=2, axis=None, keepdim=False):
    return jnp.linalg.norm(x, ord=ord, axis=axis, keepdims=keepdim)


def matrix_norm(x, ord="fro", axis=(-2, -1), keepdim=False):
    return jnp.linalg.norm(x, ord=ord, axis=axis, keepdims=keepdim)


def vecdot(x, y, axis=-1):
    return jnp.vecdot(x, y, axis=axis)


def cross(x, y, axis=-1):
    return jnp.cross(x, y, axis=axis)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)


def corrcoef(x, rowvar=True):
    return jnp.corrcoef(x, rowvar=rowvar)


def vander(x, n=None, increasing=False):
    return jnp.vander(x, N=n, increasing=increasing)


def solve_triangular(x, y, upper=True, transpose=False, unitriangular=False):
    return jax.scipy.linalg.solve_triangular(
        x, y, lower=not upper, trans=1 if transpose else 0,
        unit_diagonal=unitriangular)


def tensorinv(x, ind=2):
    return jnp.linalg.tensorinv(x, ind=ind)


def tensorsolve(x, y, axes=None):
    return jnp.linalg.tensorsolve(x, y, axes=axes)


def cholesky_inverse(x, upper=False):
    """Inverse of A from its Cholesky factor (paddle.linalg.cholesky_inverse)."""
    x = jnp.asarray(x)
    eye = jnp.eye(x.shape[-1], dtype=x.dtype)
    return jax.scipy.linalg.cho_solve((x, not upper), eye)


def matrix_transpose(x):
    """paddle.linalg.matrix_transpose: swap the last two dims."""
    return jnp.swapaxes(jnp.asarray(x), -2, -1)


def ormqr(x, tau, other, left=True, transpose=False):
    """paddle.linalg.ormqr: multiply `other` by the FULL (m, m) Q of a
    Householder factorization (x, tau) — accumulated reflectors + matmul
    (numerically equivalent; TPU has no LAPACK ormqr fast path).
    Batched over leading dims like the reference."""
    x = jnp.asarray(x)
    tau = jnp.asarray(tau)
    other = jnp.asarray(other)

    def one(xm, tm, om):
        # apply reflectors H_i = I - tau_i v_i v_i^H directly to `other`
        # (O(k·m·n), lax.fori_loop — no (m, m) Q materialized, constant
        # program size). Q = H_0 H_1 ... H_{k-1}; Q @ om applies reflectors
        # last-first, om @ Q (and Q^H @ om) first-last.
        m = xm.shape[0]
        k = tm.shape[0]
        ar = jnp.arange(m)

        def refl(i):
            return jnp.where(ar < i, 0.0,
                             jnp.where(ar == i, 1.0,
                                       jax.lax.dynamic_index_in_dim(
                                           xm, i, 1, keepdims=False)))

        qh = transpose          # Q^H x == conj-transposed application
        if left:
            def body(step, acc):     # acc (m, n)
                i = step if qh else k - 1 - step
                v = refl(i)
                coef = (jnp.conj(tm[i]) if qh else tm[i])
                return acc - coef * v[:, None] * (jnp.conj(v) @ acc)[None, :]
        else:
            def body(step, acc):     # acc (n, m): om @ Q applies first-last
                i = k - 1 - step if qh else step
                v = refl(i)
                coef = (jnp.conj(tm[i]) if qh else tm[i])
                return acc - coef * (acc @ v)[:, None] * jnp.conj(v)[None, :]
        return jax.lax.fori_loop(0, k, body, om)

    if x.ndim == 2:
        return one(x, tau, other)
    batch = x.shape[:-2]
    xf = x.reshape((-1,) + x.shape[-2:])
    tf = tau.reshape((-1,) + tau.shape[-1:])
    of = jnp.broadcast_to(other, batch + other.shape[-2:]).reshape(
        (-1,) + other.shape[-2:])
    out = jax.vmap(one)(xf, tf, of)
    return out.reshape(batch + out.shape[-2:])


def svd_lowrank(x, q=6, niter=2, M=None):
    """paddle.linalg.svd_lowrank: randomized low-rank SVD (Halko et al.
    range finder with `niter` power iterations)."""
    from paddle_tpu.core.rng import next_rng_key
    x = jnp.asarray(x)
    if M is not None:
        x = x - jnp.asarray(M)
    m, n = x.shape[-2:]
    q = min(q, m, n)
    g = jax.random.normal(next_rng_key(), x.shape[:-2] + (n, q), x.dtype)
    y = jnp.matmul(x, g)
    for _ in range(niter):
        y = jnp.matmul(x, jnp.matmul(jnp.swapaxes(x, -2, -1), y))
    qmat, _ = jnp.linalg.qr(y)
    b = jnp.matmul(jnp.swapaxes(qmat, -2, -1), x)
    u, s, vh = jnp.linalg.svd(b, full_matrices=False)
    return jnp.matmul(qmat, u), s, jnp.swapaxes(vh, -2, -1)


def pca_lowrank(x, q=None, center=True, niter=2):
    """paddle.linalg.pca_lowrank: PCA via randomized SVD."""
    x = jnp.asarray(x)
    m, n = x.shape[-2:]
    if q is None:
        q = min(6, m, n)
    if center:
        x = x - jnp.mean(x, axis=-2, keepdims=True)
    return svd_lowrank(x, q=q, niter=niter)
