"""Stacked-weight Llama inference engine — 7B-class serving on one chip.

Reference: the fused_multi_transformer serving stack
(paddle/phi/kernels/fusion/gpu/fused_multi_transformer_op.cu +
fused_multi_transformer_int8, SURVEY.md §2.2 fusion + §2.4 inference) is
how the reference serves 7B-class checkpoints: one weight image in the
fused kernel's layout, consumed by both context (prefill) and decode.

The nn.Layer `generate()` path stacks per-layer weights into the fused
kernel's (L, ...) layout *inside* the jitted program, so both copies are
live at the stack boundary — fine at 1B, impossible for Llama-2-7B int8
(2 × 6.6 GiB) on a 16 GiB v5e. This engine owns ONE stacked copy:

* prefill is a `lax.scan` over the layer dim reading the same stacks the
  decode kernel streams (the standard TPU big-model shape — scan over
  layers, static shapes, weights dequantized per layer inside the scan);
* decode rides `ops.fused_decode` with the `decode_block_plan` that also
  sized the stacks (qkv column split + padded FFN blocks at 7B scale);
* `from_config` materializes random int8/bf16 weights host-side straight
  into the stacked layout (benchmarking; never two copies), and
  `from_state_dict` imports a per-layer checkpoint state layer by layer.
"""

import logging
import math
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from paddle_tpu.ops import fused_decode as fd
from paddle_tpu.ops.rope import rope_cos_sin

__all__ = ["StackedLlamaDecoder"]

logger = logging.getLogger("paddle_tpu.inference")


class StackedLlamaDecoder:
    """Inference-only Llama with parameters in the fused kernel's stacked
    layout. `params` follows `build_fused_params` naming ({ln1, wqkv, wo,
    ln2, wg, wu, wd} (+ `*_s` int8 scales)); `embed_w` (vocab, h) bf16;
    `head` either ("tied",), ("dense", w) or ("int8", q, scale)."""

    def __init__(self, cfg, params: Dict[str, jax.Array], embed_w, norm_w,
                 head, blocks: Optional[Dict] = None):
        self.cfg = cfg
        self.params = params
        self.embed_w = embed_w
        self.norm_w = norm_w
        self.head = head
        int8 = "wqkv_s" in params
        hd = cfg.head_dim
        dq = cfg.num_heads * hd
        self.blocks = blocks or fd.decode_block_plan(
            cfg.hidden_size, dq + 2 * cfg.kv_heads * hd, dq, hd,
            cfg.intermediate_size, wbytes=1 if int8 else 2)
        self._jit_cache = {}

    # -- constructors ------------------------------------------------------

    @classmethod
    def from_config(cls, cfg, *, int8: bool = True, seed: int = 0,
                    dtype=jnp.bfloat16):
        """Random weights, materialized ON DEVICE directly in the stacked
        layout via jax.random (no host->device transfer — materializing
        Llama-2-7B through a remote-TPU tunnel host-side takes tens of
        minutes; on-device it is seconds) and never held twice."""
        # tpu-lint: allow(rng-stream): weight-init stream, not request
        # sampling — request draws fold per-request seeds (PR 5)
        key = jax.random.PRNGKey(seed)
        L, h, ffn = cfg.num_layers, cfg.hidden_size, cfg.intermediate_size
        hd = cfg.head_dim
        dq, dkv = cfg.num_heads * hd, cfg.kv_heads * hd
        dqkv = dq + 2 * dkv
        blocks = fd.decode_block_plan(h, dqkv, dq, hd, ffn,
                                      wbytes=1 if int8 else 2)
        fp = blocks["ffn_pad"]
        sd = cfg.initializer_range

        def nxt():
            nonlocal key
            # tpu-lint: allow(rng-stream): weight-init stream fork
            key, sub = jax.random.split(key)
            return sub

        def w(*shape, pad_axis=None, pad_to=0):
            if int8:
                # tpu-lint: allow(rng-stream): weight-init draw
                a = jax.random.randint(nxt(), shape, -127, 128,
                                       dtype=jnp.int8)
            else:
                # tpu-lint: allow(rng-stream): weight-init draw
                a = (jax.random.normal(nxt(), shape, jnp.float32)
                     * sd).astype(dtype)
            if pad_axis is not None and pad_to > shape[pad_axis]:
                widths = [(0, 0)] * a.ndim
                widths[pad_axis] = (0, pad_to - shape[pad_axis])
                a = jnp.pad(a, widths)
            return a

        def sc(n, pad_to=0):
            a = jnp.full((L, 1, n), sd / 127.0, jnp.float32)
            if pad_to > n:
                a = jnp.pad(a, ((0, 0), (0, 0), (0, pad_to - n)),
                            constant_values=1.0)
            return a

        params = {
            "ln1": jnp.ones((L, h), dtype),
            "ln2": jnp.ones((L, h), dtype),
            "wqkv": w(L, h, dqkv),
            "wo": w(L, dq, h),
            "wg": w(L, h, ffn, pad_axis=2, pad_to=fp),
            "wu": w(L, h, ffn, pad_axis=2, pad_to=fp),
            "wd": w(L, ffn, h, pad_axis=1, pad_to=fp),
        }
        if int8:
            params.update(wqkv_s=sc(dqkv), wo_s=sc(h), wg_s=sc(ffn, fp),
                          wu_s=sc(ffn, fp), wd_s=sc(h))
        # tpu-lint: allow(rng-stream): weight-init draw
        embed_w = (jax.random.normal(nxt(), (cfg.vocab_size, h),
                                     jnp.float32) * sd).astype(dtype)
        norm_w = jnp.ones((h,), dtype)
        if cfg.tie_word_embeddings:
            head = ("tied",)
        elif int8:
            # tpu-lint: allow(rng-stream): weight-init draw
            head = ("int8",
                    jax.random.randint(nxt(), (h, cfg.vocab_size), -127,
                                       128, dtype=jnp.int8),
                    jnp.full((cfg.vocab_size,), sd / 127.0, jnp.float32))
        else:
            # tpu-lint: allow(rng-stream): weight-init draw
            head = ("dense",
                    (jax.random.normal(nxt(), (h, cfg.vocab_size),
                                       jnp.float32) * sd).astype(dtype))
        return cls(cfg, params, embed_w, norm_w, head, blocks)

    @classmethod
    def from_state_dict(cls, cfg, state: Dict[str, jax.Array]):
        """Import a per-layer LlamaForCausalLM state dict (bf16 or
        weight-only-int8 — paddle_tpu.quantization naming)."""
        int8 = "model.layers.0.self_attn.q_proj.weight_q" in state
        hd = cfg.head_dim
        dq = cfg.num_heads * hd
        blocks = fd.decode_block_plan(
            cfg.hidden_size, dq + 2 * cfg.kv_heads * hd, dq, hd,
            cfg.intermediate_size, wbytes=1 if int8 else 2)
        params = fd.build_fused_params(state, cfg.num_layers,
                                       ffn_pad=blocks["ffn_pad"])
        if cfg.tie_word_embeddings:
            head = ("tied",)
        elif int8 and "lm_head.weight_q" in state:
            head = ("int8", state["lm_head.weight_q"],
                    state["lm_head.weight_scale"])
        else:
            head = ("dense", state["lm_head.weight"])
        return cls(cfg, params, state["model.embed_tokens.weight"],
                   state["model.norm.weight"], head, blocks)

    # -- forward pieces ----------------------------------------------------

    def _head_logits(self, xn, embed_w=None, head_arrays=None):
        """head_arrays/embed_w default to self.* for eager use; the jitted
        generate passes them as traced args (baking the ~400 MB 7B
        embed+lm_head into the executable as constants would hold a second
        on-device copy)."""
        kind = self.head[0]
        if kind == "tied":
            from paddle_tpu.ops import tied_unembed
            ew = self.embed_w if embed_w is None else embed_w
            return tied_unembed(xn, ew)
        ha = tuple(self.head[1:]) if head_arrays is None else head_arrays
        if kind == "int8":
            q, s = ha
            y = jnp.dot(xn, q.astype(xn.dtype),
                        preferred_element_type=jnp.float32)
            return y * s
        return jnp.dot(xn, ha[0])

    def _final_norm(self, x, norm_w=None):
        w = self.norm_w if norm_w is None else norm_w
        return _rms_np(x, w, self.cfg.rms_norm_eps, w.dtype)

    def prefill(self, params, ids, total: int, cache_dtype=jnp.bfloat16,
                embed_w=None):
        """Full-prompt forward as a lax.scan over the layer dim. Returns
        (last-position hidden (b, h) fp32, kv cache (L, b, total, 2*dkv))."""
        cfg = self.cfg
        b, s = ids.shape
        h, hd = cfg.hidden_size, cfg.head_dim
        nh, nkv = cfg.num_heads, cfg.kv_heads
        rep = nh // nkv
        dq, dkv = nh * hd, nkv * hd
        eps = cfg.rms_norm_eps
        int8 = "wqkv_s" in params
        dtype = self.embed_w.dtype
        scale = 1.0 / math.sqrt(hd)
        cos, sin = rope_cos_sin(s, hd, base=cfg.rope_base)
        cos = cos[None, :, None, :].astype(jnp.float32)
        sin = sin[None, :, None, :].astype(jnp.float32)

        def rope(t):                       # (b, s, n, hd)
            half = t.shape[-1] // 2
            rot = jnp.concatenate([-t[..., half:], t[..., :half]], axis=-1)
            return t * cos + rot * sin

        def mm(act, wl, sl):
            y = jnp.dot(act, wl.astype(act.dtype),
                        preferred_element_type=jnp.float32)
            return y * sl if sl is not None else y

        causal = jnp.tril(jnp.ones((s, s), bool))

        def layer(xf, wl):
            xn = _rms_np(xf, wl["ln1"], eps, dtype)
            qkv = mm(xn, wl["wqkv"], wl.get("wqkv_s"))
            q = rope(qkv[..., :dq].reshape(b, s, nh, hd))
            k = rope(qkv[..., dq:dq + dkv].reshape(b, s, nkv, hd))
            v = qkv[..., dq + dkv:].reshape(b, s, nkv, hd)
            qg = q.reshape(b, s, nkv, rep, hd) * scale
            sc_ = jnp.einsum("bsgrd,btgd->bgrst", qg, k)
            sc_ = jnp.where(causal[None, None, None], sc_, fd.NEG_INF)
            pr = jax.nn.softmax(sc_, axis=-1)
            at = jnp.einsum("bgrst,btgd->bsgrd", pr, v)
            o = mm(at.reshape(b, s, dq).astype(dtype), wl["wo"],
                   wl.get("wo_s"))
            xf = xf + o
            xn2 = _rms_np(xf, wl["ln2"], eps, dtype)
            g = mm(xn2, wl["wg"], wl.get("wg_s"))
            u = mm(xn2, wl["wu"], wl.get("wu_s"))
            act = (jax.nn.silu(g) * u).astype(dtype)
            xf = xf + mm(act, wl["wd"], wl.get("wd_s"))
            kflat = jnp.concatenate(
                [k.reshape(b, s, dkv), v.reshape(b, s, dkv)],
                axis=-1).astype(cache_dtype)
            return xf, kflat

        x = jnp.take(self.embed_w if embed_w is None else embed_w, ids,
                     axis=0).astype(jnp.float32)
        keys = [k for k in ("ln1", "wqkv", "wqkv_s", "wo", "wo_s", "ln2",
                            "wg", "wg_s", "wu", "wu_s", "wd", "wd_s")
                if k in params]
        stacks = {k: params[k] for k in keys}
        x, kv = lax.scan(lambda c, wl: layer(c, wl), x, stacks)
        kv = jnp.pad(kv, ((0, 0), (0, 0), (0, total - s), (0, 0)))
        return x[:, -1], kv

    # -- generation --------------------------------------------------------

    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, seed: int = 0,
                 cache_dtype=jnp.bfloat16,
                 deadline_s: Optional[float] = None, request_seeds=None,
                 _kv_chunk: int = 0):
        """Prefill + fused-kernel decode, the whole loop one jitted scan.
        Returns (b, prompt+new) ids including the prompt.

        cache_dtype=jnp.int8 decodes against an int8 KV cache: prefill
        runs bf16 (the calibration pass), the cache is quantized with
        per-(layer, kv-head) scales (ops.fused_decode.quantize_kv_cache)
        and the fused kernel streams int8 KV chunks — halving the
        per-step cache DMA, the long-context (s >= 2048) decode regime
        where cache bytes dominate the roofline.

        Resilience (see inference.generate): ``deadline_s`` runs the
        request as chunked decode programs and returns early at the
        budget; accelerator OOM retries ONCE with a halved KV chunk
        (``resilience.decode_degraded{stage=halved_chunk}``) — this
        engine has no layered fallback (the stacked weights ARE the
        fused layout), so a second OOM propagates.

        Sampling rides per-request RNG streams (see inference.generate):
        row r draws token t from fold_in(PRNGKey(request_seeds[r]), t),
        default seeds ``seed + r`` — batch-composition-invariant."""
        from paddle_tpu import observability as obs
        from paddle_tpu.inference import (_fold_rows, _request_seeds,
                                          _row_keys, _sample_logits)

        input_ids = jnp.asarray(input_ids)
        b, prompt_len = input_ids.shape
        total = -(-(prompt_len + max_new_tokens) // 128) * 128
        cfg = self.cfg
        kv_int8 = jnp.dtype(cache_dtype) == jnp.int8
        if not kv_int8 and jnp.dtype(cache_dtype).itemsize != 2:
            raise ValueError(
                "StackedLlamaDecoder decodes against a bf16 or int8 KV "
                f"cache; got cache_dtype={jnp.dtype(cache_dtype).name}")
        seeds0 = _request_seeds(request_seeds, seed, b)
        tracer = obs.active_tracer()
        if tracer is None and deadline_s is not None:
            # deadline checks happen at chunk boundaries — ride the split
            # programs under a local, un-attached tracer
            tracer = obs.Tracer()
        jk = (b, prompt_len, max_new_tokens, float(temperature), int(top_k),
              float(top_p), jnp.dtype(cache_dtype).name, int(_kv_chunk))
        run = self._jit_cache.get(jk)
        traced_fns = self._jit_cache.get(jk + ("traced",))
        if (run is None if tracer is None else traced_fns is None):
            cos_tab, sin_tab = rope_cos_sin(total, cfg.head_dim,
                                            base=cfg.rope_base)
            blocks = (dict(self.blocks, cache_wbytes=1) if kv_int8
                      else self.blocks)

            def logits(x, embed_w, norm_w, head_arrays):
                return self._head_logits(
                    self._final_norm(x, norm_w), embed_w, head_arrays)

            def _prefill_impl(params, embed_w, norm_w, head_arrays, ids,
                              seeds):
                with jax.named_scope("decode.prefill"):
                    x, kv = self.prefill(
                        params, ids, total,
                        jnp.bfloat16 if kv_int8 else cache_dtype,
                        embed_w=embed_w)
                if kv_int8:
                    with jax.named_scope("decode.cache_quantize"):
                        kv, kv_scales = fd.quantize_kv_cache(kv,
                                                             cfg.kv_heads)
                else:
                    kv_scales = None
                keys = _row_keys(seeds)
                with jax.named_scope("decode.sample"):
                    tok = _sample_logits(
                        logits(x, embed_w, norm_w, head_arrays),
                        _fold_rows(keys, 0), temperature, top_k, top_p)
                return (tok, kv, keys), kv_scales

            def _decode_impl(params, embed_w, norm_w, head_arrays, carry,
                             kv_scales, i0, nsteps):
                def step(carry, i):
                    tok, kv, keys = carry
                    ki = _fold_rows(keys, i)
                    pos = prompt_len + i - 1
                    x = jnp.take(embed_w, tok, axis=0)
                    cos = lax.dynamic_slice_in_dim(cos_tab, pos, 1, axis=0)
                    sin = lax.dynamic_slice_in_dim(sin_tab, pos, 1, axis=0)
                    x, kv = fd.fused_decode_step(
                        x, params, kv, pos, cos, sin,
                        num_heads=cfg.num_heads, num_kv_heads=cfg.kv_heads,
                        eps=cfg.rms_norm_eps, rope_base=cfg.rope_base,
                        blocks=blocks, kv_scales=kv_scales,
                        kv_chunk=_kv_chunk)
                    with jax.named_scope("decode.sample"):
                        nxt = _sample_logits(
                            logits(x, embed_w, norm_w, head_arrays), ki,
                            temperature, top_k, top_p)
                    return (nxt, kv, keys), nxt

                return lax.scan(step, carry, i0 + jnp.arange(nsteps))

            if tracer is None:
                def run_impl(params, embed_w, norm_w, head_arrays, ids,
                             key):
                    carry, kv_scales = _prefill_impl(
                        params, embed_w, norm_w, head_arrays, ids, key)
                    tok = carry[0]
                    carry, toks = _decode_impl(
                        params, embed_w, norm_w, head_arrays, carry,
                        kv_scales, 1, max_new_tokens - 1)
                    return jnp.concatenate([tok[:, None], toks.T], axis=1)

                run = jax.jit(run_impl)
                self._jit_cache[jk] = run
            else:
                # donate the KV carry across chunk dispatches (see
                # inference.carry_donate_argnums: avoids a full-cache
                # copy per chunk on accelerators; CPU gated off)
                from paddle_tpu.inference import carry_donate_argnums
                traced_fns = (
                    jax.jit(_prefill_impl),
                    jax.jit(_decode_impl, static_argnums=(7,),
                            donate_argnums=carry_donate_argnums(4)))
                self._jit_cache[jk + ("traced",)] = traced_fns

        head_arrays = tuple(self.head[1:])
        from paddle_tpu.resilience import faults as _faults
        from paddle_tpu.resilience import (is_resource_exhausted,
                                           record_event,
                                           remaining_deadline)

        import time as _time
        t_request = _time.perf_counter()
        try:
            _faults.maybe_fire("decode.dispatch")
            if tracer is None:
                new = run(self.params, self.embed_w, self.norm_w,
                          head_arrays, input_ids, seeds0)
            else:
                dkv = cfg.kv_heads * cfg.head_dim
                itemsize = 1 if kv_int8 else jnp.dtype(cache_dtype).itemsize
                kv_cache_bytes = (cfg.num_layers * b * total * 2 * dkv
                                  * itemsize)
                avg_len = min(prompt_len + max_new_tokens / 2.0, total)
                pf, dc = traced_fns
                pieces = obs.run_traced_decode(
                    tracer,
                    lambda: pf(self.params, self.embed_w, self.norm_w,
                               head_arrays, input_ids, seeds0),
                    lambda carry, aux, i0, c: dc(
                        self.params, self.embed_w, self.norm_w, head_arrays,
                        carry, aux, i0, c),
                    batch=b, max_new_tokens=max_new_tokens,
                    deadline_s=deadline_s,
                    attrs=dict(
                        arch="llama-stacked", fused=True,
                        prompt_len=prompt_len,
                        kv_cache_dtype=jnp.dtype(cache_dtype).name,
                        kv_cache_bytes=int(kv_cache_bytes),
                        kv_bytes_per_step=int(kv_cache_bytes * avg_len
                                              / total)))
                new = jnp.concatenate(pieces, axis=1)
        except Exception as e:  # noqa: BLE001 — filtered by class below
            if not (is_resource_exhausted(e) and _kv_chunk == 0):
                raise
            record_event("decode_degraded", stage="halved_chunk")
            logger.warning(
                "stacked decode OOM (%s); retrying with a reduced KV chunk",
                e)
            # retry rungs inherit the REMAINING request budget; 32 sits
            # strictly below every auto-picked chunk (64/128), so the
            # retry is never a recompile of the config that just OOM'd
            remaining = remaining_deadline(deadline_s, t_request)
            return self.generate(
                input_ids, max_new_tokens=max_new_tokens,
                temperature=temperature, top_k=top_k, top_p=top_p,
                seed=seed, cache_dtype=cache_dtype, deadline_s=remaining,
                request_seeds=request_seeds, _kv_chunk=32)
        return jnp.concatenate([input_ids, new], axis=1)

    def num_params(self):
        """True (unpadded) parameter count — roofline accounting."""
        cfg = self.cfg
        h, ffn, hd = cfg.hidden_size, cfg.intermediate_size, cfg.head_dim
        dq, dkv = cfg.num_heads * hd, cfg.kv_heads * hd
        per_layer = 2 * h + h * (dq + 2 * dkv) + dq * h + 3 * h * ffn
        n = cfg.vocab_size * h + cfg.num_layers * per_layer + h
        if not cfg.tie_word_embeddings:
            n += h * cfg.vocab_size
        return n


def _rms_np(x, w, eps, dtype):
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    return (xf * lax.rsqrt(var + eps)).astype(dtype) * w
