"""Inference: jitted KV-cache decoding + Predictor veneer.

Reference (SURVEY.md §2.4-inference, §2.2-fusion): AnalysisPredictor loads a
saved program and runs IR-optimized inference; generation-time decode rides
the fused_multi_transformer / masked_multihead_attention CUDA kernels.

TPU-native: the whole decode step (all layers, cache update, sampling) is
ONE jitted program with donated cache buffers — XLA fuses what
fused_multi_transformer hand-fuses; there is no separate "optimized
program" artifact because jit compilation IS the optimization pass.
"""

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.nn.layer import functional_call


def _sample_logits(logits, key, temperature=1.0, top_k=0, top_p=1.0):
    """logits (b, vocab) → token ids (b,). Greedy when temperature == 0."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits.astype(jnp.float32) / temperature
    if top_k:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest set with cumulative prob >= top_p
        cutoff_idx = jnp.sum(cum < top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1)


def generate(model, input_ids, max_new_tokens=32, temperature=0.0, top_k=0,
             top_p=1.0, eos_token_id: Optional[int] = None, seed: int = 0,
             state: Optional[Dict] = None, cache_dtype=jnp.bfloat16):
    """Autoregressive generation with a preallocated KV cache.

    model must expose forward(ids, cache=..., start_pos=...) and
    init_cache(batch, max_len) (LlamaForCausalLM-style). Returns
    (b, prompt+new) token ids including the prompt.
    """
    input_ids = jnp.asarray(input_ids)
    b, prompt_len = input_ids.shape
    total = prompt_len + max_new_tokens
    state = state if state is not None else model.trainable_state()
    cache = model.init_cache(b, total, dtype=cache_dtype)

    @jax.jit
    def prefill(state, cache, ids):
        out, cache = functional_call(model, state, ids, cache=cache,
                                     start_pos=0)
        return out[:, -1, :], cache

    @jax.jit
    def decode_step(state, cache, tok, pos, key):
        out, cache = functional_call(model, state, tok[:, None], cache=cache,
                                     start_pos=pos)
        nxt = _sample_logits(out[:, -1, :], key, temperature, top_k, top_p)
        return nxt, cache

    logits, cache = prefill(state, cache, input_ids)
    key = jax.random.PRNGKey(seed)
    key, k0 = jax.random.split(key)
    tok = _sample_logits(logits, k0, temperature, top_k, top_p)

    out_tokens = [tok]
    finished = np.zeros((b,), bool)
    for i in range(1, max_new_tokens):
        if eos_token_id is not None:
            finished |= np.asarray(tok) == eos_token_id
            if finished.all():
                break
        key, ki = jax.random.split(key)
        tok, cache = decode_step(state, cache, tok, prompt_len + i - 1, ki)
        out_tokens.append(tok)

    return jnp.concatenate([input_ids] + [t[:, None] for t in out_tokens],
                           axis=1)


class Predictor:
    """AnalysisPredictor parity: load a saved model + config, run jitted
    batched forward."""

    def __init__(self, model, state: Optional[Dict] = None):
        self.model = model
        self.state = state if state is not None else model.trainable_state()
        self._fwd = jax.jit(
            lambda st, *args, **kw: functional_call(model, st, *args, **kw))

    @classmethod
    def from_checkpoint(cls, model, path):
        from paddle_tpu.framework.io import load
        sd = load(path)
        model.set_state_dict(sd)
        return cls(model)

    def run(self, *args, **kwargs):
        return self._fwd(self.state, *args, **kwargs)

    __call__ = run

    def generate(self, input_ids, **kwargs):
        return generate(self.model, input_ids, state=self.state, **kwargs)
