"""Inference: jitted KV-cache decoding + Predictor veneer.

Reference (SURVEY.md §2.4-inference, §2.2-fusion): AnalysisPredictor loads a
saved program and runs IR-optimized inference; generation-time decode rides
the fused_multi_transformer / masked_multihead_attention CUDA kernels.

TPU-native: the whole decode step (all layers, cache update, sampling) is
ONE jitted program — XLA fuses what fused_multi_transformer hand-fuses;
there is no separate "optimized program" artifact because jit compilation
IS the optimization pass.
"""

import logging
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from paddle_tpu.nn.layer import functional_call

logger = logging.getLogger("paddle_tpu.inference")


def _inference_state(model):
    """ALL named parameters, not just trainable ones — a quantized model's
    int8 weights are trainable=False and must still be bound (otherwise
    jit bakes them into the program as constants)."""
    return model.state_dict(include_buffers=False)


def _greedy_argmax(logits):
    """Two-stage argmax over the vocab dim. XLA lowers a flat argmax over
    ~50K lanes to an iota+reduce running at ~11 GB/s (0.15 ms/step in the
    r5 decode profile); reducing lane-blocks first then the tiny block
    axis is ~50x faster. First-occurrence tie-breaking matches
    jnp.argmax: the first block holding the global max wins, then the
    first lane within it."""
    v = logits.shape[-1]
    if v % 128 or v < 4096:
        return jnp.argmax(logits, axis=-1)
    lb = logits.reshape(logits.shape[:-1] + (v // 128, 128))
    bmax = jnp.max(lb, axis=-1)
    bidx = jnp.argmax(lb, axis=-1).astype(jnp.int32)     # (b, v/128)
    blk = jnp.argmax(bmax, axis=-1).astype(jnp.int32)    # (b,)
    lane = jnp.take_along_axis(bidx, blk[..., None], axis=-1)[..., 0]
    return blk * 128 + lane


def _filter_logits(logits, top_k=0, top_p=1.0):
    """Apply top-k / nucleus (top-p) filtering to (b, vocab) fp32 logits.

    The top-p cutoff is RANK-based: the kept set is exactly the smallest
    prefix of the (stable) descending sort whose cumulative probability
    reaches top_p. A value-based cutoff (`logits < cutoff`) would retain
    every logit EQUAL to the boundary value, overshooting the nucleus
    whenever duplicates straddle it (pinned by tests/test_serving.py)."""
    if top_k:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        order = jnp.argsort(-logits, axis=-1)        # stable: ties keep
        sorted_logits = jnp.take_along_axis(logits, order, axis=-1)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep rank i iff the mass BEFORE it is < top_p — the smallest
        # prefix with cum >= top_p; rank 0 is kept unconditionally (its
        # prior mass is 0, but `0.0 < 0.0` is False at top_p == 0.0 and
        # an all-masked row would sample token id 0); scatter the rank
        # mask back to vocab order
        keep_sorted = ((cum - probs) < top_p).at[..., 0].set(True)
        inv = jnp.argsort(order, axis=-1)
        keep = jnp.take_along_axis(keep_sorted, inv, axis=-1)
        logits = jnp.where(keep, logits, -jnp.inf)
    return logits


def _sample_logits(logits, key, temperature=1.0, top_k=0, top_p=1.0):
    """logits (b, vocab) → token ids (b,). Greedy when temperature == 0.

    ``key`` is either one PRNG key — a shared gumbel stream over the
    batch — or a (b, 2) batch of per-ROW keys (the per-request streams
    `generate` builds from ``request_seeds``), sampled row-by-row so a
    request's tokens don't depend on its batch neighbours."""
    if temperature == 0.0:
        return _greedy_argmax(logits)
    logits = _filter_logits(logits.astype(jnp.float32) / temperature,
                            top_k, top_p)
    if key.ndim > 1:                 # per-request streams
        return jax.vmap(
            lambda k, lg: jax.random.categorical(k, lg))(key, logits)
    return jax.random.categorical(key, logits, axis=-1)


def _row_keys(seeds):
    """(b,) request seeds → (b, 2) per-row base PRNG keys."""
    # tpu-lint: allow(rng-stream): THE sanctioned base-key builder —
    # every request-serving draw folds a token index into these keys
    return jax.vmap(jax.random.PRNGKey)(seeds)


def _fold_rows(keys, t):
    """Fold token index t into each row's base key: the key that samples
    token t of every request, whatever batch it currently rides in."""
    return jax.vmap(jax.random.fold_in, in_axes=(0, None))(keys, t)


def carry_donate_argnums(*argnums):
    """``donate_argnums`` for a chunked-decode KV carry: the given
    argnums on accelerators, ``()`` on the CPU backend (jax-0.4 CPU
    executes donation as a defensive copy per chunk — the BENCH_r06
    capacity caveat — and older jaxlibs warn per program; the TPU path
    aliases the carry away, which ``analysis.runtime.donation_report``
    makes checkable). ONE definition shared by `generate`'s traced
    chunk programs and the stacked decoder's — and the spelling the
    ``donation`` lint rule recognizes as a sanctioned conditional
    donation (docs/ANALYSIS.md §donation)."""
    return tuple(argnums) if jax.default_backend() != "cpu" else ()


def resident_carry_donate_argnums(*argnums):
    """``donate_argnums`` for a RESIDENT fixed-shape carry — the
    serving engine's fused-tick buffers (the paged KV pool, the
    chunked-prefill KV carry, the ngram history): donated on EVERY
    backend, unlike :func:`carry_donate_argnums`.

    The distinction is shape growth vs shape identity. `generate`'s
    traced chunk carry GROWS per chunk (input and output shapes
    differ), so CPU donation buys nothing and jax-0.4 warns per
    program — hence the conditional helper above. A resident carry is
    RMW'd in place (``dynamic_update_slice`` at a static cursor; input
    shape == output shape), the caller rebinds it from the program
    output every tick, and the compiled module's ``input_output_alias``
    table records the aliasing on every backend —
    ``analysis.runtime.donation_report`` pins it
    (tests/test_analysis.py), and the ``donation`` lint rule reads
    argnums through this spelling like any ``*_donate_argnums``
    helper. jax-0.4 CPU still executes the alias as a copy (the
    SCALE.md §Donation aliasing caveat; the v5e re-measure removes
    it), but the declaration is what makes the TPU path — and the
    pin — real."""
    return tuple(argnums)


def _request_seeds(request_seeds, seed, b):
    """(b,) uint32 per-request seeds — explicit streams, or the default
    ``seed + row`` convention. ONE definition: `generate`, the stacked
    decoder and the serving engine must agree on the default or the
    engine-vs-isolated sampling parity contract silently breaks."""
    s = (jnp.asarray(request_seeds, jnp.uint32)
         if request_seeds is not None
         else jnp.uint32(seed) + jnp.arange(b, dtype=jnp.uint32))
    assert s.shape == (b,), f"request_seeds must be ({b},), got {s.shape}"
    return s


def generate(model, input_ids, max_new_tokens=32, temperature=0.0, top_k=0,
             top_p=1.0, eos_token_id: Optional[int] = None, seed: int = 0,
             state: Optional[Dict] = None, cache_dtype=jnp.bfloat16,
             deadline_s: Optional[float] = None,
             request_seeds=None, return_lengths: bool = False,
             _kv_chunk: int = 0, _force_layered: bool = False):
    """Autoregressive generation with a preallocated KV cache.

    model must expose forward(ids, cache=..., start_pos=...) and
    init_cache(batch, max_len) (LlamaForCausalLM-style). Returns
    (b, prompt+new) token ids including the prompt.

    The whole decode loop runs as ONE jitted lax.scan (a single device
    dispatch — the fused_multi_transformer-style decode path); after an eos
    every subsequent token of that row is emitted as eos.

    cache_dtype=jnp.int8 enables the int8 KV-cache decode mode (the
    fused_multi_transformer_int8 cache_kv quant analog): prefill runs in
    bf16 and acts as the calibration pass, the stacked cache is quantized
    with per-(layer, kv-head) scales, and every decode step streams int8
    KV + dequantizes on the compute path. Requires the fused decode plan
    (llama, gpt and moe archs).

    Resilience (paddle_tpu.resilience; docs/RESILIENCE.md):

    * ``deadline_s`` — per-request wall-clock budget. The request runs
      as a prefill + chunked-decode program pair (the traced-decode
      machinery) so the deadline is checked at chunk boundaries; on
      expiry the tokens produced so far come back (≥ 1) and
      ``resilience.deadline_exceeded`` increments. ``None`` (default)
      keeps the single-dispatch program untouched.
    * Accelerator OOM (RESOURCE_EXHAUSTED) triggers the degradation
      ladder: retry with a HALVED KV chunk (less VMEM scratch), then
      fall back to the layered (non-fused) decode path; each rung
      increments ``resilience.decode_degraded{stage=...}``. An int8
      cache stops at the halved-chunk rung (the layered path cannot
      stream a quantized cache — and a bf16 refill would only grow the
      footprint that just OOM'd). ``_kv_chunk``/``_force_layered`` are
      the ladder's internal knobs, not API.

    With no fault plan armed and no deadline, the request takes the
    exact code path it always did — bit-identical tokens, no added
    dispatches (pinned by tests/test_resilience.py).

    Sampling uses PER-REQUEST RNG streams: row r draws token t from
    ``fold_in(PRNGKey(request_seeds[r]), t)`` (default seeds
    ``seed + r``), so a request's sampled tokens are invariant to its
    batch composition — the property the continuous-batching engine
    (paddle_tpu.serving) needs for join/leave parity with isolated
    calls. ``return_lengths=True`` additionally returns the per-row
    generated length (tokens before the first eos) as an int32 numpy
    array — slot-free accounting for serving, pad-waste accounting for
    decode_bench — as ``(ids, lengths)``.
    """
    from paddle_tpu.core.flags import flag

    input_ids = jnp.asarray(input_ids)
    b, prompt_len = input_ids.shape
    total = prompt_len + max_new_tokens
    state = state if state is not None else _inference_state(model)
    kv_int8 = jnp.dtype(cache_dtype) == jnp.int8
    # fused decode path (ops.fused_decode, the fused_multi_transformer
    # analog): whole decoder stack per step in one Pallas call on TPU /
    # one stacked jnp program elsewhere. The cache length is padded to the
    # kernel's 128-token chunk size (attention masks the tail either way).
    plan = (model.fused_decode_plan(state, probe=True)
            if flag("FLAGS_fused_decode") and not _force_layered
            and hasattr(model, "fused_decode_plan") else None)
    if plan is not None and b > plan.get("max_batch", b):
        plan = None     # e.g. MoE no-drop bound b ≤ per-expert capacity
    if plan is not None and not kv_int8 \
            and jnp.dtype(cache_dtype).itemsize != 2:
        # the fused kernel's cache layouts are 2-byte (bf16) or int8; an
        # fp32 cache would trip the kernel's cache_wbytes contract check
        # on a kernel-eligible config — ride the layered path instead
        plan = None
    if kv_int8 and plan is None:
        raise ValueError(
            "cache_dtype=int8 requires the fused decode path (an eligible "
            "fused_decode_plan); this model/config cannot ride it")
    if plan is not None:
        total = -(-total // 128) * 128
    # int8 mode prefills through the layered path in bf16 (the
    # calibration pass); the cache is quantized after stacking.
    # The cache is created INSIDE the prefill program (matching the
    # serving engine's wave prefill): an eager jnp.zeros here would
    # compile a per-shape zeros program and upload its fill scalar on
    # every call — the exact per-request H2D the dispatch sanitizer
    # (paddle_tpu.analysis.runtime) guards against.
    cache_init_dtype = jnp.bfloat16 if kv_int8 else cache_dtype
    eos = -1 if eos_token_id is None else int(eos_token_id)

    # One decode program per static configuration, cached on the model so
    # repeated generate() calls with the same shapes don't retrace. The KV
    # cache is not donated: the program returns only tokens, so there is no
    # output buffer to alias — XLA frees the cache after its last in-scan
    # use regardless.
    #
    # Telemetry (paddle_tpu.observability): with NO tracer attached the
    # whole request stays the single-dispatch `run` program below — the
    # only added cost is the `active_tracer()` read. With a tracer
    # attached, the SAME prefill/decode impls are compiled as a prefill
    # program + a chunked decode program, so TTFT and per-chunk TPOT are
    # real host-observed measurements; tokens are identical (same step
    # function, split scan).
    from paddle_tpu import observability as obs

    tracer = obs.active_tracer()
    if tracer is None and deadline_s is not None:
        # a deadline needs chunk boundaries to check the clock at: ride
        # the traced split programs (token-identical to the single
        # dispatch) under a local, un-attached tracer
        tracer = obs.Tracer()
    jit_cache = model.__dict__.setdefault("_generate_jit_cache", {})
    jit_key = (b, prompt_len, max_new_tokens, float(temperature),
               int(top_k), float(top_p), eos, jnp.dtype(cache_dtype).name,
               model.training, plan is not None, int(_kv_chunk))
    run = jit_cache.get(jit_key)
    traced_fns = jit_cache.get(jit_key + ("traced",))
    if (run is None if tracer is None else traced_fns is None):
        if plan is not None:
            from paddle_tpu.ops import rope as rope_ops
            from paddle_tpu.ops.fused_decode import (fused_decode_step,
                                                     quantize_kv_cache)

            cos_tab, sin_tab = rope_ops.rope_cos_sin(
                total, plan["head_dim"], base=plan["rope_base"])

            def _prefill_impl(state, ids, seeds):
                # rebuild the plan from the traced state so the stacked
                # weights flow from the `state` argument (not constants)
                plan_t = model.fused_decode_plan(state)
                cache = model.init_cache(b, total, dtype=cache_init_dtype)
                # prefill on the layered path, then stack for the kernel
                with jax.named_scope("decode.prefill"):
                    out, cache = functional_call(model, state, ids,
                                                 cache=cache, start_pos=0)
                    # fused cache layout: combined flat (L, b, S, 2*nkv*hd)
                    kv = jnp.stack([jnp.concatenate(
                        [c["k"].reshape(b, total, -1),
                         c["v"].reshape(b, total, -1)],
                        axis=-1) for c in cache])
                if kv_int8:     # prefill was the calibration pass
                    with jax.named_scope("decode.cache_quantize"):
                        kv, kv_scales = quantize_kv_cache(
                            kv, plan_t["num_kv_heads"])
                else:
                    kv_scales = None
                keys = _row_keys(seeds)
                with jax.named_scope("decode.sample"):
                    tok = _sample_logits(out[:, -1, :], _fold_rows(keys, 0),
                                         temperature, top_k, top_p)
                finished = jnp.zeros((b,), bool)
                return (tok, kv, keys, finished), kv_scales

            def _decode_impl(state, carry, kv_scales, i0, nsteps):
                plan_t = model.fused_decode_plan(state)
                blocks = plan_t.get("blocks")
                if kv_int8 and blocks is not None:
                    blocks = dict(blocks, cache_wbytes=1)

                def step(carry, i):
                    tok, kv, keys, finished = carry
                    finished = finished | (tok == eos)
                    ki = _fold_rows(keys, i)
                    pos = prompt_len + i - 1
                    x = plan_t["embed"](tok, pos)
                    cos = lax.dynamic_slice_in_dim(cos_tab, pos, 1, axis=0)
                    sin = lax.dynamic_slice_in_dim(sin_tab, pos, 1, axis=0)
                    x, kv = fused_decode_step(
                        x, plan_t["params"], kv, pos, cos, sin,
                        num_heads=plan_t["num_heads"],
                        num_kv_heads=plan_t["num_kv_heads"],
                        eps=plan_t["eps"], rope_base=plan_t["rope_base"],
                        arch=plan_t.get("arch", "llama"),
                        top_k=plan_t.get("top_k", 2),
                        blocks=blocks, kv_scales=kv_scales,
                        kv_chunk=_kv_chunk)
                    with jax.named_scope("decode.sample"):
                        nxt = _sample_logits(plan_t["head"](x), ki,
                                             temperature, top_k, top_p)
                    nxt = jnp.where(finished, jnp.full_like(nxt, eos), nxt)
                    return (nxt, kv, keys, finished), nxt

                return lax.scan(step, carry, i0 + jnp.arange(nsteps))
        else:
            def _prefill_impl(state, ids, seeds):
                cache = model.init_cache(b, total, dtype=cache_init_dtype)
                with jax.named_scope("decode.prefill"):
                    out, cache = functional_call(model, state, ids,
                                                 cache=cache, start_pos=0)
                keys = _row_keys(seeds)
                with jax.named_scope("decode.sample"):
                    tok = _sample_logits(out[:, -1, :], _fold_rows(keys, 0),
                                         temperature, top_k, top_p)
                finished = jnp.zeros((b,), bool)
                return (tok, cache, keys, finished), None

            def _decode_impl(state, carry, _aux, i0, nsteps):
                def step(carry, i):
                    tok, cache, keys, finished = carry
                    finished = finished | (tok == eos)
                    ki = _fold_rows(keys, i)
                    out, cache = functional_call(
                        model, state, tok[:, None], cache=cache,
                        start_pos=prompt_len + i - 1)
                    with jax.named_scope("decode.sample"):
                        nxt = _sample_logits(out[:, -1, :], ki, temperature,
                                             top_k, top_p)
                    nxt = jnp.where(finished, jnp.full_like(nxt, eos), nxt)
                    return (nxt, cache, keys, finished), nxt

                return lax.scan(step, carry, i0 + jnp.arange(nsteps))

        if tracer is None:
            def run_impl(state, ids, seeds):
                carry, aux = _prefill_impl(state, ids, seeds)
                tok = carry[0]
                carry, toks = _decode_impl(state, carry, aux, 1,
                                           max_new_tokens - 1)
                return jnp.concatenate([tok[:, None], toks.T], axis=1)

            run = jax.jit(run_impl)
            jit_cache[jit_key] = run
        else:
            # donate the carry across the chunk dispatches so XLA
            # aliases the KV buffer instead of copying it per chunk (a 7B
            # cache copied every 32 tokens would skew the TPOT this mode
            # measures and double peak HBM); carry_donate_argnums gates
            # the CPU backend off
            traced_fns = (
                jax.jit(_prefill_impl),
                jax.jit(_decode_impl, static_argnums=(4,),
                        donate_argnums=carry_donate_argnums(1)))
            jit_cache[jit_key + ("traced",)] = traced_fns

    # per-request RNG streams: row r samples token t from
    # fold_in(PRNGKey(seeds0[r]), t) — batch-composition-invariant
    seeds0 = _request_seeds(request_seeds, seed, b)
    from paddle_tpu.resilience import faults as _faults
    from paddle_tpu.resilience import (is_resource_exhausted, record_event,
                                       remaining_deadline)

    import time as _time
    t_request = _time.perf_counter()
    try:
        # injectable accelerator-OOM site (one global read when disarmed)
        _faults.maybe_fire("decode.dispatch")
        if tracer is None:
            new_tokens = run(state, input_ids, seeds0)
        else:
            # analytic cache accounting for the request span: total
            # allocated KV bytes at the cache dtype, and the avg bytes a
            # decode step streams (cache fill averaged over the window).
            # eval_shape: the cache lives only inside the programs now,
            # so size it abstractly (no allocation, no transfer)
            cache_shapes = jax.eval_shape(
                lambda: model.init_cache(b, total,
                                         dtype=cache_init_dtype))
            leaves = jax.tree_util.tree_leaves(cache_shapes)
            itemsize = 1 if kv_int8 else jnp.dtype(cache_dtype).itemsize
            kv_cache_bytes = int(sum(l.size * itemsize for l in leaves))
            avg_len = min(prompt_len + max_new_tokens / 2.0, total)
            pf, dc = traced_fns
            pieces = obs.run_traced_decode(
                tracer,
                lambda: pf(state, input_ids, seeds0),
                lambda carry, aux, i0, c: dc(state, carry, aux, i0, c),
                batch=b, max_new_tokens=max_new_tokens,
                deadline_s=deadline_s,
                attrs=dict(
                    arch=(plan.get("arch", "llama") if plan is not None
                          else type(model).__name__),
                    fused=plan is not None, prompt_len=prompt_len,
                    kv_cache_dtype=jnp.dtype(cache_dtype).name,
                    kv_cache_bytes=kv_cache_bytes,
                    kv_bytes_per_step=int(kv_cache_bytes * avg_len / total)))
            new_tokens = jnp.concatenate(pieces, axis=1)
    except Exception as e:  # noqa: BLE001 — ladder filters by class below
        if not is_resource_exhausted(e):
            raise
        remaining = remaining_deadline(deadline_s, t_request)
        retry_kw = dict(max_new_tokens=max_new_tokens,
                        temperature=temperature, top_k=top_k, top_p=top_p,
                        eos_token_id=eos_token_id, seed=seed, state=state,
                        cache_dtype=cache_dtype, deadline_s=remaining,
                        request_seeds=request_seeds,
                        return_lengths=return_lengths)
        if plan is not None and _kv_chunk == 0:
            record_event("decode_degraded", stage="halved_chunk")
            logger.warning(
                "decode OOM (%s); retrying with a reduced KV chunk", e)
            # 32 is strictly below every auto-picked chunk (64 in the 7B
            # q-split regime, 128 plain, 256 MoE-int8), so the rung is
            # never a no-op recompile of the configuration that just
            # OOM'd; it always divides the 128-padded cache length
            return generate(model, input_ids, _kv_chunk=32, **retry_kw)
        if plan is not None and not kv_int8:
            record_event("decode_degraded", stage="layered")
            logger.warning(
                "decode OOM persists (%s); falling back to the layered "
                "(non-fused) decode path", e)
            return generate(model, input_ids, _force_layered=True,
                            **retry_kw)
        raise
    if eos_token_id is not None:
        # tpu-lint: allow(host-sync): once-per-request D2H — the eos
        # trim + gen_len accounting need the tokens on host anyway
        arr = np.asarray(new_tokens)
        # per-row generated length: tokens before the first eos
        hit = arr == eos_token_id
        gen_len = np.where(hit.any(axis=1), hit.argmax(axis=1),
                           arr.shape[1]).astype(np.int32)
        # trim columns where every row is already past its eos
        done = np.cumsum(hit, axis=1) > 1
        keep = int((~done.all(axis=0)).sum())
        new_tokens = new_tokens[:, :max(keep, 1)]
    else:
        # no host pull: keep the default path's async dispatch (shapes
        # are static, so gen_len needs no device sync)
        gen_len = np.full(new_tokens.shape[0], new_tokens.shape[1],
                          np.int32)
    out = jnp.concatenate([input_ids, new_tokens], axis=1)
    return (out, gen_len) if return_lengths else out


class Predictor:
    """AnalysisPredictor parity: load a saved model + config, run jitted
    batched forward."""

    def __init__(self, model, state: Optional[Dict] = None):
        self.model = model
        self.state = state if state is not None else _inference_state(model)
        self._fwd = jax.jit(
            lambda st, *args, **kw: functional_call(model, st, *args, **kw))

    @classmethod
    def from_checkpoint(cls, model, path):
        from paddle_tpu.framework.io import load
        sd = load(path)
        model.set_state_dict(sd)
        return cls(model)

    def run(self, *args, **kwargs):
        return self._fwd(self.state, *args, **kwargs)

    __call__ = run

    def generate(self, input_ids, **kwargs):
        return generate(self.model, input_ids, state=self.state, **kwargs)
