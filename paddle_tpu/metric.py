"""paddle.metric parity — streaming evaluation metrics.

Reference: python/paddle/metric/metrics.py (Metric base, Accuracy,
Precision, Recall, Auc) — host-side accumulators updated per batch.
"""

import numpy as np


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        return type(self).__name__.lower()


class Accuracy(Metric):
    """Top-k accuracy. update(pred (N, C) scores, label (N,) or (N, 1))."""

    def __init__(self, topk=(1,), name=None):
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self._name = name
        self.reset()

    def reset(self):
        self.correct = np.zeros(len(self.topk), np.int64)
        self.total = 0

    def update(self, pred, label):
        """Accumulate and return the CURRENT BATCH's accuracy (reference
        semantics: update() is batch-local, accumulate() is the running
        value)."""
        pred = np.asarray(pred)
        label = np.asarray(label).reshape(-1)
        maxk = max(self.topk)
        top = np.argsort(-pred, axis=-1)[:, :maxk]
        match = top == label[:, None]
        batch_correct = np.zeros(len(self.topk), np.int64)
        for i, k in enumerate(self.topk):
            batch_correct[i] = int(match[:, :k].any(axis=1).sum())
        self.correct += batch_correct
        n = label.shape[0]
        self.total += n
        batch_acc = batch_correct / max(n, 1)
        return (float(batch_acc[0]) if len(self.topk) == 1
                else [float(a) for a in batch_acc])

    def accumulate(self):
        acc = self.correct / max(self.total, 1)
        return float(acc[0]) if len(self.topk) == 1 else [float(a) for a in acc]

    def name(self):
        return self._name or "acc"


class Precision(Metric):
    """Binary precision. update(pred (N,) probabilities, label (N,) {0,1})."""

    def __init__(self, name=None):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, pred, label):
        pred = (np.asarray(pred).reshape(-1) > 0.5)
        label = np.asarray(label).reshape(-1).astype(bool)
        self.tp += int((pred & label).sum())
        self.fp += int((pred & ~label).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return float(self.tp / denom) if denom else 0.0

    def name(self):
        return self._name or "precision"


class Recall(Metric):
    """Binary recall. update(pred (N,) probabilities, label (N,) {0,1})."""

    def __init__(self, name=None):
        self._name = name
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, pred, label):
        pred = (np.asarray(pred).reshape(-1) > 0.5)
        label = np.asarray(label).reshape(-1).astype(bool)
        self.tp += int((pred & label).sum())
        self.fn += int((~pred & label).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return float(self.tp / denom) if denom else 0.0

    def name(self):
        return self._name or "recall"


class Auc(Metric):
    """ROC-AUC via threshold buckets (reference Auc num_thresholds)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def reset(self):
        n = self.num_thresholds + 1
        self._pos = np.zeros(n, np.int64)
        self._neg = np.zeros(n, np.int64)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        if preds.ndim == 2:  # (N, 2) class probabilities → P(class 1)
            preds = preds[:, 1]
        preds = preds.reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        idx = np.clip((preds * self.num_thresholds).astype(np.int64), 0,
                      self.num_thresholds)
        np.add.at(self._pos, idx[labels > 0], 1)
        np.add.at(self._neg, idx[labels <= 0], 1)

    def accumulate(self):
        # sweep thresholds high→low accumulating TP/FP; prepend the (0,0)
        # origin so the area before the first bucket counts (all-saturated
        # predictions otherwise integrate to 0 instead of 0.5)
        tp = np.concatenate([[0], np.cumsum(self._pos[::-1])])
        fp = np.concatenate([[0], np.cumsum(self._neg[::-1])])
        tot_pos, tot_neg = tp[-1], fp[-1]
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        tpr = tp / tot_pos
        fpr = fp / tot_neg
        return float(np.trapezoid(tpr, fpr)) if hasattr(np, "trapezoid") \
            else float(np.trapz(tpr, fpr))

    def name(self):
        return self._name or "auc"
