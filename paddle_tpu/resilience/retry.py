"""Bounded retry with exponential backoff — the shared transient-error
policy.

Reference (SURVEY.md §5): the reference survives coordination-service
hiccups with NCCL timeouts + launcher-level relaunch; a single flaky
etcd RPC does not kill a 1000-host job. The TPU-native analog: every
control-plane call (coordination-service KV puts/gets, heartbeat store
ops) goes through `call_with_retry` with a small bounded budget, and
every retry lands on the `resilience.retries` counter so fleet health
is visible in the metrics exporters.

Deterministic by design: the backoff schedule is a pure function of the
policy — including the OPTIONAL jitter, which is seeded rather than
drawn from a PRNG stream. Jitter exists because N workers that all lose
the same peer at the same instant would otherwise retry in lockstep (a
retry storm, re-synchronized every backoff rung); folding the policy
``seed`` and the attempt index through a hash de-correlates the
schedules while keeping every schedule reproducible — tests can still
assert the exact sleep sequence for a fixed seed, and the injected
`sleep` argument makes the tests instant.
"""

import logging
import time
import zlib
from dataclasses import dataclass
from typing import Callable, Iterable, Optional, Tuple, Type

logger = logging.getLogger("paddle_tpu.resilience")

__all__ = [
    "RetryPolicy", "backoff_delays", "call_with_retry", "kv_op",
    "is_resource_exhausted", "is_timeout", "is_not_found",
    "remaining_deadline",
]


@dataclass(frozen=True)
class RetryPolicy:
    """max_attempts counts the FIRST try too: max_attempts=3 means one
    call plus at most two retries. Delay before retry k (1-based) is
    min(base_delay_s * backoff**(k-1), max_delay_s), then scaled by the
    deterministic jitter factor for (seed, k) when ``jitter > 0``:
    a value in [1 - jitter, 1 + jitter] derived from crc32(seed:k) —
    no PRNG state, so two policies with the same seed produce the SAME
    schedule and two workers with different seeds de-correlate."""

    max_attempts: int = 3
    base_delay_s: float = 0.05
    backoff: float = 2.0
    max_delay_s: float = 2.0
    jitter: float = 0.0
    seed: int = 0


def _jitter_factor(seed: int, attempt: int, jitter: float) -> float:
    """Deterministic scale in [1 - jitter, 1 + jitter] for retry
    `attempt` (1-based) under `seed` — the attempt index is folded into
    the hash so consecutive rungs of ONE schedule de-correlate too."""
    u = zlib.crc32(f"{int(seed)}:{int(attempt)}".encode()) / 0xFFFFFFFF
    return 1.0 + jitter * (2.0 * u - 1.0)


def backoff_delays(policy: RetryPolicy) -> Iterable[float]:
    """The (max_attempts - 1) sleep durations, in order (jittered when
    the policy asks — the cap applies BEFORE the jitter scale, so the
    spread survives saturation at max_delay_s)."""
    d = policy.base_delay_s
    for k in range(1, max(policy.max_attempts, 1)):
        delay = min(d, policy.max_delay_s)
        if policy.jitter:
            delay *= _jitter_factor(policy.seed, k, policy.jitter)
        yield max(delay, 0.0)
        d *= policy.backoff


def call_with_retry(fn: Callable, *, policy: Optional[RetryPolicy] = None,
                    retry_on: Tuple[Type[BaseException], ...] = (Exception,),
                    retry_if: Optional[Callable[[BaseException], bool]] = None,
                    describe: str = "op",
                    sleep: Callable[[float], None] = time.sleep):
    """Run `fn()`; on an exception matching `retry_on` (and `retry_if`,
    when given) sleep the next backoff delay and try again, up to
    `policy.max_attempts` total attempts. The final failure re-raises.

    Each retry increments ``resilience.retries{op=describe}`` in the
    default metrics registry and logs a warning — recovery events are
    telemetry, not silence."""
    policy = policy or RetryPolicy()
    delays = list(backoff_delays(policy))
    attempt = 0
    while True:
        try:
            return fn()
        except retry_on as e:  # noqa: PERF203 — retry loop by definition
            if retry_if is not None and not retry_if(e):
                raise
            if attempt >= len(delays):
                raise
            delay = delays[attempt]
            attempt += 1
            _count_retry(describe)
            logger.warning(
                "%s failed (%s: %s); retry %d/%d in %.3fs", describe,
                type(e).__name__, e, attempt, len(delays), delay)
            sleep(delay)


def _count_retry(describe: str):
    from paddle_tpu.observability import registry
    registry().counter("resilience.retries", op=describe).inc()


_DEFAULT_POLICY = RetryPolicy()


def kv_op(describe: str, fn: Callable, *,
          policy: Optional[RetryPolicy] = _DEFAULT_POLICY,
          retry_if: Optional[Callable[[BaseException], bool]] = None):
    """THE wrapper for coordination-service control-plane calls
    (heartbeat stores, collective kv exchange): the injectable ``kv.op``
    fault site fires inside every retried attempt, so an injected
    transient error exercises the same recovery a real one hits.
    ``policy=None`` disables the retry (the fault site still fires)."""
    from paddle_tpu.resilience import faults as _faults

    def attempt():
        _faults.maybe_fire("kv.op")
        return fn()

    if policy is None:
        return attempt()
    return call_with_retry(attempt, policy=policy, describe=describe,
                           retry_if=retry_if)


def remaining_deadline(deadline_s: Optional[float],
                       t_start: float) -> Optional[float]:
    """What is left of a per-request wall-clock budget started at
    `t_start` (time.perf_counter()); None passes through. The one
    remaining-budget rule for every decode degradation rung — retries
    inherit the REMAINING budget, never a fresh allowance."""
    if deadline_s is None:
        return None
    return max(deadline_s - (time.perf_counter() - t_start), 0.0)


# ---- error-class predicates (shared across the degradation ladders) --------
#
# jax surfaces device/runtime failures as XlaRuntimeError with the gRPC
# status-code NAME in the message; matching on the string keeps these
# predicates working across jax versions (the exception class moved
# modules between 0.4 and 0.9) and lets the simulated faults match too.

def is_resource_exhausted(e: BaseException) -> bool:
    """Accelerator OOM (or the injected stand-in)."""
    s = str(e)
    return "RESOURCE_EXHAUSTED" in s or "Resource exhausted" in s


def is_timeout(e: BaseException) -> bool:
    s = str(e).lower()
    return "deadline_exceeded" in s or "timed out" in s or "timeout" in s


def is_not_found(e: BaseException) -> bool:
    s = str(e)
    return "NOT_FOUND" in s or "not found" in s.lower()
