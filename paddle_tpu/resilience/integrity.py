"""Checkpoint integrity: per-tensor checksum manifests + commit markers.

Reference (SURVEY.md §5): the reference's recovery contract is
restart-from-latest-checkpoint — which silently becomes a permanent
crash loop the moment the *latest* checkpoint is truncated by the very
crash that triggered the restart. The fix is the classic commit
protocol: every completed save writes a MANIFEST (the commit marker)
only after the data is durable, listing every file's size+crc32 and
every tensor's checksum; resume walks BACK past any step whose
manifest is missing (save never committed) or whose files no longer
match (corruption after commit).

Layout (docs/RESILIENCE.md): manifests live INSIDE the checkpoint root
in a non-numeric subdir orbax's step scan ignores::

    <ckpt_dir>/integrity/step_<N>.json        # atomic tmp+rename
    {"schema": "paddle_tpu.ckpt_manifest/v1", "step": N, "ts": ...,
     "files":   {"<relpath>": {"size": int, "crc32": int}, ...},
     "tensors": {"<dotted.path>": {"shape": [...], "dtype": "float32",
                                   "crc32": int}, ...}}

Verification tiers: `verify_files` (fast — stat + crc every file under
the step dir, no deserialization) is what `verified_latest_step` runs;
`tensors` entries additionally let a deep check compare a RESTORED
state against what was saved (CheckpointManager.verify_step(deep=True)).
"""

import json
import logging
import os
import zlib
from typing import Dict, Optional, Tuple

import numpy as np

logger = logging.getLogger("paddle_tpu.resilience")

__all__ = [
    "MANIFEST_SCHEMA", "MANIFEST_SUBDIR",
    "tensor_checksums", "file_checksums", "manifest_path",
    "write_manifest", "read_manifest", "manifest_steps",
    "verify_files", "verify_tensors",
    "is_content_failure", "corrupt_checkpoint",
]

MANIFEST_SCHEMA = "paddle_tpu.ckpt_manifest/v1"
MANIFEST_SUBDIR = "integrity"
_CRC_CHUNK = 1 << 20

# reason prefixes verify_files/verify_tensors use for DETERMINISTIC
# content failures — data that provably differs from what the save
# committed. Everything else (unreadable file, missing manifest, restore
# error) may be transient, and destroying a checkpoint over a transient
# error turns a recoverable blip into data loss. This tuple is the ONE
# contract quarantine decisions key off; keep reason strings starting
# with one of these when adding failure modes.
_CONTENT_FAILURE_PREFIXES = (
    "size mismatch", "crc mismatch", "missing file",
    "tensor mismatch", "missing tensor", "unexpected tensors",
)


def is_content_failure(reason: str) -> bool:
    """True when a verify_* reason denotes deterministic content damage
    (safe to quarantine), not a possibly-transient read failure."""
    return reason.startswith(_CONTENT_FAILURE_PREFIXES)


def _crc_file(path: str) -> Tuple[int, int]:
    crc = 0
    size = 0
    with open(path, "rb") as f:
        while True:
            buf = f.read(_CRC_CHUNK)
            if not buf:
                break
            crc = zlib.crc32(buf, crc)
            size += len(buf)
    return crc & 0xFFFFFFFF, size


def tensor_checksums(state, _prefix: str = "") -> Dict[str, dict]:
    """{dotted.path: {shape, dtype, crc32}} over a pytree of arrays.

    crc32 runs over the C-contiguous host bytes (np.asarray pulls device
    arrays — on multi-GiB states prefer tensor_checksums=False on the
    manager and rely on the file-level manifest)."""
    import jax

    out: Dict[str, dict] = {}
    leaves = jax.tree_util.tree_leaves_with_path(state)
    for path, leaf in leaves:
        key = ".".join(str(getattr(p, "key", getattr(p, "idx", p)))
                       for p in path)
        a = np.ascontiguousarray(np.asarray(leaf))
        out[key] = {"shape": list(a.shape), "dtype": str(a.dtype),
                    "crc32": zlib.crc32(a.tobytes()) & 0xFFFFFFFF}
    return out


def file_checksums(step_dir: str) -> Dict[str, dict]:
    """{relpath: {size, crc32}} for every regular file under `step_dir`."""
    out: Dict[str, dict] = {}
    for root, _dirs, files in os.walk(step_dir):
        for fn in sorted(files):
            p = os.path.join(root, fn)
            rel = os.path.relpath(p, step_dir)
            crc, size = _crc_file(p)
            out[rel] = {"size": size, "crc32": crc}
    return out


def manifest_path(ckpt_dir: str, step: int) -> str:
    return os.path.join(ckpt_dir, MANIFEST_SUBDIR, f"step_{int(step)}.json")


def write_manifest(ckpt_dir: str, step: int, files: Dict[str, dict],
                   tensors: Optional[Dict[str, dict]] = None,
                   ts: Optional[float] = None) -> str:
    """Atomically commit the manifest (tmp + rename): its existence IS
    the step's commit marker, so it must never be observable half-written."""
    import time

    path = manifest_path(ckpt_dir, step)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    doc = {"schema": MANIFEST_SCHEMA, "step": int(step),
           "ts": ts if ts is not None else time.time(),
           "files": files, "tensors": tensors or {}}
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


def manifest_steps(ckpt_dir: str) -> list:
    """Step numbers with a committed manifest under
    ``<ckpt_dir>/integrity/``, newest first — the generic walk-back
    order for any consumer of this commit protocol (checkpoint resume
    via orbax's own step scan, serving-engine snapshots via this)."""
    d = os.path.join(ckpt_dir, MANIFEST_SUBDIR)
    out = []
    if os.path.isdir(d):
        for fn in os.listdir(d):
            if fn.startswith("step_") and fn.endswith(".json"):
                digits = fn[len("step_"):-len(".json")]
                if digits.isdigit():
                    out.append(int(digits))
    return sorted(out, reverse=True)


def read_manifest(ckpt_dir: str, step: int) -> Optional[dict]:
    try:
        with open(manifest_path(ckpt_dir, step)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if doc.get("schema") != MANIFEST_SCHEMA or doc.get("step") != int(step):
        return None
    return doc


def verify_files(manifest: dict, step_dir: str) -> Tuple[bool, str]:
    """Fast integrity check: every manifest file exists under `step_dir`
    with matching size and crc32. Extra files (e.g. orbax-version debris)
    are tolerated — the manifest pins what the save wrote, not what else
    appeared."""
    for rel, meta in manifest.get("files", {}).items():
        p = os.path.join(step_dir, rel)
        if not os.path.isfile(p):
            return False, f"missing file {rel}"
        try:
            crc, size = _crc_file(p)
        except OSError as e:
            # NOT a content failure: may be a transient I/O error —
            # is_content_failure stays False so quarantine never
            # destroys the step over it
            return False, f"unreadable file {rel}: {e}"
        if size != meta["size"]:
            return False, (f"size mismatch {rel}: {size} != "
                           f"{meta['size']} (truncated?)")
        if crc != meta["crc32"]:
            return False, f"crc mismatch {rel}"
    return True, "ok"


def verify_tensors(manifest: dict, state) -> Tuple[bool, str]:
    """Deep check: a RESTORED state's per-tensor checksums match what the
    save recorded (end-to-end: serialize + disk + deserialize)."""
    want = manifest.get("tensors") or {}
    if not want:
        return False, "manifest has no tensor checksums"
    got = tensor_checksums(state)
    for key, meta in want.items():
        g = got.get(key)
        if g is None:
            return False, f"missing tensor {key}"
        if g != meta:
            return False, f"tensor mismatch {key}: {g} != {meta}"
    extra = set(got) - set(want)
    if extra:
        return False, f"unexpected tensors {sorted(extra)[:4]}"
    return True, "ok"


def corrupt_checkpoint(step_dir: str, mode: str = "truncate",
                       seed: int = 0) -> str:
    """Deterministically damage a committed checkpoint (fault injection /
    tests): pick the LARGEST regular file under `step_dir` (ties broken
    by path — the tensor data file, not a tiny metadata json) and either
    truncate it to half (`mode='truncate'` — the torn-write crash shape)
    or flip 8 bytes mid-file (`mode='flip'` — silent bit rot). Returns
    the damaged path."""
    victims = []
    for root, _dirs, files in os.walk(step_dir):
        for fn in files:
            p = os.path.join(root, fn)
            victims.append((-os.path.getsize(p), p))
    if not victims:
        raise FileNotFoundError(f"no files under {step_dir}")
    victims.sort()
    path = victims[0][1]
    size = os.path.getsize(path)
    if mode == "truncate":
        with open(path, "r+b") as f:
            f.truncate(max(size // 2, 0))
    elif mode == "flip":
        rng = np.random.RandomState(seed)
        off = max((size // 2) - 4, 0)
        with open(path, "r+b") as f:
            f.seek(off)
            orig = f.read(min(8, size - off))
            f.seek(off)
            f.write(bytes(b ^ 0xFF for b in orig) if orig
                    else rng.bytes(1))
    else:
        raise ValueError(f"unknown corrupt mode {mode!r}")
    logger.warning("fault injection: corrupted checkpoint file %s (%s)",
                   path, mode)
    return path
