"""paddle_tpu.resilience — fault-tolerant training & serving.

Three pillars (docs/RESILIENCE.md has the full story):

* **Fault injection** (`faults.py`): a `FaultPlan` fires deterministic
  faults (raise / NaN-poisoned grads / corrupted checkpoint files /
  dropped heartbeats / simulated RESOURCE_EXHAUSTED) at named sites in
  `ElasticTrainLoop`, `CheckpointManager`, `ElasticManager` and
  `inference.generate`. Zero overhead disarmed — one global read.
* **Checkpoint integrity** (`integrity.py`): per-tensor checksum
  manifests + atomic commit markers with every `CheckpointManager.save`;
  `verified_latest_step()` walks resume back past incomplete or corrupt
  steps, so one torn save can't become a permanent crash loop.
* **Graceful degradation & retry** (`retry.py`): the shared bounded
  retry/backoff helper behind the coordination-service stores, plus the
  error-class predicates the decode degradation ladder (halved KV chunk
  → layered path) and per-request deadlines key off.

Every recovery action — restart, skipped non-finite step, rewind,
corrupt checkpoint skipped, retry, degraded decode, deadline cut, fault
fired — lands on a ``resilience.*`` counter in the observability
registry, so the existing JSONL/Prometheus exporters surface fleet
health for free. `record_event` is the one helper behind those counters.
"""

import logging

from paddle_tpu.resilience.faults import (   # noqa: F401
    Fault, FaultPlan, SimulatedResourceExhausted,
    arm, disarm, armed, maybe_fire, plan,
)
from paddle_tpu.resilience.retry import (    # noqa: F401
    RetryPolicy, backoff_delays, call_with_retry, kv_op,
    is_not_found, is_resource_exhausted, is_timeout, remaining_deadline,
)
from paddle_tpu.resilience import faults, integrity, retry  # noqa: F401

logger = logging.getLogger("paddle_tpu.resilience")

__all__ = [
    "Fault", "FaultPlan", "SimulatedResourceExhausted",
    "arm", "disarm", "armed", "maybe_fire", "plan",
    "RetryPolicy", "backoff_delays", "call_with_retry", "kv_op",
    "is_not_found", "is_resource_exhausted", "is_timeout",
    "remaining_deadline", "faults", "integrity", "retry", "record_event",
]


def record_event(event: str, **labels):
    """Increment ``resilience.<event>`` (+labels) in the default metrics
    registry and log it — the one funnel for recovery-event telemetry."""
    from paddle_tpu.observability import registry

    registry().counter(f"resilience.{event}", **labels).inc()
    logger.warning("resilience event: %s %s", event, labels or "")
