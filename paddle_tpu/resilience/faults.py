"""Deterministic fault injection — the testable half of the recovery story.

Reference (SURVEY.md §5-failure): the reference's elastic tests kill
worker processes and assert the manager relaunches; recovery paths that
are never exercised rot. This module makes every failure mode the
framework claims to survive *injectable on demand and deterministic*:
a `FaultPlan` names WHERE (a site string), WHEN (the Nth call / step /
request at that site) and WHAT (raise, NaN-poison, corrupt files, drop
heartbeats, simulate RESOURCE_EXHAUSTED).

Sites wired through the stack (each documents its index semantics):

==========================  ================================================
site                        fired from / index
==========================  ================================================
``train.step``              ``ElasticTrainLoop.run`` — index = step number
``checkpoint.save``         ``CheckpointManager.save`` — index = step number
``elastic.heartbeat``       ``ElasticManager.register`` — call counter
``decode.dispatch``         ``inference.generate`` / ``StackedLlamaDecoder
                            .generate`` — per-process dispatch-attempt
                            counter (each degradation retry is a new call);
                            also fired by ``ServingEngine`` at each
                            admission pop AND each fused decode dispatch —
                            both BEFORE state mutates, so a raising fault
                            never loses the request (it stays queued /
                            its tokens stay un-appended)
``kv.op``                   ``collective._kv_put_get`` /
                            ``CoordinationServiceStore`` — call counter
``serving.snapshot``        ``ServingEngine.save_snapshot`` — call
                            counter (a raising fault aborts the commit
                            BEFORE the manifest, so restore walks back
                            to the previous intact snapshot)
``router.heartbeat``        ``serving.Router`` — call counter (one call
                            per live replica per router tick, round
                            robin). A raising fault IS a missed
                            heartbeat: the router counts it against
                            that replica's health state machine
                            (healthy → suspect → dead) instead of
                            propagating; enough consecutive misses
                            declare the replica dead and trigger
                            zero-loss failover
``transport.send``          ``serving.transport.Channel.send`` — call
                            counter, fired BEFORE the frame is written,
                            so a raising fault never leaves a half
                            frame on the wire; a raised
                            ``TransportCorruption`` simulates a torn
                            frame the peer's CRC check would reject
``transport.recv``          ``serving.transport.Channel.recv`` — call
                            counter, fired BEFORE the read, so the
                            frame stays queued for the retry
``worker.tick``             ``serving.worker`` serve loop — one call
                            per received RPC message, fired before the
                            op dispatches; kind='hang' makes the worker
                            sleep ``seconds`` (payload) holding the
                            reply, which the router's wall-clock
                            heartbeat deadline must convert into
                            suspect → dead, exactly as a live-but-hung
                            process would
``offload.swap``            ``ServingEngine`` host-tier swap paths —
                            call counter, fired BEFORE a swap-out
                            gathers (the slot preempts down the legacy
                            free+recompute path instead, zero loss) and
                            BEFORE a swap-in scatters (the parked
                            request falls back to the token-exact
                            re-prefill+replay resume); kind='hang'
                            sleeps ``seconds`` inside the swap window
                            so chaos can SIGKILL a worker mid-swap
==========================  ================================================

Zero-overhead contract: with no plan armed, ``maybe_fire`` is ONE global
read and an immediate return — nothing else in this module runs on the
hot path. (Pinned by tests/test_resilience.py.)

Kinds split in two families:

* **raising** (``raise``, ``resource_exhausted``): ``maybe_fire`` raises
  at the site — the caller's normal exception handling (restart loop,
  degradation ladder) takes over, exactly as a real fault would.
* **cooperative** (``nan_grads``, ``corrupt_checkpoint``,
  ``drop_heartbeat``, ``hang``): ``maybe_fire`` RETURNS the fired
  `Fault`; the hooked site applies the effect itself (poison the step
  outputs, damage the files just committed, skip the store put, sleep
  ``seconds`` at the exact point the site documents — e.g.
  ``serving.snapshot`` hangs INSIDE the torn window, after the engine
  state is written but before the manifest commits).
"""

import logging
import threading
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger("paddle_tpu.resilience")

__all__ = [
    "Fault", "FaultPlan", "KNOWN_SITES", "SimulatedResourceExhausted",
    "arm", "disarm", "armed", "maybe_fire", "plan",
]

RAISING_KINDS = ("raise", "resource_exhausted")
COOPERATIVE_KINDS = ("nan_grads", "corrupt_checkpoint", "drop_heartbeat",
                     "hang")

#: The registered fault sites — the module-docstring table in code.
#: tpu-lint's `fault-site` rule pins every `maybe_fire(...)`/`Fault(...)`
#: literal in the package against this tuple, so a new injection hook
#: cannot land without registering (and documenting) its site; `arm()`
#: warns on plans naming unknown sites (tests may use ad-hoc ones).
KNOWN_SITES = ("train.step", "checkpoint.save", "elastic.heartbeat",
               "decode.dispatch", "kv.op", "serving.snapshot",
               "router.heartbeat", "transport.send", "transport.recv",
               "worker.tick", "offload.swap")


class SimulatedResourceExhausted(RuntimeError):
    """Injected stand-in for XLA's RESOURCE_EXHAUSTED (device OOM).

    The message carries the literal status-code string so the same
    `retry.is_resource_exhausted` predicate matches both this and the
    real `XlaRuntimeError` from a device allocator failure."""

    def __init__(self, where: str = "decode.dispatch"):
        super().__init__(
            f"RESOURCE_EXHAUSTED: injected accelerator OOM at {where} "
            "(paddle_tpu.resilience fault injection)")


class Fault:
    """One injectable fault.

    site:  where it fires (see module table).
    kind:  'raise' | 'resource_exhausted' | 'nan_grads' |
           'corrupt_checkpoint' | 'drop_heartbeat'.
    at:    first index (call number / step / request) it fires at.
    count: how many consecutive indices it fires for (default 1) — AND
           the total-fire budget: a fault fires at most `count` times
           ever, so "kill at step 5" does not re-fire when the resumed
           run replays step 5 (that would be a permanent crash loop,
           the exact failure mode this subsystem tests its way out of).
    exc:   for kind='raise', the exception instance to raise (default
           RuntimeError("injected fault at <site>")).
    payload: kind-specific knobs, e.g. mode='truncate'|'flip' for
           corrupt_checkpoint.
    """

    __slots__ = ("site", "kind", "at", "count", "exc", "payload", "fired")

    def __init__(self, site: str, kind: str = "raise", at: int = 0,
                 count: int = 1, exc: Optional[BaseException] = None,
                 **payload):
        if kind not in RAISING_KINDS + COOPERATIVE_KINDS:
            raise ValueError(
                f"unknown fault kind {kind!r}; one of "
                f"{RAISING_KINDS + COOPERATIVE_KINDS}")
        self.site = site
        self.kind = kind
        self.at = int(at)
        self.count = int(count)
        self.exc = exc
        self.payload = payload
        self.fired = 0

    def _matches(self, index: int) -> bool:
        return self.at <= index < self.at + self.count

    def refund(self):
        """Return one fire to the budget — for a cooperative fault whose
        site turned out to have nothing to apply it to (e.g. a
        corrupt_checkpoint landing on a save_interval-skipped step)."""
        if self.fired > 0:
            self.fired -= 1

    def __repr__(self):
        return (f"Fault(site={self.site!r}, kind={self.kind!r}, "
                f"at={self.at}, count={self.count}, fired={self.fired})")


class FaultPlan:
    """An armed set of `Fault`s with per-site call counters.

    Call-counter indexing: sites that pass no explicit index (heartbeat,
    decode dispatch, kv ops) are numbered by this plan's own per-site
    counter, starting at 0 when the plan is armed — so "fire at call M"
    is deterministic regardless of process history."""

    def __init__(self, *faults: Fault):
        self.faults: List[Fault] = list(faults)
        self._calls: Dict[str, int] = {}
        self._lock = threading.Lock()

    def add(self, fault: Fault) -> "FaultPlan":
        self.faults.append(fault)
        return self

    def fired(self) -> List[Fault]:
        return [f for f in self.faults if f.fired]

    def pending(self) -> List[Fault]:
        return [f for f in self.faults if f.fired < f.count]

    def _fire(self, site: str, index: Optional[int]) -> Optional[Fault]:
        with self._lock:
            if index is None:
                index = self._calls.get(site, 0)
                self._calls[site] = index + 1
            hit = None
            for f in self.faults:
                if f.site == site and f.fired < f.count \
                        and f._matches(index):
                    f.fired += 1
                    hit = f
                    break
        if hit is None:
            return None
        _count_fired(site, hit.kind)
        logger.warning("fault injection: firing %r at index %d", hit, index)
        if hit.kind == "raise":
            raise hit.exc if hit.exc is not None else RuntimeError(
                f"injected fault at {site} (index {index})")
        if hit.kind == "resource_exhausted":
            raise SimulatedResourceExhausted(site)
        return hit


def _count_fired(site: str, kind: str):
    # lazy import: resilience must stay importable before observability
    # (and this runs only when a fault actually fires — off the hot path)
    from paddle_tpu.observability import registry
    registry().counter("resilience.faults_fired", site=site, kind=kind).inc()
    # postmortem seam: a fired fault snapshots every flight recorder that
    # has an auto-dump path configured (no-op otherwise) BEFORE any
    # raising kind unwinds the stack — the dump must not depend on the
    # caller surviving the fault
    try:
        from paddle_tpu.observability import flight
        flight.auto_dump_all(f"fault:{site}:{kind}")
    except Exception:
        pass    # telemetry must never mask the injected fault itself


_armed: Optional[FaultPlan] = None


def arm(fault_plan: FaultPlan) -> FaultPlan:
    """Make `fault_plan` the process-wide armed plan (replacing any).
    Unknown sites are legal (tests hook ad-hoc seams) but warned: a
    typo'd site silently never fires."""
    for f in fault_plan.faults:
        if f.site not in KNOWN_SITES:
            logger.warning(
                "fault plan names unregistered site %r (known: %s) — "
                "it will only fire if something calls maybe_fire(%r)",
                f.site, ", ".join(KNOWN_SITES), f.site)
    global _armed
    _armed = fault_plan
    return fault_plan


def disarm() -> Optional[FaultPlan]:
    global _armed
    p, _armed = _armed, None
    return p


def armed() -> Optional[FaultPlan]:
    return _armed


def maybe_fire(site: str, index: Optional[int] = None) -> Optional[Fault]:
    """The per-site hook. With no plan armed this is one global read.

    May RAISE (kinds 'raise' / 'resource_exhausted') or RETURN a fired
    cooperative `Fault` for the caller to apply, else None."""
    plan_ = _armed
    if plan_ is None:
        return None
    return plan_._fire(site, index)


class plan:
    """``with faults.plan(Fault(...)) as p:`` — arm for the block,
    restore the previously armed plan (if any) on exit."""

    def __init__(self, *faults: Fault):
        self.plan = FaultPlan(*faults)
        self._prev: Tuple[Optional[FaultPlan]] = (None,)

    def __enter__(self) -> FaultPlan:
        self._prev = (_armed,)
        arm(self.plan)
        return self.plan

    def __exit__(self, *exc):
        global _armed
        if _armed is self.plan:
            _armed = self._prev[0]
        return False
