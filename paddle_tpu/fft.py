"""paddle.fft parity — spectral transforms over jnp.fft.

Reference surface: python/paddle/fft.py (cuFFT-backed phi kernels). XLA
lowers these natively on TPU/CPU. Signatures keep paddle's (x, n, axis,
norm) convention.
"""

import jax.numpy as jnp


def fft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.fft(x, n=n, axis=axis, norm=norm)


def ifft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.ifft(x, n=n, axis=axis, norm=norm)


def rfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.rfft(x, n=n, axis=axis, norm=norm)


def irfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.irfft(x, n=n, axis=axis, norm=norm)


def hfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.hfft(x, n=n, axis=axis, norm=norm)


def ihfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.ihfft(x, n=n, axis=axis, norm=norm)


def fft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.fft2(x, s=s, axes=axes, norm=norm)


def ifft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.ifft2(x, s=s, axes=axes, norm=norm)


def rfft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.rfft2(x, s=s, axes=axes, norm=norm)


def irfft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.irfft2(x, s=s, axes=axes, norm=norm)


def fftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.fftn(x, s=s, axes=axes, norm=norm)


def ifftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.ifftn(x, s=s, axes=axes, norm=norm)


def rfftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.rfftn(x, s=s, axes=axes, norm=norm)


def irfftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.irfftn(x, s=s, axes=axes, norm=norm)


def fftfreq(n, d=1.0, dtype=None):
    return jnp.fft.fftfreq(n, d=d).astype(dtype) if dtype else jnp.fft.fftfreq(n, d=d)


def rfftfreq(n, d=1.0, dtype=None):
    r = jnp.fft.rfftfreq(n, d=d)
    return r.astype(dtype) if dtype else r


def fftshift(x, axes=None):
    return jnp.fft.fftshift(x, axes=axes)


def ifftshift(x, axes=None):
    return jnp.fft.ifftshift(x, axes=axes)
