"""paddle.fft parity — spectral transforms over jnp.fft.

Reference surface: python/paddle/fft.py (cuFFT-backed phi kernels). XLA
lowers these natively on TPU/CPU. Signatures keep paddle's (x, n, axis,
norm) convention.
"""

import jax.numpy as jnp


def fft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.fft(x, n=n, axis=axis, norm=norm)


def ifft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.ifft(x, n=n, axis=axis, norm=norm)


def rfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.rfft(x, n=n, axis=axis, norm=norm)


def irfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.irfft(x, n=n, axis=axis, norm=norm)


def hfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.hfft(x, n=n, axis=axis, norm=norm)


def ihfft(x, n=None, axis=-1, norm="backward"):
    return jnp.fft.ihfft(x, n=n, axis=axis, norm=norm)


def fft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.fft2(x, s=s, axes=axes, norm=norm)


def ifft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.ifft2(x, s=s, axes=axes, norm=norm)


def rfft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.rfft2(x, s=s, axes=axes, norm=norm)


def irfft2(x, s=None, axes=(-2, -1), norm="backward"):
    return jnp.fft.irfft2(x, s=s, axes=axes, norm=norm)


def fftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.fftn(x, s=s, axes=axes, norm=norm)


def ifftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.ifftn(x, s=s, axes=axes, norm=norm)


def rfftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.rfftn(x, s=s, axes=axes, norm=norm)


def irfftn(x, s=None, axes=None, norm="backward"):
    return jnp.fft.irfftn(x, s=s, axes=axes, norm=norm)


def fftfreq(n, d=1.0, dtype=None):
    return jnp.fft.fftfreq(n, d=d).astype(dtype) if dtype else jnp.fft.fftfreq(n, d=d)


def rfftfreq(n, d=1.0, dtype=None):
    r = jnp.fft.rfftfreq(n, d=d)
    return r.astype(dtype) if dtype else r


def fftshift(x, axes=None):
    return jnp.fft.fftshift(x, axes=axes)


def ifftshift(x, axes=None):
    return jnp.fft.ifftshift(x, axes=axes)


# Hermitian-input N-D transforms (reference paddle.fft.hfft2/hfftn etc.):
# Hermitian symmetry is along the LAST transform axis; the other axes get
# plain (i)fft. numpy has no nd variants — composed per the reference's
# definition, validated against torch.fft in tests.

def hfft2(x, s=None, axes=(-2, -1), norm="backward"):
    return hfftn(x, s=s, axes=axes, norm=norm)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward"):
    return ihfftn(x, s=s, axes=axes, norm=norm)


def _nd_axes(x, s, axes):
    """fftn-convention resolution: axes default to all dims, or to the
    LAST len(s) dims when only s is given; mismatched lengths raise."""
    if axes is None:
        axes = (tuple(range(x.ndim)) if s is None
                else tuple(range(x.ndim - len(s), x.ndim)))
    axes = tuple(axes)
    if s is not None and len(s) != len(axes):
        raise ValueError(
            f"shape {tuple(s)} and axes {axes} must have the same length")
    return axes


def hfftn(x, s=None, axes=None, norm="backward"):
    x = jnp.asarray(x)
    axes = _nd_axes(x, s, axes)
    if s is None:
        s = [2 * (x.shape[a] - 1) if a == axes[-1] else x.shape[a]
             for a in axes]
    for a, n in zip(axes[:-1], s[:-1]):
        x = jnp.fft.fft(x, n=n, axis=a, norm=norm)
    return jnp.fft.hfft(x, n=s[-1], axis=axes[-1], norm=norm)


def ihfftn(x, s=None, axes=None, norm="backward"):
    x = jnp.asarray(x)
    axes = _nd_axes(x, s, axes)
    if s is None:
        s = [x.shape[a] for a in axes]
    out = jnp.fft.ihfft(x, n=s[-1], axis=axes[-1], norm=norm)
    for a, n in zip(axes[:-1], s[:-1]):
        out = jnp.fft.ifft(out, n=n, axis=a, norm=norm)
    return out
