"""paddle.regularizer parity — weight-decay policy objects.

Reference: python/paddle/regularizer.py; optimizers accept
`weight_decay=L2Decay(1e-4)` (or a bare float meaning L2)."""


class L2Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __repr__(self):
        return f"L2Decay({self.coeff})"


class L1Decay:
    def __init__(self, coeff=0.0):
        self.coeff = float(coeff)

    def __repr__(self):
        return f"L1Decay({self.coeff})"
