"""Fused transformer layers (≈ paddle.incubate.nn).

Reference (SURVEY.md §2.7-incubate): Python wrappers over the Phi fusion
kernels — FusedMultiHeadAttention, FusedFeedForward, FusedMultiTransformer
(the whole-decoder inference kernel, fused_multi_transformer_op.cu).

TPU-native: "fused" means ONE lax.scan over layer-stacked weights inside one
jit — XLA keeps the whole decoder in registers/VMEM across layers, which is
what the reference's mega-kernel buys; attention rides the Pallas flash path.
"""

from typing import Optional

import jax
import jax.numpy as jnp

from paddle_tpu.nn.layer import Layer
from paddle_tpu.nn import functional as F
from paddle_tpu.nn import initializer as init
from paddle_tpu.incubate.nn import functional  # noqa: F401


class FusedMultiHeadAttention(Layer):
    """qkv proj + flash attention + out proj (+pre/post LN) in one module."""

    def __init__(self, embed_dim, num_heads, dropout_rate=0.0,
                 attn_dropout_rate=0.0, normalize_before=True, epsilon=1e-5):
        super().__init__()
        from paddle_tpu.nn.layers.norm import LayerNorm
        from paddle_tpu.nn.layers.common import Linear
        self.norm = LayerNorm(embed_dim, epsilon=epsilon)
        self.qkv_proj = Linear(embed_dim, 3 * embed_dim)
        self.out_proj = Linear(embed_dim, embed_dim)
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate

    def forward(self, x, attn_mask=None, is_causal=False):
        res = x
        if self.normalize_before:
            x = self.norm(x)
        b, s, h = x.shape
        qkv = self.qkv_proj(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(b, s, self.num_heads, self.head_dim)
        k = k.reshape(b, s, self.num_heads, self.head_dim)
        v = v.reshape(b, s, self.num_heads, self.head_dim)
        out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                             is_causal=is_causal)
        out = self.out_proj(out.reshape(b, s, h))
        out = F.dropout(out, self.dropout_rate, training=self.training)
        out = res + out
        if not self.normalize_before:
            out = self.norm(out)
        return out


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.0,
                 activation="gelu", normalize_before=True, epsilon=1e-5):
        super().__init__()
        from paddle_tpu.nn.layers.norm import LayerNorm
        from paddle_tpu.nn.layers.common import Linear
        self.norm = LayerNorm(d_model, epsilon=epsilon)
        self.fc1 = Linear(d_model, dim_feedforward)
        self.fc2 = Linear(dim_feedforward, d_model)
        self.act = {"gelu": F.gelu, "relu": F.relu, "silu": F.silu}[activation]
        self.normalize_before = normalize_before
        self.dropout_rate = dropout_rate

    def forward(self, x):
        res = x
        if self.normalize_before:
            x = self.norm(x)
        x = self.fc2(self.act(self.fc1(x)))
        x = F.dropout(x, self.dropout_rate, training=self.training)
        x = res + x
        if not self.normalize_before:
            x = self.norm(x)
        return x


class FusedMultiTransformer(Layer):
    """Whole pre-norm decoder stack as layer-stacked weights + one lax.scan
    (fused_multi_transformer parity — the inference hot path).

    Weights carry a leading num_layers dim; forward supports full-sequence
    and KV-cached single/multi-token decode. Cache layout:
    {'k','v'}: (L, b, max_len, heads, head_dim).
    """

    def __init__(self, embed_dim, num_heads, dim_feedforward, num_layers,
                 activation="gelu", epsilon=1e-5, initializer_range=0.02,
                 dtype=None):
        super().__init__()
        L, h, f = num_layers, embed_dim, dim_feedforward
        w = init.Normal(0.0, initializer_range)
        zeros = init.Constant(0.0)
        ones = init.Constant(1.0)
        mk = lambda shape, ini: self.create_parameter(
            shape, dtype=dtype, default_initializer=ini)
        self.ln1_w = mk((L, h), ones)
        self.ln1_b = mk((L, h), zeros)
        self.qkv_w = mk((L, h, 3 * h), w)
        self.qkv_b = mk((L, 3 * h), zeros)
        self.out_w = mk((L, h, h), w)
        self.out_b = mk((L, h), zeros)
        self.ln2_w = mk((L, h), ones)
        self.ln2_b = mk((L, h), zeros)
        self.ffn1_w = mk((L, h, f), w)
        self.ffn1_b = mk((L, f), zeros)
        self.ffn2_w = mk((L, f, h), w)
        self.ffn2_b = mk((L, h), zeros)
        self.num_layers, self.num_heads = L, num_heads
        self.head_dim = h // num_heads
        self.epsilon = epsilon
        self.act = {"gelu": F.gelu, "relu": F.relu, "silu": F.silu}[activation]

    def init_cache(self, batch_size, max_len, dtype=jnp.bfloat16):
        shape = (self.num_layers, batch_size, max_len, self.num_heads,
                 self.head_dim)
        return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}

    def _ln(self, x, w, b):
        xf = x.astype(jnp.float32)
        mu = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        return ((xf - mu) * jax.lax.rsqrt(var + self.epsilon)).astype(
            x.dtype) * w + b

    def forward(self, x, cache=None, start_pos=0, is_causal=True):
        b, s, h = x.shape
        params = (self.ln1_w, self.ln1_b, self.qkv_w, self.qkv_b, self.out_w,
                  self.out_b, self.ln2_w, self.ln2_b, self.ffn1_w,
                  self.ffn1_b, self.ffn2_w, self.ffn2_b)

        def layer(x, per):
            if cache is None:
                (l1w, l1b, qkvw, qkvb, ow, ob, l2w, l2b, f1w, f1b, f2w,
                 f2b) = per
                ck = cv = None
            else:
                (l1w, l1b, qkvw, qkvb, ow, ob, l2w, l2b, f1w, f1b, f2w,
                 f2b), (ck, cv) = per
            y = self._ln(x, l1w, l1b)
            qkv = jnp.matmul(y, qkvw) + qkvb
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(b, s, self.num_heads, self.head_dim)
            k = k.reshape(b, s, self.num_heads, self.head_dim)
            v = v.reshape(b, s, self.num_heads, self.head_dim)
            if cache is not None:
                ck = jax.lax.dynamic_update_slice_in_dim(
                    ck, k.astype(ck.dtype), start_pos, axis=1)
                cv = jax.lax.dynamic_update_slice_in_dim(
                    cv, v.astype(cv.dtype), start_pos, axis=1)
                max_len = ck.shape[1]
                q_pos = start_pos + jnp.arange(s)[:, None]
                mask = (jnp.arange(max_len)[None, :] <= q_pos)[None, None]
                attn = F.scaled_dot_product_attention(q, ck, cv,
                                                      attn_mask=mask)
            else:
                attn = F.scaled_dot_product_attention(q, k, v,
                                                      is_causal=is_causal)
            x = x + jnp.matmul(attn.reshape(b, s, h), ow) + ob
            y = self._ln(x, l2w, l2b)
            x = x + jnp.matmul(self.act(jnp.matmul(y, f1w) + f1b), f2w) + f2b
            return x, (ck, cv)

        if cache is None:
            def body(xc, per):
                out, _ = layer(xc, per)
                return out, None
            x, _ = jax.lax.scan(body, x, params)
            return x

        def body(xc, per):
            return layer(xc, per)
        x, (new_k, new_v) = jax.lax.scan(body, x,
                                         (params, (cache["k"], cache["v"])))
        return x, {"k": new_k, "v": new_v}
