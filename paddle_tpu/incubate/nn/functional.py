"""paddle.incubate.nn.functional parity — thin veneers over ops/."""

from paddle_tpu.ops.rope import fused_rotary_position_embedding  # noqa: F401
from paddle_tpu.ops.rms_norm import rms_norm as fused_rms_norm  # noqa: F401
from paddle_tpu.ops.flash_attention import (  # noqa: F401
    flash_attention,
    scaled_dot_product_attention as fused_dot_product_attention,
)


def fused_bias_dropout_residual_layer_norm(x, residual, bias=None,
                                           ln_scale=None, ln_bias=None,
                                           dropout_rate=0.0, epsilon=1e-5,
                                           training=True):
    """(x + bias) -> dropout -> + residual -> layernorm, XLA-fused."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.nn import functional as F
    if bias is not None:
        x = x + bias
    x = F.dropout(x, dropout_rate, training=training)
    y = (x + residual).astype(jnp.float32)
    mu = jnp.mean(y, axis=-1, keepdims=True)
    var = jnp.var(y, axis=-1, keepdims=True)
    out = (y - mu) * jax.lax.rsqrt(var + epsilon)
    out = out.astype(residual.dtype)
    if ln_scale is not None:
        out = out * ln_scale
    if ln_bias is not None:
        out = out + ln_bias
    return out


# reference paths: paddle.incubate.nn.functional.{fused_rotary_position_
# embedding, fused_rms_norm} — the TPU implementations live in paddle_tpu.ops
from paddle_tpu.ops.rope import fused_rotary_position_embedding  # noqa: F401,E402
from paddle_tpu.ops.rms_norm import rms_norm as fused_rms_norm  # noqa: F401,E402
