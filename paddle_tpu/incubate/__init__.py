"""paddle.incubate namespace parity (fused layers & functional)."""

from paddle_tpu.incubate import nn  # noqa: F401
