"""paddle_tpu — a TPU-native deep-learning framework.

A brand-new framework built on JAX/XLA/Pallas with the capability surface of
PaddlePaddle (reference: salemmohammed/Paddle): a ``Layer``/optimizer/AMP user
API, Fleet-style hybrid parallelism (DP, ZeRO sharding stages 1-3, Megatron
TP+SP, 1F1B pipeline, MoE expert parallel, ring-attention long context) over a
named TPU mesh, a semi-auto ``shard_tensor``/``Engine`` API lowering to GSPMD,
Pallas fusion kernels, and first-class checkpointing/profiling/observability.

Design (see SURVEY.md §7): the compute path is jnp/XLA under ``jax.jit``;
parallelism is expressed as named-mesh shardings compiled by GSPMD; the hot
fusion ops (flash attention, rms_norm, rope, fused decode step) are Pallas
TPU kernels with XLA fallbacks.
"""

from paddle_tpu import version as _version

__version__ = _version.__version__

# jax 0.9 API names on older jax installs — must run before any submodule
# references jax.shard_map / jax.lax.pcast / pltpu.CompilerParams.
from paddle_tpu.core import jaxcompat as _jaxcompat

_jaxcompat.install()

# Core tensor veneer --------------------------------------------------------
from paddle_tpu.tensor import (  # noqa: F401
    Tensor,
    to_tensor,
    zeros,
    zeros_like,
    ones,
    ones_like,
    full,
    full_like,
    arange,
    linspace,
    empty,
    empty_like,
    eye,
    rand,
    randn,
    randint,
    randperm,
    normal,
    uniform,
    concat,
    stack,
    split,
    chunk,
    reshape,
    transpose,
    squeeze,
    unsqueeze,
    flatten,
    cast,
    matmul,
    bmm,
    add,
    subtract,
    multiply,
    divide,
    pow,
    sqrt,
    rsqrt,
    exp,
    log,
    abs,
    clip,
    maximum,
    minimum,
    mean,
    sum,
    max,
    min,
    prod,
    argmax,
    argmin,
    cumsum,
    where,
    equal,
    not_equal,
    greater_than,
    greater_equal,
    less_than,
    less_equal,
    logical_and,
    logical_or,
    logical_not,
    isnan,
    isinf,
    isfinite,
    tanh,
    sigmoid,
    sin,
    cos,
    floor,
    ceil,
    round,
    sign,
    topk,
    sort,
    argsort,
    gather,
    take_along_axis,
    scatter,
    tile,
    expand,
    roll,
    flip,
    tril,
    triu,
    diag,
    einsum,
    norm,
    dot,
    outer,
    var,
    std,
    all,
    any,
    unique,
    nonzero,
    masked_select,
    index_select,
    numel,
    shape,
)

from paddle_tpu.core.rng import seed, get_rng_state, set_rng_state  # noqa: F401
from paddle_tpu.core.flags import set_flags, get_flags  # noqa: F401
from paddle_tpu.core.dtype import (  # noqa: F401
    float32,
    float16,
    bfloat16,
    float64,
    int8,
    int16,
    int32,
    int64,
    uint8,
    bool_,
    complex64,
    set_default_dtype,
    get_default_dtype,
)
from paddle_tpu.core import device  # noqa: F401
from paddle_tpu.core.device import set_device, get_device, is_compiled_with_tpu  # noqa: F401
from paddle_tpu.framework.io import save, load  # noqa: F401
from paddle_tpu.framework.grad import no_grad, grad  # noqa: F401
from paddle_tpu import jit  # noqa: F401  (module: jit.to_static/save/load)

from paddle_tpu import nn  # noqa: F401
from paddle_tpu.nn.layer import LazyGuard  # noqa: F401  (paddle.LazyGuard)
from paddle_tpu import optimizer  # noqa: F401
from paddle_tpu import amp  # noqa: F401
from paddle_tpu import io  # noqa: F401
from paddle_tpu import ops  # noqa: F401
from paddle_tpu import parallel  # noqa: F401
# Paddle-style alias: paddle.distributed.* (also importable as a module path)
import sys as _sys
from paddle_tpu import parallel as distributed  # noqa: F401
_sys.modules[__name__ + ".distributed"] = distributed
from paddle_tpu import linalg  # noqa: F401
from paddle_tpu import fft  # noqa: F401
from paddle_tpu import signal  # noqa: F401  (paddle.signal stft/istft)
from paddle_tpu import quantization  # noqa: F401
from paddle_tpu import regularizer  # noqa: F401
from paddle_tpu import metric  # noqa: F401
from paddle_tpu import audio  # noqa: F401
from paddle_tpu import distribution  # noqa: F401
from paddle_tpu import sparse  # noqa: F401
from paddle_tpu import models  # noqa: F401
from paddle_tpu import inference  # noqa: F401
from paddle_tpu import incubate  # noqa: F401
from paddle_tpu import vision  # noqa: F401
from paddle_tpu import profiler  # noqa: F401
from paddle_tpu import observability  # noqa: F401
from paddle_tpu import resilience  # noqa: F401
from paddle_tpu import serving  # noqa: F401
from paddle_tpu import utils  # noqa: F401
from paddle_tpu.parallel.data_parallel import DataParallel  # noqa: F401
