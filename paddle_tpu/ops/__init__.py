"""Fusion ops — the TPU stand-ins for the reference's phi fusion kernels.

Reference (SURVEY.md §2.2): paddle/phi/kernels/fusion/gpu/
{fused_multi_transformer_op.cu, fused_rope_kernel.cu, rms_norm_kernel.cu},
phi/kernels/gpu/flash_attn_kernel.cu. Here each op has (a) an XLA path —
a jnp composition XLA fuses well — and (b) a Pallas TPU kernel for the cases
where hand-tiling beats the compiler (long-seq attention). Dispatch is
centralized in `use_pallas()`.
"""

import jax

from paddle_tpu.core.flags import flag


def on_tpu() -> bool:
    try:
        plat = jax.default_backend()
    except Exception:
        return False
    return plat in ("tpu", "axon")


def use_pallas() -> bool:
    # tpu-lint: allow(host-sync): flag() is a host-side config read
    return bool(flag("FLAGS_use_pallas_kernels")) and on_tpu()


from paddle_tpu.ops import flash_attention  # noqa: F401,E402
from paddle_tpu.ops import rms_norm  # noqa: F401,E402
from paddle_tpu.ops import rope  # noqa: F401,E402
from paddle_tpu.ops.rope import fused_rotary_position_embedding  # noqa: F401,E402
from paddle_tpu.ops.flash_attention import flash_attention as flash_attn  # noqa: F401,E402


def tied_unembed(x, embed_w):
    """Unembedding against a TIED embedding table (vocab, h): contract
    the hidden dim directly — `x @ embed_w.T` materializes a (h, vocab)
    transposed copy every step (measured 0.12 ms at gpt2-medium decode,
    r5 profile)."""
    import jax

    return jax.lax.dot_general(x, embed_w, (((x.ndim - 1,), (1,)), ((), ())))
