"""Rotary position embedding (fused_rope parity).

Reference: paddle/phi/kernels/fusion/gpu/fused_rope_kernel.cu, python veneer
paddle.incubate.nn.functional.fused_rotary_position_embedding. On TPU the
sin/cos gather + rotate is fully fused by XLA into surrounding matmuls, so the
XLA path is the production path; layout is (batch, seq, heads, head_dim) and
rotation follows the reference's interleaved-halves ("NeoX") convention.
"""

import functools

import jax.numpy as jnp
import numpy as np


@functools.lru_cache(maxsize=32)
def _freqs(head_dim: int, base: float):
    return 1.0 / (base ** (np.arange(0, head_dim, 2, dtype=np.float32) / head_dim))


def rope_cos_sin(seq_len, head_dim, base=10000.0, dtype=jnp.float32,
                 position_ids=None):
    inv_freq = jnp.asarray(_freqs(head_dim, float(base)))
    if position_ids is None:
        t = jnp.arange(seq_len, dtype=jnp.float32)
    else:
        t = position_ids.astype(jnp.float32)
    freqs = jnp.einsum("...s,d->...sd", t, inv_freq)
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)


def _rotate_half(x):
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def apply_rotary_pos_emb(x, cos, sin):
    """x: (b, s, h, d); cos/sin: (s, d) or (b, s, d)."""
    while cos.ndim < x.ndim:
        cos = cos[None] if cos.ndim == 2 and x.ndim == 4 else cos[..., None, :]
        sin = sin[None] if sin.ndim == 2 and x.ndim == 4 else sin[..., None, :]
    # after loop: (1, s, 1, d) broadcastable — rebuild explicitly for clarity
    return (x * cos + _rotate_half(x) * sin).astype(x.dtype)


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    base=10000.0):
    """Apply RoPE to q/k (v passes through) — reference API parity."""
    b, s, h, d = q.shape
    if cos is None or sin is None:
        cos, sin = rope_cos_sin(s, d, base=base, dtype=jnp.float32,
                                position_ids=position_ids)
    if cos.ndim == 2:
        cos_b = cos[None, :, None, :]
        sin_b = sin[None, :, None, :]
    elif cos.ndim == 3:
        cos_b = cos[:, :, None, :]
        sin_b = sin[:, :, None, :]
    else:
        cos_b, sin_b = cos, sin
    qf = q.astype(jnp.float32)
    out_q = (qf * cos_b + _rotate_half(qf) * sin_b).astype(q.dtype)
    out_k = None
    if k is not None:
        kf = k.astype(jnp.float32)
        out_k = (kf * cos_b + _rotate_half(kf) * sin_b).astype(k.dtype)
    return out_q, out_k, v
