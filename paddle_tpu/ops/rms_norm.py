"""RMSNorm — XLA path + Pallas TPU kernel.

Reference: phi rms_norm fusion kernel
(paddle/phi/kernels/fusion/gpu/rms_norm_kernel.cu; python veneer
paddle.incubate.nn.functional.fused_rms_norm). On TPU the XLA fusion of
square→mean→rsqrt→mul is already near-bandwidth-bound-optimal; the Pallas
kernel exists to keep the reduction in fp32 while streaming bf16 rows through
VMEM, and is enabled only on TPU backends.
"""

import functools

import jax
import jax.numpy as jnp
from jax import lax


def _rms_norm_ref(x, weight, epsilon):
    xf = x.astype(jnp.promote_types(x.dtype, jnp.float32))
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    y = (xf * lax.rsqrt(var + epsilon)).astype(x.dtype)
    if weight is not None:
        y = y * weight
    return y


def rms_norm(x, weight=None, epsilon=1e-6):
    # Measured on v5e: the Pallas kernel ties the XLA fusion (both
    # HBM-bandwidth-bound), so XLA is the default (SURVEY.md §7: only keep
    # kernels that beat XLA); _rms_norm_pallas stays for benchmarking.
    return _rms_norm_ref(x, weight, epsilon)


@functools.partial(jax.jit, static_argnames=("epsilon",))
def _rms_norm_pallas(x, weight, epsilon):
    from jax.experimental import pallas as pl

    orig_shape = x.shape
    d = x.shape[-1]
    x2 = x.reshape(-1, d)
    n = x2.shape[0]
    block_rows = max(1, min(n, 512 * 1024 // (d * x2.dtype.itemsize)))
    while n % block_rows:
        block_rows -= 1

    has_w = weight is not None

    def kernel(x_ref, *rest):
        if has_w:
            w_ref, o_ref = rest
        else:
            (o_ref,) = rest
        xv = x_ref[...].astype(jnp.float32)
        var = jnp.mean(jnp.square(xv), axis=-1, keepdims=True)
        y = xv * lax.rsqrt(var + epsilon)
        y = y.astype(o_ref.dtype)
        if has_w:
            y = y * w_ref[...]
        o_ref[...] = y

    in_specs = [pl.BlockSpec((block_rows, d), lambda i: (i, 0))]
    args = [x2]
    if has_w:
        in_specs.append(pl.BlockSpec((d,), lambda i: (0,)))
        args.append(weight)
    out = pl.pallas_call(
        kernel,
        grid=(n // block_rows,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), x2.dtype),
    )(*args)
    return out.reshape(orig_shape)
