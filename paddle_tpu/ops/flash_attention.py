"""Flash attention — XLA path + Pallas TPU kernel.

Reference: phi flash_attn kernel wrapping the vendored flash-attention-2 CUDA
library (paddle/phi/kernels/gpu/flash_attn_kernel.cu, cmake/external/
flashattn.cmake; python veneer paddle.nn.functional.flash_attention).

Layouts follow the reference: q/k/v are (batch, seq, num_heads, head_dim).
GQA/MQA supported via num_kv_heads < num_heads. The Pallas kernel (blockwise
online-softmax, fp32 accumulators, causal block skipping) is used on TPU for
long sequences; the XLA einsum path covers everything else (XLA already fuses
the softmax chain and runs the matmuls on the MXU).
"""

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _repeat_kv(k, n_rep):
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.broadcast_to(k[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(
        b, s, h * n_rep, d)


def _xla_attention(q, k, v, attn_mask=None, is_causal=False, scale=None,
                   dropout_p=0.0, training=True):
    b, sq, h, d = q.shape
    sk = k.shape[1]
    n_rep = h // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    # (b, h, sq, sk) scores in fp32
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if is_causal:
        causal = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        scores = jnp.where(causal[None, None], scores, NEG_INF)
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            scores = jnp.where(attn_mask, scores, NEG_INF)
        else:
            scores = scores + attn_mask.astype(scores.dtype)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and training:
        from paddle_tpu.core import rng as _rng
        key = _rng.next_rng_key("dropout")
        keep = 1.0 - dropout_p
        mask = jax.random.bernoulli(key, keep, probs.shape)
        probs = jnp.where(mask, probs / keep, 0.0).astype(probs.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v).astype(q.dtype)


def flash_attention(q, k, v, dropout=0.0, causal=False, attn_mask=None,
                    training=True, scale=None):
    """paddle.nn.functional.flash_attention parity. Returns (out, None)."""
    out = scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask, dropout_p=dropout, is_causal=causal,
        training=training, scale=scale)
    return out, None


def scaled_dot_product_attention(q, k, v, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, scale=None):
    from paddle_tpu.ops import use_pallas
    # Pallas path: TPU, no dropout, no arbitrary mask, long enough seq to win.
    if (use_pallas() and dropout_p == 0.0 and attn_mask is None
            and q.shape[1] == k.shape[1] and q.shape[1] >= 1024
            and q.shape[1] % 512 == 0 and q.shape[-1] in (64, 128, 256)):
        try:
            return _flash_attention_vjp(q, k, v, is_causal, scale)
        except Exception:
            pass
    return _xla_attention(q, k, v, attn_mask=attn_mask, is_causal=is_causal,
                          scale=scale, dropout_p=dropout_p, training=training)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_attention_vjp(q, k, v, is_causal, scale):
    """Pallas forward; backward recomputes through the XLA composition (a
    dedicated Pallas backward kernel is a later optimization — the forward
    is where inference/prefill time goes)."""
    return _flash_attention_pallas(q, k, v, is_causal, scale)


def _flash_vjp_fwd(q, k, v, is_causal, scale):
    return _flash_attention_pallas(q, k, v, is_causal, scale), (q, k, v)


def _flash_vjp_bwd(is_causal, scale, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _xla_attention(q_, k_, v_, is_causal=is_causal,
                                          scale=scale, dropout_p=0.0),
        q, k, v)
    return vjp(g)


_flash_attention_vjp.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


# ---- Pallas blockwise flash kernel ----------------------------------------

@functools.partial(jax.jit, static_argnames=("is_causal", "scale"))
def _flash_attention_pallas(q, k, v, is_causal: bool, scale: Optional[float]):
    from jax.experimental import pallas as pl

    b, s, h, d = q.shape
    n_rep = h // k.shape[2]
    if n_rep != 1:
        k = _repeat_kv(k, n_rep)
        v = _repeat_kv(v, n_rep)
    sc = scale if scale is not None else 1.0 / math.sqrt(d)

    # TPU tiling wants the trailing block dims to be (seq, head_dim)
    qt = jnp.transpose(q, (0, 2, 1, 3))     # (b, h, s, d)
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))

    blk_q = min(512, s)
    blk_k = min(512, s)
    grid = (b, h, s // blk_q)

    def kernel(q_ref, k_ref, v_ref, o_ref):
        qi = pl.program_id(2)
        qv = q_ref[...].astype(jnp.float32) * sc  # (blk_q, d)

        def body(ki, carry):
            acc, m_prev, l_prev = carry
            kv = k_ref[pl.ds(ki * blk_k, blk_k), :].astype(jnp.float32)
            vv = v_ref[pl.ds(ki * blk_k, blk_k), :].astype(jnp.float32)
            s_blk = qv @ kv.T  # (blk_q, blk_k)
            if is_causal:
                q_pos = qi * blk_q + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
                k_pos = ki * blk_k + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
                s_blk = jnp.where(q_pos >= k_pos, s_blk, NEG_INF)
            m_cur = jnp.maximum(m_prev, jnp.max(s_blk, axis=-1))
            alpha = jnp.exp(m_prev - m_cur)
            p = jnp.exp(s_blk - m_cur[:, None])
            l_cur = l_prev * alpha + jnp.sum(p, axis=-1)
            acc = acc * alpha[:, None] + p @ vv
            return acc, m_cur, l_cur

        acc0 = jnp.zeros((blk_q, d), jnp.float32)
        m0 = jnp.full((blk_q,), NEG_INF, jnp.float32)
        l0 = jnp.zeros((blk_q,), jnp.float32)
        if is_causal:
            # only blocks at or below the diagonal contribute
            n_k = qi * (blk_q // blk_k) + 1 if blk_q >= blk_k else (qi * blk_q) // blk_k + 1
        else:
            n_k = s // blk_k
        acc, m, l = lax.fori_loop(0, n_k, body, (acc0, m0, l0))
        o_ref[...] = (acc / l[:, None]).astype(o_ref.dtype)

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, None, blk_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, s, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, s, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, None, blk_q, d),
                               lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
    )(qt, kt, vt)
    return jnp.transpose(out, (0, 2, 1, 3))
