"""Flash attention — XLA path + Pallas TPU kernels (forward AND backward).

Reference: phi flash_attn kernel wrapping the vendored flash-attention-2 CUDA
library (paddle/phi/kernels/gpu/flash_attn_kernel.cu, cmake/external/
flashattn.cmake; python veneer paddle.nn.functional.flash_attention).

Layouts follow the reference: q/k/v are (batch, seq, num_heads, head_dim).
GQA/MQA supported via num_kv_heads < num_heads. The Pallas path (blockwise
online-softmax, fp32 accumulators, causal block skipping, LSE saved for the
backward; dq and dk/dv backward kernels recompute probabilities per block so
the (s, s) matrix is never materialized) covers, on TPU:

* self-attention AND cross-attention (sq != sk, causal aligned bottom-right
  like the reference / flash-attn-2),
* per-batch KV valid lengths (`kv_lens` — the padding-mask form the CUDA
  kernel takes via cu_seqlens),
* segment ids (`segment_ids` / `kv_segment_ids` — packed-sequence masking,
  the TPU-native equivalent of flash_attn_unpadded's varlen batches),
* causal sliding windows (`window_size` — Mistral-style, with k-block
  skipping on both ends) and ALiBi (`alibi_slopes` — per-head linear
  bias applied inside the online softmax),
* odd head dims / short cross-KV via zero-padding (`_pad_for_kernel`),
* ARBITRARY DENSE MASKS (`attn_mask` (b|1, h|1, sq, sk), bool or
  additive float) — streamed as (blk_q, blk_k) tiles with all-masked
  prefix/suffix block skipping (`_mask_block_bounds`),
* IN-KERNEL ATTENTION DROPOUT — counter-based PRNG keyed on
  (seed, b, h, q-block, k-block) so the backward kernels regenerate the
  exact forward mask (`_dropout_keep`; the vendored flash-attn-2 does
  dropout in-kernel the same way),

forward and backward — the kernel-surface exclusion list is now EMPTY.
Kernels compute internally in (b, h, s, d) so the trailing block dims
meet TPU tiling (8, 128).
"""

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

NEG_INF = -1e30
LANES = 128

import logging

logger = logging.getLogger("paddle_tpu.ops.flash_attention")
_fallback_logged = False


def _log_fallback(which, e):
    global _fallback_logged
    if not _fallback_logged:
        _fallback_logged = True
        logger.warning(
            "Pallas flash attention %s failed (%s: %s); falling back to the "
            "XLA path. Set FLAGS_pallas_strict=1 to raise instead.",
            which, type(e).__name__, e)


def _repeat_kv(k, n_rep):
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.broadcast_to(k[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(
        b, s, h * n_rep, d)


def _structured_mask(sq, sk, is_causal, kv_lens, seg_q, seg_k,
                     window=None):
    """Dense (b, 1, sq, sk) or (1, 1, sq, sk) bool mask for the XLA path."""
    masks = []
    if is_causal:
        masks.append(jnp.tril(jnp.ones((sq, sk), bool),
                              k=sk - sq)[None, None])
    if window is not None:
        # sliding window (bottom-right aligned): q row i sees the last
        # `window` keys up to i + (sk - sq)
        dist = ((jnp.arange(sq)[:, None] + (sk - sq))
                - jnp.arange(sk)[None, :])
        masks.append((dist < window)[None, None])
    if kv_lens is not None:
        masks.append((jnp.arange(sk)[None, :] <
                      kv_lens[:, None])[:, None, None, :])
    if seg_q is not None:
        masks.append((seg_q[:, :, None] ==
                      seg_k[:, None, :])[:, None])
    if not masks:
        return None
    m = masks[0]
    for extra in masks[1:]:
        m = m & extra
    return m


def _xla_attention(q, k, v, attn_mask=None, is_causal=False, scale=None,
                   dropout_p=0.0, training=True, kv_lens=None,
                   seg_q=None, seg_k=None, window=None, alibi_slopes=None):
    b, sq, h, d = q.shape
    sk = k.shape[1]
    n_rep = h // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    # (b, h, sq, sk) scores in fp32 (f64 under x64 — keeps numeric-grad
    # checks meaningful)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.promote_types(
                            q.dtype, jnp.float32)) * scale
    if alibi_slopes is not None:
        dist = (jnp.arange(sk)[None, :]
                - (jnp.arange(sq)[:, None] + (sk - sq)))
        scores = scores + (alibi_slopes.astype(scores.dtype)[None, :, None,
                                                             None]
                           * dist.astype(scores.dtype)[None, None])
    structured = _structured_mask(sq, sk, is_causal, kv_lens, seg_q, seg_k,
                                  window=window)
    if structured is not None:
        scores = jnp.where(structured, scores, NEG_INF)
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            scores = jnp.where(attn_mask, scores, NEG_INF)
        else:
            scores = scores + attn_mask.astype(scores.dtype)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if structured is not None and (kv_lens is not None or seg_q is not None
                                   or sk < sq):
        # fully-masked rows emit 0 (flash-attn-2 convention; the Pallas
        # kernels match) instead of softmax's uniform garbage. Plain causal
        # self-attention can't produce empty rows — skip the extra pass.
        probs = jnp.where(structured.any(-1, keepdims=True), probs, 0.0)
    if dropout_p > 0.0 and training:
        from paddle_tpu.core import rng as _rng
        key = _rng.next_rng_key("dropout")
        keep = 1.0 - dropout_p
        mask = jax.random.bernoulli(key, keep, probs.shape)
        probs = jnp.where(mask, probs / keep, 0.0).astype(probs.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v).astype(q.dtype)


def flash_attention(q, k, v, dropout=0.0, causal=False, attn_mask=None,
                    training=True, scale=None, kv_lens=None,
                    segment_ids=None, kv_segment_ids=None,
                    window_size=None, alibi_slopes=None):
    """paddle.nn.functional.flash_attention parity. Returns (out, None)."""
    out = scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask, dropout_p=dropout, is_causal=causal,
        training=training, scale=scale, kv_lens=kv_lens,
        segment_ids=segment_ids, kv_segment_ids=kv_segment_ids,
        window_size=window_size, alibi_slopes=alibi_slopes)
    return out, None


def scaled_dot_product_attention(q, k, v, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, scale=None,
                                 kv_lens=None, segment_ids=None,
                                 kv_segment_ids=None, window_size=None,
                                 alibi_slopes=None):
    """Attention with the fused-kernel dispatch.

    TPU-native extensions beyond the reference veneer: `kv_lens` (b,) valid
    KV lengths (padding mask), `segment_ids` (b, sq) / `kv_segment_ids`
    (b, sk) packed-sequence masks (attention only within equal ids),
    `window_size` (int — causal sliding window, Mistral-style: each query
    sees the last `window_size` keys) and `alibi_slopes` ((num_heads,)
    fp32 — ALiBi linear bias, score += slope·(k_pos − q_pos)). All run
    inside the Pallas kernels forward AND backward; on other backends
    they lower to dense masks/bias on the XLA path.

    Float `attn_mask` caveat — the ≤ −1e9 "effectively masked" threshold:
    the Pallas path treats additive-mask entries ≤ −1e9 as FULLY masked
    (`_mask_block_bounds` skips blocks whose entries are all below it, and
    such scores never survive the online softmax). Use ≤ −1e9 (or −inf)
    to mean "masked", and keep finite soft penalties (score biases you
    want softmax to weigh) well above it. CONCRETE masks holding finite
    entries at or below the threshold that are not −inf (e.g. a −1e10
    soft penalty) are routed to the XLA path automatically so the two
    backends agree; a TRACED mask (built inside jit) can't be inspected,
    so there the threshold convention above is on the caller.
    """
    from paddle_tpu.ops import use_pallas
    seg_q = segment_ids
    seg_k = kv_segment_ids if kv_segment_ids is not None else segment_ids
    if (seg_q is None) != (seg_k is None):
        raise ValueError("segment_ids and kv_segment_ids must be given "
                         "together (or segment_ids alone when sq == sk)")
    if (segment_ids is not None and kv_segment_ids is None
            and q.shape[1] != k.shape[1]):
        raise ValueError(
            "segment_ids alone requires sq == sk; pass kv_segment_ids "
            f"explicitly for cross-attention (sq={q.shape[1]}, "
            f"sk={k.shape[1]})")
    if window_size is not None:
        window_size = int(window_size)
        if not is_causal:
            raise ValueError("window_size requires is_causal=True "
                             "(causal sliding window)")
        if window_size < 1:
            raise ValueError(f"window_size must be >= 1, got {window_size}")
    if alibi_slopes is not None:
        if not is_causal:
            raise ValueError(
                "alibi_slopes requires is_causal=True (the ALiBi bias is "
                "defined over causal distances; a non-causal form would "
                "reward distant FUTURE keys)")
        # slopes are fixed constants in the ALiBi formulation (a geometric
        # head schedule, not learned) — stop_gradient keeps the Pallas and
        # XLA paths consistent (the kernels do not compute dL/dslopes)
        alibi_slopes = jax.lax.stop_gradient(
            jnp.asarray(alibi_slopes, jnp.float32))
        if alibi_slopes.shape != (q.shape[2],):
            raise ValueError(
                f"alibi_slopes must be (num_heads,)=({q.shape[2]},), got "
                f"{alibi_slopes.shape}")
    # Pallas path: TPU, seq dims multiples of 128 and long enough to beat
    # XLA. Shapes the kernel can't take directly may still ride it via
    # _pad_for_kernel (odd head dims, short cross-KV). Round 5 closed the
    # last two kernel-surface gaps: ARBITRARY DENSE MASKS ((b|1, h|1, sq,
    # sk) bool or additive float, streamed as tiles with all-masked-block
    # skipping) and IN-KERNEL ATTENTION DROPOUT (counter-based PRNG keyed
    # on (seed, b, h, q-block, k-block), identical fwd/bwd masks).
    eff_dropout = float(dropout_p) if training else 0.0
    kmask = _kernel_mask(attn_mask, q.shape, k.shape)
    pallas_ok = use_pallas() and (attn_mask is None or kmask is not None)
    if (pallas_ok and kmask is not None
            and jnp.issubdtype(kmask.dtype, jnp.floating)
            and not isinstance(kmask, jax.core.Tracer)):
        # Finite soft penalties at/below the −1e9 "effectively masked"
        # threshold (e.g. −1e10) would be block-skipped EXACTLY on the
        # Pallas path but only exponentially suppressed by XLA's softmax.
        # A concrete mask can be inspected: route such masks to the XLA
        # path so the backends agree (−inf means "masked" and stays
        # kernel-eligible). The reduction runs ON DEVICE — only the bool
        # verdict syncs to host, not the (b, h, sq, sk) mask itself —
        # and the verdict is CACHED per mask object, so only the first
        # eager call with a given mask pays it (under jit the whole
        # branch traces once; r5 item flagged by the PR 3 review).
        # tpu-lint: allow(traced-branch): guarded by the Tracer
        # isinstance above — this branch only runs on CONCRETE masks
        if _float_mask_probe(attn_mask, kmask):
            pallas_ok = False
    if pallas_ok:
        padded = _pad_for_kernel(q, k, v, is_causal, scale, kv_lens, seg_k)
        if padded is not None:
            qp, kp, vp, scale_p, klp, skp, hd = padded
            if kmask is not None and kp.shape[1] != kmask.shape[3]:
                pad_v = False if kmask.dtype == jnp.int8 else 0.0
                kmask = jnp.pad(
                    kmask, ((0, 0), (0, 0), (0, 0),
                            (0, kp.shape[1] - kmask.shape[3])),
                    constant_values=pad_v)   # pad cols masked via kv_lens
            try:
                out = _flash_call(qp, kp, vp, is_causal, scale_p, klp,
                                  seg_q, skp, window=window_size,
                                  alibi_slopes=alibi_slopes, mask=kmask,
                                  dropout_p=eff_dropout)
                return out if out.shape[-1] == hd else out[..., :hd]
            except Exception as e:
                from paddle_tpu.core.flags import flag
                if flag("FLAGS_pallas_strict"):
                    raise
                _log_fallback("forward", e)
    return _xla_attention(q, k, v, attn_mask=attn_mask, is_causal=is_causal,
                          scale=scale, dropout_p=dropout_p,
                          training=training, kv_lens=kv_lens,
                          seg_q=seg_q, seg_k=seg_k, window=window_size,
                          alibi_slopes=alibi_slopes)


# verdict cache for the eager concrete-float-mask probe, keyed by the
# id() of the USER-PASSED mask object with a weakref guard: the guard
# proves the id still names the same live array (a dead entry is removed
# by the weakref callback during dealloc, before the id can be reused,
# and `ref() is mask` re-checks anyway). Only IMMUTABLE jax.Arrays are
# cached — a numpy mask can be written in place between calls, which
# would make a cached verdict silently stale. Bounded by mask lifetimes,
# not call count — serving loops reuse one mask array across thousands
# of eager calls and now pay the full-mask reduction + host sync once.
_float_mask_verdicts = {}


def _float_mask_probe(attn_mask, kmask) -> bool:
    """True when the concrete float mask holds finite entries at/below
    the −1e9 threshold (not −inf) — i.e. must route to the XLA path."""
    import weakref

    cacheable = isinstance(attn_mask, jax.Array) \
        and not isinstance(attn_mask, jax.core.Tracer)
    mid = id(attn_mask)
    if cacheable:
        entry = _float_mask_verdicts.get(mid)
        if entry is not None and entry[0]() is attn_mask:
            return entry[1]
    # tpu-lint: allow(host-sync): deliberate one-time sync — only the
    # bool verdict crosses to host, cached per mask object (weakref)
    verdict = bool(jnp.any((kmask <= -1e9) & ~jnp.isneginf(kmask)))
    if not cacheable:
        return verdict
    try:
        ref = weakref.ref(attn_mask,
                          lambda _r, _i=mid: _float_mask_verdicts.pop(_i,
                                                                      None))
    except TypeError:        # array type without weakref support
        return verdict
    _float_mask_verdicts[mid] = (ref, verdict)
    return verdict


def _kernel_mask(attn_mask, q_shape, k_shape):
    """Canonicalize a dense attn_mask for the kernels: 4-D with
    broadcastable batch/head dims and exact (sq, sk) trailing dims.
    bool masks become int8 (Mosaic has no bool operands); additive float
    masks pass through. Returns None when the shape can't ride."""
    if attn_mask is None:
        return None
    m = jnp.asarray(attn_mask)
    if m.ndim == 2:
        m = m[None, None]
    elif m.ndim == 3:
        m = m[:, None]
    if m.ndim != 4:
        return None
    b, sq, h = q_shape[0], q_shape[1], q_shape[2]
    sk = k_shape[1]
    if m.shape[2:] != (sq, sk):
        return None
    if m.shape[0] not in (1, b) or m.shape[1] not in (1, h):
        return None
    if m.dtype == jnp.bool_:
        return m.astype(jnp.int8)
    if jnp.issubdtype(m.dtype, jnp.floating):
        return m.astype(jnp.float32)
    return None


def _pad_for_kernel(q, k, v, is_causal, scale, kv_lens, seg_k):
    """Kernel-eligible (q, k, v, scale, kv_lens, seg_k, orig_hd), padding
    where needed — or None when the shape can't ride the kernel.

    Odd head_dims (SD-1.5's 40/80/160) zero-pad to the next supported lane
    width — exact: zero q/k lanes add 0 to every score and the v pad lanes
    are sliced away by the caller. Short cross-attention KV (e.g. 77 text
    tokens) pads to the next 128 block with kv_lens masking (pad seg ids
    get -1, matching no query segment). Causal with a padded KV is
    excluded (the bottom-right alignment would shift)."""
    hd = q.shape[-1]
    sk = k.shape[1]
    hd_t = hd if hd in (64, 128, 256) else next(
        (t for t in (64, 128, 256) if t >= hd), None)
    sk_t = -(-sk // 128) * 128
    if (hd_t is None or not _pallas_seq_ok(q.shape[1], sk_t)
            or (is_causal and sk_t != sk)):
        return None
    if hd_t == hd and sk_t == sk:
        return q, k, v, scale, kv_lens, seg_k, hd
    scale = scale if scale is not None else 1.0 / math.sqrt(hd)
    if sk_t != sk:
        kv_lens = (jnp.full((q.shape[0],), sk, jnp.int32)
                   if kv_lens is None else jnp.minimum(kv_lens, sk))
        if seg_k is not None:
            seg_k = jnp.pad(seg_k, ((0, 0), (0, sk_t - sk)),
                            constant_values=-1)
    q = jnp.pad(q, ((0, 0), (0, 0), (0, 0), (0, hd_t - hd)))
    pad_kv = ((0, 0), (0, sk_t - sk), (0, 0), (0, hd_t - hd))
    return q, jnp.pad(k, pad_kv), jnp.pad(v, pad_kv), scale, kv_lens, \
        seg_k, hd


# ---- Pallas kernels (internal layout (b, h, s, d)) -------------------------

def _pick_blk(s):
    """Largest block in (512, 256, 128) dividing s — lets the kernels
    cover any s % 128 == 0, not just 512-multiples."""
    for blk in (512, 256, 128):
        if s % blk == 0:
            return blk
    raise ValueError(f"seq {s} not a multiple of 128")


def _causal_nk(qi, blk_q, blk_k, off, sk):
    """Number of k-blocks a causal q-block attends to (bottom-right
    aligned: q row i sees k cols <= i + off)."""
    hi = qi * blk_q + blk_q - 1 + off          # last visible k col
    return jnp.clip((hi // blk_k) + 1, 0, sk // blk_k)


def _block_mask(s_blk, qi, ki, blk_q, blk_k, off, is_causal,
                kvlen_b, segq_blk, segk_ref, window=None, alibi=None,
                mask_at=None):
    """Apply the structured masks to one (blk_q, blk_k) score block.

    kvlen_b: scalar valid length or None; segq_blk: (blk_q, 1) ids or
    None; segk_ref: callable ki -> (1, blk_k) ids; window: static int
    sliding-window width (causal: q row i sees the last `window` keys up
    to i + off); alibi: this head's ALiBi slope (traced fp32 scalar) —
    score += slope · (k_pos − q_pos − off), the standard ≤ 0 linear bias;
    mask_at: callable ki -> (blk_q, blk_k) DENSE mask tile — bool (False
    = masked) or additive float (the reference attn_mask semantics)."""
    k_pos = ki * blk_k + lax.broadcasted_iota(
        jnp.int32, (blk_q, blk_k), 1)
    if is_causal or window is not None or alibi is not None:
        q_pos = qi * blk_q + lax.broadcasted_iota(
            jnp.int32, (blk_q, blk_k), 0)
    if alibi is not None:
        s_blk = s_blk + alibi * (k_pos - q_pos - off).astype(jnp.float32)
    if is_causal:
        s_blk = jnp.where(q_pos + off >= k_pos, s_blk, NEG_INF)
    if window is not None:
        s_blk = jnp.where(q_pos + off - k_pos < window, s_blk, NEG_INF)
    if kvlen_b is not None:
        s_blk = jnp.where(k_pos < kvlen_b, s_blk, NEG_INF)
    if segq_blk is not None:
        s_blk = jnp.where(segq_blk == segk_ref(ki), s_blk, NEG_INF)
    if mask_at is not None:
        mb = mask_at(ki)
        if mb.dtype in (jnp.bool_, jnp.int8):   # bool masks ride as int8
            s_blk = jnp.where(mb != 0, s_blk, NEG_INF)
        else:
            s_blk = s_blk + mb.astype(jnp.float32)
    return s_blk


def _dropout_keep(pltpu, seed_ref, block_id, blk_q, blk_k, keep_p):
    """Counter-based in-kernel dropout mask for one (qi, ki) score block
    (the vendored flash-attn-2 does dropout in-kernel the same way —
    canonical phi/kernels/gpu/flash_attn_kernel.cu). Reseeding the Mosaic
    PRNG on (seed, block_id) — block_id folds (b, h, q-block, k-block)
    into one int32, Mosaic's prng_seed takes at most two values — makes
    the mask a pure function of the block coordinates, so the dq (loops
    ki per qi) and dk/dv (loops qi per ki) backward kernels regenerate
    the exact forward mask regardless of their iteration order."""
    pltpu.prng_seed(seed_ref[0], block_id)
    bits = pltpu.bitcast(pltpu.prng_random_bits((blk_q, blk_k)),
                         jnp.uint32)
    return bits < jnp.uint32(min(int(keep_p * 4294967296.0), 4294967295))


def _drop_block_id(bi, hi, qi, ki, h, nq, nk):
    return ((bi * h + hi) * nq + qi) * nk + ki


def _mask_block_bounds(mask, b, h, nq, nk, blk_q, blk_k, axis_q=True):
    """Per-(b, h, row-block) [lo, hi) k-block bounds (or per-k-block q
    bounds when axis_q=False) for all-masked-block SKIPPING: prefix and
    suffix blocks with no unmasked entry are never touched. Returns two
    (b, h, n) int32 arrays (broadcast dims expanded)."""
    valid = (mask != 0) if mask.dtype in (jnp.bool_, jnp.int8) \
        else (mask > -1e9)
    mb, mh = valid.shape[0], valid.shape[1]
    blocks = valid.reshape(mb, mh, nq, blk_q, nk, blk_k).any(axis=(3, 5))
    if not axis_q:
        blocks = jnp.swapaxes(blocks, 2, 3)       # (mb, mh, nk, nq)
    n = blocks.shape[3]
    has = blocks.any(-1)
    lo = jnp.where(has, jnp.argmax(blocks, -1), 0).astype(jnp.int32)
    hi = jnp.where(has, n - jnp.argmax(blocks[..., ::-1], -1),
                   0).astype(jnp.int32)
    tgt = (b, h, blocks.shape[2])
    return (jnp.broadcast_to(lo, tgt), jnp.broadcast_to(hi, tgt))


def _window_k0(qi, blk_q, blk_k, off, window):
    """First k-block a sliding-window q-block can see (block skipping):
    q row q_pos attends k in (q_pos + off − window, q_pos + off]."""
    lo = qi * blk_q + off - window + 1          # first visible k col
    return jnp.clip(lo // blk_k, 0, None)


def _seg_specs():
    """Builder for (b, 1, s) segment-id BlockSpecs: spec(blk, full) blocks
    the axis by `blk` indexed by the grid's third dim, or takes the whole
    `full` axis when blk is None."""
    from jax.experimental import pallas as pl

    def spec(blk, full):
        if blk is None:
            return pl.BlockSpec((None, 1, full),
                                lambda bi, hi, i: (bi, 0, 0))
        return pl.BlockSpec((None, 1, blk), lambda bi, hi, i: (bi, 0, i))

    return spec


def _build_operands(qt, kt, vt, kv_lens, seg_q, seg_k, extra,
                    alibi_slopes=None, mask=None, bounds=None, seed=None):
    """Shared operand assembly: [q, k, v, (lens), (segq, segk), (alibi),
    (mask, lo, hi), (seed)] + extra."""
    ops = [qt, kt, vt]
    if kv_lens is not None:
        ops.append(kv_lens.astype(jnp.int32))
    if seg_q is not None:
        ops.append(seg_q.astype(jnp.int32)[:, None])   # (b, 1, sq)
        ops.append(seg_k.astype(jnp.int32)[:, None])   # (b, 1, sk)
    if alibi_slopes is not None:
        ops.append(alibi_slopes.astype(jnp.float32))   # (h,)
    if mask is not None:
        ops.append(mask)                               # (mb, mh, sq, sk)
        ops.extend(bounds)                             # lo, hi (b, h, n)
    if seed is not None:
        ops.append(seed)                               # (1,) int32
    return ops + extra


def _mask_specs(pl, pltpu, mask, blk_row, full_col, row_axis_q=True):
    """BlockSpecs for [mask-tile, lo, hi]: the mask streams one
    (blk_q, sk) row band (or (sq, blk_k) column band for the dkv kernel)
    per grid step, broadcast dims pinned by index-map clamping; the lo/hi
    skip bounds ride SMEM whole."""
    mb, mh = mask.shape[0], mask.shape[1]

    def imap(bi, hi, i):
        bm = jnp.minimum(bi, mb - 1)
        hm = jnp.minimum(hi, mh - 1)
        return (bm, hm, i, 0) if row_axis_q else (bm, hm, 0, i)

    shape = ((None, None, blk_row, full_col) if row_axis_q
             else (None, None, full_col, blk_row))
    return [pl.BlockSpec(shape, imap),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM)]


def _fwd_kernels(qt, kt, vt, is_causal, sc, kv_lens=None, seg_q=None,
                 seg_k=None, window=None, alibi_slopes=None, mask=None,
                 dropout_p=0.0, seed=None):
    """qt (b,h,sq,d), kt/vt (b,h,sk,d) → (out (b,h,sq,d), lse (b,h,sq)).

    mask: dense (mb, mh, sq, sk) bool/float attn_mask (broadcast dims
    allowed) streamed as (blk_q, sk) row bands, with all-masked prefix/
    suffix k-blocks skipped. dropout_p/seed: in-kernel counter-based
    attention dropout (see _dropout_keep) — probabilities drop AFTER the
    softmax statistics accumulate, matching standard dropout(softmax(s))
    semantics; the output folds the 1/keep rescale into the final
    normalization."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = qt.shape
    sk = kt.shape[2]
    blk_q = _pick_blk(sq)
    blk_k = _pick_blk(sk)
    off = sk - sq
    grid = (b, h, sq // blk_q)
    has_len = kv_lens is not None
    has_seg = seg_q is not None
    has_alibi = alibi_slopes is not None
    has_mask = mask is not None
    has_drop = dropout_p > 0.0
    keep_p = 1.0 - dropout_p
    bounds = (_mask_block_bounds(mask, b, h, sq // blk_q, sk // blk_k,
                                 blk_q, blk_k) if has_mask else None)

    def kernel(*refs):
        i = 3
        lens_ref = refs[i] if has_len else None
        i += has_len
        segq_ref = refs[i] if has_seg else None
        segk_ref = refs[i + 1] if has_seg else None
        i += 2 * has_seg
        slopes_ref = refs[i] if has_alibi else None
        i += has_alibi
        mask_ref = refs[i] if has_mask else None
        mlo_ref = refs[i + 1] if has_mask else None
        mhi_ref = refs[i + 2] if has_mask else None
        i += 3 * has_mask
        seed_ref = refs[i] if has_drop else None
        i += has_drop
        q_ref, k_ref, v_ref = refs[0], refs[1], refs[2]
        o_ref, lse_ref = refs[i], refs[i + 1]

        bi = pl.program_id(0)
        hi_ = pl.program_id(1)
        qi = pl.program_id(2)
        qv = q_ref[...].astype(jnp.float32) * sc  # (blk_q, d)
        kvlen_b = lens_ref[bi] if has_len else None
        alibi = slopes_ref[hi_] if has_alibi else None
        segq_blk = (jnp.transpose(segq_ref[...], (1, 0))
                    if has_seg else None)          # (blk_q, 1)
        seg_at = (lambda ki: segk_ref[:, pl.ds(ki * blk_k, blk_k)]) \
            if has_seg else None
        mask_at = (lambda ki: mask_ref[:, pl.ds(ki * blk_k, blk_k)]) \
            if has_mask else None

        def body(ki, carry):
            acc, m_prev, l_prev = carry
            kv = k_ref[pl.ds(ki * blk_k, blk_k), :].astype(jnp.float32)
            vv = v_ref[pl.ds(ki * blk_k, blk_k), :].astype(jnp.float32)
            s_blk = qv @ kv.T  # (blk_q, blk_k)
            s_blk = _block_mask(s_blk, qi, ki, blk_q, blk_k, off,
                                is_causal, kvlen_b, segq_blk, seg_at,
                                window=window, alibi=alibi,
                                mask_at=mask_at)
            m_cur = jnp.maximum(m_prev, jnp.max(s_blk, axis=-1))
            alpha = jnp.exp(m_prev - m_cur)
            # rows with no valid entry yet keep m at NEG_INF — their p
            # must be 0, not exp(0), so fully-masked rows emit 0
            p = jnp.where(m_cur[:, None] <= NEG_INF * 0.5, 0.0,
                          jnp.exp(s_blk - m_cur[:, None]))
            l_cur = l_prev * alpha + jnp.sum(p, axis=-1)
            if has_drop:   # l accumulates UNdropped p (flash-attn-2)
                p = jnp.where(
                    _dropout_keep(pltpu, seed_ref,
                                  _drop_block_id(bi, hi_, qi, ki, h,
                                                 sq // blk_q, sk // blk_k),
                                  blk_q, blk_k, keep_p), p, 0.0)
            acc = acc * alpha[:, None] + p @ vv
            return acc, m_cur, l_cur

        acc0 = jnp.zeros((blk_q, d), jnp.float32)
        m0 = jnp.full((blk_q,), NEG_INF, jnp.float32)
        l0 = jnp.zeros((blk_q,), jnp.float32)
        n_k = _causal_nk(qi, blk_q, blk_k, off, sk) if is_causal \
            else sk // blk_k
        if has_len:   # skip k-blocks entirely past the valid length
            n_k = jnp.minimum(n_k, (kvlen_b + blk_k - 1) // blk_k)
        k0 = _window_k0(qi, blk_q, blk_k, off, window) if window else 0
        if has_mask:  # all-masked prefix/suffix block skipping
            k0 = jnp.maximum(k0, mlo_ref[bi, hi_, qi])
            n_k = jnp.minimum(n_k, mhi_ref[bi, hi_, qi])
        acc, m, l = lax.fori_loop(k0, n_k, body, (acc0, m0, l0))
        lsafe = jnp.where(l == 0.0, 1.0, l)
        norm = lsafe * keep_p if has_drop else lsafe
        o_ref[...] = (acc / norm[:, None]).astype(o_ref.dtype)
        # TPU tiling wants 2-D trailing blocks: replicate lse across lanes
        lse_ref[...] = jnp.broadcast_to((m + jnp.log(lsafe))[:, None],
                                        (qv.shape[0], LANES))

    qspec = pl.BlockSpec((None, None, blk_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0))
    kfull = lambda: pl.BlockSpec((None, None, sk, d),
                                 lambda bi, hi, qi: (bi, hi, 0, 0))
    in_specs = [qspec, kfull(), kfull()]
    if has_len:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
    if has_seg:
        spec = _seg_specs()
        in_specs += [spec(blk_q, sq), spec(None, sk)]
    if has_alibi:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
    if has_mask:
        in_specs += _mask_specs(pl, pltpu, mask, blk_q, sk)
    if has_drop:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))

    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((None, None, blk_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, blk_q, LANES),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), qt.dtype),
            jax.ShapeDtypeStruct((b, h, sq, LANES), jnp.float32),
        ],
    )(*_build_operands(qt, kt, vt, kv_lens, seg_q, seg_k, [],
                       alibi_slopes=alibi_slopes, mask=mask, bounds=bounds,
                       seed=seed))
    return out, lse


def _bwd_dq_kernel(qt, kt, vt, dot, lse, delta, is_causal, sc,
                   kv_lens=None, seg_q=None, seg_k=None, window=None,
                   alibi_slopes=None, mask=None, dropout_p=0.0, seed=None):
    """dq: loop over k-blocks for each q-block."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = qt.shape
    sk = kt.shape[2]
    blk_q = _pick_blk(sq)
    blk_k = _pick_blk(sk)
    off = sk - sq
    grid = (b, h, sq // blk_q)
    has_len = kv_lens is not None
    has_seg = seg_q is not None
    has_alibi = alibi_slopes is not None
    has_mask = mask is not None
    has_drop = dropout_p > 0.0
    keep_p = 1.0 - dropout_p
    bounds = (_mask_block_bounds(mask, b, h, sq // blk_q, sk // blk_k,
                                 blk_q, blk_k) if has_mask else None)

    def kernel(*refs):
        i = 3
        lens_ref = refs[i] if has_len else None
        i += has_len
        segq_ref = refs[i] if has_seg else None
        segk_ref = refs[i + 1] if has_seg else None
        i += 2 * has_seg
        slopes_ref = refs[i] if has_alibi else None
        i += has_alibi
        mask_ref = refs[i] if has_mask else None
        mlo_ref = refs[i + 1] if has_mask else None
        mhi_ref = refs[i + 2] if has_mask else None
        i += 3 * has_mask
        seed_ref = refs[i] if has_drop else None
        i += has_drop
        q_ref, k_ref, v_ref = refs[0], refs[1], refs[2]
        do_ref, lse_ref, dl_ref, dq_ref = refs[i:i + 4]

        bi = pl.program_id(0)
        hi_ = pl.program_id(1)
        qi = pl.program_id(2)
        qv = q_ref[...].astype(jnp.float32)
        do = do_ref[...].astype(jnp.float32)          # (blk_q, d)
        lse_q = lse_ref[...][:, 0]                    # (blk_q,)
        delta_q = dl_ref[...][:, 0]                   # (blk_q,)
        kvlen_b = lens_ref[bi] if has_len else None
        alibi = slopes_ref[hi_] if has_alibi else None
        segq_blk = (jnp.transpose(segq_ref[...], (1, 0))
                    if has_seg else None)
        seg_at = (lambda ki: segk_ref[:, pl.ds(ki * blk_k, blk_k)]) \
            if has_seg else None
        mask_at = (lambda ki: mask_ref[:, pl.ds(ki * blk_k, blk_k)]) \
            if has_mask else None

        def body(ki, dq_acc):
            kv = k_ref[pl.ds(ki * blk_k, blk_k), :].astype(jnp.float32)
            vv = v_ref[pl.ds(ki * blk_k, blk_k), :].astype(jnp.float32)
            s_blk = (qv @ kv.T) * sc
            s_blk = _block_mask(s_blk, qi, ki, blk_q, blk_k, off,
                                is_causal, kvlen_b, segq_blk, seg_at,
                                window=window, alibi=alibi,
                                mask_at=mask_at)
            p = jnp.where(lse_q[:, None] <= NEG_INF * 0.5, 0.0,
                          jnp.exp(s_blk - lse_q[:, None]))
            dp = do @ vv.T                            # (blk_q, blk_k)
            if has_drop:   # regenerate the forward's block mask
                dp = jnp.where(
                    _dropout_keep(pltpu, seed_ref,
                                  _drop_block_id(bi, hi_, qi, ki, h,
                                                 sq // blk_q, sk // blk_k),
                                  blk_q, blk_k, keep_p),
                    dp * (1.0 / keep_p), 0.0)
            ds = p * (dp - delta_q[:, None])
            return dq_acc + (ds @ kv) * sc

        n_k = _causal_nk(qi, blk_q, blk_k, off, sk) if is_causal \
            else sk // blk_k
        if has_len:
            n_k = jnp.minimum(n_k, (kvlen_b + blk_k - 1) // blk_k)
        k0 = _window_k0(qi, blk_q, blk_k, off, window) if window else 0
        if has_mask:
            k0 = jnp.maximum(k0, mlo_ref[bi, hi_, qi])
            n_k = jnp.minimum(n_k, mhi_ref[bi, hi_, qi])
        dq = lax.fori_loop(k0, n_k, body,
                           jnp.zeros((blk_q, d), jnp.float32))
        dq_ref[...] = dq.astype(dq_ref.dtype)

    kfull = lambda: pl.BlockSpec((None, None, sk, d),
                                 lambda bi, hi, qi: (bi, hi, 0, 0))
    qblk = lambda: pl.BlockSpec((None, None, blk_q, d),
                                lambda bi, hi, qi: (bi, hi, qi, 0))
    row = lambda: pl.BlockSpec((None, None, blk_q, LANES),
                               lambda bi, hi, qi: (bi, hi, qi, 0))
    in_specs = [qblk(), kfull(), kfull()]
    if has_len:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
    if has_seg:
        spec = _seg_specs()
        in_specs += [spec(blk_q, sq), spec(None, sk)]
    if has_alibi:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
    if has_mask:
        in_specs += _mask_specs(pl, pltpu, mask, blk_q, sk)
    if has_drop:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
    in_specs += [qblk(), row(), row()]
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=qblk(),
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), qt.dtype),
    )(*_build_operands(qt, kt, vt, kv_lens, seg_q, seg_k,
                       [dot, lse, delta], alibi_slopes=alibi_slopes,
                       mask=mask, bounds=bounds, seed=seed))


def _bwd_dkv_kernel(qt, kt, vt, dot, lse, delta, is_causal, sc,
                    kv_lens=None, seg_q=None, seg_k=None, window=None,
                    alibi_slopes=None, mask=None, dropout_p=0.0,
                    seed=None):
    """dk, dv: loop over q-blocks for each k-block."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = qt.shape
    sk = kt.shape[2]
    blk_q = _pick_blk(sq)
    blk_k = _pick_blk(sk)
    off = sk - sq
    grid = (b, h, sk // blk_k)
    has_len = kv_lens is not None
    has_seg = seg_q is not None
    has_alibi = alibi_slopes is not None
    has_mask = mask is not None
    has_drop = dropout_p > 0.0
    keep_p = 1.0 - dropout_p
    bounds = (_mask_block_bounds(mask, b, h, sq // blk_q, sk // blk_k,
                                 blk_q, blk_k, axis_q=False)
              if has_mask else None)

    def kernel(*refs):
        i = 3
        lens_ref = refs[i] if has_len else None
        i += has_len
        segq_ref = refs[i] if has_seg else None
        segk_ref = refs[i + 1] if has_seg else None
        i += 2 * has_seg
        slopes_ref = refs[i] if has_alibi else None
        i += has_alibi
        mask_ref = refs[i] if has_mask else None
        mlo_ref = refs[i + 1] if has_mask else None
        mhi_ref = refs[i + 2] if has_mask else None
        i += 3 * has_mask
        seed_ref = refs[i] if has_drop else None
        i += has_drop
        q_ref, k_ref, v_ref = refs[0], refs[1], refs[2]
        do_ref, lse_ref, dl_ref, dk_ref, dv_ref = refs[i:i + 5]

        bi = pl.program_id(0)
        hi_ = pl.program_id(1)
        ki = pl.program_id(2)
        kv = k_ref[...].astype(jnp.float32)           # (blk_k, d)
        vv = v_ref[...].astype(jnp.float32)
        kvlen_b = lens_ref[bi] if has_len else None
        alibi = slopes_ref[hi_] if has_alibi else None
        # k-side ids for THIS block, as (1, blk_k); q-side read per block
        segk_blk = segk_ref[...] if has_seg else None
        seg_at = (lambda _ki: segk_blk) if has_seg else None

        def body(qi, carry):
            dk_acc, dv_acc = carry
            qv = q_ref[pl.ds(qi * blk_q, blk_q), :].astype(jnp.float32)
            do = do_ref[pl.ds(qi * blk_q, blk_q), :].astype(jnp.float32)
            lse_q = lse_ref[pl.ds(qi * blk_q, blk_q), 0]
            delta_q = dl_ref[pl.ds(qi * blk_q, blk_q), 0]
            s_blk = (qv @ kv.T) * sc                  # (blk_q, blk_k)
            segq_blk = (jnp.transpose(
                segq_ref[:, pl.ds(qi * blk_q, blk_q)], (1, 0))
                if has_seg else None)
            # mask column band for THIS k-block, rows sliced per q-block
            # (slice the REF, not a loaded value — dynamic starts only
            # exist at the ref level)
            mask_at = ((lambda _ki: mask_ref[pl.ds(qi * blk_q, blk_q), :])
                       if has_mask else None)
            s_blk = _block_mask(s_blk, qi, ki, blk_q, blk_k, off,
                                is_causal, kvlen_b, segq_blk, seg_at,
                                window=window, alibi=alibi,
                                mask_at=mask_at)
            p = jnp.where(lse_q[:, None] <= NEG_INF * 0.5, 0.0,
                          jnp.exp(s_blk - lse_q[:, None]))
            dp = do @ vv.T
            if has_drop:   # same (bi, hi, qi, ki)-keyed mask as forward
                dmask = _dropout_keep(pltpu, seed_ref,
                                      _drop_block_id(bi, hi_, qi, ki, h,
                                                     sq // blk_q,
                                                     sk // blk_k),
                                      blk_q, blk_k, keep_p)
                dv_acc = dv_acc + jnp.where(
                    dmask, p * (1.0 / keep_p), 0.0).T @ do
                dp = jnp.where(dmask, dp * (1.0 / keep_p), 0.0)
            else:
                dv_acc = dv_acc + p.T @ do
            ds = p * (dp - delta_q[:, None])
            dk_acc = dk_acc + (ds.T @ qv) * sc
            return dk_acc, dv_acc

        n_q = sq // blk_q
        if is_causal:
            # only q rows with q_pos + off >= ki*blk_k see this k-block
            q0 = jnp.clip((ki * blk_k - off) // blk_q, 0, n_q)
        else:
            q0 = 0
        q_hi = n_q
        if window is not None:
            # sliding window: q rows past k_pos + window - 1 - off can't
            # see this k-block (loose block bound; the mask is exact)
            q_hi = jnp.clip(
                (ki * blk_k + blk_k - 1 + window - off) // blk_q + 1,
                0, n_q)
        if has_mask:
            q0 = jnp.maximum(q0, mlo_ref[bi, hi_, ki])
            q_hi = jnp.minimum(q_hi, mhi_ref[bi, hi_, ki])
        dk, dv = lax.fori_loop(q0, q_hi, body,
                               (jnp.zeros((blk_k, d), jnp.float32),
                                jnp.zeros((blk_k, d), jnp.float32)))
        dk_ref[...] = dk.astype(dk_ref.dtype)
        dv_ref[...] = dv.astype(dv_ref.dtype)

    qfull = lambda: pl.BlockSpec((None, None, sq, d),
                                 lambda bi, hi, ki: (bi, hi, 0, 0))
    kblk = lambda: pl.BlockSpec((None, None, blk_k, d),
                                lambda bi, hi, ki: (bi, hi, ki, 0))
    frow = lambda: pl.BlockSpec((None, None, sq, LANES),
                                lambda bi, hi, ki: (bi, hi, 0, 0))
    in_specs = [qfull(), kblk(), kblk()]
    if has_len:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
    if has_seg:
        spec = _seg_specs()
        in_specs += [spec(None, sq), spec(blk_k, sk)]
    if has_alibi:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
    if has_mask:
        in_specs += _mask_specs(pl, pltpu, mask, blk_k, sq,
                                row_axis_q=False)
    if has_drop:
        in_specs.append(pl.BlockSpec(memory_space=pltpu.SMEM))
    in_specs += [qfull(), frow(), frow()]
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[kblk(), kblk()],
        out_shape=[jax.ShapeDtypeStruct((b, h, sk, d), qt.dtype),
                   jax.ShapeDtypeStruct((b, h, sk, d), qt.dtype)],
    )(*_build_operands(qt, kt, vt, kv_lens, seg_q, seg_k,
                       [dot, lse, delta], alibi_slopes=alibi_slopes,
                       mask=mask, bounds=bounds, seed=seed))


@functools.partial(jax.jit, static_argnames=("is_causal", "scale"))
def _flash_attention_pallas(q, k, v, is_causal: bool, scale: Optional[float]):
    """Forward-only entry (bench/eval); (b, s, h, d) in and out."""
    out, _ = _flash_fwd(q, k, v, is_causal, scale)
    return out


def _flash_fwd(q, k, v, is_causal, scale, kv_lens=None, seg_q=None,
               seg_k=None, window=None, alibi_slopes=None, mask=None,
               dropout_p=0.0, seed=None):
    b, sq, h, d = q.shape
    n_rep = h // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    sc = scale if scale is not None else 1.0 / math.sqrt(d)
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    out_t, lse = _fwd_kernels(qt, kt, vt, is_causal, sc, kv_lens=kv_lens,
                              seg_q=seg_q, seg_k=seg_k, window=window,
                              alibi_slopes=alibi_slopes, mask=mask,
                              dropout_p=dropout_p, seed=seed)
    return jnp.transpose(out_t, (0, 2, 1, 3)), lse


def _float0_like(a):
    return np.zeros(a.shape, jax.dtypes.float0) if a is not None else None


def _flash_call(q, k, v, is_causal, scale, kv_lens, seg_q, seg_k,
                window=None, alibi_slopes=None, mask=None,
                dropout_p=0.0):
    """Differentiable entry covering all structured-mask forms, dense
    masks and in-kernel dropout."""
    flags = (kv_lens is not None, seg_q is not None,
             alibi_slopes is not None, mask is not None, dropout_p > 0.0)
    dummy_len = kv_lens if flags[0] else jnp.zeros((1,), jnp.int32)
    dummy_sq = seg_q if flags[1] else jnp.zeros((1, 1), jnp.int32)
    dummy_sk = seg_k if flags[1] else jnp.zeros((1, 1), jnp.int32)
    dummy_al = (alibi_slopes if flags[2]
                else jnp.zeros((1,), jnp.float32))
    dummy_mk = mask if flags[3] else jnp.zeros((1, 1, 1, 1), jnp.int8)
    if flags[4]:
        from paddle_tpu.core import rng as _rng
        if not _rng.has_rng("dropout"):
            # Under jit tracing with no bound stream the fallback key
            # would be baked into the executable as a CONSTANT: every call
            # of the compiled function reapplies the exact same dropout
            # mask — silently biased training. Unlike the eager-friendly
            # warning in next_rng_key, in-kernel dropout refuses to trace.
            try:
                from jax._src import core as _core
                traced = not _core.trace_state_clean()
            except (ImportError, AttributeError):
                # private probe symbol: module or attribute may be gone
                # on other jax versions — treat as eager (warn path)
                traced = False
            if traced:
                raise RuntimeError(
                    "flash_attention dropout under jit with no bound "
                    "'dropout' rng stream: the kernel seed would become a "
                    "compile-time constant, reusing one dropout mask for "
                    "every call. Bind a stream with rng_guard(dropout=key)"
                    " or functional_call(..., rngs={'dropout': key}).")
        seed = jax.random.randint(_rng.next_rng_key("dropout"),
                                  (1,), -2 ** 31, 2 ** 31 - 1, jnp.int32)
    else:
        seed = jnp.zeros((1,), jnp.int32)
    return _flash_vjp_entry(q, k, v, dummy_len, dummy_sq, dummy_sk,
                            dummy_al, dummy_mk, seed, flags, is_causal,
                            scale, window, float(dropout_p))


def _mask_kw(kv_lens, seg_q, seg_k, alibi, flags, window, mask=None,
             seed=None, dropout_p=0.0):
    has_len, has_seg, has_alibi = flags[:3]
    has_mask = len(flags) > 3 and flags[3]
    has_drop = len(flags) > 4 and flags[4]
    return dict(kv_lens=kv_lens if has_len else None,
                seg_q=seg_q if has_seg else None,
                seg_k=seg_k if has_seg else None,
                window=window,
                alibi_slopes=alibi if has_alibi else None,
                mask=mask if has_mask else None,
                dropout_p=dropout_p if has_drop else 0.0,
                seed=seed if has_drop else None)


@functools.partial(jax.custom_vjp, nondiff_argnums=(9, 10, 11, 12, 13))
def _flash_vjp_entry(q, k, v, kv_lens, seg_q, seg_k, alibi, mask, seed,
                     flags, is_causal, scale, window, dropout_p):
    """Pallas forward + Pallas backward (dq / dk+dv block kernels)."""
    out, _ = _flash_fwd(q, k, v, is_causal, scale,
                        **_mask_kw(kv_lens, seg_q, seg_k, alibi, flags,
                                   window, mask, seed, dropout_p))
    return out


def _flash_vjp_fwd(q, k, v, kv_lens, seg_q, seg_k, alibi, mask, seed,
                   flags, is_causal, scale, window, dropout_p):
    out, lse = _flash_fwd(q, k, v, is_causal, scale,
                          **_mask_kw(kv_lens, seg_q, seg_k, alibi, flags,
                                     window, mask, seed, dropout_p))
    return out, (q, k, v, out, lse, kv_lens, seg_q, seg_k, alibi, mask,
                 seed)


def _pallas_bwd_impl(q, k, v, out, lse, g, is_causal, scale, g_lse=None,
                     kv_lens=None, seg_q=None, seg_k=None, window=None,
                     alibi_slopes=None, mask=None, dropout_p=0.0,
                     seed=None):
    """Shared Pallas backward. `lse` is (b, h, sq, LANES). When `g_lse`
    (b, h, sq) is given (cotangent on the returned LSE, e.g. from a ring
    merge), it folds into the softmax-grad correction: dS = P·(dP − Δ)
    with Δ_eff = rowsum(dout·out) − g_lse, since ∂lse/∂S = P."""
    b, sq, h, d = q.shape
    n_kv = k.shape[2]
    n_rep = h // n_kv
    sk = k.shape[1]
    sc = scale if scale is not None else 1.0 / math.sqrt(d)

    kr = _repeat_kv(k, n_rep)
    vr = _repeat_kv(v, n_rep)
    to_t = lambda x: jnp.transpose(x, (0, 2, 1, 3))
    qt, kt, vt = to_t(q), to_t(kr), to_t(vr)
    dot = to_t(g)
    out_t = to_t(out)
    # delta = rowsum(dout * out) (fp32) — the softmax-grad correction term
    delta = jnp.sum(dot.astype(jnp.float32) * out_t.astype(jnp.float32),
                    axis=-1)
    if g_lse is not None:
        delta = delta - g_lse.astype(jnp.float32)
    delta = jnp.broadcast_to(delta[..., None], delta.shape + (LANES,))

    kw = dict(kv_lens=kv_lens, seg_q=seg_q, seg_k=seg_k, window=window,
              alibi_slopes=alibi_slopes, mask=mask, dropout_p=dropout_p,
              seed=seed)
    dq_t = _bwd_dq_kernel(qt, kt, vt, dot, lse, delta, is_causal, sc, **kw)
    dk_t, dv_t = _bwd_dkv_kernel(qt, kt, vt, dot, lse, delta, is_causal,
                                 sc, **kw)

    from_t = lambda x: jnp.transpose(x, (0, 2, 1, 3))
    dq = from_t(dq_t).astype(q.dtype)
    dk = from_t(dk_t)
    dv = from_t(dv_t)
    if n_rep != 1:    # GQA: sum grads over the repeated head groups
        dk = dk.reshape(b, sk, n_kv, n_rep, d).sum(axis=3)
        dv = dv.reshape(b, sk, n_kv, n_rep, d).sum(axis=3)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


def _flash_vjp_bwd(flags, is_causal, scale, window, dropout_p, res, g):
    q, k, v, out, lse, kv_lens, seg_q, seg_k, alibi, mask, seed = res
    kw = _mask_kw(kv_lens, seg_q, seg_k, alibi, flags, window, mask, seed,
                  dropout_p)
    try:
        dq, dk, dv = _pallas_bwd_impl(q, k, v, out, lse, g, is_causal,
                                      scale, **kw)
    except Exception as e:
        from paddle_tpu.core.flags import flag
        if flag("FLAGS_pallas_strict") or kw["dropout_p"] > 0.0:
            # no XLA fallback under dropout: it could not reproduce the
            # kernel's counter-based mask, silently mismatching the fwd
            raise
        _log_fallback("backward", e)
        kw_x = dict(kw)
        kw_x.pop("seed")
        kw_x["attn_mask"] = _mask_as_attn(kw_x.pop("mask"))
        _, pull = jax.vjp(
            lambda q_, k_, v_: _xla_attention(
                q_, k_, v_, is_causal=is_causal, scale=scale,
                **kw_x),
            q, k, v)
        dq, dk, dv = pull(g)
    # kv_lens/segments are integer primals → float0; alibi is fp32 (a dummy
    # zeros(1) on non-ALiBi calls) so its cotangent must be a real float
    # zero — float0 for a float primal breaks under custom_vjp aval checks.
    # Dense masks are non-differentiable inputs (float masks get a real
    # zero cotangent, int8/bool get float0); the seed is int32 → float0.
    mask_ct = (_float0_like(res[9])
               if res[9].dtype in (jnp.bool_, jnp.int8)
               else jnp.zeros(res[9].shape, res[9].dtype))
    return (dq, dk, dv, _float0_like(res[5]), _float0_like(res[6]),
            _float0_like(res[7]), jnp.zeros(res[8].shape, res[8].dtype),
            mask_ct, _float0_like(res[10]))


def _mask_as_attn(mask):
    """int8 kernel mask back to bool for the XLA fallback path."""
    if mask is None:
        return None
    return (mask != 0) if mask.dtype == jnp.int8 else mask


_flash_vjp_entry.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)

# Back-compat alias used by benches/tests: plain self-attention entry.
def _flash_attention_vjp(q, k, v, is_causal, scale):
    return _flash_call(q, k, v, is_causal, scale, None, None, None)


# ---- forward + LSE (ring-attention building block) ------------------------

def _pallas_seq_ok(sq: int, sk: Optional[int] = None) -> bool:
    """Shared dispatch predicate: long enough to beat XLA and divisible by
    a supported block size (see _pick_blk)."""
    sk = sq if sk is None else sk
    return (max(sq, sk) >= 1024 and sq % 128 == 0 and sk % 128 == 0)


def _pallas_lse_ok(q, k):
    from paddle_tpu.ops import use_pallas
    s = q.shape[1]
    return (use_pallas() and s == k.shape[1] and _pallas_seq_ok(s)
            and q.shape[-1] in (64, 128, 256))


def _xla_fwd_lse(q, k, v, is_causal, scale):
    """XLA fallback: (out (b,s,h,d), lse (b,h,s) fp32)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    n_rep = h // k.shape[2]
    kr = _repeat_kv(k, n_rep)
    vr = _repeat_kv(v, n_rep)
    sc = scale if scale is not None else 1.0 / math.sqrt(d)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, kr,
                        preferred_element_type=jnp.float32) * sc
    if is_causal:
        causal = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        scores = jnp.where(causal[None, None], scores, NEG_INF)
    m = jnp.max(scores, axis=-1)
    p = jnp.exp(scores - m[..., None])
    l = jnp.sum(p, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", (p / l[..., None]).astype(q.dtype),
                     vr)
    return out.astype(q.dtype), m + jnp.log(l)


def _fwd_lse_dispatch(q, k, v, is_causal, scale):
    if _pallas_lse_ok(q, k):
        out, lse = _flash_fwd(q, k, v, is_causal, scale)
        return out, lse[..., 0]
    return _xla_fwd_lse(q, k, v, is_causal, scale)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_fwd_lse(q, k, v, is_causal=False, scale=None):
    """Attention forward returning (out, lse) for blockwise/ring merging.

    out (b, s, h, d) is the normalized chunk attention; lse (b, h, s) fp32
    is the log-sum-exp of the (scaled, masked) scores — together they let a
    caller merge several KV chunks exactly (ring attention, SURVEY.md
    §5-long-context). Pallas blockwise kernels on TPU when shapes allow
    (memory bounded by the 512-block tiles, never s²); XLA otherwise.
    Differentiable, including the lse output (the cotangent folds into the
    softmax-grad delta)."""
    return _fwd_lse_dispatch(q, k, v, is_causal, scale)


def _fwd_lse_vjp_fwd(q, k, v, is_causal, scale):
    out, lse = _fwd_lse_dispatch(q, k, v, is_causal, scale)
    return (out, lse), (q, k, v, out, lse)


def _fwd_lse_vjp_bwd(is_causal, scale, res, cts):
    q, k, v, out, lse = res
    g_out, g_lse = cts
    if _pallas_lse_ok(q, k):
        try:
            lse_lanes = jnp.broadcast_to(lse[..., None],
                                         lse.shape + (LANES,))
            return _pallas_bwd_impl(q, k, v, out, lse_lanes, g_out,
                                    is_causal, scale, g_lse=g_lse)
        except Exception as e:
            from paddle_tpu.core.flags import flag
            if flag("FLAGS_pallas_strict"):
                raise
            _log_fallback("lse-backward", e)
    _, pull = jax.vjp(
        lambda q_, k_, v_: _xla_fwd_lse(q_, k_, v_, is_causal, scale),
        q, k, v)
    return pull((g_out, g_lse))


flash_fwd_lse.defvjp(_fwd_lse_vjp_fwd, _fwd_lse_vjp_bwd)
