"""Flash attention — XLA path + Pallas TPU kernels (forward AND backward).

Reference: phi flash_attn kernel wrapping the vendored flash-attention-2 CUDA
library (paddle/phi/kernels/gpu/flash_attn_kernel.cu, cmake/external/
flashattn.cmake; python veneer paddle.nn.functional.flash_attention).

Layouts follow the reference: q/k/v are (batch, seq, num_heads, head_dim).
GQA/MQA supported via num_kv_heads < num_heads. The Pallas path (blockwise
online-softmax, fp32 accumulators, causal block skipping, LSE saved for the
backward; dq and dk/dv backward kernels recompute probabilities per block so
the (s, s) matrix is never materialized) is used on TPU for long sequences;
the XLA einsum path covers everything else. Kernels compute internally in
(b, h, s, d) so the trailing block dims meet TPU tiling (8, 128).
"""

import functools
import logging
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30
LANES = 128

logger = logging.getLogger("paddle_tpu.ops.flash_attention")
_fallback_logged = False


def _log_fallback(which, e):
    global _fallback_logged
    if not _fallback_logged:
        _fallback_logged = True
        logger.warning(
            "Pallas flash attention %s failed (%s: %s); falling back to the "
            "XLA path. Set FLAGS_pallas_strict=1 to raise instead.",
            which, type(e).__name__, e)


def _repeat_kv(k, n_rep):
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.broadcast_to(k[:, :, :, None, :], (b, s, h, n_rep, d)).reshape(
        b, s, h * n_rep, d)


def _xla_attention(q, k, v, attn_mask=None, is_causal=False, scale=None,
                   dropout_p=0.0, training=True):
    b, sq, h, d = q.shape
    sk = k.shape[1]
    n_rep = h // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    # (b, h, sq, sk) scores in fp32 (f64 under x64 — keeps numeric-grad
    # checks meaningful)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.promote_types(
                            q.dtype, jnp.float32)) * scale
    if is_causal:
        causal = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        scores = jnp.where(causal[None, None], scores, NEG_INF)
    if attn_mask is not None:
        if attn_mask.dtype == jnp.bool_:
            scores = jnp.where(attn_mask, scores, NEG_INF)
        else:
            scores = scores + attn_mask.astype(scores.dtype)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and training:
        from paddle_tpu.core import rng as _rng
        key = _rng.next_rng_key("dropout")
        keep = 1.0 - dropout_p
        mask = jax.random.bernoulli(key, keep, probs.shape)
        probs = jnp.where(mask, probs / keep, 0.0).astype(probs.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v).astype(q.dtype)


def flash_attention(q, k, v, dropout=0.0, causal=False, attn_mask=None,
                    training=True, scale=None):
    """paddle.nn.functional.flash_attention parity. Returns (out, None)."""
    out = scaled_dot_product_attention(
        q, k, v, attn_mask=attn_mask, dropout_p=dropout, is_causal=causal,
        training=training, scale=scale)
    return out, None


def scaled_dot_product_attention(q, k, v, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, scale=None):
    from paddle_tpu.ops import use_pallas
    # Pallas path: TPU, self-attention, seq any multiple of 128 (block size
    # adapts) once long enough to beat XLA. Documented exclusions that route
    # to the XLA path by design: attention dropout (modern LLM pretraining
    # runs attn dropout 0; the XLA path implements it) and dense/boolean
    # masks (padding masks belong in kv lengths — round-3 kernel work).
    if (use_pallas() and dropout_p == 0.0 and attn_mask is None
            and q.shape[1] == k.shape[1] and _pallas_seq_ok(q.shape[1])
            and q.shape[-1] in (64, 128, 256)):
        try:
            return _flash_attention_vjp(q, k, v, is_causal, scale)
        except Exception as e:
            from paddle_tpu.core.flags import flag
            if flag("FLAGS_pallas_strict"):
                raise
            _log_fallback("forward", e)
    return _xla_attention(q, k, v, attn_mask=attn_mask, is_causal=is_causal,
                          scale=scale, dropout_p=dropout_p, training=training)


# ---- Pallas kernels (internal layout (b, h, s, d)) -------------------------

def _pick_blk(s):
    """Largest block in (512, 256, 128) dividing s — lets the kernels
    cover any s % 128 == 0, not just 512-multiples."""
    for blk in (512, 256, 128):
        if s % blk == 0:
            return blk
    raise ValueError(f"seq {s} not a multiple of 128")


def _fwd_kernels(qt, kt, vt, is_causal: bool, sc: float):
    """qt/kt/vt: (b, h, s, d) → (out (b,h,s,d), lse (b,h,s)) fp32 lse."""
    from jax.experimental import pallas as pl

    b, h, s, d = qt.shape
    blk_q = blk_k = _pick_blk(s)
    grid = (b, h, s // blk_q)

    def kernel(q_ref, k_ref, v_ref, o_ref, lse_ref):
        qi = pl.program_id(2)
        qv = q_ref[...].astype(jnp.float32) * sc  # (blk_q, d)

        def body(ki, carry):
            acc, m_prev, l_prev = carry
            kv = k_ref[pl.ds(ki * blk_k, blk_k), :].astype(jnp.float32)
            vv = v_ref[pl.ds(ki * blk_k, blk_k), :].astype(jnp.float32)
            s_blk = qv @ kv.T  # (blk_q, blk_k)
            if is_causal:
                q_pos = qi * blk_q + lax.broadcasted_iota(
                    jnp.int32, (blk_q, blk_k), 0)
                k_pos = ki * blk_k + lax.broadcasted_iota(
                    jnp.int32, (blk_q, blk_k), 1)
                s_blk = jnp.where(q_pos >= k_pos, s_blk, NEG_INF)
            m_cur = jnp.maximum(m_prev, jnp.max(s_blk, axis=-1))
            alpha = jnp.exp(m_prev - m_cur)
            p = jnp.exp(s_blk - m_cur[:, None])
            l_cur = l_prev * alpha + jnp.sum(p, axis=-1)
            acc = acc * alpha[:, None] + p @ vv
            return acc, m_cur, l_cur

        acc0 = jnp.zeros((blk_q, d), jnp.float32)
        m0 = jnp.full((blk_q,), NEG_INF, jnp.float32)
        l0 = jnp.zeros((blk_q,), jnp.float32)
        if is_causal:
            n_k = qi * (blk_q // blk_k) + 1 if blk_q >= blk_k \
                else (qi * blk_q) // blk_k + 1
        else:
            n_k = s // blk_k
        acc, m, l = lax.fori_loop(0, n_k, body, (acc0, m0, l0))
        o_ref[...] = (acc / l[:, None]).astype(o_ref.dtype)
        # TPU tiling wants 2-D trailing blocks: replicate lse across lanes
        lse_ref[...] = jnp.broadcast_to((m + jnp.log(l))[:, None],
                                        (qv.shape[0], LANES))

    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((None, None, blk_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, s, d),
                         lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((None, None, s, d),
                         lambda bi, hi, qi: (bi, hi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, None, blk_q, d),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((None, None, blk_q, LANES),
                         lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), qt.dtype),
            jax.ShapeDtypeStruct((b, h, s, LANES), jnp.float32),
        ],
    )(qt, kt, vt)
    return out, lse


def _bwd_dq_kernel(qt, kt, vt, dot, lse, delta, is_causal: bool, sc: float):
    """dq: loop over k-blocks for each q-block. All (b,h,s,·)."""
    from jax.experimental import pallas as pl

    b, h, s, d = qt.shape
    blk_q = blk_k = _pick_blk(s)
    grid = (b, h, s // blk_q)

    def kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dq_ref):
        qi = pl.program_id(2)
        qv = q_ref[...].astype(jnp.float32)
        do = do_ref[...].astype(jnp.float32)          # (blk_q, d)
        lse_q = lse_ref[...][:, 0]                    # (blk_q,)
        delta_q = dl_ref[...][:, 0]                   # (blk_q,)

        def body(ki, dq_acc):
            kv = k_ref[pl.ds(ki * blk_k, blk_k), :].astype(jnp.float32)
            vv = v_ref[pl.ds(ki * blk_k, blk_k), :].astype(jnp.float32)
            s_blk = (qv @ kv.T) * sc
            if is_causal:
                q_pos = qi * blk_q + lax.broadcasted_iota(
                    jnp.int32, (blk_q, blk_k), 0)
                k_pos = ki * blk_k + lax.broadcasted_iota(
                    jnp.int32, (blk_q, blk_k), 1)
                s_blk = jnp.where(q_pos >= k_pos, s_blk, NEG_INF)
            p = jnp.exp(s_blk - lse_q[:, None])       # (blk_q, blk_k)
            dp = do @ vv.T                            # (blk_q, blk_k)
            ds = p * (dp - delta_q[:, None])
            return dq_acc + (ds @ kv) * sc

        if is_causal:
            n_k = qi * (blk_q // blk_k) + 1 if blk_q >= blk_k \
                else (qi * blk_q) // blk_k + 1
        else:
            n_k = s // blk_k
        dq = lax.fori_loop(0, n_k, body, jnp.zeros((blk_q, d), jnp.float32))
        dq_ref[...] = dq.astype(dq_ref.dtype)

    full = lambda: pl.BlockSpec((None, None, s, d),
                                lambda bi, hi, qi: (bi, hi, 0, 0))
    qblk = lambda: pl.BlockSpec((None, None, blk_q, d),
                                lambda bi, hi, qi: (bi, hi, qi, 0))
    row = lambda: pl.BlockSpec((None, None, blk_q, LANES),
                               lambda bi, hi, qi: (bi, hi, qi, 0))
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[qblk(), full(), full(), qblk(), row(), row()],
        out_specs=qblk(),
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), qt.dtype),
    )(qt, kt, vt, dot, lse, delta)


def _bwd_dkv_kernel(qt, kt, vt, dot, lse, delta, is_causal: bool, sc: float):
    """dk, dv: loop over q-blocks for each k-block."""
    from jax.experimental import pallas as pl

    b, h, s, d = qt.shape
    blk_q = blk_k = _pick_blk(s)
    grid = (b, h, s // blk_k)

    def kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dk_ref, dv_ref):
        ki = pl.program_id(2)
        kv = k_ref[...].astype(jnp.float32)           # (blk_k, d)
        vv = v_ref[...].astype(jnp.float32)

        def body(qi, carry):
            dk_acc, dv_acc = carry
            qv = q_ref[pl.ds(qi * blk_q, blk_q), :].astype(jnp.float32)
            do = do_ref[pl.ds(qi * blk_q, blk_q), :].astype(jnp.float32)
            lse_q = lse_ref[pl.ds(qi * blk_q, blk_q), 0]
            delta_q = dl_ref[pl.ds(qi * blk_q, blk_q), 0]
            s_blk = (qv @ kv.T) * sc                  # (blk_q, blk_k)
            if is_causal:
                q_pos = qi * blk_q + lax.broadcasted_iota(
                    jnp.int32, (blk_q, blk_k), 0)
                k_pos = ki * blk_k + lax.broadcasted_iota(
                    jnp.int32, (blk_q, blk_k), 1)
                s_blk = jnp.where(q_pos >= k_pos, s_blk, NEG_INF)
            p = jnp.exp(s_blk - lse_q[:, None])
            dv_acc = dv_acc + p.T @ do
            dp = do @ vv.T
            ds = p * (dp - delta_q[:, None])
            dk_acc = dk_acc + (ds.T @ qv) * sc
            return dk_acc, dv_acc

        n_q = s // blk_q
        if is_causal:
            # only q-blocks at or below the diagonal see this k-block
            q0 = (ki * blk_k) // blk_q
        else:
            q0 = 0
        dk, dv = lax.fori_loop(q0, n_q, body,
                               (jnp.zeros((blk_k, d), jnp.float32),
                                jnp.zeros((blk_k, d), jnp.float32)))
        dk_ref[...] = dk.astype(dk_ref.dtype)
        dv_ref[...] = dv.astype(dv_ref.dtype)

    full = lambda: pl.BlockSpec((None, None, s, d),
                                lambda bi, hi, ki: (bi, hi, 0, 0))
    kblk = lambda: pl.BlockSpec((None, None, blk_k, d),
                                lambda bi, hi, ki: (bi, hi, ki, 0))
    frow = lambda: pl.BlockSpec((None, None, s, LANES),
                                lambda bi, hi, ki: (bi, hi, 0, 0))
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[full(), kblk(), kblk(), full(), frow(), frow()],
        out_specs=[kblk(), kblk()],
        out_shape=[jax.ShapeDtypeStruct((b, h, s, d), qt.dtype),
                   jax.ShapeDtypeStruct((b, h, s, d), qt.dtype)],
    )(qt, kt, vt, dot, lse, delta)


@functools.partial(jax.jit, static_argnames=("is_causal", "scale"))
def _flash_attention_pallas(q, k, v, is_causal: bool, scale: Optional[float]):
    """Forward-only entry (bench/eval); (b, s, h, d) in and out."""
    out, _ = _flash_fwd(q, k, v, is_causal, scale)
    return out


def _flash_fwd(q, k, v, is_causal, scale):
    b, s, h, d = q.shape
    n_rep = h // k.shape[2]
    k = _repeat_kv(k, n_rep)
    v = _repeat_kv(v, n_rep)
    sc = scale if scale is not None else 1.0 / math.sqrt(d)
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    out_t, lse = _fwd_kernels(qt, kt, vt, is_causal, sc)
    return jnp.transpose(out_t, (0, 2, 1, 3)), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_attention_vjp(q, k, v, is_causal, scale):
    """Pallas forward + Pallas backward (dq / dk+dv block kernels)."""
    out, _ = _flash_fwd(q, k, v, is_causal, scale)
    return out


def _flash_vjp_fwd(q, k, v, is_causal, scale):
    out, lse = _flash_fwd(q, k, v, is_causal, scale)
    return out, (q, k, v, out, lse)


def _pallas_bwd_impl(q, k, v, out, lse, g, is_causal, scale, g_lse=None):
    """Shared Pallas backward. `lse` is (b, h, s, LANES). When `g_lse`
    (b, h, s) is given (cotangent on the returned LSE, e.g. from a ring
    merge), it folds into the softmax-grad correction: dS = P·(dP − Δ)
    with Δ_eff = rowsum(dout·out) − g_lse, since ∂lse/∂S = P."""
    b, s, h, d = q.shape
    n_kv = k.shape[2]
    n_rep = h // n_kv
    sc = scale if scale is not None else 1.0 / math.sqrt(d)

    kr = _repeat_kv(k, n_rep)
    vr = _repeat_kv(v, n_rep)
    to_t = lambda x: jnp.transpose(x, (0, 2, 1, 3))
    qt, kt, vt = to_t(q), to_t(kr), to_t(vr)
    dot = to_t(g)
    out_t = to_t(out)
    # delta = rowsum(dout * out) (fp32) — the softmax-grad correction term
    delta = jnp.sum(dot.astype(jnp.float32) * out_t.astype(jnp.float32),
                    axis=-1)
    if g_lse is not None:
        delta = delta - g_lse.astype(jnp.float32)
    delta = jnp.broadcast_to(delta[..., None], delta.shape + (LANES,))

    dq_t = _bwd_dq_kernel(qt, kt, vt, dot, lse, delta, is_causal, sc)
    dk_t, dv_t = _bwd_dkv_kernel(qt, kt, vt, dot, lse, delta, is_causal, sc)

    from_t = lambda x: jnp.transpose(x, (0, 2, 1, 3))
    dq = from_t(dq_t).astype(q.dtype)
    dk = from_t(dk_t)
    dv = from_t(dv_t)
    if n_rep != 1:    # GQA: sum grads over the repeated head groups
        dk = dk.reshape(b, s, n_kv, n_rep, d).sum(axis=3)
        dv = dv.reshape(b, s, n_kv, n_rep, d).sum(axis=3)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


def _flash_vjp_bwd(is_causal, scale, res, g):
    q, k, v, out, lse = res
    try:
        return _pallas_bwd_impl(q, k, v, out, lse, g, is_causal, scale)
    except Exception as e:
        from paddle_tpu.core.flags import flag
        if flag("FLAGS_pallas_strict"):
            raise
        _log_fallback("backward", e)
        _, pull = jax.vjp(
            lambda q_, k_, v_: _xla_attention(q_, k_, v_,
                                              is_causal=is_causal,
                                              scale=scale, dropout_p=0.0),
            q, k, v)
        return pull(g)


_flash_attention_vjp.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


# ---- forward + LSE (ring-attention building block) ------------------------

def _pallas_seq_ok(s: int) -> bool:
    """Shared dispatch predicate: long enough to beat XLA and divisible by
    a supported block size (see _pick_blk)."""
    return s >= 1024 and s % 128 == 0


def _pallas_lse_ok(q, k):
    from paddle_tpu.ops import use_pallas
    s = q.shape[1]
    return (use_pallas() and s == k.shape[1] and _pallas_seq_ok(s)
            and q.shape[-1] in (64, 128, 256))


def _xla_fwd_lse(q, k, v, is_causal, scale):
    """XLA fallback: (out (b,s,h,d), lse (b,h,s) fp32)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    n_rep = h // k.shape[2]
    kr = _repeat_kv(k, n_rep)
    vr = _repeat_kv(v, n_rep)
    sc = scale if scale is not None else 1.0 / math.sqrt(d)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, kr,
                        preferred_element_type=jnp.float32) * sc
    if is_causal:
        causal = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        scores = jnp.where(causal[None, None], scores, NEG_INF)
    m = jnp.max(scores, axis=-1)
    p = jnp.exp(scores - m[..., None])
    l = jnp.sum(p, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", (p / l[..., None]).astype(q.dtype),
                     vr)
    return out.astype(q.dtype), m + jnp.log(l)


def _fwd_lse_dispatch(q, k, v, is_causal, scale):
    if _pallas_lse_ok(q, k):
        out, lse = _flash_fwd(q, k, v, is_causal, scale)
        return out, lse[..., 0]
    return _xla_fwd_lse(q, k, v, is_causal, scale)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_fwd_lse(q, k, v, is_causal=False, scale=None):
    """Attention forward returning (out, lse) for blockwise/ring merging.

    out (b, s, h, d) is the normalized chunk attention; lse (b, h, s) fp32
    is the log-sum-exp of the (scaled, masked) scores — together they let a
    caller merge several KV chunks exactly (ring attention, SURVEY.md
    §5-long-context). Pallas blockwise kernels on TPU when shapes allow
    (memory bounded by the 512-block tiles, never s²); XLA otherwise.
    Differentiable, including the lse output (the cotangent folds into the
    softmax-grad delta)."""
    return _fwd_lse_dispatch(q, k, v, is_causal, scale)


def _fwd_lse_vjp_fwd(q, k, v, is_causal, scale):
    out, lse = _fwd_lse_dispatch(q, k, v, is_causal, scale)
    return (out, lse), (q, k, v, out, lse)


def _fwd_lse_vjp_bwd(is_causal, scale, res, cts):
    q, k, v, out, lse = res
    g_out, g_lse = cts
    if _pallas_lse_ok(q, k):
        try:
            lse_lanes = jnp.broadcast_to(lse[..., None],
                                         lse.shape + (LANES,))
            return _pallas_bwd_impl(q, k, v, out, lse_lanes, g_out,
                                    is_causal, scale, g_lse=g_lse)
        except Exception as e:
            from paddle_tpu.core.flags import flag
            if flag("FLAGS_pallas_strict"):
                raise
            _log_fallback("lse-backward", e)
    _, pull = jax.vjp(
        lambda q_, k_, v_: _xla_fwd_lse(q_, k_, v_, is_causal, scale),
        q, k, v)
    return pull((g_out, g_lse))


flash_fwd_lse.defvjp(_fwd_lse_vjp_fwd, _fwd_lse_vjp_bwd)
