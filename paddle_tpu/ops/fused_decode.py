"""Fused decode-step kernel — the fused_multi_transformer analog.

Reference: paddle/phi/kernels/fusion/gpu/fused_multi_transformer_op.cu +
masked_multihead_attention (SURVEY.md §2.2 fusion row, §2.8-1, §7 stage 6):
the reference's inference crown jewel runs one token through the whole
decoder stack with hand-fused CUDA kernels (qkv + rope + KV-cache append +
masked attention + FFN), streaming each layer's weights exactly once.

TPU-native design: ONE `pallas_call` for the entire stack per decode step.

* grid = (num_layers, 1 + ffn_blocks): phase 0 of each layer does
  rmsnorm→qkv→rope→cache-append→masked attention over the *filled prefix
  only*→o-proj; phases 1..J stream the SwiGLU FFN in column blocks.
* Layer weights ride BlockSpecs indexed by the layer grid dim, so Mosaic's
  pipeline double-buffers them: layer l+1's weights stream from HBM while
  layer l computes — the "stream weights once, overlap with compute"
  property the CUDA kernel gets from its warp pipeline.
* The KV cache lives in HBM (`pl.ANY` memory space, input/output aliased —
  updated in place). The new token's k/v is DMA'd into slot `pos`; the
  attention loop then DMAs 128-token chunks of the *filled* prefix
  [0, pos] into VMEM — unlike the XLA scan path it never touches the
  unfilled tail, and the whole residual stream stays in fp32 in VMEM.
* The hidden state x crosses grid steps in a VMEM scratch accumulator, so
  the only HBM traffic per step is weights (once), the filled KV prefix,
  and one token's cache append — which IS the decode roofline.

The stack covers the Llama block (RMSNorm / GQA / RoPE / SwiGLU, no
biases) and, via `arch="gpt"`, the GPT block (LayerNorm+bias / MHA / no
rope / GELU) — the architecture the reference's fused_multi_transformer
itself serves. `fused_decode_reference` is the jnp twin used for numerics
tests and as the non-TPU fallback; `examples/decode_bench.py` measures
the win.
"""

import functools
import math
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

NEG_INF = -1e30

# Per-generation VMEM capacity (MiB). The runtime exposes no VMEM
# attribute, so `device_kind` is the spec handle; unknown kinds fall back
# to the v5e value. Every public generation to date ships 128 MiB/core —
# the table is the extension point for one that differs, and
# FLAGS_vmem_mib the per-deployment escape hatch.
_VMEM_MIB_BY_KIND = {
    "TPU v4": 128,
    "TPU v5 lite": 128,     # v5e
    "TPU v5e": 128,
    "TPU v5": 128,          # v5p
    "TPU v5p": 128,
    "TPU v6 lite": 128,     # v6e / trillium
}
_VMEM_MIB_FALLBACK = 128


def _vmem_mib() -> int:
    """VMEM capacity of device 0 in MiB (flag override > Mosaic probe >
    kind table > v5e fallback).

    ``FLAGS_vmem_mib = -1`` runs the boot-time scoped-VMEM bisect probe
    (`ops/vmem_probe.py`, cached per device kind) instead of trusting the
    table. The probe's trivial kernel allocates 4 MiB less than hardware
    capacity (124 of 128 MiB on v5e — Mosaic's fixed reservations), so
    capacity = probed + 4; on v5e that reproduces the table value exactly,
    and the downstream `_vmem_budget/_vmem_limit` margins (which were
    calibrated against *real* fused kernels) stay meaningful.
    """
    from paddle_tpu.core.flags import flag
    override = flag("FLAGS_vmem_mib")
    if override and int(override) > 0:
        return int(override)
    try:
        kind = jax.devices()[0].device_kind
    except Exception:
        return _VMEM_MIB_FALLBACK
    if override and int(override) == -1:
        try:
            from paddle_tpu.ops.vmem_probe import probe_usable_vmem_mib
            return probe_usable_vmem_mib(kind) + 4
        except Exception:
            pass   # non-TPU platform or probe failure → table
    return _VMEM_MIB_BY_KIND.get(kind, _VMEM_MIB_FALLBACK)


def _vmem_budget_bytes() -> int:
    """Planning budget for double-buffered weight blocks: capacity minus
    40 MiB of headroom (KV chunks, scratch, Mosaic's own reservations —
    the margin probed on v5e where 88 of 128 MiB plans reliably)."""
    return max(48, _vmem_mib() - 40) * 2 ** 20


def _vmem_limit_bytes() -> int:
    """Scoped-VMEM limit passed to Mosaic: capacity minus 28 MiB (100 of
    128 MiB is the probed reliable ceiling on v5e)."""
    return max(64, _vmem_mib() - 28) * 2 ** 20


# ---------------------------------------------------------------------------
# Block planning (qkv column split + FFN column blocks)
# ---------------------------------------------------------------------------

# Bytes-equivalent cost of one extra grid step (~2 µs of per-step scalar
# overhead at v5e HBM bandwidth) — lets the planner trade zero-padding a
# non-128-multiple ffn (e.g. 11008 → 11264) against running many tiny
# blocks (fblk=256 would take 43 grid steps/layer on Llama-2-7B).
_GRID_STEP_BYTES = 3 * 2 ** 19


def _step_penalty(w_step):
    """Cost penalty for oversized per-grid-step weight blocks in the
    split (big-model) regime: blocks above ~30 MiB serialize DMA against
    compute — measured on llama2-7b int8 (SCALE.md r5 sweep: qs8/f512 at
    11.33 ms/step beats qs4/f1024 at 11.93 and qs6/f512 at 12.08)."""
    return max(0, 4 * (w_step - 28 * 2 ** 20))


def decode_block_plan(h: int, dqkv: int, dq: int, hd: int, ffn: int,
                      wbytes: int, q_split: Optional[int] = None,
                      cache_wbytes: int = 2) -> Dict:
    """Joint plan for the fused decode kernel's weight streaming.

    At 7B scale (h=4096) the attention weights alone (wqkv 50 MiB + wo
    17 MiB int8) cannot double-buffer in v5e's 128 MiB VMEM, so the qkv
    projection is split into `q_split` head-aligned COLUMN phases — each
    grid step streams one (h, qblk) block, mirroring how the FFN has
    always streamed in column blocks. FFN blocks are chosen from
    128-lane multiples (zero-padding ffn up to J*fblk when ffn isn't a
    128-multiple — SwiGLU pad columns contribute silu(0)*0 = 0 exactly),
    minimizing streamed bytes + grid-step overhead.

    Returns {"q_split", "qblk", "ffn_blocks", "fblk", "ffn_pad",
    "cache_wbytes"} where ffn_pad >= ffn is the padded column count
    build_fused_params must produce. `q_split` forces the split (tests).
    `cache_wbytes` records the KV-cache element size this plan assumed
    (1 = int8 cache mode); the kernel sizes its chunk scratch from the
    actual cache dtype and ASSERTS it agrees with the plan, so a stale
    bf16 plan can't silently drive an int8-cache decode (or vice versa).
    """
    budget = _vmem_budget_bytes()
    half = max((budget - 8 * 2 ** 20) // 2, 2 ** 20)
    nheads_tot = dqkv // hd

    def ffn_pick(fixed, fmax, split):
        # candidates: 128-multiples up to fmax (padding allowed) plus, for
        # non-128-multiple ffns, the exact divisors (no padding)
        if ffn <= 128:
            return (1, ffn, ffn) if ffn <= fmax else None
        cands = list(range(128, min(ffn + 127, fmax) + 1, 128))
        if split:
            # split (big-model) regime: only 512-multiples (+128/256)
            # stream cleanly — 640/768-lane blocks measured 8-200% slower
            # on the llama2-7b sweeps (SCALE.md r5)
            cands = [f for f in cands if f % 512 == 0 or f in (128, 256)]
        if not cands:
            # no lane-aligned block fits: exact divisors as a last resort
            cands = [f for f in range(1, min(ffn, fmax) + 1)
                     if ffn % f == 0]
        best = None
        for f in cands:
            jn = -(-ffn // f)
            cost = 3 * jn * f * h * wbytes + jn * _GRID_STEP_BYTES
            if split:
                cost += _step_penalty(fixed + 3 * f * h * wbytes)
            if best is None or cost < best[0] or (cost == best[0]
                                                  and f > best[2]):
                best = (cost, jn, f)
        return (best[1], best[2], best[1] * best[2]) if best else None

    best = None
    qs_list = ([q_split] if q_split else
               [q for q in range(1, nheads_tot + 1) if nheads_tot % q == 0])
    for qs in qs_list:
        qblk = dqkv // qs
        if qblk % hd:
            continue
        if qs > 1 and not q_split and (
                qblk % 128 or not (qblk % 512 == 0 or qblk in (128, 256))):
            continue    # lane-aligned, 512-multiple splits only (see
            # ffn_pick: 768-lane qkv blocks measured 3x slower)
        fixed = (qblk + dq) * h * wbytes
        pick = ffn_pick(fixed, (half - fixed) // (3 * h * wbytes), qs > 1)
        if pick is None:
            continue
        jn, fblk, pad = pick
        cost = (3 * pad * h * wbytes + jn * _GRID_STEP_BYTES
                + qs * _GRID_STEP_BYTES)
        if qs > 1:
            cost += _step_penalty(fixed + 3 * fblk * h * wbytes)
        if best is None or cost < best[0]:
            best = (cost, qs, qblk, jn, fblk, pad)
    if best is None:
        if q_split:
            raise ValueError(
                f"decode_block_plan: forced q_split={q_split} is invalid "
                f"for dqkv={dqkv}, hd={hd} under the current VMEM budget")
        # nothing fits the budget even maximally split: stream the finest
        # head-aligned qkv blocks + 128-col FFN blocks and let Mosaic cope
        qs = nheads_tot
        jn = -(-ffn // 128) if ffn > 128 else 1
        fblk = 128 if ffn > 128 else ffn
        best = (0, qs, hd, jn, fblk, jn * fblk)
    _, qs, qblk, jn, fblk, pad = best
    return {"q_split": qs, "qblk": qblk, "ffn_blocks": jn, "fblk": fblk,
            "ffn_pad": pad, "cache_wbytes": cache_wbytes}


def _pad_ffn(stacks: Dict[str, jax.Array], ffn_pad: int):
    """Zero-pad the FFN stacks' ffn dim up to ffn_pad (scales pad with 1;
    quantized pad weights are 0 so the scale value is inert)."""
    ffn = stacks["wg"].shape[2]
    if ffn_pad <= ffn:
        return stacks
    p = ffn_pad - ffn
    out = dict(stacks)
    for k in ("wg", "wu"):
        out[k] = jnp.pad(stacks[k], ((0, 0), (0, 0), (0, p)))
    out["wd"] = jnp.pad(stacks["wd"], ((0, 0), (0, p), (0, 0)))
    for k in ("wg_s", "wu_s"):
        if k in stacks:
            out[k] = jnp.pad(stacks[k], ((0, 0), (0, 0), (0, p)),
                             constant_values=1.0)
    return out


# ---------------------------------------------------------------------------
# Stacked parameter pytree
# ---------------------------------------------------------------------------

def build_fused_params(state: Dict[str, jax.Array], num_layers: int,
                       prefix: str = "model.layers.",
                       ffn_pad: int = 0) -> Dict[str, jax.Array]:
    """Stack a Llama-style flat state dict into per-layer-stacked arrays.

    Returns {ln1 (L,h), wqkv (L,h,(nh+2nkv)*hd), wo (L,nh*hd,h), ln2 (L,h),
    wg (L,h,ffn), wu (L,h,ffn), wd (L,ffn,h)}. The qkv projections are
    fused along the output dim (q|k|v) the way fused_multi_transformer's
    qkv_weight is packed.

    Weight-only-int8 states (paddle_tpu.quantization — keys `weight_q` +
    `weight_scale`) produce int8 weight stacks plus per-out-channel scale
    rows {wqkv_s (L,1,dqkv), wo_s, wg_s, wu_s, wd_s} — the
    fused_multi_transformer_int8 packing: the kernel streams int8 and
    scales the matmul OUTPUTS.
    """
    int8 = f"{prefix}0.self_attn.q_proj.weight_q" in state

    def layer(i, name):
        if int8:
            return (state[f"{prefix}{i}.{name}.weight_q"],
                    state[f"{prefix}{i}.{name}.weight_scale"])
        return state[f"{prefix}{i}.{name}.weight"], None

    cols = {"ln1": [], "wqkv": [], "wo": [], "ln2": [], "wg": [], "wu": [],
            "wd": []}
    scales = {k: [] for k in ("wqkv", "wo", "wg", "wu", "wd")}

    def put(key, w, sc):
        cols[key].append(w)
        if int8:
            scales[key].append(sc)

    for i in range(num_layers):
        cols["ln1"].append(state[f"{prefix}{i}.input_layernorm.weight"])
        qs = [layer(i, f"self_attn.{n}_proj") for n in ("q", "k", "v")]
        put("wqkv", jnp.concatenate([w for w, _ in qs], axis=1),
            jnp.concatenate([sc for _, sc in qs]) if int8 else None)
        put("wo", *layer(i, "self_attn.o_proj"))
        cols["ln2"].append(
            state[f"{prefix}{i}.post_attention_layernorm.weight"])
        put("wg", *layer(i, "mlp.gate_proj"))
        put("wu", *layer(i, "mlp.up_proj"))
        put("wd", *layer(i, "mlp.down_proj"))
    out = {k: jnp.stack(v) for k, v in cols.items()}
    if int8:
        for k, v in scales.items():
            out[f"{k}_s"] = jnp.stack(v).astype(jnp.float32)[:, None, :]
    if ffn_pad:
        out = _pad_ffn(out, ffn_pad)
    return out


def build_fused_params_gpt(state: Dict[str, jax.Array], num_layers: int,
                           prefix: str = "gpt.h.") -> Dict[str, jax.Array]:
    """GPT-block stacks: LayerNorm scale+bias, fused qkv (weight already
    packed 3h), biases on every projection, single GELU FFN."""
    g = lambda i, n: state[f"{prefix}{i}.{n}"]
    out = {
        "ln1": jnp.stack([g(i, "ln_1.weight") for i in range(num_layers)]),
        "ln1_b": jnp.stack([g(i, "ln_1.bias") for i in range(num_layers)]),
        "wqkv": jnp.stack([g(i, "attn.qkv_proj.weight")
                           for i in range(num_layers)]),
        "bqkv": jnp.stack([g(i, "attn.qkv_proj.bias")
                           for i in range(num_layers)]),
        "wo": jnp.stack([g(i, "attn.out_proj.weight")
                         for i in range(num_layers)]),
        "bo": jnp.stack([g(i, "attn.out_proj.bias")
                         for i in range(num_layers)]),
        "ln2": jnp.stack([g(i, "ln_2.weight") for i in range(num_layers)]),
        "ln2_b": jnp.stack([g(i, "ln_2.bias") for i in range(num_layers)]),
        "wg": jnp.stack([g(i, "fc_in.weight") for i in range(num_layers)]),
        "bg": jnp.stack([g(i, "fc_in.bias") for i in range(num_layers)]),
        "wd": jnp.stack([g(i, "fc_out.weight") for i in range(num_layers)]),
        "bd": jnp.stack([g(i, "fc_out.bias") for i in range(num_layers)]),
    }
    return out


def build_fused_params_moe(state: Dict[str, jax.Array], num_layers: int,
                           prefix: str = "model.layers.") -> Dict[str, jax.Array]:
    """Mixtral-block stacks: llama attention (ln1/wqkv/wo) + MoE FFN.

    Returns {ln1 (L,h), wqkv (L,h,dqkv), wo (L,dq,h), ln2 (L,h),
    gate (L,E,h) — the router projection TRANSPOSED so its lane dim is h
    (HBM lane dims want 128-multiples; E is typically 8), weg/weu
    (L,E,h,f), wed (L,E,f,h)}. The expert stacks stay in HBM; the kernel
    streams only the routed experts' weights per token (the TPU-native
    analog of the reference's fused MoE inference path —
    fused_multi_transformer + global_scatter composition).

    DeepSeekMoE shared experts (the model's concatenated `shared_mlp`)
    add dense stacks {wsg/wsu (L,h,ns·f), wsd (L,ns·f,h)} — every token
    uses them, so the kernel streams them like the llama FFN."""
    cols = {"ln1": [], "wqkv": [], "wo": [], "ln2": [], "gate": [],
            "weg": [], "weu": [], "wed": []}
    shared = f"{prefix}0.shared_mlp.gate_proj.weight" in state
    if shared:
        cols.update({"wsg": [], "wsu": [], "wsd": []})
    for i in range(num_layers):
        cols["ln1"].append(state[f"{prefix}{i}.input_layernorm.weight"])
        cols["wqkv"].append(jnp.concatenate(
            [state[f"{prefix}{i}.self_attn.{n}_proj.weight"]
             for n in ("q", "k", "v")], axis=1))
        cols["wo"].append(state[f"{prefix}{i}.self_attn.o_proj.weight"])
        cols["ln2"].append(
            state[f"{prefix}{i}.post_attention_layernorm.weight"])
        cols["gate"].append(state[f"{prefix}{i}.moe.gate.proj.weight"].T)
        cols["weg"].append(state[f"{prefix}{i}.moe.experts.w_gate"])
        cols["weu"].append(state[f"{prefix}{i}.moe.experts.w_up"])
        cols["wed"].append(state[f"{prefix}{i}.moe.experts.w_down"])
        if shared:
            cols["wsg"].append(state[f"{prefix}{i}.shared_mlp.gate_proj.weight"])
            cols["wsu"].append(state[f"{prefix}{i}.shared_mlp.up_proj.weight"])
            cols["wsd"].append(state[f"{prefix}{i}.shared_mlp.down_proj.weight"])
    return {k: jnp.stack(v) for k, v in cols.items()}


def quantize_kv_cache(kv, num_kv_heads: int):
    """Quantize a combined flat KV cache (L, b, S, 2*nkv*hd) to int8 with
    per-(layer, kv-head) symmetric scales — the fused_multi_transformer_int8
    cache_kv quant analog, calibrated from the cache contents themselves
    (prefill acts as the calibration pass; decode-appended tokens reuse the
    same static scales and clip outliers).

    Returns (cache int8, scales (L, 1, 2*nkv*hd) fp32) — the scales are
    lane-replicated across each head's hd lanes so both the kernel and the
    jnp reference can apply them with a single broadcast multiply (k-half
    scales fold into the q rows, v-half scales apply to the attention
    output)."""
    with jax.named_scope("fused_decode.quantize_kv_cache"):
        L, b, S, dkv2 = kv.shape
        hd = dkv2 // (2 * num_kv_heads)
        amax = jnp.abs(kv.astype(jnp.float32)).max(axis=(1, 2))   # (L, 2dkv)
        amax = amax.reshape(L, 2 * num_kv_heads, hd).max(axis=-1)  # (L, 2nkv)
        scales = jnp.maximum(amax / 127.0, 1e-8)
        lanes = jnp.repeat(scales, hd, axis=-1)[:, None, :]       # (L,1,2dkv)
        q = jnp.clip(jnp.round(kv.astype(jnp.float32) / lanes[:, None]),
                     -127, 127)
        return q.astype(jnp.int8), lanes


def _layernorm(x, w, b, eps):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mu) * lax.rsqrt(var + eps)
    return (y.astype(w.dtype) * w + b)


def _rms(x, w, eps):
    """fp32 rms-normalize, cast to w.dtype path of ops.rms_norm."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    y = (xf * lax.rsqrt(var + eps))
    return (y.astype(w.dtype) * w)


def _rope1(x, cos, sin):
    """x (b, n, hd) fp32; cos/sin (1, 1, hd)."""
    hd = x.shape[-1]
    x1 = x[..., : hd // 2]
    x2 = x[..., hd // 2:]
    rot = jnp.concatenate([-x2, x1], axis=-1)
    return x * cos + rot * sin


# ---------------------------------------------------------------------------
# jnp reference (numerics twin + non-TPU fallback)
# ---------------------------------------------------------------------------

def fused_decode_reference(x, params, kv_cache, pos, cos, sin, *,
                           num_heads: int, num_kv_heads: int,
                           eps: float = 1e-5, arch: str = "llama",
                           top_k: int = 2, kv_scales=None):
    """One decode step through the whole stack; pure jnp.

    x (b, h); the KV cache is stored COMBINED and FLAT as
    (L, b, S, 2*nkv*hd) with k in lanes [0, nkv*hd) and v in the rest —
    the layout the Pallas kernel DMAs (one copy per chunk, lane dim a
    128-multiple); pos scalar int; cos/sin (1, hd) fp32 for position
    `pos`. Returns (x_out (b, h), kv_cache). Matches the Pallas kernel up
    to XLA fusion differences: residual stream fp32, attention over
    [0, pos] only (masked), softmax fp32.

    int8 KV cache mode: kv_cache int8 + `kv_scales` (L, 1, 2*nkv*hd) fp32
    (see quantize_kv_cache) — reads dequantize with the per-head scales,
    the appended token is quantized with the same static scales.
    """
    L, b, S, dkv2 = kv_cache.shape
    dkv = dkv2 // 2
    nh = num_heads
    nkv = num_kv_heads
    hd = dkv // nkv
    rep = nh // nkv
    dq = nh * hd
    dtype = x.dtype
    scale = 1.0 / math.sqrt(hd)
    int8 = "wqkv_s" in params
    cos_b = cos.reshape(1, 1, hd).astype(jnp.float32)
    sin_b = sin.reshape(1, 1, hd).astype(jnp.float32)

    def wdot(act, key, l):
        w = params[key][l]
        if int8:
            y = jnp.dot(act, w.astype(act.dtype),
                        preferred_element_type=jnp.float32)
            return y * params[f"{key}_s"][l]
        return jnp.dot(act, w, preferred_element_type=jnp.float32)

    gpt = arch == "gpt"
    xf = x.astype(jnp.float32)
    for l in range(L):
        if gpt:
            xn = _layernorm(xf, params["ln1"][l], params["ln1_b"][l], eps)
        else:
            xn = _rms(xf, params["ln1"][l], eps)
        qkv = wdot(xn, "wqkv", l)
        if gpt:
            qkv = qkv + params["bqkv"][l]
        q = qkv[:, :dq].reshape(b, nh, hd)
        k = qkv[:, dq:dq + nkv * hd].reshape(b, nkv, hd)
        v = qkv[:, dq + nkv * hd:].reshape(b, nkv, hd)
        if not gpt:
            q = _rope1(q, cos_b, sin_b)
            k = _rope1(k, cos_b, sin_b)
        kv_new = jnp.concatenate(
            [k.reshape(b, dkv), v.reshape(b, dkv)], axis=-1)
        if kv_scales is not None:       # int8 cache: quantize the append
            kv_new = jnp.clip(
                jnp.round(kv_new.astype(jnp.float32) / kv_scales[l]),
                -127, 127)
        kv_cache = lax.dynamic_update_slice(
            kv_cache, kv_new.astype(kv_cache.dtype)[None, :, None],
            (l, 0, pos, 0))
        kl = kv_cache[l, :, :, :dkv].astype(jnp.float32)
        vl = kv_cache[l, :, :, dkv:].astype(jnp.float32)
        if kv_scales is not None:       # dequantize with per-head scales
            kl = kl * kv_scales[l, :, :dkv][None]
            vl = vl * kv_scales[l, :, dkv:][None]
        kl = kl.reshape(b, S, nkv, hd)
        vl = vl.reshape(b, S, nkv, hd)
        qg = q.reshape(b, nkv, rep, hd) * scale
        scores = jnp.einsum("bgrd,bsgd->bgrs", qg, kl)
        valid = jnp.arange(S)[None, None, None] <= pos
        scores = jnp.where(valid, scores, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bgrs,bsgd->bgrd", probs, vl)
        attn = attn.reshape(b, dq).astype(dtype)
        o = wdot(attn, "wo", l)
        if gpt:
            o = o + params["bo"][l]
        xf = xf + o
        if gpt:
            xn2 = _layernorm(xf, params["ln2"][l], params["ln2_b"][l], eps)
            g = wdot(xn2, "wg", l) + params["bg"][l]
            act = jax.nn.gelu(g, approximate=True).astype(dtype)
            xf = xf + wdot(act, "wd", l) + params["bd"][l]
        elif arch == "moe":
            # router math matches nn.layers.moe topk_routing: fp32 softmax
            # over the full expert set from the bf16 post-norm activations,
            # top-k renormalized. No-drop condition (b·k ≤ capacity) is
            # the fused path's eligibility gate, so `keep` is vacuous.
            xn2 = _rms(xf, params["ln2"][l], eps).astype(dtype)
            logits = jnp.dot(xn2.astype(jnp.float32),
                             params["gate"][l].astype(jnp.float32).T)
            probs = jax.nn.softmax(logits, axis=-1)
            vals, idx = lax.top_k(probs, top_k)            # (b, k)
            vals = vals / jnp.maximum(
                jnp.sum(vals, axis=-1, keepdims=True), 1e-9)
            wg_sel = jnp.take(params["weg"][l], idx, axis=0)  # (b,k,h,f)
            wu_sel = jnp.take(params["weu"][l], idx, axis=0)
            wd_sel = jnp.take(params["wed"][l], idx, axis=0)  # (b,k,f,h)
            g = jnp.einsum("bh,bkhf->bkf", xn2, wg_sel,
                           preferred_element_type=jnp.float32)
            u = jnp.einsum("bh,bkhf->bkf", xn2, wu_sel,
                           preferred_element_type=jnp.float32)
            act = (jax.nn.silu(g) * u).astype(dtype)
            d = jnp.einsum("bkf,bkfh->bkh", act, wd_sel,
                           preferred_element_type=jnp.float32)
            xf = xf + jnp.einsum("bk,bkh->bh", vals, d)
            if "wsg" in params:   # DeepSeekMoE shared experts: dense SwiGLU
                sg = jnp.dot(xn2, params["wsg"][l],
                             preferred_element_type=jnp.float32)
                su = jnp.dot(xn2, params["wsu"][l],
                             preferred_element_type=jnp.float32)
                sact = (jax.nn.silu(sg) * su).astype(dtype)
                xf = xf + jnp.dot(sact, params["wsd"][l],
                                  preferred_element_type=jnp.float32)
        else:
            xn2 = _rms(xf, params["ln2"][l], eps)
            g = wdot(xn2, "wg", l)
            u = wdot(xn2, "wu", l)
            act = (jax.nn.silu(g) * u).astype(dtype)
            xf = xf + wdot(act, "wd", l)
    return xf.astype(dtype), kv_cache


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------

def _pick_ffn_blocks(ffn: int, h: int, fixed_bytes: int, wbytes: int,
                     budget: Optional[int] = None):
    """Smallest J (ffn % J == 0) whose per-grid-step VMEM estimate —
    double-buffered weight blocks (attention weights + one FFN column
    block) on top of `fixed_bytes` of scratch — fits `budget` (default:
    derived from the device generation's VMEM, _vmem_budget_bytes)."""
    if budget is None:
        budget = _vmem_budget_bytes()
    for j in range(1, ffn + 1):
        if ffn % j:
            continue
        fblk = ffn // j
        weights = fixed_bytes + 3 * fblk * h * wbytes
        if 2 * weights + 8 * 2 ** 20 <= budget or fblk <= 128:
            return j, fblk
    return ffn, 1


def _fused_decode_pallas(x, params, kv_cache, pos, *,
                         num_heads: int, num_kv_heads: int, head_dim: int,
                         rope_base: float = 10000.0,
                         eps: float = 1e-5, chunk: int = 0,
                         arch: str = "llama", blocks: Optional[Dict] = None,
                         kv_scales=None, interpret: bool = False):
    # NOTE: not jit-wrapped — always invoked inside the caller's jit (the
    # generate() scan); a nested jit around a pallas_call trips XLA's
    # closed_call lowering cache.
    #
    # Mosaic layout rules shape this kernel (probed on v5e):
    #  * values cannot reshape the lane dim -> heads are split with lane
    #    SLICES (static, unrolled); attention batches ALL heads into one
    #    dot_general per KV block by staging q BLOCK-DIAGONALLY over the
    #    kv-group lane blocks (row n of q_s carries head n's rope'd q in
    #    its group's hd lanes, zeros elsewhere — zero lanes contract to
    #    exact 0 against the KV chunk, so one (b·nh)-row matmul replaces
    #    the old nkv unrolled per-group products)
    #  * DMA slices on the token (minor-2) dim must be 8-aligned -> the
    #    cache append is an aligned 8-token read-modify-write
    #  * HBM lane dims want 128-multiples -> the cache is stored flat as
    #    (L, b, S, nkv*hd)
    #  * bf16 relayouts through unit-dim inserts fail -> all merging math
    #    runs in fp32 with full-ref casts at the end
    #
    # int8 KV cache mode (kv_cache int8 + kv_scales (L, 1, 2*dkv) fp32):
    # chunks stream from HBM as int8 (half the cache DMA), dequantized on
    # the VMEM->MXU path — the k-half scales fold into the block-diagonal
    # q rows (one broadcast multiply), the v-half scales apply once to the
    # normalized attention output; the RMW append quantizes the new token
    # with the same static per-head scales.
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    L, b, S, dkv2 = kv_cache.shape
    dkv = dkv2 // 2
    nh = num_heads
    nkv = num_kv_heads
    hd = head_dim
    assert hd == dkv // nkv
    rep = nh // nkv
    h = x.shape[1]
    dq = nh * hd
    dqkv = dq + 2 * dkv
    ffn = params["wg"].shape[2]          # ffn_pad when a plan padded it
    int8 = "wqkv_s" in params
    kvq = kv_scales is not None
    assert kvq == (jnp.dtype(kv_cache.dtype) == jnp.int8), \
        "int8 KV cache needs kv_scales (and vice versa)"
    gpt = arch == "gpt"
    wbytes = 1 if int8 else 2
    cb = jnp.dtype(kv_cache.dtype).itemsize
    if blocks is not None:
        Qs, qblk = blocks["q_split"], blocks["qblk"]
        J, fblk = blocks["ffn_blocks"], blocks["fblk"]
        assert ffn == J * fblk, (ffn, blocks)
        assert not (gpt and Qs > 1), "qkv split unsupported for arch=gpt"
        assert blocks.get("cache_wbytes", cb) == cb, \
            (f"decode plan assumed a {blocks['cache_wbytes']}-byte KV "
             f"cache but the cache dtype is {kv_cache.dtype} ({cb} B)")
    else:
        Qs, qblk = 1, dqkv
        J, fblk = _pick_ffn_blocks(
            ffn, h, fixed_bytes=(dqkv + nh * hd) * h * wbytes, wbytes=wbytes)
    if not chunk:
        chunk = 128
        if blocks is not None:
            # pick the KV chunk so weights + scratch fit the scoped-VMEM
            # ceiling. In the split regime ck=64 measured fastest on the
            # llama2-7b sweep (SCALE.md r5) — chunk DMA granularity
            # overlaps the weight stream better than maximal chunks.
            w2 = 2 * (qblk + dq + 3 * fblk) * h * wbytes
            # scratch: RMW block + kv32 staging + block-diagonal q_s and
            # the fori_loop-carried (b, nh, dkv) fp32 attention acc
            scratch_fixed = (b * 8 * 2 * dkv * cb + b * 2 * dkv * 4
                             + 2 * b * nh * dkv * 4 + b * h * 10)
            order = (64, 128, 32, 16, 8) if Qs > 1 else (128, 64, 32, 16, 8)
            for cand in order:
                if S % cand == 0 and (w2 + scratch_fixed + 6 * 2 ** 20
                                      + 2 * b * cand * 2 * dkv * cb
                                      <= _vmem_limit_bytes()):
                    chunk = cand
                    break
    ck = min(chunk, S)
    assert S % ck == 0, f"cache len {S} not a multiple of chunk {ck}"
    assert dkv % 128 == 0, f"nkv*hd={dkv} must be a lane multiple of 128"
    dtype = x.dtype
    scale = 1.0 / math.sqrt(hd)

    def kernel(*refs):
        if gpt:       # no gate weight: single GELU FFN matmul
            (pos_ref, x_in_ref, ln1_ref, wqkv_ref, wo_ref, ln2_ref,
             wg_ref, wd_ref) = refs[:8]
            wu_ref = None
            i = 8
        else:
            (pos_ref, x_in_ref, ln1_ref, wqkv_ref, wo_ref, ln2_ref,
             wg_ref, wu_ref, wd_ref) = refs[:9]
            i = 9
        if gpt:
            (ln1b_ref, ln2b_ref, bqkv_ref, bo_ref, bg_ref,
             bd_ref) = refs[i:i + 6]
            i += 6
        if int8:
            sqkv_ref, so_ref, sg_ref, su_ref, sd_ref = refs[i:i + 5]
            i += 5
        if kvq:
            kvs_ref = refs[i]            # (1, 2*dkv) per-head cache scales
            i += 1
        kv_in = refs[i]                  # aliased with kv_ref
        x_out_ref, kv_ref = refs[i + 1], refs[i + 2]
        (x_s, xn_s, acc_s, q_s, kv32_s, kvblk_s, kvch_s,
         wsem, rsem) = refs[i + 3:]
        del kv_in

        def wdot(act, wref, sref, rows=None):
            """act @ w with weight-only-int8 dequant folded onto the
            OUTPUT columns (per-out-channel scales) — the int8 stream
            converts to bf16 on the VMEM->MXU path, never touching HBM
            in bf16 (fused_multi_transformer_int8 semantics)."""
            w = wref[...] if rows is None else wref[rows, :]
            if int8:
                y = jnp.dot(act, w.astype(act.dtype),
                            preferred_element_type=jnp.float32)
                return y if sref is None else y * sref[...]
            return jnp.dot(act, w, preferred_element_type=jnp.float32)
        li = pl.program_id(0)
        j = pl.program_id(1)
        pos = pos_ref[0]

        def qkv_phase(p):
            # Phase p streams wqkv's column block p and stages its
            # head-aligned slices; the LAST phase also runs attention.
            # (Qs == 1 reproduces the original single attention phase.)
            blk = (pos // 8) * 8
            off = pos - blk

            def chunk_copy(c, slot):
                return pltpu.make_async_copy(
                    kv_ref.at[li, :, pl.ds(c * ck, ck)],
                    kvch_s.at[slot], rsem.at[slot])

            nc = (blk + ck - 1) // ck          # chunks covering [0, blk)
            if p == 0:
                # cache-append RMW block reads: layer 0 issues its own
                # (plus chunk 0); for later layers the previous layer's
                # first FFN step prefetched them
                @pl.when(li == 0)
                def _():
                    x_s[...] = x_in_ref[...].astype(jnp.float32)
                    # one-time zero of the block-diagonal q staging: every
                    # layer rewrites the same in-block lanes, so off-block
                    # lanes stay zero for the whole stack
                    q_s[...] = jnp.zeros_like(q_s)
                    pltpu.make_async_copy(
                        kv_ref.at[li, :, pl.ds(blk, 8)], kvblk_s,
                        wsem.at[0]).start()

                @pl.when((li == 0) & (nc > 0))
                def _():
                    chunk_copy(0, 0).start()

            if gpt:
                xn = _layernorm(x_s[...], ln1_ref[...].reshape(h),
                                ln1b_ref[...].reshape(h), eps)
            else:
                xn = _rms(x_s[...], ln1_ref[...].reshape(h), eps)
            part = wdot(xn, wqkv_ref, sqkv_ref if int8 else None)
            if gpt:
                part = part + bqkv_ref[...]
                rope2 = lambda t: t
            else:
                # rope angles computed in-kernel from pos (NeoX convention:
                # freqs repeated over both halves) — no XLA cos/sin table
                half = (lax.broadcasted_iota(jnp.int32, (1, hd), 1)
                        % (hd // 2)).astype(jnp.float32)
                inv_freq = jnp.exp(half * (-2.0 * math.log(rope_base) / hd))
                ang = pos.astype(jnp.float32) * inv_freq
                cos_b = jnp.cos(ang)
                sin_b = jnp.sin(ang)
                rope2 = lambda t: (t * cos_b + jnp.concatenate(
                    [-t[:, hd // 2:], t[:, :hd // 2]], axis=-1) * sin_b)
            # heads via lane slices (no lane reshapes): q staged BLOCK-
            # DIAGONALLY into (b, nh, dkv) f32 scratch — head n's rope'd,
            # pre-scaled q lands in its kv-group's hd lanes (row n, lanes
            # [g·hd, (g+1)·hd)) so attention runs as ONE dot_general per
            # KV block for all heads; new k/v staged FLAT (b, 2*dkv) f32
            # for the RMW merge. A column block may straddle the q|k|v
            # boundaries — qblk % hd == 0 keeps every slice head-aligned.
            for t in range(qblk // hd):
                col = p * qblk + t * hd
                seg = part[:, t * hd:(t + 1) * hd]
                if col < dq:
                    n = col // hd
                    g = n // rep
                    q_s[:, n, g * hd:(g + 1) * hd] = rope2(seg) * scale
                elif col < dq + dkv:
                    kv32_s[:, col - dq:col - dq + hd] = rope2(seg)
                else:
                    kv32_s[:, col - dq:col - dq + hd] = seg
            if p == Qs - 1:
                attention_tail(blk, off, chunk_copy, nc)

        def attention_tail(blk, off, chunk_copy, nc):
            # ---- online softmax, three stages sharing one set of
            # carries: (a) double-buffered chunk loop over the prefix
            # [0, blk) from HBM; (b) the freshly merged 8-token block
            # [blk, pos] straight from VMEM; stage (b) also hides the RMW
            # write-back behind the o-proj.
            rkb = pltpu.make_async_copy(
                kv_ref.at[li, :, pl.ds(blk, 8)], kvblk_s, wsem.at[0])

            # batched-head q: the block-diagonal (b, nh, dkv) staging; in
            # int8-cache mode the k-half dequant scales fold in here (one
            # broadcast multiply — off-block lanes are zero either way)
            if kvq:
                qbd = q_s[...] * kvs_ref[...][:, :dkv][None]
            else:
                qbd = q_s[...]

            def merge(carry, kvblk, idx, limit):
                """One online-softmax block update over ALL heads: kvblk
                (b, width, 2*dkv) in cache dtype; ONE score dot_general
                (block-diagonal q rows) + ONE weighted-value dot_general
                replace the old nkv unrolled per-group products."""
                m, l, acc = carry
                kf = kvblk[:, :, :dkv].astype(jnp.float32)
                vf = kvblk[:, :, dkv:].astype(jnp.float32)
                sc = lax.dot_general(
                    qbd, kf, (((2,), (2,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32)      # (b, nh, w)
                sc = jnp.where(idx < limit, sc, NEG_INF)
                m_new = jnp.maximum(m, jnp.max(sc, axis=-1))
                alpha = jnp.exp(m - m_new)
                pp = jnp.exp(sc - m_new[..., None])
                # row n of acc holds head n's weighted v in its group's
                # lane block (other lane blocks carry other groups' values
                # weighted with head n's probs — masked out at the o-proj)
                acc = acc * alpha[..., None] + lax.dot_general(
                    pp, vf, (((2,), (1,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32)      # (b, nh, dkv)
                return m_new, l * alpha + jnp.sum(pp, axis=-1), acc

            def body(c, carry):
                slot = lax.rem(c, 2)

                @pl.when(c + 1 < nc)
                def _():
                    chunk_copy(c + 1, lax.rem(c + 1, 2)).start()

                chunk_copy(c, slot).wait()
                idx = c * ck + lax.broadcasted_iota(
                    jnp.int32, (1, 1, ck), 2)
                return merge(carry, kvch_s[slot], idx, blk)

            carry = lax.fori_loop(0, nc, body, (
                jnp.full((b, nh), NEG_INF, jnp.float32),
                jnp.zeros((b, nh), jnp.float32),
                jnp.zeros((b, nh, dkv), jnp.float32)))

            # merge the new token into the RMW block, attend to it from
            # VMEM, and write the block back (waited in FFN j==1)
            rkb.wait()
            sel = lax.broadcasted_iota(jnp.int32, (1, 8, 1), 1) == off
            newtok = kv32_s[...]
            if kvq:         # quantize the append with the static scales
                newtok = jnp.clip(
                    jnp.round(newtok / kvs_ref[...]), -127.0, 127.0)
            kvblk_s[...] = jnp.where(
                sel, newtok[:, None, :],
                kvblk_s[...].astype(jnp.float32)).astype(kv_cache.dtype)
            wkb = pltpu.make_async_copy(
                kvblk_s, kv_ref.at[li, :, pl.ds(blk, 8)], wsem.at[0])
            wkb.start()
            bidx = blk + lax.broadcasted_iota(jnp.int32, (1, 1, 8), 2)
            ms, ls, accs = merge(carry, kvblk_s[...], bidx, pos + 1)

            norm = accs / ls[..., None]                     # (b, nh, dkv)
            if kvq:         # v-half dequant scales, applied once
                norm = norm * kvs_ref[...][:, dkv:][None]
            # o-proj without a lane-merge relayout:
            #  * MHA (rep == 1): rows and lane blocks are 1:1 — mask to
            #    the block diagonal and SUM over the head rows (adding
            #    exact zeros), collapsing to flat (b, dq) for ONE full
            #    matmul against wo
            #  * GQA (rep > 1): heads of a group share a lane block, so
            #    the sum would collide — one dot_general per kv group,
            #    batched over its rep heads against wo's row blocks
            if rep == 1:
                bd = (lax.broadcasted_iota(jnp.int32, (1, nh, dkv), 2)
                      // hd == lax.broadcasted_iota(
                          jnp.int32, (1, nh, dkv), 1))
                attn = jnp.sum(jnp.where(bd, norm, 0.0), axis=1)  # (b, dq)
                oacc = wdot(attn.astype(dtype), wo_ref,
                            so_ref if int8 else None)
            else:
                oacc = jnp.zeros((b, h), jnp.float32)
                for g in range(nkv):
                    ng = norm[:, g * rep:(g + 1) * rep,
                              g * hd:(g + 1) * hd]          # (b, rep, hd)
                    w3 = wo_ref[g * rep * hd:(g + 1) * rep * hd,
                                :].reshape(rep, hd, h)
                    part = lax.dot_general(
                        ng.astype(dtype),
                        w3.astype(dtype) if int8 else w3,
                        (((2,), (1,)), ((1,), (0,))),
                        preferred_element_type=jnp.float32)  # (rep, b, h)
                    oacc = oacc + jnp.sum(part, axis=0)
                if int8:
                    oacc = oacc * so_ref[...]
            if gpt:
                oacc = oacc + bo_ref[...]
            x = x_s[...] + oacc
            x_s[...] = x
            if gpt:
                xn_s[...] = _layernorm(x, ln2_ref[...].reshape(h),
                                       ln2b_ref[...].reshape(h),
                                       eps).astype(dtype)
            else:
                xn_s[...] = _rms(x, ln2_ref[...].reshape(h),
                                 eps).astype(dtype)
            acc_s[...] = jnp.zeros_like(acc_s)

        for p in range(Qs):
            pl.when(j == p)(functools.partial(qkv_phase, p))

        @pl.when(j >= Qs)
        def ffn_phase():
            @pl.when(j == Qs)
            def prefetch_next_layer():
                # drain this layer's cache write-back, then issue the next
                # layer's RMW-block + chunk-0 reads so its attention phase
                # never stalls on DMA latency
                blk = (pos // 8) * 8
                pltpu.make_async_copy(
                    kvblk_s, kv_ref.at[li, :, pl.ds(blk, 8)],
                    wsem.at[0]).wait()

                @pl.when(li + 1 < L)
                def _():
                    pltpu.make_async_copy(
                        kv_ref.at[li + 1, :, pl.ds(blk, 8)], kvblk_s,
                        wsem.at[0]).start()

                    @pl.when(blk > 0)
                    def _():
                        pltpu.make_async_copy(
                            kv_ref.at[li + 1, :, pl.ds(0, ck)],
                            kvch_s.at[0], rsem.at[0]).start()

            xn = xn_s[...]
            g = wdot(xn, wg_ref, sg_ref if int8 else None)
            if gpt:
                g = g + bg_ref[...]
                act = jax.nn.gelu(g, approximate=True).astype(dtype)
            else:
                u = wdot(xn, wu_ref, su_ref if int8 else None)
                act = (jax.nn.silu(g) * u).astype(dtype)
            acc_s[...] += wdot(act, wd_ref, sd_ref if int8 else None)

            if gpt:
                @pl.when(j == Qs + J - 1)
                def _():
                    acc_s[...] += jnp.broadcast_to(bd_ref[...], acc_s.shape)

            @pl.when(j == Qs + J - 1)
            def _():
                x = x_s[...] + acc_s[...]
                x_s[...] = x
                x_out_ref[...] = x.astype(dtype)

    def qi(jj):
        # qkv column block: phase j < Qs streams block j; FFN phases keep
        # the last block resident (no refetch)
        return jnp.minimum(jj, Qs - 1)

    def jm(ll, jj):
        # attention phases (j < Qs) reuse whatever the previous grid step
        # held (layer l-1's last FFN block) so they issue no FFN-weight
        # fetch; j >= Qs streams block j-Qs of layer l.
        return jnp.where(jj < Qs, J - 1, jj - Qs)

    def fl(ll, jj):
        return lax.max(ll - (jj < Qs), 0)
    grid = (L, Qs + J)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),                 # pos
            pl.BlockSpec((b, h), lambda l, j: (0, 0)),             # x
            pl.BlockSpec((None, 1, h), lambda l, j: (l, 0, 0)),    # ln1
            pl.BlockSpec((None, h, qblk),
                         lambda l, j: (l, 0, qi(j))),               # wqkv
            pl.BlockSpec((None, dq, h), lambda l, j: (l, 0, 0)),   # wo
            pl.BlockSpec((None, 1, h), lambda l, j: (l, 0, 0)),    # ln2
            pl.BlockSpec((None, h, fblk),
                         lambda l, j: (fl(l, j), 0, jm(l, j))),     # wg
        ] + ([] if gpt else [
            pl.BlockSpec((None, h, fblk),
                         lambda l, j: (fl(l, j), 0, jm(l, j))),     # wu
        ]) + [
            pl.BlockSpec((None, fblk, h),
                         lambda l, j: (fl(l, j), jm(l, j), 0)),     # wd
        ] + ([
            pl.BlockSpec((None, 1, h), lambda l, j: (l, 0, 0)),     # ln1_b
            pl.BlockSpec((None, 1, h), lambda l, j: (l, 0, 0)),     # ln2_b
            pl.BlockSpec((None, 1, dqkv), lambda l, j: (l, 0, 0)),  # bqkv
            pl.BlockSpec((None, 1, h), lambda l, j: (l, 0, 0)),     # bo
            pl.BlockSpec((None, 1, fblk),
                         lambda l, j: (fl(l, j), 0, jm(l, j))),     # bg
            pl.BlockSpec((None, 1, h), lambda l, j: (l, 0, 0)),     # bd
        ] if gpt else []) + ([
            pl.BlockSpec((None, 1, qblk),
                         lambda l, j: (l, 0, qi(j))),               # sqkv
            pl.BlockSpec((None, 1, h), lambda l, j: (l, 0, 0)),     # so
            pl.BlockSpec((None, 1, fblk),
                         lambda l, j: (fl(l, j), 0, jm(l, j))),     # sg
            pl.BlockSpec((None, 1, fblk),
                         lambda l, j: (fl(l, j), 0, jm(l, j))),     # su
            pl.BlockSpec((None, 1, h), lambda l, j: (l, 0, 0)),     # sd
        ] if int8 else []) + ([
            pl.BlockSpec((None, 1, 2 * dkv), lambda l, j: (l, 0, 0)),  # kvs
        ] if kvq else []) + [
            pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),      # kv_cache
        ],
        out_specs=[
            pl.BlockSpec((b, h), lambda l, j: (0, 0)),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h), dtype),
            jax.ShapeDtypeStruct(kv_cache.shape, kv_cache.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((b, h), jnp.float32),          # x_s
            pltpu.VMEM((b, h), dtype),                # xn_s
            pltpu.VMEM((b, h), jnp.float32),          # acc_s
            pltpu.VMEM((b, nh, dkv), jnp.float32),    # q_s (block-diag)
            pltpu.VMEM((b, 2 * dkv), jnp.float32),    # kv32_s staging
            pltpu.VMEM((b, 8, 2 * dkv), kv_cache.dtype),   # kvblk_s RMW
            pltpu.VMEM((2, b, ck, 2 * dkv), kv_cache.dtype),  # kvch_s dbuf
            pltpu.SemaphoreType.DMA((1,)),            # wsem
            pltpu.SemaphoreType.DMA((2,)),            # rsem
        ],
        input_output_aliases={(9 - gpt + 6 * gpt + 5 * int8 + kvq): 1},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
            # the default 16 MiB scoped limit can't hold a layer's
            # double-buffered weights + KV chunks; raise to the device
            # generation's capacity minus headroom
            vmem_limit_bytes=_vmem_limit_bytes()),
        name="fused_decode_step",
        interpret=interpret,
    )(jnp.asarray(pos, jnp.int32).reshape(1), x,
      params["ln1"][:, None], params["wqkv"],
      params["wo"], params["ln2"][:, None], params["wg"],
      *(() if gpt else (params["wu"],)),
      params["wd"],
      *((params["ln1_b"][:, None], params["ln2_b"][:, None],
         params["bqkv"][:, None], params["bo"][:, None],
         params["bg"][:, None], params["bd"][:, None]) if gpt else ()),
      *((params["wqkv_s"], params["wo_s"], params["wg_s"],
         params["wu_s"], params["wd_s"]) if int8 else ()),
      *((jnp.asarray(kv_scales, jnp.float32),) if kvq else ()),
      kv_cache)
    return out[0], out[1]



def _pick_expert_blocks(ffn: int, h: int, fixed_bytes: int, wbytes: int,
                        budget: Optional[int] = None, nbuf: int = 2):
    """Smallest J (ffn % J == 0, block a 128-lane multiple — expert-weight
    DMAs slice the lane dim) whose `nbuf`-buffered expert blocks fit the
    VMEM budget on top of `fixed_bytes` (nbuf=3 for the prefetch-two-ahead
    routed-expert pipeline)."""
    if budget is None:
        budget = _vmem_budget_bytes()
    best = None
    for j in range(1, ffn // 128 + 1):
        if ffn % j or (ffn // j) % 128:
            continue
        fblk = ffn // j
        need = fixed_bytes + nbuf * 3 * fblk * h * wbytes + 8 * 2 ** 20
        best = (j, fblk)              # smallest valid block so far
        if need <= budget:
            return j, fblk
    if best is None:
        raise ValueError(f"expert ffn {ffn} has no 128-multiple block")
    # Nothing fit the budget: fall back to the SMALLEST valid block (the
    # last candidate) — the one least likely to overflow VMEM.
    return best


def _fused_decode_moe_pallas(x, params, kv_cache, pos, *,
                             num_heads: int, num_kv_heads: int,
                             head_dim: int, top_k: int,
                             rope_base: float = 10000.0,
                             eps: float = 1e-5, chunk: int = 0,
                             blocks: Optional[Dict] = None,
                             kv_scales=None,
                             interpret: bool = False):
    """Fused MoE decode step: llama attention block + top-k expert FFN with
    DATA-DEPENDENT weight streaming.

    The llama/gpt kernel streams its FFN weights through Mosaic-pipelined
    BlockSpecs — impossible here because which expert's weights are needed
    is decided by the router *inside* the kernel. Instead the expert
    stacks stay in HBM (`pl.ANY`) and the kernel hand-rolls a
    PREFETCH-TWO-AHEAD async-copy pipeline over b·top_k slots per layer
    (3 VMEM buffers, copies for steps u+1 AND u+2 in flight while step u
    computes), fetching ONLY the routed experts' weights — decode is
    weight-bandwidth-bound, so per-token traffic drops from E experts to
    top_k (the TPU-native analog of the reference's fused MoE inference:
    fused_multi_transformer + global_scatter, SURVEY §2.2 fusion + §2.6
    EP). The depth-2 prefetch is the b=1 bubble fix (r5: 72% of
    roofline): with double buffering, slot u+1's weights were only
    requested when slot u's matmul began, so small b·k left the DMA
    engine idle across the slot turnaround; now the attention/router
    phase launches slots 0 and 1 together and every FFN step keeps two
    fetches in flight.

    Grid (L, 1 + Js + b·k·J): phase 0 = attention + router (argmax top-k
    into SMEM so the DMA engine can address expert slices); phases 1.. =
    one (row, choice, ffn-block) expert matmul each. Requires b·top_k ≤
    routing capacity (no-drop — the eligibility gate) and E % 8 == 0.

    int8 KV cache mode (kv_cache int8 + kv_scales (L, 1, 2*dkv) fp32 —
    see `quantize_kv_cache`): same folding as the llama/gpt kernel — the
    k-half scales fold into the block-diagonal q rows, the v-half scales
    apply once to the normalized attention output, and the RMW append
    quantizes the new token with the static per-head scales. `blocks`
    (a `decode_block_plan` dict) is consistency-checked: the plan's
    `cache_wbytes` must match the actual cache dtype, and the KV chunk
    is sized from the CACHE element size, so an int8 cache streams
    double-length chunks at unchanged chunk bytes.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    L, b, S, dkv2 = kv_cache.shape
    dkv = dkv2 // 2
    nh = num_heads
    nkv = num_kv_heads
    hd = head_dim
    assert hd == dkv // nkv
    rep = nh // nkv
    h = x.shape[1]
    dq = nh * hd
    dqkv = dq + 2 * dkv
    E = params["gate"].shape[1]
    ffn = params["weg"].shape[3]
    k = top_k
    nslots = b * k
    wbytes = 2
    kvq = kv_scales is not None
    assert kvq == (jnp.dtype(kv_cache.dtype) == jnp.int8), \
        "int8 KV cache needs kv_scales (and vice versa)"
    cb = jnp.dtype(kv_cache.dtype).itemsize
    if blocks is not None:
        assert blocks.get("cache_wbytes", cb) == cb, \
            (f"decode plan assumed a {blocks['cache_wbytes']}-byte KV "
             f"cache but the cache dtype is {kv_cache.dtype} ({cb} B)")
    shared = "wsg" in params
    fs = params["wsg"].shape[2] if shared else 0
    NBUF, PF = 3, 2        # prefetch-two-ahead triple-buffered pipeline
    # attention weights ride the Mosaic pipeline (double-buffered), expert
    # blocks ride the manual pipeline — both count against VMEM, as do the
    # block-diagonal q staging and the fori_loop-carried attention acc
    attn_fixed = 2 * (dqkv + dq + E) * h * wbytes + 2 * b * nh * dkv * 4
    J, fblk = _pick_expert_blocks(ffn, h, fixed_bytes=attn_fixed,
                                  wbytes=wbytes, nbuf=NBUF)
    if shared:
        # DeepSeekMoE dense shared experts: Mosaic-pipelined column
        # blocks like the llama FFN, budgeted AFTER the expert buffers
        Js, fsblk = _pick_expert_blocks(
            fs, h, fixed_bytes=attn_fixed + NBUF * 3 * fblk * h * wbytes,
            wbytes=wbytes)
    else:
        Js, fsblk = 0, 0
    nsteps = nslots * J
    if not chunk:
        # KV chunk sized from the CACHE element size: candidates are
        # equal-BYTE chunks, so the int8 cache (cb=1) streams 256-token
        # chunks where bf16 streamed 128 — half the DMA turnarounds on
        # the same chunk bytes (the cache_wbytes accounting the plan
        # records). Capped by the scoped-VMEM limit next to the
        # attention weights + expert buffers.
        chunk = 128
        wfix = (2 * (dqkv + dq + E) * h * wbytes
                + NBUF * 3 * fblk * h * wbytes
                + (2 * 3 * fsblk * h * wbytes if shared else 0))
        scratch_fixed = (b * 8 * 2 * dkv * cb + b * 2 * dkv * 4
                         + 2 * b * nh * dkv * 4 + b * h * 10)
        order = (256, 128, 64, 32, 16, 8) if cb == 1 else \
            (128, 64, 32, 16, 8)
        for cand in order:
            if S % cand == 0 and (wfix + scratch_fixed + 6 * 2 ** 20
                                  + 2 * b * cand * 2 * dkv * cb
                                  <= _vmem_limit_bytes()):
                chunk = cand
                break
    ck = min(chunk, S)
    assert S % ck == 0, f"cache len {S} not a multiple of chunk {ck}"
    assert dkv % 128 == 0, f"nkv*hd={dkv} must be a lane multiple of 128"
    assert E % 8 == 0, f"num_experts {E} must be a multiple of 8"
    dtype = x.dtype
    scale = 1.0 / math.sqrt(hd)

    def kernel(*refs):
        (pos_ref, x_in_ref, ln1_ref, wqkv_ref, wo_ref, ln2_ref,
         gate_ref, weg_ref, weu_ref, wed_ref) = refs[:10]
        i = 10
        if shared:
            wsg_ref, wsu_ref, wsd_ref = refs[i:i + 3]
            i += 3
        if kvq:
            kvs_ref = refs[i]            # (1, 2*dkv) per-head cache scales
            i += 1
        kv_in = refs[i]
        x_out_ref, kv_ref = refs[i + 1], refs[i + 2]
        (x_s, xn_s, acc_s, q_s, kv32_s, kvblk_s, kvch_s,
         wsem, rsem, eid_s, egw_s, ewg_s, ewu_s, ewd_s, esem) = refs[i + 3:]
        del kv_in
        li = pl.program_id(0)
        t = pl.program_id(1)
        pos = pos_ref[0]

        def expert_copies(u, buf):
            """The three async copies streaming step-u's expert block."""
            s = u // J
            jj = u % J
            r = s // k
            c = s % k
            eid = eid_s[r, c]
            if J == 1:
                src_g = weg_ref.at[li, eid]
                src_u = weu_ref.at[li, eid]
                src_d = wed_ref.at[li, eid]
            else:
                src_g = weg_ref.at[li, eid, :, pl.ds(jj * fblk, fblk)]
                src_u = weu_ref.at[li, eid, :, pl.ds(jj * fblk, fblk)]
                src_d = wed_ref.at[li, eid, pl.ds(jj * fblk, fblk), :]
            return (
                pltpu.make_async_copy(src_g, ewg_s.at[buf], esem.at[buf, 0]),
                pltpu.make_async_copy(src_u, ewu_s.at[buf], esem.at[buf, 1]),
                pltpu.make_async_copy(src_d, ewd_s.at[buf], esem.at[buf, 2]),
            )

        @pl.when(t == 0)
        def attention_phase():
            @pl.when(li == 0)
            def _():
                x_s[...] = x_in_ref[...].astype(jnp.float32)
                # one-time zero of the block-diagonal q staging (layers
                # rewrite the same in-block lanes; off-block lanes stay 0)
                q_s[...] = jnp.zeros_like(q_s)

            blk = (pos // 8) * 8
            off = pos - blk
            rkb = pltpu.make_async_copy(
                kv_ref.at[li, :, pl.ds(blk, 8)], kvblk_s, wsem.at[0])

            @pl.when(li == 0)
            def _():
                rkb.start()

            xn = _rms(x_s[...], ln1_ref[...].reshape(h), eps)
            qkv = jnp.dot(xn, wqkv_ref[...],
                          preferred_element_type=jnp.float32)
            half = (lax.broadcasted_iota(jnp.int32, (1, hd), 1)
                    % (hd // 2)).astype(jnp.float32)
            inv_freq = jnp.exp(half * (-2.0 * math.log(rope_base) / hd))
            ang = pos.astype(jnp.float32) * inv_freq
            cos_b = jnp.cos(ang)
            sin_b = jnp.sin(ang)
            rope2 = lambda v: (v * cos_b + jnp.concatenate(
                [-v[:, hd // 2:], v[:, :hd // 2]], axis=-1) * sin_b)
            # q staged block-diagonally over kv-group lane blocks (see
            # _fused_decode_pallas): one dot_general per KV block for all
            # heads instead of nkv unrolled per-group products
            for n in range(nh):
                g = n // rep
                q_s[:, n, g * hd:(g + 1) * hd] = rope2(
                    qkv[:, n * hd:(n + 1) * hd]) * scale
            for g in range(nkv):
                kv32_s[:, g * hd:(g + 1) * hd] = rope2(
                    qkv[:, dq + g * hd:dq + (g + 1) * hd])
                kv32_s[:, dkv + g * hd:dkv + (g + 1) * hd] = \
                    qkv[:, dq + dkv + g * hd:dq + dkv + (g + 1) * hd]

            def chunk_copy(c, slot):
                return pltpu.make_async_copy(
                    kv_ref.at[li, :, pl.ds(c * ck, ck)],
                    kvch_s.at[slot], rsem.at[slot])

            # batched-head q; in int8-cache mode the k-half dequant
            # scales fold in here (one broadcast multiply — off-block
            # lanes are zero either way)
            if kvq:
                qbd = q_s[...] * kvs_ref[...][:, :dkv][None]
            else:
                qbd = q_s[...]

            def merge(carry, kvblk, idx, limit):
                m, l, acc = carry
                kf = kvblk[:, :, :dkv].astype(jnp.float32)
                vf = kvblk[:, :, dkv:].astype(jnp.float32)
                sc = lax.dot_general(
                    qbd, kf, (((2,), (2,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32)      # (b, nh, w)
                sc = jnp.where(idx < limit, sc, NEG_INF)
                m_new = jnp.maximum(m, jnp.max(sc, axis=-1))
                alpha = jnp.exp(m - m_new)
                pp = jnp.exp(sc - m_new[..., None])
                acc = acc * alpha[..., None] + lax.dot_general(
                    pp, vf, (((2,), (1,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32)      # (b, nh, dkv)
                return m_new, l * alpha + jnp.sum(pp, axis=-1), acc

            nc = (blk + ck - 1) // ck

            @pl.when((li == 0) & (nc > 0))
            def _():
                chunk_copy(0, 0).start()

            def body(c, carry):
                slot = lax.rem(c, 2)

                @pl.when(c + 1 < nc)
                def _():
                    chunk_copy(c + 1, lax.rem(c + 1, 2)).start()

                chunk_copy(c, slot).wait()
                idx = c * ck + lax.broadcasted_iota(
                    jnp.int32, (1, 1, ck), 2)
                return merge(carry, kvch_s[slot], idx, blk)

            carry = lax.fori_loop(0, nc, body, (
                jnp.full((b, nh), NEG_INF, jnp.float32),
                jnp.zeros((b, nh), jnp.float32),
                jnp.zeros((b, nh, dkv), jnp.float32)))

            rkb.wait()
            sel = lax.broadcasted_iota(jnp.int32, (1, 8, 1), 1) == off
            newtok = kv32_s[...]
            if kvq:         # quantize the append with the static scales
                newtok = jnp.clip(
                    jnp.round(newtok / kvs_ref[...]), -127.0, 127.0)
            kvblk_s[...] = jnp.where(
                sel, newtok[:, None, :],
                kvblk_s[...].astype(jnp.float32)).astype(kv_cache.dtype)
            wkb = pltpu.make_async_copy(
                kvblk_s, kv_ref.at[li, :, pl.ds(blk, 8)], wsem.at[0])
            wkb.start()
            bidx = blk + lax.broadcasted_iota(jnp.int32, (1, 1, 8), 2)
            ms, ls, accs = merge(carry, kvblk_s[...], bidx, pos + 1)

            norm = accs / ls[..., None]                     # (b, nh, dkv)
            if kvq:         # v-half dequant scales, applied once
                norm = norm * kvs_ref[...][:, dkv:][None]
            if rep == 1:
                bd = (lax.broadcasted_iota(jnp.int32, (1, nh, dkv), 2)
                      // hd == lax.broadcasted_iota(
                          jnp.int32, (1, nh, dkv), 1))
                attn = jnp.sum(jnp.where(bd, norm, 0.0), axis=1)
                oacc = jnp.dot(attn.astype(dtype), wo_ref[...],
                               preferred_element_type=jnp.float32)
            else:
                oacc = jnp.zeros((b, h), jnp.float32)
                for g in range(nkv):
                    ng = norm[:, g * rep:(g + 1) * rep,
                              g * hd:(g + 1) * hd]          # (b, rep, hd)
                    w3 = wo_ref[g * rep * hd:(g + 1) * rep * hd,
                                :].reshape(rep, hd, h)
                    part = lax.dot_general(
                        ng.astype(dtype), w3,
                        (((2,), (1,)), ((1,), (0,))),
                        preferred_element_type=jnp.float32)  # (rep, b, h)
                    oacc = oacc + jnp.sum(part, axis=0)
            xr = x_s[...] + oacc
            x_s[...] = xr
            xn2 = _rms(xr, ln2_ref[...].reshape(h), eps).astype(dtype)
            xn_s[...] = xn2

            # ---- router (fp32, matches nn.layers.moe topk_routing):
            # softmax over E, sequential argmax top-k (= lax.top_k's
            # lowest-index tie-breaking), renormalized weights. Ids land
            # in SMEM so the expert-weight DMAs can address them.
            logits = lax.dot_general(
                xn2.astype(jnp.float32), gate_ref[...].astype(jnp.float32),
                (((1,), (1,)), ((), ())))                   # (b, E)
            mx = jnp.max(logits, axis=-1, keepdims=True)
            ex = jnp.exp(logits - mx)
            probs = ex / jnp.sum(ex, axis=-1, keepdims=True)
            cur = probs
            vals = []
            eidx = lax.broadcasted_iota(jnp.int32, (b, E), 1)
            for c in range(k):
                v_c = jnp.max(cur, axis=-1)                 # (b,)
                a_c = jnp.argmax(cur, axis=-1).astype(jnp.int32)
                vals.append(v_c)
                for r in range(b):
                    eid_s[r, c] = a_c[r]
                cur = jnp.where(eidx == a_c[:, None], NEG_INF, cur)
            tot = vals[0]
            for c in range(1, k):
                tot = tot + vals[c]
            tot = jnp.maximum(tot, 1e-9)
            for c in range(k):
                egw_s[:, c] = vals[c] / tot
            acc_s[...] = jnp.zeros_like(acc_s)
            # prime the prefetch-two-ahead pipeline: steps 0 AND 1 go out
            # together, so slot 1's weights stream during the shared-FFN
            # phases and slot 0's matmul instead of waiting for slot 0 to
            # finish (the b=1 slot-turnaround bubble)
            for cp in expert_copies(0, 0):
                cp.start()
            if nsteps > 1:
                for cp in expert_copies(1, 1):
                    cp.start()

        @pl.when(t == 1)
        def prefetch_next_layer():
            blk = (pos // 8) * 8
            pltpu.make_async_copy(
                kvblk_s, kv_ref.at[li, :, pl.ds(blk, 8)],
                wsem.at[0]).wait()

            @pl.when(li + 1 < L)
            def _():
                pltpu.make_async_copy(
                    kv_ref.at[li + 1, :, pl.ds(blk, 8)], kvblk_s,
                    wsem.at[0]).start()

                @pl.when(blk > 0)
                def _():
                    pltpu.make_async_copy(
                        kv_ref.at[li + 1, :, pl.ds(0, ck)],
                        kvch_s.at[0], rsem.at[0]).start()

        if shared:
            # DeepSeekMoE shared experts: dense SwiGLU column blocks
            # (Mosaic-pipelined BlockSpecs, weight 1.0, ALL rows) — the
            # routed experts' slot-0 DMAs overlap these phases
            @pl.when((t > 0) & (t <= Js))
            def shared_phase():
                xn = xn_s[...]
                g = jnp.dot(xn, wsg_ref[...],
                            preferred_element_type=jnp.float32)
                u = jnp.dot(xn, wsu_ref[...],
                            preferred_element_type=jnp.float32)
                act = (jax.nn.silu(g) * u).astype(dtype)
                acc_s[...] += jnp.dot(act, wsd_ref[...],
                                      preferred_element_type=jnp.float32)

        @pl.when(t > Js)
        def ffn_phase():
            u = t - 1 - Js
            buf = lax.rem(u, NBUF)

            for cp in expert_copies(u, buf):
                cp.wait()

            # steps u+1's copies are already in flight (issued at step
            # u-1, or primed by the router phase); top up the pipeline
            # with step u+PF. Buffer (u+PF) % NBUF was last read at step
            # u-1 (NBUF = PF+1), which this sequential grid has finished.
            @pl.when(u + PF < nsteps)
            def _():
                for cp in expert_copies(u + PF, lax.rem(u + PF, NBUF)):
                    cp.start()

            s = u // J
            r = s // k
            c = s % k
            xn = xn_s[...]
            g = jnp.dot(xn, ewg_s[buf],
                        preferred_element_type=jnp.float32)
            uu = jnp.dot(xn, ewu_s[buf],
                         preferred_element_type=jnp.float32)
            act = (jax.nn.silu(g) * uu).astype(dtype)
            d = jnp.dot(act, ewd_s[buf],
                        preferred_element_type=jnp.float32)   # (b, h)
            # select row r's contribution weighted by its gate value —
            # all-rows matmul + mask avoids dynamic scratch indexing
            # (b ≤ capacity/k is small; decode is bandwidth-bound)
            rmask = lax.broadcasted_iota(jnp.int32, (b, k), 0) == r
            cmask = lax.broadcasted_iota(jnp.int32, (b, k), 1) == c
            wsel = jnp.sum(jnp.where(rmask & cmask, egw_s[...], 0.0))
            rowmask = lax.broadcasted_iota(jnp.int32, (b, 1), 0) == r
            acc_s[...] += jnp.where(rowmask, d * wsel, 0.0)

            @pl.when(t == Js + nsteps)
            def _():
                xr = x_s[...] + acc_s[...]
                x_s[...] = xr
                x_out_ref[...] = xr.astype(dtype)

    def sjm(ll, tt):
        # shared-FFN column block: phases 1..Js stream blocks 0..Js-1;
        # t==0 keeps the previous layer's last block (no refetch), expert
        # phases keep the last block resident
        return jnp.where(tt < 1, Js - 1, jnp.minimum(tt - 1, Js - 1))

    def sl(ll, tt):
        return lax.max(ll - (tt < 1), 0)

    grid = (L, 1 + Js + nsteps)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),                 # pos
            pl.BlockSpec((b, h), lambda l, t: (0, 0)),             # x
            pl.BlockSpec((None, 1, h), lambda l, t: (l, 0, 0)),    # ln1
            pl.BlockSpec((None, h, dqkv), lambda l, t: (l, 0, 0)),  # wqkv
            pl.BlockSpec((None, dq, h), lambda l, t: (l, 0, 0)),   # wo
            pl.BlockSpec((None, 1, h), lambda l, t: (l, 0, 0)),    # ln2
            pl.BlockSpec((None, E, h), lambda l, t: (l, 0, 0)),    # gate
            pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),      # weg
            pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),      # weu
            pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),      # wed
        ] + ([
            pl.BlockSpec((None, h, fsblk),
                         lambda l, t: (sl(l, t), 0, sjm(l, t))),    # wsg
            pl.BlockSpec((None, h, fsblk),
                         lambda l, t: (sl(l, t), 0, sjm(l, t))),    # wsu
            pl.BlockSpec((None, fsblk, h),
                         lambda l, t: (sl(l, t), sjm(l, t), 0)),    # wsd
        ] if shared else []) + ([
            pl.BlockSpec((None, 1, 2 * dkv), lambda l, t: (l, 0, 0)),  # kvs
        ] if kvq else []) + [
            pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),      # kv_cache
        ],
        out_specs=[
            pl.BlockSpec((b, h), lambda l, t: (0, 0)),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h), dtype),
            jax.ShapeDtypeStruct(kv_cache.shape, kv_cache.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((b, h), jnp.float32),          # x_s
            pltpu.VMEM((b, h), dtype),                # xn_s
            pltpu.VMEM((b, h), jnp.float32),          # acc_s
            pltpu.VMEM((b, nh, dkv), jnp.float32),    # q_s (block-diag)
            pltpu.VMEM((b, 2 * dkv), jnp.float32),    # kv32_s
            pltpu.VMEM((b, 8, 2 * dkv), kv_cache.dtype),   # kvblk_s
            pltpu.VMEM((2, b, ck, 2 * dkv), kv_cache.dtype),  # kvch_s
            pltpu.SemaphoreType.DMA((1,)),            # wsem
            pltpu.SemaphoreType.DMA((2,)),            # rsem
            pltpu.SMEM((b, k), jnp.int32),            # eid_s
            pltpu.VMEM((b, k), jnp.float32),          # egw_s
            pltpu.VMEM((NBUF, h, fblk), dtype),       # ewg_s
            pltpu.VMEM((NBUF, h, fblk), dtype),       # ewu_s
            pltpu.VMEM((NBUF, fblk, h), dtype),       # ewd_s
            pltpu.SemaphoreType.DMA((NBUF, 3)),       # esem
        ],
        input_output_aliases={10 + 3 * shared + kvq: 1},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
            vmem_limit_bytes=_vmem_limit_bytes()),
        name="fused_decode_moe_step",
        interpret=interpret,
    )(jnp.asarray(pos, jnp.int32).reshape(1), x,
      params["ln1"][:, None], params["wqkv"], params["wo"],
      params["ln2"][:, None], params["gate"],
      params["weg"], params["weu"], params["wed"],
      *((params["wsg"], params["wsu"], params["wsd"]) if shared else ()),
      *((jnp.asarray(kv_scales, jnp.float32),) if kvq else ()),
      kv_cache)
    return out[0], out[1]


_fallback_logged = False


def fused_decode_step(x, params, kv_cache, pos, cos, sin, *,
                      num_heads: int, num_kv_heads: int, eps: float = 1e-5,
                      rope_base: float = 10000.0, arch: str = "llama",
                      top_k: int = 2, blocks: Optional[Dict] = None,
                      kv_scales=None, kv_chunk: int = 0):
    """Dispatch: Pallas whole-stack kernel on TPU, jnp reference elsewhere.

    Args follow fused_decode_reference (combined flat KV cache). `pos` may
    be traced (it is the scan counter inside `inference.generate`).
    `top_k` applies to arch="moe" only. `blocks` is a `decode_block_plan`
    dict (the plan that padded the params must also drive the kernel; for
    arch="moe" only its `cache_wbytes` is consumed — consistency-checked
    against the cache dtype). `kv_scales` enables the int8 KV-cache mode
    (all three archs; see quantize_kv_cache). `kv_chunk` overrides the
    kernel's KV-chunk sizing (0 = let the kernel pick) — the OOM
    degradation ladder in `inference.generate` retries with a halved
    chunk, shrinking the double-buffered VMEM chunk scratch; the jnp
    reference path ignores it (no chunking to size).

    FLAGS_pallas_interpret=1 routes the Pallas kernel through interpret
    mode off-TPU — the CPU-CI path for kernel-logic parity tests.
    """
    from paddle_tpu.core.flags import flag
    from paddle_tpu.ops import use_pallas
    dkv = kv_cache.shape[-1] // 2
    # tpu-lint: allow(host-sync): flag() is a host-side config read
    interp = bool(flag("FLAGS_pallas_interpret")) and not use_pallas()
    if (use_pallas() or interp) and dkv % 128 == 0 \
            and kv_cache.shape[2] % 128 == 0:
        # plan/cache consistency is a CONTRACT error, not a hardware
        # failure: check it before the fallback try so a stale plan can't
        # silently demote every kernel-eligible step to the jnp reference
        # path. (The reference path itself ignores `blocks` — an f32
        # cache on a non-kernel backend stays valid.)
        cb = jnp.dtype(kv_cache.dtype).itemsize
        if blocks is not None and blocks.get("cache_wbytes", cb) != cb:
            raise ValueError(
                f"decode plan assumed a {blocks['cache_wbytes']}-byte KV "
                f"cache but the cache dtype is {kv_cache.dtype} ({cb} B); "
                f"rebuild the plan with decode_block_plan(cache_wbytes="
                f"{cb})")
        try:
            # named scopes mark the kernel phase boundary in xplane
            # captures (trace-time only — no runtime cost)
            if arch == "moe":
                with jax.named_scope("fused_decode.kernel_moe"):
                    return _fused_decode_moe_pallas(
                        x, params, kv_cache, pos,
                        num_heads=num_heads, num_kv_heads=num_kv_heads,
                        head_dim=dkv // num_kv_heads, top_k=top_k,
                        rope_base=rope_base, eps=eps, chunk=kv_chunk,
                        blocks=blocks, kv_scales=kv_scales,
                        interpret=interp)
            with jax.named_scope("fused_decode.kernel"):
                return _fused_decode_pallas(
                    x, params, kv_cache, pos,
                    num_heads=num_heads, num_kv_heads=num_kv_heads,
                    head_dim=dkv // num_kv_heads,
                    rope_base=rope_base, eps=eps, chunk=kv_chunk,
                    arch=arch, blocks=blocks,
                    kv_scales=kv_scales, interpret=interp)
        except Exception as e:  # pragma: no cover - hardware-dependent
            if flag("FLAGS_pallas_strict"):
                raise
            global _fallback_logged
            if not _fallback_logged:
                _fallback_logged = True
                import logging
                logging.getLogger("paddle_tpu.ops.fused_decode").warning(
                    "Pallas fused decode failed (%s: %s); using the jnp "
                    "reference path. FLAGS_pallas_strict=1 to raise.",
                    type(e).__name__, e)
    with jax.named_scope("fused_decode.reference"):
        return fused_decode_reference(
            x, params, kv_cache, pos, cos, sin,
            num_heads=num_heads, num_kv_heads=num_kv_heads, eps=eps,
            arch=arch, top_k=top_k, kv_scales=kv_scales)


# ---------------------------------------------------------------------------
# Paged decode (continuous-batching serving): block-table KV pool
# ---------------------------------------------------------------------------
#
# The contiguous (L, b, S, 2*nkv*hd) cache above sizes every slot for
# prompt+max_new — a request that finishes early strands its tail, and a
# batch pads every slot to the longest member. The serving engine
# (paddle_tpu.serving) instead carves the cache into fixed-size KV BLOCKS
# shared by all slots (the vLLM paged-KV layout on the fused kernel):
#
#   kv_pool       (L, num_blocks, block_tokens, 2*nkv*hd)   HBM, aliased
#   block_tables  (b, max_blocks) int32   slot-local chunk c -> physical
#                                         block (layer-invariant: block n
#                                         holds the same token span in
#                                         every layer's pool plane)
#   positions     (b,) int32              per-slot append position
#
# One block == one KV chunk of the kernel's online-softmax walk, so the
# chunk copy indexes through the block table (the same SMEM-addressed DMA
# technique the MoE kernel uses for routed expert weights) and slots of
# wildly different lengths share one dispatch: per-row chunk counts only
# mask (an all-masked online-softmax merge is an exact no-op).


def paged_pool_shape(num_layers: int, num_blocks: int, block_tokens: int,
                     num_kv_heads: int, head_dim: int):
    """Shape of the paged KV pool (the serving engine's one cache tensor)."""
    return (num_layers, num_blocks, block_tokens,
            2 * num_kv_heads * head_dim)


# ---------------------------------------------------------------------------
# Tensor-parallel (mp) shard layouts for the paged serving path
# ---------------------------------------------------------------------------
#
# The serving engine shards ONE replica over the `mp` mesh axis by
# splitting attention heads (KV groups) and ffn columns across shards —
# column-parallel qkv/gate/up, with the o-proj and down-proj matmuls
# kept FULL on every shard behind one `all_gather` each. That flavor
# (gather the (b, cols) activation instead of psum-ing the (b, h)
# partial outputs) is what makes the sharded engine BIT-IDENTICAL to
# the single-chip engine: an all_gather is pure data movement, so the
# wo/wd matmuls see exactly the mp=1 operand and reduce in exactly the
# mp=1 order, while a psum would re-associate the h-dim reduction.
#
# Shard-major column permutations: the fused canonical layouts
# interleave regions ([q|k|v] for wqkv, [k|v] for the pool's last dim),
# so a plain contiguous split of the canonical columns would hand each
# shard a slice CROSSING region boundaries. The device twins are
# permuted SHARD-MAJOR instead — shard s's slice is itself a valid
# canonical layout at the local head counts — while host mirrors stay
# canonical (snapshots and parity pins never see the permutation).
# Because the reference q-head order is group-major (q.reshape(b, nkv,
# rep, hd)), sharding KV groups contiguously gives each shard a
# contiguous q-head range, so the tiled all_gather below reproduces the
# exact reference (b, dq) column order.

def mp_qkv_permutation(num_heads: int, num_kv_heads: int, head_dim: int,
                       mp: int):
    """Column permutation (len (nh+2*nkv)*hd, numpy int32) taking the
    canonical fused ``[q|k|v]`` wqkv/bqkv column layout to shard-major:
    ``w[:, perm]`` puts shard s's columns at ``[s*csz, (s+1)*csz)`` as
    ``[q_s|k_s|v_s]`` — exactly the canonical fused layout at the local
    head counts ``nh/mp``/``nkv/mp``. Requires mp | num_kv_heads (and
    mp | num_heads via the GQA rep structure)."""
    nh, nkv, hd = int(num_heads), int(num_kv_heads), int(head_dim)
    if nkv % mp or nh % mp:
        raise ValueError(
            f"mp={mp} must divide num_heads={nh} and num_kv_heads={nkv}")
    dq, dkv = nh * hd, nkv * hd
    q = np.arange(dq, dtype=np.int32).reshape(mp, dq // mp)
    k = dq + np.arange(dkv, dtype=np.int32).reshape(mp, dkv // mp)
    v = dq + dkv + np.arange(dkv, dtype=np.int32).reshape(mp, dkv // mp)
    return np.concatenate([np.concatenate([q[s], k[s], v[s]])
                           for s in range(mp)]).astype(np.int32)


def mp_kv_permutation(num_kv_heads: int, head_dim: int, mp: int):
    """Column permutation (len 2*nkv*hd) taking the pool/scale
    canonical ``[k|v]`` last-dim layout to shard-major
    ``[k_0|v_0|k_1|v_1|...]`` so a plain contiguous mp-split hands
    shard s the canonical ``[k_s|v_s]`` local layout."""
    nkv, hd = int(num_kv_heads), int(head_dim)
    if nkv % mp:
        raise ValueError(f"mp={mp} must divide num_kv_heads={nkv}")
    dkv = nkv * hd
    k = np.arange(dkv, dtype=np.int32).reshape(mp, dkv // mp)
    v = dkv + np.arange(dkv, dtype=np.int32).reshape(mp, dkv // mp)
    return np.concatenate([np.concatenate([k[s], v[s]])
                           for s in range(mp)]).astype(np.int32)


def mp_gather_kv_lastdim(x, mp_axis: str):
    """Inside a shard_map body: all-gather a LOCAL canonical ``[k|v]``
    last dim (2*nkv_loc*hd) back to the FULL canonical ``[k|v]`` layout
    (2*nkv*hd). Pure layout movement — bitwise, no arithmetic."""
    g = jax.lax.all_gather(x, mp_axis, axis=x.ndim - 1, tiled=True)
    m = jax.lax.axis_size(mp_axis)
    loc = g.shape[-1] // (2 * m)
    # tiled gather is shard-major [k0|v0|k1|v1|...]; swap to [k|v]
    parts = g.reshape(g.shape[:-1] + (m, 2, loc))
    return jnp.swapaxes(parts, -3, -2).reshape(g.shape)


def mp_local_kv_lastdim(x, mp_axis: str):
    """Inside a shard_map body: slice this shard's canonical
    ``[k_s|v_s]`` columns out of a FULL canonical ``[k|v]`` last dim —
    the inverse of :func:`mp_gather_kv_lastdim` (replicated-compute
    producers like the chunk forward hand the pool scatter its local
    columns through this)."""
    r = jax.lax.axis_index(mp_axis)
    m = jax.lax.axis_size(mp_axis)
    dkv = x.shape[-1] // 2
    loc = dkv // m
    ax = x.ndim - 1
    k = jax.lax.dynamic_slice_in_dim(x, r * loc, loc, axis=ax)
    v = jax.lax.dynamic_slice_in_dim(x, dkv + r * loc, loc, axis=ax)
    return jnp.concatenate([k, v], axis=-1)


def _mp_gather_cols(act, mp_axis: str):
    """all-gather a column-parallel (b, cols_loc) activation to the full
    (b, cols) operand — shard-contiguous column order, which IS the
    reference order for both the attention output (contiguous q-head
    ranges per shard) and the ffn activation (contiguous column split).
    """
    return jax.lax.all_gather(act, mp_axis, axis=1, tiled=True)


def fused_paged_decode_reference(x, params, kv_pool, block_tables, positions,
                                 cos, sin, *, num_heads: int,
                                 num_kv_heads: int, eps: float = 1e-5,
                                 arch: str = "llama", kv_scales=None,
                                 mp_axis: Optional[str] = None):
    """One decode step against a paged KV pool; pure jnp twin.

    x (b, h); kv_pool (L, NB, BT, 2*nkv*hd); block_tables (b, MB) int32;
    positions (b,) int32 (each slot's append position — the number of
    tokens already cached for that slot); cos/sin (b, hd) fp32 rope rows
    gathered at each slot's position. Returns (x_out (b, h), kv_pool).

    int8 pool mode: kv_scales (L, b, 2*nkv*hd) fp32 — per-SLOT scales
    (serving calibrates each request from its own prefill, unlike the
    batch-shared scales of `fused_decode_reference`).

    The arithmetic is kept line-for-line with `fused_decode_reference`
    (same einsums, same masking, same cast points) so a slot's step is
    bit-identical to the same tokens decoding through a contiguous cache
    — the continuous-batching parity contract (tests/test_serving.py).
    Slots whose block-table tail is unallocated must point spare entries
    at a valid (scratch) block: the copies are masked, not skipped.

    Tensor-parallel mode (``mp_axis`` set, inside a full-manual
    shard_map body): the caller passes the LOCAL head counts, the local
    shard-major wqkv/wg/wu (+ scale/bias) columns and the local pool /
    kv_scales last dim; the per-head attention math above runs
    unchanged over the local heads, and the two column-parallel
    activations (attention output, ffn activation) are all-gathered
    back to full width before the FULL wo/wd matmuls — one collective
    per site, bitwise identical to the mp=1 step (no psum
    re-association). x stays replicated (b, full h) throughout.
    """
    L, NB, BT, dkv2 = kv_pool.shape
    b, MB = block_tables.shape
    S = MB * BT
    dkv = dkv2 // 2
    nh = num_heads
    nkv = num_kv_heads
    hd = dkv // nkv
    rep = nh // nkv
    dq = nh * hd
    dtype = x.dtype
    scale = 1.0 / math.sqrt(hd)
    int8 = "wqkv_s" in params
    gpt = arch == "gpt"
    if arch not in ("llama", "gpt"):
        raise NotImplementedError(
            f"paged decode supports arch llama/gpt, got {arch!r}")
    cos_b = cos.reshape(b, 1, hd).astype(jnp.float32)
    sin_b = sin.reshape(b, 1, hd).astype(jnp.float32)
    rows = jnp.arange(b)
    app_bid = jnp.take_along_axis(
        block_tables, (positions // BT)[:, None], axis=1)[:, 0]   # (b,)
    app_off = positions % BT
    kv_news = []    # per-layer appended rows, written back in ONE scatter

    def wdot(act, key, l):
        w = params[key][l]
        if int8:
            y = jnp.dot(act, w.astype(act.dtype),
                        preferred_element_type=jnp.float32)
            return y * params[f"{key}_s"][l]
        return jnp.dot(act, w, preferred_element_type=jnp.float32)

    xf = x.astype(jnp.float32)
    for l in range(L):
        if gpt:
            xn = _layernorm(xf, params["ln1"][l], params["ln1_b"][l], eps)
        else:
            xn = _rms(xf, params["ln1"][l], eps)
        qkv = wdot(xn, "wqkv", l)
        if gpt:
            qkv = qkv + params["bqkv"][l]
        q = qkv[:, :dq].reshape(b, nh, hd)
        k = qkv[:, dq:dq + nkv * hd].reshape(b, nkv, hd)
        v = qkv[:, dq + nkv * hd:].reshape(b, nkv, hd)
        if not gpt:
            q = _rope1(q, cos_b, sin_b)
            k = _rope1(k, cos_b, sin_b)
        kv_new = jnp.concatenate(
            [k.reshape(b, dkv), v.reshape(b, dkv)], axis=-1)
        if kv_scales is not None:     # int8 pool: per-slot static scales
            kv_new = jnp.clip(
                jnp.round(kv_new.astype(jnp.float32) / kv_scales[l]),
                -127, 127)
        kv_new = kv_new.astype(kv_pool.dtype)
        kv_news.append(kv_new)
        # gather the slot's logical cache view [0, S) for attention
        # (spare table entries gather a scratch block — masked below)
        # and inject this step's append into the GATHERED view; the pool
        # itself is written once after the layer walk. A per-layer
        # `kv_pool.at[l, ...].set` costs a full pool copy per LAYER on
        # backends without in-place scatter (jax-0.4 CPU ignores
        # donation: measured 211 -> ~55 ms per b=8 step); the values the
        # attention sees are identical either way, because each row's
        # append block is private (copy-on-write invariant) and the
        # injected entry is exactly what the scatter would have stored.
        kvl = kv_pool[l][block_tables].reshape(b, S, dkv2)
        kvl = kvl.at[rows, positions].set(kv_new)
        kl = kvl[:, :, :dkv].astype(jnp.float32)
        vl = kvl[:, :, dkv:].astype(jnp.float32)
        if kv_scales is not None:     # dequantize with per-slot scales
            kl = kl * kv_scales[l][:, None, :dkv]
            vl = vl * kv_scales[l][:, None, dkv:]
        kl = kl.reshape(b, S, nkv, hd)
        vl = vl.reshape(b, S, nkv, hd)
        qg = q.reshape(b, nkv, rep, hd) * scale
        scores = jnp.einsum("bgrd,bsgd->bgrs", qg, kl)
        valid = (jnp.arange(S)[None, None, None]
                 <= positions[:, None, None, None])
        scores = jnp.where(valid, scores, NEG_INF)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bgrs,bsgd->bgrd", probs, vl)
        attn = attn.reshape(b, dq).astype(dtype)
        if mp_axis is not None:
            attn = _mp_gather_cols(attn, mp_axis)
        o = wdot(attn, "wo", l)
        if gpt:
            o = o + params["bo"][l]
        xf = xf + o
        if gpt:
            xn2 = _layernorm(xf, params["ln2"][l], params["ln2_b"][l], eps)
            g = wdot(xn2, "wg", l) + params["bg"][l]
            act = jax.nn.gelu(g, approximate=True).astype(dtype)
            if mp_axis is not None:
                act = _mp_gather_cols(act, mp_axis)
            xf = xf + wdot(act, "wd", l) + params["bd"][l]
        else:
            xn2 = _rms(xf, params["ln2"][l], eps)
            g = wdot(xn2, "wg", l)
            u = wdot(xn2, "wu", l)
            act = (jax.nn.silu(g) * u).astype(dtype)
            if mp_axis is not None:
                act = _mp_gather_cols(act, mp_axis)
            xf = xf + wdot(act, "wd", l)
    # ONE combined append for all layers (indices collide for no two
    # rows: append blocks are never shared)
    kv_pool = kv_pool.at[:, app_bid, app_off].set(jnp.stack(kv_news))
    return xf.astype(dtype), kv_pool


def _fused_paged_decode_pallas(x, params, kv_pool, block_tables, positions,
                               *, num_heads: int, num_kv_heads: int,
                               head_dim: int, rope_base: float = 10000.0,
                               eps: float = 1e-5, arch: str = "llama",
                               blocks: Optional[Dict] = None,
                               kv_scales=None, interpret: bool = False):
    """Paged-pool variant of `_fused_decode_pallas` (llama/gpt, no q-split).

    Differences from the contiguous kernel:

    * the KV cache is the (L, NB, BT, 2*nkv*hd) pool; every chunk copy /
      RMW append resolves its physical block through the SMEM block table
      (`bt_ref[r, c]` — the data-dependent DMA addressing the MoE kernel
      pioneered for routed expert weights), so the copies are per-ROW
      (b DMAs per chunk instead of 1) — serving batches are small and
      decode is bandwidth-bound, so the extra descriptors are noise;
    * `positions` is per-row: rope angles, the append RMW offset and the
      online-softmax limits all broadcast (b, 1, 1) instead of scalar.
      Rows past their own prefix mask every lane of a merge — an exact
      no-op — so one dispatch serves slots of different lengths;
    * int8 pool scales are per-SLOT ((L, b, 2*nkv*hd)).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    L, NB, BT, dkv2 = kv_pool.shape
    b, MB = block_tables.shape
    dkv = dkv2 // 2
    nh = num_heads
    nkv = num_kv_heads
    hd = head_dim
    assert hd == dkv // nkv
    rep = nh // nkv
    h = x.shape[1]
    dq = nh * hd
    dqkv = dq + 2 * dkv
    ffn = params["wg"].shape[2]
    int8 = "wqkv_s" in params
    kvq = kv_scales is not None
    assert kvq == (jnp.dtype(kv_pool.dtype) == jnp.int8), \
        "int8 KV pool needs kv_scales (and vice versa)"
    gpt = arch == "gpt"
    wbytes = 1 if int8 else 2
    cb = jnp.dtype(kv_pool.dtype).itemsize
    ck = BT                 # one block == one KV chunk of the walk
    assert BT % 8 == 0, f"block_tokens {BT} must be a multiple of 8"
    assert dkv % 128 == 0, f"nkv*hd={dkv} must be a lane multiple of 128"
    if blocks is not None:
        assert blocks.get("cache_wbytes", cb) == cb, \
            (f"decode plan assumed a {blocks['cache_wbytes']}-byte KV "
             f"cache but the pool dtype is {kv_pool.dtype} ({cb} B)")
        if blocks.get("q_split", 1) != 1:
            raise ValueError(
                "paged decode does not support the q-split (big-model) "
                "regime yet; build the plan with q_split=1")
        J, fblk = blocks["ffn_blocks"], blocks["fblk"]
        assert ffn == J * fblk, (ffn, blocks)
    else:
        J, fblk = _pick_ffn_blocks(
            ffn, h, fixed_bytes=(dqkv + dq) * h * wbytes, wbytes=wbytes)
    dtype = x.dtype
    scale = 1.0 / math.sqrt(hd)

    def kernel(*refs):
        if gpt:
            (pos_ref, bt_ref, posv_ref, x_in_ref, ln1_ref, wqkv_ref,
             wo_ref, ln2_ref, wg_ref, wd_ref) = refs[:10]
            wu_ref = None
            i = 10
            (ln1b_ref, ln2b_ref, bqkv_ref, bo_ref, bg_ref,
             bd_ref) = refs[i:i + 6]
            i += 6
        else:
            (pos_ref, bt_ref, posv_ref, x_in_ref, ln1_ref, wqkv_ref,
             wo_ref, ln2_ref, wg_ref, wu_ref, wd_ref) = refs[:11]
            i = 11
        if int8:
            sqkv_ref, so_ref, sg_ref, su_ref, sd_ref = refs[i:i + 5]
            i += 5
        if kvq:
            kvs_ref = refs[i]          # (b, 2*dkv) per-SLOT cache scales
            i += 1
        kv_in = refs[i]                # aliased with kv_ref
        x_out_ref, kv_ref = refs[i + 1], refs[i + 2]
        (x_s, xn_s, acc_s, q_s, kv32_s, kvblk_s, kvch_s,
         wsem, rsem) = refs[i + 3:]
        del kv_in

        def wdot(act, wref, sref):
            w = wref[...]
            if int8:
                y = jnp.dot(act, w.astype(act.dtype),
                            preferred_element_type=jnp.float32)
                return y if sref is None else y * sref[...]
            return jnp.dot(act, w, preferred_element_type=jnp.float32)

        li = pl.program_id(0)
        j = pl.program_id(1)

        # ---- per-row paged DMA descriptors (block table in SMEM) ----
        def rmw_read(l, r):
            p = pos_ref[r]
            bid = bt_ref[r, p // BT]
            return pltpu.make_async_copy(
                kv_ref.at[l, bid, pl.ds((p % BT) // 8 * 8, 8)],
                kvblk_s.at[r], wsem.at[r])

        def rmw_write(l, r):
            p = pos_ref[r]
            bid = bt_ref[r, p // BT]
            return pltpu.make_async_copy(
                kvblk_s.at[r],
                kv_ref.at[l, bid, pl.ds((p % BT) // 8 * 8, 8)],
                wsem.at[r])

        def chunk_copy(l, c, slot, r):
            return pltpu.make_async_copy(
                kv_ref.at[l, bt_ref[r, c]], kvch_s.at[slot, r],
                rsem.at[slot, r])

        # chunk walk bound: the LONGEST row's full-8-block prefix (rows
        # past their own prefix contribute all-masked merges — exact
        # no-ops, the price of one shared dispatch)
        nc = (pos_ref[0] // 8 * 8 + ck - 1) // ck
        for r in range(1, b):
            nc = jnp.maximum(nc, (pos_ref[r] // 8 * 8 + ck - 1) // ck)

        @pl.when(j == 0)
        def attention_phase():
            posv = posv_ref[...]                       # (b, 1) int32
            blk_v = posv // 8 * 8
            blk3 = blk_v.reshape(b, 1, 1)

            @pl.when(li == 0)
            def _():
                x_s[...] = x_in_ref[...].astype(jnp.float32)
                # one-time zero of the block-diagonal q staging (layers
                # rewrite the same in-block lanes; off-block lanes stay 0)
                q_s[...] = jnp.zeros_like(q_s)
                for r in range(b):
                    rmw_read(li, r).start()

                @pl.when(nc > 0)
                def _():
                    for r in range(b):
                        chunk_copy(li, 0, 0, r).start()

            if gpt:
                xn = _layernorm(x_s[...], ln1_ref[...].reshape(h),
                                ln1b_ref[...].reshape(h), eps)
            else:
                xn = _rms(x_s[...], ln1_ref[...].reshape(h), eps)
            qkv = wdot(xn, wqkv_ref, sqkv_ref if int8 else None)
            if gpt:
                qkv = qkv + bqkv_ref[...]
                rope2 = lambda t: t
            else:
                # per-row rope angles from the per-row positions
                half = (lax.broadcasted_iota(jnp.int32, (1, hd), 1)
                        % (hd // 2)).astype(jnp.float32)
                inv_freq = jnp.exp(half * (-2.0 * math.log(rope_base) / hd))
                ang = posv.astype(jnp.float32) * inv_freq      # (b, hd)
                cos_b = jnp.cos(ang)
                sin_b = jnp.sin(ang)
                rope2 = lambda t: (t * cos_b + jnp.concatenate(
                    [-t[:, hd // 2:], t[:, :hd // 2]], axis=-1) * sin_b)
            # q staged block-diagonally over kv-group lane blocks (see
            # _fused_decode_pallas); new k/v staged flat for the RMW merge
            for n in range(nh):
                g = n // rep
                q_s[:, n, g * hd:(g + 1) * hd] = rope2(
                    qkv[:, n * hd:(n + 1) * hd]) * scale
            for g in range(nkv):
                kv32_s[:, g * hd:(g + 1) * hd] = rope2(
                    qkv[:, dq + g * hd:dq + (g + 1) * hd])
                kv32_s[:, dkv + g * hd:dkv + (g + 1) * hd] = \
                    qkv[:, dq + dkv + g * hd:dq + dkv + (g + 1) * hd]

            if kvq:     # per-slot k-half dequant scales fold into q rows
                qbd = q_s[...] * kvs_ref[...][:, None, :dkv]
            else:
                qbd = q_s[...]

            def merge(carry, kvblk, idx, limit):
                """Online-softmax block update over ALL heads; `limit` is
                per-row (b, 1, 1) — an all-masked row is an exact no-op
                (alpha = 1, pp = 0), which is what lets one dispatch
                serve slots of different lengths."""
                m, l, acc = carry
                kf = kvblk[:, :, :dkv].astype(jnp.float32)
                vf = kvblk[:, :, dkv:].astype(jnp.float32)
                sc = lax.dot_general(
                    qbd, kf, (((2,), (2,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32)      # (b, nh, w)
                sc = jnp.where(idx < limit, sc, NEG_INF)
                m_new = jnp.maximum(m, jnp.max(sc, axis=-1))
                alpha = jnp.exp(m - m_new)
                pp = jnp.exp(sc - m_new[..., None])
                acc = acc * alpha[..., None] + lax.dot_general(
                    pp, vf, (((2,), (1,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32)      # (b, nh, dkv)
                return m_new, l * alpha + jnp.sum(pp, axis=-1), acc

            def body(c, carry):
                slot = lax.rem(c, 2)

                @pl.when(c + 1 < nc)
                def _():
                    for r in range(b):
                        chunk_copy(li, c + 1, lax.rem(c + 1, 2), r).start()

                for r in range(b):
                    chunk_copy(li, c, slot, r).wait()
                idx = c * ck + lax.broadcasted_iota(
                    jnp.int32, (1, 1, ck), 2)
                return merge(carry, kvch_s[slot], idx, blk3)

            carry = lax.fori_loop(0, nc, body, (
                jnp.full((b, nh), NEG_INF, jnp.float32),
                jnp.zeros((b, nh), jnp.float32),
                jnp.zeros((b, nh, dkv), jnp.float32)))

            # merge each row's new token into its RMW block, attend to it
            # from VMEM, write the block back (waited in FFN j==1)
            for r in range(b):
                rmw_read(li, r).wait()
            off3 = (posv - blk_v).reshape(b, 1, 1)
            sel = lax.broadcasted_iota(jnp.int32, (1, 8, 1), 1) == off3
            newtok = kv32_s[...]
            if kvq:     # quantize the append with the per-slot scales
                newtok = jnp.clip(
                    jnp.round(newtok / kvs_ref[...]), -127.0, 127.0)
            kvblk_s[...] = jnp.where(
                sel, newtok[:, None, :],
                kvblk_s[...].astype(jnp.float32)).astype(kv_pool.dtype)
            for r in range(b):
                rmw_write(li, r).start()
            bidx = blk3 + lax.broadcasted_iota(jnp.int32, (1, 1, 8), 2)
            ms, ls, accs = merge(carry, kvblk_s[...], bidx,
                                 posv.reshape(b, 1, 1) + 1)

            norm = accs / ls[..., None]                     # (b, nh, dkv)
            if kvq:     # per-slot v-half dequant scales, applied once
                norm = norm * kvs_ref[...][:, None, dkv:]
            if rep == 1:
                bd = (lax.broadcasted_iota(jnp.int32, (1, nh, dkv), 2)
                      // hd == lax.broadcasted_iota(
                          jnp.int32, (1, nh, dkv), 1))
                attn = jnp.sum(jnp.where(bd, norm, 0.0), axis=1)  # (b, dq)
                oacc = wdot(attn.astype(dtype), wo_ref,
                            so_ref if int8 else None)
            else:
                oacc = jnp.zeros((b, h), jnp.float32)
                for g in range(nkv):
                    ng = norm[:, g * rep:(g + 1) * rep,
                              g * hd:(g + 1) * hd]          # (b, rep, hd)
                    w3 = wo_ref[g * rep * hd:(g + 1) * rep * hd,
                                :].reshape(rep, hd, h)
                    part = lax.dot_general(
                        ng.astype(dtype),
                        w3.astype(dtype) if int8 else w3,
                        (((2,), (1,)), ((1,), (0,))),
                        preferred_element_type=jnp.float32)  # (rep, b, h)
                    oacc = oacc + jnp.sum(part, axis=0)
                if int8:
                    oacc = oacc * so_ref[...]
            if gpt:
                oacc = oacc + bo_ref[...]
            xr = x_s[...] + oacc
            x_s[...] = xr
            if gpt:
                xn_s[...] = _layernorm(xr, ln2_ref[...].reshape(h),
                                       ln2b_ref[...].reshape(h),
                                       eps).astype(dtype)
            else:
                xn_s[...] = _rms(xr, ln2_ref[...].reshape(h),
                                 eps).astype(dtype)
            acc_s[...] = jnp.zeros_like(acc_s)

        @pl.when(j >= 1)
        def ffn_phase():
            @pl.when(j == 1)
            def prefetch_next_layer():
                # drain this layer's per-row write-backs, then issue the
                # next layer's RMW + chunk-0 reads
                for r in range(b):
                    rmw_write(li, r).wait()

                @pl.when(li + 1 < L)
                def _():
                    for r in range(b):
                        rmw_read(li + 1, r).start()

                    @pl.when(nc > 0)
                    def _():
                        for r in range(b):
                            chunk_copy(li + 1, 0, 0, r).start()

            xn = xn_s[...]
            g = wdot(xn, wg_ref, sg_ref if int8 else None)
            if gpt:
                g = g + bg_ref[...]
                act = jax.nn.gelu(g, approximate=True).astype(dtype)
            else:
                u = wdot(xn, wu_ref, su_ref if int8 else None)
                act = (jax.nn.silu(g) * u).astype(dtype)
            acc_s[...] += wdot(act, wd_ref, sd_ref if int8 else None)

            if gpt:
                @pl.when(j == J)
                def _():
                    acc_s[...] += jnp.broadcast_to(bd_ref[...], acc_s.shape)

            @pl.when(j == J)
            def _():
                xr = x_s[...] + acc_s[...]
                x_s[...] = xr
                x_out_ref[...] = xr.astype(dtype)

    def jm(ll, jj):
        # FFN column block: phase j >= 1 streams block j-1; the attention
        # phase keeps the previous layer's last block (no refetch)
        return jnp.where(jj < 1, J - 1, jj - 1)

    def fl(ll, jj):
        return lax.max(ll - (jj < 1), 0)

    grid = (L, 1 + J)
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),                 # positions
        pl.BlockSpec(memory_space=pltpu.SMEM),                 # block table
        pl.BlockSpec((b, 1), lambda l, j: (0, 0)),             # posv
        pl.BlockSpec((b, h), lambda l, j: (0, 0)),             # x
        pl.BlockSpec((None, 1, h), lambda l, j: (l, 0, 0)),    # ln1
        pl.BlockSpec((None, h, dqkv), lambda l, j: (l, 0, 0)),  # wqkv
        pl.BlockSpec((None, dq, h), lambda l, j: (l, 0, 0)),   # wo
        pl.BlockSpec((None, 1, h), lambda l, j: (l, 0, 0)),    # ln2
        pl.BlockSpec((None, h, fblk),
                     lambda l, j: (fl(l, j), 0, jm(l, j))),     # wg
    ] + ([] if gpt else [
        pl.BlockSpec((None, h, fblk),
                     lambda l, j: (fl(l, j), 0, jm(l, j))),     # wu
    ]) + [
        pl.BlockSpec((None, fblk, h),
                     lambda l, j: (fl(l, j), jm(l, j), 0)),     # wd
    ] + ([
        pl.BlockSpec((None, 1, h), lambda l, j: (l, 0, 0)),     # ln1_b
        pl.BlockSpec((None, 1, h), lambda l, j: (l, 0, 0)),     # ln2_b
        pl.BlockSpec((None, 1, dqkv), lambda l, j: (l, 0, 0)),  # bqkv
        pl.BlockSpec((None, 1, h), lambda l, j: (l, 0, 0)),     # bo
        pl.BlockSpec((None, 1, fblk),
                     lambda l, j: (fl(l, j), 0, jm(l, j))),     # bg
        pl.BlockSpec((None, 1, h), lambda l, j: (l, 0, 0)),     # bd
    ] if gpt else []) + ([
        pl.BlockSpec((None, 1, dqkv), lambda l, j: (l, 0, 0)),  # sqkv
        pl.BlockSpec((None, 1, h), lambda l, j: (l, 0, 0)),     # so
        pl.BlockSpec((None, 1, fblk),
                     lambda l, j: (fl(l, j), 0, jm(l, j))),     # sg
        pl.BlockSpec((None, 1, fblk),
                     lambda l, j: (fl(l, j), 0, jm(l, j))),     # su
        pl.BlockSpec((None, 1, h), lambda l, j: (l, 0, 0)),     # sd
    ] if int8 else []) + ([
        pl.BlockSpec((None, b, 2 * dkv), lambda l, j: (l, 0, 0)),  # kvs
    ] if kvq else []) + [
        pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),      # kv pool
    ]
    operands = [
        jnp.asarray(positions, jnp.int32).reshape(b),
        jnp.asarray(block_tables, jnp.int32),
        jnp.asarray(positions, jnp.int32).reshape(b, 1),
        x,
        params["ln1"][:, None], params["wqkv"], params["wo"],
        params["ln2"][:, None], params["wg"],
        *(() if gpt else (params["wu"],)),
        params["wd"],
        *((params["ln1_b"][:, None], params["ln2_b"][:, None],
           params["bqkv"][:, None], params["bo"][:, None],
           params["bg"][:, None], params["bd"][:, None]) if gpt else ()),
        *((params["wqkv_s"], params["wo_s"], params["wg_s"],
           params["wu_s"], params["wd_s"]) if int8 else ()),
        *((jnp.asarray(kv_scales, jnp.float32),) if kvq else ()),
        kv_pool,
    ]
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((b, h), lambda l, j: (0, 0)),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h), dtype),
            jax.ShapeDtypeStruct(kv_pool.shape, kv_pool.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((b, h), jnp.float32),          # x_s
            pltpu.VMEM((b, h), dtype),                # xn_s
            pltpu.VMEM((b, h), jnp.float32),          # acc_s
            pltpu.VMEM((b, nh, dkv), jnp.float32),    # q_s (block-diag)
            pltpu.VMEM((b, 2 * dkv), jnp.float32),    # kv32_s staging
            pltpu.VMEM((b, 8, 2 * dkv), kv_pool.dtype),    # kvblk_s RMW
            pltpu.VMEM((2, b, ck, 2 * dkv), kv_pool.dtype),  # kvch_s dbuf
            pltpu.SemaphoreType.DMA((b,)),            # wsem (per row)
            pltpu.SemaphoreType.DMA((2, b)),          # rsem (slot, row)
        ],
        input_output_aliases={len(in_specs) - 1: 1},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
            vmem_limit_bytes=_vmem_limit_bytes()),
        name="fused_paged_decode_step",
        interpret=interpret,
    )(*operands)
    return out[0], out[1]


def fused_paged_decode_step(x, params, kv_pool, block_tables, positions,
                            cos, sin, *, num_heads: int, num_kv_heads: int,
                            eps: float = 1e-5, rope_base: float = 10000.0,
                            arch: str = "llama",
                            blocks: Optional[Dict] = None, kv_scales=None,
                            mp_axis: Optional[str] = None):
    """Dispatch one PAGED decode step: Pallas kernel on TPU (or under
    FLAGS_pallas_interpret), jnp paged reference elsewhere.

    Args follow `fused_paged_decode_reference` (block-table pool, per-row
    positions). cos/sin are the (b, hd) rope rows gathered at each slot's
    position — consumed by the reference path only (the kernel computes
    rope in-kernel from `positions`, like the contiguous kernel).
    `blocks` is a `decode_block_plan` dict; the paged kernel rejects
    q-split plans and consistency-checks `cache_wbytes` against the pool
    dtype. `kv_scales` (L, b, 2*nkv*hd) enables the per-slot int8 pool.
    ``mp_axis`` (inside a shard_map body, local heads/pool columns)
    routes the jnp reference unconditionally — the per-shard problem is
    1/mp of the single-chip one and the collective sits OUTSIDE the
    per-head math, so the XLA path shards cleanly today; teaching the
    Pallas kernel a local-shard mode is a later PR.
    """
    from paddle_tpu.core.flags import flag
    from paddle_tpu.ops import use_pallas
    if arch not in ("llama", "gpt"):
        raise NotImplementedError(
            f"paged decode supports arch llama/gpt, got {arch!r}")
    dkv = kv_pool.shape[-1] // 2
    BT = kv_pool.shape[2]
    # tpu-lint: allow(host-sync): flag() is a host-side config read
    interp = bool(flag("FLAGS_pallas_interpret")) and not use_pallas()
    if mp_axis is None and (use_pallas() or interp) and dkv % 128 == 0 \
            and BT % 8 == 0:
        cb = jnp.dtype(kv_pool.dtype).itemsize
        if blocks is not None and blocks.get("cache_wbytes", cb) != cb:
            raise ValueError(
                f"decode plan assumed a {blocks['cache_wbytes']}-byte KV "
                f"cache but the pool dtype is {kv_pool.dtype} ({cb} B); "
                f"rebuild the plan with decode_block_plan(cache_wbytes="
                f"{cb})")
        try:
            with jax.named_scope("fused_decode.kernel_paged"):
                return _fused_paged_decode_pallas(
                    x, params, kv_pool, block_tables, positions,
                    num_heads=num_heads, num_kv_heads=num_kv_heads,
                    head_dim=dkv // num_kv_heads, rope_base=rope_base,
                    eps=eps, arch=arch, blocks=blocks,
                    kv_scales=kv_scales, interpret=interp)
        except Exception as e:  # pragma: no cover - hardware-dependent
            if flag("FLAGS_pallas_strict"):
                raise
            global _fallback_logged
            if not _fallback_logged:
                _fallback_logged = True
                import logging
                logging.getLogger("paddle_tpu.ops.fused_decode").warning(
                    "Pallas paged decode failed (%s: %s); using the jnp "
                    "reference path. FLAGS_pallas_strict=1 to raise.",
                    type(e).__name__, e)
    with jax.named_scope("fused_decode.reference_paged"):
        return fused_paged_decode_reference(
            x, params, kv_pool, block_tables, positions, cos, sin,
            num_heads=num_heads, num_kv_heads=num_kv_heads, eps=eps,
            arch=arch, kv_scales=kv_scales, mp_axis=mp_axis)


# ---------------------------------------------------------------------------
# Coscheduled tick (fused Sarathi): prefill-chunk append + decode step
# ---------------------------------------------------------------------------
#
# The chunked serving tick used to dispatch TWO programs — a chunk
# program (prefill rows) and the fused paged decode (decode rows) —
# with the bf16 KV carry staged between them. Coscheduling folds both
# into ONE program: the chunk rows' freshly computed block-aligned KV
# scatters into the pool on the way into the decode step's chunk walk,
# so the pool crosses exactly one program boundary per tick (one
# donated buffer, one future `shard_map` seam for tensor-parallel
# serving instead of two — ROADMAP "One-program tick").
#
# Pallas-side story: the pool is donated by the caller, so on TPU the
# block scatter lowers to an in-place dynamic-update ahead of the
# kernel's table-resolved KV chunk walk — same HBM buffer, zero copy,
# and the decode walk never reads the chunk rows' blocks (a prefilling
# slot's block-table row points at scratch until adoption), so the
# scheduler may overlap the scatter DMA with the decode kernel's
# weight streaming. On the jnp reference path the win is one pool
# traversal per tick instead of two (jax-0.4 CPU materializes each
# program's pool output — BENCH_r06's chunked-capacity caveat;
# BENCH_r09 measures the recovery).


def paged_chunk_scatter(kv_pool, chunk_bids, chunk_kv):
    """Scatter prefill-chunk KV blocks into the paged pool.

    ``chunk_bids`` (n, nb) int32 physical block ids per prefilling row
    (entries past a row's allocated table target the scratch block);
    ``chunk_kv`` (L, n, nb, BT, 2*nkv*hd) the rows' block-aligned KV
    (bf16 chunk appends, or a whole quantized prompt on an int8 last
    chunk). One combined scatter for all layers — the per-layer form
    costs a full pool copy per LAYER on backends without in-place
    scatter (the `fused_paged_decode_reference` lesson)."""
    return kv_pool.at[:, chunk_bids].set(chunk_kv.astype(kv_pool.dtype))


def paged_block_gather(kv_pool, bids):
    """Gather whole physical blocks out of the paged pool — the
    device-side half of a swap-out / prefix export (docs/SERVING.md
    §Hierarchical KV).

    ``bids`` (n,) int32 physical block ids (callers pad to a bucketed
    length with the scratch block, exactly like a block table's
    unallocated tail, so the swap compile set stays finite); returns
    ``(L, n, BT, 2*nkv*hd)`` in the pool dtype. The result is a fresh
    buffer, so the caller may free the source blocks the moment the
    gather is DISPATCHED — the copy is ordered before any later pool
    mutation on the same stream, and ``copy_to_host_async`` overlaps
    the D2H leg with subsequent serving ticks."""
    return kv_pool[:, bids]


def paged_block_scatter(kv_pool, bids, vals):
    """Scatter host-staged block payloads back into the paged pool —
    the device-side half of a swap-in / tier-prefix promotion. Same
    contract as :func:`paged_chunk_scatter` (donate the pool at the jit
    boundary; entries past the real count target scratch); split out so
    swap traffic shares one seam with chunk appends instead of growing
    a second scatter idiom. The fused tick program never sees these
    blocks mid-flight: they land in the pool BEFORE the dispatch that
    first reads them, so compile-set and donation pins are untouched."""
    return kv_pool.at[:, bids].set(vals.astype(kv_pool.dtype))


def fused_paged_tick_step(x, params, kv_pool, block_tables, positions,
                          cos, sin, *, num_heads: int, num_kv_heads: int,
                          eps: float = 1e-5, rope_base: float = 10000.0,
                          arch: str = "llama",
                          blocks: Optional[Dict] = None, kv_scales=None,
                          chunk_bids=None, chunk_kv=None,
                          mp_axis: Optional[str] = None):
    """One fused Sarathi tick: coschedule a prefill-chunk append with
    the fused paged decode step — ONE program, the pool threaded
    through both updates (donate it at the jit boundary; the serving
    engine pins the aliasing via ``analysis.runtime.donation_report``).

    ``chunk_bids``/``chunk_kv`` (see :func:`paged_chunk_scatter`) may
    be ``None``, in which case this is exactly
    :func:`fused_paged_decode_step` — chunkless ticks share the body.
    The chunk rows' blocks and the decode rows' append blocks are
    disjoint by construction (prefilling slots idle against scratch
    until adoption), so the scatter/decode order is value-irrelevant;
    scatter-first matches the two-program tick it replaces.

    Under ``mp_axis`` the chunk forward runs REPLICATED (the full-model
    prefill math), so ``chunk_kv`` arrives in the FULL canonical [k|v]
    layout; each shard slices its own canonical columns out before the
    scatter into its local pool shard."""
    if chunk_bids is not None:
        if mp_axis is not None \
                and chunk_kv.shape[-1] != kv_pool.shape[-1]:
            chunk_kv = mp_local_kv_lastdim(chunk_kv, mp_axis)
        with jax.named_scope("fused_decode.chunk_scatter"):
            kv_pool = paged_chunk_scatter(kv_pool, chunk_bids, chunk_kv)
    return fused_paged_decode_step(
        x, params, kv_pool, block_tables, positions, cos, sin,
        num_heads=num_heads, num_kv_heads=num_kv_heads, eps=eps,
        rope_base=rope_base, arch=arch, blocks=blocks,
        kv_scales=kv_scales, mp_axis=mp_axis)


# ---------------------------------------------------------------------------
# Paged verify (speculative decoding): score a k-token tail per slot
# ---------------------------------------------------------------------------
#
# Speculative decoding turns k proposed tokens per slot into ONE scoring
# dispatch instead of k serial decode dispatches: the verify pass runs
# the whole stack over the tail [t0, p1..pk] (t0 = the slot's last
# sampled token, p* the proposals), appends every tail token's KV
# through the PR 10 multi-token append path, and returns the k+1 hidden
# states the engine samples the target tokens from. Decode is
# bandwidth-bound, so weights streamed once per k+1 tokens instead of
# once per token is the whole win (ROADMAP "Speculative decoding on the
# paged engine").
#
# Rejected-token KV is handled by POSITION, not by rollback: a slot's
# attention always masks to its own append position, and future appends
# overwrite stale entries in place — accepting a tokens is just
# "advance the position by a+1".


def fused_paged_verify_reference(x, params, kv_pool, block_tables,
                                 positions, cos, sin, *, num_heads: int,
                                 num_kv_heads: int, eps: float = 1e-5,
                                 arch: str = "llama", kv_scales=None,
                                 mp_axis: Optional[str] = None):
    """Score a K1-token tail per slot against the paged pool; pure jnp.

    x (b, K1, h): the embedded tail tokens — x[:, j] is token j embedded
    at position ``positions + j``; cos/sin (b, K1, hd) are the matching
    rope rows. kv_pool/block_tables/positions as in
    `fused_paged_decode_reference` (``positions`` is each slot's append
    position for tail token 0). Returns (x_out (b, K1, h), kv_pool) with
    every tail token's KV appended at positions [pos, pos+K1).

    Bit-identity contract (the speculative-vs-sequential parity pin,
    tests/test_serving_spec.py): tail token j's computation is the SAME
    per-token math as `fused_paged_decode_reference` — one (b, h) row
    per step, same einsums, same masks, same cast points — run K1 times
    over per-layer gathered views that carry each token's append
    forward (injection produces the exact values a scatter-then-regather
    would). A verify pass over an all-accepted tail therefore produces
    bitwise the logits K1 sequential decode steps would.

    Appends whose position falls outside the slot's table range (the
    over-speculation tail of a slot near its cap) are redirected to the
    scratch block (block 0) — garbage by contract, never attended (a
    query's mask never reaches past its own position).

    ``mp_axis`` arms the same tensor-parallel contract as
    `fused_paged_decode_reference`: local heads/pool columns in, one
    all_gather per column-parallel activation, bitwise mp=1 logits out.
    """
    L, NB, BT, dkv2 = kv_pool.shape
    b, MB = block_tables.shape
    K1 = x.shape[1]
    S = MB * BT
    dkv = dkv2 // 2
    nh = num_heads
    nkv = num_kv_heads
    hd = dkv // nkv
    rep = nh // nkv
    dq = nh * hd
    dtype = x.dtype
    scale = 1.0 / math.sqrt(hd)
    int8 = "wqkv_s" in params
    gpt = arch == "gpt"
    if arch not in ("llama", "gpt"):
        raise NotImplementedError(
            f"paged verify supports arch llama/gpt, got {arch!r}")
    rows = jnp.arange(b)

    def wdot(act, key, l):
        w = params[key][l]
        if int8:
            y = jnp.dot(act, w.astype(act.dtype),
                        preferred_element_type=jnp.float32)
            return y * params[f"{key}_s"][l]
        return jnp.dot(act, w, preferred_element_type=jnp.float32)

    # per-layer gathered views, carried across the tail tokens so token
    # j+1 sees token j's append without a per-token pool scatter (the
    # jax-0.4 CPU donation caveat: each pool scatter is a full copy —
    # one combined scatter at the end, like the decode reference)
    views = [kv_pool[l][block_tables].reshape(b, S, dkv2)
             for l in range(L)]
    app_news = []                   # per-token (L, b, dkv2) appends
    outs = []
    for j in range(K1):
        posj = positions + j
        cos_b = cos[:, j].reshape(b, 1, hd).astype(jnp.float32)
        sin_b = sin[:, j].reshape(b, 1, hd).astype(jnp.float32)
        xf = x[:, j].astype(jnp.float32)
        kv_news = []
        for l in range(L):
            if gpt:
                xn = _layernorm(xf, params["ln1"][l], params["ln1_b"][l],
                                eps)
            else:
                xn = _rms(xf, params["ln1"][l], eps)
            qkv = wdot(xn, "wqkv", l)
            if gpt:
                qkv = qkv + params["bqkv"][l]
            q = qkv[:, :dq].reshape(b, nh, hd)
            k = qkv[:, dq:dq + nkv * hd].reshape(b, nkv, hd)
            v = qkv[:, dq + nkv * hd:].reshape(b, nkv, hd)
            if not gpt:
                q = _rope1(q, cos_b, sin_b)
                k = _rope1(k, cos_b, sin_b)
            kv_new = jnp.concatenate(
                [k.reshape(b, dkv), v.reshape(b, dkv)], axis=-1)
            if kv_scales is not None:   # int8 pool: per-slot scales
                kv_new = jnp.clip(
                    jnp.round(kv_new.astype(jnp.float32) / kv_scales[l]),
                    -127, 127)
            kv_new = kv_new.astype(kv_pool.dtype)
            kv_news.append(kv_new)
            # inject this token's append into the carried view; an
            # out-of-range position (over-speculation past the cap) is
            # dropped — its pool write goes to scratch below
            kvl = views[l].at[rows, posj].set(kv_new, mode="drop")
            views[l] = kvl
            kl = kvl[:, :, :dkv].astype(jnp.float32)
            vl = kvl[:, :, dkv:].astype(jnp.float32)
            if kv_scales is not None:
                kl = kl * kv_scales[l][:, None, :dkv]
                vl = vl * kv_scales[l][:, None, dkv:]
            kl = kl.reshape(b, S, nkv, hd)
            vl = vl.reshape(b, S, nkv, hd)
            qg = q.reshape(b, nkv, rep, hd) * scale
            scores = jnp.einsum("bgrd,bsgd->bgrs", qg, kl)
            valid = (jnp.arange(S)[None, None, None]
                     <= posj[:, None, None, None])
            scores = jnp.where(valid, scores, NEG_INF)
            probs = jax.nn.softmax(scores, axis=-1)
            attn = jnp.einsum("bgrs,bsgd->bgrd", probs, vl)
            attn = attn.reshape(b, dq).astype(dtype)
            if mp_axis is not None:
                attn = _mp_gather_cols(attn, mp_axis)
            o = wdot(attn, "wo", l)
            if gpt:
                o = o + params["bo"][l]
            xf = xf + o
            if gpt:
                xn2 = _layernorm(xf, params["ln2"][l], params["ln2_b"][l],
                                 eps)
                g = wdot(xn2, "wg", l) + params["bg"][l]
                act = jax.nn.gelu(g, approximate=True).astype(dtype)
                if mp_axis is not None:
                    act = _mp_gather_cols(act, mp_axis)
                xf = xf + wdot(act, "wd", l) + params["bd"][l]
            else:
                xn2 = _rms(xf, params["ln2"][l], eps)
                g = wdot(xn2, "wg", l)
                u = wdot(xn2, "wu", l)
                act = (jax.nn.silu(g) * u).astype(dtype)
                if mp_axis is not None:
                    act = _mp_gather_cols(act, mp_axis)
                xf = xf + wdot(act, "wd", l)
        outs.append(xf.astype(dtype))
        app_news.append(jnp.stack(kv_news))         # (L, b, dkv2)
    # ONE combined scatter of every (layer, token) append; positions
    # past the table range land in the scratch block
    posm = positions[:, None] + jnp.arange(K1)[None]        # (b, K1)
    cm = posm // BT
    bid = jnp.take_along_axis(block_tables,
                              jnp.minimum(cm, MB - 1), axis=1)
    bid = jnp.where(cm < MB, bid, 0)                # 0 = scratch block
    off = posm % BT
    vals = jnp.stack(app_news, axis=2)              # (L, b, K1, dkv2)
    kv_pool = kv_pool.at[:, bid, off].set(vals)
    return jnp.stack(outs, axis=1), kv_pool


def _fused_paged_verify_pallas(x, params, kv_pool, block_tables,
                               positions, *, num_heads: int,
                               num_kv_heads: int, head_dim: int,
                               rope_base: float = 10000.0,
                               eps: float = 1e-5, arch: str = "llama",
                               blocks: Optional[Dict] = None,
                               kv_scales=None, interpret: bool = False):
    """Paged verify kernel: `_fused_paged_decode_pallas` with the
    single-token RMW append widened to a K1-token causal tail.

    x arrives TOKEN-MAJOR flat (K1*b, h) — token j's rows are the
    contiguous slice [j*b, (j+1)*b) so every per-token stage is a
    static slice (Mosaic cannot stride sublanes). Per layer:

    * the qkv pass runs ONE matmul over all K1*b rows; tail token j's
      heads are staged block-diagonally into q rows [j*nh, (j+1)*nh)
      of a (b, K1*nh, dkv) staging, so the prefix chunk walk scores
      ALL tail queries with one dot_general per KV block (every tail
      query attends the whole committed prefix — one shared walk);
    * the append window [pos//8*8, pos+K1) replaces the 8-token RMW
      block: NW 8-aligned segments per row, each resolved through the
      block table independently (BT % 8 == 0 means an 8-aligned
      segment never straddles a physical block; segments past the
      table range redirect to the scratch block). Tail k/v merge at
      offsets off+j, the window is attended with PER-QUERY causal
      limits (query j masks to pos+j), and the segments write back —
      the multi-token append path;
    * the o-proj/FFN run per tail token over the same static slices.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    L, NB, BT, dkv2 = kv_pool.shape
    b, MB = block_tables.shape
    K1b = x.shape[0]
    K1 = K1b // b
    assert K1 * b == K1b, (x.shape, b)
    dkv = dkv2 // 2
    nh = num_heads
    nkv = num_kv_heads
    hd = head_dim
    assert hd == dkv // nkv
    rep = nh // nkv
    h = x.shape[1]
    dq = nh * hd
    dqkv = dq + 2 * dkv
    ffn = params["wg"].shape[2]
    int8 = "wqkv_s" in params
    kvq = kv_scales is not None
    assert kvq == (jnp.dtype(kv_pool.dtype) == jnp.int8), \
        "int8 KV pool needs kv_scales (and vice versa)"
    gpt = arch == "gpt"
    wbytes = 1 if int8 else 2
    cb = jnp.dtype(kv_pool.dtype).itemsize
    ck = BT
    assert BT % 8 == 0, f"block_tokens {BT} must be a multiple of 8"
    assert dkv % 128 == 0, f"nkv*hd={dkv} must be a lane multiple of 128"
    # append-window segments: off <= 7 plus K1 tail tokens, 8-aligned
    NW = (7 + K1 + 7) // 8
    if blocks is not None:
        assert blocks.get("cache_wbytes", cb) == cb, \
            (f"decode plan assumed a {blocks['cache_wbytes']}-byte KV "
             f"cache but the pool dtype is {kv_pool.dtype} ({cb} B)")
        if blocks.get("q_split", 1) != 1:
            raise ValueError(
                "paged verify does not support the q-split (big-model) "
                "regime yet; build the plan with q_split=1")
        J, fblk = blocks["ffn_blocks"], blocks["fblk"]
        assert ffn == J * fblk, (ffn, blocks)
    else:
        J, fblk = _pick_ffn_blocks(
            ffn, h, fixed_bytes=(dqkv + dq) * h * wbytes, wbytes=wbytes)
    dtype = x.dtype
    scale = 1.0 / math.sqrt(hd)

    def kernel(*refs):
        if gpt:
            (pos_ref, bt_ref, posv_ref, x_in_ref, ln1_ref, wqkv_ref,
             wo_ref, ln2_ref, wg_ref, wd_ref) = refs[:10]
            wu_ref = None
            i = 10
            (ln1b_ref, ln2b_ref, bqkv_ref, bo_ref, bg_ref,
             bd_ref) = refs[i:i + 6]
            i += 6
        else:
            (pos_ref, bt_ref, posv_ref, x_in_ref, ln1_ref, wqkv_ref,
             wo_ref, ln2_ref, wg_ref, wu_ref, wd_ref) = refs[:11]
            i = 11
        if int8:
            sqkv_ref, so_ref, sg_ref, su_ref, sd_ref = refs[i:i + 5]
            i += 5
        if kvq:
            kvs_ref = refs[i]          # (b, 2*dkv) per-SLOT pool scales
            i += 1
        kv_in = refs[i]
        x_out_ref, kv_ref = refs[i + 1], refs[i + 2]
        (x_s, xn_s, acc_s, q_s, kv32_s, kvtl_s, kvch_s,
         wsem, rsem) = refs[i + 3:]
        del kv_in

        def wdot(act, wref, sref):
            w = wref[...]
            if int8:
                y = jnp.dot(act, w.astype(act.dtype),
                            preferred_element_type=jnp.float32)
                return y if sref is None else y * sref[...]
            return jnp.dot(act, w, preferred_element_type=jnp.float32)

        li = pl.program_id(0)
        j = pl.program_id(1)

        # ---- per-row paged DMA descriptors (block table in SMEM) ----
        def seg_src(l, r, m):
            """The m-th 8-token segment of row r's append window,
            resolved through its block table; past-the-table segments
            (over-speculation near the cap) redirect to scratch."""
            q0 = pos_ref[r] // 8 * 8 + m * 8
            c = q0 // BT
            bid = jnp.where(c < MB, bt_ref[r, jnp.minimum(c, MB - 1)], 0)
            return kv_ref.at[l, bid, pl.ds(q0 % BT, 8)]

        def seg_read(l, r, m):
            return pltpu.make_async_copy(
                seg_src(l, r, m), kvtl_s.at[r, pl.ds(m * 8, 8)],
                wsem.at[m, r])

        def seg_write(l, r, m):
            return pltpu.make_async_copy(
                kvtl_s.at[r, pl.ds(m * 8, 8)], seg_src(l, r, m),
                wsem.at[m, r])

        def chunk_copy(l, c, slot, r):
            return pltpu.make_async_copy(
                kv_ref.at[l, bt_ref[r, c]], kvch_s.at[slot, r],
                rsem.at[slot, r])

        # chunk walk bound: the LONGEST row's committed full-8 prefix
        nc = (pos_ref[0] // 8 * 8 + ck - 1) // ck
        for r in range(1, b):
            nc = jnp.maximum(nc, (pos_ref[r] // 8 * 8 + ck - 1) // ck)

        @pl.when(j == 0)
        def attention_phase():
            posv = posv_ref[...]                        # (b, 1) int32
            blk_v = posv // 8 * 8
            blk3 = blk_v.reshape(b, 1, 1)

            @pl.when(li == 0)
            def _():
                x_s[...] = x_in_ref[...].astype(jnp.float32)
                q_s[...] = jnp.zeros_like(q_s)
                for r in range(b):
                    for m in range(NW):
                        seg_read(li, r, m).start()

                @pl.when(nc > 0)
                def _():
                    for r in range(b):
                        chunk_copy(li, 0, 0, r).start()

            if gpt:
                xn = _layernorm(x_s[...], ln1_ref[...].reshape(h),
                                ln1b_ref[...].reshape(h), eps)
            else:
                xn = _rms(x_s[...], ln1_ref[...].reshape(h), eps)
            qkv = wdot(xn, wqkv_ref, sqkv_ref if int8 else None)
            if gpt:
                qkv = qkv + bqkv_ref[...]
            half = (lax.broadcasted_iota(jnp.int32, (1, hd), 1)
                    % (hd // 2)).astype(jnp.float32)
            inv_freq = jnp.exp(half * (-2.0 * math.log(rope_base) / hd))
            # per-(token, row) staging: token t's heads land in q rows
            # [t*nh, (t+1)*nh) block-diagonally; its k/v in kv32_s[:, t]
            for t in range(K1):
                seg = qkv[t * b:(t + 1) * b]            # (b, dqkv)
                if gpt:
                    rope2 = lambda v: v                 # noqa: E731
                else:
                    ang = (posv + t).astype(jnp.float32) * inv_freq
                    cos_b = jnp.cos(ang)
                    sin_b = jnp.sin(ang)
                    rope2 = lambda v: (v * cos_b + jnp.concatenate(
                        [-v[:, hd // 2:], v[:, :hd // 2]],
                        axis=-1) * sin_b)               # noqa: E731
                for n in range(nh):
                    g = n // rep
                    q_s[:, t * nh + n, g * hd:(g + 1) * hd] = rope2(
                        seg[:, n * hd:(n + 1) * hd]) * scale
                for g in range(nkv):
                    kv32_s[:, t, g * hd:(g + 1) * hd] = rope2(
                        seg[:, dq + g * hd:dq + (g + 1) * hd])
                    kv32_s[:, t, dkv + g * hd:dkv + (g + 1) * hd] = \
                        seg[:, dq + dkv + g * hd:dq + dkv + (g + 1) * hd]

            if kvq:     # per-slot k-half dequant scales fold into q rows
                qbd = q_s[...] * kvs_ref[...][:, None, :dkv]
            else:
                qbd = q_s[...]

            def merge(carry, kvblk, idx, limit):
                """Online-softmax block update over all K1*nh queries;
                `limit` is per-(row, query) — the causal tail masks
                query j to its own position."""
                m, l, acc = carry
                kf = kvblk[:, :, :dkv].astype(jnp.float32)
                vf = kvblk[:, :, dkv:].astype(jnp.float32)
                sc = lax.dot_general(
                    qbd, kf, (((2,), (2,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32)  # (b, K1*nh, w)
                sc = jnp.where(idx < limit, sc, NEG_INF)
                m_new = jnp.maximum(m, jnp.max(sc, axis=-1))
                alpha = jnp.exp(m - m_new)
                pp = jnp.exp(sc - m_new[..., None])
                acc = acc * alpha[..., None] + lax.dot_general(
                    pp, vf, (((2,), (1,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32)
                return m_new, l * alpha + jnp.sum(pp, axis=-1), acc

            def body(c, carry):
                slot = lax.rem(c, 2)

                @pl.when(c + 1 < nc)
                def _():
                    for r in range(b):
                        chunk_copy(li, c + 1, lax.rem(c + 1, 2), r).start()

                for r in range(b):
                    chunk_copy(li, c, slot, r).wait()
                idx = c * ck + lax.broadcasted_iota(
                    jnp.int32, (1, 1, ck), 2)
                # every tail query attends the whole committed prefix
                return merge(carry, kvch_s[slot], idx, blk3)

            carry = lax.fori_loop(0, nc, body, (
                jnp.full((b, K1 * nh), NEG_INF, jnp.float32),
                jnp.zeros((b, K1 * nh), jnp.float32),
                jnp.zeros((b, K1 * nh, dkv), jnp.float32)))

            # merge the K1 tail tokens into the append window at
            # offsets off+t, attend it with per-query causal limits,
            # write the segments back (waited in FFN j==1)
            for r in range(b):
                for m in range(NW):
                    seg_read(li, r, m).wait()
            off3 = (posv - blk_v).reshape(b, 1, 1)
            wi = lax.broadcasted_iota(jnp.int32, (1, NW * 8, 1), 1)
            win = kvtl_s[...].astype(jnp.float32)
            newtok = kv32_s[...]                        # (b, K1, 2dkv)
            if kvq:     # quantize the appends with the per-slot scales
                newtok = jnp.clip(
                    jnp.round(newtok / kvs_ref[...][:, None]),
                    -127.0, 127.0)
            for t in range(K1):
                win = jnp.where(wi == off3 + t, newtok[:, t][:, None],
                                win)
            kvtl_s[...] = win.astype(kv_pool.dtype)
            for r in range(b):
                for m in range(NW):
                    seg_write(li, r, m).start()
            widx = blk3 + lax.broadcasted_iota(
                jnp.int32, (1, 1, NW * 8), 2)
            # query t of each row masks to its own position pos+t
            jq = (lax.broadcasted_iota(jnp.int32, (1, K1 * nh, 1), 1)
                  // nh)
            ms_, ls, accs = merge(carry, kvtl_s[...], widx,
                                  posv.reshape(b, 1, 1) + jq + 1)

            norm = accs / ls[..., None]             # (b, K1*nh, dkv)
            if kvq:     # per-slot v-half dequant scales, applied once
                norm = norm * kvs_ref[...][:, None, dkv:]
            # o-proj per tail token over its static head-row slice
            for t in range(K1):
                nt = norm[:, t * nh:(t + 1) * nh, :]    # (b, nh, dkv)
                if rep == 1:
                    bd = (lax.broadcasted_iota(
                        jnp.int32, (1, nh, dkv), 2) // hd
                        == lax.broadcasted_iota(
                            jnp.int32, (1, nh, dkv), 1))
                    attn = jnp.sum(jnp.where(bd, nt, 0.0), axis=1)
                    oacc = wdot(attn.astype(dtype), wo_ref,
                                so_ref if int8 else None)
                else:
                    oacc = jnp.zeros((b, h), jnp.float32)
                    for g in range(nkv):
                        ng = nt[:, g * rep:(g + 1) * rep,
                                g * hd:(g + 1) * hd]
                        w3 = wo_ref[g * rep * hd:(g + 1) * rep * hd,
                                    :].reshape(rep, hd, h)
                        part = lax.dot_general(
                            ng.astype(dtype),
                            w3.astype(dtype) if int8 else w3,
                            (((2,), (1,)), ((1,), (0,))),
                            preferred_element_type=jnp.float32)
                        oacc = oacc + jnp.sum(part, axis=0)
                    if int8:
                        oacc = oacc * so_ref[...]
                if gpt:
                    oacc = oacc + bo_ref[...]
                x_s[t * b:(t + 1) * b, :] = \
                    x_s[t * b:(t + 1) * b, :] + oacc
            xr = x_s[...]
            if gpt:
                xn_s[...] = _layernorm(xr, ln2_ref[...].reshape(h),
                                       ln2b_ref[...].reshape(h),
                                       eps).astype(dtype)
            else:
                xn_s[...] = _rms(xr, ln2_ref[...].reshape(h),
                                 eps).astype(dtype)
            acc_s[...] = jnp.zeros_like(acc_s)

        @pl.when(j >= 1)
        def ffn_phase():
            @pl.when(j == 1)
            def prefetch_next_layer():
                for r in range(b):
                    for m in range(NW):
                        seg_write(li, r, m).wait()

                @pl.when(li + 1 < L)
                def _():
                    for r in range(b):
                        for m in range(NW):
                            seg_read(li + 1, r, m).start()

                    @pl.when(nc > 0)
                    def _():
                        for r in range(b):
                            chunk_copy(li + 1, 0, 0, r).start()

            xn = xn_s[...]
            g = wdot(xn, wg_ref, sg_ref if int8 else None)
            if gpt:
                g = g + bg_ref[...]
                act = jax.nn.gelu(g, approximate=True).astype(dtype)
            else:
                u = wdot(xn, wu_ref, su_ref if int8 else None)
                act = (jax.nn.silu(g) * u).astype(dtype)
            acc_s[...] += wdot(act, wd_ref, sd_ref if int8 else None)

            if gpt:
                @pl.when(j == J)
                def _():
                    acc_s[...] += jnp.broadcast_to(bd_ref[...],
                                                   acc_s.shape)

            @pl.when(j == J)
            def _():
                xr = x_s[...] + acc_s[...]
                x_s[...] = xr
                x_out_ref[...] = xr.astype(dtype)

    def jm(ll, jj):
        return jnp.where(jj < 1, J - 1, jj - 1)

    def fl(ll, jj):
        return lax.max(ll - (jj < 1), 0)

    grid = (L, 1 + J)
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),                 # positions
        pl.BlockSpec(memory_space=pltpu.SMEM),                 # block table
        pl.BlockSpec((b, 1), lambda l, j: (0, 0)),             # posv
        pl.BlockSpec((K1b, h), lambda l, j: (0, 0)),           # x
        pl.BlockSpec((None, 1, h), lambda l, j: (l, 0, 0)),    # ln1
        pl.BlockSpec((None, h, dqkv), lambda l, j: (l, 0, 0)),  # wqkv
        pl.BlockSpec((None, dq, h), lambda l, j: (l, 0, 0)),   # wo
        pl.BlockSpec((None, 1, h), lambda l, j: (l, 0, 0)),    # ln2
        pl.BlockSpec((None, h, fblk),
                     lambda l, j: (fl(l, j), 0, jm(l, j))),     # wg
    ] + ([] if gpt else [
        pl.BlockSpec((None, h, fblk),
                     lambda l, j: (fl(l, j), 0, jm(l, j))),     # wu
    ]) + [
        pl.BlockSpec((None, fblk, h),
                     lambda l, j: (fl(l, j), jm(l, j), 0)),     # wd
    ] + ([
        pl.BlockSpec((None, 1, h), lambda l, j: (l, 0, 0)),     # ln1_b
        pl.BlockSpec((None, 1, h), lambda l, j: (l, 0, 0)),     # ln2_b
        pl.BlockSpec((None, 1, dqkv), lambda l, j: (l, 0, 0)),  # bqkv
        pl.BlockSpec((None, 1, h), lambda l, j: (l, 0, 0)),     # bo
        pl.BlockSpec((None, 1, fblk),
                     lambda l, j: (fl(l, j), 0, jm(l, j))),     # bg
        pl.BlockSpec((None, 1, h), lambda l, j: (l, 0, 0)),     # bd
    ] if gpt else []) + ([
        pl.BlockSpec((None, 1, dqkv), lambda l, j: (l, 0, 0)),  # sqkv
        pl.BlockSpec((None, 1, h), lambda l, j: (l, 0, 0)),     # so
        pl.BlockSpec((None, 1, fblk),
                     lambda l, j: (fl(l, j), 0, jm(l, j))),     # sg
        pl.BlockSpec((None, 1, fblk),
                     lambda l, j: (fl(l, j), 0, jm(l, j))),     # su
        pl.BlockSpec((None, 1, h), lambda l, j: (l, 0, 0)),     # sd
    ] if int8 else []) + ([
        pl.BlockSpec((None, b, 2 * dkv), lambda l, j: (l, 0, 0)),  # kvs
    ] if kvq else []) + [
        pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),      # kv pool
    ]
    operands = [
        jnp.asarray(positions, jnp.int32).reshape(b),
        jnp.asarray(block_tables, jnp.int32),
        jnp.asarray(positions, jnp.int32).reshape(b, 1),
        x,
        params["ln1"][:, None], params["wqkv"], params["wo"],
        params["ln2"][:, None], params["wg"],
        *(() if gpt else (params["wu"],)),
        params["wd"],
        *((params["ln1_b"][:, None], params["ln2_b"][:, None],
           params["bqkv"][:, None], params["bo"][:, None],
           params["bg"][:, None], params["bd"][:, None]) if gpt else ()),
        *((params["wqkv_s"], params["wo_s"], params["wg_s"],
           params["wu_s"], params["wd_s"]) if int8 else ()),
        *((jnp.asarray(kv_scales, jnp.float32),) if kvq else ()),
        kv_pool,
    ]
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((K1b, h), lambda l, j: (0, 0)),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.HBM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((K1b, h), dtype),
            jax.ShapeDtypeStruct(kv_pool.shape, kv_pool.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((K1b, h), jnp.float32),        # x_s
            pltpu.VMEM((K1b, h), dtype),              # xn_s
            pltpu.VMEM((K1b, h), jnp.float32),        # acc_s
            pltpu.VMEM((b, K1 * nh, dkv), jnp.float32),   # q_s
            pltpu.VMEM((b, K1, 2 * dkv), jnp.float32),    # kv32_s
            pltpu.VMEM((b, NW * 8, 2 * dkv), kv_pool.dtype),  # kvtl_s
            pltpu.VMEM((2, b, ck, 2 * dkv), kv_pool.dtype),   # kvch_s
            pltpu.SemaphoreType.DMA((NW, b)),         # wsem (seg, row)
            pltpu.SemaphoreType.DMA((2, b)),          # rsem (slot, row)
        ],
        input_output_aliases={len(in_specs) - 1: 1},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),
            vmem_limit_bytes=_vmem_limit_bytes()),
        name="fused_paged_verify_step",
        interpret=interpret,
    )(*operands)
    return out[0], out[1]


def fused_paged_verify_step(x, params, kv_pool, block_tables, positions,
                            cos, sin, *, num_heads: int, num_kv_heads: int,
                            eps: float = 1e-5, rope_base: float = 10000.0,
                            arch: str = "llama",
                            blocks: Optional[Dict] = None, kv_scales=None,
                            mp_axis: Optional[str] = None):
    """Dispatch one PAGED verify step (speculative decoding's scoring
    pass): Pallas kernel on TPU (or under FLAGS_pallas_interpret), jnp
    verify reference elsewhere.

    x (b, K1, h) — the K1 tail tokens (the slot's last sampled token
    followed by its K proposals) embedded at positions ``positions + j``;
    cos/sin (b, K1, hd) the matching rope rows (reference path only —
    the kernel computes rope in-kernel from `positions`). Returns
    (x_out (b, K1, h), kv_pool) with every tail token's KV appended.
    The engine samples the target tokens from x_out and commits the
    longest proposal prefix that matches its own stream's samples —
    docs/SERVING.md §Speculative decoding.
    """
    from paddle_tpu.core.flags import flag
    from paddle_tpu.ops import use_pallas
    if arch not in ("llama", "gpt"):
        raise NotImplementedError(
            f"paged verify supports arch llama/gpt, got {arch!r}")
    b, K1, h = x.shape
    dkv = kv_pool.shape[-1] // 2
    BT = kv_pool.shape[2]
    # tpu-lint: allow(host-sync): flag() is a host-side config read
    interp = bool(flag("FLAGS_pallas_interpret")) and not use_pallas()
    if mp_axis is None and (use_pallas() or interp) and dkv % 128 == 0 \
            and BT % 8 == 0:
        cb = jnp.dtype(kv_pool.dtype).itemsize
        if blocks is not None and blocks.get("cache_wbytes", cb) != cb:
            raise ValueError(
                f"decode plan assumed a {blocks['cache_wbytes']}-byte KV "
                f"cache but the pool dtype is {kv_pool.dtype} ({cb} B); "
                f"rebuild the plan with decode_block_plan(cache_wbytes="
                f"{cb})")
        try:
            with jax.named_scope("fused_decode.kernel_paged_verify"):
                # token-major flat: token j's rows contiguous at [j*b,
                # (j+1)*b) so the kernel's per-token stages are static
                # slices
                xf = x.transpose(1, 0, 2).reshape(K1 * b, h)
                y, pool = _fused_paged_verify_pallas(
                    xf, params, kv_pool, block_tables, positions,
                    num_heads=num_heads, num_kv_heads=num_kv_heads,
                    head_dim=dkv // num_kv_heads, rope_base=rope_base,
                    eps=eps, arch=arch, blocks=blocks,
                    kv_scales=kv_scales, interpret=interp)
                return y.reshape(K1, b, h).transpose(1, 0, 2), pool
        except Exception as e:  # pragma: no cover - hardware-dependent
            if flag("FLAGS_pallas_strict"):
                raise
            global _fallback_logged
            if not _fallback_logged:
                _fallback_logged = True
                import logging
                logging.getLogger("paddle_tpu.ops.fused_decode").warning(
                    "Pallas paged verify failed (%s: %s); using the jnp "
                    "reference path. FLAGS_pallas_strict=1 to raise.",
                    type(e).__name__, e)
    with jax.named_scope("fused_decode.reference_paged_verify"):
        return fused_paged_verify_reference(
            x, params, kv_pool, block_tables, positions, cos, sin,
            num_heads=num_heads, num_kv_heads=num_kv_heads, eps=eps,
            arch=arch, kv_scales=kv_scales, mp_axis=mp_axis)
