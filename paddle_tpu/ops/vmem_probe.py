"""Boot-time Mosaic scoped-VMEM probe (VERDICT r4 #10).

`_VMEM_MIB_BY_KIND` in `fused_decode.py` asserts 128 MiB for every TPU
generation but was only ever *measured* on v5e. `FLAGS_vmem_mib = -1`
replaces the belief with a measurement: bisect the largest scoped-VMEM
scratch allocation that Mosaic will compile AND the chip will run, cached
per `device_kind` for the process lifetime.

The probe's trivial kernel measures the max single scratch allocation:
capacity minus Mosaic's small fixed reservations (124 of 128 MiB on
v5e). `_vmem_mib()` therefore treats capacity as probed + 4 — on v5e
that reproduces the kind-table value exactly, and the planner's larger
margins (28/40 MiB, calibrated against the *real* fused kernels whose
pipelined BlockSpecs consume VMEM beyond the plan's own accounting)
continue to apply on top.

Reference analog: the reference reads VMEM-equivalent limits from the
device properties (`phi::GPUContext` exposes shared-mem capacity);
TPU runtimes expose no VMEM attribute, hence the probe.
"""

import functools

import jax
import jax.numpy as jnp

_STEP_MIB = 4          # probe granularity
_LO_MIB = 16           # Mosaic's historical default limit — always fits
_HI_MIB = 1024         # no announced generation exceeds this


def _fits(mib: int) -> bool:
    """True iff a Pallas kernel holding a `mib`-MiB VMEM scratch compiles
    and executes on the local TPU."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows = mib * 2 ** 20 // (128 * 4)   # (rows, 128) f32 == mib MiB

    def kernel(o_ref, scratch):
        scratch[0, :] = jnp.ones((128,), jnp.float32)
        # touch the far end so the allocation can't be elided
        scratch[rows - 1, :] = jnp.ones((128,), jnp.float32)
        o_ref[0, :] = scratch[0, :] + scratch[rows - 1, :]

    try:
        fn = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            scratch_shapes=[pltpu.VMEM((rows, 128), jnp.float32)],
            compiler_params=pltpu.CompilerParams(
                vmem_limit_bytes=(mib + 2) * 2 ** 20),
        )
        # tpu-lint: allow(host-sync): the probe MUST block — it exists
        # to learn whether this VMEM configuration compiles and runs
        jax.block_until_ready(jax.jit(fn)())
        return True
    except Exception:
        return False


@functools.lru_cache(maxsize=None)
def probe_usable_vmem_mib(device_kind: str) -> int:
    """Largest scoped-VMEM scratch (MiB, `_STEP_MIB` granularity) that
    compiles + runs on this chip. Cached per device kind.

    Only meaningful on a real TPU backend; raises on other platforms.
    """
    if jax.devices()[0].platform != "tpu":
        raise RuntimeError(
            "VMEM probe needs a TPU backend; FLAGS_vmem_mib=-1 is only "
            f"valid on TPU (platform={jax.devices()[0].platform!r})")
    assert _fits(_LO_MIB), "even the 16 MiB floor failed — probe is broken"
    # exponential search up from the floor, then bisect
    lo, hi = _LO_MIB, None
    cand = _LO_MIB * 2
    while cand <= _HI_MIB:
        if _fits(cand):
            lo = cand
            cand *= 2
        else:
            hi = cand
            break
    if hi is None:
        return _HI_MIB
    while hi - lo > _STEP_MIB:
        mid = (lo + hi) // 2 // _STEP_MIB * _STEP_MIB
        if _fits(mid):
            lo = mid
        else:
            hi = mid
    return lo
