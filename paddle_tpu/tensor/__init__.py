"""Tensor API veneer — paddle-style creation/math/manipulation ops over jnp.

The reference binds ~400 tensor methods through pybind `_C_ops` to phi kernels
(ref: python/paddle/tensor/{creation,math,manipulation,linalg}.py). On TPU every
op is a jnp call that XLA fuses; this module provides the paddle-shaped names
(axis= keyword, paddle argument orders) so reference users find what they expect.

Tensors ARE jax.Arrays — no wrapper class. `Tensor` is an alias usable in
isinstance checks and annotations.
"""

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.dtype import to_jax_dtype, get_default_dtype
from paddle_tpu.core import rng as _rng

Tensor = jax.Array


# ---- creation --------------------------------------------------------------

def to_tensor(data, dtype=None, stop_gradient=True):
    if dtype is not None:
        return jnp.asarray(data, dtype=to_jax_dtype(dtype))
    # tpu-lint: allow(host-sync): guard keeps device arrays out of np
    arr = np.asarray(data) if not isinstance(data, (jax.Array, np.ndarray)) else data
    if isinstance(arr, np.ndarray) and arr.dtype == np.float64:
        arr = arr.astype(np.float32)  # paddle defaults float data to fp32
    return jnp.asarray(arr)


def zeros(shape, dtype=None):
    return jnp.zeros(shape, dtype=to_jax_dtype(dtype))


def ones(shape, dtype=None):
    return jnp.ones(shape, dtype=to_jax_dtype(dtype))


def full(shape, fill_value, dtype=None):
    return jnp.full(shape, fill_value, dtype=to_jax_dtype(dtype) if dtype else None)


def zeros_like(x, dtype=None):
    return jnp.zeros_like(x, dtype=to_jax_dtype(dtype) if dtype else None)


def ones_like(x, dtype=None):
    return jnp.ones_like(x, dtype=to_jax_dtype(dtype) if dtype else None)


def full_like(x, fill_value, dtype=None):
    return jnp.full_like(x, fill_value, dtype=to_jax_dtype(dtype) if dtype else None)


def empty(shape, dtype=None):
    return jnp.zeros(shape, dtype=to_jax_dtype(dtype))


def empty_like(x, dtype=None):
    return jnp.zeros_like(x, dtype=to_jax_dtype(dtype) if dtype else None)


def arange(start=0, end=None, step=1, dtype=None):
    if end is None:
        start, end = 0, start
    return jnp.arange(start, end, step, dtype=to_jax_dtype(dtype) if dtype else None)


def linspace(start, stop, num, dtype=None):
    return jnp.linspace(start, stop, num, dtype=to_jax_dtype(dtype) if dtype else None)


def eye(num_rows, num_columns=None, dtype=None):
    return jnp.eye(num_rows, num_columns, dtype=to_jax_dtype(dtype))


def rand(shape, dtype=None):
    return jax.random.uniform(_rng.next_rng_key(), shape,
                              dtype=to_jax_dtype(dtype) if dtype else get_default_dtype())


def randn(shape, dtype=None):
    return jax.random.normal(_rng.next_rng_key(), tuple(shape),
                             dtype=to_jax_dtype(dtype) if dtype else get_default_dtype())


def randint(low=0, high=None, shape=(1,), dtype="int64"):
    if high is None:
        low, high = 0, low
    return jax.random.randint(_rng.next_rng_key(), tuple(shape), low, high,
                              dtype=to_jax_dtype(dtype))


def randperm(n, dtype="int64"):
    return jax.random.permutation(_rng.next_rng_key(), n).astype(to_jax_dtype(dtype))


def normal(mean=0.0, std=1.0, shape=(1,)):
    return mean + std * jax.random.normal(_rng.next_rng_key(), tuple(shape),
                                          dtype=get_default_dtype())


def uniform(shape, dtype=None, min=-1.0, max=1.0):
    return jax.random.uniform(_rng.next_rng_key(), tuple(shape),
                              dtype=to_jax_dtype(dtype) if dtype else get_default_dtype(),
                              minval=min, maxval=max)


# ---- manipulation ----------------------------------------------------------

def concat(x, axis=0):
    return jnp.concatenate(x, axis=axis)


def stack(x, axis=0):
    return jnp.stack(x, axis=axis)


def split(x, num_or_sections, axis=0):
    if isinstance(num_or_sections, int):
        return jnp.split(x, num_or_sections, axis=axis)
    # paddle semantics: list of section sizes, -1 means remainder
    sizes = list(num_or_sections)
    total = x.shape[axis]
    if -1 in sizes:
        i = sizes.index(-1)
        sizes[i] = total - (sum(sizes) + 1)
    idx = np.cumsum(sizes)[:-1].tolist()
    return jnp.split(x, idx, axis=axis)


def chunk(x, chunks, axis=0):
    return jnp.split(x, chunks, axis=axis)


def reshape(x, shape):
    return jnp.reshape(x, shape)


def transpose(x, perm):
    return jnp.transpose(x, perm)


def squeeze(x, axis=None):
    return jnp.squeeze(x, axis=axis)


def unsqueeze(x, axis):
    return jnp.expand_dims(x, axis)


def flatten(x, start_axis=0, stop_axis=-1):
    ndim = x.ndim
    if stop_axis < 0:
        stop_axis += ndim
    if start_axis < 0:
        start_axis += ndim
    new_shape = x.shape[:start_axis] + (-1,) + x.shape[stop_axis + 1:]
    return jnp.reshape(x, new_shape)


def cast(x, dtype):
    return x.astype(to_jax_dtype(dtype))


def tile(x, repeat_times):
    return jnp.tile(x, repeat_times)


def expand(x, shape):
    shape = tuple(x.shape[i - (len(shape) - x.ndim)] if s == -1 else s
                  for i, s in enumerate(shape))
    return jnp.broadcast_to(x, shape)


def roll(x, shifts, axis=None):
    return jnp.roll(x, shifts, axis=axis)


def flip(x, axis):
    return jnp.flip(x, axis=axis)


def tril(x, diagonal=0):
    return jnp.tril(x, k=diagonal)


def triu(x, diagonal=0):
    return jnp.triu(x, k=diagonal)


def diag(x, offset=0):
    return jnp.diag(x, k=offset)


def gather(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


def take_along_axis(x, indices, axis):
    return jnp.take_along_axis(x, indices, axis=axis)


def scatter(x, index, updates, overwrite=True):
    if overwrite:
        return x.at[index].set(updates)
    return x.at[index].add(updates)


def index_select(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


def masked_select(x, mask):
    return x[mask]


def where(condition, x=None, y=None):
    if x is None and y is None:
        return jnp.where(condition)
    return jnp.where(condition, x, y)


def nonzero(x, as_tuple=False):
    nz = jnp.nonzero(x)
    if as_tuple:
        return nz
    return jnp.stack(nz, axis=1)


def unique(x, return_index=False, return_inverse=False, return_counts=False):
    return jnp.unique(x, return_index=return_index, return_inverse=return_inverse,
                      return_counts=return_counts)


# ---- math ------------------------------------------------------------------

def matmul(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2)
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2)
    return jnp.matmul(x, y)


def bmm(x, y):
    return jnp.matmul(x, y)


def dot(x, y):
    return jnp.sum(x * y, axis=-1)


def outer(x, y):
    return jnp.outer(x, y)


def einsum(equation, *operands):
    return jnp.einsum(equation, *operands)


def add(x, y):
    return jnp.add(x, y)


def subtract(x, y):
    return jnp.subtract(x, y)


def multiply(x, y):
    return jnp.multiply(x, y)


def divide(x, y):
    return jnp.divide(x, y)


def pow(x, y):
    return jnp.power(x, y)


def sqrt(x):
    return jnp.sqrt(x)


def rsqrt(x):
    return jax.lax.rsqrt(x)


def exp(x):
    return jnp.exp(x)


def log(x):
    return jnp.log(x)


def abs(x):
    return jnp.abs(x)


def clip(x, min=None, max=None):
    return jnp.clip(x, min, max)


def maximum(x, y):
    return jnp.maximum(x, y)


def minimum(x, y):
    return jnp.minimum(x, y)


def tanh(x):
    return jnp.tanh(x)


def sigmoid(x):
    return jax.nn.sigmoid(x)


def sin(x):
    return jnp.sin(x)


def cos(x):
    return jnp.cos(x)


def floor(x):
    return jnp.floor(x)


def ceil(x):
    return jnp.ceil(x)


def round(x):
    return jnp.round(x)


def sign(x):
    return jnp.sign(x)


def cumsum(x, axis=None):
    return jnp.cumsum(x, axis=axis)


# ---- reductions ------------------------------------------------------------

def mean(x, axis=None, keepdim=False):
    return jnp.mean(x, axis=axis, keepdims=keepdim)


def sum(x, axis=None, dtype=None, keepdim=False):
    return jnp.sum(x, axis=axis, dtype=to_jax_dtype(dtype) if dtype else None,
                   keepdims=keepdim)


def max(x, axis=None, keepdim=False):
    return jnp.max(x, axis=axis, keepdims=keepdim)


def min(x, axis=None, keepdim=False):
    return jnp.min(x, axis=axis, keepdims=keepdim)


def prod(x, axis=None, keepdim=False):
    return jnp.prod(x, axis=axis, keepdims=keepdim)


def var(x, axis=None, unbiased=True, keepdim=False):
    return jnp.var(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


def std(x, axis=None, unbiased=True, keepdim=False):
    return jnp.std(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


def argmax(x, axis=None, keepdim=False, dtype="int64"):
    out = jnp.argmax(x, axis=axis, keepdims=keepdim)
    return out.astype(to_jax_dtype(dtype))


def argmin(x, axis=None, keepdim=False, dtype="int64"):
    out = jnp.argmin(x, axis=axis, keepdims=keepdim)
    return out.astype(to_jax_dtype(dtype))


def all(x, axis=None, keepdim=False):
    return jnp.all(x, axis=axis, keepdims=keepdim)


def any(x, axis=None, keepdim=False):
    return jnp.any(x, axis=axis, keepdims=keepdim)


def norm(x, p=2, axis=None, keepdim=False):
    if p == "fro" or p == 2:
        return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdim))
    if p == 1:
        return jnp.sum(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == np.inf:
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    return jnp.power(jnp.sum(jnp.power(jnp.abs(x), p), axis=axis, keepdims=keepdim), 1.0 / p)


# ---- comparisons -----------------------------------------------------------

def equal(x, y):
    return jnp.equal(x, y)


def not_equal(x, y):
    return jnp.not_equal(x, y)


def greater_than(x, y):
    return jnp.greater(x, y)


def greater_equal(x, y):
    return jnp.greater_equal(x, y)


def less_than(x, y):
    return jnp.less(x, y)


def less_equal(x, y):
    return jnp.less_equal(x, y)


def logical_and(x, y):
    return jnp.logical_and(x, y)


def logical_or(x, y):
    return jnp.logical_or(x, y)


def logical_not(x):
    return jnp.logical_not(x)


def isnan(x):
    return jnp.isnan(x)


def isinf(x):
    return jnp.isinf(x)


def isfinite(x):
    return jnp.isfinite(x)


def allclose(x, y, rtol=1e-5, atol=1e-8):
    return jnp.allclose(x, y, rtol=rtol, atol=atol)


def equal_all(x, y):
    return jnp.array_equal(x, y)


# ---- sort / search ---------------------------------------------------------

def topk(x, k, axis=-1, largest=True, sorted=True):
    if not largest:
        vals, idx = jax.lax.top_k(-jnp.moveaxis(x, axis, -1), k)
        vals = -vals
    else:
        vals, idx = jax.lax.top_k(jnp.moveaxis(x, axis, -1), k)
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis)
    return vals, idx.astype(jnp.int64)


def sort(x, axis=-1, descending=False):
    out = jnp.sort(x, axis=axis)
    return jnp.flip(out, axis=axis) if descending else out


def argsort(x, axis=-1, descending=False):
    out = jnp.argsort(x, axis=axis)
    return jnp.flip(out, axis=axis) if descending else out


# ---- meta ------------------------------------------------------------------

def numel(x):
    return int(np.prod(x.shape)) if x.shape else 1


def shape(x):
    return list(x.shape)


# ---- breadth modules (math / manipulation extras, linalg re-export) --------
# Imported wholesale: every public name becomes paddle_tpu.tensor.<name>
# (and paddle_tpu.<name> via the package-level tensor import).

from paddle_tpu.tensor.math_ops import *        # noqa: F401,F403,E402
from paddle_tpu.tensor.manipulation_ops import *  # noqa: F401,F403,E402
from paddle_tpu.tensor.extra_ops import *  # noqa: F401,F403,E402
from paddle_tpu.linalg import (  # noqa: F401,E402
    cholesky,
    corrcoef,
    cov,
    cross,
    vander,
    cholesky_solve,
    eig,
    eigvals,
    eigvalsh,
    inverse,
    lstsq,
    lu,
    lu_unpack,
    matrix_power,
    matrix_rank,
    pinv,
    qr,
    slogdet,
    solve,
    svd,
    triangular_solve,
)
