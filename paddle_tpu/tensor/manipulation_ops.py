"""Tensor manipulation breadth: indexing / reshaping / search extras.

Reference surface: python/paddle/tensor/{manipulation,search}.py. Thin
paddle-shaped veneers over jnp; imported into `paddle_tpu.tensor`.
"""

import jax
import jax.numpy as jnp


# ---- reshaping / axes -------------------------------------------------------


def moveaxis(x, source, destination):
    return jnp.moveaxis(x, source, destination)


def swapaxes(x, axis0, axis1):
    return jnp.swapaxes(x, axis0, axis1)


def unbind(x, axis=0):
    return tuple(jnp.moveaxis(x, axis, 0))


def unflatten(x, axis, shape):
    axis = axis % x.ndim
    new_shape = x.shape[:axis] + tuple(shape) + x.shape[axis + 1:]
    return x.reshape(new_shape)


def tensor_split(x, num_or_indices, axis=0):
    return jnp.array_split(x, num_or_indices, axis=axis)


def hsplit(x, num_or_indices):
    return jnp.hsplit(x, num_or_indices)


def vsplit(x, num_or_indices):
    return jnp.vsplit(x, num_or_indices)


def dsplit(x, num_or_indices):
    return jnp.dsplit(x, num_or_indices)


def hstack(xs):
    return jnp.hstack(xs)


def vstack(xs):
    return jnp.vstack(xs)


def dstack(xs):
    return jnp.dstack(xs)


def column_stack(xs):
    return jnp.column_stack(xs)


def row_stack(xs):
    return jnp.vstack(xs)


def atleast_1d(*xs):
    r = jnp.atleast_1d(*xs)
    return r


def atleast_2d(*xs):
    return jnp.atleast_2d(*xs)


def atleast_3d(*xs):
    return jnp.atleast_3d(*xs)


def broadcast_to(x, shape):
    return jnp.broadcast_to(x, shape)


def expand_as(x, y):
    return jnp.broadcast_to(x, y.shape)


def broadcast_tensors(*xs):
    return jnp.broadcast_arrays(*xs)


def broadcast_shape(x_shape, y_shape):
    return list(jnp.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def repeat_interleave(x, repeats, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


def rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, k=k, axes=axes)


def as_complex(x):
    return jax.lax.complex(x[..., 0], x[..., 1])


def as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


def real(x):
    return jnp.real(x)


def imag(x):
    return jnp.imag(x)


def conj(x):
    return jnp.conj(x)


# ---- diag family ------------------------------------------------------------


def diagflat(x, offset=0):
    return jnp.diagflat(x, k=offset)


def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    """Batched diagonal embed: (..., n) → (..., n, n) with x on `offset`."""
    n = x.shape[-1] + abs(offset)
    base = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
    idx = jnp.arange(x.shape[-1])
    rows = idx + max(-offset, 0)
    cols = idx + max(offset, 0)
    base = base.at[..., rows, cols].set(x)
    if (dim1, dim2) != (-2, -1):
        base = jnp.moveaxis(base, (-2, -1), (dim1, dim2))
    return base


def tril_indices(row, col=None, offset=0):
    col = col if col is not None else row
    return jnp.stack(jnp.tril_indices(row, k=offset, m=col))


def triu_indices(row, col=None, offset=0):
    col = col if col is not None else row
    return jnp.stack(jnp.triu_indices(row, k=offset, m=col))


def meshgrid(*xs, indexing="ij"):
    xs = xs[0] if len(xs) == 1 and isinstance(xs[0], (list, tuple)) else xs
    return jnp.meshgrid(*xs, indexing=indexing)


# ---- indexing / scatter -----------------------------------------------------


def masked_fill(x, mask, value):
    return jnp.where(mask, jnp.asarray(value, x.dtype), x)


def index_add(x, index, axis, value):
    return _index_op(x, index, axis, value, "add")


def index_put(x, indices, value, accumulate=False):
    if accumulate:
        return x.at[tuple(indices)].add(value)
    return x.at[tuple(indices)].set(value)


def index_fill(x, index, axis, value):
    return _index_op(x, index, axis,
                     jnp.asarray(value, x.dtype), "set")


def _index_op(x, index, axis, value, mode):
    ix = [slice(None)] * x.ndim
    ix[axis] = index
    ref = x.at[tuple(ix)]
    return ref.add(value) if mode == "add" else ref.set(value)


def put_along_axis(x, indices, values, axis, reduce="assign"):
    if hasattr(jnp, "put_along_axis"):
        if reduce == "assign":
            return jnp.put_along_axis(x, indices, values, axis=axis,
                                      inplace=False)
    # scatter via explicit coordinate grid
    values = jnp.broadcast_to(values, indices.shape)
    coords = list(jnp.indices(indices.shape))
    coords[axis] = indices
    ref = x.at[tuple(coords)]
    return {"assign": ref.set, "add": ref.add, "multiply": ref.multiply,
            "mul": ref.multiply, "amax": ref.max, "amin": ref.min}[reduce](values)


def take(x, index, mode="raise"):
    """Reference take: index into the FLATTENED tensor."""
    jmode = {"raise": None, "wrap": "wrap", "clip": "clip"}[mode]
    return jnp.take(x.reshape(-1), index, mode=jmode)


def select_scatter(x, values, axis, index):
    ix = [slice(None)] * x.ndim
    ix[axis] = index
    return x.at[tuple(ix)].set(values)


def slice_scatter(x, value, axes, starts, ends, strides=None):
    x = jnp.asarray(x)
    strides = strides if strides is not None else [1] * len(axes)
    ix = [slice(None)] * x.ndim
    for ax, st, en, sr in zip(axes, starts, ends, strides):
        ix[ax] = slice(st, en, sr)
    return x.at[tuple(ix)].set(value)


def gather_nd(x, index):
    return x[tuple(jnp.moveaxis(index, -1, 0))]


def scatter_nd_add(x, index, updates):
    return x.at[tuple(jnp.moveaxis(index, -1, 0))].add(updates)


def scatter_nd(index, updates, shape):
    return jnp.zeros(shape, updates.dtype).at[
        tuple(jnp.moveaxis(index, -1, 0))].add(updates)


# ---- search -----------------------------------------------------------------


def searchsorted(sorted_sequence, values, out_int32=False, right=False):
    side = "right" if right else "left"
    out = jnp.searchsorted(sorted_sequence, values, side=side)
    return out.astype(jnp.int32) if out_int32 else out


def bucketize(x, sorted_sequence, out_int32=False, right=False):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def argwhere(x):
    return jnp.argwhere(x)


def msort(x):
    return jnp.sort(x, axis=0)


def nanargmax(x, axis=None, keepdim=False):
    return jnp.nanargmax(x, axis=axis, keepdims=keepdim)


def nanargmin(x, axis=None, keepdim=False):
    return jnp.nanargmin(x, axis=axis, keepdims=keepdim)
