"""Tensor math breadth: elementwise / reduction / cumulative ops.

Reference surface: python/paddle/tensor/math.py (~200 functions over phi
kernels). Each op here is a jnp call XLA fuses; signatures keep paddle's
argument orders and axis= keywords. Imported wholesale into
`paddle_tpu.tensor` (the paddle.* namespace veneer).
"""

import jax
import jax.numpy as jnp
from jax import lax

# ---- elementwise: exp/log family -------------------------------------------


def log1p(x):
    return jnp.log1p(x)


def log2(x):
    return jnp.log2(x)


def log10(x):
    return jnp.log10(x)


def expm1(x):
    return jnp.expm1(x)


def logaddexp(x, y):
    return jnp.logaddexp(x, y)


# ---- elementwise: trig / hyperbolic ----------------------------------------


def tan(x):
    return jnp.tan(x)


def sinh(x):
    return jnp.sinh(x)


def cosh(x):
    return jnp.cosh(x)


def asin(x):
    return jnp.arcsin(x)


def acos(x):
    return jnp.arccos(x)


def atan(x):
    return jnp.arctan(x)


def atan2(x, y):
    return jnp.arctan2(x, y)


def asinh(x):
    return jnp.arcsinh(x)


def acosh(x):
    return jnp.arccosh(x)


def atanh(x):
    return jnp.arctanh(x)


def deg2rad(x):
    return jnp.deg2rad(x)


def rad2deg(x):
    return jnp.rad2deg(x)


# ---- elementwise: special ---------------------------------------------------


def erf(x):
    return jax.scipy.special.erf(x)


def erfinv(x):
    return jax.scipy.special.erfinv(x)


def lgamma(x):
    return jax.scipy.special.gammaln(x)


def digamma(x):
    return jax.scipy.special.digamma(x)


def polygamma(x, n):
    return jax.scipy.special.polygamma(n, x)


def i0(x):
    return jax.scipy.special.i0(x)


def i0e(x):
    return jax.scipy.special.i0e(x)


def i1(x):
    return jax.scipy.special.i1(x)


def i1e(x):
    return jax.scipy.special.i1e(x)


# ---- elementwise: rounding / parts -----------------------------------------


def trunc(x):
    return jnp.trunc(x)


def frac(x):
    return x - jnp.trunc(x)


def neg(x):
    return jnp.negative(x)


def reciprocal(x):
    return jnp.reciprocal(x)


def square(x):
    return jnp.square(x)


def remainder(x, y):
    return jnp.remainder(x, y)


mod = remainder
floor_mod = remainder


def fmod(x, y):
    return jnp.fmod(x, y)


def floor_divide(x, y):
    return jnp.floor_divide(x, y)


def divide_no_nan(x, y):
    return jnp.where(y == 0, jnp.zeros_like(x * y), x / y)


def copysign(x, y):
    return jnp.copysign(x, y)


def signbit(x):
    return jnp.signbit(x)


def heaviside(x, y):
    return jnp.heaviside(x, y)


def hypot(x, y):
    return jnp.hypot(x, y)


def nextafter(x, y):
    return jnp.nextafter(x, y)


def ldexp(x, y):
    return jnp.ldexp(x, y)


def frexp(x):
    return jnp.frexp(x)


def gcd(x, y):
    return jnp.gcd(x, y)


def lcm(x, y):
    return jnp.lcm(x, y)


def fmax(x, y):
    return jnp.fmax(x, y)


def fmin(x, y):
    return jnp.fmin(x, y)


def lerp(x, y, weight):
    return x + weight * (y - x)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


def stanh(x, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)


def multiplex(inputs, index):
    """Row-wise select: out[i] = inputs[index[i]][i] (reference multiplex)."""
    stacked = jnp.stack(inputs)                        # (n, b, ...)
    idx = index.reshape((1, -1) + (1,) * (stacked.ndim - 2)).astype(jnp.int32)
    return jnp.take_along_axis(stacked, idx, axis=0)[0]


# ---- logical / bitwise ------------------------------------------------------


def logical_xor(x, y):
    return jnp.logical_xor(x, y)


def bitwise_and(x, y):
    return jnp.bitwise_and(x, y)


def bitwise_or(x, y):
    return jnp.bitwise_or(x, y)


def bitwise_xor(x, y):
    return jnp.bitwise_xor(x, y)


def bitwise_not(x):
    return jnp.bitwise_not(x)


def bitwise_left_shift(x, y):
    return jnp.left_shift(x, y)


def bitwise_right_shift(x, y):
    return jnp.right_shift(x, y)


def isclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def isreal(x):
    return jnp.isreal(x)


def isneginf(x):
    return jnp.isneginf(x)


def isposinf(x):
    return jnp.isposinf(x)


# ---- reductions -------------------------------------------------------------


def amax(x, axis=None, keepdim=False):
    return jnp.max(x, axis=axis, keepdims=keepdim)


def amin(x, axis=None, keepdim=False):
    return jnp.min(x, axis=axis, keepdims=keepdim)


def median(x, axis=None, keepdim=False):
    return jnp.median(x, axis=axis, keepdims=keepdim)


def nanmedian(x, axis=None, keepdim=False):
    return jnp.nanmedian(x, axis=axis, keepdims=keepdim)


def nanmean(x, axis=None, keepdim=False):
    return jnp.nanmean(x, axis=axis, keepdims=keepdim)


def nansum(x, axis=None, keepdim=False, dtype=None):
    return jnp.nansum(x, axis=axis, keepdims=keepdim, dtype=dtype)


def nanquantile(x, q, axis=None, keepdim=False):
    return jnp.nanquantile(x, q, axis=axis, keepdims=keepdim)


def quantile(x, q, axis=None, keepdim=False):
    return jnp.quantile(x, q, axis=axis, keepdims=keepdim)


def logsumexp(x, axis=None, keepdim=False):
    return jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdim)


def count_nonzero(x, axis=None, keepdim=False):
    return jnp.count_nonzero(x, axis=axis, keepdims=keepdim)


def kthvalue(x, k, axis=-1, keepdim=False):
    """Returns (values, indices) of the k-th smallest along axis (1-based)."""
    idx = jnp.argsort(x, axis=axis)
    kth_idx = jnp.take(idx, k - 1, axis=axis)
    vals = jnp.take_along_axis(
        x, jnp.expand_dims(kth_idx, axis), axis=axis)
    if not keepdim:
        vals = jnp.squeeze(vals, axis)
    return vals, kth_idx


def histogram(x, bins=100, min=0, max=0, weight=None, density=False):
    """Reference semantics: min==max==0 → use data range."""
    if min == 0 and max == 0:
        lo, hi = jnp.min(x), jnp.max(x)
    else:
        lo, hi = min, max
    hist, _ = jnp.histogram(x, bins=bins, range=(lo, hi), weights=weight,
                            density=density)
    return hist


def bincount(x, weights=None, minlength=0):
    return jnp.bincount(x, weights=weights, minlength=minlength)


# ---- cumulative -------------------------------------------------------------


def cumprod(x, dim=None, dtype=None):
    return jnp.cumprod(x, axis=dim, dtype=dtype)


def _cum_with_indices(x, axis, is_max):
    """(values, indices) running max/min via an associative pair-scan."""
    n = x.shape[axis]
    idx = jnp.arange(n)
    idx = jnp.reshape(idx, [-1 if i == (axis % x.ndim) else 1
                            for i in range(x.ndim)])
    idx = jnp.broadcast_to(idx, x.shape)

    def combine(a, b):
        av, ai = a
        bv, bi = b
        if is_max:
            take_b = bv >= av
        else:
            take_b = bv <= av
        return jnp.where(take_b, bv, av), jnp.where(take_b, bi, ai)

    vals, inds = lax.associative_scan(combine, (x, idx), axis=axis)
    return vals, inds


def cummax(x, axis=None, dtype="int64"):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return _cum_with_indices(x, axis, is_max=True)


def cummin(x, axis=None, dtype="int64"):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return _cum_with_indices(x, axis, is_max=False)


def logcumsumexp(x, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return lax.cumlogsumexp(x, axis=axis)


def diff(x, n=1, axis=-1, prepend=None, append=None):
    return jnp.diff(x, n=n, axis=axis, prepend=prepend, append=append)


def trapezoid(y, x=None, dx=1.0, axis=-1):
    return jax.scipy.integrate.trapezoid(y, x=x, dx=dx, axis=axis)


# ---- matrix-ish one-liners kept in paddle.* root ----------------------------


def addmm(input, x, y, beta=1.0, alpha=1.0):
    return beta * input + alpha * (x @ y)


def inner(x, y):
    return jnp.inner(x, y)


def kron(x, y):
    return jnp.kron(x, y)


def cross(x, y, axis=-1):
    return jnp.cross(x, y, axis=axis)


def trace(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


def diagonal(x, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


def tensordot(x, y, axes=2):
    return jnp.tensordot(x, y, axes=axes)


def vander(x, n=None, increasing=False):
    return jnp.vander(x, N=n, increasing=increasing)


def corrcoef(x, rowvar=True):
    return jnp.corrcoef(x, rowvar=rowvar)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0,
                   fweights=fweights, aweights=aweights)


def cdist(x, y, p=2.0):
    """Pairwise p-norm distances: x (..., m, d), y (..., n, d) → (..., m, n)."""
    diffs = x[..., :, None, :] - y[..., None, :, :]
    if p == 2.0:
        return jnp.sqrt(jnp.sum(diffs * diffs, axis=-1))
    return jnp.sum(jnp.abs(diffs) ** p, axis=-1) ** (1.0 / p)


def dist(x, y, p=2.0):
    return jnp.linalg.norm((x - y).reshape(-1), ord=p)
