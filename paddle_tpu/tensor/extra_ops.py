"""Tensor-API long tail (VERDICT r2 #7) — the breadth users trip on when
porting: set ops, window/sliding ops, masked scatter forms, complex views,
batched matmul variants, statistics.

Reference: python/paddle/tensor/{math,manipulation,linalg,stat}.py veneers
over phi kernels (SURVEY.md §2.7 counts ~400 public tensor functions).
Each op here is a jnp composition XLA fuses; ops with data-dependent
output shapes (unique_consecutive, combinations' input) follow the same
eager-outside-jit contract as `tensor.unique`.
"""

import itertools
import math as _math

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "as_strided", "baddbmm", "block_diag", "cartesian_prod",
    "combinations", "cumulative_trapezoid", "diagonal_scatter", "fliplr",
    "flipud", "frac_", "histogramdd", "index_sample",
    "is_complex", "is_floating_point", "is_integer", "isin", "logaddexp2",
    "logit", "masked_scatter", "mm", "mode", "mv", "pdist",
    "pinverse", "polar", "positive", "ravel", "renorm",
    "sgn", "sinc", "tolist", "unique_consecutive",
    "unfold", "vdot", "view_as_complex", "view_as_real",
    "exp2", "float_power", "true_divide", "bitwise_invert", "gammaln",
    "gammainc", "erfc", "xlogy", "aminmax", "broadcast_shapes", "crop",
    "strided_slice",
    # round-5 tail (VERDICT r4 #2)
    "complex", "is_tensor", "is_empty", "t", "slice", "add_n",
    "histogram_bin_edges", "finfo", "iinfo", "binomial", "standard_gamma",
    "log_normal", "randint_like",
    "angle", "assign", "clone", "rank", "increment", "scale", "softsign",
    "logspace", "histc", "unstack", "view", "view_as", "swapdims",
    "shard_index", "reduce_as", "multigammaln", "lu_solve",
    "standard_normal", "bernoulli", "poisson", "multinomial",
    "gammaincc", "negative",
]


# ---- views / predicates ----------------------------------------------------

def is_complex(x):
    return jnp.iscomplexobj(x)


def is_floating_point(x):
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(jnp.asarray(x).dtype, jnp.integer)


def view_as_real(x):
    """(..., ) complex → (..., 2) real."""
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


def view_as_complex(x):
    """(..., 2) real → (...,) complex."""
    return jax.lax.complex(x[..., 0], x[..., 1])


def polar(abs_, angle):
    return jax.lax.complex(abs_ * jnp.cos(angle), abs_ * jnp.sin(angle))


def positive(x):
    return +jnp.asarray(x)


def ravel(x):
    return jnp.ravel(x)


def tolist(x):
    # tpu-lint: allow(host-sync): tolist IS a host conversion by contract
    return np.asarray(x).tolist()


def sgn(x):
    """Sign; for complex inputs x/|x| (0 stays 0) — the reference's sgn."""
    x = jnp.asarray(x)
    if jnp.iscomplexobj(x):
        mag = jnp.abs(x)
        return jnp.where(mag == 0, 0, x / jnp.where(mag == 0, 1, mag))
    return jnp.sign(x)


def sinc(x):
    return jnp.sinc(x)


def logaddexp2(x, y):
    return jnp.logaddexp2(x, y)


def logit(x, eps=None):
    if eps is not None:
        x = jnp.clip(x, eps, 1.0 - eps)
    return jax.scipy.special.logit(x)


def frac_(x):
    return x - jnp.trunc(x)


# ---- matmul family ---------------------------------------------------------

def mm(x, y):
    return jnp.matmul(x, y)


def mv(x, vec):
    return jnp.matmul(x, vec)


def vdot(x, y):
    return jnp.vdot(x, y)


def baddbmm(input, x, y, beta=1.0, alpha=1.0):
    return beta * input + alpha * jnp.matmul(x, y)


def pinverse(x, rcond=1e-15):
    return jnp.linalg.pinv(x, rtol=rcond)


# ---- stacking / reshaping views -------------------------------------------

def fliplr(x):
    return jnp.fliplr(x)


def flipud(x):
    return jnp.flipud(x)


def block_diag(*inputs):
    return jax.scipy.linalg.block_diag(*inputs)


def cartesian_prod(*xs):
    grids = jnp.meshgrid(*xs, indexing="ij")
    return jnp.stack([g.ravel() for g in grids], axis=-1) \
        if len(xs) > 1 else xs[0]


def combinations(x, r=2, with_replacement=False):
    """All r-combinations of a 1-D tensor (static length)."""
    n = x.shape[0]
    it = (itertools.combinations_with_replacement(range(n), r)
          if with_replacement else itertools.combinations(range(n), r))
    idx = np.asarray(list(it), dtype=np.int32).reshape(-1, r)
    return jnp.take(x, jnp.asarray(idx), axis=0)


def as_strided(x, shape, stride, offset=0):
    """Strided view via gather (shape/stride are static python ints)."""
    flat = jnp.ravel(x)
    idx = np.full(tuple(shape), offset, dtype=np.int64)
    for d, (sz, st) in enumerate(zip(shape, stride)):
        expand = [1] * len(shape)
        expand[d] = sz
        idx = idx + np.arange(sz, dtype=np.int64).reshape(expand) * st
    return jnp.take(flat, jnp.asarray(idx))


def unfold(x, axis, size, step):
    """Sliding windows of `size` every `step` along `axis` (window dim
    appended last — the reference's layout)."""
    x = jnp.moveaxis(jnp.asarray(x), axis, -1)
    n = x.shape[-1]
    n_win = (n - size) // step + 1
    starts = np.arange(n_win) * step
    idx = starts[:, None] + np.arange(size)[None, :]      # (n_win, size)
    out = jnp.take(x, jnp.asarray(idx), axis=-1)          # (..., n_win, size)
    return jnp.moveaxis(out, -2, axis)


# ---- scatter views ---------------------------------------------------------

def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1):
    x = jnp.asarray(x)
    xm = jnp.moveaxis(x, (axis1, axis2), (-2, -1))
    n, m = xm.shape[-2:]
    rows = np.arange(max(n, m))
    r = rows[(rows + max(0, offset) < m) & (rows - min(0, offset) < n)]
    ii = r - min(0, offset)
    jj = r + max(0, offset)
    xm = xm.at[..., ii, jj].set(jnp.moveaxis(jnp.asarray(y), -1, -1))
    return jnp.moveaxis(xm, (-2, -1), (axis1, axis2))


def index_sample(x, index):
    """x (N, D), index (N, M) int → (N, M): per-row gather (reference
    paddle.index_sample)."""
    return jnp.take_along_axis(jnp.asarray(x), jnp.asarray(index), axis=1)


def masked_scatter(x, mask, value):
    """Fill True positions of `mask` with consecutive elements of
    `value` (row-major), like the reference/torch masked_scatter."""
    x = jnp.asarray(x)
    mask = jnp.broadcast_to(jnp.asarray(mask, bool), x.shape)
    flat_m = mask.ravel()
    src = jnp.asarray(value).ravel()
    if not isinstance(flat_m, jax.core.Tracer):   # eager: enforce like ref
        # tpu-lint: allow(host-sync): tracer-guarded eager-only validation
        need = int(np.asarray(flat_m).sum())
        if src.shape[0] < need:
            raise ValueError(
                f"masked_scatter: value has {src.shape[0]} elements but "
                f"mask selects {need}")
    pos = jnp.cumsum(flat_m) - 1
    gathered = jnp.take(src, jnp.clip(pos, 0, src.shape[0] - 1))
    return jnp.where(flat_m, gathered, x.ravel()).reshape(x.shape)


# ---- set / search ops ------------------------------------------------------

def isin(x, test_x, assume_unique=False, invert=False):
    return jnp.isin(jnp.asarray(x), jnp.asarray(test_x), invert=invert)


def unique_consecutive(x, return_inverse=False, return_counts=False,
                       axis=None):
    """Collapse consecutive duplicates (eager: data-dependent output
    shape, same contract as tensor.unique)."""
    # tpu-lint: allow(host-sync): eager op — data-dependent output shape
    xn = np.asarray(x)
    if axis is None:
        xn = xn.ravel()
        axis = 0
    moved = np.moveaxis(xn, axis, 0)
    flat = moved.reshape(moved.shape[0], -1)
    if flat.shape[0] == 0:
        keep = np.zeros(0, bool)
    else:
        keep = np.concatenate([[True], np.any(flat[1:] != flat[:-1],
                                              axis=1)])
    out = jnp.asarray(np.moveaxis(moved[keep], 0, axis))
    res = (out,)
    if return_inverse:
        res += (jnp.asarray(np.cumsum(keep) - 1),)
    if return_counts:
        starts = np.flatnonzero(keep)
        counts = np.diff(np.append(starts, flat.shape[0]))
        res += (jnp.asarray(counts),)
    return res if len(res) > 1 else out


def mode(x, axis=-1, keepdim=False):
    """(values, indices) of the most frequent element along `axis`; ties
    break toward the smallest value (reference semantics)."""
    x = jnp.asarray(x)
    xs = jnp.sort(x, axis=axis)
    # count occurrences of each sorted element: O(n^2) along axis — API
    # parity for modest sizes (the reference kernel is O(n log n))
    a = jnp.moveaxis(x, axis, -1)
    s = jnp.moveaxis(xs, axis, -1)
    cnt = jnp.sum(s[..., :, None] == a[..., None, :], axis=-1)
    best = jnp.argmax(cnt, axis=-1)                  # first max = smallest
    vals = jnp.take_along_axis(s, best[..., None], axis=-1)[..., 0]
    idx = jnp.argmax(jnp.moveaxis(x, axis, -1) == vals[..., None], axis=-1)
    if keepdim:
        vals = jnp.expand_dims(vals, axis)
        idx = jnp.expand_dims(idx, axis)
    return vals, idx


# ---- statistics ------------------------------------------------------------

def histogramdd(x, bins=10, ranges=None, density=False, weights=None):
    return jnp.histogramdd(x, bins=bins, range=ranges, density=density,
                           weights=weights)


def cumulative_trapezoid(y, x=None, dx=1.0, axis=-1):
    y = jnp.asarray(y)
    ym = jnp.moveaxis(y, axis, -1)
    mids = (ym[..., 1:] + ym[..., :-1]) / 2.0
    if x is not None:
        xd = jnp.diff(jnp.moveaxis(jnp.asarray(x), axis, -1), axis=-1)
        mids = mids * xd
    else:
        mids = mids * dx
    return jnp.moveaxis(jnp.cumsum(mids, axis=-1), -1, axis)


def pdist(x, p=2.0):
    """Condensed pairwise distances of (N, D) rows."""
    n = x.shape[0]
    ii, jj = np.triu_indices(n, k=1)
    diff = x[jnp.asarray(ii)] - x[jnp.asarray(jj)]
    if p == 2.0:
        return jnp.sqrt(jnp.sum(diff * diff, axis=-1))
    return jnp.sum(jnp.abs(diff) ** p, axis=-1) ** (1.0 / p)


def renorm(x, p, axis, max_norm):
    """Scale each slice along `axis` whose p-norm exceeds max_norm down to
    exactly max_norm."""
    x = jnp.asarray(x)
    xm = jnp.moveaxis(x, axis, 0).reshape(x.shape[axis], -1)
    norms = jnp.sum(jnp.abs(xm) ** p, axis=1) ** (1.0 / p)
    scale = jnp.where(norms > max_norm,
                      max_norm / jnp.maximum(norms, 1e-12), 1.0)
    shape = [1] * x.ndim
    shape[axis] = x.shape[axis]
    return x * scale.reshape(shape).astype(x.dtype)


# ---- elementwise stragglers -------------------------------------------------

def exp2(x):
    return jnp.exp2(x)


def float_power(x, y):
    return jnp.float_power(x, y)


def true_divide(x, y):
    return jnp.true_divide(x, y)


def bitwise_invert(x):
    return jnp.invert(x)


def gammaln(x):
    return jax.scipy.special.gammaln(x)


def gammainc(a, x):
    return jax.scipy.special.gammainc(a, x)


def erfc(x):
    return jax.scipy.special.erfc(x)


def xlogy(x, y):
    return jax.scipy.special.xlogy(x, y)


def aminmax(x, axis=None, keepdim=False):
    return (jnp.min(x, axis=axis, keepdims=keepdim),
            jnp.max(x, axis=axis, keepdims=keepdim))


def broadcast_shapes(*shapes):
    return jnp.broadcast_shapes(*shapes)


def crop(x, shape, offsets=None):
    """Static crop (reference paddle.crop): take `shape` starting at
    `offsets` (zeros when omitted)."""
    offsets = offsets or [0] * len(shape)
    idx = tuple(_py_slice(o, o + s) for o, s in zip(offsets, shape))
    return jnp.asarray(x)[idx]


def strided_slice(x, axes, starts, ends, strides):
    idx = [_py_slice(None)] * jnp.asarray(x).ndim
    for a, s, e, st in zip(axes, starts, ends, strides):
        idx[a] = _py_slice(s, e, st)
    return jnp.asarray(x)[tuple(idx)]


# ---- round-3 second batch: real paddle APIs still missing ------------------

def angle(x):
    return jnp.angle(x)


def assign(x, output=None):
    """Functional assign (returns a copy; paddle's in-place form has no
    meaning for immutable jax arrays — callers rebind)."""
    return jnp.array(jnp.asarray(x), copy=True)


clone = assign


def rank(x):
    return jnp.asarray(jnp.asarray(x).ndim)


def increment(x, value=1.0):
    return jnp.asarray(x) + value


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None):
    x = jnp.asarray(x)
    out = x * scale + bias if bias_after_scale else (x + bias) * scale
    if act is not None:
        out = getattr(jax.nn, act)(out)
    return out


def softsign(x):
    return jax.nn.soft_sign(x)


def logspace(start, stop, num, base=10.0, dtype=None):
    return jnp.logspace(start, stop, int(num), base=base, dtype=dtype)


def histc(x, bins=100, min=0.0, max=0.0):
    x = jnp.asarray(x).ravel()
    if min == 0.0 and max == 0.0:
        lo, hi = jnp.min(x), jnp.max(x)
    else:
        lo, hi = min, max
    return jnp.histogram(x, bins=bins, range=(lo, hi))[0]


def unstack(x, axis=0, num=None):
    x = jnp.asarray(x)
    n = num if num is not None else x.shape[axis]
    return [jnp.squeeze(s, axis=axis)
            for s in jnp.split(x, n, axis=axis)]


def view(x, shape_or_dtype):
    """Reshape view, or bitcast view when given a dtype (paddle.view:
    width-changing bitcasts fold into / split from the LAST dim)."""
    x = jnp.asarray(x)
    if isinstance(shape_or_dtype, (list, tuple)):
        return x.reshape(shape_or_dtype)
    src = jnp.dtype(x.dtype).itemsize
    dst = jnp.dtype(shape_or_dtype).itemsize
    if dst > src:                     # widening: lax requires the last dim
        ratio = dst // src            # grouped as (..., n//ratio, ratio)
        n = x.shape[-1]
        if n % ratio:
            raise ValueError(
                f"view: last dim {n} not divisible by the width ratio "
                f"{ratio} for {x.dtype} -> {jnp.dtype(shape_or_dtype).name}")
        x = x.reshape(x.shape[:-1] + (n // ratio, ratio))
    out = jax.lax.bitcast_convert_type(x, shape_or_dtype)
    if out.ndim == x.ndim + 1:        # narrowing: fold the new axis
        return out.reshape(x.shape[:-1] + (-1,))
    return out


def view_as(x, other):
    return jnp.asarray(x).reshape(jnp.asarray(other).shape)


def swapdims(x, axis1, axis2):
    return jnp.swapaxes(jnp.asarray(x), axis1, axis2)


def shard_index(x, index_num, nshards, shard_id, ignore_value=-1):
    """Map global ids to shard-local ids (reference paddle.shard_index)."""
    x = jnp.asarray(x)
    per = (index_num + nshards - 1) // nshards
    lo, hi = shard_id * per, (shard_id + 1) * per
    inside = (x >= lo) & (x < hi)
    return jnp.where(inside, x - lo, ignore_value)


def reduce_as(x, target):
    """Sum-reduce x down to target's shape (reference paddle.reduce_as)."""
    x = jnp.asarray(x)
    t = jnp.asarray(target)
    lead = x.ndim - t.ndim
    axes = tuple(range(lead)) + tuple(
        lead + i for i, (sx, st) in enumerate(zip(x.shape[lead:], t.shape))
        if st == 1 and sx != 1)
    out = jnp.sum(x, axis=axes, keepdims=False)
    return out.reshape(t.shape)


def multigammaln(x, p):
    return jax.scipy.special.multigammaln(x, p)


def lu_solve(b, lu_data, lu_pivots):
    """Solve A x = b from lu()'s factorization (1-based pivots)."""
    return jax.scipy.linalg.lu_solve(
        (jnp.asarray(lu_data), jnp.asarray(lu_pivots) - 1), jnp.asarray(b))


# random-family: SAME "default" stream and default-dtype handling as
# tensor.rand/randn (rng_guard frames under jit work identically)
def _next_key():
    from paddle_tpu.core import rng as _rng
    return _rng.next_rng_key()


def standard_normal(shape, dtype=None):
    from paddle_tpu.core.dtype import get_default_dtype, to_jax_dtype
    return jax.random.normal(
        _next_key(), tuple(shape),
        dtype=to_jax_dtype(dtype) if dtype else get_default_dtype())


def bernoulli(x):
    x = jnp.asarray(x)
    return jax.random.bernoulli(_next_key(), x).astype(x.dtype)


def poisson(x):
    x = jnp.asarray(x)
    return jax.random.poisson(_next_key(), x).astype(x.dtype)


def multinomial(x, num_samples=1, replacement=False):
    x = jnp.asarray(x)
    logits = jnp.log(jnp.maximum(x, 1e-30))
    if replacement:
        if x.ndim > 1:
            out = jax.random.categorical(
                _next_key(), logits, axis=-1,
                shape=(num_samples,) + x.shape[:-1])
            return jnp.moveaxis(out, 0, -1)   # samples axis last, any rank
        return jax.random.categorical(
            _next_key(), logits, shape=(num_samples,))
    if not isinstance(x, jax.core.Tracer):   # eager: enforce like ref
        # tpu-lint: allow(host-sync): tracer-guarded eager-only validation
        nz = int(np.asarray((x > 0).sum(-1).min()))
        if num_samples > nz:
            raise ValueError(
                f"multinomial(replacement=False): num_samples "
                f"{num_samples} exceeds the {nz} nonzero-weight "
                "categories")
    # without replacement: Gumbel top-k
    g = jax.random.gumbel(_next_key(), x.shape)
    return jax.lax.top_k(logits + g, num_samples)[1]


def gammaincc(x, y):
    """Regularized upper incomplete gamma (reference paddle.gammaincc)."""
    return jax.scipy.special.gammaincc(jnp.asarray(x), jnp.asarray(y))


def negative(x):
    return jnp.negative(jnp.asarray(x))


# ---------------------------------------------------------------------------
# round-5 breadth tail (VERDICT r4 #2): remaining public tensor-namespace
# APIs — reference python/paddle/tensor/{creation,random,attribute,math}.py
# ---------------------------------------------------------------------------

def complex(real, imag):
    """paddle.complex: real + 1j*imag (broadcasting; ints promote to
    float32 like the reference)."""
    real = jnp.asarray(real)
    if not jnp.issubdtype(real.dtype, jnp.floating):
        real = real.astype(jnp.float32)
    imag = jnp.asarray(imag).astype(real.dtype)
    return jax.lax.complex(*jnp.broadcast_arrays(real, imag))


def is_tensor(x):
    """paddle.is_tensor."""
    return isinstance(x, (jax.Array, np.ndarray))


def is_empty(x):
    """paddle.is_empty: whether the tensor holds zero elements."""
    return jnp.asarray(jnp.asarray(x).size == 0)


def t(x):
    """paddle.t: 0/1-D unchanged; 2-D transposed; >2-D is an error."""
    x = jnp.asarray(x)
    if x.ndim > 2:
        raise ValueError(
            f"paddle.t expects a tensor with rank <= 2, got {x.ndim}")
    return x.T if x.ndim == 2 else x


_py_slice = slice      # the builtin; shadowed by the reference API below


def slice(input, axes, starts, ends):   # noqa: A001 - reference name
    """paddle.slice: slice `input` along `axes` from starts to ends
    (negative indices wrap; ends clamp to the dim)."""
    x = jnp.asarray(input)
    idx = [jnp.s_[:]] * x.ndim
    for ax, s, e in zip(axes, starts, ends):
        d = x.shape[ax]
        s = min(max(int(s) + d if int(s) < 0 else int(s), 0), d)
        e = min(max(int(e) + d if int(e) < 0 else int(e), 0), d)
        idx[ax] = jnp.s_[s:e]
    return x[tuple(idx)]


def add_n(inputs):
    """paddle.add_n: elementwise sum of a list of tensors."""
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    out = jnp.asarray(inputs[0])
    for v in inputs[1:]:
        out = out + jnp.asarray(v)
    return out


def histogram_bin_edges(input, bins=100, min=0, max=0):  # noqa: A002
    """paddle.histogram_bin_edges: uniform bin edges over [min, max]
    (both 0 -> the data range, like paddle.histogram)."""
    x = jnp.asarray(input).astype(jnp.float32)
    if min == 0 and max == 0:
        lo, hi = jnp.min(x), jnp.max(x)
        degenerate = lo == hi
        lo = jnp.where(degenerate, lo - 0.5, lo)
        hi = jnp.where(degenerate, hi + 0.5, hi)
    else:
        lo = jnp.asarray(min, jnp.float32)
        hi = jnp.asarray(max, jnp.float32)
    return lo + (hi - lo) * jnp.arange(bins + 1, dtype=jnp.float32) / bins


def finfo(dtype):
    """paddle.finfo (floating-point type limits; ml_dtypes-aware)."""
    from paddle_tpu.core.dtype import to_jax_dtype
    return jnp.finfo(to_jax_dtype(dtype))


def iinfo(dtype):
    """paddle.iinfo (integer type limits)."""
    from paddle_tpu.core.dtype import to_jax_dtype
    return jnp.iinfo(to_jax_dtype(dtype))


def binomial(count, prob):
    """paddle.binomial: per-element Binomial(count, prob) samples
    (int64, like the reference)."""
    count = jnp.asarray(count)
    prob = jnp.asarray(prob, jnp.float32)
    out = jax.random.binomial(_next_key(), count.astype(jnp.float32), prob)
    return out.astype(jnp.int_)    # int64 when x64 is enabled, else int32


def standard_gamma(x):
    """paddle.standard_gamma: elementwise Gamma(alpha=x, scale=1)."""
    x = jnp.asarray(x)
    return jax.random.gamma(_next_key(), x.astype(jnp.float32)).astype(
        x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else jnp.float32)


def log_normal(mean=1.0, std=2.0, shape=None):
    """paddle.log_normal: exp(Normal(mean, std)) samples of `shape`
    (mean/std are the parameters of the UNDERLYING normal)."""
    from paddle_tpu.core.dtype import get_default_dtype
    shape = (1,) if shape is None else tuple(shape)
    z = jax.random.normal(_next_key(), shape, dtype=jnp.float32)
    return jnp.exp(mean + std * z).astype(get_default_dtype())


def randint_like(x, low=0, high=None, dtype=None):
    """paddle.randint_like: uniform ints in [low, high) shaped like x."""
    from paddle_tpu.core.dtype import to_jax_dtype
    x = jnp.asarray(x)
    if high is None:
        low, high = 0, low
    out = jax.random.randint(_next_key(), x.shape, int(low), int(high))
    return out.astype(to_jax_dtype(dtype) if dtype else x.dtype)
