"""Transforms (≈ python/paddle/vision/transforms) — numpy/jnp host-side.

Input pipeline stage: operates on host images (PIL/numpy) BEFORE data
reaches the device; np conversions here are the contract, not syncs.
"""
# tpu-lint: allow-file(host-sync): host image pipeline by contract

import numpy as np


def _is_chw(x):
    """Channels-first heuristic: 3-D with a small leading dim. Ambiguous
    only for images whose height is 1/3/4 AND whose channel count is not —
    callers with such data should pass HWC (the dataset default)."""
    return x.ndim == 3 and x.shape[0] in (1, 3, 4) and \
        x.shape[-1] not in (1, 3, 4)


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class ToTensor:
    """HWC uint8 [0,255] → CHW float32 [0,1]."""

    def __call__(self, img):
        arr = np.asarray(img, dtype=np.float32) / 255.0
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(2, 0, 1)


class Normalize:
    def __init__(self, mean, std, data_format="CHW"):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, x):
        x = np.asarray(x, np.float32)
        if self.data_format == "CHW":
            return (x - self.mean[:, None, None]) / self.std[:, None, None]
        return (x - self.mean) / self.std


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.interpolation = interpolation

    def __call__(self, x):
        import jax
        import jax.numpy as jnp
        arr = jnp.asarray(x, jnp.float32)
        chw = _is_chw(arr)
        if chw:
            out_shape = (arr.shape[0],) + self.size
        else:
            out_shape = self.size + arr.shape[2:]
        method = {"bilinear": "linear", "nearest": "nearest"}.get(
            self.interpolation, self.interpolation)
        return np.asarray(jax.image.resize(arr, out_shape, method=method))


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, x):
        x = np.asarray(x)
        chw = _is_chw(x)
        h, w = (x.shape[1], x.shape[2]) if chw else (x.shape[0], x.shape[1])
        th, tw = self.size
        i, j = max(0, (h - th) // 2), max(0, (w - tw) // 2)
        return x[:, i:i + th, j:j + tw] if chw else x[i:i + th, j:j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, x):
        if np.random.rand() < self.prob:
            x = np.asarray(x)
            # width axis: last for 2-D/CHW, second-to-last for HWC
            axis = -1 if x.ndim == 2 or _is_chw(x) else -2
            return np.flip(x, axis=axis).copy()
        return x


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, x):
        if np.random.rand() < self.prob:
            x = np.asarray(x)
            chw = _is_chw(x)
            return (x[:, ::-1] if chw else x[::-1]).copy()
        return x


class Pad:
    """Pad all borders (reference transforms.Pad); HWC or CHW arrays."""

    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = ((padding, padding), (padding, padding)) \
            if isinstance(padding, int) else \
            ((padding[1], padding[3]), (padding[0], padding[2])) \
            if len(padding) == 4 else \
            ((padding[1], padding[1]), (padding[0], padding[0]))
        self.fill = fill
        self.mode = {"constant": "constant", "reflect": "reflect",
                     "edge": "edge", "symmetric": "symmetric"}[padding_mode]

    def __call__(self, x):
        x = np.asarray(x)
        chw = _is_chw(x)
        (pt, pb), (pl, pr) = self.padding
        if x.ndim == 2:
            cfg = [(pt, pb), (pl, pr)]
        elif chw:
            cfg = [(0, 0), (pt, pb), (pl, pr)]
        else:
            cfg = [(pt, pb), (pl, pr), (0, 0)]
        kw = {"constant_values": self.fill} if self.mode == "constant" else {}
        return np.pad(x, cfg, mode=self.mode, **kw)


class RandomCrop:
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill

    def __call__(self, x):
        x = np.asarray(x)
        if self.padding is not None:
            x = Pad(self.padding, fill=self.fill)(x)
        chw = _is_chw(x)
        h, w = (x.shape[1], x.shape[2]) if chw else (x.shape[0], x.shape[1])
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            ph, pw = max(th - h, 0), max(tw - w, 0)
            x = Pad((0, 0, pw, ph), fill=self.fill)(x)
            h, w = h + ph, w + pw
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return x[:, i:i + th, j:j + tw] if chw else x[i:i + th, j:j + tw]


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale, self.ratio = scale, ratio
        self.interpolation = interpolation

    def __call__(self, x):
        x = np.asarray(x)
        chw = _is_chw(x)
        h, w = (x.shape[1], x.shape[2]) if chw else (x.shape[0], x.shape[1])
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            tw = int(round(np.sqrt(target * ar)))
            th = int(round(np.sqrt(target / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = np.random.randint(0, h - th + 1)
                j = np.random.randint(0, w - tw + 1)
                crop = x[:, i:i + th, j:j + tw] if chw \
                    else x[i:i + th, j:j + tw]
                return Resize(self.size, self.interpolation)(crop)
        return Resize(self.size, self.interpolation)(CenterCrop(
            min(h, w))(x))


class Grayscale:
    """RGB → luma; num_output_channels 1 or 3 (reference Grayscale).
    Already-gray inputs (2-D, or 1-channel HWC/CHW) pass through with
    channel replication as requested."""

    def __init__(self, num_output_channels=1):
        self.n = num_output_channels

    def __call__(self, x):
        x = np.asarray(x)
        if x.ndim == 2:
            g, chw = x, False
        elif _is_chw(x):
            chw = True
            if x.shape[0] == 1:
                g = x[0]
            else:
                wts = np.float32([0.299, 0.587, 0.114])
                g = np.tensordot(wts, x[:3].astype(np.float32),
                                 axes=(0, 0)).astype(x.dtype)
        else:
            chw = False
            if x.shape[-1] == 1:
                g = x[..., 0]
            else:
                wts = np.float32([0.299, 0.587, 0.114])
                g = (x[..., :3].astype(np.float32) @ wts).astype(x.dtype)
        if chw:
            g = g[None]
            return np.repeat(g, self.n, axis=0) if self.n == 3 else g
        g = g[..., None]
        return np.repeat(g, self.n, axis=-1) if self.n == 3 else g


class ColorJitter:
    """Brightness/contrast/saturation jitter on HWC/CHW uint8 or float."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation
        # hue shift needs HSV conversion; approximated as disabled
        self.hue = hue

    @staticmethod
    def _factor(v):
        return np.random.uniform(max(0.0, 1 - v), 1 + v) if v else 1.0

    def __call__(self, x):
        x = np.asarray(x)
        dt = x.dtype
        xf = x.astype(np.float32)
        hi = 255.0 if np.issubdtype(dt, np.integer) else 1.0
        b, c, s = (self._factor(self.brightness), self._factor(self.contrast),
                   self._factor(self.saturation))
        xf = xf * b
        xf = (xf - xf.mean()) * c + xf.mean()
        chw = _is_chw(xf)
        gray = xf.mean(axis=0, keepdims=True) if chw else \
            xf.mean(axis=-1, keepdims=True)
        xf = (xf - gray) * s + gray
        return np.clip(xf, 0, hi).astype(dt)


class RandomRotation:
    """Random rotation via PIL (reference RandomRotation); HWC uint8."""

    def __init__(self, degrees, interpolation="nearest", expand=False,
                 fill=0):
        self.degrees = (-degrees, degrees) if np.isscalar(degrees) \
            else tuple(degrees)
        self.expand = expand
        self.fill = fill

    def __call__(self, x):
        from PIL import Image
        arr = np.asarray(x)
        angle = np.random.uniform(*self.degrees)
        img = Image.fromarray(arr.squeeze() if arr.ndim == 3 and
                              arr.shape[-1] == 1 else arr)
        out = np.asarray(img.rotate(angle, expand=self.expand,
                                    fillcolor=self.fill))
        if arr.ndim == 3 and arr.shape[-1] == 1:
            out = out[..., None]
        return out


class ToPILImage:
    def __call__(self, x):
        from PIL import Image
        arr = np.asarray(x)
        if arr.ndim == 3 and arr.shape[0] in (1, 3, 4):  # CHW → HWC
            arr = arr.transpose(1, 2, 0)
        if arr.dtype != np.uint8:
            arr = (np.clip(arr, 0, 1) * 255).astype(np.uint8)
        return Image.fromarray(arr.squeeze())
