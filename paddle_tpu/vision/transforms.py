"""Transforms (≈ python/paddle/vision/transforms) — numpy/jnp host-side."""

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = list(transforms)

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class ToTensor:
    """HWC uint8 [0,255] → CHW float32 [0,1]."""

    def __call__(self, img):
        arr = np.asarray(img, dtype=np.float32) / 255.0
        if arr.ndim == 2:
            arr = arr[:, :, None]
        return arr.transpose(2, 0, 1)


class Normalize:
    def __init__(self, mean, std, data_format="CHW"):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, x):
        x = np.asarray(x, np.float32)
        if self.data_format == "CHW":
            return (x - self.mean[:, None, None]) / self.std[:, None, None]
        return (x - self.mean) / self.std


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.interpolation = interpolation

    def __call__(self, x):
        import jax
        import jax.numpy as jnp
        arr = jnp.asarray(x, jnp.float32)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        if chw:
            out_shape = (arr.shape[0],) + self.size
        else:
            out_shape = self.size + arr.shape[2:]
        method = {"bilinear": "linear", "nearest": "nearest"}.get(
            self.interpolation, self.interpolation)
        return np.asarray(jax.image.resize(arr, out_shape, method=method))


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, x):
        x = np.asarray(x)
        chw = x.ndim == 3 and x.shape[0] in (1, 3, 4)
        h, w = (x.shape[1], x.shape[2]) if chw else (x.shape[0], x.shape[1])
        th, tw = self.size
        i, j = max(0, (h - th) // 2), max(0, (w - tw) // 2)
        return x[:, i:i + th, j:j + tw] if chw else x[i:i + th, j:j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, x):
        if np.random.rand() < self.prob:
            x = np.asarray(x)
            return x[..., ::-1].copy()
        return x
