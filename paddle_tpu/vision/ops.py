"""paddle.vision.ops parity — detection-family operators.

Reference: python/paddle/vision/ops.py over phi detection kernels
(SURVEY.md §2.7 vision extras). TPU-native shapes: the box math is pure
jnp (XLA fuses it); `roi_align`/`roi_pool` are bilinear/max gathers with
static sampling grids (MXU-free, bandwidth-bound — the right form for
TPU); `nms` follows the same eager-outside-jit contract as
`tensor.unique` (its output length is data-dependent; inside jit the
reference kernel is equally dynamic). Each op is validated against a
hand-rolled numpy oracle in tests/test_vision_ops.py.
"""

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["box_area", "box_iou", "nms", "roi_align", "roi_pool",
           "box_coder", "prior_box", "yolo_box", "deform_conv2d",
           "DeformConv2D", "distribute_fpn_proposals"]

from paddle_tpu.nn.layer import Layer
from paddle_tpu.nn import initializer as init


def box_area(boxes):
    """(N, 4) [x1, y1, x2, y2] → (N,) areas."""
    boxes = jnp.asarray(boxes)
    return (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])


def box_iou(boxes1, boxes2):
    """Pairwise IoU: (N, 4), (M, 4) → (N, M)."""
    boxes1 = jnp.asarray(boxes1)
    boxes2 = jnp.asarray(boxes2)
    lt = jnp.maximum(boxes1[:, None, :2], boxes2[None, :, :2])
    rb = jnp.minimum(boxes1[:, None, 2:], boxes2[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = box_area(boxes1)[:, None] + box_area(boxes2)[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """paddle.vision.ops.nms: greedy non-maximum suppression.

    Data-dependent output length → runs the greedy loop with a FIXED
    N-iteration lax.fori_loop over a suppression mask (jit-compatible
    core), then compacts eagerly. With `category_idxs`, suppression is
    per category (batched-NMS offset trick)."""
    boxes = jnp.asarray(boxes, jnp.float32)
    n = boxes.shape[0]
    if n == 0:
        # int32 like the non-empty path below — callers indexing with the
        # result must not see a dtype that depends on the input size
        return jnp.zeros((0,), jnp.int32)
    if scores is None:
        order = jnp.arange(n)
    else:
        order = jnp.argsort(-jnp.asarray(scores, jnp.float32),
                            stable=True)
    if category_idxs is not None:
        # disjoint coordinate offsets per category → one plain NMS
        cat = jnp.asarray(category_idxs)[order]
        span = jnp.max(boxes) - jnp.min(boxes) + 1.0
        shifted = boxes[order] + (cat.astype(jnp.float32)
                                  * span)[:, None]
    else:
        shifted = boxes[order]
    iou = box_iou(shifted, shifted)

    def body(i, keep):
        # suppress j > i when iou(i, j) > thr and i itself is kept
        row = (iou[i] > iou_threshold) & (jnp.arange(n) > i) & keep[i]
        return keep & ~row

    keep = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
    # tpu-lint: allow(host-sync): nms is eager by contract (the kept
    # count is data-dependent) — this pull realizes the keep mask
    kept = np.asarray(order)[np.asarray(keep)]
    if top_k is not None:
        kept = kept[:top_k]
    return jnp.asarray(kept, jnp.int32)


_roi_adaptive_warned = False


def _roi_align_grid(x, batch_idx, x1, y1, rw, rh, ph, pw, sry, srx):
    """roi_align over one group of RoIs with a fixed (sry, srx)
    samples/bin grid (static shapes — vmap-able)."""
    n, c, h, w = x.shape
    ys = (y1[:, None] + (jnp.arange(ph * sry) + 0.5)[None, :]
          * (rh[:, None] / (ph * sry)))
    xs = (x1[:, None] + (jnp.arange(pw * srx) + 0.5)[None, :]
          * (rw[:, None] / (pw * srx)))

    def bilinear(img, yy, xx):
        """img (c, h, w); yy (P,), xx (Q,) → (c, P, Q)."""
        vy = (yy >= -1.0) & (yy <= h)         # ref: >1px outside → 0
        vx = (xx >= -1.0) & (xx <= w)
        yy = jnp.clip(yy, 0.0, h - 1.0)
        xx = jnp.clip(xx, 0.0, w - 1.0)
        y0 = jnp.floor(yy).astype(jnp.int32)
        x0 = jnp.floor(xx).astype(jnp.int32)
        y1_ = jnp.minimum(y0 + 1, h - 1)
        x1_ = jnp.minimum(x0 + 1, w - 1)
        wy = yy - y0
        wx = xx - x0
        g = lambda yi, xi: img[:, yi, :][:, :, xi]
        out = (g(y0, x0) * (1 - wy)[None, :, None] * (1 - wx)[None, None]
               + g(y1_, x0) * wy[None, :, None] * (1 - wx)[None, None]
               + g(y0, x1_) * (1 - wy)[None, :, None] * wx[None, None]
               + g(y1_, x1_) * wy[None, :, None] * wx[None, None])
        return out * (vy[None, :, None] & vx[None, None]).astype(out.dtype)

    def one(bi, yy, xx):
        img = x[bi]
        s = bilinear(img, yy, xx)                   # (c, ph*sry, pw*srx)
        s = s.reshape(c, ph, sry, pw, srx)
        return s.mean(axis=(2, 4))

    return jax.vmap(one)(batch_idx, ys, xs)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """paddle.vision.ops.roi_align (NCHW): average of bilinear samples on
    a static grid per output bin.

    sampling_ratio<=0 reproduces the reference's ADAPTIVE grid —
    ceil(roi_h/pooled_h) × ceil(roi_w/pooled_w) samples per bin, per
    RoI — whenever the boxes are concrete (the common eager/predictor
    case): RoIs are grouped by grid size and each group runs the static
    vmap kernel. Under jit the boxes are traced (data-dependent shapes
    cannot be expressed), so the default falls back to a fixed 2
    samples/bin with a ONE-TIME warning; pass sampling_ratio explicitly
    for exact traced parity with a configured reference model. Samples
    falling more than one pixel outside the image contribute ZERO
    (reference semantics), nearer out-of-range samples clamp to the
    border."""
    x = jnp.asarray(x)
    concrete_boxes = not isinstance(boxes, jax.core.Tracer)
    boxes = jnp.asarray(boxes, jnp.float32)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    n, c, h, w = x.shape
    # batch index per roi from boxes_num
    # tpu-lint: allow(host-sync): boxes_num must be concrete (np.repeat)
    bn = np.asarray(boxes_num)
    batch_idx = jnp.asarray(np.repeat(np.arange(len(bn)), bn), jnp.int32)
    off = 0.5 if aligned else 0.0
    x1 = boxes[:, 0] * spatial_scale - off
    y1 = boxes[:, 1] * spatial_scale - off
    x2 = boxes[:, 2] * spatial_scale - off
    y2 = boxes[:, 3] * spatial_scale - off
    rw = x2 - x1
    rh = y2 - y1
    if not aligned:
        rw = jnp.maximum(rw, 1.0)
        rh = jnp.maximum(rh, 1.0)
    if sampling_ratio > 0:
        return _roi_align_grid(x, batch_idx, x1, y1, rw, rh, ph, pw,
                               sampling_ratio, sampling_ratio)
    if not concrete_boxes:
        global _roi_adaptive_warned
        if not _roi_adaptive_warned:
            _roi_adaptive_warned = True
            import warnings
            warnings.warn(
                "roi_align: sampling_ratio<=0 under jit uses a fixed 2 "
                "samples/bin (the reference's adaptive ceil(roi/pooled) "
                "grid needs concrete boxes); pass sampling_ratio "
                "explicitly to pin the grid and silence this warning")
        return _roi_align_grid(x, batch_idx, x1, y1, rw, rh, ph, pw, 2, 2)
    # reference-exact adaptive grid: group RoIs by their
    # (ceil(rh/ph), ceil(rw/pw)) sample counts, run each group static
    # tpu-lint: allow(host-sync): concrete-boxes eager path only — the
    # adaptive grid groups RoIs by host-computed sample counts
    rh_np, rw_np = np.asarray(rh), np.asarray(rw)
    sry = np.maximum(np.ceil(rh_np / ph), 1).astype(np.int64)
    srx = np.maximum(np.ceil(rw_np / pw), 1).astype(np.int64)
    # same output dtype as the fixed-grid paths (f32 coords promote the
    # bilinear math), so eager/adaptive and jit/fallback results agree
    odt = jnp.result_type(x.dtype, jnp.float32)
    out = jnp.zeros((boxes.shape[0], c, ph, pw), odt)
    for sy, sx in sorted(set(zip(sry.tolist(), srx.tolist()))):
        sel = np.where((sry == sy) & (srx == sx))[0]
        idx = jnp.asarray(sel, jnp.int32)
        sub = _roi_align_grid(x, batch_idx[idx], x1[idx], y1[idx],
                              rw[idx], rh[idx], ph, pw, int(sy), int(sx))
        out = out.at[idx].set(sub.astype(out.dtype))
    return out


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0):
    """paddle.vision.ops.roi_pool (max pooling over quantized bins).
    Implemented as a dense bin-membership max (TPU-friendly: no dynamic
    shapes)."""
    x = jnp.asarray(x)
    boxes = jnp.asarray(boxes, jnp.float32)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    n, c, h, w = x.shape
    # tpu-lint: allow(host-sync): boxes_num must be concrete (np.repeat)
    bn = np.asarray(boxes_num)
    batch_idx = jnp.asarray(np.repeat(np.arange(len(bn)), bn), jnp.int32)
    x1 = jnp.round(boxes[:, 0] * spatial_scale).astype(jnp.int32)
    y1 = jnp.round(boxes[:, 1] * spatial_scale).astype(jnp.int32)
    x2 = jnp.round(boxes[:, 2] * spatial_scale).astype(jnp.int32)
    y2 = jnp.round(boxes[:, 3] * spatial_scale).astype(jnp.int32)
    rw = jnp.maximum(x2 - x1 + 1, 1)
    rh = jnp.maximum(y2 - y1 + 1, 1)

    hh = jnp.arange(h)
    ww = jnp.arange(w)

    def one(bi, x1_, y1_, rw_, rh_):
        img = x[bi]                                   # (c, h, w)
        # reference bin boundaries OVERLAP: bin i spans rows
        # [floor(i·rh/ph), ceil((i+1)·rh/ph)) relative to y1 — a pixel on
        # a fractional boundary belongs to BOTH adjacent bins
        bi_ = jnp.arange(ph)[:, None]
        rel_y = (hh - y1_)[None, :]                   # (1, h)
        ylo = jnp.floor(bi_ * rh_ / ph)
        yhi = jnp.ceil((bi_ + 1) * rh_ / ph)
        ymask = ((rel_y >= ylo) & (rel_y < yhi)
                 & (rel_y >= 0) & (rel_y < rh_))      # (ph, h)
        bj = jnp.arange(pw)[:, None]
        rel_x = (ww - x1_)[None, :]
        xlo = jnp.floor(bj * rw_ / pw)
        xhi = jnp.ceil((bj + 1) * rw_ / pw)
        xmask = ((rel_x >= xlo) & (rel_x < xhi)
                 & (rel_x >= 0) & (rel_x < rw_))      # (pw, w)
        m = ymask[:, None, :, None] & xmask[None, :, None, :]
        vals = jnp.where(m[None], img[:, None, None], -jnp.inf)
        out = vals.max(axis=(3, 4))
        return jnp.where(jnp.isfinite(out), out, 0.0)

    return jax.vmap(one)(batch_idx, x1, y1, rw, rh)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True):
    """paddle.vision.ops.box_coder: encode/decode boxes against priors."""
    pb = jnp.asarray(prior_box, jnp.float32)
    tb = jnp.asarray(target_box, jnp.float32)
    var = (jnp.asarray(prior_box_var, jnp.float32)
           if prior_box_var is not None else None)
    norm = 0.0 if box_normalized else 1.0
    pw = pb[:, 2] - pb[:, 0] + norm
    phh = pb[:, 3] - pb[:, 1] + norm
    pcx = pb[:, 0] + pw * 0.5
    pcy = pb[:, 1] + phh * 0.5
    if code_type == "encode_center_size":
        tw = tb[:, 2] - tb[:, 0] + norm
        th = tb[:, 3] - tb[:, 1] + norm
        tcx = tb[:, 0] + tw * 0.5
        tcy = tb[:, 1] + th * 0.5
        out = jnp.stack([(tcx - pcx) / pw, (tcy - pcy) / phh,
                         jnp.log(tw / pw), jnp.log(th / phh)], axis=1)
        if var is not None:
            out = out / var
        return out
    # decode: target (N, 4) deltas against priors
    d = tb * var if var is not None else tb
    cx = d[:, 0] * pw + pcx
    cy = d[:, 1] * phh + pcy
    bw = jnp.exp(d[:, 2]) * pw
    bh = jnp.exp(d[:, 3]) * phh
    return jnp.stack([cx - bw * 0.5, cy - bh * 0.5,
                      cx + bw * 0.5 - norm, cy + bh * 0.5 - norm], axis=1)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5):
    """paddle.vision.ops.prior_box (SSD anchors). input (n, c, h, w),
    image (n, c, ih, iw) → (h, w, num_priors, 4), (h, w, num_priors, 4)."""
    h, w = jnp.asarray(input).shape[2:]
    ih, iw = jnp.asarray(image).shape[2:]
    sw = steps[0] or iw / w
    sh = steps[1] or ih / h
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - a) < 1e-6 for a in ars):
            ars.append(ar)
            if flip:
                ars.append(1.0 / ar)
    if max_sizes and len(max_sizes) != len(min_sizes):
        raise ValueError(
            f"prior_box: max_sizes ({len(max_sizes)}) must pair 1:1 with "
            f"min_sizes ({len(min_sizes)}) — the reference zips them")
    whs = []
    for i, ms in enumerate(min_sizes):
        for ar in ars:
            whs.append((ms * np.sqrt(ar), ms / np.sqrt(ar)))
        if max_sizes:
            mx = max_sizes[i]               # paired, not cross-product
            whs.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
    # tpu-lint: allow(host-sync): host anchor table (python lists in)
    whs = np.asarray(whs, np.float32)                 # (np_, 2)
    cx = (np.arange(w) + offset) * sw
    cy = (np.arange(h) + offset) * sh
    cxg, cyg = np.meshgrid(cx, cy)                    # (h, w)
    n_p = whs.shape[0]
    out = np.zeros((h, w, n_p, 4), np.float32)
    out[..., 0] = (cxg[:, :, None] - whs[None, None, :, 0] / 2) / iw
    out[..., 1] = (cyg[:, :, None] - whs[None, None, :, 1] / 2) / ih
    out[..., 2] = (cxg[:, :, None] + whs[None, None, :, 0] / 2) / iw
    out[..., 3] = (cyg[:, :, None] + whs[None, None, :, 1] / 2) / ih
    if clip:
        out = np.clip(out, 0.0, 1.0)
    # tpu-lint: allow(host-sync): host anchor table (python lists in)
    var = np.broadcast_to(np.asarray(variance, np.float32),
                          out.shape).copy()
    return jnp.asarray(out), jnp.asarray(var)


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, scale_x_y=1.0):
    """paddle.vision.ops.yolo_box: decode YOLOv3 head outputs.
    x (n, an*(5+cls), h, w) → (boxes (n, h*w*an, 4),
    scores (n, h*w*an, cls))."""
    x = jnp.asarray(x, jnp.float32)
    n, _, h, w = x.shape
    an = len(anchors) // 2
    # tpu-lint: allow(host-sync): anchors is a host python list
    anc = jnp.asarray(np.asarray(anchors, np.float32).reshape(an, 2))
    p = x.reshape(n, an, 5 + class_num, h, w)
    gx = (jnp.arange(w) + 0.0)[None, None, None, :]
    gy = (jnp.arange(h) + 0.0)[None, None, :, None]
    sig = jax.nn.sigmoid
    bx = (gx + sig(p[:, :, 0]) * scale_x_y
          - (scale_x_y - 1) * 0.5) / w
    by = (gy + sig(p[:, :, 1]) * scale_x_y
          - (scale_x_y - 1) * 0.5) / h
    in_w = w * downsample_ratio
    in_h = h * downsample_ratio
    bw = jnp.exp(p[:, :, 2]) * anc[None, :, 0, None, None] / in_w
    bh = jnp.exp(p[:, :, 3]) * anc[None, :, 1, None, None] / in_h
    conf = sig(p[:, :, 4])
    cls = sig(p[:, :, 5:]) * conf[:, :, None]
    img_size = jnp.asarray(img_size, jnp.float32)      # (n, 2) [h, w]
    imh = img_size[:, 0][:, None, None, None]
    imw = img_size[:, 1][:, None, None, None]
    x1 = (bx - bw / 2) * imw
    y1 = (by - bh / 2) * imh
    x2 = (bx + bw / 2) * imw
    y2 = (by + bh / 2) * imh
    if clip_bbox:
        x1 = jnp.clip(x1, 0)
        y1 = jnp.clip(y1, 0)
        x2 = jnp.minimum(x2, imw - 1)
        y2 = jnp.minimum(y2, imh - 1)
    # ANCHOR-MAJOR flatten (reference kernel layout: idx = a·h·w + r·w + c)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1)       # (n, an, h, w, 4)
    boxes = boxes.reshape(n, -1, 4)
    # mask out low-confidence predictions like the reference (zeroed)
    keep = (conf > conf_thresh)
    cls = jnp.where(keep[:, :, None], cls, 0.0)        # (n, an, cls, h, w)
    scores = cls.transpose(0, 1, 3, 4, 2).reshape(n, -1, class_num)
    boxes = boxes * (scores.sum(-1, keepdims=True) > 0)
    return boxes, scores


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None):
    """paddle.vision.ops.deform_conv2d (DCNv1; DCNv2 with `mask`):
    bilinear-sample the input at offset positions, then a dense matmul —
    the gather+MXU form TPU wants. x (n, cin, h, w); offset
    (n, 2*dg*kh*kw, oh, ow); weight (cout, cin/groups, kh, kw)."""
    x = jnp.asarray(x)
    offset = jnp.asarray(offset, jnp.float32)
    weight = jnp.asarray(weight)
    st = (stride, stride) if isinstance(stride, int) else tuple(stride)
    pa = (padding, padding) if isinstance(padding, int) else tuple(padding)
    di = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)
    n, cin, h, w = x.shape
    cout, cin_g, kh, kw = weight.shape
    oh = (h + 2 * pa[0] - di[0] * (kh - 1) - 1) // st[0] + 1
    ow = (w + 2 * pa[1] - di[1] * (kw - 1) - 1) // st[1] + 1
    dg = deformable_groups
    xp = jnp.pad(x, ((0, 0), (0, 0), (pa[0], pa[0]), (pa[1], pa[1])))
    hp, wp = xp.shape[2:]
    # sampling positions: (oh, ow, kh, kw)
    gy = (jnp.arange(oh) * st[0])[:, None, None, None] + \
        (jnp.arange(kh) * di[0])[None, None, :, None]
    gx = (jnp.arange(ow) * st[1])[None, :, None, None] + \
        (jnp.arange(kw) * di[1])[None, None, None, :]
    off = offset.reshape(n, dg, kh, kw, 2, oh, ow)
    oy = off[:, :, :, :, 0].transpose(0, 1, 4, 5, 2, 3)  # (n,dg,oh,ow,kh,kw)
    ox = off[:, :, :, :, 1].transpose(0, 1, 4, 5, 2, 3)
    py = gy[None, None].astype(jnp.float32) + oy
    px = gx[None, None].astype(jnp.float32) + ox
    if mask is not None:
        mk = jnp.asarray(mask, jnp.float32).reshape(
            n, dg, kh, kw, oh, ow).transpose(0, 1, 4, 5, 2, 3)
    else:
        mk = None

    cpg = cin // dg         # channels per deformable group

    def sample_group(xg, pyg, pxg, mg):
        """xg (cpg, hp, wp); pyg/pxg (oh, ow, kh, kw) → (cpg, oh, ow, kh, kw)."""
        yc = jnp.clip(pyg, 0.0, hp - 1.0)
        xc = jnp.clip(pxg, 0.0, wp - 1.0)
        valid = ((pyg > -1.0) & (pyg < hp) & (pxg > -1.0) & (pxg < wp))
        y0 = jnp.floor(yc).astype(jnp.int32)
        x0 = jnp.floor(xc).astype(jnp.int32)
        y1 = jnp.minimum(y0 + 1, hp - 1)
        x1 = jnp.minimum(x0 + 1, wp - 1)
        wy = yc - y0
        wx = xc - x0
        flat = xg.reshape(cpg, -1)
        g = lambda yi, xi: flat[:, (yi * wp + xi).reshape(-1)].reshape(
            (cpg,) + yi.shape)
        v = (g(y0, x0) * (1 - wy) * (1 - wx) + g(y1, x0) * wy * (1 - wx)
             + g(y0, x1) * (1 - wy) * wx + g(y1, x1) * wy * wx)
        v = v * valid
        if mg is not None:
            v = v * mg
        return v

    def one(xi, pyi, pxi, mi):
        """xi (cin, hp, wp) one image; vmapped over the batch (a python
        loop would unroll n copies of the gather graph)."""
        groups_out = []
        for gidx in range(dg):
            mg = mi[gidx] if mi is not None else None
            groups_out.append(sample_group(
                xi[gidx * cpg:(gidx + 1) * cpg], pyi[gidx], pxi[gidx],
                mg))
        return jnp.concatenate(groups_out, axis=0)  # (cin, oh, ow, kh, kw)

    if mk is not None:
        cols = jax.vmap(one)(xp, py, px, mk)
    else:
        cols = jax.vmap(lambda a, b, c: one(a, b, c, None))(xp, py, px)
    # (n, cin, oh, ow, kh, kw) @ weight (cout, cin/groups, kh, kw)
    if groups == 1:
        out = jnp.einsum("nchwyx,ocyx->nohw", cols, weight)
    else:
        cg = cin // groups
        og = cout // groups
        outs = []
        for gi in range(groups):
            outs.append(jnp.einsum(
                "nchwyx,ocyx->nohw",
                cols[:, gi * cg:(gi + 1) * cg],
                weight[gi * og:(gi + 1) * og]))
        out = jnp.concatenate(outs, axis=1)
    if bias is not None:
        out = out + jnp.asarray(bias)[None, :, None, None]
    return out


class DeformConv2D(Layer):
    """paddle.vision.ops.DeformConv2D (layer form of deform_conv2d)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        ks = ((kernel_size, kernel_size) if isinstance(kernel_size, int)
              else tuple(kernel_size))
        self.stride, self.padding, self.dilation = stride, padding, dilation
        self.deformable_groups, self.groups = deformable_groups, groups
        w_init = weight_attr or init.XavierNormal()
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups, ks[0], ks[1]),
            default_initializer=w_init, dtype="float32")
        if bias_attr is not False:
            self.bias = self.create_parameter(
                (out_channels,), default_initializer=init.Constant(0.0),
                dtype="float32", is_bias=True)
        else:
            self.bias = None

    def forward(self, x, offset, mask=None):
        bias = self._parameters.get("bias")
        return deform_conv2d(
            x, offset, self.weight,
            bias.value if bias is not None else None,
            stride=self.stride, padding=self.padding,
            dilation=self.dilation,
            deformable_groups=self.deformable_groups, groups=self.groups,
            mask=mask)


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, rois_num=None):
    """paddle.vision.ops.distribute_fpn_proposals: route each RoI to an
    FPN level by its scale. Eager (data-dependent split sizes)."""
    # tpu-lint: allow(host-sync): eager op — data-dependent split sizes
    rois = np.asarray(fpn_rois, np.float32)
    scale = np.sqrt(np.maximum(
        (rois[:, 2] - rois[:, 0]) * (rois[:, 3] - rois[:, 1]), 0))
    lvl = np.floor(np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    if rois_num is not None:
        # tpu-lint: allow(host-sync): eager op — data-dependent splits
        rn = np.asarray(rois_num)
        img_of = np.repeat(np.arange(len(rn)), rn)
    outs, idxs, nums = [], [], [] if rois_num is not None else None
    for level in range(min_level, max_level + 1):
        sel = np.nonzero(lvl == level)[0]
        outs.append(jnp.asarray(rois[sel]))
        idxs.append(sel)
        if rois_num is not None:
            # per-IMAGE counts at this level (the reference's rois_num
            # output is (batch,) per level, not a single total)
            nums.append(jnp.asarray(
                np.bincount(img_of[sel], minlength=len(rn)), np.int32))
    restore = np.argsort(np.concatenate(idxs)) if idxs else np.zeros(0)
    return outs, jnp.asarray(restore, jnp.int32), nums
