"""ResNet family (≈ python/paddle/vision/models/resnet.py).

NCHW like the reference; convs hit the MXU conv path, BN buffers update
through the functional bridge's mutable-buffer mechanism."""

from typing import List, Optional, Type, Union

import jax.numpy as jnp

from paddle_tpu import nn
from paddle_tpu.nn import functional as F


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, in_ch, ch, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2D(in_ch, ch, 3, stride=stride, padding=1,
                               bias_attr=False)
        self.bn1 = nn.BatchNorm2D(ch)
        self.conv2 = nn.Conv2D(ch, ch, 3, padding=1, bias_attr=False)
        self.bn2 = nn.BatchNorm2D(ch)
        self.downsample = downsample

    def forward(self, x):
        identity = x if self.downsample is None else self.downsample(x)
        out = F.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        return F.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, in_ch, ch, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2D(in_ch, ch, 1, bias_attr=False)
        self.bn1 = nn.BatchNorm2D(ch)
        self.conv2 = nn.Conv2D(ch, ch, 3, stride=stride, padding=1,
                               bias_attr=False)
        self.bn2 = nn.BatchNorm2D(ch)
        self.conv3 = nn.Conv2D(ch, ch * 4, 1, bias_attr=False)
        self.bn3 = nn.BatchNorm2D(ch * 4)
        self.downsample = downsample

    def forward(self, x):
        identity = x if self.downsample is None else self.downsample(x)
        out = F.relu(self.bn1(self.conv1(x)))
        out = F.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        return F.relu(out + identity)


class ResNet(nn.Layer):
    def __init__(self, block, depth_cfg: List[int], num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.conv1 = nn.Conv2D(3, 64, 7, stride=2, padding=3, bias_attr=False)
        self.bn1 = nn.BatchNorm2D(64)
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        self.in_ch = 64
        self.layer1 = self._make_layer(block, 64, depth_cfg[0])
        self.layer2 = self._make_layer(block, 128, depth_cfg[1], stride=2)
        self.layer3 = self._make_layer(block, 256, depth_cfg[2], stride=2)
        self.layer4 = self._make_layer(block, 512, depth_cfg[3], stride=2)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, ch, blocks, stride=1):
        downsample = None
        if stride != 1 or self.in_ch != ch * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.in_ch, ch * block.expansion, 1, stride=stride,
                          bias_attr=False),
                nn.BatchNorm2D(ch * block.expansion))
        layers = [block(self.in_ch, ch, stride, downsample)]
        self.in_ch = ch * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.in_ch, ch))
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.maxpool(F.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        if self.with_pool:
            x = jnp.mean(x, axis=(2, 3))
        if self.num_classes > 0:
            if x.ndim > 2:           # with_pool=False: flatten like the ref
                x = x.reshape(x.shape[0], -1)
            x = self.fc(x)
        return x

    def num_params(self):
        import numpy as np
        return sum(int(np.prod(p.shape)) for _, p in self.named_parameters())


def resnet18(num_classes=1000, **kw):
    return ResNet(BasicBlock, [2, 2, 2, 2], num_classes=num_classes, **kw)


def resnet34(num_classes=1000, **kw):
    return ResNet(BasicBlock, [3, 4, 6, 3], num_classes=num_classes, **kw)


def resnet50(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 4, 6, 3], num_classes=num_classes, **kw)


def resnet101(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 4, 23, 3], num_classes=num_classes, **kw)
