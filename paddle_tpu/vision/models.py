"""ResNet family (≈ python/paddle/vision/models/resnet.py).

NCHW like the reference; convs hit the MXU conv path, BN buffers update
through the functional bridge's mutable-buffer mechanism."""

from typing import List, Optional, Type, Union

import jax.numpy as jnp

from paddle_tpu import nn
from paddle_tpu.nn import functional as F


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, in_ch, ch, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2D(in_ch, ch, 3, stride=stride, padding=1,
                               bias_attr=False)
        self.bn1 = nn.BatchNorm2D(ch)
        self.conv2 = nn.Conv2D(ch, ch, 3, padding=1, bias_attr=False)
        self.bn2 = nn.BatchNorm2D(ch)
        self.downsample = downsample

    def forward(self, x):
        identity = x if self.downsample is None else self.downsample(x)
        out = F.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        return F.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, in_ch, ch, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2D(in_ch, ch, 1, bias_attr=False)
        self.bn1 = nn.BatchNorm2D(ch)
        self.conv2 = nn.Conv2D(ch, ch, 3, stride=stride, padding=1,
                               bias_attr=False)
        self.bn2 = nn.BatchNorm2D(ch)
        self.conv3 = nn.Conv2D(ch, ch * 4, 1, bias_attr=False)
        self.bn3 = nn.BatchNorm2D(ch * 4)
        self.downsample = downsample

    def forward(self, x):
        identity = x if self.downsample is None else self.downsample(x)
        out = F.relu(self.bn1(self.conv1(x)))
        out = F.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        return F.relu(out + identity)


class ResNet(nn.Layer):
    def __init__(self, block, depth_cfg: List[int], num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.conv1 = nn.Conv2D(3, 64, 7, stride=2, padding=3, bias_attr=False)
        self.bn1 = nn.BatchNorm2D(64)
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        self.in_ch = 64
        self.layer1 = self._make_layer(block, 64, depth_cfg[0])
        self.layer2 = self._make_layer(block, 128, depth_cfg[1], stride=2)
        self.layer3 = self._make_layer(block, 256, depth_cfg[2], stride=2)
        self.layer4 = self._make_layer(block, 512, depth_cfg[3], stride=2)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, ch, blocks, stride=1):
        downsample = None
        if stride != 1 or self.in_ch != ch * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.in_ch, ch * block.expansion, 1, stride=stride,
                          bias_attr=False),
                nn.BatchNorm2D(ch * block.expansion))
        layers = [block(self.in_ch, ch, stride, downsample)]
        self.in_ch = ch * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.in_ch, ch))
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.maxpool(F.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        if self.with_pool:
            x = jnp.mean(x, axis=(2, 3))
        if self.num_classes > 0:
            if x.ndim > 2:           # with_pool=False: flatten like the ref
                x = x.reshape(x.shape[0], -1)
            x = self.fc(x)
        return x

    def num_params(self):
        import numpy as np
        return sum(int(np.prod(p.shape)) for _, p in self.named_parameters())


def resnet18(num_classes=1000, **kw):
    return ResNet(BasicBlock, [2, 2, 2, 2], num_classes=num_classes, **kw)


def resnet34(num_classes=1000, **kw):
    return ResNet(BasicBlock, [3, 4, 6, 3], num_classes=num_classes, **kw)


def resnet50(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 4, 6, 3], num_classes=num_classes, **kw)


def resnet101(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 4, 23, 3], num_classes=num_classes, **kw)


class LeNet(nn.Layer):
    """Reference: paddle.vision.models.LeNet (MNIST-scale)."""

    def __init__(self, num_classes=10):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1), nn.ReLU(),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5, stride=1, padding=0), nn.ReLU(),
            nn.MaxPool2D(2, 2))
        self.fc = nn.Sequential(
            nn.Linear(400, 120), nn.Linear(120, 84),
            nn.Linear(84, num_classes))

    def forward(self, x):
        x = self.features(x)
        return self.fc(x.reshape(x.shape[0], -1))


class AlexNet(nn.Layer):
    """Reference: paddle.vision.models.AlexNet."""

    def __init__(self, num_classes=1000, dropout=0.5):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, 2))
        self.avgpool = nn.AdaptiveAvgPool2D(6)
        self.classifier = nn.Sequential(
            nn.Dropout(dropout), nn.Linear(256 * 36, 4096), nn.ReLU(),
            nn.Dropout(dropout), nn.Linear(4096, 4096), nn.ReLU(),
            nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.avgpool(self.features(x))
        return self.classifier(x.reshape(x.shape[0], -1))


_VGG_CFGS = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
          512, 512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
          "M", 512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512,
          512, 512, "M", 512, 512, 512, 512, "M"],
}


class VGG(nn.Layer):
    """Reference: paddle.vision.models.VGG (cfgs A/B/D/E = 11/13/16/19)."""

    def __init__(self, cfg="D", num_classes=1000, batch_norm=False,
                 dropout=0.5):
        super().__init__()
        layers = []
        in_c = 3
        for v in _VGG_CFGS[cfg] if isinstance(cfg, str) else cfg:
            if v == "M":
                layers.append(nn.MaxPool2D(2, 2))
            else:
                layers.append(nn.Conv2D(in_c, v, 3, padding=1))
                if batch_norm:
                    layers.append(nn.BatchNorm2D(v))
                layers.append(nn.ReLU())
                in_c = v
        self.features = nn.Sequential(*layers)
        self.avgpool = nn.AdaptiveAvgPool2D(7)
        self.classifier = nn.Sequential(
            nn.Linear(512 * 49, 4096), nn.ReLU(), nn.Dropout(dropout),
            nn.Linear(4096, 4096), nn.ReLU(), nn.Dropout(dropout),
            nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.avgpool(self.features(x))
        return self.classifier(x.reshape(x.shape[0], -1))


def vgg11(batch_norm=False, num_classes=1000, **kw):
    return VGG("A", num_classes, batch_norm, **kw)


def vgg13(batch_norm=False, num_classes=1000, **kw):
    return VGG("B", num_classes, batch_norm, **kw)


def vgg16(batch_norm=False, num_classes=1000, **kw):
    return VGG("D", num_classes, batch_norm, **kw)


def vgg19(batch_norm=False, num_classes=1000, **kw):
    return VGG("E", num_classes, batch_norm, **kw)


class _InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride, expand_ratio):
        super().__init__()
        hidden = int(round(in_c * expand_ratio))
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if expand_ratio != 1:
            layers += [nn.Conv2D(in_c, hidden, 1, bias_attr=False),
                       nn.BatchNorm2D(hidden), nn.ReLU6()]
        layers += [
            nn.Conv2D(hidden, hidden, 3, stride=stride, padding=1,
                      groups=hidden, bias_attr=False),
            nn.BatchNorm2D(hidden), nn.ReLU6(),
            nn.Conv2D(hidden, out_c, 1, bias_attr=False),
            nn.BatchNorm2D(out_c)]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        return x + self.conv(x) if self.use_res else self.conv(x)


class MobileNetV2(nn.Layer):
    """Reference: paddle.vision.models.MobileNetV2 (inverted residuals)."""

    def __init__(self, scale=1.0, num_classes=1000, dropout=0.2):
        super().__init__()
        cfg = [  # t, c, n, s
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        in_c = max(8, int(32 * scale))
        features = [nn.Conv2D(3, in_c, 3, stride=2, padding=1,
                              bias_attr=False),
                    nn.BatchNorm2D(in_c), nn.ReLU6()]
        for t, c, n, s in cfg:
            out_c = max(8, int(c * scale))
            for i in range(n):
                features.append(_InvertedResidual(
                    in_c, out_c, s if i == 0 else 1, t))
                in_c = out_c
        last = max(1280, int(1280 * scale))
        features += [nn.Conv2D(in_c, last, 1, bias_attr=False),
                     nn.BatchNorm2D(last), nn.ReLU6()]
        self.features = nn.Sequential(*features)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.classifier = nn.Sequential(nn.Dropout(dropout),
                                        nn.Linear(last, num_classes))

    def forward(self, x):
        x = self.pool(self.features(x))
        return self.classifier(x.reshape(x.shape[0], -1))


def mobilenet_v2(scale=1.0, num_classes=1000, **kw):
    return MobileNetV2(scale=scale, num_classes=num_classes, **kw)


# ---------------------------------------------------------------------------
# round-5 backbone tail (reference python/paddle/vision/models/{densenet,
# squeezenet,shufflenetv2}.py)
# ---------------------------------------------------------------------------

class _DenseLayer(nn.Layer):
    def __init__(self, in_ch, growth_rate, bn_size):
        super().__init__()
        inter = bn_size * growth_rate
        self.norm1 = nn.BatchNorm2D(in_ch)
        self.conv1 = nn.Conv2D(in_ch, inter, 1, bias_attr=False)
        self.norm2 = nn.BatchNorm2D(inter)
        self.conv2 = nn.Conv2D(inter, growth_rate, 3, padding=1,
                               bias_attr=False)

    def forward(self, x):
        y = self.conv1(F.relu(self.norm1(x)))
        y = self.conv2(F.relu(self.norm2(y)))
        return jnp.concatenate([x, y], axis=1)


class _Transition(nn.Layer):
    def __init__(self, in_ch, out_ch):
        super().__init__()
        self.norm = nn.BatchNorm2D(in_ch)
        self.conv = nn.Conv2D(in_ch, out_ch, 1, bias_attr=False)

    def forward(self, x):
        x = self.conv(F.relu(self.norm(x)))
        return F.avg_pool2d(x, 2, stride=2)


class DenseNet(nn.Layer):
    """Reference: paddle.vision.models.DenseNet (layers=121|161|169|201)."""

    _cfgs = {121: (6, 12, 24, 16), 161: (6, 12, 36, 24),
             169: (6, 12, 32, 32), 201: (6, 12, 48, 32)}

    def __init__(self, layers=121, growth_rate=32, bn_size=4,
                 num_classes=1000):
        super().__init__()
        if layers == 161:
            growth_rate, init_ch = 48, 96
        else:
            init_ch = 64
        blocks = self._cfgs[layers]
        self.conv0 = nn.Conv2D(3, init_ch, 7, stride=2, padding=3,
                               bias_attr=False)
        self.norm0 = nn.BatchNorm2D(init_ch)
        stages = []
        ch = init_ch
        for i, n in enumerate(blocks):
            stage = []
            for _ in range(n):
                stage.append(_DenseLayer(ch, growth_rate, bn_size))
                ch += growth_rate
            stages.append(nn.Sequential(*stage))
            if i != len(blocks) - 1:
                stages.append(_Transition(ch, ch // 2))
                ch //= 2
        self.features = nn.Sequential(*stages)
        self.norm5 = nn.BatchNorm2D(ch)
        self.classifier = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = F.relu(self.norm0(self.conv0(x)))
        x = F.max_pool2d(x, 3, stride=2, padding=1)
        x = F.relu(self.norm5(self.features(x)))
        x = F.adaptive_avg_pool2d(x, 1)
        return self.classifier(x.reshape(x.shape[0], -1))


def densenet121(num_classes=1000, **kw):
    return DenseNet(121, num_classes=num_classes, **kw)


def densenet161(num_classes=1000, **kw):
    return DenseNet(161, num_classes=num_classes, **kw)


class _Fire(nn.Layer):
    def __init__(self, in_ch, squeeze, e1, e3):
        super().__init__()
        self.squeeze = nn.Conv2D(in_ch, squeeze, 1)
        self.expand1 = nn.Conv2D(squeeze, e1, 1)
        self.expand3 = nn.Conv2D(squeeze, e3, 3, padding=1)

    def forward(self, x):
        s = F.relu(self.squeeze(x))
        return jnp.concatenate([F.relu(self.expand1(s)),
                                F.relu(self.expand3(s))], axis=1)


class SqueezeNet(nn.Layer):
    """Reference: paddle.vision.models.SqueezeNet (version '1.0'|'1.1')."""

    def __init__(self, version="1.0", num_classes=1000, dropout=0.5):
        super().__init__()
        self.version = str(version)
        if self.version == "1.0":
            self.conv1 = nn.Conv2D(3, 96, 7, stride=2)
            fires = [(96, 16, 64, 64), (128, 16, 64, 64),
                     (128, 32, 128, 128), (256, 32, 128, 128),
                     (256, 48, 192, 192), (384, 48, 192, 192),
                     (384, 64, 256, 256), (512, 64, 256, 256)]
            self.pool_after = (0, 3, 7)     # maxpool after these fires
        else:
            self.conv1 = nn.Conv2D(3, 64, 3, stride=2)
            fires = [(64, 16, 64, 64), (128, 16, 64, 64),
                     (128, 32, 128, 128), (256, 32, 128, 128),
                     (256, 48, 192, 192), (384, 48, 192, 192),
                     (384, 64, 256, 256), (512, 64, 256, 256)]
            self.pool_after = (1, 3)
        self.fires = nn.LayerList([_Fire(*f) for f in fires])
        self.drop = nn.Dropout(dropout)
        self.final_conv = nn.Conv2D(512, num_classes, 1)

    def forward(self, x):
        x = F.relu(self.conv1(x))
        x = F.max_pool2d(x, 3, stride=2)
        for i, fire in enumerate(self.fires):
            x = fire(x)
            if i in self.pool_after:
                x = F.max_pool2d(x, 3, stride=2)
        x = F.relu(self.final_conv(self.drop(x)))
        x = F.adaptive_avg_pool2d(x, 1)
        return x.reshape(x.shape[0], -1)


def squeezenet1_0(num_classes=1000, **kw):
    return SqueezeNet("1.0", num_classes=num_classes, **kw)


def squeezenet1_1(num_classes=1000, **kw):
    return SqueezeNet("1.1", num_classes=num_classes, **kw)


def _channel_shuffle(x, groups):
    n, c, h, w = x.shape
    return x.reshape(n, groups, c // groups, h, w).transpose(
        0, 2, 1, 3, 4).reshape(n, c, h, w)


class _ShuffleUnit(nn.Layer):
    def __init__(self, in_ch, out_ch, stride):
        super().__init__()
        self.stride = stride
        branch = out_ch // 2
        if stride == 2:
            self.b1_dw = nn.Conv2D(in_ch, in_ch, 3, stride=2, padding=1,
                                   groups=in_ch, bias_attr=False)
            self.b1_dwbn = nn.BatchNorm2D(in_ch)
            self.b1_pw = nn.Conv2D(in_ch, branch, 1, bias_attr=False)
            self.b1_pwbn = nn.BatchNorm2D(branch)
            in2 = in_ch
        else:
            in2 = in_ch // 2
        self.b2_pw1 = nn.Conv2D(in2, branch, 1, bias_attr=False)
        self.b2_bn1 = nn.BatchNorm2D(branch)
        self.b2_dw = nn.Conv2D(branch, branch, 3, stride=stride, padding=1,
                               groups=branch, bias_attr=False)
        self.b2_bn2 = nn.BatchNorm2D(branch)
        self.b2_pw2 = nn.Conv2D(branch, branch, 1, bias_attr=False)
        self.b2_bn3 = nn.BatchNorm2D(branch)

    def forward(self, x):
        if self.stride == 2:
            left = F.relu(self.b1_pwbn(self.b1_pw(
                self.b1_dwbn(self.b1_dw(x)))))
            right = x
        else:
            c = x.shape[1] // 2
            left, right = x[:, :c], x[:, c:]
        y = F.relu(self.b2_bn1(self.b2_pw1(right)))
        y = self.b2_bn2(self.b2_dw(y))
        y = F.relu(self.b2_bn3(self.b2_pw2(y)))
        out = jnp.concatenate([left, y], axis=1)
        return _channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    """Reference: paddle.vision.models.ShuffleNetV2 (scale 0.5|1.0|1.5|2.0)."""

    _chs = {0.5: (48, 96, 192, 1024), 1.0: (116, 232, 464, 1024),
            1.5: (176, 352, 704, 1024), 2.0: (244, 488, 976, 2048)}

    def __init__(self, scale=1.0, num_classes=1000):
        super().__init__()
        c2, c3, c4, c5 = self._chs[scale]
        self.conv1 = nn.Conv2D(3, 24, 3, stride=2, padding=1,
                               bias_attr=False)
        self.bn1 = nn.BatchNorm2D(24)
        stages = []
        in_ch = 24
        for out_ch, repeat in ((c2, 4), (c3, 8), (c4, 4)):
            units = [_ShuffleUnit(in_ch, out_ch, 2)]
            for _ in range(repeat - 1):
                units.append(_ShuffleUnit(out_ch, out_ch, 1))
            stages.append(nn.Sequential(*units))
            in_ch = out_ch
        self.stages = nn.Sequential(*stages)
        self.conv5 = nn.Conv2D(in_ch, c5, 1, bias_attr=False)
        self.bn5 = nn.BatchNorm2D(c5)
        self.fc = nn.Linear(c5, num_classes)

    def forward(self, x):
        x = F.relu(self.bn1(self.conv1(x)))
        x = F.max_pool2d(x, 3, stride=2, padding=1)
        x = self.stages(x)
        x = F.relu(self.bn5(self.conv5(x)))
        x = F.adaptive_avg_pool2d(x, 1)
        return self.fc(x.reshape(x.shape[0], -1))


def shufflenet_v2_x1_0(num_classes=1000, **kw):
    return ShuffleNetV2(1.0, num_classes=num_classes, **kw)


def shufflenet_v2_x0_5(num_classes=1000, **kw):
    return ShuffleNetV2(0.5, num_classes=num_classes, **kw)
