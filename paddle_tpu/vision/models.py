"""ResNet family (≈ python/paddle/vision/models/resnet.py).

NCHW like the reference; convs hit the MXU conv path, BN buffers update
through the functional bridge's mutable-buffer mechanism."""

from typing import List, Optional, Type, Union

import jax.numpy as jnp

from paddle_tpu import nn
from paddle_tpu.nn import functional as F


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, in_ch, ch, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2D(in_ch, ch, 3, stride=stride, padding=1,
                               bias_attr=False)
        self.bn1 = nn.BatchNorm2D(ch)
        self.conv2 = nn.Conv2D(ch, ch, 3, padding=1, bias_attr=False)
        self.bn2 = nn.BatchNorm2D(ch)
        self.downsample = downsample

    def forward(self, x):
        identity = x if self.downsample is None else self.downsample(x)
        out = F.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        return F.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, in_ch, ch, stride=1, downsample=None):
        super().__init__()
        self.conv1 = nn.Conv2D(in_ch, ch, 1, bias_attr=False)
        self.bn1 = nn.BatchNorm2D(ch)
        self.conv2 = nn.Conv2D(ch, ch, 3, stride=stride, padding=1,
                               bias_attr=False)
        self.bn2 = nn.BatchNorm2D(ch)
        self.conv3 = nn.Conv2D(ch, ch * 4, 1, bias_attr=False)
        self.bn3 = nn.BatchNorm2D(ch * 4)
        self.downsample = downsample

    def forward(self, x):
        identity = x if self.downsample is None else self.downsample(x)
        out = F.relu(self.bn1(self.conv1(x)))
        out = F.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        return F.relu(out + identity)


class ResNet(nn.Layer):
    def __init__(self, block, depth_cfg: List[int], num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.conv1 = nn.Conv2D(3, 64, 7, stride=2, padding=3, bias_attr=False)
        self.bn1 = nn.BatchNorm2D(64)
        self.maxpool = nn.MaxPool2D(3, stride=2, padding=1)
        self.in_ch = 64
        self.layer1 = self._make_layer(block, 64, depth_cfg[0])
        self.layer2 = self._make_layer(block, 128, depth_cfg[1], stride=2)
        self.layer3 = self._make_layer(block, 256, depth_cfg[2], stride=2)
        self.layer4 = self._make_layer(block, 512, depth_cfg[3], stride=2)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, ch, blocks, stride=1):
        downsample = None
        if stride != 1 or self.in_ch != ch * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.in_ch, ch * block.expansion, 1, stride=stride,
                          bias_attr=False),
                nn.BatchNorm2D(ch * block.expansion))
        layers = [block(self.in_ch, ch, stride, downsample)]
        self.in_ch = ch * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.in_ch, ch))
        return nn.Sequential(*layers)

    def forward(self, x):
        x = self.maxpool(F.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        if self.with_pool:
            x = jnp.mean(x, axis=(2, 3))
        if self.num_classes > 0:
            if x.ndim > 2:           # with_pool=False: flatten like the ref
                x = x.reshape(x.shape[0], -1)
            x = self.fc(x)
        return x

    def num_params(self):
        import numpy as np
        return sum(int(np.prod(p.shape)) for _, p in self.named_parameters())


def resnet18(num_classes=1000, **kw):
    return ResNet(BasicBlock, [2, 2, 2, 2], num_classes=num_classes, **kw)


def resnet34(num_classes=1000, **kw):
    return ResNet(BasicBlock, [3, 4, 6, 3], num_classes=num_classes, **kw)


def resnet50(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 4, 6, 3], num_classes=num_classes, **kw)


def resnet101(num_classes=1000, **kw):
    return ResNet(BottleneckBlock, [3, 4, 23, 3], num_classes=num_classes, **kw)


class LeNet(nn.Layer):
    """Reference: paddle.vision.models.LeNet (MNIST-scale)."""

    def __init__(self, num_classes=10):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(1, 6, 3, stride=1, padding=1), nn.ReLU(),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(6, 16, 5, stride=1, padding=0), nn.ReLU(),
            nn.MaxPool2D(2, 2))
        self.fc = nn.Sequential(
            nn.Linear(400, 120), nn.Linear(120, 84),
            nn.Linear(84, num_classes))

    def forward(self, x):
        x = self.features(x)
        return self.fc(x.reshape(x.shape[0], -1))


class AlexNet(nn.Layer):
    """Reference: paddle.vision.models.AlexNet."""

    def __init__(self, num_classes=1000, dropout=0.5):
        super().__init__()
        self.features = nn.Sequential(
            nn.Conv2D(3, 64, 11, stride=4, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(64, 192, 5, padding=2), nn.ReLU(),
            nn.MaxPool2D(3, 2),
            nn.Conv2D(192, 384, 3, padding=1), nn.ReLU(),
            nn.Conv2D(384, 256, 3, padding=1), nn.ReLU(),
            nn.Conv2D(256, 256, 3, padding=1), nn.ReLU(),
            nn.MaxPool2D(3, 2))
        self.avgpool = nn.AdaptiveAvgPool2D(6)
        self.classifier = nn.Sequential(
            nn.Dropout(dropout), nn.Linear(256 * 36, 4096), nn.ReLU(),
            nn.Dropout(dropout), nn.Linear(4096, 4096), nn.ReLU(),
            nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.avgpool(self.features(x))
        return self.classifier(x.reshape(x.shape[0], -1))


_VGG_CFGS = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
          512, 512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512,
          "M", 512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512,
          512, 512, "M", 512, 512, 512, 512, "M"],
}


class VGG(nn.Layer):
    """Reference: paddle.vision.models.VGG (cfgs A/B/D/E = 11/13/16/19)."""

    def __init__(self, cfg="D", num_classes=1000, batch_norm=False,
                 dropout=0.5):
        super().__init__()
        layers = []
        in_c = 3
        for v in _VGG_CFGS[cfg] if isinstance(cfg, str) else cfg:
            if v == "M":
                layers.append(nn.MaxPool2D(2, 2))
            else:
                layers.append(nn.Conv2D(in_c, v, 3, padding=1))
                if batch_norm:
                    layers.append(nn.BatchNorm2D(v))
                layers.append(nn.ReLU())
                in_c = v
        self.features = nn.Sequential(*layers)
        self.avgpool = nn.AdaptiveAvgPool2D(7)
        self.classifier = nn.Sequential(
            nn.Linear(512 * 49, 4096), nn.ReLU(), nn.Dropout(dropout),
            nn.Linear(4096, 4096), nn.ReLU(), nn.Dropout(dropout),
            nn.Linear(4096, num_classes))

    def forward(self, x):
        x = self.avgpool(self.features(x))
        return self.classifier(x.reshape(x.shape[0], -1))


def vgg11(batch_norm=False, num_classes=1000, **kw):
    return VGG("A", num_classes, batch_norm, **kw)


def vgg13(batch_norm=False, num_classes=1000, **kw):
    return VGG("B", num_classes, batch_norm, **kw)


def vgg16(batch_norm=False, num_classes=1000, **kw):
    return VGG("D", num_classes, batch_norm, **kw)


def vgg19(batch_norm=False, num_classes=1000, **kw):
    return VGG("E", num_classes, batch_norm, **kw)


class _InvertedResidual(nn.Layer):
    def __init__(self, in_c, out_c, stride, expand_ratio):
        super().__init__()
        hidden = int(round(in_c * expand_ratio))
        self.use_res = stride == 1 and in_c == out_c
        layers = []
        if expand_ratio != 1:
            layers += [nn.Conv2D(in_c, hidden, 1, bias_attr=False),
                       nn.BatchNorm2D(hidden), nn.ReLU6()]
        layers += [
            nn.Conv2D(hidden, hidden, 3, stride=stride, padding=1,
                      groups=hidden, bias_attr=False),
            nn.BatchNorm2D(hidden), nn.ReLU6(),
            nn.Conv2D(hidden, out_c, 1, bias_attr=False),
            nn.BatchNorm2D(out_c)]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        return x + self.conv(x) if self.use_res else self.conv(x)


class MobileNetV2(nn.Layer):
    """Reference: paddle.vision.models.MobileNetV2 (inverted residuals)."""

    def __init__(self, scale=1.0, num_classes=1000, dropout=0.2):
        super().__init__()
        cfg = [  # t, c, n, s
            (1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
            (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        in_c = max(8, int(32 * scale))
        features = [nn.Conv2D(3, in_c, 3, stride=2, padding=1,
                              bias_attr=False),
                    nn.BatchNorm2D(in_c), nn.ReLU6()]
        for t, c, n, s in cfg:
            out_c = max(8, int(c * scale))
            for i in range(n):
                features.append(_InvertedResidual(
                    in_c, out_c, s if i == 0 else 1, t))
                in_c = out_c
        last = max(1280, int(1280 * scale))
        features += [nn.Conv2D(in_c, last, 1, bias_attr=False),
                     nn.BatchNorm2D(last), nn.ReLU6()]
        self.features = nn.Sequential(*features)
        self.pool = nn.AdaptiveAvgPool2D(1)
        self.classifier = nn.Sequential(nn.Dropout(dropout),
                                        nn.Linear(last, num_classes))

    def forward(self, x):
        x = self.pool(self.features(x))
        return self.classifier(x.reshape(x.shape[0], -1))


def mobilenet_v2(scale=1.0, num_classes=1000, **kw):
    return MobileNetV2(scale=scale, num_classes=num_classes, **kw)
