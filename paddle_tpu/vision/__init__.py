"""paddle.vision parity: model zoo backbones + transforms.

Reference (SURVEY.md §2.7): python/paddle/vision/ — datasets, transforms,
pretrained backbones (`paddle.vision.models.resnet50`)."""

from paddle_tpu.vision import datasets  # noqa: F401
from paddle_tpu.vision import models  # noqa: F401
from paddle_tpu.vision import ops  # noqa: F401  (detection ops)
from paddle_tpu.vision import transforms  # noqa: F401
from paddle_tpu.vision.models import (  # noqa: F401
    ResNet,
    resnet18,
    resnet34,
    resnet50,
    resnet101,
    LeNet,
    AlexNet,
    VGG,
    vgg11,
    vgg13,
    vgg16,
    vgg19,
    MobileNetV2,
    mobilenet_v2,
    DenseNet,
    densenet121,
    densenet161,
    SqueezeNet,
    squeezenet1_0,
    squeezenet1_1,
    ShuffleNetV2,
    shufflenet_v2_x0_5,
    shufflenet_v2_x1_0,
)
