"""paddle.vision parity: model zoo backbones + transforms.

Reference (SURVEY.md §2.7): python/paddle/vision/ — datasets, transforms,
pretrained backbones (`paddle.vision.models.resnet50`)."""

from paddle_tpu.vision import models  # noqa: F401
from paddle_tpu.vision import transforms  # noqa: F401
from paddle_tpu.vision.models import (  # noqa: F401
    ResNet,
    resnet18,
    resnet34,
    resnet50,
    resnet101,
)
