"""Vision datasets (reference: python/paddle/vision/datasets/).

Parsers for the standard on-disk formats (MNIST IDX, CIFAR python
batches, class-per-directory image folders). This box has zero egress, so
`download=True` raises with instructions instead of silently failing;
point `image_path`/`data_file` at local copies, or use FakeData for
pipeline tests.
"""
# tpu-lint: allow-file(host-sync): on-disk → host-numpy parsers

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from paddle_tpu.io import Dataset

_NO_EGRESS = ("this environment has no network egress — place the dataset "
              "files locally and pass their path (download=False)")


class FakeData(Dataset):
    """Deterministic synthetic images (torchvision FakeData analog) — for
    exercising input pipelines without any files.

    `image_shape` is (C, H, W) metadata; raw samples are HWC uint8 arrays
    like every decoded image in this module (run ToTensor for CHW float)."""

    def __init__(self, size=100, image_shape=(3, 32, 32), num_classes=10,
                 transform=None, seed=0):
        self.size = size
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __len__(self):
        return self.size

    def __getitem__(self, idx):
        rng = np.random.RandomState(self.seed + idx)
        c, h, w = self.image_shape
        img = rng.randint(0, 256, (h, w, c), dtype=np.uint8)
        label = int(rng.randint(0, self.num_classes))
        if self.transform is not None:
            img = self.transform(img)
        return img, label


def _read_idx_images(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise ValueError(f"{path}: bad IDX image magic {magic}")
        data = np.frombuffer(f.read(n * rows * cols), dtype=np.uint8)
    return data.reshape(n, rows, cols)


def _read_idx_labels(path):
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise ValueError(f"{path}: bad IDX label magic {magic}")
        return np.frombuffer(f.read(n), dtype=np.uint8)


class MNIST(Dataset):
    """MNIST from IDX files (reference paddle.vision.datasets.MNIST).

    image_path/label_path: the ubyte(.gz) files; mode selects the default
    filenames when a directory is given."""

    NAMES = {"train": ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
             "test": ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")}

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        if download and (image_path is None or
                         not os.path.exists(image_path)):
            raise RuntimeError(_NO_EGRESS)
        if image_path and os.path.isdir(image_path):
            img_name, lbl_name = self.NAMES[mode]
            root = image_path
            image_path = self._find(root, img_name)
            label_path = self._find(root, lbl_name)
        if not image_path or not label_path:
            raise ValueError("MNIST needs image_path and label_path "
                             f"({_NO_EGRESS})")
        self.images = _read_idx_images(image_path)
        self.labels = _read_idx_labels(label_path)
        self.transform = transform

    @staticmethod
    def _find(root, base):
        for suffix in ("", ".gz"):
            p = os.path.join(root, base + suffix)
            if os.path.exists(p):
                return p
        raise FileNotFoundError(f"{base}[.gz] not under {root}")

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, int(self.labels[idx])


class FashionMNIST(MNIST):
    """Same IDX format, different files."""


class Cifar10(Dataset):
    """CIFAR-10 from the python-pickle tar (reference Cifar10)."""

    train_batches = [f"data_batch_{i}" for i in range(1, 6)]
    test_batches = ["test_batch"]
    archive_prefix = "cifar-10-batches-py"

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        if download and (data_file is None or not os.path.exists(data_file)):
            raise RuntimeError(_NO_EGRESS)
        if data_file is None:
            raise ValueError(f"Cifar10 needs data_file ({_NO_EGRESS})")
        names = self.train_batches if mode == "train" else self.test_batches
        imgs, labels = [], []
        for raw in self._iter_batches(data_file, names):
            d = pickle.loads(raw, encoding="bytes")
            imgs.append(np.asarray(d[b"data"], np.uint8))
            labels.extend(d.get(b"labels", d.get(b"fine_labels")))
        self.images = np.concatenate(imgs).reshape(-1, 3, 32, 32) \
            .transpose(0, 2, 3, 1)  # HWC
        self.labels = np.asarray(labels, np.int64)
        self.transform = transform

    def _iter_batches(self, data_file, names):
        if os.path.isdir(data_file):
            for n in names:
                with open(os.path.join(data_file, n), "rb") as f:
                    yield f.read()
            return
        with tarfile.open(data_file, "r:*") as tf:
            for n in names:
                member = f"{self.archive_prefix}/{n}"
                try:
                    m = tf.extractfile(member)
                except KeyError:
                    m = None
                if m is None:
                    raise FileNotFoundError(
                        f"{data_file}: archive member {member!r} missing "
                        "or not a regular file")
                yield m.read()

    def __len__(self):
        return len(self.images)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, int(self.labels[idx])


class Cifar100(Cifar10):
    train_batches = ["train"]
    test_batches = ["test"]
    archive_prefix = "cifar-100-python"


IMG_EXTENSIONS = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".ppm", ".tif",
                  ".tiff", ".webp")


class DatasetFolder(Dataset):
    """class-per-subdirectory dataset (reference DatasetFolder)."""

    def __init__(self, root, loader=None, extensions=IMG_EXTENSIONS,
                 transform=None, is_valid_file=None):
        self.root = root
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise FileNotFoundError(f"no class directories under {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for fn in sorted(files):
                    p = os.path.join(dirpath, fn)
                    ok = (is_valid_file(p) if is_valid_file
                          else fn.lower().endswith(extensions))
                    if ok:
                        self.samples.append((p, self.class_to_idx[c]))
        self.loader = loader or self._pil_loader
        self.transform = transform

    @staticmethod
    def _pil_loader(path):
        from PIL import Image
        with Image.open(path) as img:
            return np.asarray(img.convert("RGB"))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, label


class ImageFolder(Dataset):
    """Flat (or recursive) unlabeled image folder (reference ImageFolder)."""

    def __init__(self, root, loader=None, extensions=IMG_EXTENSIONS,
                 transform=None):
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fn in sorted(files):
                if fn.lower().endswith(extensions):
                    self.samples.append(os.path.join(dirpath, fn))
        self.loader = loader or DatasetFolder._pil_loader
        self.transform = transform

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]
