"""paddle.sparse parity — COO/CSR tensors over jax.experimental.sparse.

Reference: python/paddle/sparse/ (sparse_coo_tensor, sparse_csr_tensor,
to_dense/to_sparse_coo, elementwise + matmul over phi sparse kernels).
TPU-native: jax's BCOO/BCSR lower sparse ops to XLA gather/scatter —
fine for genuinely sparse data pipelines; dense MXU math remains the fast
path for model weights.
"""

import jax.numpy as jnp
from jax.experimental import sparse as jsparse


def sparse_coo_tensor(indices, values, shape=None, dtype=None,
                      stop_gradient=True):
    """indices: (ndim, nnz) — the reference layout. With shape=None the
    shape is inferred from the largest index per dimension (paddle
    semantics)."""
    values = jnp.asarray(values, dtype)
    idx = jnp.asarray(indices).T  # BCOO wants (nnz, ndim)
    if shape is None:
        shape = tuple(int(m) + 1 for m in idx.max(axis=0))
    return jsparse.BCOO((values, idx), shape=tuple(shape))


def sparse_csr_tensor(crows, cols, values, shape, dtype=None,
                      stop_gradient=True):
    values = jnp.asarray(values, dtype)
    return jsparse.BCSR((values, jnp.asarray(cols), jnp.asarray(crows)),
                        shape=tuple(shape))


def to_dense(x):
    return x.todense()


def to_sparse_coo(x, sparse_dim=None):
    return jsparse.BCOO.fromdense(jnp.asarray(x))


def to_sparse_csr(x):
    return jsparse.BCSR.fromdense(jnp.asarray(x))


def is_sparse_coo(x):
    return isinstance(x, jsparse.BCOO)


def is_sparse_csr(x):
    return isinstance(x, jsparse.BCSR)


def matmul(x, y):
    """Sparse @ dense (or dense @ dense passthrough)."""
    return x @ y


def _densify(x):
    return to_dense(x) if is_sparse_coo(x) or is_sparse_csr(x) else \
        jnp.asarray(x)


def add(x, y):
    if is_sparse_coo(x) and is_sparse_coo(y):
        return x + y
    if is_sparse_csr(x) and is_sparse_csr(y):
        return to_sparse_csr(_densify(x) + _densify(y))  # stays CSR
    return _densify(x) + _densify(y)


def nnz(x):
    return x.nse


# sparse.nn.functional analogs used by the reference's sparse conv nets are
# dense-subsumed on TPU; relu on values keeps sparsity structure:
def relu(x):
    if is_sparse_coo(x):
        return jsparse.BCOO((jnp.maximum(x.data, 0), x.indices),
                            shape=x.shape)
    if is_sparse_csr(x):
        return jsparse.BCSR((jnp.maximum(x.data, 0), x.indices, x.indptr),
                            shape=x.shape)
    return jnp.maximum(x, 0)
