"""Device control veneer (`paddle.set_device` parity).

On TPU, device placement is owned by XLA + shardings; this module exposes the
query surface (`get_device`, device counts) and maps `set_device` onto JAX's
default-device mechanism.
"""

import jax

_current = [None]


def set_device(device: str):
    """Accepts 'tpu', 'cpu', 'tpu:0' etc. Sets JAX default device."""
    name = device.split(":")[0]
    idx = int(device.split(":")[1]) if ":" in device else 0
    plat_devices = [d for d in jax.devices() if name in ("any", d.platform, _canon(d.platform))]
    if not plat_devices:
        plat_devices = jax.devices()
    dev = plat_devices[min(idx, len(plat_devices) - 1)]
    jax.config.update("jax_default_device", dev)
    _current[0] = device
    return dev


def _canon(platform: str) -> str:
    return {"axon": "tpu"}.get(platform, platform)


def get_device() -> str:
    if _current[0] is not None:
        return _current[0]
    d = jax.devices()[0]
    return f"{_canon(d.platform)}:{d.id}"


def device_count() -> int:
    return jax.device_count()


def local_device_count() -> int:
    return jax.local_device_count()


def is_compiled_with_tpu() -> bool:
    return any(_canon(d.platform) == "tpu" for d in jax.devices())


def is_compiled_with_cuda() -> bool:
    return False
