from paddle_tpu.core import dtype, enforce, flags, rng, device  # noqa: F401
