"""Enforce-style error checking with rich context.

The reference wraps every native call in PADDLE_ENFORCE* macros that attach an
error class, a hint, and a call-stack summary (ref: paddle/fluid/platform/enforce.h,
phi::enforce). Here errors surface from Python/XLA directly, so this module only
provides the user-facing check helpers and an error-context manager that prefixes
framework context onto exceptions (the moral equivalent of Paddle's error stacks).
"""

import contextlib


class EnforceError(RuntimeError):
    pass


class NotFoundError(EnforceError):
    pass


class InvalidArgumentError(EnforceError, ValueError):
    pass


class UnimplementedError(EnforceError, NotImplementedError):
    pass


def enforce(cond, msg="enforce failed", exc=EnforceError):
    if not cond:
        raise exc(msg)


def enforce_eq(a, b, msg=""):
    if a != b:
        raise InvalidArgumentError(f"Expected {a!r} == {b!r}. {msg}")


def enforce_shape(x, expected, msg=""):
    got = tuple(x.shape)
    expected = tuple(expected)
    if len(got) != len(expected) or any(
        e is not None and e != g for g, e in zip(got, expected)
    ):
        raise InvalidArgumentError(f"Expected shape {expected}, got {got}. {msg}")


@contextlib.contextmanager
def error_context(ctx: str):
    """Prefix `ctx` onto any exception escaping the block (≈ Paddle error stacks)."""
    try:
        yield
    except Exception as e:
        note = f"[paddle_tpu] {ctx}"
        if hasattr(e, "add_note"):
            e.add_note(note)
            raise
        raise type(e)(f"{note}: {e}") from e
