"""Run the jax-0.9-targeted codebase on older jax (0.4.x).

The package is written against the jax 0.9 public API (``jax.shard_map``
with ``axis_names=`` partial-manual mode, ``jax.lax.pcast`` vma casts,
``jax.typeof``, ``jax.set_mesh``, and the renamed Pallas-TPU params
``pltpu.CompilerParams`` / ``pltpu.MemorySpace``). Containers that ship a
0.4.x jax lack all of these, so ``install()`` — invoked at the top of
``paddle_tpu/__init__`` before any submodule touches jax — grafts
equivalents onto the jax namespace:

* ``jax.shard_map(f, mesh=, in_specs=, out_specs=, axis_names={...})``
  lowers to ``jax.experimental.shard_map.shard_map`` with
  ``auto = mesh_axes - axis_names`` (0.4.x's partial-auto spelling) and
  ``check_rep=False`` (0.4.x cannot rep-check partial-auto bodies, and
  without vma tracking the pcast discipline has nothing to verify).
* ``jax.lax.pcast(x, axes, to=...)`` becomes the identity: vma ("varying
  over manual axes") tracking does not exist in 0.4.x, so the casts the
  0.9 type system requires are vacuous there.
* ``jax.typeof`` maps to the aval — callers only probe ``.vma`` via
  ``getattr(..., "vma", ())``, which stays an empty default.
* ``jax.set_mesh(mesh)`` returns the mesh itself (a context manager in
  0.4.x); the ambient-abstract-mesh dispatch in ``mp_layers.constrain``
  already falls back when ``jax.sharding.get_abstract_mesh`` is missing.
* ``pltpu.CompilerParams`` ← ``pltpu.TPUCompilerParams`` and
  ``pltpu.MemorySpace`` ← a namespace with ``HBM`` aliased to ``ANY``
  (0.4.x has no dedicated HBM enum member; ANY keeps a ref off-chip,
  which is what every use here wants).

Everything is additive: on a jax that already has the 0.9 names,
``install()`` is a no-op.
"""

import jax

_ACTIVE = False


def active() -> bool:
    """True when install() had to graft 0.9 names onto an older jax —
    i.e. this process runs on the 0.4.x compat layer. Tests exercising
    0.9-only behavior (grad through partial-manual shard_map, vma-typed
    cond branches) skip on it."""
    return _ACTIVE


def _install_shard_map():
    if hasattr(jax, "shard_map"):
        return
    from jax.experimental.shard_map import shard_map as _esm

    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  axis_names=None, check_vma=None, check_rep=None, **kw):
        # 0.9's partial-manual (axis_names ⊂ mesh axes) maps to 0.4.x's
        # `auto=` — but 0.4.x partial-auto lowers axis_index/ppermute
        # through a PartitionId instruction XLA:CPU's SPMD partitioner
        # rejects. Run FULLY manual instead: specs already name every
        # axis the body's collectives use, and unnamed axes degrade to
        # manual replication — correct, merely forgoing auto-axis
        # parallelism on old-jax installs. check_rep defaults to True —
        # 0.4.x's replication-tracking rewrite, which grad-through-
        # shard_map needs (with check_rep=False, device-varying SCALAR
        # residuals of the backward have no concatenable out_spec and
        # trace fails). Callers that return all_gather results under a
        # replicated out_spec (the serving engine's tensor-parallel
        # programs — no grad involved) pass an EXPLICIT check_rep=False
        # (0.9 spelling: check_vma=False): 0.4.x's checker cannot infer
        # that an all_gather output is replicated and rejects the spec.
        del axis_names
        if check_rep is None:
            check_rep = check_vma if check_vma is not None else True
        return _esm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                    check_rep=bool(check_rep))

    jax.shard_map = shard_map

    # 0.4.x's replication checker has no rules for a few identity-like
    # primitives the codebase traces through (checkpoint_name's `name`).
    # They forward their operand's replication unchanged.
    try:
        from jax.experimental import shard_map as _sm
        from jax._src.ad_checkpoint import name_p
        if name_p not in _sm._check_rules:
            _sm.register_standard_check(name_p)
            _sm.register_standard_rewrite(name_p)
    except Exception:
        pass


def _install_lax_names():
    if not hasattr(jax.lax, "pcast"):
        def pcast(x, axes=None, to=None):
            # to="varying" maps to 0.4.x shard_map's pbroadcast (the
            # physical no-op that demotes "replicated over axes" to
            # "varying" in the replication checker). Outside a shard_map
            # trace — or for axes not in scope — it is the identity.
            axes = (axes,) if isinstance(axes, str) else tuple(axes or ())
            try:
                from jax._src import core as _core
                env = _core.get_axis_env()
                axes = tuple(a for a in axes if env.axis_exists(a))
                if not axes or to != "varying":
                    return x
                from jax.experimental.shard_map import pbroadcast
                return jax.tree.map(lambda t: pbroadcast(t, axes), x)
            except Exception:
                return x
        jax.lax.pcast = pcast
    if not hasattr(jax.lax, "axis_size"):
        def axis_size(name):
            from jax._src import core as _core
            return _core.get_axis_env().axis_size(name)
        jax.lax.axis_size = axis_size
    if not hasattr(jax, "typeof"):
        def typeof(x):
            from jax import core
            return core.get_aval(x)
        jax.typeof = typeof
    if not hasattr(jax, "set_mesh"):
        import contextlib

        def set_mesh(mesh):
            # concrete Mesh is already a context manager in 0.4.x;
            # anything else (None / abstract) gets a null context
            if hasattr(mesh, "__enter__"):
                return mesh
            return contextlib.nullcontext(mesh)
        jax.set_mesh = set_mesh


def _install_pallas_names():
    try:
        from jax.experimental.pallas import tpu as pltpu
    except Exception:       # pallas not importable on this platform
        return
    if not hasattr(pltpu, "CompilerParams") and hasattr(
            pltpu, "TPUCompilerParams"):
        pltpu.CompilerParams = pltpu.TPUCompilerParams
    if not hasattr(pltpu, "MemorySpace") and hasattr(pltpu, "TPUMemorySpace"):
        ms = pltpu.TPUMemorySpace

        class MemorySpace:
            ANY = ms.ANY
            HBM = ms.ANY
            VMEM = ms.VMEM
            SMEM = ms.SMEM
        pltpu.MemorySpace = MemorySpace


def install():
    global _ACTIVE
    if not hasattr(jax, "shard_map"):
        _ACTIVE = True
    _install_shard_map()
    _install_lax_names()
    _install_pallas_names()
