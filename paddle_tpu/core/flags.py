"""Global flag registry — env-overridable runtime knobs.

Mirrors the reference's three-tier flag system (gflags `PD_DEFINE_EXPORTED_*` in
paddle/phi/core/flags.cc, settable via env `FLAGS_x` or `paddle.set_flags`).
Flags are defined here in one registry, overridable from the environment at import
time (`FLAGS_check_nan_inf=1 python train.py`) or from code via `set_flags`.
"""

import os
from dataclasses import dataclass
from typing import Any, Callable, Dict


@dataclass
class _Flag:
    name: str
    default: Any
    parser: Callable[[str], Any]
    help: str
    value: Any = None


_REGISTRY: Dict[str, _Flag] = {}


def _parse_bool(s):
    return str(s).lower() in ("1", "true", "yes", "on")


def define_flag(name, default, help="", parser=None):
    if parser is None:
        if isinstance(default, bool):
            parser = _parse_bool
        elif isinstance(default, int):
            parser = int
        elif isinstance(default, float):
            parser = float
        else:
            parser = str
    value = default
    env = os.environ.get(name)
    if env is not None:
        value = parser(env)
    _REGISTRY[name] = _Flag(name, default, parser, help, value)
    return value


def set_flags(flags: Dict[str, Any]):
    for k, v in flags.items():
        if k not in _REGISTRY:
            raise KeyError(f"Unknown flag {k!r}. Known: {sorted(_REGISTRY)}")
        f = _REGISTRY[k]
        f.value = f.parser(v) if isinstance(v, str) else v


def get_flags(flags=None):
    if flags is None:
        return {k: f.value for k, f in _REGISTRY.items()}
    if isinstance(flags, str):
        flags = [flags]
    return {k: _REGISTRY[k].value for k in flags}


def flag(name):
    return _REGISTRY[name].value


# ---- Core flags (parity with the reference's commonly used FLAGS_*) --------
define_flag("FLAGS_check_nan_inf", False, "Scan op outputs/grads for NaN/Inf each step")
define_flag("FLAGS_deterministic", False, "Force deterministic ops where possible")
define_flag("FLAGS_allocator_strategy", "xla_bfc", "Informational: XLA owns allocation on TPU")
define_flag("FLAGS_fraction_of_gpu_memory_to_use", 0.9, "Mapped to XLA mem fraction knob")
define_flag("FLAGS_use_pallas_kernels", True, "Use Pallas fusion kernels when on TPU")
define_flag("FLAGS_pallas_strict", False, "Raise (instead of XLA fallback) when a Pallas kernel fails")
define_flag("FLAGS_fused_decode", True, "Use the fused decode-step path (fused_multi_transformer analog) in generate()")
define_flag("FLAGS_vmem_mib", 0, "Override the device VMEM capacity (MiB) used for Pallas kernel budgets; 0 = derive from device_kind")
define_flag("FLAGS_pallas_interpret", False, "Off-TPU, run Pallas kernels in interpret mode instead of the XLA fallback (CPU-CI kernel parity)")
define_flag("FLAGS_log_level", "INFO", "paddle_tpu logger level")
define_flag("FLAGS_profile_dir", "", "If set, jax.profiler traces are written here")
define_flag("FLAGS_benchmark", False, "Print per-step timing")
