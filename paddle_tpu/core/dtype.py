"""Dtype aliases and default-dtype control.

Mirrors the reference's dtype surface (paddle.float32 etc., `paddle.set_default_dtype`;
ref: python/paddle/framework/dtype.py). TPU-first: bfloat16 is a first-class citizen
and the preferred compute dtype on the MXU.
"""

import jax.numpy as jnp
import numpy as np

float32 = jnp.float32
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float64 = jnp.float64
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
uint8 = jnp.uint8
bool_ = jnp.bool_
complex64 = jnp.complex64

_STR2DTYPE = {
    "float32": float32,
    "fp32": float32,
    "float16": float16,
    "fp16": float16,
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "float64": float64,
    "fp64": float64,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "uint8": uint8,
    "bool": bool_,
    "complex64": complex64,
}

_default_dtype = [jnp.float32]


def to_jax_dtype(dtype):
    """Normalize a user dtype spec (string / np dtype / jnp dtype) to a jnp dtype."""
    if dtype is None:
        return get_default_dtype()
    if isinstance(dtype, str):
        try:
            return _STR2DTYPE[dtype]
        except KeyError:
            raise ValueError(f"Unknown dtype string: {dtype!r}")
    return jnp.dtype(dtype).type


def set_default_dtype(dtype):
    _default_dtype[0] = to_jax_dtype(dtype)


def get_default_dtype():
    return _default_dtype[0]


def is_floating(dtype):
    return jnp.issubdtype(jnp.dtype(dtype), np.floating)
