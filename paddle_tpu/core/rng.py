"""RNG discipline: global seed, named streams, and the TP rng-state tracker.

The reference keeps per-device stateful generators (`phi::Generator`, `paddle.seed`)
and, for tensor parallelism, a named rng-state tracker so dropout masks are identical
across TP ranks for replicated activations but distinct for model-parallel ones
(ref: python/paddle/distributed/fleet/meta_parallel/parallel_layers/random.py,
`get_rng_state_tracker`).

TPU-first design: JAX RNG is functional (explicit keys). Eager code gets a stateful
veneer (`paddle_tpu.seed`, fresh key per draw); jitted code threads keys explicitly.
`rng_guard` pushes a dict of named streams for a traced region — layers pull keys by
stream name via `next_rng_key`, each pull folding in a counter so draws are unique
and reproducible under trace.
"""

import contextlib
import threading
from typing import Dict, Optional

import jax
import numpy as np


class _GlobalGenerator:
    """Stateful eager generator: splits off a fresh key per draw."""

    def __init__(self, seed_: int = 0):
        self._seed = seed_
        self._count = 0
        self._lock = threading.Lock()

    def seed(self, s: int):
        with self._lock:
            self._seed = int(s)
            self._count = 0

    def next_key(self) -> jax.Array:
        with self._lock:
            c = self._count
            self._count += 1
        return jax.random.fold_in(jax.random.PRNGKey(self._seed), c)

    def get_state(self):
        return (self._seed, self._count)

    def set_state(self, state):
        self._seed, self._count = int(state[0]), int(state[1])


_GLOBAL = _GlobalGenerator(0)


def seed(s: int):
    """Set the global seed (`paddle.seed` parity)."""
    _GLOBAL.seed(s)
    return _GLOBAL


def get_rng_state():
    return _GLOBAL.get_state()


def set_rng_state(state):
    _GLOBAL.set_state(state)


def global_key() -> jax.Array:
    return _GLOBAL.next_key()


# ---- Traced rng streams ----------------------------------------------------

class _StreamFrame:
    def __init__(self, keys: Dict[str, jax.Array]):
        self.keys = dict(keys)
        self.counters: Dict[str, int] = {}


_tls = threading.local()


def _stack():
    if not hasattr(_tls, "stack"):
        _tls.stack = []
    return _tls.stack


@contextlib.contextmanager
def rng_guard(rngs: Optional[Dict[str, jax.Array]] = None, **kw):
    """Push named rng streams for the dynamic extent of a (possibly traced) call.

    >>> with rng_guard(dropout=key):
    ...     y = model(x)   # Dropout layers pull from the 'dropout' stream
    """
    keys = dict(rngs or {})
    keys.update(kw)
    frame = _StreamFrame(keys)
    _stack().append(frame)
    try:
        yield frame
    finally:
        _stack().pop()


def has_rng(name: str) -> bool:
    for frame in reversed(_stack()):
        if name in frame.keys:
            return True
    return False


def next_rng_key(name: str = "default") -> jax.Array:
    """Pull the next key from stream `name`; falls back to the eager global gen."""
    for frame in reversed(_stack()):
        if name in frame.keys:
            c = frame.counters.get(name, 0)
            frame.counters[name] = c + 1
            return jax.random.fold_in(frame.keys[name], c)
    # Eager fallback (outside jit): stateful global generator.
    try:
        from jax._src import core as _core
        if not _core.trace_state_clean():
            import warnings
            warnings.warn(
                f"next_rng_key({name!r}) called under jit tracing with no rng "
                "stream bound: the key becomes a compile-time constant, so "
                "every call of the compiled function reuses the same "
                "randomness. Pass rngs={...} to functional_call / rng_guard.",
                stacklevel=2)
    except ImportError:
        pass
    return _GLOBAL.next_key()


class RNGStatesTracker:
    """Named seeds for TP-aware dropout (`get_rng_state_tracker` parity).

    Register e.g. 'global_seed' (same on all mp ranks) and 'local_seed'
    (offset by mp rank); `rng_state(name)` scopes subsequent draws to it.
    """

    def __init__(self):
        self._seeds: Dict[str, int] = {}

    def add(self, name: str, seed_: int):
        if name in self._seeds:
            raise ValueError(f"rng state {name!r} already added")
        self._seeds[name] = int(seed_)

    def reset(self):
        self._seeds.clear()

    @contextlib.contextmanager
    def rng_state(self, name: str = "global_seed"):
        if name not in self._seeds:
            raise KeyError(f"rng state {name!r} not registered (have {sorted(self._seeds)})")
        key = jax.random.PRNGKey(self._seeds[name])
        with rng_guard(default=key, dropout=key):
            yield


_TRACKER = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _TRACKER


def model_parallel_random_seed(seed_: int, mp_rank: int = 0):
    """Set up 'global_seed'/'local_seed' streams the way Fleet TP does."""
    _TRACKER.reset()
    _TRACKER.add("global_seed", seed_ + 100003)
    _TRACKER.add("local_seed", seed_ + 100003 + 1024 * (1 + mp_rank))
    np.random.seed(seed_)
    seed(seed_)
