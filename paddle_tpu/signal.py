"""paddle.signal parity: stft / istft.

Reference: python/paddle/signal.py (SURVEY.md §2.7 tensor-API family).
TPU-native: frame + window + rfft/fft compose into XLA ops; istft is the
standard overlap-add with window-envelope normalization (COLA). Validated
against torch.stft/istft in tests/test_signal.py.
"""

from typing import Optional

import jax
import jax.numpy as jnp

__all__ = ["stft", "istft"]


def _frame(x, frame_length, hop_length):
    """(..., n) -> (..., frame_length, n_frames) (the reference layout)."""
    n = x.shape[-1]
    n_frames = 1 + (n - frame_length) // hop_length
    starts = jnp.arange(n_frames) * hop_length
    idx = starts[None, :] + jnp.arange(frame_length)[:, None]
    return jnp.take(x, idx, axis=-1)          # (..., frame_length, n_frames)


def stft(x, n_fft, hop_length: Optional[int] = None,
         win_length: Optional[int] = None, window=None, center: bool = True,
         pad_mode: str = "reflect", normalized: bool = False,
         onesided: bool = True):
    """paddle.signal.stft: returns (..., n_fft//2+1 | n_fft, n_frames)
    complex. Real input + onesided=True rides rfft."""
    x = jnp.asarray(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is None:
        window = jnp.ones((win_length,), x.dtype)
    window = jnp.asarray(window)
    if win_length < n_fft:                 # center-pad the window to n_fft
        lp = (n_fft - win_length) // 2
        window = jnp.pad(window, (lp, n_fft - win_length - lp))
    if center:
        pad = [(0, 0)] * (x.ndim - 1) + [(n_fft // 2, n_fft // 2)]
        x = jnp.pad(x, pad, mode=pad_mode)
    frames = _frame(x, n_fft, hop_length)          # (..., n_fft, n_frames)
    frames = frames * window[:, None]
    if jnp.iscomplexobj(frames) or not onesided:
        spec = jnp.fft.fft(frames, axis=-2)
        if onesided:
            spec = spec[..., : n_fft // 2 + 1, :]
    else:
        spec = jnp.fft.rfft(frames, axis=-2)
    if normalized:
        spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
    return spec


def istft(x, n_fft, hop_length: Optional[int] = None,
          win_length: Optional[int] = None, window=None, center: bool = True,
          normalized: bool = False, onesided: bool = True,
          length: Optional[int] = None, return_complex: bool = False):
    """paddle.signal.istft: inverse of stft by windowed overlap-add."""
    x = jnp.asarray(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is None:
        window = jnp.ones((win_length,), jnp.float32)
    window = jnp.asarray(window)
    if win_length < n_fft:
        lp = (n_fft - win_length) // 2
        window = jnp.pad(window, (lp, n_fft - win_length - lp))
    if normalized:
        x = x * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
    if onesided and return_complex:
        raise ValueError(
            "istft: onesided=True cannot return a complex signal (the "
            "reference rejects this combination)")
    if onesided and not return_complex:
        frames = jnp.fft.irfft(x, n=n_fft, axis=-2)   # (..., n_fft, T)
    else:
        frames = jnp.fft.ifft(x, n=n_fft, axis=-2)
        if not return_complex:
            frames = frames.real
    frames = frames * window[:, None]
    n_frames = frames.shape[-1]
    out_len = n_fft + hop_length * (n_frames - 1)
    lead = frames.shape[:-2]
    out = jnp.zeros(lead + (out_len,), frames.dtype)
    env = jnp.zeros((out_len,), jnp.float32)
    idx = (jnp.arange(n_frames)[:, None] * hop_length
           + jnp.arange(n_fft)[None, :])               # (T, n_fft)
    out = out.at[..., idx.ravel()].add(
        jnp.moveaxis(frames, -1, -2).reshape(lead + (-1,)))
    env = env.at[idx.ravel()].add(
        jnp.tile(jnp.square(window.astype(jnp.float32)), (n_frames,)))
    out = out / jnp.where(env > 1e-11, env, 1.0)
    if center:
        out = out[..., n_fft // 2:]
        if length is None:           # no target length: trim the tail half
            out = out[..., : out.shape[-1] - n_fft // 2]
    if length is not None:
        out = (jnp.pad(out, [(0, 0)] * (out.ndim - 1)
                       + [(0, max(0, length - out.shape[-1]))])
               [..., :length])
    return out
