"""Headline benchmark: GPT-2 345M pretrain tokens/sec/chip (+ MFU).

BASELINE.md config #1 ("GPT-2 345M single-device"). The reference repo
publishes no numbers (BASELINE.json "published": {}), so `vs_baseline`
reports measured MFU relative to the driver's north-star 45% MFU target —
1.0 means the north star is met on this chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
"""

import functools
import json
import time

import jax
import jax.numpy as jnp
import numpy as np


# bf16 peak FLOP/s per chip by device kind (public spec sheets)
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5": 459e12,        # v5p
    "TPU v5p": 459e12,
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,   # v6e / trillium
}


def main():
    import paddle_tpu
    from paddle_tpu.models.gpt import GPTConfig, GPTPretrainModel
    from paddle_tpu.nn.layer import functional_call
    from paddle_tpu.optimizer import AdamW

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"

    # a Pallas regression must FAIL the bench, not silently re-ride XLA
    # (no-op off-TPU: the kernels only dispatch on the TPU backend)
    paddle_tpu.set_flags({"FLAGS_pallas_strict": True})

    paddle_tpu.seed(0)
    cfg = GPTConfig.gpt2_medium()
    cfg.hidden_dropout_prob = 0.0
    cfg.attention_dropout_prob = 0.0
    if not on_tpu:          # CPU smoke: shrink so the bench still completes
        cfg = GPTConfig(vocab_size=50304, hidden_size=256, num_layers=4,
                        num_heads=8, max_position_embeddings=1024,
                        hidden_dropout_prob=0.0, attention_dropout_prob=0.0)

    model = GPTPretrainModel(cfg).bfloat16()
    n_params = model.num_params()

    # b8 is the single-chip sweet spot on v5e (b16 triggers XLA spilling)
    B, S = (8, 1024) if on_tpu else (2, 256)
    opt = AdamW(learning_rate=1e-4)
    state = model.trainable_state()
    opt_state = opt.init_state(state)

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (B, S + 1)))
    x, y = ids[:, :-1], ids[:, 1:]

    n_steps = 20 if on_tpu else 3

    def one_step(carry, _):
        state, opt_state = carry
        def loss_fn(s):
            logits = functional_call(model, s, x)
            return model.loss(logits, y)
        loss, grads = jax.value_and_grad(loss_fn)(state)
        state, opt_state = opt.update(grads, opt_state, state)
        return (state, opt_state), loss

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def run_steps(state, opt_state):
        (state, opt_state), losses = jax.lax.scan(
            one_step, (state, opt_state), None, length=n_steps)
        return state, opt_state, losses

    # warmup/compile (also amortizes any host↔device tunnel latency out of
    # the timed region — one dispatch covers all n_steps)
    state, opt_state, losses = run_steps(state, opt_state)
    float(losses[-1])

    t0 = time.perf_counter()
    state, opt_state, losses = run_steps(state, opt_state)
    loss = losses[-1]
    float(loss)          # full host sync
    dt = time.perf_counter() - t0

    # device-side step time from the xplane trace: the remote tunnel adds
    # ~10 ms of dispatch overhead per run() that is not the chip's time;
    # both numbers are reported, MFU uses the device clock when available
    dt_dev = None
    if on_tpu:
        try:
            import shutil
            from paddle_tpu.profiler import xplane
            shutil.rmtree("/tmp/bench_prof", ignore_errors=True)
            with jax.profiler.trace("/tmp/bench_prof"):
                state, opt_state, losses = run_steps(state, opt_state)
                loss = losses[-1]
                float(loss)
            dt_dev = xplane.device_total_seconds("/tmp/bench_prof",
                                                 "jit_run_steps")
        except Exception:
            pass

    tokens_per_step = B * S
    tok_s = tokens_per_step * n_steps / (dt_dev or dt)

    # train FLOPs/token ≈ 6N + attention term 12·L·h·S (h=hidden, causal ½·2)
    flops_per_token = 6 * n_params + 12 * cfg.num_layers * cfg.hidden_size * S
    peak = PEAK_FLOPS.get(dev.device_kind, 197e12 if on_tpu else 1e12)
    mfu = tok_s * flops_per_token / peak

    from paddle_tpu import observability as obs

    rec = obs.bench_record(
        "gpt2-345m tokens/sec/chip", round(tok_s, 1), "tokens/s",
        device=dev.device_kind,
        vs_baseline=round(mfu / 0.45, 4),
        mfu=round(mfu, 4),
        mfu_basis="dense_6n",
        params=n_params,
        batch=B, seq=S, steps=n_steps,
        step_time_ms=round(1000 * (dt_dev or dt) / n_steps, 2),
        wall_step_time_ms=round(1000 * dt / n_steps, 2),
        timing="device(xplane)" if dt_dev else "wall",
        final_loss=round(float(loss), 4),
        memory=obs.memory.memory_snapshot(),
    )
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
