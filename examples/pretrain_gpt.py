"""GPT-2 pretrain end-to-end (BASELINE config #1), exercising the full stack:
native data pipeline → fleet train step (any hybrid config) → checkpoints →
metrics. Runs on one TPU chip or the CPU simulator.

  python examples/pretrain_gpt.py --steps 20 --preset tiny
  python examples/pretrain_gpt.py --preset 345m --amp bfloat16 \
      --dp 1 --mp 1 --steps 100 --ckpt-dir /tmp/gpt_run
"""

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.io.lm_dataset import PackedTokenDataset
from paddle_tpu.models.gpt import GPTConfig, GPTPretrainModel
from paddle_tpu.optimizer import AdamW, lr as lr_mod, ClipGradByGlobalNorm
from paddle_tpu.parallel import fleet
from paddle_tpu.parallel.checkpoint import CheckpointManager
from paddle_tpu.parallel.strategy import DistributedStrategy
from paddle_tpu.profiler import MetricsLogger, StepTimer, model_flops_per_token


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", default="tiny", choices=["tiny", "345m"])
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=128)
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--mp", type=int, default=1)
    ap.add_argument("--pp", type=int, default=1)
    ap.add_argument("--sharding", type=int, default=1)
    ap.add_argument("--zero", type=int, default=0)
    ap.add_argument("--amp", default=None, choices=[None, "bfloat16", "float16"])
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--metrics", default="metrics.jsonl")
    args = ap.parse_args()

    paddle.seed(0)
    if args.preset == "tiny":
        cfg = GPTConfig.tiny(vocab_size=4096)
    else:
        cfg = GPTConfig.gpt2_medium()
    cfg.hidden_dropout_prob = 0.0
    cfg.attention_dropout_prob = 0.0
    if args.pp > 1:
        cfg.tie_word_embeddings = False

    s = DistributedStrategy()
    s.hybrid_configs = {"dp_degree": args.dp, "mp_degree": args.mp,
                        "pp_degree": args.pp,
                        "sharding_degree": args.sharding}
    if args.zero:
        s.sharding = True
        s.sharding_configs.stage = args.zero
    if args.pp > 1:
        s.pipeline = True
        s.pipeline_configs.accumulate_steps = max(2, args.pp)
    if args.amp:
        s.amp = True
        s.amp_configs.dtype = args.amp
    fleet.init(is_collective=True, strategy=s)

    model = GPTPretrainModel(cfg)
    print(f"model: {model.num_params() / 1e6:.1f}M params, "
          f"mesh={dict(fleet.get_fleet().mesh.shape)}")

    # synthetic corpus through the native packing pipeline
    rng = np.random.RandomState(0)
    tokens = rng.randint(1, cfg.vocab_size, 2_000_00).astype(np.int32)
    ds = PackedTokenDataset(tokens, seq_len=args.seq, eos_id=0)

    sched = lr_mod.LinearWarmup(lr_mod.CosineAnnealingDecay(3e-4, args.steps),
                                warmup_steps=max(2, args.steps // 20),
                                start_lr=0.0, end_lr=3e-4)
    opt = AdamW(learning_rate=sched, weight_decay=0.01,
                grad_clip=ClipGradByGlobalNorm(1.0))
    loss_fn = (None if args.pp > 1
               else lambda logits, b: model.loss(logits, b["labels"]))
    step_fn, init_fn = fleet.make_train_step(model, opt, loss_fn, strategy=s)
    state, opt_state = init_fn()

    mngr = (CheckpointManager(args.ckpt_dir, max_to_keep=2)
            if args.ckpt_dir else None)
    metrics = MetricsLogger(args.metrics)
    timer = StepTimer(model_flops_per_token(model.num_params()))

    step = 0
    while step < args.steps:
        for batch in ds.epoch_batches(args.batch, seed=step):
            if step >= args.steps:
                break
            with timer:
                state, opt_state, loss = step_fn(
                    state, opt_state,
                    {"input": jnp.asarray(batch["input"]),
                     "labels": jnp.asarray(batch["labels"])})
                jax.block_until_ready(loss)
            step += 1
            if step % 10 == 0 or step == args.steps:
                tps = timer.tokens_per_sec(args.batch * args.seq)
                print(f"step {step:5d}  loss {float(loss):.4f}  "
                      f"{tps:,.0f} tok/s")
                metrics.log(step=step, loss=float(loss), tokens_per_sec=tps,
                            mfu=timer.mfu(args.batch * args.seq))
            if mngr and step % 50 == 0:
                mngr.save(step, {"model": state, "opt": opt_state})
    if mngr:
        mngr.save(args.steps, {"model": state, "opt": opt_state}, force=True)
        mngr.wait_until_finished()
        print(f"checkpoints: {mngr.all_steps()}")


if __name__ == "__main__":
    main()
