"""Single-chip MoE training throughput (Mixtral-style).

Exercises the token-dispatch hot path (the global_scatter/gather
mechanism analog — SURVEY.md §2.6-EP) under real training on one chip;
the default 'fused' dispatch gathers expert input blocks directly from
the token rows and combines with an inverse-gather segment-sum (the r5
dispatch-residual redesign). MFU uses activated FLOPs (top-k experts per
token, not all E), the standard MoE accounting. `--xplane_breakdown`
dumps the bucketed per-op attribution (dispatch / expert matmul /
optimizer / attention) so the residual can be tracked across rounds.

Run: python examples/moe_bench.py [--layers 12 --experts 8]
"""

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

PEAK = {"TPU v5 lite": 197e12, "TPU v5e": 197e12, "TPU v5": 459e12,
        "TPU v4": 275e12, "TPU v6 lite": 918e12}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--experts", type=int, default=8)
    ap.add_argument("--hidden", type=int, default=1024)
    ap.add_argument("--ffn", type=int, default=2816)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=1024)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--dispatch", default="fused",
                    choices=["scatter", "sort", "fused", "einsum",
                             "alltoall", "dropless"])
    ap.add_argument("--xplane_breakdown", action="store_true",
                    help="dump the per-op residual attribution (dispatch / "
                         "expert matmul / optimizer / attention) from an "
                         "xplane trace of the timed step")
    # cf=1.0 in this parametrization (cap = cf*k*T/E) IS the GShard top-2
    # capacity convention (2.0*T/E); 1.25 adds headroom at 25% extra
    # expert compute
    ap.add_argument("--capacity_factor", type=float, default=1.0)
    ns = ap.parse_args()

    import paddle_tpu
    from paddle_tpu.models.mixtral import MixtralConfig, MixtralForCausalLM
    from paddle_tpu.nn.layer import functional_call
    from paddle_tpu.optimizer import AdamW

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    if not on_tpu:
        ns.layers, ns.hidden, ns.ffn, ns.seq, ns.steps = 2, 128, 256, 128, 2

    # a Pallas regression must FAIL the bench, not silently re-ride XLA
    paddle_tpu.set_flags({"FLAGS_pallas_strict": True})

    paddle_tpu.seed(0)
    cfg = MixtralConfig(
        vocab_size=32000 if on_tpu else 512, hidden_size=ns.hidden,
        intermediate_size=ns.ffn, num_layers=ns.layers,
        num_heads=max(4, ns.hidden // 64), num_kv_heads=max(4, ns.hidden // 128),
        max_position_embeddings=max(2048, ns.seq),
        num_experts=ns.experts, top_k=2,
        capacity_factor=ns.capacity_factor,
        moe_dispatch="scatter" if ns.dispatch == "dropless" else ns.dispatch,
        moe_dropless=ns.dispatch == "dropless")
    model = MixtralForCausalLM(cfg).bfloat16()
    n_params = model.num_params()
    opt = AdamW(learning_rate=1e-4, multi_precision=False)
    state = model.trainable_state()
    opt_state = opt.init_state(state)

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size,
                                  (ns.batch, ns.seq + 1)))
    x, y = ids[:, :-1], ids[:, 1:]

    def one_step(carry, _):
        state, opt_state = carry

        def loss_fn(s):
            out = functional_call(model, s, x)
            return model.loss(out, y)

        loss, grads = jax.value_and_grad(loss_fn)(state)
        state, opt_state = opt.update(grads, opt_state, state)
        return (state, opt_state), loss

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def run(state, opt_state):
        (state, opt_state), losses = jax.lax.scan(
            one_step, (state, opt_state), None, length=ns.steps)
        return state, opt_state, losses

    state, opt_state, losses = run(state, opt_state)
    float(losses[-1])
    t0 = time.perf_counter()
    state, opt_state, losses = run(state, opt_state)
    loss = float(losses[-1])
    dt = time.perf_counter() - t0

    # device-side step time via xplane (the tunnel adds ~10ms/dispatch of
    # wall overhead; the profiler reads the TPU's own clock)
    dt_dev = None
    if on_tpu:
        try:
            import shutil
            from paddle_tpu.profiler import xplane
            shutil.rmtree("/tmp/moe_bench_prof", ignore_errors=True)
            with jax.profiler.trace("/tmp/moe_bench_prof"):
                state, opt_state, losses = run(state, opt_state)
                float(losses[-1])
            dt_dev = xplane.device_total_seconds("/tmp/moe_bench_prof",
                                                 "jit_run")
        except Exception:
            pass

    # --xplane_breakdown: bucketed per-op attribution so the next round
    # can verify the dispatch residual shrank (works on the CPU sim too —
    # host planes are used when no device plane exists)
    breakdown = top_ops = None
    if ns.xplane_breakdown:
        try:
            import shutil
            from paddle_tpu.profiler import xplane
            shutil.rmtree("/tmp/moe_bench_bd", ignore_errors=True)
            with jax.profiler.trace("/tmp/moe_bench_bd"):
                state, opt_state, losses = run(state, opt_state)
                float(losses[-1])
            planes = xplane.load_latest("/tmp/moe_bench_bd")
            rows = xplane.op_summary(planes)
            if not rows:            # CPU sim: no TPU/GPU plane
                rows = xplane.op_summary(planes, device_only=False)
            breakdown = {k: round(v / ns.steps, 3) for k, v in
                         xplane.bucket_summary(rows).items()}
            top_ops = [{"name": r["name"][:64],
                        "total_ms": round(r["total_ms"], 3),
                        "pct": round(r["pct"], 2)} for r in rows[:10]]
        except Exception as e:
            breakdown = {"error": f"{type(e).__name__}: {e}"[:200]}

    tok_s = ns.batch * ns.seq * ns.steps / (dt_dev or dt)
    # activated params: attention + top_k of E experts + embeddings
    h, f, e, L = cfg.hidden_size, cfg.intermediate_size, cfg.num_experts, \
        cfg.num_layers
    expert_params = 3 * h * f
    act_params = n_params - L * e * expert_params + L * cfg.top_k * expert_params
    flops_tok = 6 * act_params + 12 * L * h * ns.seq
    mfu = tok_s * flops_tok / PEAK.get(dev.device_kind,
                                       197e12 if on_tpu else 1e12)
    from paddle_tpu import observability as obs

    rec = obs.bench_record(
        f"mixtral-{ns.layers}L-{ns.experts}e train tokens/s/chip",
        round(tok_s, 1), "tokens/s",
        device=dev.device_kind,
        dispatch=ns.dispatch,
        mfu=round(mfu, 4),
        mfu_basis="activated",
        params=n_params,
        params_activated=act_params,
        batch=ns.batch, seq=ns.seq, steps=ns.steps,
        step_time_ms=round(1000 * (dt_dev or dt) / ns.steps, 2),
        wall_step_time_ms=round(1000 * dt / ns.steps, 2),
        timing="device(xplane)" if dt_dev else "wall",
        final_loss=round(loss, 4),
        memory=obs.memory.memory_snapshot(),
        **({"xplane_breakdown_ms_per_step": breakdown,
            "xplane_top_ops": top_ops} if ns.xplane_breakdown else {}),
    )
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
