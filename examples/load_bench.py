"""Open-loop serving load harness: the latency-vs-throughput curve.

``serving_bench.py`` answers "how much faster is continuous batching
than static batching" with a *closed* virtual clock — useful for the
A/B, useless for SLOs: closed-loop arrival generators slow down when
the server slows down, which hides exactly the queueing tails
production traffic produces. This harness drives the
``ServingEngine`` **open-loop**: arrivals land at wall-clock times
drawn independently of engine progress (Poisson, or bursty on-off),
so when the engine falls behind, the queue — and TTFT — grow the way
they do under real overload.

The sweep: offered load is expressed as multiples of the engine's
*calibrated* capacity (a closed-loop saturated drain measures
tokens/s, converted to requests/s via the mean budget), so
``--loads 0.5,0.9,1.5`` means the same thing on a laptop CPU and a
v5e. Each point emits one ``paddle_tpu.bench/v1`` record carrying the
percentile fields (``observability.SLOReport``): p50/p95/p99
TTFT/TPOT, token-weighted **goodput-under-SLO** against the
``(--slo_ttft_s, --slo_tpot_s)`` target, offered vs achieved request
rate, and the per-segment step-time breakdown from the engine stats.
A final record names the **goodput knee** — the highest offered load
whose goodput still clears ``--knee_goodput`` — which is the serving
headline ROADMAP's SLO item asks for (and the regression baseline the
chunked-prefill / speculative PRs will move). Run:

    python examples/load_bench.py [--model llama-medium]
        [--arrivals poisson|bursty] [--loads 0.5,0.9,1.5]
        [--slo_ttft_s 2.0] [--slo_tpot_s 0.25]
        [--flight_dump /tmp/flight.jsonl]
        [--shed [--max_queue N] [--deadline_s D]]
        [--priority_mix "low:1,normal:2,high:1"]

``--shed`` arms the PR 8 overload controls (bounded queue +
deadline-infeasibility rejection) for the measured points — the A/B
against unshedded collapse: past the knee the unshedded queue grows
without bound and ``ttft_p99_s`` explodes, while the shedded run keeps
the ADMITTED requests' tails flat and reports the drop as
``shed_rate``. ``--priority_mix`` adds classes, which also exercises
displacement shedding and slot preemption (``preemptions`` field).

``--chunk_tokens N`` + ``--prompt_mix long`` is the chunked-prefill
A/B (docs/SERVING.md §Chunked prefill; BENCH_r06): under a bimodal
prompt mix a monolithic wave prefill stalls every decode slot per
long prompt (``tpot_p99_s`` grows with load), while the chunked
engine bounds the stall at one chunk — run both arms on the same box
with the same seed and compare ``tpot_p99_s``/goodput per point
(records carry ``chunk_tokens``/``prefill_chunks``).

``--speculate k`` + ``--prompt_mix repeat`` is the speculative A/B
(docs/SERVING.md §Speculative decoding; BENCH_r07): motif-tiled
prompts make the n-gram proposer fire, and each record carries
``acceptance_rate``/``accepted_len_hist``/``dispatches_per_token`` —
the CPU gate is fused dispatches per committed token (CPU wall time
is compute-bound and pays the verify tail's extra matmuls; the TPU
kernel streams weights once per tail, so dispatches/token is the
proxy for the on-chip speedup).

Prefix caching is off here (random prompts never share blocks) and
prompt lengths quantize to few pad shapes, keeping prefill compile
churn out of the measured tails; the first sweep point still pays any
residual compiles, so compare points within a run, not across runs.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from serving_bench import (add_mesh_args, add_offload_args,
                           add_timeline_arg, build_engine_mesh,
                           build_model, build_speculate, mesh_fields,
                           offload_engine_kwargs, offload_fields,
                           spec_fields, spec_hist_base, timeline_fields)


def parse_priority_mix(spec):
    """``"low:1,normal:2,high:1"`` -> (names, weights). Empty/None means
    every request rides the default class."""
    if not spec:
        return None
    names, weights = [], []
    for part in spec.split(","):
        name, _, w = part.partition(":")
        names.append(name.strip())
        weights.append(float(w) if w else 1.0)
    total = sum(weights)
    return names, [w / total for w in weights]


def make_requests(ns, rng):
    """N requests with uniform prompt lengths / budgets (the queueing
    dynamics, not the length mix, are under test here); ``--priority_mix``
    assigns classes, ``--deadline_s`` attaches a deadline to every
    request (what infeasibility shedding prices).

    ``--prompt_mix long`` makes the length mix bimodal: ``--long_frac``
    of the requests carry a ``--long_prompt``-token prompt — the
    head-of-line regime where one monolithic wave prefill stalls every
    active decode slot (the chunked-prefill A/B; docs/SERVING.md
    §Chunked prefill)."""
    mix = parse_priority_mix(getattr(ns, "priority_mix", None))
    pmix = getattr(ns, "prompt_mix", "uniform")
    long_mix = pmix == "long"
    # 'repeat': each prompt tiles a short per-request motif — the
    # extraction/quoting-style repetitive regime where the n-gram
    # proposer's suffix match actually fires (the speculative A/B mix;
    # greedy decode of a repetitive prompt also cycles, which
    # self-speculation exploits)
    repeat_mix = pmix == "repeat"
    reqs = []
    for _ in range(ns.requests):
        if long_mix and rng.random_sample() < ns.long_frac:
            plen = int(ns.long_prompt)
        else:
            plen = int(rng.randint(ns.min_prompt, ns.max_prompt + 1))
        budget = int(rng.randint(ns.min_new, ns.max_new + 1))
        prio = (mix[0][int(rng.choice(len(mix[0]), p=mix[1]))]
                if mix else "normal")
        if repeat_mix:
            motif = rng.randint(3, ns.vocab, (max(2, plen // 4),))
            prompt = np.tile(motif, -(-plen // len(motif)))[:plen]
        else:
            prompt = rng.randint(3, ns.vocab, (plen,))
        reqs.append(dict(prompt=prompt,
                         budget=budget, priority=prio,
                         deadline=getattr(ns, "deadline_s", None)))
    return reqs


def gen_arrivals(n, rps, mode, rng, on_s=0.5, off_s=0.5):
    """Wall-clock arrival offsets (seconds from t0) for ``n`` requests
    at mean rate ``rps``.

    ``poisson``: i.i.d. exponential gaps. ``bursty``: on-off modulated
    Poisson — exponential ON windows (mean ``on_s``) arriving at
    ``rps / duty`` so the long-run mean is still ``rps``, separated by
    exponential OFF gaps (mean ``off_s``); the bursts are what stress
    admission and the queue."""
    if mode == "poisson":
        return np.cumsum(rng.exponential(1.0 / rps, n))
    duty = on_s / (on_s + off_s)
    rate_on = rps / duty
    out = []
    t = 0.0
    while len(out) < n:
        on_end = t + rng.exponential(on_s)
        while len(out) < n:
            t += rng.exponential(1.0 / rate_on)
            if t > on_end:
                break
            out.append(t)
        t = max(t, on_end) + rng.exponential(off_s)
    return np.asarray(out[:n])


def drive_open_loop(eng, reqs, arrivals):
    """Submit request i once the wall clock passes ``arrivals[i]``,
    stepping the engine regardless of queue state (open loop). Returns
    (wall seconds from first arrival epoch to full drain, rejected
    count) — with shedding enabled a submit may raise
    ``serving.Rejected`` (queue full / deadline infeasible), which is a
    *measured outcome* here, not an error."""
    from paddle_tpu import serving

    n = len(reqs)
    i = 0
    rejected = 0
    t0 = time.perf_counter()
    while i < n or not eng.idle:
        now = time.perf_counter() - t0
        while i < n and arrivals[i] <= now:
            r = reqs[i]
            try:
                eng.submit(serving.Request(
                    r["prompt"], max_new_tokens=r["budget"],
                    priority=r.get("priority", "normal"),
                    deadline_s=r.get("deadline")))
            except serving.Rejected:
                rejected += 1
            i += 1
        if eng.idle and i < n:
            # nothing in flight: sleep toward the next arrival instead
            # of spinning the scheduler against an empty batch
            time.sleep(min(max(arrivals[i] - now, 0.0), 0.05))
            continue
        eng.step()
    return time.perf_counter() - t0, rejected


def calibrate(eng, reqs, reps=1):
    """Closed-loop saturated pass: submit everything at t=0, drain.
    Doubles as compile warmup (prefill shapes + the step program) and
    yields the capacity estimate the load multiples are scaled by.

    ``reps`` > 1 keeps the BEST pass (highest tokens/s) — the same
    best-of-reps convention serving_bench uses for its interleaved A/B
    pairs. The box's CPU budget swings ~2x over tens of seconds, and
    the chunked-vs-monolithic A/B runs its arms as back-to-back
    processes: a single calibration pass landing in a slow window
    would deflate that arm's re-measured capacity (and inflate its
    absolute offered rates) by pure scheduling noise. Best-of filters
    the contention the way adjacent interleaved passes do."""
    from paddle_tpu import serving

    best_tok_s = 0.0
    mean_budget = sum(r["budget"] for r in reqs) / len(reqs)
    for _ in range(max(1, reps)):
        eng.reset_stats()
        eng.results.clear()
        t0 = time.perf_counter()
        for r in reqs:
            eng.submit(serving.Request(r["prompt"],
                                       max_new_tokens=r["budget"]))
            eng.step()      # staggered submits compile small-wave shapes
        eng.drain()
        wall = time.perf_counter() - t0
        st = eng.stats
        tok_s = (st["decode_tokens"] + st["requests_finished"]) / wall
        best_tok_s = max(best_tok_s, tok_s)
    return best_tok_s, best_tok_s / mean_budget     # tokens/s, requests/s


def step_breakdown(stats):
    steps = max(stats["steps"], 1)
    return {k: round(stats[f"step_{k}_s"] / steps, 6)
            for k in ("admit", "prefill", "dispatch", "sync")}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default=None)
    ap.add_argument("--requests", type=int, default=48,
                    help="requests per offered-load point")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--block_tokens", type=int, default=32)
    ap.add_argument("--max_seq_len", type=int, default=None)
    ap.add_argument("--min_prompt", type=int, default=8)
    ap.add_argument("--max_prompt", type=int, default=24)
    ap.add_argument("--min_new", type=int, default=8)
    ap.add_argument("--max_new", type=int, default=32)
    ap.add_argument("--prompt_mix", choices=("uniform", "long", "repeat"),
                    default="uniform",
                    help="'long' = bimodal prompt lengths: --long_frac "
                    "of requests carry a --long_prompt-token prompt "
                    "(the prefill head-of-line-blocking regime the "
                    "chunked-prefill A/B measures); 'repeat' = "
                    "motif-tiled repetitive prompts (the regime the "
                    "speculative n-gram proposer accelerates — the "
                    "--speculate A/B mix)")
    ap.add_argument("--long_prompt", type=int, default=256,
                    help="long-prompt length for --prompt_mix long")
    ap.add_argument("--long_frac", type=float, default=0.25,
                    help="fraction of long prompts for --prompt_mix "
                    "long")
    ap.add_argument("--chunk_tokens", type=int, default=None,
                    help="arm chunked prefill: prompts prefill this "
                    "many tokens per program, interleaved with decode "
                    "(None = monolithic wave prefill — the A/B "
                    "baseline). Must be a multiple of --block_tokens")
    ap.add_argument("--decode_per_chunk", type=int, default=1,
                    help="decode dispatches guaranteed between "
                    "consecutive prefill chunks")
    ap.add_argument("--arrivals", choices=("poisson", "bursty"),
                    default="poisson")
    ap.add_argument("--burst_on_s", type=float, default=0.5)
    ap.add_argument("--burst_off_s", type=float, default=0.5)
    ap.add_argument("--loads", default="0.5,0.9,1.5",
                    help="offered load as multiples of calibrated "
                    "capacity (comma list; >1 is deliberate overload — "
                    "that is where the knee lives)")
    ap.add_argument("--slo_ttft_s", type=float, default=2.0)
    ap.add_argument("--slo_tpot_s", type=float, default=0.25)
    ap.add_argument("--knee_goodput", type=float, default=0.9,
                    help="goodput threshold defining the knee")
    ap.add_argument("--cache_int8", action="store_true")
    ap.add_argument("--shed", action="store_true",
                    help="enable load shedding: bounded queue "
                    "(--max_queue) + deadline-infeasibility rejection — "
                    "the A/B against unshedded overload collapse")
    ap.add_argument("--max_queue", type=int, default=None,
                    help="queue bound when --shed (default 4*slots)")
    ap.add_argument("--priority_mix", default=None,
                    help='e.g. "low:1,normal:2,high:1" — weighted '
                    "random priority classes (exercises displacement "
                    "shedding and slot preemption)")
    ap.add_argument("--deadline_s", type=float, default=None,
                    help="per-request deadline (what --shed's "
                    "infeasibility estimator prices)")
    ap.add_argument("--flight_dump", default=None,
                    help="flight-recorder auto-dump path (postmortems "
                    "on fault/pool/deadline events)")
    ap.add_argument("--sanitize", action="store_true",
                    help="arm the dispatch sanitizer: steady-state "
                         "engine steps must perform 0 H2D transfers "
                         "and 0 recompiles or the bench dies "
                         "(paddle_tpu.analysis.runtime)")
    ap.add_argument("--speculate", type=int, default=0,
                    help="arm speculative decoding with k proposals "
                    "per slot per tick (0 = off) — pair with "
                    "--prompt_mix repeat for the goodput A/B; records "
                    "grow acceptance_rate/accepted_len_hist/"
                    "dispatches_per_token")
    ap.add_argument("--proposer", choices=("ngram", "draft"),
                    default="ngram",
                    help="speculative proposer (see serving_bench)")
    ap.add_argument("--draft_model", default="llama-tiny",
                    help="draft model name for --proposer draft")
    ap.add_argument("--replicas", type=int, default=1,
                    help="drive the replicated tier (serving.Router "
                    "over N engine replicas, prefix-affinity + least-"
                    "loaded placement) instead of one engine — the "
                    "tier's latency/throughput curve")
    ap.add_argument("--calib_reps", type=int, default=3,
                    help="warm calibration passes (best tokens/s kept) "
                    "— best-of-reps filters CPU-contention noise out of "
                    "the capacity estimate, matching serving_bench's "
                    "interleaved-pair convention")
    ap.add_argument("--chunk_autotune", action="store_true",
                    help="autotune the chunk size per admission: the "
                    "engine picks the largest power-of-two chunk bucket "
                    "whose predicted fused-tick time fits under "
                    "--slo_tpot_s (requires --chunk_tokens as the cold "
                    "default)")
    add_mesh_args(ap)
    add_offload_args(ap)
    add_timeline_arg(ap)
    ap.add_argument("--seed", type=int, default=0)
    ns = ap.parse_args()

    dev = jax.devices()[0]
    name = ns.model or ("llama-345m" if dev.platform == "tpu"
                        else "llama-medium")
    cfg, model = build_model(name)
    ns.vocab = cfg.vocab_size
    if ns.max_seq_len is None:
        top_prompt = (max(ns.max_prompt, ns.long_prompt)
                      if ns.prompt_mix == "long" else ns.max_prompt)
        need = top_prompt + ns.max_new
        ns.max_seq_len = -(-need // ns.block_tokens) * ns.block_tokens

    from paddle_tpu import observability as obs
    from paddle_tpu import serving

    max_queue = (ns.max_queue if ns.max_queue is not None
                 else 4 * ns.slots) if ns.shed else None
    ekw = dict(
        max_slots=ns.slots, block_tokens=ns.block_tokens,
        max_seq_len=ns.max_seq_len,
        cache_dtype=jnp.int8 if ns.cache_int8 else jnp.bfloat16,
        prefix_caching=False, flight_dump_path=ns.flight_dump,
        chunk_tokens=ns.chunk_tokens,
        decode_per_chunk=ns.decode_per_chunk,
        speculate=build_speculate(ns),
        mesh=build_engine_mesh(ns),
        sanitize=ns.sanitize,
        **offload_engine_kwargs(ns))
    if ns.chunk_autotune:
        ekw.update(chunk_autotune=True, slo_tpot_s=ns.slo_tpot_s)
    if ns.replicas > 1:
        eng = serving.Router(model, replicas=ns.replicas,
                             snapshot_every=None, **ekw)
    else:
        eng = serving.ServingEngine(model, **ekw)

    rng = np.random.RandomState(ns.seed)
    reqs = make_requests(ns, rng)
    calibrate(eng, reqs)                # cold pass: compiles dominate
    # warm passes, best-of-reps: the capacity estimate (and the chunked
    # A/B's re-measured absolute capacity) filters CPU-contention noise
    cap_tok_s, cap_rps = calibrate(eng, reqs, reps=ns.calib_reps)
    print(f"# calibrated capacity: {cap_tok_s:.1f} tokens/s "
          f"~ {cap_rps:.2f} req/s", file=sys.stderr)
    # shedding arms AFTER calibration (the saturated closed-loop pass
    # would otherwise shed its own warmup) — the measured points see the
    # bounded queue + infeasibility estimator
    if ns.replicas > 1:
        eng.set_overload_controls(max_queue=max_queue,
                                  shed_infeasible=ns.shed)
    else:
        eng.max_queue = max_queue
        eng.shed_infeasible = ns.shed

    curve = []
    loads = [float(x) for x in ns.loads.split(",") if x]
    for mult in loads:
        rps = mult * cap_rps
        arrivals = gen_arrivals(ns.requests, rps, ns.arrivals, rng,
                                ns.burst_on_s, ns.burst_off_s)
        eng.reset_stats()
        eng.results.clear()
        # accepted-length histogram base: the registry histogram is
        # process-global, so each point's record diffs against this
        # snapshot (calibration + earlier points must not leak in)
        hist_base = spec_hist_base(ns)
        wall, rejected = drive_open_loop(eng, reqs, arrivals)
        rep = obs.SLOReport(ns.slo_ttft_s, ns.slo_tpot_s)
        served = 0
        for res in eng.results.values():
            if res.finish == "shed":
                continue        # displaced: counted in shed_rate, not
            served += 1         # in the served-latency percentiles
            rep.add(res.ttft_s, res.tpot_s, tokens=max(1, res.gen_len))
        st = eng.stats
        shed = rejected + st["requests_shed"]
        tok_s = (st["decode_tokens"] + served) / wall
        rec = obs.bench_record(
            f"{name} open-loop {ns.arrivals} {mult:g}x tokens/s",
            round(tok_s, 1), "tokens/s", device=dev.device_kind,
            timing="wall", batch=ns.slots, mode=ns.arrivals,
            load_mult=mult, n_requests=ns.requests,
            offered_rps=round(rps, 4),
            achieved_rps=round(served / wall, 4),
            occupancy=round(st["decode_tokens"] / max(
                st["decode_tokens"] + st["idle_slot_steps"], 1), 3),
            step_breakdown_s=step_breakdown(st),
            shed_rate=round(shed / ns.requests, 4),
            preemptions=st["preemptions"],
            replicas=ns.replicas,
            prompt_mix=ns.prompt_mix,
            chunk_tokens=ns.chunk_tokens,
            prefill_chunks=st["prefill_chunks"],
            # the speculative perf gate's metric: fused dispatches a
            # slot pays per committed token (1.0 without speculation)
            dispatches_per_token=round(
                st["decode_slot_dispatches"]
                / max(st["decode_tokens"], 1), 4),
            **spec_fields(eng, ns, hist_base),
            **offload_fields(eng, ns),
            **({"tier_prefix_hit_rate":
                round(eng.tier_prefix_hit_rate, 4)}
               if ns.replicas > 1 else {}),
            **mesh_fields(ns, ekw["mesh"]), **rep.bench_fields())
        print(json.dumps(rec))
        curve.append(dict(load_mult=mult, offered_rps=round(rps, 4),
                          tokens_per_s=round(tok_s, 1),
                          goodput=rec["goodput"],
                          shed_rate=rec["shed_rate"],
                          ttft_p99_s=rec["ttft_p99_s"],
                          tpot_p99_s=rec["tpot_p99_s"]))

    # the knee: highest offered load still clearing the goodput bar —
    # the number a capacity planner actually provisions against
    good = [c for c in curve if c["goodput"] >= ns.knee_goodput]
    knee = max(good, key=lambda c: c["offered_rps"]) if good else None
    rec = obs.bench_record(
        f"{name} goodput-under-SLO knee ({ns.arrivals})",
        knee["offered_rps"] if knee else 0.0, "req/s",
        device=dev.device_kind, timing="wall",
        slo_ttft_s=ns.slo_ttft_s, slo_tpot_s=ns.slo_tpot_s,
        knee_goodput=ns.knee_goodput,
        knee_load_mult=knee["load_mult"] if knee else None,
        prompt_mix=ns.prompt_mix, chunk_tokens=ns.chunk_tokens,
        calibrated_capacity_rps=round(cap_rps, 4), curve=curve,
        # the flight ring (and results) cover the LAST sweep point —
        # the timeline is that point's postmortem window
        **timeline_fields(ns, eng))
    print(json.dumps(rec))
    eng.close()         # free the KV pool (long sweeps, repeated runs)


if __name__ == "__main__":
    main()
