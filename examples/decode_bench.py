"""Decode-step benchmark vs HBM roofline (fused_multi_transformer parity).

The reference's inference crown jewel is the fused decode-step kernel
(phi/kernels/fusion/gpu/fused_multi_transformer_op.cu +
masked_multihead_attention): one token per step, the whole layer stack in
one kernel chain. The TPU-native equivalent is the scan-fused decode in
`paddle_tpu.inference.generate` — the entire decode loop is ONE XLA
program, so XLA fuses per-layer matmul→rope→cache-update→attention chains
the way the CUDA kernel hand-fuses them.

Decode is HBM-bandwidth bound: every step must read all parameters once
(batch-amortized) plus each sequence's KV cache. This bench measures
achieved decode tokens/s and compares against that roofline:

    bytes/step  =  param_bytes  +  B · kv_bytes(cache_len)
    roofline tok/s  =  B · HBM_BW / bytes_per_step

Run: python examples/decode_bench.py [--model llama-1b|gpt2-345m]
[--batch 8] [--int8] [--cache_int8]. Prints one JSON line; SCALE.md
records the measured table (fused decode-step kernel, device-clock
timing). The long-context int8-KV-cache row (cache bytes dominate):
python examples/decode_bench.py --model llama-345m --prompt_len 2048
--new_tokens 256 --cache_int8
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

# HBM bandwidth by device kind (public spec sheets, GB/s)
HBM_BW = {
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v5": 2765e9,
    "TPU v5p": 2765e9,
    "TPU v4": 1228e9,
    "TPU v6 lite": 1640e9,
}


def build_model(name):
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    if name == "gpt2-345m":
        from paddle_tpu.models.gpt import GPTConfig, GPTPretrainModel
        cfg = GPTConfig.gpt2_medium()
        cfg.hidden_dropout_prob = 0.0
        cfg.attention_dropout_prob = 0.0
        m = GPTPretrainModel(cfg).bfloat16()
        m.eval()
        return cfg, m
    if name == "llama-tiny":  # CPU smoke
        cfg = LlamaConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                          num_heads=4, num_kv_heads=4, intermediate_size=256,
                          max_position_embeddings=512)
    elif name == "llama-345m":
        cfg = LlamaConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                          num_heads=16, num_kv_heads=16,
                          intermediate_size=2816,
                          max_position_embeddings=2048)
    elif name == "llama-1b":  # TinyLlama-1.1B shape
        cfg = LlamaConfig(vocab_size=32000, hidden_size=2048, num_layers=22,
                          num_heads=32, num_kv_heads=4,
                          intermediate_size=5632,
                          max_position_embeddings=2048)
    elif name == "llama2-7b":
        # Llama-2-7B, served int8 weight-only via the stacked-weight
        # engine (inference.stacked): ~6.6 GiB int8 weights + KV cache fit
        # the 16 GiB v5e with ONE weight image; the fused kernel streams
        # qkv in column phases (decode_block_plan q_split) because the 7B
        # attention weights cannot double-buffer whole in VMEM
        cfg = LlamaConfig.llama2_7b()
        return cfg, None          # built via StackedLlamaDecoder below
    elif name == "mixtral-1b":
        # the moe_bench shape (0.93 B total / 0.31 B activated): 12L ×
        # 8 experts top-2 — decodes through the fused MoE kernel, which
        # streams only the routed experts' weights per token
        from paddle_tpu.models.mixtral import (MixtralConfig,
                                               MixtralForCausalLM)
        cfg = MixtralConfig(vocab_size=32000, hidden_size=1024,
                            intermediate_size=2816, num_layers=12,
                            num_heads=16, num_kv_heads=8,
                            max_position_embeddings=2048,
                            num_experts=8, top_k=2)
        m = MixtralForCausalLM(cfg).bfloat16()
        m.eval()
        return cfg, m
    elif name == "deepseek-16b-d4":
        # DeepSeekMoE-16B cross-section (BASELINE #4's first-named MoE):
        # the full 28-layer width — 64 fine-grained experts top-6 + 2
        # shared experts, vocab 102400 — depth-reduced to 4 layers so the
        # layered-prefill + stacked-decode weight pair fits a 16 GiB v5e.
        # The fused kernel streams the 2 shared experts as dense SwiGLU
        # blocks and exactly 6 routed experts per token.
        import dataclasses
        from paddle_tpu.models.mixtral import (MixtralConfig,
                                               MixtralForCausalLM)
        cfg = dataclasses.replace(MixtralConfig.deepseek_moe_16b(),
                                  num_layers=4,
                                  max_position_embeddings=2048)
        m = MixtralForCausalLM(cfg).bfloat16()
        m.eval()
        return cfg, m
    else:
        raise SystemExit(f"unknown model {name}")
    return cfg, LlamaForCausalLM(cfg).bfloat16()


def kv_bytes_per_token(cfg, dtype_bytes=2):
    head_dim = cfg.hidden_size // cfg.num_heads
    nkv = getattr(cfg, "kv_heads", None) or cfg.num_kv_heads
    return 2 * cfg.num_layers * nkv * head_dim * dtype_bytes


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default=None)
    ap.add_argument("--batch", type=int, default=None,
                    help="default 8 (1 for mixtral-1b: the fused MoE "
                    "kernel's no-drop gate caps batch at 2)")
    ap.add_argument("--prompt_len", type=int, default=128)
    ap.add_argument("--new_tokens", type=int, default=256)
    ap.add_argument("--int8", action="store_true",
                    help="weight-only int8 (halves the weight stream — "
                    "the fused_multi_transformer_int8 analog)")
    ap.add_argument("--cache_int8", action="store_true",
                    help="int8 KV cache (fused_multi_transformer_int8 "
                    "cache_kv quant analog): prefill calibrates per-head "
                    "scales, decode streams int8 KV — the long-context "
                    "(s >= 2048) row where cache bytes dominate runs "
                    "--prompt_len 2048 --cache_int8")
    ap.add_argument("--traced", action="store_true",
                    help="attach an observability.Tracer for the final "
                    "timed run: emits request spans (TTFT/TPOT/per-chunk "
                    "decode) into the BENCH json and "
                    "/tmp/decode_bench_spans.jsonl — the per-phase "
                    "evidence the SCALE.md re-measure rows ask for")
    ap.add_argument("--report_plan", default=None, metavar="PATH",
                    help="write the analytic roofline plan here; feed it "
                    "to `python examples/scale_report.py --report "
                    "/tmp/decode_bench_prof --plan PATH` for the "
                    "per-phase %%-of-roofline table")
    ap.add_argument("--sanitize", action="store_true",
                    help="pin the warm path: after warmup, one "
                         "generate pair runs under no_recompile "
                         "and dies on any compile "
                         "(paddle_tpu.analysis.runtime)")
    ap.add_argument("--reps", type=int, default=3,
                    help="wall-timing repetitions (CI smoke uses 1)")
    ap.add_argument("--eos", type=int, default=None,
                    help="eos token id: adds a static-path pad-waste "
                    "accounting pass — generate(return_lengths=True) "
                    "reports per-row generated length, and every decode "
                    "step past a row's eos is waste the continuous-"
                    "batching engine (examples/serving_bench.py) "
                    "reclaims")
    ap.add_argument("--device_time", action="store_true",
                    help="force the xplane device-clock pass off-TPU "
                    "(on TPU it always runs; the CPU backend yields no "
                    "device plane and trace start/stop costs ~15 s on "
                    "the bare container, so CPU smoke skips it)")
    ns = ap.parse_args()

    import paddle_tpu
    from paddle_tpu.inference import generate

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    name = ns.model or ("llama-345m" if on_tpu else "llama-tiny")
    if ns.batch is None:
        ns.batch = 1 if name in ("mixtral-1b", "deepseek-16b-d4") else 8
    if not on_tpu:
        ns.batch, ns.prompt_len, ns.new_tokens = 2, 8, 16

    # a Pallas regression must FAIL the bench, not silently re-ride XLA
    paddle_tpu.set_flags({"FLAGS_pallas_strict": True})

    if name == "llama2-7b" and not ns.int8:
        print("note: llama2-7b implies --int8 (bf16 weights alone exceed "
              "a 16 GiB v5e)", file=sys.stderr)
        ns.int8 = True

    paddle_tpu.seed(0)
    cfg, model = build_model(name)
    if model is None:        # stacked-weight engine (7B-class)
        from paddle_tpu.inference.stacked import StackedLlamaDecoder
        model = StackedLlamaDecoder.from_config(cfg, int8=ns.int8)
    n_params = model.num_params()
    moe = name in ("mixtral-1b", "deepseek-16b-d4")
    if moe:
        # the streaming roofline below describes the fused MoE kernel;
        # refuse to silently measure the all-experts scan fallback
        # (FLAGS_pallas_strict can't catch this: no kernel failure occurs)
        plan = model.fused_decode_plan(model.trainable_state(), probe=True)
        if plan is None:
            raise SystemExit(
                f"{name} config is ineligible for the fused MoE decode "
                "kernel (fused_decode_plan returned None) — it would "
                "silently measure the all-experts scan fallback")
        if ns.batch > plan["max_batch"]:
            raise SystemExit(
                f"{name} fused decode needs batch <= "
                f"{plan['max_batch']}; got {ns.batch}")
    stacked = name == "llama2-7b"
    if stacked:
        state = None              # the engine owns its (int8) stacks
    elif ns.int8:
        from paddle_tpu.quantization import quantize_model, quantized_state
        quantize_model(model)
        state = quantized_state(model)
    else:
        state = model.trainable_state()

    cache_dtype = jnp.int8 if ns.cache_int8 else jnp.bfloat16

    rng = np.random.RandomState(0)
    prompt = jnp.asarray(
        rng.randint(0, cfg.vocab_size, (ns.batch, ns.prompt_len)))

    # The whole decode loop is ONE dispatch; through the remote-TPU tunnel
    # block_until_ready does not actually fence, and each dispatch carries
    # ~70 ms of relay latency. So (a) force completion by pulling a value
    # that depends on the last token, (b) time two decode lengths and use
    # the marginal time per token, cancelling the fixed dispatch cost.
    def timed(n_tokens):
        if stacked:
            out = model.generate(prompt, max_new_tokens=n_tokens,
                                 temperature=0.0, cache_dtype=cache_dtype)
        else:
            out = generate(model, prompt, max_new_tokens=n_tokens,
                           temperature=0.0, state=state,
                           cache_dtype=cache_dtype)
        return int(out[:, -1].sum())  # sync on dependent value

    n_short = max(8, ns.new_tokens // 4)
    timed(n_short)            # compile both lengths
    timed(ns.new_tokens)
    if ns.sanitize:
        # warm-path pin: the measured reps below must be pure cache
        # hits — a recompile here is exactly the silent regression the
        # sanitizer exists to catch (docs/ANALYSIS.md)
        from paddle_tpu.analysis import runtime as _sanitizer
        with _sanitizer.no_recompile(
                what="warm decode_bench generate pair"):
            timed(n_short)
            timed(ns.new_tokens)
    # the tunnel adds 10-300 ms of nondeterministic wall overhead per
    # dispatch; measure the DEVICE clock via the xplane parser when
    # available (min-of-reps wall marginal as fallback), marginal between
    # the two decode lengths to cancel prefill + fixed costs
    # wall reps run UNTRACED (the r2 methodology, clean fallback); one
    # traced pair afterwards supplies the device-clock numbers
    reps = max(ns.reps, 1)
    t_short, t_long = [], []
    for _ in range(reps):
        t0 = time.perf_counter()
        timed(n_short)
        t_short.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        timed(ns.new_tokens)
        t_long.append(time.perf_counter() - t0)

    def device_time(n):
        import shutil
        d = "/tmp/decode_bench_prof"
        shutil.rmtree(d, ignore_errors=True)
        with jax.profiler.trace(d):
            timed(n)
        from paddle_tpu.profiler import xplane
        return xplane.device_total_seconds(d, "jit_run")

    try:
        # any accelerator gets the device-clock pass (the xplane parser
        # reads GPU planes too); only the CPU backend — which yields no
        # device plane and pays ~15 s of trace start/stop on the bare
        # container — skips it unless forced
        if dev.platform != "cpu" or ns.device_time:
            d_short, d_long = (device_time(n_short),
                               device_time(ns.new_tokens))
        else:
            d_short = d_long = None
    except Exception:
        d_short = d_long = None
    if d_short is not None and d_long is not None:
        dt = d_long - d_short
        timing = "device(xplane)"
        n_eff = ns.new_tokens - n_short
    else:
        dt = min(t_long) - min(t_short)
        timing = "wall(min-of-reps)"
        n_eff = ns.new_tokens - n_short
        if dt <= 0:
            # a loaded host can make the short run's best wall exceed
            # the long run's (seen at --reps 1 in the CI smoke): the
            # marginal is pure noise — report the absolute long-run
            # rate instead of a negative throughput
            dt = min(t_long)
            n_eff = ns.new_tokens
            timing = "wall(absolute)"

    tok_s = ns.batch * n_eff / dt
    per_seq = n_eff / dt

    # roofline: average cache length over the decode window. The UNTIED
    # embedding table is NOT streamed per step — decode gathers b rows
    # from it; only a TIED head (gpt2) re-reads it as the unembedding
    # matmul. (Round-5 correction: earlier rooflines counted the unread
    # embed table, inflating bytes/step — the deepseek row came out at
    # "114% of roofline", which is how the bug surfaced. Historical rows
    # in SCALE.md are re-derived under this definition.) int8 quantizes
    # every linear INCLUDING lm_head; the bf16 embed table is excluded
    # either way. MoE: the fused kernel streams only min(b·k, E) routed
    # experts per layer per step.
    avg_len = ns.prompt_len + ns.new_tokens / 2
    tied = bool(getattr(cfg, "tie_word_embeddings", False)) \
        or name == "gpt2-345m"
    embed_params = 0 if tied else cfg.vocab_size * cfg.hidden_size
    if moe:
        # routed stacks stream only min(b·k, E) experts/layer; DENSE params
        # (attention, router, shared experts, head) stream whole
        expert_params = 3 * cfg.hidden_size * cfg.intermediate_size
        dense_params = (n_params - embed_params
                        - cfg.num_layers * cfg.num_experts * expert_params)
        streamed = (dense_params + cfg.num_layers * min(
            ns.batch * cfg.top_k, cfg.num_experts) * expert_params)
        param_bytes = 2 * streamed
    elif ns.int8:
        param_bytes = n_params - embed_params
    else:
        param_bytes = 2 * (n_params - embed_params)
    cache_bytes = kv_bytes_per_token(cfg, 1 if ns.cache_int8 else 2)
    step_bytes = param_bytes + ns.batch * cache_bytes * avg_len
    bw = HBM_BW.get(dev.device_kind, 819e9 if on_tpu else 50e9)
    roofline_tok_s = ns.batch * bw / step_bytes

    # ---- unified telemetry: BENCH schema + roofline plan + spans ----------
    from paddle_tpu import observability as obs

    # the analytic per-phase plan scale_report --report joins against an
    # xplane capture (decode_bench's own trace lands in
    # /tmp/decode_bench_prof); substring attribution is best-effort, so
    # the catch-all phases keep the unmatched time visible
    roofline_plan = {
        "hbm_gbps": round(bw / 1e9, 1),
        "steps": ns.new_tokens,
        "phases": [
            {"name": "decode_kernel",
             "match": ["fused_decode", "pallas", "custom-call"],
             "bytes_per_step": step_bytes},
            {"name": "glue_matmul", "match": ["dot", "einsum", "convolution"],
             "bytes_per_step": 0},
            {"name": "sampling_glue",
             "match": ["argmax", "reduce", "iota", "sort", "top-k", "top_k",
                       "select", "compare"],
             "bytes_per_step": 0},
        ],
    }
    if ns.report_plan:
        with open(ns.report_plan, "w") as f:
            json.dump(roofline_plan, f)

    spans = None
    if ns.traced:
        # traced run: generate() switches to prefill + chunked decode
        # dispatches so TTFT/TPOT are host-measured; tokens unchanged.
        # The first traced call compiles the prefill/chunk programs (the
        # untraced warmups above cached only the single-dispatch
        # program), so warm up once and measure the second request. The
        # measured request runs INSIDE a jax.profiler capture into the
        # --report dir, so the decode.request/prefill/chunk
        # TraceAnnotations land in the same xplane the roofline join
        # reads (skipped on bare CPU unless --device_time: trace
        # start/stop costs ~15 s there and yields no device plane).
        import contextlib
        import shutil
        with obs.trace(decode_chunk=32):
            timed(ns.new_tokens)
        if dev.platform != "cpu" or ns.device_time:
            shutil.rmtree("/tmp/decode_bench_prof", ignore_errors=True)
            capture = jax.profiler.trace("/tmp/decode_bench_prof")
        else:
            capture = contextlib.nullcontext()
        with capture, obs.trace(decode_chunk=32) as tracer:
            timed(ns.new_tokens)
        spans = tracer.span_dicts()
        obs.validate_spans(spans, require_request=True)
        tracer.export_jsonl("/tmp/decode_bench_spans.jsonl")

    pad_waste = None
    if ns.eos is not None:
        if stacked:
            print("note: --eos pad-waste accounting needs "
                  "generate(return_lengths=True); the stacked engine "
                  "reports ids only — skipped", file=sys.stderr)
        else:
            # static-batch pad waste: every row decodes the full
            # new_tokens budget; tokens after a row's eos are pure
            # padding (the scheduling gap serving_bench's continuous
            # engine closes — its A/B record quotes this number)
            _, lens = generate(model, prompt, max_new_tokens=ns.new_tokens,
                               temperature=0.0, state=state,
                               cache_dtype=cache_dtype, eos_token_id=ns.eos,
                               return_lengths=True)
            useful = int(np.minimum(lens + 1, ns.new_tokens).sum())
            pad_waste = round(1 - useful / (ns.batch * ns.new_tokens), 3)

    tag = (" int8" if ns.int8 else "") + (" kv8" if ns.cache_int8 else "")
    rec = obs.bench_record(
        f"{name}{tag} decode tokens/s (batch={ns.batch})",
        round(tok_s, 1), "tokens/s",
        device=dev.device_kind,
        tokens_per_sec_per_seq=round(per_seq, 1),
        roofline_tokens_per_sec=round(roofline_tok_s, 1),
        frac_of_roofline=round(tok_s / roofline_tok_s, 3),
        params=n_params,
        batch=ns.batch, prompt_len=ns.prompt_len,
        new_tokens=ns.new_tokens,
        step_time_ms=round(1000 * dt / n_eff, 3),
        timing=timing,
        **({"pad_waste_frac": pad_waste} if pad_waste is not None else {}),
        roofline_plan=roofline_plan,
        memory=obs.memory.memory_snapshot(),
        **({"request_span": next(
            s["attrs"] for s in spans if s["name"] == "decode.request")}
           if spans else {}),
    )
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
