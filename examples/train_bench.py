"""Single-chip training throughput for larger-than-headline models.

BASELINE's scale story needs evidence beyond GPT-2 345M: this benches the
largest Llama config that fits one v5e chip (16 GiB) with pure-bf16 AdamW
(moments in bf16, no fp32 master — 6 bytes/param of optimizer state).
Same timing discipline as bench.py: the whole step loop is ONE lax.scan
inside jit, synced by pulling the final loss (the axon tunnel's
block_until_ready does not fence).

Run: python examples/train_bench.py [--model llama-1b3] [--steps 10]
"""

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

PEAK_FLOPS = {
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,
}


def build(name):
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    shapes = {
        # ~1.36 B params — GPT-3 XL-ish shape, fits v5e with bf16 AdamW
        "llama-1b3": dict(vocab_size=32000, hidden_size=2048, num_layers=24,
                          num_heads=32, num_kv_heads=32,
                          intermediate_size=5632,
                          max_position_embeddings=2048),
        # TinyLlama-1.1B shape (GQA)
        "llama-1b": dict(vocab_size=32000, hidden_size=2048, num_layers=22,
                         num_heads=32, num_kv_heads=4,
                         intermediate_size=5632,
                         max_position_embeddings=2048),
        "llama-tiny": dict(vocab_size=512, hidden_size=128, num_layers=2,
                           num_heads=4, num_kv_heads=4,
                           intermediate_size=256,
                           max_position_embeddings=512),
    }
    cfg = LlamaConfig(**shapes[name])
    cfg.recompute = name != "llama-tiny"  # per-layer remat for the big runs
    return cfg


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default=None)
    ap.add_argument("--batch", type=int, default=None,
                    help="default 4 (2 for llama-1b3: the core_attn save "
                    "set + 1.36B state only fits 16 GiB at b2)")
    ap.add_argument("--seq", type=int, default=2048)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--per_step_dispatch", action="store_true",
                    help="one jit call per step (halves state memory: no "
                    "scan double-buffer) — timing then includes ~70ms "
                    "tunnel latency per step; MFU still uses the device "
                    "clock")
    ap.add_argument("--granularity", default=None,
                    choices=["full", "full_attn", "core_attn"],
                    help="recompute_granularity (reference fleet "
                    "recompute): default core_attn for the 1B configs "
                    "(q/k/v + FFN matmul outputs saved — fits v5e now "
                    "that multi_precision=False keeps bf16 moments), "
                    "full elsewhere")
    ns = ap.parse_args()

    import paddle_tpu
    from paddle_tpu.nn.layer import functional_call
    from paddle_tpu.optimizer import AdamW

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"
    name = ns.model or ("llama-1b3" if on_tpu else "llama-tiny")
    if ns.batch is None:
        ns.batch = 2 if name == "llama-1b3" else 4
    if not on_tpu:
        ns.batch, ns.seq, ns.steps = 2, 128, 2

    # a Pallas regression must FAIL the bench, not silently re-ride XLA
    paddle_tpu.set_flags({"FLAGS_pallas_strict": True})

    paddle_tpu.seed(0)
    cfg = build(name)
    if ns.granularity is not None:
        cfg.recompute_granularity = ns.granularity
    elif name in ("llama-1b", "llama-1b3"):
        # selective remat + bf16 moments: 1.1B 43.3 → 57.1% measured; the
        # saved matmul outputs need the no-scan-double-buffer layout
        cfg.recompute_granularity = "core_attn"
        ns.per_step_dispatch = True
    if name in ("llama-1b", "llama-1b3"):
        cfg.loss_seq_chunks = 4   # never materialize (b, s, 32000) logits
    from paddle_tpu.models.llama import LlamaForCausalLM
    model = LlamaForCausalLM(cfg).bfloat16()
    n_params = model.num_params()
    # pure-bf16 AdamW: moments live in the param dtype (no fp32 master)
    opt = AdamW(learning_rate=1e-4, multi_precision=False)
    state = model.trainable_state()
    opt_state = opt.init_state(state)

    rng = np.random.RandomState(0)
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, (ns.batch, ns.seq + 1)))
    x, y = ids[:, :-1], ids[:, 1:]

    def one_step(carry, _):
        state, opt_state = carry

        def loss_fn(s):
            return functional_call(model, s, x, y, method="train_loss")

        loss, grads = jax.value_and_grad(loss_fn)(state)
        state, opt_state = opt.update(grads, opt_state, state)
        return (state, opt_state), loss

    # donate the carried state — without this the old buffers stay live
    # across the dispatch and the 1B+ configs don't fit
    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def run_steps(state, opt_state):
        (state, opt_state), losses = jax.lax.scan(
            one_step, (state, opt_state), None, length=ns.steps)
        return state, opt_state, losses

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def run_one(state, opt_state):
        (state, opt_state), loss = one_step((state, opt_state), None)
        return state, opt_state, loss

    if ns.per_step_dispatch:
        state, opt_state, loss = run_one(state, opt_state)
        float(loss)
        t0 = time.perf_counter()
        for _ in range(ns.steps):
            state, opt_state, loss = run_one(state, opt_state)
            loss = float(loss)  # sync every step (includes tunnel latency)
        dt = time.perf_counter() - t0
        jit_name = "jit_run_one"
    else:
        state, opt_state, losses = run_steps(state, opt_state)
        float(losses[-1])  # compile+warmup, real sync
        t0 = time.perf_counter()
        state, opt_state, losses = run_steps(state, opt_state)
        loss = losses[-1]
        loss = float(loss)
        dt = time.perf_counter() - t0
        jit_name = "jit_run_steps"

    # device-clock step time via the xplane parser (the axon tunnel adds
    # 10-300 ms of nondeterministic wall overhead per dispatch; MFU uses
    # the device number when available, wall is reported alongside)
    dt_dev = None
    if on_tpu:
        try:
            import shutil
            from paddle_tpu.profiler import xplane
            shutil.rmtree("/tmp/train_bench_prof", ignore_errors=True)
            with jax.profiler.trace("/tmp/train_bench_prof"):
                if ns.per_step_dispatch:
                    for _ in range(ns.steps):
                        state, opt_state, loss = run_one(state, opt_state)
                        loss = float(loss)
                else:
                    state, opt_state, losses = run_steps(state, opt_state)
                    float(losses[-1])
            dt_dev = xplane.device_total_seconds("/tmp/train_bench_prof",
                                                 jit_name)
        except Exception:
            pass

    tokens_per_step = ns.batch * ns.seq
    tok_s = tokens_per_step * ns.steps / (dt_dev or dt)
    flops_per_token = 6 * n_params + 12 * cfg.num_layers * cfg.hidden_size * ns.seq
    peak = PEAK_FLOPS.get(dev.device_kind, 197e12 if on_tpu else 1e12)
    mfu = tok_s * flops_per_token / peak

    from paddle_tpu import observability as obs

    rec = obs.bench_record(
        f"{name} train tokens/sec/chip", round(tok_s, 1), "tokens/s",
        device=dev.device_kind,
        mfu=round(mfu, 4),
        mfu_basis="dense_6n",
        vs_baseline=round(mfu / 0.45, 4),
        params=n_params,
        batch=ns.batch, seq=ns.seq, steps=ns.steps,
        step_time_ms=round(1000 * (dt_dev or dt) / ns.steps, 2),
        wall_step_time_ms=round(1000 * dt / ns.steps, 2),
        timing="device(xplane)" if dt_dev else "wall",
        final_loss=round(loss, 4),
        memory=obs.memory.memory_snapshot(),
    )
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
